// Native single-instance LibraBFTv2 discrete-event engine.
//
// Mirrors the integer semantics of the tensorized JAX simulator
// (librabft_simulator_tpu/sim/simulator.py) and the Python oracle
// (librabft_simulator_tpu/oracle/{engine,sim}.py) exactly — same hashing,
// same windowed record tables, same event ordering and rng counters — so a
// trajectory is bit-comparable across all three implementations
// (tests/test_native.py).  The reference's native runtime is the Rust
// workspace at /root/reference; this is its C++ counterpart for the rebuilt
// framework (fast host-side single-instance runs, e.g. the real-node driver
// or spot-checking TPU fleets).
//
// Build: g++ -O2 -shared -fPIC -o libbft_engine.so engine.cpp
// ABI:   extern "C" bft_run(...) — see librabft_simulator_tpu/native.py.

#include <cstdint>
#include <cstring>
#include <vector>
#include <algorithm>

namespace {

using u32 = uint32_t;
using i64 = long long;

constexpr int NEVER = 2147483647;
constexpr int KIND_NOTIFY = 0, KIND_REQUEST = 1, KIND_RESPONSE = 2, KIND_TIMER = 3;
constexpr int EL_ONGOING = 0, EL_WON = 1, EL_CLOSED = 2;
constexpr int EQUIV_SALT = 1 << 20;
constexpr int TABLE_BITS = 10;

constexpr u32 TAG_BLOCK = 0x9E3779B1u, TAG_QC = 0xC2B2AE3Du,
              TAG_STATE = 0x165667B1u, TAG_EPOCH = 0x5851F42Du,
              TAG_LEADER = 0x2545F491u, TAG_SEED = 0x9E447687u;

u32 mix32(u32 h, u32 x) {
  h ^= x;
  h *= 0x9E3779B1u; h ^= h >> 16;
  h *= 0x85EBCA6Bu; h ^= h >> 13;
  h *= 0xC2B2AE35u; h ^= h >> 16;
  return h;
}

template <typename... W>
u32 fold(W... words) {
  u32 h = 0x811C9DC5u;
  u32 ws[] = {static_cast<u32>(words)...};
  for (u32 w : ws) h = mix32(h, w);
  return h;
}

u32 rng_u32(u32 seed, u32 counter) { return fold(TAG_SEED, seed, counter); }

u32 state_tag_next(u32 prev, u32 proposer, u32 index, u32 time) {
  return fold(TAG_STATE, prev, proposer, index, time);
}

u32 epoch_initial_tag(u32 e) { return fold(TAG_EPOCH, e); }
u32 initial_state_tag() { return fold(TAG_STATE, 0u); }

struct Params {
  int n_nodes, window, queue_cap, chain_k, commit_log;
  int commands_per_epoch, target_commit_interval, delta;
  int lam_fp, commit_chain, max_clock, dur_table_size;
  int shuffle_receivers = 0;
  int epoch_handoff = 2;  // ring depth E of held previous-epoch packs; 0=off
  u32 drop_u32;
  // tables appended by caller
};

struct BlockMsg {
  bool valid = false;
  int round = 0, author = 0, prev_round = 0, time = 0, cmd_proposer = 0,
      cmd_index = 0;
  u32 prev_tag = 0, tag = 0;
};

struct QcMsg {
  bool valid = false, commit_valid = false;
  int epoch = 0, round = 0, state_depth = 0, commit_depth = 0, author = 0;
  u32 blk_tag = 0, state_tag = 0, commit_tag = 0, votes_lo = 0, votes_hi = 0,
      tag = 0;
};

struct VoteMsg {
  bool valid = false, commit_valid = false;
  int epoch = 0, round = 0, state_depth = 0, commit_depth = 0, author = 0;
  u32 blk_tag = 0, state_tag = 0, commit_tag = 0;
};

struct TimeoutsMsg {
  int round = 0;
  std::vector<uint8_t> valid;
  std::vector<int> hcbr;
  explicit TimeoutsMsg(int n = 0) : valid(n, 0), hcbr(n, 0) {}
};

struct Payload {
  int epoch = 0;
  QcMsg hcc, hqc;
  BlockMsg hcc_blk, prop_blk;
  VoteMsg vote;
  TimeoutsMsg tc_to, cur_to;
  std::vector<BlockMsg> chain_blk;
  std::vector<QcMsg> chain_qc;
  int req_hqc_round = 0, req_hcr = 0;
  Payload(int n = 0, int k = 0)
      : tc_to(n), cur_to(n), chain_blk(k), chain_qc(k) {}
};

int quorum_threshold(const std::vector<int>& w) {
  int t = 0;
  for (int x : w) t += x;
  return 2 * t / 3 + 1;
}

int pick_author(const std::vector<int>& w, u32 seed) {
  int total = 0;
  for (int x : w) total += x;
  int target = static_cast<int>(seed % static_cast<u32>(total));
  int cum = 0;
  for (size_t i = 0; i < w.size(); i++) {
    cum += w[i];
    if (cum > target) return static_cast<int>(i);
  }
  return static_cast<int>(w.size()) - 1;
}

int leader_of_round(const std::vector<int>& w, int r) {
  return pick_author(w, fold(TAG_LEADER, static_cast<u32>(r)));
}

struct Hop {
  bool valid, hit;
  int round, var;
};

struct Store {
  const Params& p;
  // [W][2] tables
  std::vector<uint8_t> blk_valid, qc_valid, qc_commit_valid;
  std::vector<int> blk_round, blk_author, blk_prev_round, blk_time,
      blk_cmd_proposer, blk_cmd_index, qc_round, qc_blk_var, qc_state_depth,
      qc_commit_depth, qc_author;
  std::vector<u32> blk_prev_tag, blk_tag, qc_state_tag, qc_commit_tag,
      qc_votes_lo, qc_votes_hi, qc_tag;
  // per-author
  std::vector<uint8_t> vt_valid, vt_commit_valid, to_valid, tc_valid;
  std::vector<int> vt_blk_var, vt_state_depth, vt_commit_depth, to_hcbr,
      tc_hcbr;
  std::vector<u32> vt_state_tag, vt_commit_tag;
  // ballot [2][2]
  uint8_t bal_used[2][2] = {};
  int bal_weight[2][2] = {}, bal_state_depth[2][2] = {};
  u32 bal_state_tag[2][2] = {};
  int to_weight = 0;
  int epoch_id = 0, initial_round = 0, initial_state_depth = 0;
  u32 initial_tag, initial_state_tag_;
  int current_round = 1, proposed_var = -1, election = EL_ONGOING, won_var = 0,
      won_slot = 0, hqc_round = 0, hqc_var = 0, htc_round = 0, hcr = 0;
  bool hcc_valid = false, anchored = false;
  int hcc_round = 0, hcc_var = 0;

  explicit Store(const Params& pp) : p(pp) { reset(); }

  void reset() {
    int W = p.window, N = p.n_nodes;
    auto zi = [&](std::vector<int>& v) { v.assign(W * 2, 0); };
    auto zu = [&](std::vector<u32>& v) { v.assign(W * 2, 0); };
    auto zb = [&](std::vector<uint8_t>& v) { v.assign(W * 2, 0); };
    zb(blk_valid); zi(blk_round); zi(blk_author); zi(blk_prev_round);
    zu(blk_prev_tag); zi(blk_time); zi(blk_cmd_proposer); zi(blk_cmd_index);
    zu(blk_tag);
    zb(qc_valid); zi(qc_round); zi(qc_blk_var); zi(qc_state_depth);
    zu(qc_state_tag); zb(qc_commit_valid); zi(qc_commit_depth);
    zu(qc_commit_tag); zu(qc_votes_lo); zu(qc_votes_hi);
    zi(qc_author); zu(qc_tag);
    vt_valid.assign(N, 0); vt_blk_var.assign(N, 0);
    vt_state_depth.assign(N, 0); vt_state_tag.assign(N, 0);
    vt_commit_valid.assign(N, 0); vt_commit_depth.assign(N, 0);
    vt_commit_tag.assign(N, 0);
    std::memset(bal_used, 0, sizeof bal_used);
    std::memset(bal_weight, 0, sizeof bal_weight);
    std::memset(bal_state_depth, 0, sizeof bal_state_depth);
    std::memset(bal_state_tag, 0, sizeof bal_state_tag);
    to_valid.assign(N, 0); to_hcbr.assign(N, 0); to_weight = 0;
    tc_valid.assign(N, 0); tc_hcbr.assign(N, 0);
    epoch_id = 0; initial_round = 0;
    initial_tag = epoch_initial_tag(0);
    initial_state_depth = 0; initial_state_tag_ = initial_state_tag();
    current_round = 1; proposed_var = -1; election = EL_ONGOING;
    won_var = won_slot = 0; hqc_round = hqc_var = htc_round = hcr = 0;
    hcc_valid = false; hcc_round = hcc_var = 0; anchored = false;
  }

  int slot(int r) const { return ((r % p.window) + p.window) % p.window; }
  int ix(int sl, int v) const { return sl * 2 + v; }

  int blk_find(int r, u32 tag) const {
    int sl = slot(r);
    for (int v = 0; v < 2; v++)
      if (blk_valid[ix(sl, v)] && blk_round[ix(sl, v)] == r &&
          blk_tag[ix(sl, v)] == tag)
        return v;
    return -1;
  }

  int qc_find(int r, u32 tag) const {
    int sl = slot(r);
    for (int v = 0; v < 2; v++)
      if (qc_valid[ix(sl, v)] && qc_round[ix(sl, v)] == r &&
          qc_tag[ix(sl, v)] == tag)
        return v;
    return -1;
  }

  void hqc_ref(int& r, u32& tag) const {
    if (hqc_round > initial_round) {
      r = hqc_round;
      tag = qc_tag[ix(slot(hqc_round), hqc_var)];
    } else {
      r = hqc_round;
      tag = initial_tag;
    }
  }

  // (found, prev_round, prev_var); prev_var -1 = initial QC.
  bool prev_qc_of_block(int r, int var, int& pr, int& pv) const {
    int sl = slot(r);
    pr = blk_prev_round[ix(sl, var)];
    u32 pt = blk_prev_tag[ix(sl, var)];
    if (pr == initial_round && pt == initial_tag) {
      pv = -1;
      return true;
    }
    pv = qc_find(pr, pt);
    return pv >= 0;
  }

  std::vector<Hop> qc_walk_back(bool start_valid, int start_round,
                                int start_var, int steps) const {
    std::vector<Hop> out;
    bool alive = start_valid && start_round > initial_round;
    int r = start_round, v = start_var;
    for (int i = 0; i < steps; i++) {
      int bvar = qc_blk_var[ix(slot(r), v)];
      int pr, pv;
      bool found = prev_qc_of_block(r, bvar, pr, pv);
      bool hit = alive && found && pv < 0;
      out.push_back({alive, hit, r, v});
      bool alive2 = alive && found && pv >= 0;
      if (alive2) { r = pr; v = pv; }
      alive = alive2;
    }
    return out;
  }

  int previous_round(int r, int var) const {
    return blk_prev_round[ix(slot(r), var)];
  }

  int second_previous_round(int r, int var) const {
    int pr, pv;
    bool found = prev_qc_of_block(r, var, pr, pv);
    if (pv < 0 || !found) return initial_round;
    int bvar = qc_blk_var[ix(slot(pr), pv)];
    return blk_prev_round[ix(slot(pr), bvar)];
  }

  void vote_committed_state(int blk_round_, int blk_var, bool& ok, int& d,
                            u32& t, bool& undet) const {
    int C = p.commit_chain;
    int pr, pv;
    bool found0 = prev_qc_of_block(blk_round_, blk_var, pr, pv);
    auto hops = qc_walk_back(found0 && pv >= 0, pr, std::max(pv, 0), C - 1);
    ok = true;
    int prev_r = blk_round_;
    for (int i = 0; i < C - 1; i++) {
      ok = ok && hops[i].valid && prev_r == hops[i].round + 1;
      prev_r = hops[i].round;
    }
    bool touched = (found0 && pv < 0);
    for (int i = 0; i < C - 1; i++) touched = touched || hops[i].hit;
    undet = anchored && touched;
    const Hop& last = hops[C - 2];
    int sl = slot(last.round);
    d = ok ? qc_state_depth[ix(sl, last.var)] : 0;
    t = ok ? qc_state_tag[ix(sl, last.var)] : 0;
  }

  bool compute_state(int blk_round_, int blk_var, int& d, u32& t) const {
    int pr, pv;
    bool found = prev_qc_of_block(blk_round_, blk_var, pr, pv);
    int base_d;
    u32 base_t;
    if (pv < 0) {
      base_d = initial_state_depth;
      base_t = initial_state_tag_;
    } else {
      base_d = qc_state_depth[ix(slot(pr), pv)];
      base_t = qc_state_tag[ix(slot(pr), pv)];
    }
    int sl = slot(blk_round_);
    t = state_tag_next(base_t, blk_cmd_proposer[ix(sl, blk_var)],
                       blk_cmd_index[ix(sl, blk_var)], blk_time[ix(sl, blk_var)]);
    d = base_d + 1;
    return found;
  }

  void update_commit_chain(int qr, int qv) {
    int C = p.commit_chain;
    auto hops = qc_walk_back(true, qr, qv, C);
    bool ok = true;
    for (int i = 0; i < C; i++) {
      ok = ok && hops[i].valid;
      if (i > 0) ok = ok && hops[i - 1].round == hops[i].round + 1;
    }
    int r1 = hops[C - 1].round;
    ok = ok && r1 > hcr;
    if (ok) {
      hcr = r1;
      hcc_valid = true;
      hcc_round = qr;
      hcc_var = qv;
    }
  }

  void update_current_round(int r) {
    if (r > current_round) {
      current_round = r;
      proposed_var = -1;
      std::fill(vt_valid.begin(), vt_valid.end(), 0);
      std::fill(to_valid.begin(), to_valid.end(), 0);
      to_weight = 0;
      std::memset(bal_used, 0, sizeof bal_used);
      std::memset(bal_weight, 0, sizeof bal_weight);
      std::memset(bal_state_depth, 0, sizeof bal_state_depth);
      std::memset(bal_state_tag, 0, sizeof bal_state_tag);
      election = EL_ONGOING;
      won_var = won_slot = 0;
    }
  }

  void pick_variant(const uint8_t* valid_col, const int* round_col,
                    const u32* tag_col, int r, u32 tag, int& var, bool& dup,
                    bool& room) const {
    bool stale0 = !valid_col[0] || round_col[0] != r;
    bool stale1 = !valid_col[1] || round_col[1] != r;
    bool dup0 = !stale0 && tag_col[0] == tag;
    bool dup1 = !stale1 && tag_col[1] == tag;
    dup = dup0 || dup1;
    var = stale0 ? 0 : (stale1 ? 1 : -1);
    room = var >= 0;
  }

  bool insert_block(const std::vector<int>& w, const BlockMsg& b,
                    int rec_epoch) {
    int sl = slot(b.round);
    uint8_t vcol[2] = {blk_valid[ix(sl, 0)], blk_valid[ix(sl, 1)]};
    int rcol[2] = {blk_round[ix(sl, 0)], blk_round[ix(sl, 1)]};
    u32 tcol[2] = {blk_tag[ix(sl, 0)], blk_tag[ix(sl, 1)]};
    int var; bool dup, room;
    pick_variant(vcol, rcol, tcol, b.round, b.tag, var, dup, room);
    bool prev_initial =
        b.prev_round == initial_round && b.prev_tag == initial_tag;
    bool prev_known = prev_initial || qc_find(b.prev_round, b.prev_tag) >= 0;
    bool in_window = b.round > current_round - p.window;
    bool ok = b.valid && rec_epoch == epoch_id && !dup && room && prev_known &&
              b.round > b.prev_round && in_window;
    if (!ok) return false;
    var = std::max(var, 0);
    int k = ix(sl, var);
    blk_valid[k] = 1; blk_round[k] = b.round; blk_author[k] = b.author;
    blk_prev_round[k] = b.prev_round; blk_prev_tag[k] = b.prev_tag;
    blk_time[k] = b.time; blk_cmd_proposer[k] = b.cmd_proposer;
    blk_cmd_index[k] = b.cmd_index; blk_tag[k] = b.tag;
    if (b.round == current_round && leader_of_round(w, current_round) == b.author)
      proposed_var = var;
    return true;
  }

  bool insert_vote(const std::vector<int>& w, const VoteMsg& v) {
    int author = std::min(std::max(v.author, 0), p.n_nodes - 1);
    int bvar = blk_find(v.round, v.blk_tag);
    bool cs_ok, cs_undet;
    int cs_d;
    u32 cs_t;
    vote_committed_state(v.round, std::max(bvar, 0), cs_ok, cs_d, cs_t,
                         cs_undet);
    bool commit_match =
        cs_undet ||
        (v.commit_valid == cs_ok &&
         (!cs_ok || (v.commit_depth == cs_d && v.commit_tag == cs_t)));
    bool ok = v.valid && v.epoch == epoch_id && bvar >= 0 && commit_match &&
              v.round == current_round && !vt_valid[author];
    if (!ok) return false;
    bvar = std::max(bvar, 0);
    vt_valid[author] = 1; vt_blk_var[author] = bvar;
    vt_state_depth[author] = v.state_depth; vt_state_tag[author] = v.state_tag;
    vt_commit_valid[author] = v.commit_valid;
    vt_commit_depth[author] = v.commit_depth;
    vt_commit_tag[author] = v.commit_tag;
    if (election != EL_ONGOING) return true;
    bool m0 = bal_used[bvar][0] && bal_state_depth[bvar][0] == v.state_depth &&
              bal_state_tag[bvar][0] == v.state_tag;
    bool m1 = bal_used[bvar][1] && bal_state_depth[bvar][1] == v.state_depth &&
              bal_state_tag[bvar][1] == v.state_tag;
    int s;
    if (m0) s = 0;
    else if (m1) s = 1;
    else if (!bal_used[bvar][0]) s = 0;
    else if (!bal_used[bvar][1]) s = 1;
    else return true;
    bal_used[bvar][s] = 1;
    bal_weight[bvar][s] += w[author];
    bal_state_depth[bvar][s] = v.state_depth;
    bal_state_tag[bvar][s] = v.state_tag;
    if (bal_weight[bvar][s] >= quorum_threshold(w)) {
      election = EL_WON;
      won_var = bvar;
      won_slot = s;
    }
    return true;
  }

  bool insert_qc(const std::vector<int>& w, const QcMsg& q) {
    int sl = slot(q.round);
    uint8_t vcol[2] = {qc_valid[ix(sl, 0)], qc_valid[ix(sl, 1)]};
    int rcol[2] = {qc_round[ix(sl, 0)], qc_round[ix(sl, 1)]};
    u32 tcol[2] = {qc_tag[ix(sl, 0)], qc_tag[ix(sl, 1)]};
    int var; bool dup, room;
    pick_variant(vcol, rcol, tcol, q.round, q.tag, var, dup, room);
    int bvar = blk_find(q.round, q.blk_tag);
    int bvar_c = std::max(bvar, 0);
    bool author_ok = blk_author[ix(sl, bvar_c)] == q.author;
    bool cs_ok, cs_undet;
    int cs_d;
    u32 cs_t;
    vote_committed_state(q.round, bvar_c, cs_ok, cs_d, cs_t, cs_undet);
    bool commit_match =
        cs_undet ||
        (q.commit_valid == cs_ok &&
         (!cs_ok || (q.commit_depth == cs_d && q.commit_tag == cs_t)));
    int st_d;
    u32 st_t;
    bool exec_ok = compute_state(q.round, bvar_c, st_d, st_t);
    bool state_match = exec_ok && st_d == q.state_depth && st_t == q.state_tag;
    bool in_window = q.round > current_round - p.window;
    // Vote-set re-verification (record_store.rs:371-387): masked authors
    // must be known, their weight must reach quorum, and the tag must
    // recompute from the carried fields including the mask.
    int vote_w = 0;
    for (int a = 0; a < p.n_nodes; a++) {
      u32 bit = a < 32 ? (q.votes_lo >> a) & 1u : (q.votes_hi >> (a - 32)) & 1u;
      if (bit) vote_w += w[a];
    }
    bool known = p.n_nodes >= 64 ||
                 (p.n_nodes >= 32 ? (q.votes_hi >> (p.n_nodes - 32)) == 0
                                  : ((q.votes_lo >> p.n_nodes) == 0 &&
                                     q.votes_hi == 0));
    bool quorum_ok = known && vote_w >= quorum_threshold(w);
    bool tag_ok =
        q.tag == fold(TAG_QC, (u32)q.epoch, (u32)q.round, q.blk_tag,
                      (u32)q.state_depth, q.state_tag,
                      (u32)(q.commit_valid ? 1 : 0), (u32)q.commit_depth,
                      q.commit_tag, q.votes_lo, q.votes_hi, (u32)q.author);
    bool ok = q.valid && q.epoch == epoch_id && !dup && room && bvar >= 0 &&
              author_ok && commit_match && state_match && in_window &&
              quorum_ok && tag_ok;
    if (!ok) return false;
    var = std::max(var, 0);
    int k = ix(sl, var);
    qc_valid[k] = 1; qc_round[k] = q.round; qc_blk_var[k] = bvar_c;
    qc_state_depth[k] = q.state_depth; qc_state_tag[k] = q.state_tag;
    qc_commit_valid[k] = q.commit_valid; qc_commit_depth[k] = q.commit_depth;
    qc_commit_tag[k] = q.commit_tag;
    qc_votes_lo[k] = q.votes_lo; qc_votes_hi[k] = q.votes_hi;
    qc_author[k] = q.author; qc_tag[k] = q.tag;
    if (q.round > hqc_round) { hqc_round = q.round; hqc_var = var; }
    update_current_round(q.round + 1);
    update_commit_chain(q.round, var);
    return true;
  }

  bool insert_timeout(const std::vector<int>& w, int t_epoch, int t_round,
                      int t_hcbr, int t_author) {
    int author = std::min(std::max(t_author, 0), p.n_nodes - 1);
    bool ok = t_epoch == epoch_id && t_hcbr <= hqc_round &&
              t_round == current_round && !to_valid[author];
    if (!ok) return false;
    to_valid[author] = 1;
    to_hcbr[author] = t_hcbr;
    to_weight += w[author];
    if (to_weight >= quorum_threshold(w)) {
      tc_valid = to_valid;
      tc_hcbr = to_hcbr;
      htc_round = current_round;
      update_current_round(current_round + 1);
    }
    return true;
  }

  u32 make_block_tag(int r, int author, int prev_round, u32 prev_tag, int time,
                     int cmd_proposer, int cmd_index) const {
    return fold(TAG_BLOCK, (u32)epoch_id, (u32)r, (u32)author, (u32)prev_round,
                prev_tag, (u32)time, (u32)cmd_proposer, (u32)cmd_index);
  }

  bool propose_block(const std::vector<int>& w, int author, int prev_round,
                     u32 prev_tag, int time, int cmd_index) {
    BlockMsg b;
    b.valid = true; b.round = current_round; b.author = author;
    b.prev_round = prev_round; b.prev_tag = prev_tag; b.time = time;
    b.cmd_proposer = author; b.cmd_index = cmd_index;
    b.tag = make_block_tag(current_round, author, prev_round, prev_tag, time,
                           author, cmd_index);
    return insert_block(w, b, epoch_id);
  }

  bool create_vote(const std::vector<int>& w, int author, int blk_round_,
                   int blk_var) {
    int sl = slot(blk_round_);
    bool cs_ok, cs_undet;
    int cs_d;
    u32 cs_t;
    vote_committed_state(blk_round_, blk_var, cs_ok, cs_d, cs_t, cs_undet);
    int st_d;
    u32 st_t;
    bool exec_ok = compute_state(blk_round_, blk_var, st_d, st_t);
    VoteMsg v;
    v.valid = exec_ok; v.epoch = epoch_id; v.round = blk_round_;
    v.blk_tag = blk_tag[ix(sl, blk_var)];
    v.state_depth = st_d; v.state_tag = st_t;
    v.commit_valid = cs_ok; v.commit_depth = cs_d; v.commit_tag = cs_t;
    v.author = author;
    return insert_vote(w, v) && exec_ok;
  }

  bool create_timeout(const std::vector<int>& w, int author, int round_) {
    return insert_timeout(w, epoch_id, round_, hqc_round, author);
  }

  bool has_timeout(int author, int round_) const {
    return round_ == current_round && to_valid[std::max(author, 0)];
  }

  bool check_new_qc(const std::vector<int>& w, int author) {
    if (election != EL_WON) return false;
    int bvar = won_var;
    int sl = slot(current_round);
    if (blk_author[ix(sl, bvar)] != author) return false;
    int st_d = bal_state_depth[bvar][won_slot];
    u32 st_t = bal_state_tag[bvar][won_slot];
    bool cs_ok, cs_undet;
    int cs_d;
    u32 cs_t;
    vote_committed_state(current_round, bvar, cs_ok, cs_d, cs_t, cs_undet);
    u32 lo = 0, hi = 0;
    for (int i = 0; i < p.n_nodes; i++) {
      bool m = vt_valid[i] && vt_state_depth[i] == st_d &&
               vt_state_tag[i] == st_t && vt_blk_var[i] == bvar;
      if (m && i < 32) lo |= 1u << i;
      else if (m) hi |= 1u << (i - 32);
    }
    u32 tag = fold(TAG_QC, (u32)epoch_id, (u32)current_round,
                   blk_tag[ix(sl, bvar)], (u32)st_d, st_t, (u32)(cs_ok ? 1 : 0),
                   (u32)cs_d, cs_t, lo, hi, (u32)author);
    QcMsg q;
    q.valid = true; q.epoch = epoch_id; q.round = current_round;
    q.blk_tag = blk_tag[ix(sl, bvar)];
    q.state_depth = st_d; q.state_tag = st_t;
    q.commit_valid = cs_ok; q.commit_depth = cs_d; q.commit_tag = cs_t;
    q.votes_lo = lo; q.votes_hi = hi;
    q.author = author; q.tag = tag;
    election = EL_CLOSED;
    insert_qc(w, q);
    return true;
  }

  struct Commit { int round, depth; u32 tag; };

  std::vector<Commit> committed_states_after(int after_round) const {
    int W = p.window;
    int start_r = hcc_valid ? hcc_round : 0;
    auto hops = qc_walk_back(hcc_valid, start_r, hcc_var, W);
    int skip = p.commit_chain - 1;
    std::vector<Commit> out;
    for (int i = 0; i < (int)hops.size(); i++) {
      if (hops[i].valid && i >= skip && hops[i].round > after_round) {
        int sl = slot(hops[i].round);
        out.push_back({hops[i].round, qc_state_depth[ix(sl, hops[i].var)],
                       qc_state_tag[ix(sl, hops[i].var)]});
      }
    }
    std::reverse(out.begin(), out.end());
    return out;
  }
};

struct Pacemaker {
  int active_epoch = 0, active_round = 0, active_leader = -1, round_start = 0,
      round_duration = 0;
};

struct NodeExtra {
  int latest_voted_round = 0, locked_round = 0, latest_query_all = 0,
      tracker_epoch = 0, tracker_hcr = 0, tracker_commit_time = 0;
};

struct Context {
  int next_cmd_index = 0, commit_count = 0, last_depth = 0, sync_jumps = 0,
      skipped_commits = 0;
  u32 last_tag = initial_state_tag();
  std::vector<int> log_round, log_depth;
  std::vector<u32> log_tag;
  explicit Context(int H) : log_round(H, 0), log_depth(H, 0), log_tag(H, 0) {}
};

struct PacemakerActions {
  bool should_propose = false, should_create_timeout = false,
       should_broadcast = false, should_query_all = false;
  int propose_prev_round = 0, timeout_round = 0, send_leader = -1,
      next_sched = NEVER;
  u32 propose_prev_tag = 0;
};

struct NodeActions {
  int next_sched = NEVER;
  std::vector<uint8_t> send_mask;
  bool should_query_all = false;
  // Cross-epoch handoff capture (mirrors core/node.py NodeUpdateActions).
  bool ho_switched = false;
  int ho_epoch_old = -1;
  Payload ho_pack;
};

struct Engine {
  Params p;
  std::vector<int> delay_table, dur_table, weights;
  std::vector<uint8_t> byz_eq, byz_silent;
  u32 seed;
  std::vector<Store> stores;
  std::vector<Pacemaker> pms;
  std::vector<NodeExtra> nxs;
  std::vector<Context> ctxs;
  struct Msg {
    bool valid = false;
    int time = 0, kind = 0, stamp = 0, sender = 0, receiver = 0;
    Payload pay;
  };
  std::vector<Msg> queue;
  // Cross-epoch handoff ring (mirrors SimState.ho_pay / ho_epoch:
  // [N][E] packs, slot = epoch % E where E = p.epoch_handoff).
  std::vector<std::vector<Payload>> ho_pay;
  std::vector<std::vector<int>> ho_epoch;
  std::vector<int> startup, timer_time, timer_stamp;
  int clock = 0, stamp_ctr = 0;
  bool halted = false;
  i64 n_events = 0, n_msgs_sent = 0, n_msgs_dropped = 0, n_queue_full = 0;

  Engine(const Params& pp, u32 sd, const int* dtab, const int* dur,
         const int* w, const uint8_t* eq, const uint8_t* silent)
      : p(pp), seed(sd) {
    int n = p.n_nodes;
    delay_table.assign(dtab, dtab + (1 << TABLE_BITS));
    dur_table.assign(dur, dur + p.dur_table_size);
    weights.assign(w, w + n);
    byz_eq.assign(eq, eq + n);
    byz_silent.assign(silent, silent + n);
    for (int i = 0; i < n; i++) {
      stores.emplace_back(p);
      pms.emplace_back();
      nxs.emplace_back();
      ctxs.emplace_back(p.commit_log);
    }
    queue.assign(p.queue_cap, Msg{false, 0, 0, 0, 0, 0, Payload(n, p.chain_k)});
    int E_ho = p.epoch_handoff > 0 ? p.epoch_handoff : 0;
    ho_pay.assign(n, std::vector<Payload>(E_ho, Payload(n, p.chain_k)));
    ho_epoch.assign(n, std::vector<int>(E_ho, -1));
    for (int c = 0; c < n; c++) {
      int d = delay_table[rng_u32(seed, (u32)c) >> (32 - TABLE_BITS)] + 1;
      startup.push_back(d);
      timer_time.push_back(d);
      timer_stamp.push_back(c);
    }
    stamp_ctr = n;
  }

  int round_duration(int active_round, int hcr) const {
    int hccr = hcr > 0 ? hcr + 2 : 0;
    int n = std::min(std::max(active_round - hccr, 0), p.dur_table_size - 1);
    return dur_table[n];
  }

  bool proposed_block_valid(const Pacemaker& pm, const Store& s) const {
    return pm.active_epoch == s.epoch_id && pm.active_round == s.current_round &&
           pm.active_leader >= 0 && s.proposed_var >= 0;
  }

  PacemakerActions update_pacemaker(Pacemaker& pm, Store& s, int author,
                                    int epoch_id, int latest_query_all,
                                    int clk) {
    PacemakerActions a;
    int active_round = std::max(s.hqc_round, s.htc_round) + 1;
    bool enter = epoch_id > pm.active_epoch ||
                 (epoch_id == pm.active_epoch && active_round > pm.active_round);
    if (enter) {
      pm.active_epoch = epoch_id;
      pm.active_round = active_round;
      pm.active_leader = leader_of_round(weights, active_round);
      pm.round_start = clk;
      pm.round_duration = round_duration(active_round, s.hcr);
    }
    a.send_leader = (enter && pm.active_leader != author) ? pm.active_leader : -1;
    a.next_sched = NEVER;
    bool has_prop = proposed_block_valid(pm, s);
    s.hqc_ref(a.propose_prev_round, a.propose_prev_tag);
    a.should_propose = pm.active_leader == author && !has_prop;
    a.should_broadcast = a.should_propose;
    if (a.should_propose) a.next_sched = clk;
    bool has_to = s.has_timeout(author, pm.active_round);
    // Wide-int saturating sums: durations reach ~2^30, so int adds would be
    // UB; mirror the tensor path's min(a + b, NEVER).
    int deadline =
        (int)std::min<i64>((i64)pm.round_start + pm.round_duration, NEVER);
    bool past = clk >= deadline;
    a.should_create_timeout = !has_to && past;
    a.should_broadcast = a.should_broadcast || a.should_create_timeout;
    a.timeout_round = pm.active_round;
    if (!has_to && !past) a.next_sched = std::min(a.next_sched, deadline);
    int period = (int)(((i64)p.lam_fp * pm.round_duration) >> 16);
    int qad = (int)std::min<i64>((i64)latest_query_all + period, NEVER);
    a.should_query_all = has_to && clk >= qad;
    if (a.should_query_all)
      qad = (int)std::min<i64>((i64)clk + period, NEVER);
    if (has_to) a.next_sched = std::min(a.next_sched, qad);
    return a;
  }

  void process_commits(Store& s, NodeExtra& nx, Context& cx, int author,
                       NodeActions& out) {
    auto commits = s.committed_states_after(nx.tracker_hcr);
    int H = p.commit_log;
    bool sw = false;
    int sw_e = 0, sw_d = 0;
    u32 sw_t = 0;
    for (auto& c : commits) {
      if (sw || c.depth <= cx.last_depth) continue;
      int pos = cx.commit_count % H;
      cx.log_round[pos] = c.round;
      cx.log_depth[pos] = c.depth;
      cx.log_tag[pos] = c.tag;
      cx.commit_count++;
      cx.skipped_commits += c.depth - cx.last_depth - 1;
      cx.last_depth = c.depth;
      cx.last_tag = c.tag;
      int new_epoch = c.depth / p.commands_per_epoch;
      if (new_epoch > s.epoch_id) {
        sw = true;
        sw_e = new_epoch;
        sw_d = c.depth;
        sw_t = c.tag;
      }
    }
    if (sw) {
      // Cross-epoch handoff capture: the old store's response pack, built
      // post-update pre-switch (mirrors core/node.py process_commits).
      out.ho_switched = true;
      out.ho_epoch_old = s.epoch_id;
      if (p.epoch_handoff) out.ho_pack = handle_request(s, author, Payload());
      s.reset();
      s.epoch_id = sw_e;
      s.initial_tag = epoch_initial_tag((u32)sw_e);
      s.initial_state_depth = sw_d;
      s.initial_state_tag_ = sw_t;
      nx.latest_voted_round = 0;
      nx.locked_round = 0;
    }
  }

  void update_tracker(NodeExtra& nx, const Store& s, int clk,
                      bool& should_query_all, int& next_sched) {
    bool epoch_adv = s.epoch_id > nx.tracker_epoch;
    bool commit_adv = s.hcr > nx.tracker_hcr;
    bool bump = epoch_adv || commit_adv;
    nx.tracker_epoch = std::max(nx.tracker_epoch, s.epoch_id);
    if (bump) {
      nx.tracker_hcr = s.hcr;
      nx.tracker_commit_time = clk;
    }
    i64 deadline = (i64)std::max(nx.tracker_commit_time, nx.latest_query_all) +
                   p.target_commit_interval;
    should_query_all = clk >= deadline;
    if (should_query_all) deadline = (i64)clk + p.target_commit_interval;
    next_sched = (int)std::min<i64>(deadline, NEVER);
  }

  NodeActions update_node(Store& s, Pacemaker& pm, NodeExtra& nx, Context& cx,
                          int author, int clk) {
    int n = p.n_nodes;
    NodeActions out;
    out.send_mask.assign(n, 0);
    PacemakerActions pa =
        update_pacemaker(pm, s, author, s.epoch_id, nx.latest_query_all, clk);
    for (int i = 0; i < n; i++)
      out.send_mask[i] = (i == pa.send_leader && pa.send_leader >= 0);
    if (pa.should_create_timeout) {
      s.create_timeout(weights, author, pa.timeout_round);
      nx.latest_voted_round = std::max(nx.latest_voted_round, pa.timeout_round);
    }
    if (pa.should_propose) {
      s.propose_block(weights, author, pa.propose_prev_round,
                      pa.propose_prev_tag, clk, cx.next_cmd_index);
      cx.next_cmd_index++;
    }
    bool has_prop = proposed_block_valid(pm, s);
    int bvar = std::max(s.proposed_var, 0);
    int block_round = s.current_round;
    int proposer = s.blk_author[s.ix(s.slot(block_round), bvar)];
    int prev_r = s.previous_round(block_round, bvar);
    bool may_vote = has_prop && block_round > nx.latest_voted_round &&
                    prev_r >= nx.locked_round;
    if (may_vote) {
      int second_prev = s.second_previous_round(block_round, bvar);
      nx.latest_voted_round = block_round;
      nx.locked_round = std::max(nx.locked_round, second_prev);
      bool voted = s.create_vote(weights, author, block_round, bvar);
      if (voted)
        for (int i = 0; i < n; i++) out.send_mask[i] = (i == proposer);
    }
    bool qc_created = s.check_new_qc(weights, author);
    bool broadcast = pa.should_broadcast || qc_created;
    out.next_sched = qc_created ? clk : pa.next_sched;
    process_commits(s, nx, cx, author, out);
    bool tr_query;
    int tr_next;
    update_tracker(nx, s, clk, tr_query, tr_next);
    out.should_query_all = pa.should_query_all || tr_query;
    out.next_sched = std::min(out.next_sched, tr_next);
    if (out.should_query_all) nx.latest_query_all = clk;
    if (broadcast)
      for (int i = 0; i < n; i++)
        out.send_mask[i] = out.send_mask[i] || (i != author);
    return out;
  }

  // ---- data sync ----------------------------------------------------------
  QcMsg qc_msg_at(const Store& s, int r, int var, bool valid) const {
    QcMsg q;
    int sl = s.slot(r), k = s.ix(sl, var);
    int bk = s.ix(sl, s.qc_blk_var[k]);
    q.valid = valid; q.epoch = s.epoch_id; q.round = s.qc_round[k];
    q.blk_tag = s.blk_tag[bk]; q.state_depth = s.qc_state_depth[k];
    q.state_tag = s.qc_state_tag[k]; q.commit_valid = s.qc_commit_valid[k];
    q.commit_depth = s.qc_commit_depth[k]; q.commit_tag = s.qc_commit_tag[k];
    q.votes_lo = s.qc_votes_lo[k]; q.votes_hi = s.qc_votes_hi[k];
    q.author = s.qc_author[k]; q.tag = s.qc_tag[k];
    return q;
  }

  BlockMsg blk_msg_at(const Store& s, int r, int var, bool valid) const {
    BlockMsg b;
    int k = s.ix(s.slot(r), var);
    b.valid = valid; b.round = s.blk_round[k]; b.author = s.blk_author[k];
    b.prev_round = s.blk_prev_round[k]; b.prev_tag = s.blk_prev_tag[k];
    b.time = s.blk_time[k]; b.cmd_proposer = s.blk_cmd_proposer[k];
    b.cmd_index = s.blk_cmd_index[k]; b.tag = s.blk_tag[k];
    return b;
  }

  VoteMsg own_vote_msg(const Store& s, int author) const {
    int a = std::min(std::max(author, 0), p.n_nodes - 1);
    VoteMsg v;
    int bvar = s.vt_blk_var[a];
    v.valid = s.vt_valid[a]; v.epoch = s.epoch_id; v.round = s.current_round;
    v.blk_tag = s.blk_tag[s.ix(s.slot(s.current_round), bvar)];
    v.state_depth = s.vt_state_depth[a]; v.state_tag = s.vt_state_tag[a];
    v.commit_valid = s.vt_commit_valid[a];
    v.commit_depth = s.vt_commit_depth[a];
    v.commit_tag = s.vt_commit_tag[a];
    v.author = a;
    return v;
  }

  Payload create_notification(const Store& s, int author) const {
    Payload pay(p.n_nodes, p.chain_k);
    pay.epoch = s.epoch_id;
    pay.hcc = qc_msg_at(s, s.hcc_round, s.hcc_var, s.hcc_valid);
    pay.hqc = qc_msg_at(s, s.hqc_round, s.hqc_var, s.hqc_round > 0);
    int sl = s.slot(s.current_round);
    int prop_var = std::max(s.proposed_var, 0);
    bool prop_valid =
        s.proposed_var >= 0 && s.blk_author[s.ix(sl, prop_var)] == author;
    pay.prop_blk = blk_msg_at(s, s.current_round, prop_var, prop_valid);
    pay.vote = own_vote_msg(s, author);
    pay.tc_to.round = s.htc_round;
    pay.tc_to.valid = s.tc_valid;
    pay.tc_to.hcbr = s.tc_hcbr;
    pay.cur_to.round = s.current_round;
    pay.cur_to.valid = s.to_valid;
    pay.cur_to.hcbr = s.to_hcbr;
    return pay;
  }

  Payload create_request(const Store& s) const {
    Payload pay(p.n_nodes, p.chain_k);
    pay.epoch = s.epoch_id;
    pay.req_hqc_round = s.hqc_round;
    pay.req_hcr = s.hcr;
    return pay;
  }

  void insert_timeout_batch(Store& s, const TimeoutsMsg& tm, int rec_epoch) {
    for (int a = 0; a < p.n_nodes; a++)
      if (tm.valid[a]) s.insert_timeout(weights, rec_epoch, tm.round, tm.hcbr[a], a);
  }

  bool handle_notification(Store& s, const Payload& pay) {
    bool should_sync = pay.epoch > s.epoch_id;
    if (pay.hcc.valid) {
      s.insert_qc(weights, pay.hcc);
      should_sync =
          should_sync || pay.hcc.epoch > s.epoch_id ||
          (pay.hcc.epoch == s.epoch_id && pay.hcc.round > s.hcr + 2);
    }
    if (pay.hqc.valid) {
      s.insert_qc(weights, pay.hqc);
      should_sync =
          should_sync || pay.hqc.epoch > s.epoch_id ||
          (pay.hqc.epoch == s.epoch_id && pay.hqc.round > s.hqc_round);
    }
    if (pay.prop_blk.valid) s.insert_block(weights, pay.prop_blk, pay.epoch);
    insert_timeout_batch(s, pay.tc_to, pay.epoch);
    insert_timeout_batch(s, pay.cur_to, pay.epoch);
    if (pay.vote.valid) s.insert_vote(weights, pay.vote);
    return should_sync;
  }

  Payload handle_request(const Store& s, int author, const Payload&) const {
    Payload resp = create_notification(s, author);
    auto hops = s.qc_walk_back(s.hqc_round > 0, s.hqc_round, s.hqc_var,
                               p.chain_k);
    std::reverse(hops.begin(), hops.end());
    for (int i = 0; i < p.chain_k; i++) {
      int bvar = s.qc_blk_var[s.ix(s.slot(hops[i].round), hops[i].var)];
      resp.chain_blk[i] = blk_msg_at(s, hops[i].round, bvar, hops[i].valid);
      resp.chain_qc[i] = qc_msg_at(s, hops[i].round, hops[i].var, hops[i].valid);
    }
    int hcc_bvar = s.qc_blk_var[s.ix(s.slot(s.hcc_round), s.hcc_var)];
    resp.hcc_blk = blk_msg_at(s, s.hcc_round, hcc_bvar, s.hcc_valid);
    resp.vote.valid = false;
    return resp;
  }

  void handle_response(Store& s, NodeExtra& nx, Context& cx,
                       const Payload& pay) {
    bool gap_jump =
        pay.hqc.valid &&
        (pay.epoch > s.epoch_id ||
         pay.hqc.round > s.hqc_round + (p.window - p.chain_k));
    bool do_jump = gap_jump && pay.chain_qc[0].valid;
    if (do_jump) {
      const QcMsg& base = pay.chain_qc[0];
      s.reset();
      s.epoch_id = pay.epoch;
      s.initial_round = base.round;
      s.initial_tag = base.tag;
      s.initial_state_depth = base.state_depth;
      s.initial_state_tag_ = base.state_tag;
      s.current_round = base.round + 1;
      s.hqc_round = base.round;
      s.htc_round = base.round;
      s.hcr = base.round;
      s.anchored = true;
      nx.latest_voted_round = 0;
      nx.locked_round = 0;
      if (pay.hcc.valid && pay.hcc.commit_valid &&
          pay.hcc.commit_depth > cx.last_depth) {
        cx.skipped_commits += pay.hcc.commit_depth - cx.last_depth;
        cx.last_depth = pay.hcc.commit_depth;
        cx.last_tag = pay.hcc.commit_tag;
      }
      cx.sync_jumps++;
    }
    for (int i = 0; i < p.chain_k; i++) {
      if (do_jump && i == 0) continue;
      if (pay.chain_blk[i].valid) s.insert_block(weights, pay.chain_blk[i], pay.epoch);
      if (pay.chain_qc[i].valid) s.insert_qc(weights, pay.chain_qc[i]);
    }
    if (pay.hcc_blk.valid) s.insert_block(weights, pay.hcc_blk, pay.epoch);
    if (pay.hcc.valid) s.insert_qc(weights, pay.hcc);
    insert_timeout_batch(s, pay.tc_to, pay.epoch);
    insert_timeout_batch(s, pay.cur_to, pay.epoch);
    if (pay.prop_blk.valid) s.insert_block(weights, pay.prop_blk, pay.epoch);
  }

  Payload equivocated(const Payload& pay) const {
    Payload p2 = pay;
    const BlockMsg& b = pay.prop_blk;
    p2.prop_blk.cmd_index = b.cmd_index + EQUIV_SALT;
    p2.prop_blk.tag =
        fold(TAG_BLOCK, (u32)pay.epoch, (u32)b.round, (u32)b.author,
             (u32)b.prev_round, b.prev_tag, (u32)b.time, (u32)b.cmd_proposer,
             (u32)(b.cmd_index + EQUIV_SALT));
    p2.vote.valid = false;
    return p2;
  }

  // ---- the event loop -----------------------------------------------------
  void select_event(int& idx, int& t_min, bool& is_timer) const {
    int cm = p.queue_cap, n = p.n_nodes;
    t_min = NEVER;
    for (int i = 0; i < cm; i++)
      t_min = std::min(t_min, queue[i].valid ? queue[i].time : NEVER);
    for (int i = 0; i < n; i++) t_min = std::min(t_min, timer_time[i]);
    int k_best = -1;
    for (int i = 0; i < cm; i++)
      if (queue[i].valid && queue[i].time == t_min)
        k_best = std::max(k_best, queue[i].kind);
    for (int i = 0; i < n; i++)
      if (timer_time[i] == t_min) k_best = std::max(k_best, KIND_TIMER);
    int s_best = NEVER;
    idx = -1;
    for (int i = 0; i < cm; i++)
      if (queue[i].valid && queue[i].time == t_min && queue[i].kind == k_best &&
          queue[i].stamp < s_best) {
        s_best = queue[i].stamp;
      }
    for (int i = 0; i < n; i++)
      if (timer_time[i] == t_min && k_best == KIND_TIMER &&
          timer_stamp[i] < s_best) {
        s_best = timer_stamp[i];
      }
    for (int i = 0; i < cm && idx < 0; i++)
      if (queue[i].valid && queue[i].time == t_min && queue[i].kind == k_best &&
          queue[i].stamp == s_best)
        idx = i;
    for (int i = 0; i < n && idx < 0; i++)
      if (timer_time[i] == t_min && k_best == KIND_TIMER &&
          timer_stamp[i] == s_best)
        idx = cm + i;
    is_timer = idx >= cm;
  }

  void step() {
    int n = p.n_nodes, cm = p.queue_cap;
    int idx, t_min;
    bool is_timer;
    select_event(idx, t_min, is_timer);
    if (halted || t_min > p.max_clock) {
      halted = true;
      return;
    }
    int clk = std::max(clock, std::min(t_min, NEVER - 1));
    int kind, a, sender;
    Payload pay_in(n, p.chain_k);
    if (is_timer) {
      a = idx - cm;
      kind = KIND_TIMER;
      sender = 0;
    } else {
      Msg& m = queue[idx];
      kind = m.kind;
      a = std::min(std::max(m.receiver, 0), n - 1);
      sender = m.sender;
      pay_in = m.pay;
      m.valid = false;
    }
    Store& s = stores[a];
    Pacemaker& pm = pms[a];
    NodeExtra& nx = nxs[a];
    Context& cx = ctxs[a];
    int local_clock = clk - startup[a];

    bool is_notify = kind == KIND_NOTIFY && !is_timer;
    bool is_request = kind == KIND_REQUEST && !is_timer;
    bool is_response = kind == KIND_RESPONSE && !is_timer;
    bool do_update = is_timer || is_notify || is_response;

    bool should_sync = false;
    if (is_notify) should_sync = handle_notification(s, pay_in);
    else if (is_response) handle_response(s, nx, cx, pay_in);

    NodeActions actions;
    actions.send_mask.assign(n, 0);
    if (do_update) actions = update_node(s, pm, nx, cx, a, local_clock);

    Payload response = handle_request(s, a, pay_in);
    // Cross-epoch handoff (mirrors sim/simulator.py): capture the pack
    // update_node built from the post-update, pre-switch store; serve it to
    // requesters still in that epoch.
    if (p.epoch_handoff > 0) {
      int E_ho = p.epoch_handoff;
      if (do_update && actions.ho_switched) {
        int wslot = std::max(actions.ho_epoch_old, 0) % E_ho;
        ho_pay[a][wslot] = actions.ho_pack;
        ho_epoch[a][wslot] = actions.ho_epoch_old;
      }
      int rslot = std::max(pay_in.epoch, 0) % E_ho;
      if (is_request && pay_in.epoch == ho_epoch[a][rslot] &&
          pay_in.epoch < s.epoch_id)
        response = ho_pay[a][rslot];
    }

    bool silent = byz_silent[a];
    bool want_sync_req = is_notify && should_sync && !silent;
    bool want_response = is_request && !silent;
    bool cand0_want = want_sync_req || want_response;
    int cand0_kind = want_response ? KIND_RESPONSE : KIND_REQUEST;
    int cand0_recv = std::min(std::max(sender, 0), n - 1);

    Payload notif = create_notification(s, a);
    Payload notif_b = equivocated(notif);
    Payload request = create_request(s);

    int ncand = 2 * n + 1;
    std::vector<uint8_t> want(ncand, 0);
    std::vector<int> kinds(ncand), recvs(ncand), paysel(ncand, 2);
    want[0] = cand0_want;
    kinds[0] = cand0_kind;
    recvs[0] = cand0_recv;
    paysel[0] = want_response ? 3 : 2;
    // Seeded receiver permutation (mirrors sim/simulator.py: stable sort of
    // per-receiver hash keys off (seed, pre-update stamp_ctr)).
    std::vector<int> recv_order(n);
    for (int i = 0; i < n; i++) recv_order[i] = i;
    if (p.shuffle_receivers) {
      u32 base = rng_u32(seed, (u32)stamp_ctr);
      std::vector<u32> keys(n);
      for (int i = 0; i < n; i++) keys[i] = mix32(base, (u32)(i + 1));
      std::stable_sort(recv_order.begin(), recv_order.end(),
                       [&](int x, int y) { return keys[x] < keys[y]; });
    }
    for (int i = 0; i < n; i++) {
      int r = recv_order[i];
      want[1 + i] = actions.send_mask[r] && r != a && do_update && !silent;
      kinds[1 + i] = KIND_NOTIFY;
      recvs[1 + i] = r;
      paysel[1 + i] = (byz_eq[a] && (r * 2 >= n)) ? 1 : 0;
      want[1 + n + i] =
          actions.should_query_all && do_update && !silent && r != a;
      kinds[1 + n + i] = KIND_REQUEST;
      recvs[1 + n + i] = r;
      paysel[1 + n + i] = 2;
    }
    int timer_gap = do_update ? 1 : 0;
    std::vector<int> stamps(ncand);
    {
      int pos = -1;
      for (int j = 0; j < ncand; j++) {
        if (want[j]) pos++;
        stamps[j] = stamp_ctr + pos + (j > 0 ? timer_gap : 0);
      }
    }
    int total_consumed = timer_gap;
    for (int j = 0; j < ncand; j++) total_consumed += want[j] ? 1 : 0;
    int timer_stamp_new = stamp_ctr + (cand0_want ? 1 : 0);

    std::vector<int> free_slots;
    for (int i = 0; i < cm; i++)
      if (!queue[i].valid) free_slots.push_back(i);
    size_t rank = 0;
    for (int j = 0; j < ncand; j++) {
      if (!want[j]) continue;
      u32 u_delay = rng_u32(seed, (u32)stamps[j]);
      u32 u_drop = mix32(u_delay, 0x632BE59Bu);
      int delay = delay_table[u_delay >> (32 - TABLE_BITS)];
      if (u_drop < p.drop_u32) {
        n_msgs_dropped++;
        continue;
      }
      if (rank >= free_slots.size()) {
        n_queue_full++;
        rank++;
        continue;
      }
      Msg& m = queue[free_slots[rank++]];
      m.valid = true;
      m.time = clk + delay;
      m.kind = kinds[j];
      m.stamp = stamps[j];
      m.sender = a;
      m.receiver = recvs[j];
      switch (paysel[j]) {
        case 0: m.pay = notif; break;
        case 1: m.pay = notif_b; break;
        case 2: m.pay = request; break;
        default: m.pay = response;
      }
      n_msgs_sent++;
    }
    if (do_update) {
      i64 next_g = actions.next_sched >= NEVER
                       ? (i64)NEVER
                       : std::min<i64>((i64)actions.next_sched + startup[a], NEVER);
      timer_time[a] = (int)std::max<i64>(next_g, (i64)clk + 1);
      timer_stamp[a] = timer_stamp_new;
    }
    clock = clk;
    stamp_ctr += total_consumed;
    n_events++;
  }

  void run(i64 max_events) {
    for (i64 i = 0; i < max_events && !halted; i++) step();
  }
};

}  // namespace

extern "C" {

// Flat result layout per node: commit_count, last_depth, last_tag,
// current_round, hqc_round, hcr, sync_jumps, skipped_commits (8 i64 each),
// then the commit ring: commit_log * 3 entries (round, depth, tag) per node.
int bft_run(
    // params
    int n_nodes, int window, int queue_cap, int chain_k, int commit_log,
    int commands_per_epoch, int target_commit_interval, int lam_fp,
    int commit_chain, int max_clock, int dur_table_size,
    int shuffle_receivers, int epoch_handoff, u32 drop_u32, u32 seed,
    i64 max_events,
    // tables / masks
    const int* delay_table, const int* dur_table, const int* weights,
    const uint8_t* byz_eq, const uint8_t* byz_silent,
    // outputs
    i64* global_out,  // [6]: n_events, clock, stamp_ctr, sent, dropped, full
    i64* node_out,    // [n_nodes * 7]
    i64* log_out      // [n_nodes * commit_log * 3]
) {
  Params p;
  p.n_nodes = n_nodes; p.window = window; p.queue_cap = queue_cap;
  p.chain_k = chain_k; p.commit_log = commit_log;
  p.commands_per_epoch = commands_per_epoch;
  p.target_commit_interval = target_commit_interval;
  p.delta = 0; p.lam_fp = lam_fp; p.commit_chain = commit_chain;
  p.max_clock = max_clock; p.dur_table_size = dur_table_size;
  p.shuffle_receivers = shuffle_receivers;
  p.epoch_handoff = epoch_handoff;
  p.drop_u32 = drop_u32;
  Engine e(p, seed, delay_table, dur_table, weights, byz_eq, byz_silent);
  e.run(max_events);
  global_out[0] = e.n_events;
  global_out[1] = e.clock;
  global_out[2] = e.stamp_ctr;
  global_out[3] = e.n_msgs_sent;
  global_out[4] = e.n_msgs_dropped;
  global_out[5] = e.n_queue_full;
  for (int a = 0; a < n_nodes; a++) {
    const Store& s = e.stores[a];
    const Context& c = e.ctxs[a];
    i64* o = node_out + a * 8;
    o[0] = c.commit_count;
    o[1] = c.last_depth;
    o[2] = c.last_tag;
    o[3] = s.current_round;
    o[4] = s.hqc_round;
    o[5] = s.hcr;
    o[6] = c.sync_jumps;
    o[7] = c.skipped_commits;
    for (int i = 0; i < commit_log; i++) {
      i64* l = log_out + (a * commit_log + i) * 3;
      l[0] = c.log_round[i];
      l[1] = c.log_depth[i];
      l[2] = c.log_tag[i];
    }
  }
  return e.halted ? 1 : 0;
}

}  // extern "C"
