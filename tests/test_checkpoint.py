"""Checkpoint/resume: a restored run continues bit-identically."""

import jax
import numpy as np
import pytest

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import checkpoint as C
from librabft_simulator_tpu.sim import simulator as S


def test_save_load_roundtrip(tmp_path):
    p = SimParams(n_nodes=3, max_clock=500)
    st = S.run_to_completion(p, S.init_state(p, 42))
    f = str(tmp_path / "ck.npz")
    C.save(f, st)
    st2 = C.load(f, p, like=S.init_state(p, 0))
    for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(st2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resume_continues_identically(tmp_path):
    p = SimParams(n_nodes=3, max_clock=2**30)
    run = S.make_run_fn(p, 64, batched=False)
    st_full = run(S.dedupe_buffers(S.init_state(p, 7)))
    st_full = run(st_full)

    st_half = run(S.dedupe_buffers(S.init_state(p, 7)))
    f = str(tmp_path / "half.npz")
    C.save(f, st_half)
    st_resumed = C.load(f, p, like=S.init_state(p, 0))
    st_resumed = run(S.dedupe_buffers(st_resumed))
    for a, b in zip(jax.tree.leaves(st_full), jax.tree.leaves(st_resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_soft_state_compat_on_shape_change(tmp_path):
    """Soft diagnostic/cache state survives capacity changes between save
    and resume: a trace_cap=0 checkpoint restores into a traced config with
    an empty, COHERENT ring (count reset with the arrays — a preserved
    count over a zeroed ring would fabricate decoder entries), and a
    resized handoff ring (grown and shrunk) restores empty (ho_epoch -1),
    while PROTOCOL leaves restore exactly."""
    p0 = SimParams(n_nodes=3, max_clock=400, trace_cap=0, handoff_epochs=2)
    st = S.run_to_completion(p0, S.init_state(p0, 11))
    assert int(np.asarray(st.trace_count)) > 0  # counted even when cap=0
    f = str(tmp_path / "soft.npz")
    C.save(f, st)

    for e_new in (3, 1):  # grow and shrink the handoff ring
        p1 = SimParams(n_nodes=3, max_clock=400, trace_cap=64,
                       handoff_epochs=e_new)
        st2 = C.load(f, p1, like=S.init_state(p1, 0))
        np.testing.assert_array_equal(np.asarray(st2.trace_node),
                                      np.zeros(64, np.int32))
        assert int(np.asarray(st2.trace_count)) == 0  # coherent empty ring
        np.testing.assert_array_equal(np.asarray(st2.ho_epoch),
                                      np.full((3, e_new), -1, np.int32))
        np.testing.assert_array_equal(np.asarray(st2.store.current_round),
                                      np.asarray(st.store.current_round))
        np.testing.assert_array_equal(np.asarray(st2.ctx.commit_count),
                                      np.asarray(st.ctx.commit_count))


def test_batched_checkpoint(tmp_path):
    p = SimParams(n_nodes=3, max_clock=300)
    st = S.run_to_completion(p, S.init_batch(p, np.arange(4, dtype=np.uint32)),
                             batched=True)
    f = str(tmp_path / "batch.npz")
    C.save(f, st)
    st2 = C.load(f, p, like=S.init_batch(p, np.zeros(4, np.uint32)))
    for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(st2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_restore_pads_and_masks(tmp_path):
    """Restoring a checkpoint saved at a batch the mesh's device count
    doesn't divide pads with pre-halted instances instead of crashing:
    protocol leaves restore exactly onto the mesh (placed shard by shard),
    the padding is born halted with zero observables, and a divisible batch
    restores without padding."""
    from librabft_simulator_tpu.parallel import mesh as mesh_ops

    p = SimParams(n_nodes=3, max_clock=300)
    st = S.init_batch(p, np.arange(5, dtype=np.uint32))
    f = str(tmp_path / "fleet.npz")
    C.save(f, st)

    mesh = mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])
    st2, n_valid = C.load_sharded(f, p, mesh)  # 5 % 2 != 0 -> pad to 6
    assert n_valid == 5
    assert int(st2.clock.shape[0]) == 6
    assert len(st2.clock.sharding.device_set) == 2
    for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(st2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b)[:5])
    halted = np.asarray(st2.halted)
    assert bool(halted[5]) and not halted[:5].any()
    assert int(np.asarray(st2.n_events)[5]) == 0

    # Divisible batch: no padding, same placement path.
    st4 = S.init_batch(p, np.arange(4, dtype=np.uint32))
    f4 = str(tmp_path / "fleet4.npz")
    C.save(f4, st4)
    st5, n_valid4 = C.load_sharded(f4, p, mesh)
    assert n_valid4 == 4 and int(st5.clock.shape[0]) == 4
    for a, b in zip(jax.tree.leaves(st4), jax.tree.leaves(st5)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # A single-instance checkpoint is not a fleet: clear error, not a crash.
    f1 = str(tmp_path / "one.npz")
    C.save(f1, S.init_state(p, 0))
    with pytest.raises(ValueError, match="batched"):
        C.load_sharded(f1, p, mesh)


def test_load_checkpoint_missing_new_fields(tmp_path):
    """Checkpoints written before a SimState field existed still load: the
    absent leaves default to their freshly-initialised values."""
    p = SimParams(n_nodes=3, max_clock=300)
    st = S.run_to_completion(p, S.init_state(p, 3))
    f = str(tmp_path / "old.npz")
    C.save(f, st)
    # Simulate an old checkpoint: strip the round-4 handoff leaves.
    data = dict(np.load(f))
    stripped = {k: v for k, v in data.items() if not k.startswith("ho_")}
    assert len(stripped) < len(data)
    np.savez_compressed(f, **stripped)
    st2 = C.load(f, p, like=S.init_state(p, 0))
    like = S.init_state(p, 0)
    np.testing.assert_array_equal(np.asarray(st2.ho_epoch),
                                  np.asarray(like.ho_epoch))
    assert int(st2.n_events) == int(st.n_events)
    np.testing.assert_array_equal(np.asarray(st2.ctx.commit_count),
                                  np.asarray(st.ctx.commit_count))


def test_scenario_plane_restore_pre_pr11(tmp_path):
    """A pre-PR-11 checkpoint (no sc_* leaves) restores into a
    scenario-armed config with knob-DEFAULT plane rows — the scenario the
    load params themselves describe — and the resumed run continues
    bit-identically to an uninterrupted scenario run carrying those same
    default rows (the PR 4 watchdog-restore pattern, except the default
    is the params' values, not zeros)."""
    import dataclasses

    from fleet_shapes import FLEET_SCENARIO_SER_KW, SERVE_CHUNK, SERVE_SLOTS

    p = SimParams(max_clock=2**30, **FLEET_SCENARIO_SER_KW)
    run = S.make_run_fn(p, SERVE_CHUNK, batched=True)
    seeds = np.arange(SERVE_SLOTS, dtype=np.uint32)
    full = run(S.dedupe_buffers(S.init_batch(p, seeds)))
    full = run(full)

    half = run(S.dedupe_buffers(S.init_batch(p, seeds)))
    f = str(tmp_path / "pre11.npz")
    C.save(f, half)
    # Simulate the pre-PR-11 artifact: strip the scenario leaves.
    data = dict(np.load(f))
    stripped = {k: v for k, v in data.items() if not k.startswith("sc_")}
    assert len(stripped) == len(data) - 2
    np.savez_compressed(f, **stripped)
    st2 = C.load(f, p, like=S.init_batch(p, np.zeros(SERVE_SLOTS,
                                                     np.uint32)))
    # Knob-default rows synthesized from the load params.
    np.testing.assert_array_equal(
        np.asarray(st2.sc_delay),
        np.broadcast_to(p.delay_table(), (SERVE_SLOTS,) +
                        p.delay_table().shape))
    np.testing.assert_array_equal(
        np.asarray(st2.sc_commit),
        np.full((SERVE_SLOTS, 1), p.commit_chain, np.int32))
    # Round-trip regression: the resumed run continues bit-identically.
    st2 = run(S.dedupe_buffers(st2))
    for a, b in zip(jax.tree.leaves(full), jax.tree.leaves(st2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # A scenario-on checkpoint loaded scenario-OFF drops the plane loudly
    # into the static knobs (zero-width leaves) and still restores the
    # protocol state exactly.
    p_off = dataclasses.replace(p, scenario=False)
    off = C.load(f, p_off, like=S.init_batch(p_off,
                                             np.zeros(SERVE_SLOTS,
                                                      np.uint32)))
    assert np.asarray(off.sc_delay).shape == (SERVE_SLOTS, 0)
    np.testing.assert_array_equal(np.asarray(off.clock),
                                  np.asarray(half.clock))


def test_macro_step_boundary_roundtrip(tmp_path):
    """K-event macro-steps (SimParams.macro_k) across a checkpoint: a K=4
    run checkpointed mid-run restores and CONTINUES UNDER K=1
    bit-identically — the state at a macro-step boundary is exactly the
    K=1 state after the same number of events, so checkpoints are
    portable across K (an operator can change the dispatch amortization
    between save and resume without forking the trajectory).  Shapes are
    the warmed tests/fleet_shapes.py micro contract (macro_k is a
    compile key)."""
    from fleet_shapes import (FLEET_B, FLEET_CHUNK, FLEET_MACRO_K,
                              FLEET_MACRO_SER_KW, FLEET_SER_KW)

    p1 = SimParams(max_clock=2**30, **FLEET_SER_KW)
    p4 = SimParams(max_clock=2**30, **FLEET_MACRO_SER_KW)
    seeds = np.arange(FLEET_B, dtype=np.uint32)
    run1 = S.make_run_fn(p1, FLEET_CHUNK)   # FLEET_CHUNK events/chunk
    run4 = S.make_run_fn(p4, FLEET_CHUNK)   # FLEET_CHUNK * K events/chunk

    # One K=4 chunk, checkpointed at its macro-step boundary...
    st4 = run4(S.dedupe_buffers(S.init_batch(p4, seeds)))
    f = str(tmp_path / "macro.npz")
    C.save(f, st4)
    # ... restores exactly (same leaves back) ...
    st_res = C.load(f, p1, like=S.init_batch(p1, np.zeros(FLEET_B, np.uint32)))
    for a, b in zip(jax.tree.leaves(st4), jax.tree.leaves(st_res)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # ... and a K=1 continuation of the restored state lands bit-identical
    # to a pure K=1 run of the same total event count.
    st_res = S.dedupe_buffers(st_res)
    for _ in range(FLEET_MACRO_K):
        st_res = run1(st_res)
    st_ref = S.dedupe_buffers(S.init_batch(p1, seeds))
    for _ in range(2 * FLEET_MACRO_K):
        st_ref = run1(st_ref)
    for (pt, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(st_ref)[0],
            jax.tree_util.tree_flatten_with_path(st_res)[0]):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            "/".join(str(q) for q in pt))


def test_watchdog_leaf_restore(tmp_path):
    """Round 9's consensus-watchdog plane through the checkpoint paths:
    (1) a watchdog-on save/load round-trips the wd counters exactly;
    (2) a pre-stream checkpoint (no wd key) restores under a watchdog-on
        config with an EMPTY wd plane (counters restart; protocol leaves
        exact);
    (3) a watchdog toggle between save and resume (shape change) restarts
        the plane empty instead of failing."""
    from fleet_shapes import FLEET_B, FLEET_CHUNK, FLEET_WD_LANE_KW
    from librabft_simulator_tpu.telemetry import stream as tstream

    # The warmed micro fleet shape (tests/fleet_shapes.py): the checkpoint
    # paths add no compiles of their own.  The silent node guarantees a
    # nonzero wd counter so the round trip pins real data, not zeros.
    p_wd = SimParams(max_clock=150, **FLEET_WD_LANE_KW)
    seeds = np.arange(FLEET_B, dtype=np.uint32)
    st = S.init_batch(p_wd, seeds)
    st = st.replace(byz_silent=st.byz_silent.at[2, 0].set(True))
    st = S.run_to_completion(p_wd, st, chunk=FLEET_CHUNK, batched=True)
    assert np.asarray(st.wd).shape == (FLEET_B, tstream.WD_WIDTH)
    assert np.asarray(st.wd)[:, 1:].any()  # something actually tripped
    like = S.init_batch(p_wd, np.zeros(FLEET_B, np.uint32))
    f = str(tmp_path / "wd.npz")
    C.save(f, st)
    st2 = C.load(f, p_wd, like=like)
    np.testing.assert_array_equal(np.asarray(st2.wd), np.asarray(st.wd))

    # (2) strip the wd key: the pre-PR-4 checkpoint shape.
    data = dict(np.load(f))
    assert "wd" in data
    del data["wd"]
    f_old = str(tmp_path / "old.npz")
    np.savez_compressed(f_old, **data)
    st3 = C.load(f_old, p_wd, like=like)
    np.testing.assert_array_equal(
        np.asarray(st3.wd), np.zeros((FLEET_B, tstream.WD_WIDTH), np.int32))
    np.testing.assert_array_equal(np.asarray(st3.n_events),
                                  np.asarray(st.n_events))
    np.testing.assert_array_equal(np.asarray(st3.ctx.commit_count),
                                  np.asarray(st.ctx.commit_count))

    # (3) watchdog off at resume: zero-width plane, protocol leaves exact.
    p_off = SimParams(max_clock=150, **{
        k: v for k, v in FLEET_WD_LANE_KW.items()
        if not k.startswith("watchdog")})
    st4 = C.load(f, p_off,
                 like=S.init_batch(p_off, np.zeros(FLEET_B, np.uint32)))
    assert np.asarray(st4.wd).shape == (FLEET_B, 0)
    np.testing.assert_array_equal(np.asarray(st4.ctx.commit_count),
                                  np.asarray(st.ctx.commit_count))


def test_ring_preemption_resume_across_outer_call(tmp_path, monkeypatch):
    """Device-dispatch preemption: a wrap="device" fleet checkpointed at
    an outer-call boundary (the ONLY place state egresses — mid-ring the
    chunks live in-graph) resumes bit-identically to an uninterrupted
    run, under BOTH wraps.  The ring retires up to K=4 chunks per outer
    call, so the saved state is 8 chunks in after just 2 dispatches; the
    resume may change the wrap (device -> host and device -> device) —
    like macro_k, the dispatch amortization is a deployment knob, never a
    trajectory fork.  AOT off: load_sharded's callback-placed arrays are
    the input form deserialized executables abort on."""
    from fleet_shapes import (FLEET_B, FLEET_CHUNK, FLEET_RING_SER_KW,
                              FLEET_SER_KW)
    from librabft_simulator_tpu.parallel import mesh as mesh_ops
    from librabft_simulator_tpu.parallel import sharded

    monkeypatch.setenv("LIBRABFT_AOT", "0")
    p_ring = SimParams(max_clock=120, **FLEET_RING_SER_KW)
    p_host = SimParams(max_clock=120, **FLEET_SER_KW)
    seeds = sharded.fleet_seeds(0, FLEET_B)
    mesh2 = mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])

    ref = sharded.run_sharded(p_ring, mesh2, S.init_batch(p_ring, seeds),
                              num_steps=FLEET_CHUNK * 200,
                              chunk=FLEET_CHUNK)

    # Preempt after 2 outer calls (8 chunks at K=4).
    mid = sharded.run_sharded(p_ring, mesh2, S.init_batch(p_ring, seeds),
                              num_steps=FLEET_CHUNK * 8, chunk=FLEET_CHUNK)
    f = str(tmp_path / "ring.npz")
    C.save(f, mid)

    for p_resume in (p_host, p_ring):
        st, n_valid = C.load_sharded(f, p_resume, mesh2)
        assert n_valid == FLEET_B
        out = sharded.run_sharded(p_resume, mesh2, st,
                                  num_steps=FLEET_CHUNK * 200,
                                  chunk=FLEET_CHUNK, pad=False)
        wrap = p_resume.wrap or "host"
        for (pt, a), (_, b) in zip(
                jax.tree_util.tree_flatten_with_path(ref)[0],
                jax.tree_util.tree_flatten_with_path(out)[0]):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)[:FLEET_B],
                err_msg=f"resume wrap={wrap}: "
                        + "/".join(str(q) for q in pt))


def test_topology_change_dp2_to_dp4_and_dp3(tmp_path, monkeypatch):
    """Elastic-resize substrate: a fleet checkpointed mid-run on a dp=2
    mesh restores onto dp=4 AND dp=3 (the pad-and-mask path — 5 % 3 and
    5 % 4 both force pre-halted padding), continues, and the final state
    is bit-equal to an uninterrupted run — the device count is a pure
    deployment choice, never a trajectory fork.  Micro shapes from
    tests/fleet_shapes.py (the warmed contract).  AOT off: this test
    dispatches on load_sharded's callback-placed arrays, the input form
    deserialized executables abort on (the ResidentFleet.restore rule);
    the jit path under test here is fine with them."""
    from fleet_shapes import FLEET_B, FLEET_CHUNK, FLEET_SER_KW
    from librabft_simulator_tpu.parallel import mesh as mesh_ops
    from librabft_simulator_tpu.parallel import sharded

    monkeypatch.setenv("LIBRABFT_AOT", "0")
    p = SimParams(max_clock=120, **FLEET_SER_KW)
    seeds = sharded.fleet_seeds(0, FLEET_B)
    mesh2 = mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])

    # Uninterrupted reference (the tier-1 parity fixtures pin this equal
    # to the unsharded engines already).
    ref = sharded.run_sharded(p, mesh2, S.init_batch(p, seeds),
                              num_steps=FLEET_CHUNK * 200,
                              chunk=FLEET_CHUNK)

    # Mid-run checkpoint at a chunk boundary on dp=2.
    mid = sharded.run_sharded(p, mesh2, S.init_batch(p, seeds),
                              num_steps=FLEET_CHUNK * 2, chunk=FLEET_CHUNK)
    f = str(tmp_path / "dp2.npz")
    C.save(f, mid)  # mid landed on host (padded odd batch), rows [0, B)

    for n_dp in (4, 3):
        mesh_new = mesh_ops.make_mesh(n_dp=n_dp, n_mp=1,
                                      devices=jax.devices()[:n_dp])
        st, n_valid = C.load_sharded(f, p, mesh_new)
        assert n_valid == FLEET_B
        padded = -(-FLEET_B // n_dp) * n_dp
        assert int(st.clock.shape[0]) == padded
        # Padding rows are born halted; real rows carry whatever the
        # mid-run state says (some may have halted naturally already).
        assert np.asarray(st.halted)[FLEET_B:].all()
        # Continue on the NEW topology to completion; the pre-placed
        # state is already padded, so run_sharded pads zero more.
        out = sharded.run_sharded(p, mesh_new, st,
                                  num_steps=FLEET_CHUNK * 200,
                                  chunk=FLEET_CHUNK, pad=False)
        for (pt, a), (_, b) in zip(
                jax.tree_util.tree_flatten_with_path(ref)[0],
                jax.tree_util.tree_flatten_with_path(out)[0]):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)[:FLEET_B],
                err_msg=f"dp={n_dp}: " + "/".join(str(q) for q in pt))
