"""Checkpoint/resume: a restored run continues bit-identically."""

import jax
import numpy as np

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import checkpoint as C
from librabft_simulator_tpu.sim import simulator as S


def test_save_load_roundtrip(tmp_path):
    p = SimParams(n_nodes=3, max_clock=500)
    st = S.run_to_completion(p, S.init_state(p, 42))
    f = str(tmp_path / "ck.npz")
    C.save(f, st)
    st2 = C.load(f, p, like=S.init_state(p, 0))
    for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(st2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resume_continues_identically(tmp_path):
    p = SimParams(n_nodes=3, max_clock=2**30)
    run = S.make_run_fn(p, 64, batched=False)
    st_full = run(S.dedupe_buffers(S.init_state(p, 7)))
    st_full = run(st_full)

    st_half = run(S.dedupe_buffers(S.init_state(p, 7)))
    f = str(tmp_path / "half.npz")
    C.save(f, st_half)
    st_resumed = C.load(f, p, like=S.init_state(p, 0))
    st_resumed = run(S.dedupe_buffers(st_resumed))
    for a, b in zip(jax.tree.leaves(st_full), jax.tree.leaves(st_resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_soft_state_compat_on_shape_change(tmp_path):
    """Soft diagnostic/cache state survives capacity changes between save
    and resume: a trace_cap=0 checkpoint restores into a traced config with
    an empty, COHERENT ring (count reset with the arrays — a preserved
    count over a zeroed ring would fabricate decoder entries), and a
    resized handoff ring (grown and shrunk) restores empty (ho_epoch -1),
    while PROTOCOL leaves restore exactly."""
    p0 = SimParams(n_nodes=3, max_clock=400, trace_cap=0, handoff_epochs=2)
    st = S.run_to_completion(p0, S.init_state(p0, 11))
    assert int(np.asarray(st.trace_count)) > 0  # counted even when cap=0
    f = str(tmp_path / "soft.npz")
    C.save(f, st)

    for e_new in (3, 1):  # grow and shrink the handoff ring
        p1 = SimParams(n_nodes=3, max_clock=400, trace_cap=64,
                       handoff_epochs=e_new)
        st2 = C.load(f, p1, like=S.init_state(p1, 0))
        np.testing.assert_array_equal(np.asarray(st2.trace_node),
                                      np.zeros(64, np.int32))
        assert int(np.asarray(st2.trace_count)) == 0  # coherent empty ring
        np.testing.assert_array_equal(np.asarray(st2.ho_epoch),
                                      np.full((3, e_new), -1, np.int32))
        np.testing.assert_array_equal(np.asarray(st2.store.current_round),
                                      np.asarray(st.store.current_round))
        np.testing.assert_array_equal(np.asarray(st2.ctx.commit_count),
                                      np.asarray(st.ctx.commit_count))


def test_batched_checkpoint(tmp_path):
    p = SimParams(n_nodes=3, max_clock=300)
    st = S.run_to_completion(p, S.init_batch(p, np.arange(4, dtype=np.uint32)),
                             batched=True)
    f = str(tmp_path / "batch.npz")
    C.save(f, st)
    st2 = C.load(f, p, like=S.init_batch(p, np.zeros(4, np.uint32)))
    for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(st2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_load_checkpoint_missing_new_fields(tmp_path):
    """Checkpoints written before a SimState field existed still load: the
    absent leaves default to their freshly-initialised values."""
    p = SimParams(n_nodes=3, max_clock=300)
    st = S.run_to_completion(p, S.init_state(p, 3))
    f = str(tmp_path / "old.npz")
    C.save(f, st)
    # Simulate an old checkpoint: strip the round-4 handoff leaves.
    data = dict(np.load(f))
    stripped = {k: v for k, v in data.items() if not k.startswith("ho_")}
    assert len(stripped) < len(data)
    np.savez_compressed(f, **stripped)
    st2 = C.load(f, p, like=S.init_state(p, 0))
    like = S.init_state(p, 0)
    np.testing.assert_array_equal(np.asarray(st2.ho_epoch),
                                  np.asarray(like.ho_epoch))
    assert int(st2.n_events) == int(st.n_events)
    np.testing.assert_array_equal(np.asarray(st2.ctx.commit_count),
                                  np.asarray(st.ctx.commit_count))
