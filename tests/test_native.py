"""Native C++ engine parity vs the Python oracle (and transitively the JAX
path, via tests/test_parity.py)."""

import numpy as np
import pytest

from librabft_simulator_tpu import native
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.oracle.sim import OracleSim


def assert_native_matches_oracle(p, seed, **kw):
    res = native.run(p, seed, **kw)
    orc_kw = {
        {"byz_equivocate": "byz_equivocate", "byz_silent": "byz_silent",
         "weights": "weights"}[k]: np.asarray(v).tolist() for k, v in kw.items()
    }
    orc = OracleSim(p, seed, **orc_kw).run()
    assert res.n_events == orc.n_events
    assert res.clock == orc.clock
    assert res.stamp_ctr == orc.stamp_ctr
    assert res.n_msgs_sent == orc.n_msgs_sent
    assert res.n_msgs_dropped == orc.n_msgs_dropped
    assert res.n_queue_full == orc.n_queue_full
    for a in range(p.n_nodes):
        assert res.committed_chain(a) == orc.committed_chain(a), f"node {a}"
        assert res.current_round(a) == orc.stores[a].current_round
        assert res.hqc_round(a) == orc.stores[a].hqc_round
        assert res.hcr(a) == orc.stores[a].hcr
    return res, orc


def test_build():
    assert native.build()


@pytest.mark.parametrize("seed", [0, 1, 42])
def test_native_parity_3node(seed):
    p = SimParams(n_nodes=3, max_clock=1000)
    res, orc = assert_native_matches_oracle(p, seed)
    assert res.commit_count(0) > 0


def test_native_parity_drop_pareto():
    p = SimParams(n_nodes=4, max_clock=1500, delay_kind="pareto", drop_prob=0.05)
    assert_native_matches_oracle(p, 7)


def test_native_parity_weighted():
    p = SimParams(n_nodes=4, max_clock=800)
    assert_native_matches_oracle(p, 3, weights=np.asarray([1, 2, 3, 1], np.int32))


def test_native_parity_byzantine():
    p = SimParams(n_nodes=4, max_clock=1000)
    assert_native_matches_oracle(
        p, 13, byz_equivocate=np.asarray([0, 0, 0, 1], np.uint8))
    assert_native_matches_oracle(
        p, 17, byz_silent=np.asarray([0, 0, 0, 1], np.uint8))


def test_native_parity_hotstuff():
    p = SimParams(n_nodes=3, max_clock=800, commit_chain=2)
    res, _ = assert_native_matches_oracle(p, 11)
    assert res.commit_count(0) > 0


def test_native_speed_smoke():
    # The native engine exists to be fast on host: a long run finishes quickly.
    import time

    p = SimParams(n_nodes=3, max_clock=100000, target_commit_interval=1000)
    t0 = time.perf_counter()
    res = native.run(p, 5)
    dt = time.perf_counter() - t0
    assert res.halted
    assert res.n_events > 10000
    assert dt < 10.0
