"""The tier-1 micro fleet shapes — single source of truth.

tests/test_multichip.py builds its P_SER/P_LANE params from these dicts and
scripts/warm_cache.py warms executables for exactly them, so the warmed
compile-cache keys and the suite's compiled shapes can never drift apart
(only max_clock differs between the two consumers, and max_clock is
runtime data, outside the jit key).

The checkify sanitizer (audit/sanitize.py) compiles its OWN executable on
these same micro shapes: tests/test_audit.py's tier-1 smoke, the
warm_cache SANITIZE_SHAPES children, and scripts/graph_audit.py
--sanitize all use (FLEET_SER_KW / FLEET_LANE_KW, FLEET_B, FLEET_CHUNK),
so the debug build is warmed by the same contract.  The graph-audit
jaxpr traces never compile and key on nothing here (graph_lint.MICRO_*
are capacity twins of these dicts minus the observability knobs).

Pure data: no imports, safe to load from any process.
"""

FLEET_SER_KW = {"n_nodes": 3, "window": 8, "chain_k": 2, "commit_log": 8,
                "queue_cap": 16, "telemetry": True, "flight_cap": 16,
                "trace_cap": 32}
FLEET_LANE_KW = dict(FLEET_SER_KW, n_nodes=4, delay_kind="uniform")
FLEET_B = 5        # deliberately not divisible by the 2-shard mesh
FLEET_CHUNK = 32

# Watchdog-armed twins (tests/test_stream.py + the digest-enabled fleet
# warm shapes): same micro capacities with the in-graph consensus watchdog
# on.  The stall threshold is low enough that micro runs actually trip the
# liveness detector — watchdog_stall_events is a compile key (the
# threshold is baked into the traced compare), so it must match between
# the warmer and the suite exactly.
FLEET_WD_STALL = 48
FLEET_WD_SER_KW = dict(FLEET_SER_KW, watchdog=True,
                       watchdog_stall_events=FLEET_WD_STALL)
FLEET_WD_LANE_KW = dict(FLEET_LANE_KW, watchdog=True,
                        watchdog_stall_events=FLEET_WD_STALL)

# K-event macro-step twins (tests/test_checkpoint.py's macro-boundary
# round trip, tests/test_stream.py's K>1 digest pins): the serial micro
# shapes with SimParams.macro_k armed.  macro_k is a compile key (the
# inner-scan trip count is baked into the chunk graph), so the suite's
# K rung must match the warmed one exactly — single-sourced here.
FLEET_MACRO_K = 4
FLEET_MACRO_SER_KW = dict(FLEET_SER_KW, macro_k=FLEET_MACRO_K)
FLEET_MACRO_WD_SER_KW = dict(FLEET_WD_SER_KW, macro_k=FLEET_MACRO_K)

# Resident fleet service twins (serve/; tests/test_serve.py): the micro
# shapes with the per-slot scenario plane armed.  ``scenario`` is a
# compile key (the sc_* leaves change the argument signature and the
# commit rule becomes a traced select), but it is the LAST fork this
# family needs: one scenario executable serves every delay kind, drop
# rate, Byzantine schedule, and 2-vs-3 commit chain the suite mixes —
# which is exactly the AOT-store collapse the serve PR exists for.  The
# service's resident chunk runs sharded (SERVE_DP) at SERVE_CHUNK
# macro-steps per dispatch; test_serve and warm_cache both read these.
FLEET_SCENARIO_SER_KW = dict(FLEET_SER_KW, scenario=True)
FLEET_SCENARIO_LANE_KW = dict(FLEET_LANE_KW, scenario=True)
SERVE_SLOTS = 4
SERVE_CHUNK = 32
SERVE_DP = 2

# Adversary-engine twins (adversary/; tests/test_adversary.py): the
# 4-NODE micro shape (f=1 Byzantine windows stay inside the 3f+1
# tolerance, link matrices are 4x4) with the attack-schedule + network
# planes armed.  ``adversary`` and ``adv_windows`` are compile keys (the
# plane's shapes), so the suite's shapes and the warmed executables must
# match exactly — single-sourced here.  Both engines share the shape;
# the identity referees additionally run the SERIAL engine at the bare
# 4-node FLEET_LANE_KW (the off twin), so warm_cache warms that serial
# flavor too.  The serve referee arms watchdog (the per-request
# safety/liveness verdicts fleet_watch --serve shows) + scenario on the
# same base.
# Device-dispatch ring twins (SimParams.wrap="device";
# parallel/sharded.py): the micro fleet pair under the in-graph chunk
# retirement loop.  ``wrap`` and ``ring_k`` are compile keys (the ring
# depth is the [K, D] buffer shape and the AOT store's "ring" flavor),
# so the suite's ring tests, warm_cache's sharded ring children, and
# the perf sentinel's ring_dispatch rung must all use this K.
FLEET_RING_K = 4
FLEET_RING_SER_KW = dict(FLEET_SER_KW, wrap="device", ring_k=FLEET_RING_K)
FLEET_RING_LANE_KW = dict(FLEET_LANE_KW, wrap="device",
                          ring_k=FLEET_RING_K)

ADV_WINDOWS = 4
FLEET_ADV_KW = dict(FLEET_LANE_KW, adversary=True, adv_windows=ADV_WINDOWS)
# One dict, two engine names (so call sites read naturally): the engines
# MUST share the shape — diverging copies would silently compile two
# adversary families and defeat the single-sourcing this file exists for.
FLEET_ADV_SER_KW = FLEET_ADV_KW
FLEET_ADV_LANE_KW = FLEET_ADV_KW
FLEET_ADV_SERVE_KW = dict(FLEET_ADV_KW, scenario=True, watchdog=True,
                          watchdog_stall_events=FLEET_WD_STALL)
