"""Two-chain HotStuff-style commit rule (BASELINE config #5): the protocol
plug-in surface of the C-chain generalization (core/store.py
update_commit_chain / vote_committed_state with commit_chain=2)."""

import jax.numpy as jnp
import numpy as np

from librabft_simulator_tpu.core import config, store as store_ops
from librabft_simulator_tpu.core.types import SimParams, Store
from librabft_simulator_tpu.sim import simulator as S
from tests.test_simulator import assert_safety


def make_round(p, s, w, time):
    leader = int(config.leader_of_round(w, s.current_round))
    r, t = store_ops.hqc_ref(p, s)
    s, ok = store_ops.propose_block(p, s, w, leader, r, t, time, int(time))
    assert bool(ok)
    var = int(s.proposed_var)
    for a in range(int(config.quorum_threshold(w))):
        s, ok = store_ops.create_vote(p, s, w, a, s.current_round, var)
    s, created = store_ops.check_new_qc(p, s, w, leader)
    assert bool(created)
    return s


def test_two_chain_commits_one_round_earlier():
    # With C=2, two contiguous QCs commit; with C=3 it takes three.
    w = jnp.ones((2,), jnp.int32)
    p2 = SimParams(n_nodes=2, commit_chain=2)
    s = Store.initial(p2)
    s = make_round(p2, s, w, 10)
    assert int(s.hcr) == 0
    s = make_round(p2, s, w, 20)
    assert int(s.hcr) == 1  # rounds 1,2 contiguous -> round 1 commits
    p3 = SimParams(n_nodes=2, commit_chain=3)
    s3 = Store.initial(p3)
    s3 = make_round(p3, s3, w, 10)
    s3 = make_round(p3, s3, w, 20)
    assert int(s3.hcr) == 0  # 3-chain still needs one more


def test_two_chain_requires_contiguity():
    w = jnp.ones((2,), jnp.int32)
    p = SimParams(n_nodes=2, commit_chain=2)
    s = Store.initial(p)
    s = make_round(p, s, w, 10)
    assert int(s.hcr) == 0  # a lone QC commits nothing even under 2-chain
    # Force a TC gap: rounds no longer contiguous.
    for a in range(2):
        s, _ = store_ops.create_timeout(p, s, w, a, s.current_round)
    s = make_round(p, s, w, 30)
    assert int(s.hcr) == 0  # QC3 chains to QC1: non-contiguous, no commit
    s = make_round(p, s, w, 40)
    assert int(s.hcr) == 3  # QC3+QC4 contiguous -> round 3 commits


def test_end_to_end_hotstuff_16_nodes():
    # BASELINE config #5 shape (instances shrunk for CI).
    import jax

    p = SimParams(n_nodes=16, max_clock=1500, commit_chain=2, queue_cap=256)
    st = S.run_to_completion(p, S.init_batch(p, np.arange(4, dtype=np.uint32)),
                             batched=True)
    cc = np.asarray(st.ctx.commit_count)
    assert (cc.max(axis=1) > 0).mean() >= 0.75
    for b in range(4):
        assert_safety(jax.tree.map(lambda x: x[b], st), 16)


def test_two_chain_commits_faster_end_to_end():
    p2 = SimParams(n_nodes=3, max_clock=800, commit_chain=2)
    p3 = SimParams(n_nodes=3, max_clock=800, commit_chain=3)
    st2 = S.run_to_completion(p2, S.init_state(p2, 21))
    st3 = S.run_to_completion(p3, S.init_state(p3, 21))
    # Same trajectory of rounds; the 2-chain rule can only commit earlier.
    assert int(np.asarray(st2.ctx.commit_count).min()) >= \
        int(np.asarray(st3.ctx.commit_count).min())
