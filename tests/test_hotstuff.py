"""Two-chain HotStuff-style commit rule (BASELINE config #5): the protocol
plug-in surface of the C-chain generalization (core/store.py
update_commit_chain / vote_committed_state with commit_chain=2)."""

import jax.numpy as jnp
import numpy as np

from librabft_simulator_tpu.core import config, store as store_ops
from librabft_simulator_tpu.core.types import SimParams, Store
from librabft_simulator_tpu.sim import simulator as S
from tests.test_simulator import assert_safety


def make_round(p, s, w, time):
    leader = int(config.leader_of_round(w, s.current_round))
    r, t = store_ops.hqc_ref(p, s)
    s, ok = store_ops.propose_block(p, s, w, leader, r, t, time, int(time))
    assert bool(ok)
    var = int(s.proposed_var)
    for a in range(int(config.quorum_threshold(w))):
        s, ok = store_ops.create_vote(p, s, w, a, s.current_round, var)
    s, created = store_ops.check_new_qc(p, s, w, leader)
    assert bool(created)
    return s


def test_two_chain_commits_one_round_earlier():
    # With C=2, two contiguous QCs commit; with C=3 it takes three.
    w = jnp.ones((2,), jnp.int32)
    p2 = SimParams(n_nodes=2, commit_chain=2)
    s = Store.initial(p2)
    s = make_round(p2, s, w, 10)
    assert int(s.hcr) == 0
    s = make_round(p2, s, w, 20)
    assert int(s.hcr) == 1  # rounds 1,2 contiguous -> round 1 commits
    p3 = SimParams(n_nodes=2, commit_chain=3)
    s3 = Store.initial(p3)
    s3 = make_round(p3, s3, w, 10)
    s3 = make_round(p3, s3, w, 20)
    assert int(s3.hcr) == 0  # 3-chain still needs one more


def test_two_chain_requires_contiguity():
    w = jnp.ones((2,), jnp.int32)
    p = SimParams(n_nodes=2, commit_chain=2)
    s = Store.initial(p)
    s = make_round(p, s, w, 10)
    assert int(s.hcr) == 0  # a lone QC commits nothing even under 2-chain
    # Force a TC gap: rounds no longer contiguous.
    for a in range(2):
        s, _ = store_ops.create_timeout(p, s, w, a, s.current_round)
    s = make_round(p, s, w, 30)
    assert int(s.hcr) == 0  # QC3 chains to QC1: non-contiguous, no commit
    s = make_round(p, s, w, 40)
    assert int(s.hcr) == 3  # QC3+QC4 contiguous -> round 3 commits


def test_end_to_end_hotstuff_16_nodes():
    # BASELINE config #5 shape (instances shrunk for CI).
    import jax

    p = SimParams(n_nodes=16, max_clock=1500, commit_chain=2, queue_cap=256)
    st = S.run_to_completion(p, S.init_batch(p, np.arange(4, dtype=np.uint32)),
                             batched=True)
    cc = np.asarray(st.ctx.commit_count)
    assert (cc.max(axis=1) > 0).mean() >= 0.75
    for b in range(4):
        assert_safety(jax.tree.map(lambda x: x[b], st), 16)


def test_two_chain_commit_latency_on_fixed_chain():
    # The sound comparison runs both rules over the SAME contiguous QC chain
    # (commit timing feeds back into round durations in a full simulation, so
    # "2-chain commits more per wall-clock" is not a theorem seed-by-seed).
    # On a chain of contiguous QCs at rounds 1..K, the C-chain rule commits
    # round r once QCs r..r+C-1 exist: hcr = max(0, K - C + 1).
    w = jnp.ones((3,), jnp.int32)
    p2 = SimParams(n_nodes=3, commit_chain=2)
    p3 = SimParams(n_nodes=3, commit_chain=3)
    s2, s3 = Store.initial(p2), Store.initial(p3)
    for k in range(1, 7):
        s2 = make_round(p2, s2, w, 10 * k)
        s3 = make_round(p3, s3, w, 10 * k)
        assert int(s2.hcr) == max(0, k - 1)
        assert int(s3.hcr) == max(0, k - 2)
        assert int(s2.hcr) >= int(s3.hcr)  # 2-chain is never later


def test_two_chain_end_to_end_live_and_safe():
    # Both rules stay live and safe on the same seed in full simulation.
    for chain in (2, 3):
        p = SimParams(n_nodes=3, max_clock=800, commit_chain=chain)
        st = S.run_to_completion(p, S.init_state(p, 21))
        assert int(np.asarray(st.ctx.commit_count).min()) >= 3
        assert_safety(st, 3)
