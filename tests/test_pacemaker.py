"""Pacemaker: round entry, leader stability, duration growth, timeouts,
query-all (/root/reference/librabft-v2/src/pacemaker.rs)."""

import jax.numpy as jnp

from librabft_simulator_tpu.core import config, pacemaker as pm_ops, store as store_ops
from librabft_simulator_tpu.core.types import NEVER, Pacemaker, SimParams, Store


def mk(n=3, **kw):
    p = SimParams(n_nodes=n, **kw)
    return p, jnp.ones((n,), jnp.int32), Store.initial(p), Pacemaker.initial(), \
        jnp.asarray(p.duration_table())


def test_duration_table_growth():
    p = SimParams(delta=20, gamma=2.0)
    tbl = p.duration_table()
    assert tbl[0] == 0 and tbl[1] == 20 and tbl[2] == 80 and tbl[3] == 180
    assert all(tbl[i] <= tbl[i + 1] for i in range(len(tbl) - 1))


def test_enter_round_and_leader():
    p, w, s, pm, dur = mk()
    author = int(config.leader_of_round(w, 1))
    pm2, a = pm_ops.update_pacemaker(p, pm, s, w, author, 0, 0, 0, dur)
    assert int(pm2.active_round) == 1
    assert int(pm2.active_leader) == author
    assert bool(a.should_propose) and bool(a.should_broadcast)
    assert int(a.propose_prev_round) == 0
    # Re-entering the same round keeps leader/duration (stability).
    pm3, _ = pm_ops.update_pacemaker(p, pm2, s, w, author, 0, 0, 5, dur)
    assert int(pm3.active_leader) == author
    assert int(pm3.round_start) == int(pm2.round_start)


def test_non_leader_syncs_with_leader():
    p, w, s, pm, dur = mk()
    leader = int(config.leader_of_round(w, 1))
    other = (leader + 1) % p.n_nodes
    pm2, a = pm_ops.update_pacemaker(p, pm, s, w, other, 0, 0, 0, dur)
    assert not bool(a.should_propose)
    assert int(a.send_leader) == leader


def test_timeout_at_deadline():
    p, w, s, pm, dur = mk(delta=20, gamma=2.0)
    leader = int(config.leader_of_round(w, 1))
    other = (leader + 1) % p.n_nodes
    pm2, a = pm_ops.update_pacemaker(p, pm, s, w, other, 0, 0, 0, dur)
    deadline = int(pm2.round_start + pm2.round_duration)
    assert not bool(a.should_create_timeout)
    assert int(a.next_sched) == deadline
    # At the deadline: create a timeout and broadcast it.
    pm3, a2 = pm_ops.update_pacemaker(p, pm2, s, w, other, 0, 0, deadline, dur)
    assert bool(a2.should_create_timeout)
    assert int(a2.timeout_round) == 1
    assert bool(a2.should_broadcast)


def test_query_all_period_after_timeout():
    p, w, s, pm, dur = mk(delta=20, gamma=2.0)
    author = 0
    s2, ok = store_ops.create_timeout(p, s, w, author, 1)
    assert bool(ok)
    pm2, a = pm_ops.update_pacemaker(p, pm, s2, w, author, 0, 0, 1000, dur)
    # Holding a timeout past the deadline: no new timeout; periodic query-all.
    assert not bool(a.should_create_timeout)
    assert bool(a.should_query_all)  # latest_query_all=0 is long past
    period = (p.lam_fp * int(pm2.round_duration)) >> 16
    pm3, a2 = pm_ops.update_pacemaker(p, pm2, s2, w, author, 0, 1000, 1000, dur)
    assert not bool(a2.should_query_all)
    assert int(a2.next_sched) == 1000 + period


def test_round_advances_with_hqc_htc():
    p, w, s, pm, dur = mk()
    s = s.replace(hqc_round=jnp.int32(4), htc_round=jnp.int32(6))
    s = store_ops.update_current_round(s, 7)
    pm2, _ = pm_ops.update_pacemaker(p, pm, s, w, 0, 0, 0, 50, dur)
    assert int(pm2.active_round) == 7  # max(hqc, htc) + 1
