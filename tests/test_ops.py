"""Pallas event-select kernel == plain-XLA reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from librabft_simulator_tpu.ops.pallas_queue import (
    NEVER, select_events, select_events_reference,
)


def random_batch(rng, B, M, max_t=100):
    times = rng.integers(0, max_t, (B, M)).astype(np.int32)
    invalid = rng.random((B, M)) < 0.3
    times = np.where(invalid, NEVER, times)
    kinds = rng.integers(0, 4, (B, M)).astype(np.int32)
    # Unique stamps per row (the simulator guarantees this).
    stamps = np.argsort(rng.random((B, M))).astype(np.int32)
    return jnp.asarray(times), jnp.asarray(kinds), jnp.asarray(stamps)


@pytest.mark.parametrize("shape", [(4, 35), (8, 128), (3, 200)])
def test_select_matches_reference(shape):
    rng = np.random.default_rng(0)
    B, M = shape
    t, k, s = random_batch(rng, B, M)
    idx_p, tmin_p = select_events(t, k, s, interpret=True)
    idx_r, tmin_r = select_events_reference(t, k, s)
    np.testing.assert_array_equal(np.asarray(idx_p), np.asarray(idx_r))
    np.testing.assert_array_equal(np.asarray(tmin_p), np.asarray(tmin_r))


def test_ties_resolved_lexicographically():
    # Equal times: higher kind wins; equal kind: lower stamp; then lowest col.
    t = jnp.asarray([[5, 5, 5, 9]], jnp.int32)
    k = jnp.asarray([[1, 3, 3, 3]], jnp.int32)
    s = jnp.asarray([[0, 7, 2, 1]], jnp.int32)
    idx, tmin = select_events(t, k, s, interpret=True)
    assert int(idx[0]) == 2 and int(tmin[0]) == 5


def test_pallas_select_in_engine_bit_identical():
    """The kernel's real call site: a serial-engine run with
    SimParams.select_kernel='pallas_interpret' is bit-identical to the
    default XLA select (same config, same seeds, full final state)."""
    from librabft_simulator_tpu.core.types import SimParams
    from librabft_simulator_tpu.sim import simulator as S

    kw = dict(n_nodes=3, max_clock=300, window=8, chain_k=2, commit_log=8,
              queue_cap=16)
    p_x = SimParams(**kw)
    p_p = SimParams(select_kernel="pallas_interpret", **kw)
    seeds = np.arange(2, dtype=np.uint32)
    st_x = S.run_to_completion(p_x, S.init_batch(p_x, seeds), batched=True,
                               chunk=64)
    st_p = S.run_to_completion(p_p, S.init_batch(p_p, seeds), batched=True,
                               chunk=64)
    for a, b in zip(jax.tree.leaves(st_x), jax.tree.leaves(st_p)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(np.sum(np.asarray(st_x.n_events))) > 0
