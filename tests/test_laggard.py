"""Quantifies the documented K-tail/state-sync-jump divergence from the
reference's unbounded catch-up (record_store.rs:801-831, util.rs:8-10).

The reference responder ships *every* record the requester is missing, so a
laggard delivers every commit (gapless committed_history).  The rebuild's
fixed-shape K-tail responses mean a node more than ``chain_k`` rounds behind
on records commits via the newest tail and *bypasses* the middle depths; a
node beyond the window re-anchors entirely (``sync_jumps``) and adopts the
certified state.  Both loss modes are accounted in
``Context.skipped_commits`` with the invariant

    commit_count + skipped_commits == last_depth          (every node, always)

which these tests pin, along with quantified loss bounds.
"""

import jax
import numpy as np

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import simulator as S
from librabft_simulator_tpu.sim.byzantine import check_safety

g = jax.device_get


def run_fleet(p, n_inst):
    st = S.init_batch(p, np.arange(n_inst, dtype=np.uint32))
    st = S.run_to_completion(p, st, batched=True, max_chunks=400)
    assert bool(np.all(g(st.halted)))
    return st


def assert_accounting_invariant(st):
    cc = np.asarray(g(st.ctx.commit_count))
    sk = np.asarray(g(st.ctx.skipped_commits))
    ld = np.asarray(g(st.ctx.last_depth))
    np.testing.assert_array_equal(cc + sk, ld)
    return cc, sk, ld


def log_gap_total(st, b, a):
    """Observable skipped depths in the ring log of (instance, node)."""
    log_depth = np.asarray(g(st.ctx.log_depth))
    cc = int(np.asarray(g(st.ctx.commit_count))[b, a])
    H = log_depth.shape[-1]
    seq = [int(log_depth[b, a, i % H]) for i in range(max(cc - H, 0), cc)]
    if not seq:
        return 0, 0
    gaps = int(np.sum(np.diff(seq) - 1)) if len(seq) > 1 else 0
    return gaps, seq[0]


def test_invariant_and_bounded_loss_benign():
    """Default 3-node config: every skipped depth is accounted, the ring-log
    gaps match the counter exactly (no jumps, ring not wrapped), and the
    loss fraction stays small."""
    p = SimParams(n_nodes=3, max_clock=1500)
    st = run_fleet(p, 12)
    cc, sk, ld = assert_accounting_invariant(st)
    assert int(np.sum(g(st.ctx.sync_jumps))) == 0
    B, N = cc.shape
    for b in range(B):
        for a in range(N):
            if cc[b, a] <= st.ctx.log_depth.shape[-1]:  # ring not wrapped
                gaps, first = log_gap_total(st, b, a)
                assert gaps + (first - 1 if cc[b, a] else 0) == sk[b, a], \
                    (b, a, gaps, first, sk[b, a])
    # Loss is real but small in a benign run (K-tail catch-up bypasses).
    assert sk.sum() / max(ld.sum(), 1) < 0.2
    assert bool(np.all(check_safety(st)))


def test_invariant_under_drop_and_jumps():
    """BASELINE config #3's shape scaled down (small window + drop): the
    invariant holds through state-sync jumps and heavy catch-up, and jumped
    nodes still track the fleet's committed frontier."""
    p = SimParams(n_nodes=4, max_clock=6000, window=8, chain_k=2,
                  commit_log=16, drop_prob=0.2)
    st = run_fleet(p, 24)
    cc, sk, ld = assert_accounting_invariant(st)
    assert bool(np.all(check_safety(st)))
    # Loss concentrates where catch-up happened; fleet-wide it stays a
    # minority share of total progress.
    assert sk.sum() > 0
    assert sk.sum() / max(ld.sum(), 1) < 0.5
    # Every node converges near the instance frontier (no permanent stall).
    lag = ld.max(axis=1, keepdims=True) - ld
    assert float(np.median(lag)) <= 4 * p.window


def test_skip_fraction_reported_per_instance():
    """The per-node counters expose reference-vs-rebuild delivery loss as a
    measurable quantity (what a user of the reference's full catch-up gives
    up by switching): report shape sanity + determinism."""
    p = SimParams(n_nodes=3, max_clock=1500)
    st1 = run_fleet(p, 6)
    st2 = run_fleet(p, 6)
    np.testing.assert_array_equal(np.asarray(g(st1.ctx.skipped_commits)),
                                  np.asarray(g(st2.ctx.skipped_commits)))
