import os

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from librabft_simulator_tpu.utils.rlimit import raise_stack_limit  # noqa: E402

raise_stack_limit()

# Virtual 8-device CPU mesh for tests; must happen before any jax computation.
# (The axon TPU plugin ignores the JAX_PLATFORMS env var, so we also set the
# config flag explicitly.)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# Every compiled XLA executable adds hundreds of memory mappings and JAX
# keeps them all alive; a full-suite pytest process crosses the default
# vm.max_map_count (65530) after ~75 tests, after which mmap failures
# surface as SIGSEGV inside whatever touches a large executable next
# (compile, serialize, or cache-read — observed as wandering segfaults
# always at the same test count).  Raise the limit when we can (root
# container); otherwise trim JAX's live-executable count per module below.
_MAPS_PRIOR = None
try:
    with open("/proc/sys/vm/max_map_count") as _f:
        _map_count = int(_f.read())
    if _map_count < 1048576:
        with open("/proc/sys/vm/max_map_count", "w") as _f:
            _f.write("1048576")
        _MAPS_PRIOR = _map_count  # restored in pytest_sessionfinish
    _MAPS_RAISED = True
except OSError:
    _MAPS_RAISED = False


def _other_jax_job_running():
    """True if another live process that depends on the raised map count is
    visible (pytest, bench, warm_cache, probes, any librabft tooling) —
    restoring the sysctl under it would reinstate the mmap segfaults."""
    me = os.getpid()
    needles = (b"pytest", b"bench.py", b"warm_cache", b"fleet_watch",
               b"component_profile", b"librabft")
    try:
        for pid in os.listdir("/proc"):
            if not pid.isdigit() or int(pid) == me:
                continue
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmd = f.read()
            except OSError:
                continue
            if any(n in cmd for n in needles):
                return True
    except OSError:
        pass
    return False


def pytest_sessionfinish(session, exitstatus):
    """Undo the container-global sysctl raise once the suite is done
    (skipped while a concurrent pytest still depends on the raised limit)."""
    if _MAPS_PRIOR is not None and not _other_jax_job_running():
        try:
            with open("/proc/sys/vm/max_map_count", "w") as _f:
                _f.write(str(_MAPS_PRIOR))
        except OSError:
            pass

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: repeat test runs skip XLA recompiles.
# Single-sourced (utils/cache.py) so the suite, warm_cache.py, bench.py
# and the CLI all share ONE cache (LIBRABFT_COMPILE_CACHE moves it).
from librabft_simulator_tpu.utils.cache import setup_compile_cache  # noqa: E402

setup_compile_cache()


def pytest_runtest_teardown(item, nextitem):
    """Fallback when vm.max_map_count could not be raised: drop live
    executables between modules so mappings don't accumulate past the limit
    (the persistent cache makes later reloads cheap)."""
    if _MAPS_RAISED:
        return
    if nextitem is None or item.fspath != nextitem.fspath:
        jax.clear_caches()
