import os

# Virtual 8-device CPU mesh for tests; must happen before any jax computation.
# (The axon TPU plugin ignores the JAX_PLATFORMS env var, so we also set the
# config flag explicitly.)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: repeat test runs skip XLA recompiles.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
