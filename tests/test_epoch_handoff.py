"""Cross-epoch catch-up (SimParams.epoch_handoff).

The reference keeps previous epochs' record stores and serves their records
to laggards (/root/reference/librabft-v2/src/node.rs ``record_store_at``,
``data_sync.rs:82-92``).  The windowed rebuild drops old stores at an epoch
switch; without a handoff, a node reaching the boundary first can DEADLOCK
the network: the new epoch can't reach quorum (peers are still in the old
epoch and reject new-epoch records), and the old epoch can't finish (the
switched node's store no longer holds the boundary chain; a state-sync jump
is impossible while the new epoch has no QC to anchor on).

The handoff: at the switch, capture the old store's full response pack (built
post-update, pre-switch — the commit-enabling QC is often minted in the same
update); serve it to any requester still in that epoch.  Laggards then commit
through the boundary in order and switch on their own — no jump, no skipped
commits for one-epoch laggards.
"""

import numpy as np
import pytest

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.oracle.sim import OracleSim
from librabft_simulator_tpu.sim import parallel_sim as P
from librabft_simulator_tpu.sim.byzantine import check_safety
from librabft_simulator_tpu.sim.simulator import dedupe_buffers

from test_native import assert_native_matches_oracle
from test_parity import assert_parity

import jax


def boundary_params(**kw):
    kw.setdefault("n_nodes", 3)
    kw.setdefault("commands_per_epoch", 6)
    kw.setdefault("max_clock", 12000)
    kw.setdefault("drop_prob", 0.15)
    return SimParams(**kw)


def test_handoff_cures_boundary_deadlock():
    """Seed 3 deadlocks at the first boundary without the handoff (one node
    switches, the rest can never follow); with it the fleet keeps committing
    across epochs."""
    on = OracleSim(boundary_params(max_clock=60000), 3).run(max_events=2000000)
    assert min(s.epoch_id for s in on.stores) >= 1
    assert min(c.commit_count for c in on.ctxs) >= 10
    assert on.n_handoff_served > 0

    off = OracleSim(boundary_params(max_clock=60000, epoch_handoff=False),
                    3).run(max_events=2000000)
    assert max(c.commit_count for c in off.ctxs) <= 6  # stuck at the boundary


def test_handoff_laggards_keep_full_history():
    """One-epoch laggards served by the handoff commit the boundary depths in
    order: no state-sync jumps, (almost) no skipped commits."""
    o = OracleSim(boundary_params(), 3).run(max_events=500000)
    assert min(s.epoch_id for s in o.stores) >= 1
    assert sum(c.sync_jumps for c in o.ctxs) == 0
    assert sum(c.skipped_commits for c in o.ctxs) == 0


def test_handoff_parity_jax_vs_oracle():
    st, orc = assert_parity(boundary_params(), 3)
    assert orc.n_handoff_served > 0
    assert min(int(x) for x in st.store.epoch_id) >= 1


def test_handoff_parity_native_vs_oracle():
    res, orc = assert_native_matches_oracle(boundary_params(), 3)
    assert orc.n_handoff_served > 0


def _partitioned_oracle(E, t_end=450, mc=2000, seed=1):
    """Run a 4-node oracle fleet with node 3 network-partitioned (both
    directions eaten) until ``t_end``, then healed.  commands_per_epoch=3
    makes the fleet cross several epoch boundaries during the partition;
    chain_k=8 covers an epoch's rounds so a served old-epoch pack connects
    to the laggard's chain without a jump."""
    p = SimParams(n_nodes=4, commands_per_epoch=3, max_clock=mc,
                  chain_k=8, handoff_epochs=E)
    o = OracleSim(p, seed)
    victim = 3
    for _ in range(300000):
        if o.halted:
            break
        o.step()
        if o.clock < t_end:
            for m in o.queue:
                if m.valid and (m.receiver == victim or m.sender == victim):
                    m.valid = False
    return o


def test_multi_epoch_laggard_recovers_via_ring():
    """A node partitioned across MULTIPLE epoch boundaries recovers through
    the [N, E, F] handoff ring with full history: it climbs the held packs
    epoch by epoch — no state-sync jump, no skipped commits (VERDICT r4 #6;
    reference keeps all epochs' stores: node.rs record_store_at)."""
    o = _partitioned_oracle(E=4)
    assert min(s.epoch_id for s in o.stores) >= 2
    assert len({s.epoch_id for s in o.stores}) == 1  # caught up fully
    assert [c.sync_jumps for c in o.ctxs] == [0, 0, 0, 0]
    assert [c.skipped_commits for c in o.ctxs] == [0, 0, 0, 0]
    assert len({c.commit_count for c in o.ctxs}) == 1  # full history
    assert o.n_handoff_served > 0


def test_multi_epoch_laggard_needs_ring_depth():
    """Same scenario with a depth-1 ring: by heal time the old-epoch packs
    are overwritten, so the multi-epoch laggard cannot be served its next
    epoch and stalls (or must jump) — the capability the ring adds."""
    o = _partitioned_oracle(E=1, mc=1500)
    victim = o.ctxs[3]
    fleet_epoch = max(s.epoch_id for s in o.stores)
    assert fleet_epoch >= 2
    stuck = o.stores[3].epoch_id < fleet_epoch
    jumped_or_lossy = victim.sync_jumps > 0 or victim.skipped_commits > 0
    assert stuck or jumped_or_lossy


@pytest.mark.slow  # up to 400 x 256-step lane-engine windows at
# max_clock=30000: the test the 870 s tier-1 budget was dying inside at
# the seed (39 dots); the serial/oracle handoff tests above keep the
# capability covered in tier-1.
def test_parallel_engine_crosses_epochs():
    """The windowed parallel engine with the handoff also advances past the
    boundary and stays safe."""
    p = boundary_params(max_clock=30000, delay_kind="uniform", drop_prob=0.1,
                        window=16, chain_k=4)
    seeds = np.arange(8, dtype=np.uint32)
    st = P.init_batch(p, seeds)
    st = dedupe_buffers(st)
    run = P.make_run_fn(p, 256)
    # Sync-storm instances advance ~1 time unit per window, so the window
    # budget must comfortably exceed max_clock/256 chunks (observed: ~255).
    for _ in range(400):
        st = run(st)
        if bool(np.all(jax.device_get(st.halted))):
            break
    assert bool(np.all(jax.device_get(st.halted)))
    ep = np.asarray(jax.device_get(st.store.epoch_id))
    assert (ep.max(axis=1) >= 1).mean() > 0.5  # most instances cross
    assert bool(np.all(check_safety(st)))
