"""telemetry/: the in-graph metrics plane + flight recorder.

Three contracts (the acceptance referees of the observability PR):

(a) telemetry OFF is free and inert: the state's telemetry leaves are
    zero-width and a telemetry-ON run is bit-identical to the OFF run on
    every common leaf — observing the fleet must never perturb it (the
    engine-identity pattern from tests/test_packing.py; the kernel-census
    CI gate separately pins that the OFF *graph* is unchanged).
(b) counters match the pure-Python oracle's event tallies exactly on a
    seeded run — including the flight-recorder tail row-for-row.
(c) histograms match numpy-bucketed raw latencies, and the reported
    quantile bounds bracket numpy's inverted-CDF quantiles.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.oracle.sim import OracleSim
from librabft_simulator_tpu.sim import parallel_sim as P
from librabft_simulator_tpu.sim import simulator as S
from librabft_simulator_tpu.telemetry import plane as tplane
from librabft_simulator_tpu.telemetry import report as treport
from librabft_simulator_tpu.utils import quantile as Q

# trace_cap matches across the pair: the round-switch trace ring is a
# pre-existing feature whose shape must not confound the telemetry
# on-vs-off identity comparison.
P_OFF = SimParams(n_nodes=3, max_clock=400, trace_cap=256)
P_ON = dataclasses.replace(P_OFF, telemetry=True, flight_cap=64)


def strip_tel(st):
    """Project out the telemetry leaves so ON and OFF states compare."""
    return st.replace(metrics=jnp.zeros((0,), jnp.int32),
                      flight=jnp.zeros((0, tplane.FR_COLS), jnp.int32))


def assert_trees_equal(a, b):
    flat_a = jax.tree_util.tree_flatten_with_path(a)[0]
    flat_b = jax.tree_util.tree_flatten_with_path(b)[0]
    assert len(flat_a) == len(flat_b)
    for (pt, la), (_, lb) in zip(flat_a, flat_b):
        path = "/".join(str(q) for q in pt)
        assert la.dtype == lb.dtype, path
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb), path)


def test_registry_layout():
    slots, width = tplane.registry(P_ON.structural())
    assert width == tplane.width(P_ON) > 0
    # Offsets tile the plane exactly, in registration order.
    off = 0
    for name, (o, size, agg) in slots.items():
        assert o == off, name
        assert size >= 1 and agg in (tplane.SUM, tplane.MAX)
        off += size
    assert off == width
    # Per-node region scales with the fleet width.
    assert tplane.slot(P_ON, "node_depth_hwm")[1] == P_ON.n_nodes
    # Off params have a zero-width plane and ring.
    assert tplane.width(P_OFF) == 0
    assert tplane.init_flight(P_OFF).shape == (0, tplane.FR_COLS)


def test_telemetry_off_is_inert_serial():
    """(a) for the serial engine: OFF state carries zero-width telemetry
    leaves; ON run is bit-identical to the OFF run on every common leaf."""
    a = S.run_to_completion(P_OFF, S.init_state(P_OFF, 0))
    b = S.run_to_completion(P_ON, S.init_state(P_ON, 0))
    assert a.metrics.shape == (0,)
    assert a.flight.shape == (0, tplane.FR_COLS)
    assert b.metrics.shape == (tplane.width(P_ON),)
    assert_trees_equal(strip_tel(a), strip_tel(b))
    assert min(int(c) for c in a.ctx.commit_count) > 0  # non-trivial run


def test_counters_and_flight_match_oracle():
    """(b): every plane slot the oracle mirrors (event-kind counts, queue
    high-water marks, commit-latency misses) matches its tallies exactly,
    the loss/jump slots match the state counters they shadow, and the
    flight-recorder tail equals the oracle's event log row-for-row."""
    seed = 5
    st = S.run_to_completion(P_ON, S.init_state(P_ON, seed))
    orc = OracleSim(P_ON, seed).run()
    md = treport.metrics_dict(P_ON, st)
    ev = [md["ev_notify"], md["ev_request"], md["ev_response"],
          md["ev_timer"]]
    assert ev == orc.tel["ev_kind"]
    assert sum(ev) == orc.n_events == int(st.n_events)
    assert md["fr_count"] == orc.n_events
    assert md["drops"] == orc.n_msgs_dropped
    assert md["overflow"] == orc.n_queue_full
    assert md["sync_jumps"] == sum(c.sync_jumps for c in orc.ctxs)
    assert md["queue_hwm"] == orc.tel["queue_hwm"] > 0
    assert md["node_depth_hwm"] == orc.tel["node_depth_hwm"]
    assert md["commit_lat_miss"] == orc.tel["commit_lat_miss"]
    # Flight tail: last K oracle events, byte-for-byte, oldest first.
    tail = treport.decode_flight(P_ON, st)
    assert len(tail) == min(P_ON.flight_cap, orc.n_events)
    assert tail == orc.tel["flight"][-len(tail):]


def test_histograms_match_numpy_quantiles():
    """(c): device histograms equal numpy-bucketed oracle latencies, and the
    reported p50/p99 bucket bounds bracket numpy's inverted-CDF quantiles
    of the raw samples."""
    seed = 11
    st = S.run_to_completion(P_ON, S.init_state(P_ON, seed))
    orc = OracleSim(P_ON, seed).run()
    md = treport.metrics_dict(P_ON, st)
    for hist_name, lats in [("round_lat_hist", orc.tel["round_lats"]),
                            ("commit_lat_hist", orc.tel["commit_lats"])]:
        assert len(lats) > 0, hist_name
        expect = np.bincount(Q.bucket_np(lats), minlength=Q.HIST_BUCKETS)
        assert md[hist_name] == [int(v) for v in expect], hist_name
        for q in (0.50, 0.99):
            lo, hi = treport.histogram_quantile(md[hist_name], q)
            v = float(np.percentile(lats, 100 * q, method="inverted_cdf"))
            assert lo <= v < hi or (hi == 2**31 - 1 and v >= lo), \
                (hist_name, q, lo, v, hi)


def test_histogram_quantile_edge_cases():
    assert treport.histogram_quantile(np.zeros(Q.HIST_BUCKETS), 0.5) == (-1, -1)
    counts = np.zeros(Q.HIST_BUCKETS, np.int64)
    counts[0] = 3
    assert treport.histogram_quantile(counts, 0.5) == (0, 1)
    counts[-1] = 1  # open-ended last bucket
    assert treport.histogram_quantile(counts, 0.99)[1] == 2**31 - 1


def test_run_report_merges_data_writer(tmp_path):
    st = S.run_to_completion(P_ON, S.init_state(P_ON, 3))
    rep = treport.run_report(P_ON, st, data_dir=str(tmp_path))
    assert (tmp_path / "round_switches.txt").exists()
    assert rep["summary"]["n_events"] == int(st.n_events)
    assert rep["telemetry"]["events"]["timer"] > 0
    assert len(rep["flight"]) > 0
    assert rep["histogram_edges"] == [int(e) for e in Q.histogram_edges()]
    ev = rep["telemetry"]["events"]
    assert sum(ev.values()) == rep["summary"]["n_events"]


@pytest.mark.slow  # two fresh parallel-engine compiles (~minutes on CPU);
# tier-1 telemetry coverage rides the serial tests above — the plane update
# code is shared, only the lane-wise accumulation differs.
def test_telemetry_off_is_inert_parallel():
    p_off = SimParams(n_nodes=4, max_clock=300, epoch_handoff=False)
    p_on = dataclasses.replace(p_off, telemetry=True, flight_cap=32)
    a = P.run_to_completion(p_off, P.init_state(p_off, 1), chunk=16)
    b = P.run_to_completion(p_on, P.init_state(p_on, 1), chunk=16)
    assert_trees_equal(strip_tel(a), strip_tel(b))
    md = treport.metrics_dict(p_on, b)
    ev_sum = (md["ev_notify"] + md["ev_request"] + md["ev_response"]
              + md["ev_timer"])
    assert ev_sum == int(b.n_events) == md["fr_count"]
    assert md["windows"] > 0
    assert md["drops"] == int(b.n_msgs_dropped)
    assert md["overflow"] == int(b.n_inbox_full)
    tail = treport.decode_flight(p_on, b)
    assert len(tail) == min(p_on.flight_cap, ev_sum)
    # Lane rows land (window, iteration, lane)-ordered; per actor the event
    # times are still monotone.
    for actor in range(p_on.n_nodes):
        times = [r["time"] for r in tail if r["actor"] == actor]
        assert times == sorted(times)
