"""utils/aot.py: the AOT-serialized executable store.

Referees for the compile-tax-PR acceptance criteria:

(a) export/load round trip is BIT-IDENTICAL to the jit path for both
    engines at the warmed fleet_shapes micro shapes, and for the 2-shard
    sharded digest contract (state leaves AND the [D] digest vector);
    the compile ledger says ``aot-hit`` with true load seconds on the
    loaded leg;
(b) corrupted artifacts and foreign-toolchain/store-version entries are
    REFUSED with a clean fallback to the jit path (bit-identical values,
    ``aot-stale`` on the ledger, never a crash);
(c) ``LIBRABFT_AOT=0`` is provably inert: the traced step's graph-audit
    eqn-signature hash is unchanged (hence identical HLO, hence the
    census budgets exactly unchanged — the census lowers that graph),
    and the wrapper dispatches the exact jit callable without touching
    the store;
(d) store keying: flavor meta (num_steps, engine) and shapes all
    separate entries; the key is stable for identical inputs;
(e) the persistent-cache toolchain stamp (utils/cache.py): a foreign
    stamp flips :func:`stale_toolchain` and the ledger classifies the
    session's misses ``stale-toolchain`` instead of bare
    ``persistent-miss``.

The module-scoped ``store`` fixture exports each flavor ONCE (a full
fresh compile per flavor — the export contract bypasses the persistent
cache, by design); every store-backed test reuses those artifacts.
Those tests are marked ``slow``: the fixture's ~4 fresh compiles would
eat 3-4 minutes of the 870 s tier-1 budget — the exact tax this PR
removes — so ci_tier1.sh runs this module in full as its own explicit
referee leg instead (after the suite, with its own time cap).  The
keying/stamp/verdict tests stay in tier-1 (no compiles).
"""

import json
import os
import pickle
import shutil

import jax
import numpy as np
import pytest

from fleet_shapes import FLEET_B, FLEET_CHUNK, FLEET_LANE_KW, FLEET_SER_KW
from librabft_simulator_tpu.audit import sanitize
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.parallel import mesh as mesh_ops
from librabft_simulator_tpu.parallel import sharded
from librabft_simulator_tpu.sim import parallel_sim, simulator
from librabft_simulator_tpu.telemetry import ledger as tledger
from librabft_simulator_tpu.utils import aot
from librabft_simulator_tpu.utils import cache as ucache

P_SER = SimParams(max_clock=120, **FLEET_SER_KW)
P_LANE = SimParams(max_clock=120, **FLEET_LANE_KW)
SEEDS = np.arange(FLEET_B, dtype=np.uint32)

#: One chunk is enough for a bit-exact contract; reusing the fleet chunk
#: keeps the compiled executables the warmed suite shapes.
CHUNK = FLEET_CHUNK


def _leaves_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(jax.device_get(x)),
                       np.asarray(jax.device_get(y)))
        for x, y in zip(la, lb))


def _env(monkeypatch, store_dir, on="1", write="0"):
    monkeypatch.setenv(aot.DIR_ENV, str(store_dir))
    monkeypatch.setenv(aot.AOT_ENV, on)
    monkeypatch.setenv(aot.WRITE_ENV, write)
    aot.reset_cache()


def _serial_run(p):
    st = simulator.dedupe_buffers(simulator.init_batch(p, SEEDS))
    return simulator.make_run_fn(p, CHUNK)(st)


def _lane_run(p):
    st = simulator.dedupe_buffers(parallel_sim.init_batch(p, SEEDS))
    return parallel_sim.make_run_fn(p, CHUNK)(st)


def _sanitize_run(p):
    st = simulator.dedupe_buffers(simulator.init_batch(p, SEEDS))
    return sanitize.run_checked(p, st, CHUNK, batched=True,
                                engine=simulator)


def _sharded_run(p):
    mesh = mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])
    st = simulator.init_batch(p, sharded.fleet_seeds(0, FLEET_B))
    st, n_valid = sharded.pad_to_multiple(p, st, mesh.size)
    st = mesh_ops.shard_batch(mesh, simulator.dedupe_buffers(st))
    run = sharded.make_sharded_run_fn(p, mesh, CHUNK)
    st, dg = run(st)
    return sharded.unpad(st, n_valid), np.asarray(jax.device_get(dg))


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    """Export serial + lane + sharded chunk executables into one store
    (each a full fresh compile — paid once for the whole module) and
    record the jit-path reference outputs for bit-identity checks."""
    d = tmp_path_factory.mktemp("aot_store")
    saved = {k: os.environ.get(k)
             for k in (aot.DIR_ENV, aot.AOT_ENV, aot.WRITE_ENV)}
    os.environ[aot.DIR_ENV] = str(d)
    os.environ[aot.WRITE_ENV] = "1"
    os.environ[aot.AOT_ENV] = "1"
    aot.reset_cache()
    try:
        ref = {
            "serial": _serial_run(P_SER),
            "lane": _lane_run(P_LANE),
            "sharded": _sharded_run(P_SER),
            "sanitize": _sanitize_run(P_SER),
        }
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        aot.reset_cache()
    man = aot.read_manifest(str(d))
    assert man is not None and len(man["entries"]) >= 4, \
        "store fixture failed to export (see utils/aot._export)"
    return {"dir": d, "ref": ref}


def _assert_hit_matches(monkeypatch, store, which, runner, p):
    """Load leg: point a fresh process-state at the store, run, compare
    bit-for-bit and check the aot-hit verdict."""
    _env(monkeypatch, store["dir"])
    lg = tledger.reset()
    out = runner(p)
    assert _leaves_equal(out, store["ref"][which])
    hits = [e for e in lg.compiles if e["cache"] == "aot-hit"]
    assert hits, f"no aot-hit recorded for {which}: " \
                 f"{[e['cache'] for e in lg.compiles]}"
    assert hits[0]["aot_load_s"] > 0
    assert hits[0]["compile_s"] == 0.0  # no backend compile happened


@pytest.mark.slow  # store fixture: ~4 fresh export compiles
def test_serial_roundtrip_bit_identical(store, monkeypatch):
    """(a) serial engine: loaded executable == jit executable, leaf for
    leaf, and the ledger records the load as aot-hit."""
    _assert_hit_matches(monkeypatch, store, "serial", _serial_run, P_SER)


@pytest.mark.slow  # store fixture: ~4 fresh export compiles
def test_lane_roundtrip_bit_identical(store, monkeypatch):
    """(a) lane engine round trip."""
    _assert_hit_matches(monkeypatch, store, "lane", _lane_run, P_LANE)


@pytest.mark.slow  # store fixture: ~4 fresh export compiles
def test_sharded_roundtrip_digest_contract(store, monkeypatch):
    """(a) the 2-shard digest contract: run the sharded chunk from the
    store and compare the unpadded state AND the [D] digest vector."""
    _env(monkeypatch, store["dir"])
    lg = tledger.reset()
    st, dg = _sharded_run(P_SER)
    ref_st, ref_dg = store["ref"]["sharded"]
    assert _leaves_equal(st, ref_st)
    assert np.array_equal(dg, ref_dg)
    assert any(e["cache"] == "aot-hit" for e in lg.compiles)


@pytest.mark.slow  # store fixture: ~4 fresh export compiles
def test_corrupt_artifact_clean_jit_fallback(store, monkeypatch, tmp_path):
    """(b) a corrupted .bin is refused (aot-stale on the ledger) and the
    run falls back to the jit path with bit-identical output — no crash,
    no partial state."""
    d = tmp_path / "corrupt_store"
    shutil.copytree(store["dir"], d)
    for name in os.listdir(d):
        if name.endswith(".bin"):
            with open(d / name, "wb") as f:
                f.write(b"not an executable")
    _env(monkeypatch, d)
    lg = tledger.reset()
    out = _serial_run(P_SER)
    assert _leaves_equal(out, store["ref"]["serial"])
    assert any(e["cache"] == "aot-stale" for e in lg.compiles)
    assert not any(e["cache"] == "aot-hit" for e in lg.compiles)


@pytest.mark.slow  # store fixture: ~4 fresh export compiles
def test_foreign_toolchain_refused(store, monkeypatch, tmp_path):
    """(b) an entry stamped by another jaxlib is stale, not loadable: the
    sidecar toolchain gates the load, the ledger says aot-stale and
    names the fallback verdict, and values match the jit path."""
    d = tmp_path / "foreign_store"
    shutil.copytree(store["dir"], d)
    for name in os.listdir(d):
        if name.endswith(".json") and name != "manifest.json":
            path = d / name
            with open(path) as f:
                side = json.load(f)
            side["toolchain"] = {"jax": "0.0.0", "jaxlib": "0.0.0"}
            with open(path, "w") as f:
                json.dump(side, f)
    _env(monkeypatch, d)
    lg = tledger.reset()
    out = _serial_run(P_SER)
    assert _leaves_equal(out, store["ref"]["serial"])
    stale = [e for e in lg.compiles if e["cache"] == "aot-stale"]
    assert stale and "fallback" in stale[0]


@pytest.mark.slow  # store fixture: ~4 fresh export compiles
def test_foreign_store_version_refused(store, monkeypatch, tmp_path):
    """(b) a future AOT_VERSION is refused the same way (schema skew must
    never deserialize a payload it doesn't understand)."""
    d = tmp_path / "ver_store"
    shutil.copytree(store["dir"], d)
    for name in os.listdir(d):
        if name.endswith(".json") and name != "manifest.json":
            path = d / name
            with open(path) as f:
                side = json.load(f)
            side["aot_version"] = aot.AOT_VERSION + 1
            with open(path, "w") as f:
                json.dump(side, f)
    _env(monkeypatch, d)
    lg = tledger.reset()
    out = _serial_run(P_SER)
    assert _leaves_equal(out, store["ref"]["serial"])
    assert any(e["cache"] == "aot-stale" for e in lg.compiles)


@pytest.mark.slow  # store fixture: ~4 fresh export compiles
def test_aot_off_is_inert(store, monkeypatch):
    """(c) LIBRABFT_AOT=0: the wrapper dispatches the exact jit callable
    and never touches the store (a poisoned loader proves it), and the
    traced step graph is eqn-identical either way — the graph-audit
    signature hash, hence the lowered HLO the kernel census counts,
    cannot move (the store is host-side dispatch plumbing only)."""
    from librabft_simulator_tpu.audit import graph_lint

    def poisoned(key):
        raise AssertionError("store consulted with LIBRABFT_AOT=0")

    sigs = {}
    for on in ("1", "0"):
        _env(monkeypatch, store["dir"], on=on)
        if on == "0":
            monkeypatch.setattr(aot, "load", poisoned)
        cj, _, _ = graph_lint.trace_step(
            "serial", SimParams(**graph_lint.MICRO_SER_KW))
        sigs[on] = graph_lint.signature_hash(cj.jaxpr)
    assert sigs["1"] == sigs["0"]
    # And the dispatch path: off means the wrapped callable IS the jit
    # path (bit-identical output with the loader poisoned).
    out = _serial_run(P_SER)
    assert _leaves_equal(out, store["ref"]["serial"])


@pytest.mark.slow  # store fixture: ~4 fresh export compiles
def test_sanitize_retrace_out_roundtrip(store, monkeypatch):
    """(a) the checkify sanitizer build: its error pytree's out-tree
    holds live tracebacks (unpicklable), so its entry is stored
    ``trees: "retrace-out"`` and the loader rebuilds the out-tree from an
    abstract trace — the loaded executable still runs the checked chunk
    bit-identically and throws through err like the compiled one."""
    _env(monkeypatch, store["dir"])
    man = aot.read_manifest(str(store["dir"]))
    entries = [e for e in man["entries"] if e.get("flavor") == "sanitize"]
    assert entries, [e.get("flavor") for e in man["entries"]]
    e = entries[0]
    assert e["trees"] == "retrace-out"
    run = sanitize.make_checked_run_fn(P_SER, CHUNK, batched=True,
                                       engine=simulator)
    jit_fn = run.__wrapped__
    st = simulator.dedupe_buffers(simulator.init_batch(P_SER, SEEDS))
    loaded = aot._deserialize(
        os.path.join(store["dir"], e["file"]), e,
        out_tree_thunk=lambda: aot._out_tree(jit_fn, (st,)))
    err, out = loaded(st)
    err.throw()
    assert _leaves_equal(out, store["ref"]["sanitize"])


@pytest.mark.slow  # store fixture: ~4 fresh export compiles
def test_wrapped_runner_traceable_under_outer_jit(store, monkeypatch):
    """An aot-wrapped runner called with TRACERS (an outer transform
    tracing through it — the sharded wrap='jit' A/B form does exactly
    this) must route to the jit path, which inlines; a loaded executable
    cannot consume tracers.  Values stay bit-identical to the direct
    call."""
    _env(monkeypatch, store["dir"])
    st = simulator.dedupe_buffers(simulator.init_batch(P_SER, SEEDS))
    run = simulator.make_run_fn(P_SER, CHUNK)
    out = jax.jit(lambda s: run(s))(st)
    assert _leaves_equal(out, store["ref"]["serial"])


def test_store_key_separates_flavors():
    """(d) the key separates num_steps / engine / digest flavor and the
    argument-shape signature; identical inputs key identically."""
    sig_a = aot.shape_signature((np.zeros((4, 8), np.int32),))
    sig_b = aot.shape_signature((np.zeros((5, 8), np.int32),))
    assert sig_a != sig_b
    assert sig_a == aot.shape_signature((np.zeros((4, 8), np.int32),))
    k = aot.store_key("p1", sig_a, engine="serial", num_steps=32)
    assert k == aot.store_key("p1", sig_a, engine="serial", num_steps=32)
    assert k != aot.store_key("p1", sig_a, engine="serial", num_steps=64)
    assert k != aot.store_key("p1", sig_a, engine="lane", num_steps=32)
    assert k != aot.store_key("p1", sig_b, engine="serial", num_steps=32)
    assert k != aot.store_key("p2", sig_a, engine="serial", num_steps=32)


@pytest.mark.slow  # store fixture: ~4 fresh export compiles
def test_manifest_schema_and_cli(store, capsys):
    """The manifest records key -> file, engine, flavor, compile seconds
    and toolchain per entry, and the jax-free CLI lists it."""
    man = aot.read_manifest(str(store["dir"]))
    assert man["schema"] == "librabft_aot_store"
    assert man["aot_version"] == aot.AOT_VERSION
    engines = set()
    for e in man["entries"]:
        for field in ("store_key", "file", "engine", "flavor", "shapes",
                      "compile_s", "toolchain", "size_bytes"):
            assert field in e, f"manifest entry missing {field}"
        assert e["toolchain"] == ucache.toolchain()
        assert os.path.exists(os.path.join(store["dir"], e["file"]))
        engines.add(e["engine"])
    assert {"serial", "lane", "sharded/serial"} <= engines
    assert aot.main(["--list", "--dir", str(store["dir"])]) == 0
    out = capsys.readouterr().out
    assert "executables" in out and "serial" in out


@pytest.mark.slow  # store fixture: ~4 fresh export compiles
def test_write_disabled_never_writes(store, monkeypatch, tmp_path):
    """Default (suite) behavior: LIBRABFT_AOT_WRITE unset means a miss
    never writes — the store stays a build artifact, not a side effect
    of running tests."""
    d = tmp_path / "empty_store"
    d.mkdir()
    _env(monkeypatch, d, write="0")
    _serial_run(P_SER)
    assert os.listdir(d) == []


def test_cache_toolchain_stamp(monkeypatch, tmp_path):
    """(e) utils/cache.py stamps the persistent-cache dir: fresh dir gets
    the current stamp (not stale); a foreign stamp flips
    stale_toolchain() and is rewritten to current for the next session."""
    d = tmp_path / "pcache"
    d.mkdir()
    monkeypatch.setattr(ucache, "_STALE_TOOLCHAIN", None)
    ucache._stamp_cache_dir(str(d))
    assert ucache.stale_toolchain() is None
    stamp_path = d / ucache.STAMP_FILE
    with open(stamp_path) as f:
        assert json.load(f) == ucache.toolchain()
    foreign = {"jax": "0.0.0", "jaxlib": "0.0.0"}
    with open(stamp_path, "w") as f:
        json.dump(foreign, f)
    ucache._stamp_cache_dir(str(d))
    assert ucache.stale_toolchain() == foreign
    with open(stamp_path) as f:
        assert json.load(f) == ucache.toolchain()  # rewritten current


def test_stale_toolchain_ledger_verdict(monkeypatch):
    """(e) with the stale flag up, a persistent-cache miss classifies
    ``stale-toolchain`` (the round-11 silent-invalidation failure mode,
    made loud); with it down the verdict stays ``persistent-miss``."""
    for prior, want in ((None, "persistent-miss"),
                        ({"jaxlib": "old"}, "stale-toolchain")):
        monkeypatch.setattr(ucache, "_STALE_TOOLCHAIN", prior)
        lg = tledger.RuntimeLedger(clock=lambda: 0.0)
        with lg.compile_attribution("k1", engine="serial"):
            lg.on_event("/jax/compilation_cache/cache_misses")
            lg.on_event_duration(
                "/jax/core/compile/backend_compile_duration", 3.0)
        assert lg.compiles[0]["cache"] == want


@pytest.mark.slow  # store fixture: ~4 fresh export compiles
def test_loaded_executable_reused_across_wrappers(store, monkeypatch):
    """One deserialize per process per entry: a second make_run_fn for
    the same params/shapes reuses the module-wide loaded executable (no
    second load — the per-entry cache is keyed on (dir, store key))."""
    _env(monkeypatch, store["dir"])
    tledger.reset()
    st = simulator.dedupe_buffers(simulator.init_batch(P_SER, SEEDS))
    out1 = simulator.make_run_fn(P_SER, CHUNK)(st)
    loads_before = dict(aot._LOADED)
    st2 = simulator.dedupe_buffers(simulator.init_batch(P_SER, SEEDS))
    out2 = simulator.make_run_fn(P_SER, CHUNK)(st2)
    assert _leaves_equal(out1, out2)
    assert dict(aot._LOADED) == loads_before  # same objects, no new loads


def test_process_topology_stale(monkeypatch, tmp_path):
    """Multi-process key hazard: the store key hashes the GLOBAL device
    count, but a serialized executable bakes in the per-process device
    assignment — so a sidecar whose process_count doesn't match this
    process's world is ``stale`` (loudly, on the ledger path), never a
    silent wrong-topology load.  Pre-field sidecars (no process_count)
    count as single-process builds: still a hit single-process, stale the
    moment the loader runs inside a pod."""
    d = tmp_path / "store"
    d.mkdir()
    monkeypatch.setenv(aot.DIR_ENV, str(d))
    aot.reset_cache()

    def put(key, extra):
        with open(d / (key + ".bin"), "wb") as f:
            f.write(b"\x00")
        side = {"aot_version": aot.AOT_VERSION,
                "toolchain": ucache.toolchain(), **extra}
        with open(d / (key + ".json"), "w") as f:
            json.dump(side, f)

    put("aaaa", {"process_count": 1})   # single-host build
    put("bbbb", {"process_count": 2})   # pod build
    put("cccc", {})                     # pre-field sidecar (= 1 process)

    # Single-process world (this suite): 1-process and legacy sidecars
    # hit; the pod build is stale.
    assert jax.process_count() == 1
    assert aot.lookup("aaaa")[0] == "hit"
    assert aot.lookup("cccc")[0] == "hit"
    assert aot.lookup("bbbb")[0] == "stale"

    # Pod world (2 processes): the single-host store — including the
    # legacy sidecar — is loudly stale; the matching pod build hits.
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    assert aot.lookup("aaaa")[0] == "stale"
    assert aot.lookup("cccc")[0] == "stale"
    assert aot.lookup("bbbb")[0] == "hit"


def test_save_records_process_topology(monkeypatch, tmp_path):
    """save() stamps the builder's process topology into the sidecar (the
    diagnosis fields lookup() judges by)."""
    monkeypatch.setenv(aot.DIR_ENV, str(tmp_path / "s"))

    class FakeCompiled:
        pass

    # serialize() will fail on the fake; save must return None cleanly —
    # the topology fields are pinned via a real export elsewhere (slow
    # leg); here pin the sidecar schema through a monkeypatched
    # serializer so the test stays compile-free.
    import jax.experimental.serialize_executable as se

    monkeypatch.setattr(se, "serialize", lambda c: ("payload", None, None))
    path = aot.save("dddd", FakeCompiled(), compile_s=1.0, engine="x")
    assert path is not None
    with open(str(tmp_path / "s" / "dddd.json")) as f:
        side = json.load(f)
    assert side["process_count"] == jax.process_count() == 1
    assert side["process_index"] == 0
    assert side["device_count_global"] == jax.device_count()
    assert side["device_count_local"] == jax.local_device_count()
