"""Forged-QC defense: vote-set re-verification on QC insert.

Mirrors the reference's per-vote re-verification of received QCs
(/root/reference/librabft-v2/src/record_store.rs:330-389): a QC carries its
aggregated author-bit mask; receivers check the masked weight reaches quorum
and that the content tag (the aggregate-signature stand-in) recomputes from
the carried fields.  Tested at the unit level against both the tensor store
and the Python oracle (decision parity), and end-to-end with a ``forge_qc``
Byzantine attacker.

Model boundary (same as the reference's simulated crypto): a forger that
fabricates a *full-quorum* mask with a self-consistent tag corresponds to
forging signatures and is out of scope; the defense stops every forgery
detectable from the certificate itself.
"""

import jax.numpy as jnp
import numpy as np

from librabft_simulator_tpu.core import config, store as store_ops
from librabft_simulator_tpu.core.types import QcMsg, SimParams, Store
from librabft_simulator_tpu.oracle import engine as O
from librabft_simulator_tpu.sim import byzantine as B
from librabft_simulator_tpu.sim import simulator as S

from tests.test_record_store import SharedStore


def forged_qc_for_current_proposal(p, s, forger, votes_lo, votes_hi,
                                   tamper_tag=False):
    """A QC message on the store's current proposal claiming the given vote
    mask; every non-vote field is what an honest quorum would certify."""
    bvar = max(int(s.proposed_var), 0)
    r = int(s.current_round)
    sl = r % p.window
    _, st_d, st_t = store_ops.compute_state(p, s, r, bvar)
    cs_ok, cs_d, cs_t, _ = store_ops.vote_committed_state(p, s, r, bvar)
    lo = jnp.uint32(votes_lo)
    hi = jnp.uint32(votes_hi)
    tag = store_ops.qc_tag(s.epoch_id, r, s.blk_tag[sl, bvar], st_d, st_t,
                           cs_ok, cs_d, cs_t, lo, hi, forger)
    if tamper_tag:
        tag = tag ^ jnp.uint32(1)
    return QcMsg(
        valid=jnp.bool_(True), epoch=s.epoch_id, round=jnp.int32(r),
        blk_tag=s.blk_tag[sl, bvar], state_depth=st_d, state_tag=st_t,
        commit_valid=cs_ok, commit_depth=cs_d, commit_tag=cs_t,
        votes_lo=lo, votes_hi=hi, author=jnp.int32(forger), tag=tag,
    )


def proposal_store(n=4):
    """A store where the legitimate leader proposed and all honest nodes
    could vote (but have not)."""
    st = SharedStore(n)
    leader = st.leader()
    assert st.propose(leader, 5)
    return st, leader


def test_quorumless_forgery_rejected():
    st, leader = proposal_store(4)
    # Forger = the leader itself, claiming only its own vote.
    q = forged_qc_for_current_proposal(st.p, st.s, leader, 1 << leader, 0)
    s2, ok = store_ops.insert_qc(st.p, st.s, st.w, q)
    assert not bool(ok)
    assert int(jnp.sum(s2.qc_valid)) == 0


def test_unknown_author_bits_rejected():
    st, leader = proposal_store(4)
    # Mask weight 4 >= quorum 3, but bits 10..13 name non-existent authors.
    q = forged_qc_for_current_proposal(st.p, st.s, leader, 0b1111 << 10, 0)
    _, ok = store_ops.insert_qc(st.p, st.s, st.w, q)
    assert not bool(ok)


def test_tampered_tag_rejected():
    st, leader = proposal_store(4)
    q = forged_qc_for_current_proposal(st.p, st.s, leader, 0b0111, 0,
                                       tamper_tag=True)
    _, ok = store_ops.insert_qc(st.p, st.s, st.w, q)
    assert not bool(ok)


def test_consistent_quorum_qc_accepted():
    """The same forged message WITH a quorum-weight mask and untampered tag
    passes — the model boundary — confirming the rejections above are due to
    the vote-set checks, not some other predicate."""
    st, leader = proposal_store(4)
    q = forged_qc_for_current_proposal(st.p, st.s, leader, 0b0111, 0)
    s2, ok = store_ops.insert_qc(st.p, st.s, st.w, q)
    assert bool(ok)
    assert int(s2.hqc_round) == 1


def test_honest_qc_roundtrip_still_accepted():
    """check_new_qc's minted QC re-inserts cleanly at another node."""
    st = SharedStore(4)
    st.make_round(10)
    st.make_round(20)
    assert st.snapshot()["hqc_round"] == 2


def test_oracle_decision_parity():
    """The oracle's insert_qc makes the same accept/reject decisions."""
    p = SimParams(n_nodes=4)
    weights = [1, 1, 1, 1]

    def build_oracle_store():
        s = O.Store(p)
        leader = O.leader_of_round(weights, s.current_round)
        r, t = s.hqc_ref()
        assert s.propose_block(weights, leader, r, t, 5, 5)
        return s, leader

    def forged(s, forger, lo, hi, tamper=False):
        bvar = max(s.proposed_var, 0)
        r = s.current_round
        sl = s._slot(r)
        _, st_d, st_t = s.compute_state(r, bvar)
        cs_ok, cs_d, cs_t, _ = s.vote_committed_state(r, bvar)
        tag = O.fold(O.TAG_QC, s.epoch_id & O.M32, r & O.M32,
                     s.blk_tag[sl][bvar], st_d & O.M32, st_t,
                     int(cs_ok) & O.M32, cs_d & O.M32, cs_t, lo, hi,
                     forger & O.M32)
        if tamper:
            tag ^= 1
        return O.QcMsg(valid=True, epoch=s.epoch_id, round=r,
                       blk_tag=s.blk_tag[sl][bvar], state_depth=st_d,
                       state_tag=st_t, commit_valid=cs_ok, commit_depth=cs_d,
                       commit_tag=cs_t, votes_lo=lo, votes_hi=hi,
                       author=forger, tag=tag)

    s, leader = build_oracle_store()
    assert not s.insert_qc(weights, forged(s, leader, 1 << leader, 0))
    s, leader = build_oracle_store()
    assert not s.insert_qc(weights, forged(s, leader, 0b1111 << 10, 0))
    s, leader = build_oracle_store()
    assert not s.insert_qc(weights, forged(s, leader, 0b0111, 0, tamper=True))
    s, leader = build_oracle_store()
    assert s.insert_qc(weights, forged(s, leader, 0b0111, 0))


def test_mask_weight_helper():
    p = SimParams(n_nodes=4)
    w = jnp.asarray([1, 2, 3, 4], jnp.int32)
    got, known = store_ops.mask_weight(p, w, jnp.uint32(0b1011), jnp.uint32(0))
    assert int(got) == 1 + 2 + 4 and bool(known)
    _, known = store_ops.mask_weight(p, w, jnp.uint32(1 << 4), jnp.uint32(0))
    assert not bool(known)
    _, known = store_ops.mask_weight(p, w, jnp.uint32(0), jnp.uint32(1))
    assert not bool(known)
    p40 = SimParams(n_nodes=40)
    w40 = jnp.ones((40,), jnp.int32)
    got, known = store_ops.mask_weight(
        p40, w40, jnp.uint32(0xFFFFFFFF), jnp.uint32(0xFF))
    assert int(got) == 40 and bool(known)
    _, known = store_ops.mask_weight(
        p40, w40, jnp.uint32(0), jnp.uint32(1 << 8))
    assert not bool(known)


def test_forge_attacker_end_to_end():
    """A forge_qc attacker in the full simulator: honest nodes reject the
    forged certificates (safety holds, commits still happen), and no stored
    QC at any honest node carries a sub-quorum vote mask."""
    p = SimParams(n_nodes=4, delay_kind="uniform", max_clock=1500, window=8,
                  chain_k=2, commit_log=16)
    st = B.init_fault_batch(p, np.arange(8, dtype=np.uint32), f=1,
                            kind="forge_qc")
    st = S.run_to_completion(p, st, batched=True, chunk=256, max_chunks=60)
    assert bool(np.all(np.asarray(st.halted)))
    honest = np.arange(p.n_nodes) >= 1
    assert bool(np.all(B.check_safety(st, honest)))
    cc = np.asarray(st.ctx.commit_count)[:, honest]
    assert cc.max() > 0
    # Every stored QC's mask reaches quorum (the forged ones were rejected).
    qc_valid = np.asarray(st.store.qc_valid)          # [B, N, W, V]
    lo = np.asarray(st.store.qc_votes_lo).astype(np.uint64)
    thresh = int(config.quorum_threshold(jnp.ones((4,), jnp.int32)))
    weights_of_mask = np.zeros_like(lo, dtype=np.int64)
    for a in range(p.n_nodes):
        weights_of_mask += ((lo >> a) & 1).astype(np.int64)
    assert np.all(weights_of_mask[qc_valid] >= thresh)
