"""North-star parity: the jitted JAX trajectory is bit-identical to the
pure-Python oracle (BASELINE.json: 'commit sequences byte-identical')."""

import dataclasses

import jax
import numpy as np
import pytest

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.oracle.sim import OracleSim
from librabft_simulator_tpu.sim import simulator as S


def jax_run(p, seed, **init_kw):
    st = S.init_state(p, seed, **init_kw)
    return S.run_to_completion(p, st)


def jax_committed_chain(st, node):
    cc = int(st.ctx.commit_count[node])
    H = st.ctx.log_depth.shape[-1]
    out = []
    for i in range(max(cc - H, 0), cc):
        pos = i % H
        out.append((int(st.ctx.log_depth[node, pos]), int(st.ctx.log_tag[node, pos])))
    return out


def assert_parity(p, seed, **init_kw):
    st = jax_run(p, seed, **init_kw)
    orc_kw = {k: np.asarray(v).tolist() for k, v in init_kw.items()}
    orc = OracleSim(p, seed, **orc_kw).run()
    assert int(st.n_events) == orc.n_events
    assert int(st.clock) == orc.clock
    assert int(st.stamp_ctr) == orc.stamp_ctr
    assert int(st.n_msgs_sent) == orc.n_msgs_sent
    assert int(st.n_msgs_dropped) == orc.n_msgs_dropped
    assert int(st.n_queue_full) == orc.n_queue_full
    for a in range(p.n_nodes):
        assert jax_committed_chain(st, a) == orc.committed_chain(a), f"node {a}"
        assert int(st.ctx.last_depth[a]) == orc.ctxs[a].last_depth
        assert int(st.ctx.last_tag[a]) == orc.ctxs[a].last_tag
        assert int(st.store.current_round[a]) == orc.stores[a].current_round
        assert int(st.store.hqc_round[a]) == orc.stores[a].hqc_round
        assert int(st.store.hcr[a]) == orc.stores[a].hcr
        assert int(st.node.locked_round[a]) == orc.nxs[a].locked_round
    return st, orc


@pytest.mark.parametrize("seed", [0, 1, 42])
def test_parity_default_3node(seed):
    p = SimParams(n_nodes=3, max_clock=1000)
    st, orc = assert_parity(p, seed)
    assert min(int(c) for c in st.ctx.commit_count) > 0  # non-trivial


def test_parity_4node_uniform():
    p = SimParams(n_nodes=4, max_clock=800, delay_kind="uniform")
    assert_parity(p, 7)


def test_parity_drop_and_pareto():
    p = SimParams(n_nodes=3, max_clock=1500, delay_kind="pareto", drop_prob=0.05)
    st, orc = assert_parity(p, 5)
    assert orc.n_msgs_dropped > 0


def test_parity_weighted_authors():
    p = SimParams(n_nodes=4, max_clock=800)
    assert_parity(p, 3, weights=np.asarray([1, 2, 3, 1], np.int32))


def test_parity_hotstuff_2chain():
    p = SimParams(n_nodes=3, max_clock=800, commit_chain=2)
    st, orc = assert_parity(p, 11)
    assert min(int(c) for c in st.ctx.commit_count) > 0


def test_parity_byzantine_silent():
    p = SimParams(n_nodes=4, max_clock=1000)
    silent = np.asarray([False, False, False, True])
    assert_parity(p, 13, byz_silent=silent)


def test_parity_byzantine_equivocate():
    p = SimParams(n_nodes=4, max_clock=1000)
    eq = np.asarray([True, False, False, False])
    assert_parity(p, 17, byz_equivocate=eq)


def test_parity_byzantine_forge_qc():
    p = SimParams(n_nodes=4, max_clock=1000)
    forge = np.asarray([True, False, False, False])
    st, orc = assert_parity(p, 29, byz_forge_qc=forge)
    assert max(int(c) for c in st.ctx.commit_count) > 0


def test_parity_small_window_forces_jumps():
    p = SimParams(n_nodes=3, max_clock=2000, window=8, chain_k=2, drop_prob=0.1)
    st, orc = assert_parity(p, 19)


def test_parity_long_stall_wide_durations():
    # Heavy drop keeps commits rare, so round durations (delta * n^gamma) grow
    # past 2^16 — the regime where the 16.16 query-all product would overflow
    # int32 if computed naively (core/pacemaker.py saturating arithmetic).
    p = SimParams(n_nodes=4, max_clock=3_000_000, drop_prob=0.5, gamma=4.0)
    st, orc = assert_parity(p, 23)
    assert max(o.round_duration for o in orc.pms) > 65536


def test_unroll_parity():
    """SimParams.unroll only changes how XLA lowers the interior scans
    (rolled while-loops vs unrolled bodies) — the trajectory must be
    bit-identical, including the pick_author branchless form."""
    p = SimParams(n_nodes=4, max_clock=800, delay_kind="uniform")
    st_rolled = jax_run(p, 7)
    st_unrolled = jax_run(dataclasses.replace(p, unroll=True), 7)
    flat_a = jax.tree_util.tree_leaves(st_rolled)
    flat_b = jax.tree_util.tree_leaves(st_unrolled)
    for xa, xb in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))
