"""Seeded receiver-shuffle option (SimParams.shuffle_receivers).

The reference shuffles the delivery order of every broadcast
(/root/reference/bft-lib/src/simulator.rs:343), so which replica's vote
reaches the leader first is randomized per event.  The rebuild's default
enumerates receivers in index order; this option restores the reference's
fuzzing semantics via a seeded permutation that all three implementations
(JAX serial engine, Python oracle, C++ engine) replay bit-identically.
"""

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.oracle.sim import OracleSim

from test_native import assert_native_matches_oracle
from test_parity import assert_parity


def shuffled_params(**kw):
    kw.setdefault("n_nodes", 4)
    kw.setdefault("max_clock", 800)
    kw.setdefault("shuffle_receivers", True)
    return SimParams(**kw)


def test_shuffle_parity_jax_vs_oracle():
    st, orc = assert_parity(shuffled_params(), 7)
    assert min(int(c) for c in st.ctx.commit_count) > 0


def test_shuffle_parity_jax_vs_oracle_drop_pareto():
    p = shuffled_params(n_nodes=3, max_clock=1500, delay_kind="pareto",
                        drop_prob=0.05)
    assert_parity(p, 5)


def test_shuffle_parity_native_vs_oracle():
    res, orc = assert_native_matches_oracle(shuffled_params(), 7)
    assert res.commit_count(0) > 0


def test_shuffle_changes_trajectory():
    """Same seed, shuffle on vs off: the permutation reassigns delay draws to
    receivers, so the trajectories must diverge."""
    base = SimParams(n_nodes=4, max_clock=800)
    orc_off = OracleSim(base, 7).run()
    orc_on = OracleSim(shuffled_params(), 7).run()
    assert orc_off.n_events != orc_on.n_events or any(
        orc_off.committed_chain(a) != orc_on.committed_chain(a)
        for a in range(4)
    )


def test_shuffle_deterministic():
    p = shuffled_params()
    a = OracleSim(p, 11).run()
    b = OracleSim(p, 11).run()
    assert a.n_events == b.n_events and a.stamp_ctr == b.stamp_ctr
    for i in range(p.n_nodes):
        assert a.committed_chain(i) == b.committed_chain(i)
