"""Multi-chip: sharded run equals unsharded run on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.parallel import mesh as mesh_ops
from librabft_simulator_tpu.parallel import sharded
from librabft_simulator_tpu.sim import simulator as S


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return mesh_ops.make_mesh(n_dp=4, n_mp=2)


@pytest.mark.slow  # 102,400-step sharded run on 8 *virtual* CPU devices:
# multi-minute compile+run, the single biggest sink in the 870 s tier-1
# budget; the placement/psum tests below keep multichip wiring covered.
def test_sharded_equals_unsharded(mesh):
    p = SimParams(n_nodes=3, max_clock=300)
    seeds = np.arange(16, dtype=np.uint32)
    ref = S.run_to_completion(p, S.init_batch(p, seeds), batched=True)
    st = sharded.run_sharded(p, mesh, S.init_batch(p, seeds), num_steps=512 * 200)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(st)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # 25,600-step sharded lane-engine run on the virtual
# mesh (see above); environment-bound, not logic-bound.
def test_sharded_parallel_engine_equals_unsharded(mesh):
    """The lane-compacted throughput engine is also collective-free SPMD
    over dp: sharded == unsharded, bit-exact."""
    from librabft_simulator_tpu.sim import parallel_sim as P

    p = SimParams(n_nodes=4, max_clock=400, window=8, chain_k=2,
                  commit_log=16, delay_kind="uniform")
    seeds = np.arange(16, dtype=np.uint32)
    ref = P.run_to_completion(p, P.init_batch(p, seeds), chunk=64,
                              batched=True)
    st = sharded.run_sharded(p, mesh, P.init_batch(p, seeds),
                             num_steps=64 * 400, chunk=64, engine=P)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(st)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_shard_placement(mesh):
    p = SimParams(n_nodes=3)
    st = mesh_ops.shard_batch(mesh, S.init_batch(p, np.arange(8, dtype=np.uint32)))
    assert len(st.clock.sharding.device_set) == 8


def test_mp_quorum_psum(mesh):
    w = jnp.ones((16,), jnp.int32)
    mask = jnp.arange(16) < 11
    assert int(sharded.sharded_count_votes(mesh, w, mask)) == 11
    assert bool(sharded.sharded_quorum_reached(mesh, w, mask))
    mask2 = jnp.arange(16) < 10
    assert not bool(sharded.sharded_quorum_reached(mesh, w, mask2))
