"""Multi-chip fleet runtime: sharded == unsharded on the virtual CPU mesh.

Tier-1 (non-slow) coverage runs a REAL 2-shard dp fleet end to end on
micro-capacity params — small window/queue/horizon keep the two extra XLA
compiles (reference chunk scan + its shard_map wrapping) inside the tier-1
budget — and pins, from one pair of runs each for the serial and lane
engines:

* leaf-bit-identical trajectories vs the unsharded engine at a batch NOT
  divisible by the shard count (the pre-halted padding path);
* telemetry-plane merge and flight-recorder equality (the per-shard fold
  in telemetry/report.py);
* DataWriter round-trace equality per instance;
* padding contributes ZERO to every observable, pinned against the
  pure-Python oracle;
* the pipelined host loop's poll path transfers exactly one small [D]
  fleet-health digest per dispatched chunk (never the [B] halt plane);
* the fleet flight-recorder concat lands instance-tagged rows in
  instance-major order, each tail row-for-row the oracle's event log;
* the mp quorum path armed by SimParams.mp_authors is live in the real
  step (degenerate n_mp=1 identity);
* the device-resident dispatch ring (SimParams.wrap="device": an
  in-graph while_loop retiring up to ring_k chunks per outer call,
  streaming [K,13] digests) is bit-identical to the host wrap on BOTH
  engines at the 2-shard mesh, and its ledger spans amortize the halt
  poll below one per retired chunk.

The 8-shard full-horizon runs stay @slow (multi-minute compile+run on the
8 *virtual* device mesh; environment-bound, not logic-bound).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fleet_shapes import (
    FLEET_B, FLEET_CHUNK, FLEET_LANE_KW, FLEET_RING_K, FLEET_RING_LANE_KW,
    FLEET_RING_SER_KW, FLEET_SER_KW)
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.parallel import mesh as mesh_ops
from librabft_simulator_tpu.parallel import sharded
from librabft_simulator_tpu.sim import parallel_sim as PE
from librabft_simulator_tpu.sim import simulator as S
from librabft_simulator_tpu.telemetry import report as treport


def assert_leaves_equal(a, b, n_valid=None):
    """Bit-equality of every leaf (optionally only the first n_valid
    instances of ``b``, for padded fleets)."""
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        y = np.asarray(y)
        if n_valid is not None:
            y = y[:n_valid]
        np.testing.assert_array_equal(np.asarray(x), y)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return mesh_ops.make_mesh(n_dp=4, n_mp=2)


@pytest.fixture(scope="module")
def mesh2():
    """A 2-shard pure-dp mesh on the first two virtual devices."""
    return mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])


# Micro-capacity fleet shapes: small enough that the tier-1 compile cost
# of (reference scan + shard_map wrapping) stays modest, big enough for a
# non-trivial run (hundreds of events, commits, round switches).  B=5 is
# deliberately NOT divisible by the 2-shard mesh: every fixture run
# exercises the pre-halted padding path.  The structural kwargs come from
# tests/fleet_shapes.py — the single source of truth shared with
# scripts/warm_cache.py — so the shapes the cache warmer compiles are
# exactly the shapes these tests run (max_clock is runtime data, outside
# the jit key).
P_SER = SimParams(max_clock=120, **FLEET_SER_KW)
P_LANE = SimParams(max_clock=150, **FLEET_LANE_KW)
B_ODD = FLEET_B
CHUNK = FLEET_CHUNK
SEEDS = sharded.fleet_seeds(0, B_ODD)


@pytest.fixture(scope="module")
def serial_pair(mesh2):
    ref = S.run_to_completion(P_SER, S.init_batch(P_SER, SEEDS), chunk=CHUNK,
                              batched=True)
    st = sharded.run_sharded(P_SER, mesh2, S.init_batch(P_SER, SEEDS),
                             num_steps=CHUNK * 200, chunk=CHUNK)
    return ref, st


@pytest.fixture(scope="module")
def lane_pair(mesh2):
    ref = PE.run_to_completion(P_LANE, PE.init_batch(P_LANE, SEEDS),
                               chunk=CHUNK, batched=True)
    st = sharded.run_sharded(P_LANE, mesh2, PE.init_batch(P_LANE, SEEDS),
                             num_steps=CHUNK * 200, chunk=CHUNK, engine=PE)
    return ref, st


def test_make_mesh_too_few_devices_raises():
    with pytest.raises(ValueError, match="devices"):
        mesh_ops.make_mesh(n_dp=len(jax.devices()) + 1, n_mp=1)
    with pytest.raises(ValueError, match="n_mp"):
        mesh_ops.make_mesh(n_dp=1, n_mp=0)


def test_two_shard_serial_parity_odd_batch(serial_pair):
    """Serial engine, 2 dp shards, B=5 (padded to 6): every leaf —
    including the telemetry plane and flight ring — is bit-identical to
    the unsharded fleet, and a non-trivial amount of work ran."""
    ref, st = serial_pair
    assert_leaves_equal(ref, st)
    assert int(np.sum(np.asarray(st.n_events))) > 100
    assert min(int(c) for c in np.asarray(st.ctx.commit_count).ravel()) > 0


def test_two_shard_lane_engine_parity_odd_batch(lane_pair):
    """The lane-compacted throughput engine is collective-free SPMD over dp
    too: 2-shard run bit-identical at the padded odd batch."""
    ref, st = lane_pair
    assert_leaves_equal(ref, st)
    assert int(np.sum(np.asarray(st.n_events))) > 100


def test_two_shard_telemetry_merge_and_datawriter(serial_pair):
    """The per-shard telemetry fold and the DataWriter decode of the
    sharded fleet equal the unsharded ones exactly."""
    from librabft_simulator_tpu.analysis import data_writer as dw

    ref, st = serial_pair
    assert treport.merged_metrics(P_SER, st) == treport.merged_metrics(
        P_SER, ref)
    full = treport.fleet_flight(P_SER, st)
    assert full == treport.fleet_flight(P_SER, ref)
    assert treport.fleet_flight(P_SER, st, max_instances=2) == [
        r for r in full if r["instance"] < 2]
    for i in range(B_ODD):
        np.testing.assert_array_equal(
            dw.round_switch_table(P_SER, st, i),
            dw.round_switch_table(P_SER, ref, i))
        assert dw.summary_dict(P_SER, st, i) == dw.summary_dict(P_SER, ref, i)


def test_sharded_telemetry_fold_divisible_batch(serial_pair, mesh2):
    """The per-SHARD fold branches of telemetry/report.py
    (addressable_shards walk in _plane_partial, metrics-shard span matching
    and the max_instances skip in fleet_flight) against the host fold on
    identical data.  The parity fixtures all use the padded odd batch,
    whose result lands on host (unpad) and takes the single-block fallback
    — so this re-places a DIVISIBLE slice of the same run onto the mesh,
    the placement a divisible-B production fleet (sweeps --dp) reports
    from, with no extra engine compiles."""
    ref, _ = serial_pair
    host4 = jax.tree.map(lambda x: np.asarray(x)[:4], ref)
    sh = mesh_ops.batch_sharding(mesh2)
    dev4 = jax.tree.map(lambda x: jax.device_put(x, sh), host4)
    assert len(dev4.metrics.addressable_shards) == 2  # genuinely 2-sharded
    assert treport.merged_metrics(P_SER, dev4) == treport.merged_metrics(
        P_SER, host4)
    full = treport.fleet_flight(P_SER, dev4)
    assert full == treport.fleet_flight(P_SER, host4)
    # max_instances=2: the second shard (span [2, 4)) is skipped whole;
    # =3: the limit cuts mid-shard.
    for k in (2, 3):
        assert treport.fleet_flight(P_SER, dev4, max_instances=k) == [
            r for r in full if r["instance"] < k]


def test_padding_contributes_zero_oracle_pinned(serial_pair):
    """Padded (pre-halted) instances contribute nothing to any observable:
    the padded 2-shard fleet's merged counters equal the SUM of the
    pure-Python oracle's per-instance tallies (any padding leakage would
    overshoot), and its flight rows are exactly the real instances'."""
    from librabft_simulator_tpu.oracle.sim import OracleSim

    _, st = serial_pair
    orcs = [OracleSim(P_SER, int(s)).run() for s in SEEDS]
    md = treport.merged_metrics(P_SER, st)
    ev = [md["ev_notify"], md["ev_request"], md["ev_response"],
          md["ev_timer"]]
    assert ev == [sum(o.tel["ev_kind"][k] for o in orcs) for k in range(4)]
    assert md["fr_count"] == sum(o.n_events for o in orcs)
    assert md["drops"] == sum(o.n_msgs_dropped for o in orcs)
    assert md["overflow"] == sum(o.n_queue_full for o in orcs)
    assert md["sync_jumps"] == sum(
        sum(c.sync_jumps for c in o.ctxs) for o in orcs)
    assert md["queue_hwm"] == max(o.tel["queue_hwm"] for o in orcs) > 0
    assert md["node_depth_hwm"] == [
        max(o.tel["node_depth_hwm"][a] for o in orcs)
        for a in range(P_SER.n_nodes)]
    assert md["commit_lat_miss"] == sum(o.tel["commit_lat_miss"] for o in orcs)
    # Flight rows: per real instance, the oracle's event-log tail —
    # and no rows at all from padding (every instance tag < B).
    rows = treport.fleet_flight(P_SER, st)
    assert {r["instance"] for r in rows} <= set(range(B_ODD))
    for i, orc in enumerate(orcs):
        mine = [{k: v for k, v in r.items() if k != "instance"}
                for r in rows if r["instance"] == i]
        assert len(mine) == min(P_SER.flight_cap, orc.n_events)
        assert mine == orc.tel["flight"][-len(mine):]


def test_fleet_flight_concat_order_oracle_pinned(serial_pair):
    """The fleet flight-recorder concat is a deterministic, instance-major
    sequence: for the padded (indivisible-B) 2-shard fleet, the FULL row
    list equals instance 0's oracle event-log tail, then instance 1's, …
    — each tagged with its instance and in chronological tail order, with
    no padding rows interleaved anywhere.  Pinning the concat ORDER (not
    just per-instance membership) keeps report consumers that index rows
    positionally safe against a shard-fold reordering."""
    from librabft_simulator_tpu.oracle.sim import OracleSim

    _, st = serial_pair
    rows = treport.fleet_flight(P_SER, st)
    expected = []
    for i, s in enumerate(SEEDS):
        orc = OracleSim(P_SER, int(s)).run()
        tail = orc.tel["flight"][-min(P_SER.flight_cap, orc.n_events):]
        expected += [dict(r, instance=i) for r in tail]
    assert rows == expected


def test_poll_path_fetches_digest_only(mesh2, monkeypatch, serial_pair):
    """The pipelined host loop's per-chunk halt poll transfers exactly ONE
    small [D] fleet-health digest per dispatched chunk — never the [B]
    halted plane (the pre-stream run_sharded fetched one bare scalar; the
    pre-PR-3 one the full plane every chunk).  Zero added host syncs: the
    digest IS the halt poll, so fetch count == dispatched chunk count."""
    from librabft_simulator_tpu.telemetry import stream as tstream

    fetched = []
    real_get = jax.device_get

    def spy(x):
        fetched.append(np.shape(x))
        return real_get(x)

    dispatched = []
    real_make = sharded.make_sharded_run_fn

    def make_counting(*a, **kw):
        run = real_make(*a, **kw)

        def counting(st):
            dispatched.append(1)
            return run(st)

        return counting

    monkeypatch.setattr(jax, "device_get", spy)
    monkeypatch.setattr(sharded, "make_sharded_run_fn", make_counting)
    st = sharded.run_sharded(P_SER, mesh2, S.init_batch(P_SER, SEEDS),
                             num_steps=CHUNK * 200, chunk=CHUNK)
    assert len(fetched) > 0
    assert all(s == (tstream.DIGEST_WIDTH,) for s in fetched), fetched
    assert len(fetched) == len(dispatched)  # one poll per chunk, no extras
    monkeypatch.undo()
    assert_leaves_equal(serial_pair[0], st)


def test_non_pipelined_fallback_matches(mesh2, serial_pair):
    """pipeline=False (strict chunk-by-chunk polling) and the GSPMD 'jit'
    wrap both yield the identical trajectory."""
    ref, _ = serial_pair
    st = sharded.run_sharded(P_SER, mesh2, S.init_batch(P_SER, SEEDS),
                             num_steps=CHUNK * 200, chunk=CHUNK,
                             pipeline=False)
    assert_leaves_equal(ref, st)
    st_jit = sharded.run_sharded(P_SER, mesh2, S.init_batch(P_SER, SEEDS),
                                 num_steps=CHUNK * 200, chunk=CHUNK,
                                 wrap="jit")
    assert_leaves_equal(ref, st_jit)


# Ring-dispatch fleet shapes: identical structural kwargs to the host-wrap
# fixtures above except wrap="device" + ring_k (both compile keys), so the
# trajectory itself is pinned bit-identical to the SAME serial_pair /
# lane_pair references — the ring changes WHO drives the chunk loop, never
# what it computes.  Shapes come from tests/fleet_shapes.py so the cache
# warmer pre-compiles exactly these ring executables.
P_RING_SER = SimParams(max_clock=120, **FLEET_RING_SER_KW)
P_RING_LANE = SimParams(max_clock=150, **FLEET_RING_LANE_KW)


def test_device_wrap_ring_serial_bit_identical(mesh2, serial_pair):
    """wrap="device" (in-graph while_loop ring, K=4) retires chunks
    bit-identically to the host wrap on the serial engine, and the ledger
    shows the poll amortization the ring exists for: one POLL per
    dispatched outer call covering >= 1 retired chunks, i.e.
    polls-per-retired-chunk <= 1 (< 1 once any dispatch retires > 1)."""
    from librabft_simulator_tpu.telemetry import ledger as tledger

    ref, _ = serial_pair
    st = sharded.run_sharded(P_RING_SER, mesh2,
                             S.init_batch(P_RING_SER, SEEDS),
                             num_steps=CHUNK * 200, chunk=CHUNK)
    assert_leaves_equal(ref, st)
    ring = tledger.get().ring_stats()
    assert ring is not None, "device wrap recorded no ring POLL spans"
    assert ring["dispatches"] >= 1
    # Strict amortization: this fleet runs many chunks before halting, so
    # at least one outer call must retire >1 chunk — the host wrap's 1.0
    # polls-per-retired-chunk is the bound the ring exists to beat.
    assert ring["retired_chunks"] > ring["dispatches"]
    assert ring["polls_per_retired_chunk"] < 1.0


def test_device_wrap_ring_lane_bit_identical(mesh2, lane_pair):
    """Same ring referee for the lane-compacted throughput engine: the
    make_scan_fn contract is engine-agnostic, so the in-graph ring retires
    the lane engine's chunks bit-identically too."""
    ref, _ = lane_pair
    st = sharded.run_sharded(P_RING_LANE, mesh2,
                             PE.init_batch(P_RING_LANE, SEEDS),
                             num_steps=CHUNK * 200, chunk=CHUNK, engine=PE)
    assert_leaves_equal(ref, st)


def test_device_wrap_requires_shard_map(mesh2):
    """wrap="device" composes with the shard_map wrap only — the GSPMD
    'jit' wrap has no per-shard body to host the ring while_loop."""
    with pytest.raises(ValueError, match="shard_map"):
        sharded.run_sharded(P_RING_SER, mesh2,
                            S.init_batch(P_RING_SER, SEEDS),
                            num_steps=CHUNK * 4, chunk=CHUNK, wrap="jit")


def test_pad_round_trip_and_seeds():
    st = S.init_batch(P_SER, SEEDS)
    padded, n_valid = sharded.pad_to_multiple(P_SER, st, 4)
    assert n_valid == B_ODD and sharded.batch_size(padded) == 8
    assert np.all(np.asarray(padded.halted)[B_ODD:])
    assert not np.any(np.asarray(padded.halted)[:B_ODD])
    assert_leaves_equal(st, sharded.unpad(padded, n_valid))
    # fleet_seeds is layout-independent: per-shard slices == global slice.
    all16 = sharded.fleet_seeds(7, 16)
    np.testing.assert_array_equal(all16[4:8], sharded.fleet_seeds(7, 4, 4))


def test_mp_authors_quorum_wiring():
    """SimParams.mp_authors arms the psum path inside the REAL quorum
    checks (core/store.py via core/config.py): a full step traced under a
    1-shard mp shard_map is bit-identical to the plain step, and the psum
    is actually in the traced graph (count_votes outside an 'mp' context
    raises)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as PS
    from librabft_simulator_tpu.core import config

    p0 = SimParams(n_nodes=3, max_clock=100, window=8, chain_k=2,
                   commit_log=8, queue_cap=16)
    p1 = dataclasses.replace(p0, mp_authors=True)
    mesh1 = mesh_ops.make_mesh(n_dp=1, n_mp=1, devices=jax.devices()[:1])
    ref = jax.jit(S.step_fn_partial(p0))(S.init_state(p0, 7))
    stepped = shard_map(S.step_fn_partial(p1), mesh=mesh1, in_specs=(PS(),),
                        out_specs=PS(), check_rep=False)
    got = jax.jit(stepped)(S.init_state(p1, 7))
    assert_leaves_equal(ref, got)
    # The armed path really is a collective: no mp axis in scope -> error.
    with pytest.raises(NameError):
        jax.jit(lambda w: config.count_votes(
            w, w > 0, axis_name=config.MP_AXIS))(jnp.ones((4,), jnp.int32))
    # And the fleet runtime refuses mp_authors on a wide mp mesh (the
    # batch shards over BOTH axes there, so the quorum psum would mix
    # unrelated instances' weights — fail loud, not livelock).
    mesh_1x2 = mesh_ops.make_mesh(n_dp=1, n_mp=2, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="mp_authors"):
        sharded.make_sharded_run_fn(p1, mesh_1x2, 4)
    # ... and under the GSPMD wrap even at n_mp == 1 (no bound axis there).
    with pytest.raises(ValueError, match="shard_map"):
        sharded.make_sharded_run_fn(p1, mesh1, 4, wrap="jit")


def test_shard_placement(mesh):
    p = SimParams(n_nodes=3)
    st = mesh_ops.shard_batch(mesh, S.init_batch(p, np.arange(8, dtype=np.uint32)))
    assert len(st.clock.sharding.device_set) == 8


def test_mp_quorum_psum(mesh):
    w = jnp.ones((16,), jnp.int32)
    mask = jnp.arange(16) < 11
    assert int(sharded.sharded_count_votes(mesh, w, mask)) == 11
    assert bool(sharded.sharded_quorum_reached(mesh, w, mask))
    mask2 = jnp.arange(16) < 10
    assert not bool(sharded.sharded_quorum_reached(mesh, w, mask2))


@pytest.mark.slow  # 102,400-step sharded run on 8 *virtual* CPU devices:
# multi-minute compile+run, the single biggest sink in the 870 s tier-1
# budget; the micro 2-shard parities above keep the runtime covered in
# tier-1.
def test_sharded_equals_unsharded_8dev(mesh):
    from librabft_simulator_tpu.analysis import data_writer as dw

    p = SimParams(n_nodes=3, max_clock=300, telemetry=True, flight_cap=32,
                  trace_cap=64)
    seeds = np.arange(13, dtype=np.uint32)  # NOT divisible by the 8 devices
    ref = S.run_to_completion(p, S.init_batch(p, seeds), batched=True)
    st = sharded.run_sharded(p, mesh, S.init_batch(p, seeds),
                             num_steps=512 * 200)
    assert_leaves_equal(ref, st)
    assert treport.merged_metrics(p, st) == treport.merged_metrics(p, ref)
    for i in range(len(seeds)):
        np.testing.assert_array_equal(dw.round_switch_table(p, st, i),
                                      dw.round_switch_table(p, ref, i))


@pytest.mark.slow  # 25,600-step sharded lane-engine run on the virtual
# mesh (see above); environment-bound, not logic-bound.
def test_sharded_parallel_engine_equals_unsharded_8dev(mesh):
    """The lane-compacted throughput engine is also collective-free SPMD
    over dp: sharded == unsharded, bit-exact, with padding."""
    p = SimParams(n_nodes=4, max_clock=400, window=8, chain_k=2,
                  commit_log=16, delay_kind="uniform")
    seeds = np.arange(13, dtype=np.uint32)
    ref = PE.run_to_completion(p, PE.init_batch(p, seeds), chunk=64,
                               batched=True)
    st = sharded.run_sharded(p, mesh, PE.init_batch(p, seeds),
                             num_steps=64 * 400, chunk=64, engine=PE)
    assert_leaves_equal(ref, st)
