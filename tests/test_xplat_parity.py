"""On-chip cross-platform parity as a pytest leg (scripts/xplat_parity.py).

Round 5 validated TPU == CPU bit-parity at n=4 shapes, but the tunnel died
before the n=16/64 parallel lowerings (lane routing, flat inbox scatters at
wide widths) could be diffed — PERF_NOTES.md carries that caveat on the
config-3/5 TPU sweep rows.  These tests close it AUTOMATICALLY the next
time the suite runs with a chip visible (e.g. JAX_PLATFORMS=axon): they
skip themselves on CPU-only hosts, so the tier-1 CPU gate is unaffected.

Every test asserts n_bad == 0: every state leaf of the accelerator run
equals the CPU run bit-for-bit.
"""

import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

from xplat_parity import run_check  # noqa: E402


def _accelerator_visible() -> bool:
    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except RuntimeError:
        return False


pytestmark = [
    pytest.mark.tpu,
    pytest.mark.skipif(not _accelerator_visible(),
                       reason="no accelerator device visible "
                              "(jax.devices() is CPU-only)"),
]


def test_serial_fleet_bit_parity():
    """The round-5 validated shape, re-checked after any engine change
    (this PR: packed planes + dense queue writes under TPU lowering)."""
    res = run_check("serial", batch=2048, chunk=96, calls=2)
    assert res.get("n_bad") == 0, res


def test_parallel_n16_2chain_bit_parity():
    """Open caveat (PERF_NOTES.md): sweep config-5's n=16 parallel
    lowering was never diffed on device."""
    res = run_check("parallel", batch=256, chunk=8, calls=2, n_nodes=16,
                    commit_chain=2)
    assert res.get("n_bad") == 0, res


def test_parallel_n64_pareto_drop_bit_parity():
    """Open caveat (PERF_NOTES.md): sweep config-3's n=64 lane routing +
    flat inbox scatters at wide widths."""
    res = run_check("parallel", batch=64, chunk=8, calls=2, n_nodes=64,
                    delay_kind="pareto", drop_prob=0.05)
    assert res.get("n_bad") == 0, res
