"""NodeState update_node loop
(/root/reference/librabft-v2/src/unit_tests/node_tests.rs + node.rs:240-304)."""

import jax
import jax.numpy as jnp

from librabft_simulator_tpu.core import config, node as node_ops, store as store_ops
from librabft_simulator_tpu.core.types import (
    Context, NodeExtra, Pacemaker, SimParams, Store,
)


def slices(p, n):
    return (
        Store.initial(p), Pacemaker.initial(), NodeExtra.initial(),
        Context.initial(p), jnp.ones((n,), jnp.int32),
        jnp.asarray(p.duration_table()),
    )


def test_initial_state_roundtrip():
    # make_initial_state / save / load equality (node_tests.rs:16-21) maps to
    # pytree equality of freshly built state.
    p = SimParams(n_nodes=1)
    s0 = Store.initial(p)
    s1 = Store.initial(p)
    for a, b in zip(jax.tree.leaves(s0), jax.tree.leaves(s1)):
        assert (a == b).all()


def test_single_node_progresses_and_commits():
    # n=1: quorum of 1, the node proposes, votes, mints QCs and commits alone.
    p = SimParams(n_nodes=1)
    s, pm, nx, cx, w, dur = slices(p, 1)
    clock = 0
    for _ in range(8):
        s, pm, nx, cx, act = node_ops.update_node(p, s, pm, nx, cx, w, 0, clock, dur)
        clock = max(clock + 1, int(act.next_sched))
    assert int(s.hqc_round) >= 3
    assert int(s.hcr) >= 1
    assert int(cx.commit_count) >= 1
    # Committed depths are the 1,2,3,... chain of executed commands.
    depths = [int(cx.log_depth[i]) for i in range(int(cx.commit_count))]
    assert depths == list(range(1, len(depths) + 1))


def test_insert_block_qc_updates_hqc():
    # node_tests.rs:24-76: handcrafted block + QC insert moves the hqc.
    p = SimParams(n_nodes=1)
    s, pm, nx, cx, w, dur = slices(p, 1)
    b = store_ops.make_block_msg(p, s, 0, jnp.int32(0), s.initial_tag, 1, 0, 0)
    s, ok = store_ops.insert_block(p, s, w, b, s.epoch_id)
    assert bool(ok)
    s2, ok = store_ops.create_vote(p, s, w, 0, s.current_round, 0)
    assert bool(ok)
    s3, created = store_ops.check_new_qc(p, s2, w, 0)
    assert bool(created)
    assert int(s3.hqc_round) == 1
    _, hqc_tag = store_ops.hqc_ref(p, s3)
    assert int(hqc_tag) != int(s3.initial_tag)


def test_voting_rules_lock_and_latest_voted():
    p = SimParams(n_nodes=3)
    s, pm, nx, cx, w, dur = slices(p, 3)
    author = int(config.leader_of_round(w, 1))
    s, pm, nx, cx, act = node_ops.update_node(p, s, pm, nx, cx, w, author, 0, dur)
    # Leader proposed at round 1 and voted for its own proposal.
    assert int(s.proposed_var) >= 0
    assert int(nx.latest_voted_round) == 1
    assert bool(s.vt_valid[author])
    # The vote goes to the proposer; a second update must not re-vote.
    nx_before = int(nx.latest_voted_round)
    s, pm, nx, cx, act = node_ops.update_node(p, s, pm, nx, cx, w, author, 1, dur)
    assert int(nx.latest_voted_round) == nx_before


def test_timeout_blocks_vote_at_that_round():
    p = SimParams(n_nodes=3, delta=5, gamma=1.0)
    s, pm, nx, cx, w, dur = slices(p, 3)
    leader = int(config.leader_of_round(w, 1))
    other = (leader + 1) % 3
    # First update enters round 1 (round_start = clock); the second, past the
    # deadline, creates a timeout.
    s, pm, nx, cx, act = node_ops.update_node(p, s, pm, nx, cx, w, other, 100, dur)
    assert not bool(s.to_valid[other])
    deadline = int(act.next_sched)
    s, pm, nx, cx, act = node_ops.update_node(p, s, pm, nx, cx, w, other, deadline, dur)
    assert bool(s.to_valid[other])
    assert int(nx.latest_voted_round) >= 1  # never vote at a timed-out round


def test_epoch_switch_resets_store():
    # commands_per_epoch=2: after committing depth 2, the node switches epoch.
    p = SimParams(n_nodes=1, commands_per_epoch=2)
    s, pm, nx, cx, w, dur = slices(p, 1)
    clock = 0
    for _ in range(12):
        s, pm, nx, cx, act = node_ops.update_node(p, s, pm, nx, cx, w, 0, clock, dur)
        clock = max(clock + 1, int(act.next_sched))
        if int(s.epoch_id) >= 1:
            break
    assert int(s.epoch_id) >= 1
    assert int(nx.locked_round) == 0
    assert int(s.initial_state_depth) >= 2
    assert int(cx.commit_count) >= 2
