"""telemetry/stream.py: the per-chunk fleet-health digest, the in-graph
consensus watchdog, and the host timeline.

The acceptance referees of the live-stream PR:

(a) the digest and every watchdog detector match the pure-Python oracle
    exactly on a seeded Byzantine fleet that actually TRIPS the liveness
    stall and (via a doctored committed log — the modeled attacks cannot
    break safety, which is the point of the protocol) the safety
    invariants;
(b) watchdog OFF is free and inert: the wd leaf is zero-width and a
    watchdog-ON run is bit-identical to the OFF run on every common leaf
    (the engine-identity pattern from tests/test_telemetry.py; the
    kernel-census CI gate separately pins the OFF *graph* unchanged);
(c) the slot registry is frozen: the committed digest/watchdog slot order
    is pinned here, and every serialized consumer refuses an artifact from
    another registry version;
(d) the host timeline (TimelineRecorder / NDJSON / fleet_watch) reproduces
    the device digests row-for-row, and the sharded runner's stream ends
    on the fleet's true final digest.

One batched run per engine covers (a), (b) and (d): instance 2 carries
enough silent nodes to break quorum (one of the 3-node serial shape, two
of the 4-node lane shape — one silent node of four leaves a live 3-vote
quorum), instance 3 a doctored committed log (a pre-planted conflicting
entry at depth 1 under a foreign tag with a regressed round), the rest
are honest — so a single compile exercises every detector side by side
with clean instances.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fleet_shapes import (
    FLEET_B, FLEET_CHUNK, FLEET_LANE_KW, FLEET_MACRO_K,
    FLEET_MACRO_WD_SER_KW, FLEET_SER_KW, FLEET_WD_LANE_KW, FLEET_WD_SER_KW)
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.oracle.sim import OracleSim
from librabft_simulator_tpu.sim import parallel_sim as PE
from librabft_simulator_tpu.sim import simulator as S
from librabft_simulator_tpu.telemetry import report as treport
from librabft_simulator_tpu.telemetry import stream as tstream

P_SER = SimParams(max_clock=120, **FLEET_SER_KW)
P_WD_SER = SimParams(max_clock=120, **FLEET_WD_SER_KW)
P_MACRO_WD = SimParams(max_clock=120, **FLEET_MACRO_WD_SER_KW)
P_LANE = SimParams(max_clock=150, **FLEET_LANE_KW)
P_WD_LANE = SimParams(max_clock=150, **FLEET_WD_LANE_KW)
SEEDS = np.arange(FLEET_B, dtype=np.uint32)
SILENT_I = 2   # instance 2: enough silent nodes to break quorum -> stall
DOCTOR_I = 3   # instance 3: doctored committed log -> safety trips


def silent_nodes(p):
    """Silence the smallest node set that breaks quorum: one node of
    three, two of four (one of four leaves a live 3-vote quorum)."""
    return (0,) if p.n_nodes == 3 else (0, 1)


def doctor_ctx(st, i):
    """Plant a conflicting committed entry on instance ``i``'s node 1:
    depth 1 under a tag no honest chain produces, with an absurdly high
    round.  Every honest node's first commit of depth 1 then trips the
    conflicting-commit detector, and node 1's own next commit (same epoch,
    lower round) trips the round-regression detector.  Delivery semantics
    are untouched (commit gating reads last_depth, not commit_count), and
    the oracle twin below doctors the identical fields, so the doctored
    trajectory still pins bit-exactly."""
    cx = st.ctx
    return st.replace(ctx=cx.replace(
        commit_count=cx.commit_count.at[i, 1].set(1),
        log_depth=cx.log_depth.at[i, 1, 0].set(1),
        log_tag=cx.log_tag.at[i, 1, 0].set(0xDEADBEEF),
        log_round=cx.log_round.at[i, 1, 0].set(999)))


def doctor_oracle(orc):
    cx = orc.ctxs[1]
    cx.commit_count = 1
    cx.log_depth[0] = 1
    cx.log_tag[0] = 0xDEADBEEF
    cx.log_round[0] = 999
    return orc


def byz_fleet_state(p, engine):
    st = engine.init_batch(p, SEEDS)
    for a in silent_nodes(p):
        st = st.replace(byz_silent=st.byz_silent.at[SILENT_I, a].set(True))
    return doctor_ctx(st, DOCTOR_I)


def oracle_fleet(p):
    orcs = []
    for i, s in enumerate(SEEDS):
        byz = [i == SILENT_I and a in silent_nodes(p)
               for a in range(p.n_nodes)]
        orc = OracleSim(p, int(s), byz_silent=byz)
        if i == DOCTOR_I:
            doctor_oracle(orc)
        orcs.append(orc.run())
    return orcs


def state_digest(p, st):
    return tstream.decode_digest(
        jax.device_get(tstream.compute_digest(p, st)))


def strip_wd(st):
    b = np.asarray(st.clock).shape[:1]
    return st.replace(wd=jnp.zeros(b + (0,), jnp.int32))


def assert_trees_equal(a, b):
    flat_a = jax.tree_util.tree_flatten_with_path(a)[0]
    flat_b = jax.tree_util.tree_flatten_with_path(b)[0]
    assert len(flat_a) == len(flat_b)
    for (pt, la), (_, lb) in zip(flat_a, flat_b):
        path = "/".join(str(q) for q in pt)
        assert la.dtype == lb.dtype, path
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb), path)


@pytest.fixture(scope="module")
def ser_wd_run(tmp_path_factory):
    """The serial Byzantine fleet, run through the single-chip digest
    contract with a TimelineRecorder streaming NDJSON."""
    path = str(tmp_path_factory.mktemp("stream") / "ser.ndjson")
    rec = tstream.TimelineRecorder(p=P_WD_SER, total_instances=FLEET_B,
                                   out=path)
    st = S.run_to_completion(P_WD_SER, byz_fleet_state(P_WD_SER, S),
                             chunk=FLEET_CHUNK, batched=True, stream=rec)
    rec.close()
    return st, rec, path


@pytest.fixture(scope="module")
def ser_oracles():
    return oracle_fleet(P_WD_SER)


def test_registry_frozen():
    """(c): the committed slot orders.  Reordering, inserting, or removing
    ANY entry must bump REGISTRY_VERSION — this pin is what turns a silent
    slot drift into a loud test failure."""
    assert tstream.REGISTRY_VERSION == 1
    assert tstream.DIGEST_SLOTS == (
        ("halted", "sum"),
        ("events", "sum"),
        ("commits", "sum"),
        ("drops", "sum"),
        ("overflow", "sum"),
        ("queue_depth_max", "max"),
        ("committed_round_min", "min"),
        ("committed_round_max", "max"),
        ("wd_stall", "sum"),
        ("wd_queue_sat", "sum"),
        ("wd_sync_jump", "sum"),
        ("wd_safety_conflict", "sum"),
        ("wd_round_regress", "sum"),
    )
    assert tstream.DIGEST_WIDTH == 13
    assert tstream.SLOT["halted"] == 0  # slot 0 IS the halt poll
    assert tstream.WD_SLOTS == ("stall_ev", "stall", "queue_sat",
                                "sync_jump", "safety_conflict",
                                "round_regress")
    assert tstream.WD_DETECTORS == tstream.WD_SLOTS[1:]
    # The wd plane is sized by the params, zero-width when off.
    assert tstream.wd_width(P_WD_SER) == tstream.WD_WIDTH == 6
    assert tstream.wd_width(P_SER) == 0
    assert S.init_state(P_SER, 0).wd.shape == (0,)
    assert S.init_state(P_WD_SER, 0).wd.shape == (tstream.WD_WIDTH,)


def test_digest_and_watchdog_match_oracle_serial(ser_wd_run, ser_oracles):
    """(a): the fleet digest — watchdog trip counts included — equals the
    fold of the per-instance oracle digests exactly, and the Byzantine /
    doctored instances actually tripped the detectors being pinned."""
    st, _, _ = ser_wd_run
    dev = state_digest(P_WD_SER, st)
    assert dev == tstream.fold_digests(o.digest() for o in ser_oracles)
    assert dev["wd_stall"] >= 1            # silent node: quorum loss
    assert dev["wd_safety_conflict"] >= 1  # doctored conflicting entry
    assert dev["wd_round_regress"] >= 1    # doctored round regression
    assert dev["halted"] == FLEET_B
    assert dev["watchdog_flags"] & (1 << tstream.WD_DETECTORS.index("stall"))
    # Per-instance wd planes: clean instances stay clean.
    wd = np.asarray(st.wd)
    assert wd.shape == (FLEET_B, tstream.WD_WIDTH)
    for i in (0, 1, 4):
        assert not wd[i, 1:].any(), i


def test_watchdog_off_is_inert_serial(ser_wd_run):
    """(b) for the serial engine: the OFF run of the SAME Byzantine fleet
    is bit-identical on every common leaf — watching for anomalies must
    never perturb the trajectory it watches."""
    st_on, _, _ = ser_wd_run
    st_off = S.run_to_completion(P_SER, byz_fleet_state(P_SER, S),
                                 chunk=FLEET_CHUNK, batched=True)
    assert st_off.wd.shape == (FLEET_B, 0)
    assert_trees_equal(strip_wd(st_off), strip_wd(st_on))
    # The digest works with the watchdog off too: wd slots read zero.
    d = state_digest(P_SER, st_off)
    assert {k: v for k, v in d.items() if not k.startswith("wd")
            and k != "watchdog_flags"} == {
        k: v for k, v in state_digest(P_WD_SER, st_on).items()
        if not k.startswith("wd") and k != "watchdog_flags"}
    assert all(d["wd_" + n] == 0 for n in tstream.WD_DETECTORS)
    assert d["watchdog_flags"] == 0


def test_queue_saturation_detector_oracle_pinned():
    """The queue-pressure detector, tripped for real: the 4-node shape's
    shared queue saturates under a silent node (timers pile up while
    quorum stalls), and the per-event saturation count pins against the
    oracle exactly, alongside the whole digest."""
    p = P_WD_LANE  # 4-node shape, SERIAL (shared-queue) engine + oracle
    st = S.init_batch(p, SEEDS)
    st = st.replace(byz_silent=st.byz_silent.at[SILENT_I, 0].set(True))
    st = S.run_to_completion(p, st, chunk=FLEET_CHUNK, batched=True)
    orcs = []
    for i, s in enumerate(SEEDS):
        byz = [i == SILENT_I and a == 0 for a in range(p.n_nodes)]
        orcs.append(OracleSim(p, int(s), byz_silent=byz).run())
    dev = state_digest(p, st)
    assert dev == tstream.fold_digests(o.digest() for o in orcs)
    assert dev["wd_queue_sat"] >= 1
    assert dev["overflow"] >= 1  # saturation really overflowed the queue


@pytest.fixture(scope="module")
def lane_wd_run():
    return PE.run_to_completion(P_WD_LANE, byz_fleet_state(P_WD_LANE, PE),
                                chunk=FLEET_CHUNK, batched=True)


def test_digest_and_watchdog_lane_engine(lane_wd_run):
    """(a) for the lane engine: the digest equals the values recomputed on
    host from the final state leaves (the oracle replays the serial
    engine's shared-queue trajectory, so the lane run pins against its own
    state — the same discipline test_telemetry.py uses), the per-event
    safety detectors trip on the doctored instance, and the sync-jump
    counter shadows the engine's own tally exactly."""
    st = lane_wd_run
    dev = state_digest(P_WD_LANE, st)
    g = lambda x: np.asarray(jax.device_get(x))  # noqa: E731
    assert dev["halted"] == int(g(st.halted).sum()) == FLEET_B
    assert dev["events"] == int(g(st.n_events).sum())
    assert dev["commits"] == int(g(st.ctx.commit_count).sum())
    assert dev["drops"] == int(g(st.n_msgs_dropped).sum())
    assert dev["overflow"] == int(g(st.n_inbox_full).sum())
    occ = g(st.in_valid).astype(np.int64).sum(axis=(1, 2))
    assert dev["queue_depth_max"] == int(occ.max())
    assert dev["committed_round_min"] == int(g(st.store.hcr).min())
    assert dev["committed_round_max"] == int(g(st.store.hcr).max())
    wd = g(st.wd)
    assert dev["wd_sync_jump"] == int(g(st.ctx.sync_jumps).sum())
    assert dev["wd_safety_conflict"] == int(
        wd[:, tstream.WD_SAFETY_CONFLICT].sum()) >= 1
    assert dev["wd_round_regress"] == int(
        wd[:, tstream.WD_ROUND_REGRESS].sum()) >= 1
    assert wd[SILENT_I, tstream.WD_STALL] >= 1  # the stalled instance
    # Clean instances trip nothing.
    for i in (0, 1, 4):
        assert not wd[i, 1:].any(), i


def test_watchdog_off_is_inert_lane(lane_wd_run):
    """(b) for the lane engine."""
    st_on = lane_wd_run
    st_off = PE.run_to_completion(P_LANE, byz_fleet_state(P_LANE, PE),
                                  chunk=FLEET_CHUNK, batched=True)
    assert st_off.wd.shape == (FLEET_B, 0)
    assert_trees_equal(strip_wd(st_off), strip_wd(st_on))


def test_timeline_recorder_rows_and_ndjson(ser_wd_run, ser_oracles):
    """(d): the recorder's rows carry the raw digests plus derived rates,
    the final row IS the fleet's final digest, and the NDJSON file round
    trips through load_ndjson row-for-row."""
    st, rec, path = ser_wd_run
    assert len(rec.rows) >= 1
    final = state_digest(P_WD_SER, st)
    last = rec.rows[-1]
    assert {n: last[n] for n, _ in tstream.DIGEST_SLOTS} == {
        n: final[n] for n, _ in tstream.DIGEST_SLOTS}
    assert last["watchdog_flags"] == final["watchdog_flags"]
    assert last["halt_frac"] == 1.0
    # Monotone cumulative slots chunk over chunk.
    for a, b in zip(rec.rows, rec.rows[1:]):
        assert b["events"] >= a["events"]
        assert b["halted"] >= a["halted"]
        assert b["t_s"] >= a["t_s"]
    # NDJSON round trip: meta carries the registry version; rows match.
    meta, rows = tstream.load_ndjson(path)
    assert meta["registry_version"] == tstream.REGISTRY_VERSION
    assert meta["watchdog"] is True
    assert [r for r in rows if r["kind"] == "row"] == rec.rows
    # The summary block run-reports/bench attach.
    s = rec.summary()
    assert s["registry_version"] == tstream.REGISTRY_VERSION
    assert s["chunks"] == len(rec.rows)
    assert s["final"]["halted"] == FLEET_B
    assert s["watchdog_flags"] == final["watchdog_flags"]


def test_registry_version_refusal(tmp_path):
    """(c): every serialized consumer refuses a foreign registry version
    with a clear error — stream files, saved run-reports, and raw digest
    vectors of the wrong width."""
    bad = tmp_path / "bad.ndjson"
    bad.write_text(json.dumps({"kind": "meta", "registry_version": 999})
                   + "\n")
    with pytest.raises(ValueError, match="registry version"):
        tstream.load_ndjson(str(bad))
    # A pre-versioning file (no meta line at all) is refused too.
    raw = tmp_path / "raw.ndjson"
    raw.write_text(json.dumps({"kind": "row", "halted": 1}) + "\n")
    with pytest.raises(ValueError, match="meta line"):
        tstream.load_ndjson(str(raw))
    rep = tmp_path / "report.json"
    rep.write_text(json.dumps({"registry_version": 0}))
    with pytest.raises(ValueError, match="registry version"):
        treport.load_report(str(rep))
    with pytest.raises(ValueError, match="digest shape"):
        tstream.decode_digest(np.zeros(tstream.DIGEST_WIDTH + 1, np.int32))


def test_fold_digests_and_padding():
    """fold_digests is the host twin of the device's mesh reduction, and
    pad_digest models a pre-halted padding instance: halted 1, everything
    else neutral for its slot's aggregation."""
    pad = tstream.pad_digest()
    assert pad["halted"] == 1 and pad["events"] == 0
    a = dict(pad, halted=1, events=7, queue_depth_max=3,
             committed_round_min=2, committed_round_max=5, wd_stall=1)
    b = dict(pad, halted=0, events=4, queue_depth_max=9,
             committed_round_min=1, committed_round_max=3)
    f = tstream.fold_digests([a, b])
    assert f["halted"] == 1 and f["events"] == 11
    assert f["queue_depth_max"] == 9
    assert f["committed_round_min"] == 1 and f["committed_round_max"] == 5
    assert f["wd_stall"] == 1
    assert f["watchdog_flags"] == 1 << tstream.WD_DETECTORS.index("stall")
    with pytest.raises(ValueError, match="at least one"):
        tstream.fold_digests([])


def test_run_report_carries_version_and_digest(ser_wd_run, tmp_path):
    """run_report stamps the registry version and the final digest (the
    stream summary riding along when a recorder observed the run), and
    save/load round-trips under the version check."""
    st, rec, _ = ser_wd_run
    rep = treport.run_report(P_WD_SER, st, stream=rec)
    assert rep["registry_version"] == tstream.REGISTRY_VERSION
    assert rep["digest"] == state_digest(P_WD_SER, st)
    assert rep["stream"]["chunks"] == len(rec.rows)
    path = str(tmp_path / "report.json")
    treport.save_report(path, rep)
    assert treport.load_report(path) == json.loads(json.dumps(rep))


def test_digest_true_event_counts_at_macro_k(ser_wd_run, ser_oracles):
    """K-event macro-steps through the digest contract: at macro_k=4 each
    dispatched chunk retires K-fold more events, and the digest's
    event/commit counters must stay TRUE in-state tallies (accounted per
    inner iteration) — a per-dispatch tally would undercount K-fold.
    Pinned three ways: the final digest equals the fold of the
    per-event oracle digests exactly, the final state is bit-identical
    to the K=1 run, and the chunk-1 row already carries K x chunk
    event-steps of progress."""
    rec = tstream.TimelineRecorder(p=P_MACRO_WD, total_instances=FLEET_B)
    st = S.run_to_completion(P_MACRO_WD, byz_fleet_state(P_MACRO_WD, S),
                             chunk=FLEET_CHUNK, batched=True, stream=rec)
    dev = state_digest(P_MACRO_WD, st)
    assert dev == tstream.fold_digests(o.digest() for o in ser_oracles)
    assert dev["events"] > 0 and dev["halted"] == FLEET_B
    # Bit-identity with the K=1 run of the same fleet (macro_k reshapes
    # dispatch, never trajectory).
    assert_trees_equal(ser_wd_run[0], st)
    # The recorder's steps metadata counts EVENT-steps, not dispatches.
    assert rec.rows[0]["steps"] == FLEET_CHUNK * FLEET_MACRO_K
    # And the K=1 stream of the same horizon needed K-fold more chunks
    # (same trajectory, fewer dispatches — the whole point).
    k1_rows = len(ser_wd_run[1].rows)
    assert len(rec.rows) < k1_rows
    assert k1_rows <= FLEET_MACRO_K * len(rec.rows)


def test_sharded_macro_digest_true_counts(ser_wd_run, ser_oracles):
    """The fleet runtime at macro_k=4: run_sharded's per-chunk digest poll
    still ends on the fleet's true final digest — the fold of the oracle
    digests plus the pre-halted pad row — and the unpadded state matches
    the K=1 single-chip run bit-for-bit (macro_k threads through
    make_sharded_run_fn without touching the poll contract)."""
    from librabft_simulator_tpu.parallel import mesh as mesh_ops
    from librabft_simulator_tpu.parallel import sharded

    assert len(jax.devices()) >= 2, "conftest must force 8 CPU devices"
    mesh2 = mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])
    rec = tstream.TimelineRecorder(p=P_MACRO_WD)
    st = sharded.run_sharded(P_MACRO_WD, mesh2,
                             byz_fleet_state(P_MACRO_WD, S),
                             num_steps=FLEET_CHUNK * 200, chunk=FLEET_CHUNK,
                             stream=rec)
    last = rec.rows[-1]
    expect = tstream.fold_digests(
        [o.digest() for o in ser_oracles] + [tstream.pad_digest()])
    assert {n: last[n] for n, _ in tstream.DIGEST_SLOTS} == {
        n: expect[n] for n, _ in tstream.DIGEST_SLOTS}
    assert last["halted"] == 6
    # steps metadata: event-steps (chunk * K per dispatched chunk).
    assert rec.rows[0]["steps"] == FLEET_CHUNK * FLEET_MACRO_K
    assert_trees_equal(ser_wd_run[0], st)


def test_sharded_stream_ends_on_true_final_digest(ser_wd_run, ser_oracles):
    """(d) for the fleet runtime: run_sharded's per-chunk digest poll
    (padded 2-shard mesh, B=5 -> 6) feeds the recorder a timeline whose
    final row equals the fold of the oracle digests plus one pad_digest
    row — the padding's only trace is its pre-halted count — and the
    unpadded final state matches the single-chip run bit-for-bit."""
    from librabft_simulator_tpu.parallel import mesh as mesh_ops
    from librabft_simulator_tpu.parallel import sharded

    assert len(jax.devices()) >= 2, "conftest must force 8 CPU devices"
    mesh2 = mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])
    rec = tstream.TimelineRecorder(p=P_WD_SER)
    st = sharded.run_sharded(P_WD_SER, mesh2, byz_fleet_state(P_WD_SER, S),
                             num_steps=FLEET_CHUNK * 200, chunk=FLEET_CHUNK,
                             stream=rec)
    assert rec.total_instances == 6  # set_fleet reported the PADDED total
    last = rec.rows[-1]
    expect = tstream.fold_digests(
        [o.digest() for o in ser_oracles] + [tstream.pad_digest()])
    assert {n: last[n] for n, _ in tstream.DIGEST_SLOTS} == {
        n: expect[n] for n, _ in tstream.DIGEST_SLOTS}
    assert last["halted"] == 6 and last["halt_frac"] == 1.0
    assert_trees_equal(ser_wd_run[0], st)
