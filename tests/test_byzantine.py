"""Safety under Byzantine faults (BASELINE config #4):
no conflicting commits among honest nodes for f <= floor((n-1)/3).

Liveness notes: the leader schedule is a fixed pseudorandom sequence
(config.leader_of_round), so a faulty author stalls exactly the rounds it
leads.  For n=4, author 3 first leads at round 13 — making IT faulty keeps
early rounds honest-led, which lets liveness assertions run at short clocks.
Author 0 leads rounds 2,5,7,8,9,10,12, so making it faulty defers commits
past clock ~10k: those configs assert safety only.
"""

import jax.numpy as jnp
import numpy as np

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import byzantine as B
from librabft_simulator_tpu.sim import simulator as S


def run_fleet(p, n_inst, f, kind, authors=None):
    seeds = np.arange(n_inst, dtype=np.uint32)
    st = B.init_fault_batch(p, seeds, f=f, kind=kind, authors=authors)
    return S.run_to_completion(p, st, batched=True, max_chunks=400)


def test_equivocator_within_threshold_safe_and_live():
    p = SimParams(n_nodes=4, max_clock=1500)
    st = run_fleet(p, 24, f=1, kind="equivocate", authors=[3])
    honest = np.arange(4) != 3
    safe = B.check_safety(st, honest)
    assert safe.all(), f"{(~safe).sum()} unsafe instances"
    cc = np.asarray(st.ctx.commit_count)[:, honest]
    assert (cc.max(axis=1) > 0).mean() > 0.8


def test_equivocator_bad_schedule_still_safe():
    # Author 0 equivocating blocks early commit windows: liveness is deferred
    # but safety must be unconditional.
    p = SimParams(n_nodes=4, max_clock=3000)
    st = run_fleet(p, 16, f=1, kind="equivocate")  # authors=[0]
    honest = np.arange(4) >= 1
    assert B.check_safety(st, honest).all()


def test_silent_node_within_threshold_safe_and_live():
    p = SimParams(n_nodes=4, max_clock=2000)
    st = run_fleet(p, 16, f=1, kind="silent", authors=[3])
    honest = np.arange(4) != 3
    assert B.check_safety(st, honest).all()
    cc = np.asarray(st.ctx.commit_count)[:, honest]
    assert (cc.max(axis=1) > 0).all()


def test_f_sweep_structure():
    p = SimParams(n_nodes=4, max_clock=800)
    res = B.f_sweep(p, n_instances=8, f_values=[0, 1], kind="equivocate")
    assert [r.f for r in res] == [0, 1]
    for r in res:
        assert r.safe_fraction == 1.0
    assert res[0].live_fraction == 1.0


def test_device_safety_checker_matches_reference():
    """The device-side sort-reduction == the Python triple loop, on a real
    Byzantine batch AND on a state with an injected conflict."""
    p = SimParams(n_nodes=4, max_clock=1200)
    st = run_fleet(p, 12, f=1, kind="equivocate", authors=[3])
    honest = np.arange(4) != 3
    np.testing.assert_array_equal(B.check_safety(st, honest),
                                  B.check_safety_reference(st, honest))
    np.testing.assert_array_equal(B.check_safety(st),
                                  B.check_safety_reference(st))
    # Inject a conflicting tag at an equal depth into instance 0, node 1.
    log_tag = np.asarray(st.ctx.log_tag).copy()
    log_depth = np.asarray(st.ctx.log_depth).copy()
    cc = np.asarray(st.ctx.commit_count)
    b = int(np.argmax(cc[:, 1] > 0))
    assert cc[b, 1] > 0 and cc[b, 2] > 0
    log_depth[b, 1, 0] = log_depth[b, 2, 0]
    log_tag[b, 1, 0] = log_tag[b, 2, 0] ^ 1
    st2 = st.replace(ctx=st.ctx.replace(
        log_tag=jnp.asarray(log_tag), log_depth=jnp.asarray(log_depth)))
    got = B.check_safety(st2, honest)
    ref = B.check_safety_reference(st2, honest)
    np.testing.assert_array_equal(got, ref)
    assert not got[b]


def test_forge_qc_sweep_safe():
    """config #4 with the forge_qc attacker: sweep stays safe."""
    p = SimParams(n_nodes=4, max_clock=800)
    res = B.f_sweep(p, n_instances=8, f_values=[0, 1], kind="forge_qc")
    for r in res:
        assert r.safe_fraction == 1.0


def test_too_many_silent_loses_liveness_not_safety():
    # f=2 of 4 silent: quorum of 3 unreachable -> no commits, but never unsafe.
    p = SimParams(n_nodes=4, max_clock=800)
    st = run_fleet(p, 8, f=2, kind="silent")
    honest = np.arange(4) >= 2
    assert B.check_safety(st, honest).all()
    cc = np.asarray(st.ctx.commit_count)[:, honest]
    assert (cc == 0).all()
