"""Mirrors /root/reference/bft-lib/src/unit_tests/configuration_tests.rs."""

import jax.numpy as jnp
import numpy as np

from librabft_simulator_tpu.core import config


def test_count():
    weights = jnp.asarray([1, 2, 3], jnp.int32)
    assert int(config.total_votes(weights)) == 6
    mask1 = jnp.asarray([False, True, False])
    assert int(config.count_votes(weights, mask1)) == 2
    mask_none = jnp.asarray([False, False, False])
    assert int(config.count_votes(weights, mask_none)) == 0


def test_pick_author_weighted_hits():
    # Over total_votes consecutive residues, each author is hit in proportion
    # to its weight (configuration_tests.rs::test_pick_author).
    weights = jnp.asarray([1, 2, 5], jnp.int32)
    hits = {}
    for seed in range(20, 20 + 8):
        a = int(config.pick_author(weights, jnp.uint32(seed)))
        hits[a] = hits.get(a, 0) + 1
    assert sorted(hits.values()) == [1, 2, 5]


def test_quorum_thresholds():
    for n, expect in [(1, 1), (2, 2), (3, 3), (4, 3), (5, 4), (6, 5)]:
        w = jnp.ones((n,), jnp.int32)
        assert int(config.quorum_threshold(w)) == expect


def test_validity_thresholds():
    # (N + 2) / 3 (configuration.rs:58-62): f+1 for N = 3f+1.
    for n, expect in [(1, 1), (2, 1), (3, 1), (4, 2), (5, 2), (6, 2), (7, 3)]:
        w = jnp.ones((n,), jnp.int32)
        assert int(config.validity_threshold(w)) == expect


def test_leader_of_round_is_deterministic_and_weighted():
    w = jnp.asarray([0, 0, 7], jnp.int32)
    for r in range(1, 10):
        assert int(config.leader_of_round(w, r)) == 2  # only author with weight
    w2 = jnp.ones((4,), jnp.int32)
    leaders = {int(config.leader_of_round(w2, r)) for r in range(1, 40)}
    assert leaders == {0, 1, 2, 3}  # every author leads eventually
    a = int(config.leader_of_round(w2, 5))
    assert a == int(config.leader_of_round(w2, 5))
