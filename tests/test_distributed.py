"""Multi-process fleet runtime (distributed/): local-cluster referees.

The acceptance contracts of the pod-scale subsystem, run on REAL
``jax.distributed`` processes (loopback coordinator, gloo CPU
collectives, one device per child):

* a 2-process local-cluster fleet run is leaf-BIT-IDENTICAL to the
  single-process sharded run at the same micro shape, digest stream
  included, for BOTH engines — and every process fetched exactly one
  [13] digest per dispatched chunk (the run_sharded poll contract,
  restated per host);
* per-host egress: each process writes only its own result shard /
  NDJSON stream, and the host-0 merge step reassembles the exact fleet;
* resize-under-fire: a 2-process fleet checkpoints mid-run, one process
  is SIGKILLed while the fleet is still dispatching, and a 1-process
  resume from the surviving per-host shards runs to a final state
  bit-equal to an uninterrupted run.

Cluster children warm a DEDICATED AOT store (/tmp/librabft_aot_dist —
persistent across runs, like /tmp/jax_cache): on multi-process CPU the
persistent XLA cache cannot cross processes (jax hashes the device
assignment into the cache key on every platform but GPU, so process 0
hits and every other process recompiles ~30 s per run); the AOT store,
keyed on the GLOBAL device count, is both the fix and the production
ship-the-store-to-every-host workflow.  First-ever run pays the export
compiles; afterwards every child aot-hits in a few seconds.
"""

import json
import os
import sys

import jax
import numpy as np
import pytest

from fleet_shapes import FLEET_B, FLEET_CHUNK, FLEET_LANE_KW, FLEET_SER_KW
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.distributed import bootstrap, egress, elastic
from librabft_simulator_tpu.distributed.workers import _digest_rows
from librabft_simulator_tpu.parallel import mesh as mesh_ops
from librabft_simulator_tpu.parallel import sharded
from librabft_simulator_tpu.sim import checkpoint as C
from librabft_simulator_tpu.sim import parallel_sim as PE
from librabft_simulator_tpu.sim import simulator as S
from librabft_simulator_tpu.telemetry import stream as tstream

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "scripts"))
import fleet_watch  # noqa: E402

P_SER = SimParams(max_clock=120, **FLEET_SER_KW)
P_LANE = SimParams(max_clock=150, **FLEET_LANE_KW)
SEEDS = sharded.fleet_seeds(0, FLEET_B)

#: The cluster children's AOT store: dedicated (never the suite's
#: default store) and persistent across sessions so only the first-ever
#: run pays the multi-process export compiles.
DIST_AOT = {"LIBRABFT_AOT_DIR": "/tmp/librabft_aot_dist",
            "LIBRABFT_AOT_WRITE": "1"}

ENGINES = {
    "serial": (S, P_SER),
    "parallel": (PE, P_LANE),
}


def _cluster_fleet(tmp_path, engine_name: str):
    _, p = ENGINES[engine_name]
    out_dir = str(tmp_path / f"out-{engine_name}")
    results = bootstrap.local_cluster(
        2, "librabft_simulator_tpu.distributed.workers:fleet_run",
        {"params_kw": {**dict(FLEET_SER_KW if engine_name == "serial"
                              else FLEET_LANE_KW),
                       "max_clock": p.max_clock},
         "engine": engine_name, "b": FLEET_B, "chunk": FLEET_CHUNK,
         "out_dir": out_dir},
        timeout_s=900, workdir=str(tmp_path / f"cluster-{engine_name}"),
        env_extra=DIST_AOT)
    return results, out_dir


def _reference(engine_name: str):
    eng, p = ENGINES[engine_name]
    mesh2 = mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])
    rec = tstream.TimelineRecorder(p)
    ref = sharded.run_sharded(p, mesh2, eng.init_batch(p, SEEDS),
                              num_steps=FLEET_CHUNK * 200,
                              chunk=FLEET_CHUNK, engine=eng, stream=rec)
    return ref, rec


@pytest.mark.parametrize("engine_name", ["serial", "parallel"])
def test_two_process_cluster_bit_identical(tmp_path, engine_name):
    """ACCEPTANCE: the 2-process local-cluster fleet == the
    single-process sharded run, leaf-for-leaf, digest stream included;
    exactly one [13] digest fetch per dispatched chunk PER PROCESS
    (each child's spy restates the test_multichip monkeypatch pin)."""
    eng, p = ENGINES[engine_name]
    results, out_dir = _cluster_fleet(tmp_path, engine_name)
    ref, rec = _reference(engine_name)

    # Per-process digest-poll contract.
    for res in results:
        assert res["poll_shapes_ok"], res
        assert res["chunks_polled"] == res["chunks_dispatched"] > 0
        assert res["process_count"] == 2 and res["global_devices"] == 2

    # Per-host egress covered disjoint spans of the real fleet.
    spans = sorted(tuple(s) for r in results for s in r["spans"])
    assert spans == [(0, 3), (3, 5)]

    # Digest stream: identical across hosts (mesh-reduced in-graph) and
    # identical to the single-process run's, chunk for chunk.
    assert results[0]["digest_rows"] == results[1]["digest_rows"]
    assert results[0]["digest_rows"] == _digest_rows(rec)

    # Host-0 merge of the per-host result shards == the single-process
    # fleet, bit-for-bit, every leaf.
    merged = egress.merge_shards(os.path.join(out_dir, "result.d"))
    like = jax.eval_shape(
        lambda: eng.init_batch(p, np.zeros(FLEET_B, np.uint32)))
    got = C.load(merged, p, like=like)
    for (pt, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(ref)[0],
            jax.tree_util.tree_flatten_with_path(got)[0]):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg="/".join(str(q) for q in pt))

    # Per-host NDJSON streams carry their writer's identity and decode
    # under the frozen registry version.
    for pid in (0, 1):
        meta, rows = tstream.load_ndjson(
            os.path.join(out_dir, f"fleet.p{pid}.ndjson"))
        assert meta["process_id"] == pid and meta["process_count"] == 2
        assert [r for r in rows if r.get("kind") == "row"]

    # Per-host telemetry partials fold to the single-process fleet view.
    if p.telemetry:
        from librabft_simulator_tpu.telemetry import report as treport

        folded = egress.fold_metric_dicts(
            p, [r["telemetry_partial"] for r in results])
        assert folded == treport.merged_metrics(p, ref)


def test_resize_under_fire(tmp_path):
    """ACCEPTANCE: kill one process mid-run, resume on fewer from the
    per-host checkpoint shards, final results bit-equal to an
    uninterrupted run.  The fleet runs a non-halting horizon so the kill
    provably lands while chunks are still dispatching; both legs run the
    same fixed chunk count (deterministic boundaries)."""
    params_kw = dict(FLEET_SER_KW, max_clock=2**30)
    p = SimParams(**params_kw)
    ckpt_dir = str(tmp_path / "ckpt.d")
    scene = elastic.resize_under_fire(
        2,
        {"params_kw": params_kw, "engine": "serial", "b": FLEET_B,
         "chunk": FLEET_CHUNK, "stop_chunks": 2, "ckpt_dir": ckpt_dir,
         "keep_firing": True},
        victim=1, timeout_s=900, workdir=str(tmp_path / "fire"))
    assert scene["returncodes"][1] is not None  # the victim is dead
    assert os.path.exists(os.path.join(ckpt_dir, "shard-0.npz"))
    assert os.path.exists(os.path.join(ckpt_dir, "shard-1.npz"))

    # Resume on FEWER processes (1, here in-process) from the shards the
    # dead fleet left behind; continue for 4 more chunks.
    mesh2 = mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])
    host, n_valid = elastic.resume(ckpt_dir, p)
    assert n_valid == FLEET_B
    out = sharded.run_sharded(p, mesh2, host, num_steps=FLEET_CHUNK * 4,
                              chunk=FLEET_CHUNK)

    # Uninterrupted reference: 6 chunks straight through.
    ref = sharded.run_sharded(p, mesh2, S.init_batch(p, SEEDS),
                              num_steps=FLEET_CHUNK * 6, chunk=FLEET_CHUNK)
    for (pt, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(ref)[0],
            jax.tree_util.tree_flatten_with_path(out)[0]):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg="/".join(str(q) for q in pt))


# ---------------------------------------------------------------------------
# Cluster-free units (span math, shard save/merge, bootstrap knobs,
# merge watch) — milliseconds, no child processes, no compiles.
# ---------------------------------------------------------------------------


def test_local_spans_math():
    mesh2 = mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])
    # Single process owns everything; padding rows trimmed; adjacent
    # spans merged.
    assert egress.local_spans(mesh2, 6, 5, process_index=0) == [(0, 5)]
    assert egress.local_spans(mesh2, 6, 6, process_index=0) == [(0, 6)]
    # A process owning no devices of this mesh gets nothing.
    assert egress.local_spans(mesh2, 6, 5, process_index=3) == []
    with pytest.raises(ValueError, match="tile"):
        egress.local_spans(mesh2, 5, 5, process_index=0)


def test_shard_save_merge_roundtrip(tmp_path):
    """save_shards on a (single-process) sharded fleet + merge_shards
    reassembles the exact batched checkpoint; gaps and mixed fleets are
    refused loudly."""
    ctx = bootstrap.DistContext(0, 1, None, False)
    mesh2 = mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])
    st = S.init_batch(P_SER, SEEDS)
    padded, n_valid = sharded.pad_to_multiple(P_SER, st, mesh2.size)
    dev = mesh_ops.shard_batch(mesh2, padded)
    d = str(tmp_path / "ck.d")
    egress.save_shards(d, dev, n_valid, mesh2, ctx)
    merged = egress.merge_shards(d)
    like = jax.eval_shape(
        lambda: S.init_batch(P_SER, np.zeros(FLEET_B, np.uint32)))
    got = C.load(merged, P_SER, like=like)
    for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Incomplete shard set: loud refusal, not a partial fleet.
    side_path = os.path.join(d, "shard-0.json")
    with open(side_path) as f:
        side = json.load(f)
    # Offset span, payload-consistent (5 rows either way): a pure
    # coverage gap — rows [0, 1) belong to nobody.
    side["spans"] = [[1, 6]]
    with open(side_path, "w") as f:
        json.dump(side, f)
    with pytest.raises(ValueError, match="covers"):
        egress.merge_shards(d)
    # Mixed n_valid across shards: also loud.
    side["spans"] = [[0, 5]]
    side["n_valid"] = 7
    with open(side_path, "w") as f:
        json.dump(side, f)
    with pytest.raises(ValueError, match="n_valid"):
        egress.merge_shards(d)
    with pytest.raises(FileNotFoundError):
        egress.merge_shards(str(tmp_path / "empty.d"))


def test_merge_shards_corruption_refused(tmp_path):
    """Corruption paths of the failover restart (round-16 satellite):
    a truncated ``shard-<pid>.npz`` (writer SIGKILLed mid-write), a
    sidecar/payload span mismatch (mixed checkpoint generations), and a
    torn final NDJSON line in a per-host digest stream — each refused
    loudly with a recovery hint (or, for the torn tail, tolerated per
    the PR-7 contract), never an unhandled traceback."""
    ctx = bootstrap.DistContext(0, 1, None, False)
    mesh2 = mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])
    st = S.init_batch(P_SER, SEEDS)
    padded, n_valid = sharded.pad_to_multiple(P_SER, st, mesh2.size)
    dev = mesh_ops.shard_batch(mesh2, padded)
    d = str(tmp_path / "ck.d")
    egress.save_shards(d, dev, n_valid, mesh2, ctx)
    bin_path = os.path.join(d, "shard-0.npz")
    with open(bin_path, "rb") as f:
        blob = f.read()

    # (a) Truncated archive: a clean ValueError naming the shard and the
    # likely cause — np.load's zipfile internals never escape.
    with open(bin_path, "wb") as f:
        f.write(blob[: len(blob) // 3])
    with pytest.raises(ValueError, match="unreadable checkpoint shard"):
        egress.merge_shards(d)

    # (b) Sidecar/payload span mismatch: the sidecar promises 6 rows,
    # the archive block holds 5 — concatenating would silently corrupt
    # the resumed fleet, so the merge refuses before assembling.
    with open(bin_path, "wb") as f:
        f.write(blob)
    side_path = os.path.join(d, "shard-0.json")
    with open(side_path) as f:
        side = json.load(f)
    side["spans"] = [[0, 6]]
    side["n_valid"] = 6
    with open(side_path, "w") as f:
        json.dump(side, f)
    with pytest.raises(ValueError, match="sidecar span .* disagree"):
        egress.merge_shards(d)

    # (c) Torn final NDJSON line in a per-host digest stream (the
    # timeout-kill signature): the intact prefix loads, and the merged
    # fleet_watch view still renders; corrupt NON-final rows stay loud.
    path = egress.host_stream_path(str(tmp_path / "fleet.ndjson"), 0)
    dg = np.zeros((tstream.DIGEST_WIDTH,), np.int64)
    rec = tstream.TimelineRecorder(
        P_SER, total_instances=6, out=path,
        meta={"process_id": 0, "process_count": 1})
    rec.record(dg, steps=32)
    rec.close()
    with open(path) as f:
        whole = f.read()
    with open(path, "a") as f:
        f.write('{"kind": "digest", "chunk": 99, "torn')  # no newline
    meta, rows = tstream.load_ndjson(path)
    assert len(rows) == 1 and rows[0]["chunk"] == 0
    rc = fleet_watch.main([str(tmp_path / "fleet.p*.ndjson"),
                           "--merge", "--once"])
    assert rc == 0
    with open(path, "w") as f:
        f.write(whole.splitlines()[0] + "\n" + '{"torn": mid\n'
                + whole.splitlines()[-1] + "\n")
    with pytest.raises(ValueError):
        tstream.load_ndjson(path)


def test_bootstrap_env_knobs(monkeypatch):
    """Knob wiring: unset/1 -> the degenerate single-process context
    (nothing initializes); a partial multi-process triple fails loud."""
    monkeypatch.setattr(bootstrap, "_CTX", None)
    monkeypatch.delenv(bootstrap.NPROC_ENV, raising=False)
    ctx = bootstrap.init_from_env()
    assert ctx == bootstrap.DistContext(0, 1, None, False)
    assert not ctx.is_multiprocess and ctx.is_host0

    monkeypatch.setattr(bootstrap, "_CTX", None)
    monkeypatch.setenv(bootstrap.NPROC_ENV, "2")
    with pytest.raises(ValueError, match="coordinator triple"):
        bootstrap.init_from_env()
    monkeypatch.setenv(bootstrap.COORD_ENV, "127.0.0.1:1")
    monkeypatch.setenv(bootstrap.PID_ENV, "5")
    monkeypatch.setattr(bootstrap, "_CTX", None)
    with pytest.raises(ValueError, match="out of range"):
        bootstrap.init_from_env()
    monkeypatch.setattr(bootstrap, "_CTX", None)

    with pytest.raises(ValueError, match=">= 1"):
        bootstrap.local_cluster(0, "x:y")
    with pytest.raises(ValueError, match="module:function"):
        bootstrap._resolve_target("no_colon")


def test_fold_metric_dicts():
    """The host-0 telemetry merge: counters sum, high-water marks max —
    against merged_metrics on the concatenated fleet."""
    from librabft_simulator_tpu.telemetry import report as treport

    st = S.run_to_completion(P_SER, S.init_batch(P_SER, SEEDS),
                             chunk=FLEET_CHUNK, batched=True)
    host = jax.tree.map(lambda x: np.asarray(x), st)
    left = jax.tree.map(lambda x: x[:3], host)
    right = jax.tree.map(lambda x: x[3:], host)
    folded = egress.fold_metric_dicts(
        P_SER, [treport.merged_metrics(P_SER, left),
                treport.merged_metrics(P_SER, right)])
    assert folded == treport.merged_metrics(P_SER, host)
    with pytest.raises(ValueError, match="at least one"):
        egress.fold_metric_dicts(P_SER, [])


def test_fleet_watch_merge(tmp_path, capsys):
    """scripts/fleet_watch.py --merge: two per-host streams render as one
    host-tagged fleet view; zero glob matches exits 1 with a message,
    never a traceback."""
    p = P_SER
    dg = np.zeros((tstream.DIGEST_WIDTH,), np.int64)
    dg[tstream.SLOT["events"]] = 7
    for pid in (0, 1):
        path = egress.host_stream_path(str(tmp_path / "fleet.ndjson"), pid)
        rec = tstream.TimelineRecorder(
            p, total_instances=6, out=path,
            meta={"process_id": pid, "process_count": 2})
        rec.record(dg, steps=32)
        rec.close()

    rc = fleet_watch.main([str(tmp_path / "fleet.p*.ndjson"),
                           "--merge", "--once"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "host p0" in out and "host p1" in out
    assert out.count("   p0 ") + out.count("   p1 ") >= 2

    rc = fleet_watch.main([str(tmp_path / "fleet.p*.ndjson"),
                           "--merge", "--summary"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["p0"]["final"]["events"] == 7 == doc["p1"]["final"]["events"]

    rc = fleet_watch.main([str(tmp_path / "nothing.p*.ndjson"),
                           "--merge", "--once"])
    err = capsys.readouterr().err
    assert rc == 1 and "matched no files" in err


def test_unpad_padding_only_process_lands_empty():
    """A process owning ONLY padding rows (b=5 over 4 single-device
    processes pads to 8; the last process holds [6, 8)) lands an EMPTY
    local slice, not a crash — the multi-process block walk of
    parallel.sharded.unpad."""

    class FakeShard:
        def __init__(self, start, data):
            self.index = (slice(start, start + data.shape[0]),)
            self.data = data

    class FakeSharding:
        is_fully_addressable = False

    class FakeLeaf:
        def __init__(self, start, rows, tail=(3,)):
            self.sharding = FakeSharding()
            self.dtype = np.int32
            self.shape = (8,) + tail
            self.addressable_shards = [
                FakeShard(start, np.ones((rows,) + tail, np.int32))]

    out = sharded.unpad(FakeLeaf(6, 2), 5)     # rows [6, 8): all padding
    assert out.shape == (0, 3)
    mid = sharded.unpad(FakeLeaf(4, 2), 5)     # rows [4, 6): one valid
    assert mid.shape == (1, 3)


def test_host_stream_path_convention():
    assert egress.host_stream_path("/x/fleet.ndjson", 3) == \
        "/x/fleet.p3.ndjson"
    assert egress.host_stream_path("/x/fleet", 0) == "/x/fleet.p0.ndjson"


@pytest.mark.slow  # a third cluster launch + the serve executable's
# multi-process compile; the single-process serve referees (test_serve)
# and the 2-process fleet parities above cover the shared machinery.
def test_two_process_serve_smoke(tmp_path):
    """Multi-process resident service: 2 controllers submit identical
    requests, the fleet drains, and the union of per-host egressed
    results covers every request exactly once (per-host shard-local
    egress — each result lands only on its slot's owner)."""
    from fleet_shapes import FLEET_SCENARIO_SER_KW

    specs = [{"seed": s, "max_clock": 100} for s in (1, 2, 3)]
    results = bootstrap.local_cluster(
        2, "librabft_simulator_tpu.distributed.workers:serve_smoke",
        {"params_kw": dict(FLEET_SCENARIO_SER_KW, max_clock=100),
         "specs": specs, "slots": 4, "chunk": FLEET_CHUNK,
         "out_dir": str(tmp_path / "serve")},
        timeout_s=900, workdir=str(tmp_path / "cluster-serve"),
        env_extra=DIST_AOT)
    for res in results:
        assert res["pending"] == 0 and res["active"] == 0
    all_ids = sorted(results[0]["submitted"])
    local_sets = [set(r["egressed_local"]) for r in results]
    assert sorted(set().union(*local_sets)) == all_ids
    assert not (local_sets[0] & local_sets[1])  # disjoint ownership
