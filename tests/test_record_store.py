"""Mirrors /root/reference/librabft-v2/src/unit_tests/record_store_tests.rs
on the tensorized store (single-node slice, no vmap)."""

import jax.numpy as jnp
import numpy as np
import pytest

from librabft_simulator_tpu.core import config, store as store_ops
from librabft_simulator_tpu.core.types import ELECTION_WON, SimParams, Store


class SharedStore:
    """Test double for SharedRecordStore (record_store_tests.rs:8-104):
    one store written to by several authors."""

    def __init__(self, n=2, window=16):
        self.p = SimParams(n_nodes=n, window=window)
        self.w = jnp.ones((n,), jnp.int32)
        self.s = Store.initial(self.p)

    # -- helpers mirroring the Rust test harness --------------------------
    def propose(self, author, time, prev=None):
        prev_r, prev_t = prev if prev is not None else store_ops.hqc_ref(self.p, self.s)
        self.s, ok = store_ops.propose_block(
            self.p, self.s, self.w, author, prev_r, prev_t, time,
            cmd_index=int(time),
        )
        return bool(ok)

    def vote(self, author, var):
        self.s, ok = store_ops.create_vote(
            self.p, self.s, self.w, author, self.s.current_round, var
        )
        return bool(ok)

    def timeout(self, author, round_):
        self.s, ok = store_ops.create_timeout(self.p, self.s, self.w, author, round_)
        return bool(ok)

    def check_qc(self, author):
        self.s, created = store_ops.check_new_qc(self.p, self.s, self.w, author)
        return bool(created)

    def leader(self):
        return int(config.leader_of_round(self.w, self.s.current_round))

    def make_round(self, time):
        leader = self.leader()
        assert self.propose(leader, time)
        var = int(self.s.proposed_var)
        thresh = int(config.quorum_threshold(self.w))
        for a in range(thresh):
            assert self.vote(a, var)
        assert self.check_qc(leader)

    def make_tc(self):
        thresh = int(config.quorum_threshold(self.w))
        r = int(self.s.current_round)
        for a in range(thresh):
            self.timeout(a, r)

    # -- observations -----------------------------------------------------
    def n_blocks(self):
        return int(jnp.sum(self.s.blk_valid))

    def n_qcs(self):
        return int(jnp.sum(self.s.qc_valid))

    def n_timeouts(self):
        return int(jnp.sum(self.s.to_valid))

    def snapshot(self):
        s = self.s
        return dict(
            hqc_round=int(s.hqc_round), htc_round=int(s.htc_round),
            hcr=int(s.hcr), current_round=int(s.current_round),
        )


def test_initial_store():
    st = SharedStore(2)
    assert st.n_blocks() == 0 and st.n_qcs() == 0 and st.n_timeouts() == 0
    assert st.snapshot() == dict(hqc_round=0, htc_round=0, hcr=0, current_round=1)
    r, t = store_ops.hqc_ref(st.p, st.s)
    assert int(r) == 0 and int(t) == int(st.s.initial_tag)


def test_propose_and_vote_no_qc():
    st = SharedStore(2)
    assert st.propose(0, 1, prev=(jnp.int32(0), st.s.initial_tag))
    assert st.propose(1, 2, prev=(jnp.int32(0), st.s.initial_tag))
    assert st.n_blocks() == 2
    assert st.vote(0, 0)
    assert not st.vote(0, 0)  # one vote per author
    assert st.vote(1, 1)      # a vote for the *other* block
    leader = st.leader()
    assert not st.check_qc(leader)
    assert st.n_qcs() == 0
    assert st.snapshot() == dict(hqc_round=0, htc_round=0, hcr=0, current_round=1)


def test_vote_with_quorum():
    st = SharedStore(2)
    assert st.propose(0, 1)
    assert st.propose(1, 2)
    var = int(st.s.proposed_var)  # the legitimate leader's proposal
    assert var >= 0
    assert st.vote(0, var)
    assert st.vote(1, var)
    assert int(st.s.election) == ELECTION_WON
    assert st.check_qc(st.leader())
    assert st.n_blocks() == 2 and st.n_qcs() == 1
    assert st.snapshot() == dict(hqc_round=1, htc_round=0, hcr=0, current_round=2)


def test_timeouts_no_tc():
    st = SharedStore(2)
    assert st.propose(1, 2)
    assert st.timeout(0, 1)
    assert not st.timeout(0, 1)  # one timeout per author
    assert not st.timeout(1, 0)  # wrong round
    assert st.n_blocks() == 1 and st.n_qcs() == 0 and st.n_timeouts() == 1
    assert st.snapshot() == dict(hqc_round=0, htc_round=0, hcr=0, current_round=1)


def test_timeouts_with_tc():
    st = SharedStore(2)
    assert st.propose(1, 2)
    assert not st.timeout(1, 0)  # ignored: stale round
    assert st.timeout(0, 1)
    assert st.timeout(1, 1)      # completes the TC -> round 2
    assert st.timeout(1, 2)      # single timeout at the new round
    assert st.n_blocks() == 1 and st.n_qcs() == 0
    snap = st.snapshot()
    assert snap["htc_round"] == 1 and snap["current_round"] == 2
    assert st.n_timeouts() == 1
    assert st.timeout(0, 2)      # completes the next TC
    snap = st.snapshot()
    assert snap["htc_round"] == 2 and snap["current_round"] == 3
    assert st.n_timeouts() == 0


def test_non_contiguous_qcs():
    st = SharedStore(2)
    st.make_round(10)
    st.make_round(20)
    st.make_tc()
    st.make_round(40)
    assert st.n_blocks() == 3 and st.n_qcs() == 3
    assert st.snapshot() == dict(hqc_round=4, htc_round=3, hcr=0, current_round=5)
    assert st.n_timeouts() == 0


def test_commit_3chain():
    st = SharedStore(2)
    st.make_round(10)
    st.make_tc()
    st.make_round(30)
    st.make_round(40)
    st.make_round(50)
    st.make_tc()
    assert st.n_blocks() == 4 and st.n_qcs() == 4
    assert st.snapshot() == dict(hqc_round=5, htc_round=6, hcr=3, current_round=7)
    assert st.n_timeouts() == 0
    s = st.s
    assert bool(s.hcc_valid) and int(s.hcc_round) == 5
    # previous/second-previous rounds of the commit certificate's block
    # (record_store_tests.rs:258-277).
    sl = int(s.hcc_round) % st.p.window
    bvar = s.qc_blk_var[sl, int(s.hcc_var)]
    assert int(store_ops.previous_round(st.p, s, s.hcc_round, bvar)) == 4
    assert int(store_ops.second_previous_round(st.p, s, s.hcc_round, bvar)) == 3
    # committed_states_after(0) -> rounds [1, 3] (record_store_tests.rs:279-291).
    keep, rounds, depths, tags = store_ops.committed_states_after(st.p, s, 0)
    got = [(int(r), int(d)) for k, r, d in zip(np.asarray(keep), np.asarray(rounds),
                                               np.asarray(depths)) if k]
    assert [r for r, _ in got] == [1, 3]
    assert [d for _, d in got] == [1, 2]  # one command per block on the commit chain


def test_vote_committed_state_matches_commit_rule():
    st = SharedStore(2)
    st.make_round(10)
    st.make_round(20)
    # A QC on the round-3 proposal would form a 1-2-3 chain -> commits round 1.
    leader = st.leader()
    assert st.propose(leader, 30)
    var = int(st.s.proposed_var)
    ok, d, t, undet = store_ops.vote_committed_state(
        st.p, st.s, st.s.current_round, var)
    assert bool(ok) and int(d) == 1
    assert not bool(undet)  # no state-sync anchor in this store
    # After a TC gap, the chain is non-contiguous -> no commit.
    st.make_tc()
    leader = st.leader()
    assert st.propose(leader, 40)
    var = int(st.s.proposed_var)
    ok, _, _, _ = store_ops.vote_committed_state(
        st.p, st.s, st.s.current_round, var)
    assert not bool(ok)


def test_window_reuse_keeps_recent_rounds():
    st = SharedStore(2, window=8)
    for i in range(20):
        st.make_round(10 * (i + 1))
    # 20 rounds through a window of 8: old slots recycled, chain still commits.
    assert st.snapshot()["hcr"] == 18
    assert st.n_blocks() <= 8 * st.p.variants
