"""Resident fleet service referees (serve/: scenario plane + admission).

The three serving-semantics pins of PR 14:

(a) **Heterogeneous-fleet parity** — mixed delay kinds, mixed 2-/3-chain
    commit rules, and mixed Byzantine schedules in ONE scenario-armed
    batch are bit-identical PER SLOT to dedicated static batch-mode runs
    of each scenario, and match the oracle's counters/chains.
(b) **Admission isolation** — installing a new scenario into a halted
    slot mid-run leaves every live slot's trajectory bit-identical to an
    undisturbed run (halted slots are observably inert; the admission
    write is a pure masked select).
(c) **Resident poll contract** — the never-exiting service loop still
    fetches exactly one [13] digest per dispatched chunk (the
    monkeypatched-device_get proof, serving edition), and a serve session
    spanning >= 3 distinct scenario configs records exactly ONE sharded
    fleet-chunk compile entry — no per-scenario recompiles.

Engine-running tests are slow-marked (each pays micro-shape compiles on a
cold cache); scripts/ci_tier1.sh runs this module IN FULL as an explicit
referee leg, like tests/test_aot.py.  Shapes ride tests/fleet_shapes.py
so scripts/warm_cache.py pre-pays the heavy ones.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.oracle.sim import OracleSim
from librabft_simulator_tpu.parallel import mesh as mesh_ops
from librabft_simulator_tpu.parallel import sharded
from librabft_simulator_tpu.serve import scenario as sc
from librabft_simulator_tpu.serve import api as serve_api
from librabft_simulator_tpu.serve.service import ResidentFleet
from librabft_simulator_tpu.sim import parallel_sim as PS
from librabft_simulator_tpu.sim import simulator as S
from librabft_simulator_tpu.telemetry import ledger as tledger
from librabft_simulator_tpu.telemetry import stream as tstream

from fleet_shapes import (FLEET_CHUNK, FLEET_LANE_KW, FLEET_SER_KW,
                          SERVE_CHUNK, SERVE_DP, SERVE_SLOTS)

MAX_CLOCK = 300
P_BASE = SimParams(max_clock=MAX_CLOCK, **FLEET_SER_KW)
P_SC = dataclasses.replace(P_BASE, scenario=True)

#: The heterogeneous referee fleet: mixed delay kinds, mixed 2-/3-chain,
#: mixed Byzantine schedules — one scenario per slot, SERVE_SLOTS wide.
SPECS = [
    sc.ScenarioSpec(max_clock=MAX_CLOCK, seed=11),
    sc.ScenarioSpec(max_clock=MAX_CLOCK, delay_kind="uniform",
                    commit_chain=2, seed=22),
    sc.ScenarioSpec(max_clock=MAX_CLOCK, delay_kind="pareto",
                    delay_pareto_scale=2.0, delay_pareto_alpha=2.5,
                    drop_prob=0.05, seed=33),
    sc.ScenarioSpec(max_clock=MAX_CLOCK, byz_kind="equivocate", byz_f=1,
                    commit_chain=2, seed=44),
]
assert len(SPECS) == SERVE_SLOTS


def leaves_with_paths(st):
    return [(jax.tree_util.keystr(k), np.asarray(jax.device_get(v)))
            for k, v in jax.tree_util.tree_flatten_with_path(st)[0]]


def assert_slot_equal(ded_state, het_state, slot: int):
    """Every non-scenario leaf of the heterogeneous fleet's ``slot`` row
    must equal the dedicated run bit-for-bit."""
    ded = leaves_with_paths(ded_state)
    het = leaves_with_paths(het_state)
    assert len(ded) == len(het)
    for (ka, a), (kb, b) in zip(ded, het):
        if ".sc_delay" in ka or ".sc_commit" in ka:
            continue  # the plane rows themselves (zero-width on ded side)
        assert np.array_equal(a, b[slot]), f"slot {slot} leaf {ka} differs"


def dedicated_run(spec: sc.ScenarioSpec, base: SimParams, engine=S):
    """The static batch-mode reference: scenario plane OFF, this
    scenario's knobs as compile-time params."""
    p_i = spec.to_params(base)
    eq, silent, forge = spec.byz_masks(base)
    st = engine.init_state(p_i, spec.seed, byz_equivocate=eq,
                           byz_silent=silent, byz_forge_qc=forge)
    return p_i, engine.run_to_completion(p_i, st, chunk=FLEET_CHUNK)


# ---------------------------------------------------------------------------
# (a) heterogeneous-fleet parity.
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_heterogeneous_fleet_bit_identical_and_oracle_pinned():
    st = sc.init_specs(P_SC, SPECS)
    st = S.run_to_completion(P_SC, st, batched=True, chunk=FLEET_CHUNK)
    for i, spec in enumerate(SPECS):
        p_i, ded = dedicated_run(spec, P_BASE)
        assert_slot_equal(ded, st, i)
        # Oracle pin: the slot's counters and committed chains replay the
        # per-event reference semantics of exactly this scenario.
        eq, silent, forge = (np.asarray(m) for m in spec.byz_masks(P_BASE))
        orc = OracleSim(p_i, spec.seed, byz_equivocate=list(eq),
                        byz_silent=list(silent),
                        byz_forge_qc=list(forge)).run()
        assert int(jax.device_get(st.n_events)[i]) == orc.n_events
        H = int(st.ctx.log_depth.shape[-1])
        cc = np.asarray(jax.device_get(st.ctx.commit_count))[i]
        ld = np.asarray(jax.device_get(st.ctx.log_depth))[i]
        lt = np.asarray(jax.device_get(st.ctx.log_tag))[i]
        for a in range(p_i.n_nodes):
            chain = [(int(ld[a, j % H]), int(lt[a, j % H]))
                     for j in range(max(int(cc[a]) - H, 0), int(cc[a]))]
            assert chain == orc.committed_chain(a), (i, a)


@pytest.mark.slow
def test_heterogeneous_fleet_lane_engine():
    """The lane engine serves the same heterogeneous plane: per-slot
    bit-identity against dedicated lane runs (no inbox overflow at the
    micro shape, so window composition is trajectory-invariant)."""
    base = SimParams(max_clock=MAX_CLOCK, **FLEET_LANE_KW)
    p_sc = dataclasses.replace(base, scenario=True)
    specs = [
        sc.ScenarioSpec(max_clock=MAX_CLOCK, delay_kind="uniform", seed=5),
        sc.ScenarioSpec(max_clock=MAX_CLOCK, delay_kind="uniform",
                        commit_chain=2, seed=6),
        sc.ScenarioSpec(max_clock=MAX_CLOCK, delay_kind="constant",
                        delay_mean=7.0, byz_kind="silent", byz_f=1, seed=7),
        sc.ScenarioSpec(max_clock=MAX_CLOCK, delay_kind="uniform",
                        drop_prob=0.02, seed=8),
    ]
    st = sc.init_specs(p_sc, specs, engine=PS)
    st = PS.run_to_completion(p_sc, st, batched=True, chunk=FLEET_CHUNK)
    for i, spec in enumerate(specs):
        _, ded = dedicated_run(spec, base, engine=PS)
        assert_slot_equal(ded, st, i)


@pytest.mark.slow
def test_knob_default_plane_is_inert():
    """A scenario-armed fleet carrying knob-DEFAULT rows is bit-identical
    to the plain static engine — the census/R6 'plane off the hot path'
    claim, run dynamically."""
    seeds = [101, 102, 103, 104]
    rows = [sc.default_row(P_SC, s) for s in seeds]
    st = sc.init_rows(P_SC, sc.stack_rows(rows))
    st = S.run_to_completion(P_SC, st, batched=True, chunk=FLEET_CHUNK)
    for i, seed in enumerate(seeds):
        ded = S.run_to_completion(
            P_BASE, S.init_state(P_BASE, seed), chunk=FLEET_CHUNK)
        assert_slot_equal(ded, st, i)


# ---------------------------------------------------------------------------
# (b) admission isolation.
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_admission_leaves_live_slots_bit_identical():
    short = sc.ScenarioSpec(max_clock=40, seed=55)       # halts early
    specs = [SPECS[0], SPECS[1], short, SPECS[3]]
    run = S.make_run_fn(P_SC, FLEET_CHUNK, batched=True)

    def chunks(st, k):
        for _ in range(k):
            st = run(st)
        return st

    n1, n2 = 3, 8
    # Undisturbed reference: n1 + n2 chunks straight through.
    ref = chunks(S.dedupe_buffers(sc.init_specs(P_SC, specs)), n1 + n2)
    # Disturbed run: after n1 chunks the short slot has halted; admit a
    # NEW scenario into it and keep going.
    st = chunks(S.dedupe_buffers(sc.init_specs(P_SC, specs)), n1)
    halted = np.asarray(jax.device_get(st.halted))
    assert halted[2] and not halted[[0, 1, 3]].any()
    new_spec = sc.ScenarioSpec(max_clock=MAX_CLOCK, delay_kind="uniform",
                               commit_chain=2, seed=66)
    donor_row = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x)),
        sc.init_slot(P_SC, new_spec.plane_row(P_SC)))
    donor = jax.tree.map(
        lambda r: np.broadcast_to(r, (SERVE_SLOTS,) + r.shape).copy(),
        donor_row)
    mask = np.zeros((SERVE_SLOTS,), bool)
    mask[2] = True
    st = sc.install_rows(st, jnp.asarray(mask), donor)
    st = chunks(st, n2)
    # Live slots: bit-identical to the undisturbed run.
    ref_l = leaves_with_paths(ref)
    got_l = leaves_with_paths(st)
    for (ka, a), (_, b) in zip(ref_l, got_l):
        for slot in (0, 1, 3):
            assert np.array_equal(a[slot], b[slot]), \
                f"admission perturbed live slot {slot} leaf {ka}"
    # The admitted slot equals a fresh dedicated run of the new scenario
    # advanced the same n2 chunks (halted slots make extra chunks no-ops).
    p_new = new_spec.to_params(P_BASE)
    run_new = S.make_run_fn(p_new, FLEET_CHUNK, batched=False)
    ded_st = S.dedupe_buffers(S.init_state(p_new, new_spec.seed))
    for _ in range(n2):
        ded_st = run_new(ded_st)
    ded_l = leaves_with_paths(ded_st)
    for (ka, a), (_, b) in zip(ded_l, got_l):
        if ".sc_delay" in ka or ".sc_commit" in ka:
            continue
        assert np.array_equal(a, b[2]), f"admitted slot leaf {ka} differs"


# ---------------------------------------------------------------------------
# (c) the resident loop's poll + compile contracts.
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_resident_loop_digest_only_and_one_compile(monkeypatch, tmp_path):
    if len(jax.devices()) < SERVE_DP:
        pytest.skip("needs virtual devices (conftest sets 8)")
    mesh = mesh_ops.make_mesh(n_dp=SERVE_DP, n_mp=1,
                              devices=jax.devices()[:SERVE_DP])
    before = len([e for e in tledger.get().compiles
                  if str(e.get("engine", "")).startswith("sharded")])
    svc = ResidentFleet(P_BASE, slots=SERVE_SLOTS, mesh=mesh,
                        chunk=SERVE_CHUNK,
                        out=str(tmp_path / "serve.ndjson"))
    digest_fetches = []
    real_get = jax.device_get

    def spy(x):
        if np.shape(x) == (tstream.DIGEST_WIDTH,):
            digest_fetches.append(1)
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", spy)
    ids = [svc.submit(spec) for spec in SPECS[:3]]  # 3 distinct configs
    res = svc.drain()
    monkeypatch.undo()
    svc.close()
    # One [13] digest per dispatched chunk — no hidden plane polls.
    assert len(digest_fetches) == svc.chunks_polled > 0
    # Exactly ONE fleet-chunk compile entry across >= 3 admitted configs.
    entries = [e for e in tledger.get().compiles
               if str(e.get("engine", "")).startswith("sharded")]
    assert len(entries) - before == 1, \
        [e.get("structural") for e in entries]
    # Results exist, are tagged, and match their dedicated references.
    for rid, spec in zip(ids, SPECS[:3]):
        r = res[rid]
        assert r["request_id"] == rid and r["safe"] is True
        _, ded = dedicated_run(spec, P_BASE)
        assert r["events"] == int(jax.device_get(ded.n_events))
        assert r["commits"] == [int(c) for c in
                                np.asarray(jax.device_get(
                                    ded.ctx.commit_count))]
    # The NDJSON stream replays the lifecycle (fleet_watch --serve input).
    rows = [json.loads(line)
            for line in (tmp_path / "serve.ndjson").read_text().splitlines()]
    events = [r for r in rows if r.get("kind") == "request"]
    assert {e["event"] for e in events} >= {"submitted", "admitted",
                                            "first_chunk", "egressed"}
    egressed = [e for e in events if e["event"] == "egressed"]
    assert {e["id"] for e in egressed} == set(ids)
    assert all(e["ttfc_s"] is not None for e in egressed)


@pytest.mark.slow
def test_resident_ring_results_match_host_wrap(tmp_path):
    """Ring-depth serve knob: a fleet armed with ``ring_k`` (device wrap,
    admission/egress only at outer-call boundaries) drains to the same
    tagged results as the host-wrap reference, and the process ledger
    records the outer-call ring polls (retired/cap attrs) the admission
    -latency tradeoff is measured from."""
    from fleet_shapes import FLEET_RING_K
    if len(jax.devices()) < SERVE_DP:
        pytest.skip("needs virtual devices (conftest sets 8)")
    mesh = mesh_ops.make_mesh(n_dp=SERVE_DP, n_mp=1,
                              devices=jax.devices()[:SERVE_DP])
    specs = [SPECS[0], SPECS[2]]
    ref = ResidentFleet(P_BASE, slots=SERVE_SLOTS, mesh=mesh,
                        chunk=SERVE_CHUNK)
    for i, s in enumerate(specs):
        ref.submit(s, request_id=f"q{i}")
    ref_res = ref.drain()
    ref.close()
    svc = ResidentFleet(P_BASE, slots=SERVE_SLOTS, mesh=mesh,
                        chunk=SERVE_CHUNK, ring_k=FLEET_RING_K,
                        out=str(tmp_path / "ring.ndjson"))
    for i, s in enumerate(specs):
        svc.submit(s, request_id=f"q{i}")
    res = svc.drain()
    svc.close()
    assert set(res) == set(ref_res)
    for rid in res:
        for key in ("events", "clock", "commits", "safe"):
            assert res[rid][key] == ref_res[rid][key], (rid, key)
    ring = tledger.get().ring_stats()
    assert ring is not None and ring["dispatches"] >= 1
    assert ring["retired_chunks"] >= ring["dispatches"]


@pytest.mark.slow
def test_service_checkpoint_preemption_round_trip(tmp_path):
    """Preemption/eviction: a mid-flight service checkpoints, restores,
    and finishes with the same results as an uninterrupted one."""
    if len(jax.devices()) < SERVE_DP:
        pytest.skip("needs virtual devices (conftest sets 8)")
    mesh = mesh_ops.make_mesh(n_dp=SERVE_DP, n_mp=1,
                              devices=jax.devices()[:SERVE_DP])
    specs = [SPECS[0], SPECS[1]]
    ref = ResidentFleet(P_BASE, slots=SERVE_SLOTS, mesh=mesh,
                        chunk=SERVE_CHUNK)
    for i, s in enumerate(specs):
        ref.submit(s, request_id=f"q{i}")
    ref_res = ref.drain()
    svc = ResidentFleet(P_BASE, slots=SERVE_SLOTS, mesh=mesh,
                        chunk=SERVE_CHUNK)
    for i, s in enumerate(specs):
        svc.submit(s, request_id=f"q{i}")
    svc.serve(max_chunks=3)  # partially served, then preempted
    ck = str(tmp_path / "svc.npz")
    svc.save(ck)
    svc.close()
    resumed = ResidentFleet.restore(ck, P_BASE, mesh=mesh)
    res = resumed.drain()
    resumed.close()
    assert set(res) == {"q0", "q1"}
    for rid in res:
        for key in ("events", "clock", "commits", "safe"):
            assert res[rid][key] == ref_res[rid][key], (rid, key)


# ---------------------------------------------------------------------------
# Host-side units (fast; run inside the 870 s suite too).
# ---------------------------------------------------------------------------


def test_spec_validation_and_round_trip():
    spec = sc.ScenarioSpec(delay_kind="pareto", commit_chain=2,
                           byz_kind="silent", byz_f=1, seed=9)
    assert sc.ScenarioSpec.from_dict(spec.to_dict()) == spec
    with pytest.raises(ValueError, match="unknown scenario field"):
        sc.ScenarioSpec.from_dict({"delay_knid": "uniform"})
    with pytest.raises(ValueError, match="Byzantine schedule"):
        sc.ScenarioSpec(byz_kind="omission")
    with pytest.raises(ValueError, match="commit_chain"):
        sc.ScenarioSpec(commit_chain=4)
    # The dedicated-run projection carries every scenario knob.
    p_i = spec.to_params(P_BASE)
    assert (p_i.delay_kind, p_i.commit_chain) == ("pareto", 2)
    assert not p_i.scenario


def test_structural_key_coarsens_under_scenario():
    """The executable-count collapse, stated on the key itself: scenario
    params differing in every per-slot knob share one structural key."""
    a = dataclasses.replace(
        P_SC, delay_kind="pareto", drop_prob=0.2, commit_chain=2,
        max_clock=77)
    b = dataclasses.replace(
        P_SC, delay_kind="constant", delay_mean=3.0, commit_chain=3)
    assert a.structural() == b.structural() == P_SC.structural()
    # Scenario OFF keeps commit_chain structural (the static family).
    off2 = dataclasses.replace(P_BASE, commit_chain=2)
    assert off2.structural() != P_BASE.structural()


def test_scenario_params_guard():
    with pytest.raises(ValueError, match="scenario=True"):
        sc.init_rows(P_BASE, sc.stack_rows([sc.default_row(P_BASE, 0)]))


def test_load_requests_ndjson(tmp_path):
    path = tmp_path / "req.ndjson"
    path.write_text(
        '{"id": "a", "delay_kind": "uniform", "commit_chain": 2}\n'
        "# comment\n"
        '{"seed": 3}\n')
    reqs = serve_api.load_requests(str(path))
    assert [rid for rid, _ in reqs] == ["a", "3"]
    assert reqs[0][1].commit_chain == 2
    bad = tmp_path / "bad.ndjson"
    bad.write_text('{"delay_knid": "x"}\n')
    with pytest.raises(ValueError, match="bad.ndjson:1"):
        serve_api.load_requests(str(bad))
    empty = tmp_path / "empty.ndjson"
    empty.write_text("# nothing\n")
    with pytest.raises(ValueError, match="no requests"):
        serve_api.load_requests(str(empty))


def test_schedule_registry():
    from librabft_simulator_tpu.sim import byzantine

    eq, silent, forge = byzantine.schedule_masks(P_BASE, "honest", 2)
    assert not (np.asarray(eq).any() or np.asarray(silent).any()
                or np.asarray(forge).any())
    eq, silent, forge = byzantine.schedule_masks(P_BASE, "silent", 1)
    assert np.asarray(silent).sum() == 1 and not np.asarray(eq).any()
    with pytest.raises(ValueError, match="unknown Byzantine schedule"):
        byzantine.schedule_masks(P_BASE, "nope")
