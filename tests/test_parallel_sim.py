"""Tests for the conservative-window parallel engine (sim/parallel_sim.py).

The serial engine is the oracle-parity reference; this engine is the
throughput mode.  Its correctness story is tested here directly:

* bit-exact determinism for a seed;
* window-composition invariance: running with a *narrower* conservative
  lookahead (d_min=1) must give bit-identical final states — the
  Chandy-Misra argument says window width only affects how much work lands
  in each step, never the per-node trajectories;
* statistical agreement with the serial engine on matched configs
  (events and commits per unit of *virtual time*; wall-clock and stamp
  interleavings legitimately differ);
* safety under Byzantine equivocation/silence masks;
* inbox-overflow accounting under an artificially tiny inbox.
"""

import jax
import numpy as np
import pytest

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import parallel_sim as P
from librabft_simulator_tpu.sim import simulator as S
from librabft_simulator_tpu.sim.byzantine import byz_masks, check_safety
from librabft_simulator_tpu.sim.simulator import dedupe_buffers

g = jax.device_get


def _small_kw(**kw):
    kw.setdefault("n_nodes", 4)
    kw.setdefault("delay_kind", "uniform")
    kw.setdefault("max_clock", 1500)
    kw.setdefault("window", 8)
    kw.setdefault("chain_k", 2)
    kw.setdefault("commit_log", 16)
    return kw


def small_params(**kw):
    return SimParams(**_small_kw(**kw))


def run_parallel(p, seeds, chunk=256, max_chunks=120, d_min=None, **init_kw):
    if init_kw:
        st = jax.vmap(lambda s: P.init_state(p, s, **init_kw))(
            np.asarray(seeds, np.uint32))
    else:
        st = P.init_batch(p, seeds)
    st = dedupe_buffers(st)
    run = P.make_run_fn(p, chunk, d_min=d_min)
    for _ in range(max_chunks):
        st = run(st)
        if bool(np.all(g(st.halted))):
            break
    assert bool(np.all(g(st.halted))), "parallel run did not halt"
    return st


def state_fingerprint(st):
    """Deterministic summary tuple of the protocol-visible final state."""
    return (
        np.asarray(g(st.store.current_round)),
        np.asarray(g(st.ctx.commit_count)),
        np.asarray(g(st.ctx.last_depth)),
        np.asarray(g(st.ctx.last_tag)),
        np.asarray(g(st.ctx.log_tag)),
        np.asarray(g(st.n_events)),
        np.asarray(g(st.n_msgs_sent)),
        np.asarray(g(st.n_inbox_full)),
    )


def assert_same_state(a, b):
    for x, y in zip(state_fingerprint(a), state_fingerprint(b)):
        np.testing.assert_array_equal(x, y)


def test_determinism_same_seed():
    p = small_params()
    seeds = np.arange(6, dtype=np.uint32)
    st1 = run_parallel(p, seeds)
    st2 = run_parallel(p, seeds)
    assert_same_state(st1, st2)
    assert int(np.sum(g(st1.ctx.commit_count))) > 0


def test_window_composition_invariance():
    """d_min=1 (narrowest conservative windows) == native d_min, bit-exact."""
    p = small_params()
    seeds = np.arange(4, dtype=np.uint32)
    assert P.d_min_of(p) > 1, "uniform table should have min latency > 1"
    st_wide = run_parallel(p, seeds)
    st_narrow = run_parallel(p, seeds, d_min=1, max_chunks=240)
    assert_same_state(st_wide, st_narrow)


def test_lane_drain_composition_invariance():
    """Lane count and drain depth only reshape windows: A=1/K=1 (strictly
    serial schedule), A=2/K=3, and narrow-lookahead hybrids must all be
    bit-identical to the auto shape.  This is the regression test for the
    per-node-horizon unsoundness (two-hop feedback: a node's own in-window
    send can cause a reply that lands before its wider per-node horizon)."""
    p = small_params()
    seeds = np.arange(4, dtype=np.uint32)
    ref = run_parallel(p, seeds)
    for kw, dm in [
        (dict(active_lanes=1, drain_k=1), None),
        (dict(active_lanes=2, drain_k=3), None),
        (dict(active_lanes=1, drain_k=2), 1),
    ]:
        st = run_parallel(SimParams(**{**_small_kw(), **kw}), seeds, d_min=dm,
                          max_chunks=400)
        assert_same_state(ref, st)


def test_statistical_agreement_with_serial():
    """Same config, same virtual horizon: event/commit density per unit of
    virtual time must agree between engines (they are different stamp
    interleavings of the same protocol + delay distribution)."""
    p = small_params(max_clock=2500)
    seeds = np.arange(24, dtype=np.uint32)
    stp = run_parallel(p, seeds)
    sts = S.run_to_completion(p, S.init_batch(p, seeds), batched=True,
                              chunk=256, max_chunks=80)
    assert bool(np.all(g(sts.halted)))
    # Zero-loss fidelity on both sides makes the comparison meaningful.
    assert int(np.sum(g(stp.n_inbox_full))) == 0
    assert int(np.sum(g(sts.n_queue_full))) == 0
    T = p.max_clock * len(seeds)
    for name, field in [("events", "n_events"), ("msgs", "n_msgs_sent")]:
        dp = float(np.sum(g(getattr(stp, field)))) / T
        ds = float(np.sum(g(getattr(sts, field)))) / T
        assert dp == pytest.approx(ds, rel=0.15), (name, dp, ds)
    cp = float(np.sum(g(stp.ctx.commit_count))) / T
    cs = float(np.sum(g(sts.ctx.commit_count))) / T
    assert cp == pytest.approx(cs, rel=0.15), ("commits", cp, cs)
    assert cp > 0


@pytest.mark.parametrize("kind", ["equivocate", "silent"])
def test_byzantine_safety(kind):
    """f=1 faulty author at n=4: honest nodes never commit conflicting
    states; honest liveness holds for equivocation."""
    p = small_params(max_clock=2000)
    eq, silent, forge = byz_masks(p, 1, kind)
    seeds = np.arange(8, dtype=np.uint32)
    st = run_parallel(p, seeds, byz_equivocate=eq, byz_silent=silent,
                      byz_forge_qc=forge)
    honest = np.arange(p.n_nodes) >= 1
    assert bool(np.all(check_safety(st, honest)))
    cc = np.asarray(g(st.ctx.commit_count))[:, honest]
    if kind == "equivocate":
        assert cc.max() > 0


def test_inbox_overflow_accounted_and_safe():
    """A 6-slot inbox at n=4 must overflow under broadcast load; the engine
    counts the loss, stays safe, and still halts."""
    p = small_params(inbox_cap=6, max_clock=1200)
    seeds = np.arange(6, dtype=np.uint32)
    st = run_parallel(p, seeds, max_chunks=120)
    assert int(np.sum(g(st.n_inbox_full))) > 0
    assert bool(np.all(check_safety(st)))


def test_inbox_cap_param_respected():
    p = small_params(inbox_cap=6)
    assert P.inbox_cap(p) == 6
    assert P.inbox_cap(small_params()) == 16


def test_lane_engine_refuses_macro_k():
    """SimParams.macro_k is a serial-engine knob (the lane engine's
    horizon windows already batch events per dispatch) — a macro-armed
    lane run must fail loud at make-time, never silently bench K=1."""
    p = small_params(macro_k=2)
    with pytest.raises(ValueError, match="serial-engine knob"):
        P.make_run_fn(p, 4)
    with pytest.raises(ValueError, match="serial-engine knob"):
        P.make_scan_fn(p, 4)
