"""Adversary-engine referees (adversary/: attack-schedule + network
planes, DSL, serve integration).

The four load-bearing pins of the subsystem:

(a) **Off/inert identity** — the adversary plane OFF is bit-identical to
    the pre-plane engines on every shared leaf, and an ARMED plane
    carrying the inert (all-zero) program is bit-identical to OFF — on
    BOTH engines.  (Kernel identity of the off graph is the census
    budget gate; the graph audit's R6 adversary arm is the static twin.)
(b) **Static-mask reproduction** — an always-on window reproducing the
    legacy ``byz_masks`` schedule is bit-identical to the static-mask
    run: serial, lane, and a 2-shard sharded leg.
(c) **Oracle parity under attack** — windowed equivocation, targeted
    silence, partition-with-heal, leader-targeted delay, and per-link
    matrices replay bit-exactly against ``OracleSim(attack=...)``.
(d) **Per-link lane horizon** — the derived lookahead is pinned >= the
    global bound (strictly tighter on an asymmetric matrix) and the
    protocol-visible trajectory is invariant across window compositions
    under it (the soundness referee).

Engine-running tests are slow-marked (micro-shape compiles);
scripts/ci_tier1.sh runs this module IN FULL as an explicit referee leg.
Shapes ride tests/fleet_shapes.py so scripts/warm_cache.py pre-pays them.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from librabft_simulator_tpu.adversary import dsl, plane as aplane
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.oracle.sim import OracleSim
from librabft_simulator_tpu.serve import scenario as sc
from librabft_simulator_tpu.sim import byzantine
from librabft_simulator_tpu.sim import checkpoint as ckpt
from librabft_simulator_tpu.sim import parallel_sim as PS
from librabft_simulator_tpu.sim import simulator as S

from fleet_shapes import (FLEET_ADV_LANE_KW, FLEET_ADV_SER_KW,
                          FLEET_ADV_SERVE_KW, FLEET_CHUNK, FLEET_LANE_KW,
                          SERVE_CHUNK, SERVE_DP, SERVE_SLOTS)

MAX_CLOCK = 300
#: The 4-node OFF twin both engines' identity referees compare against
#: (the adversary shapes are FLEET_LANE_KW + the armed plane).
P_OFF = SimParams(max_clock=MAX_CLOCK, **FLEET_LANE_KW)
P_ADV_SER = SimParams(max_clock=MAX_CLOCK, **FLEET_ADV_SER_KW)
P_ADV_LANE = SimParams(max_clock=MAX_CLOCK, **FLEET_ADV_LANE_KW)


def leaves(st):
    return {jax.tree_util.keystr(k): np.asarray(jax.device_get(v))
            for k, v in jax.tree_util.tree_flatten_with_path(st)[0]}


def assert_equal_leaves(a, b, skip=(".adv_",), what=""):
    """Bit-identity over every leaf whose path contains none of ``skip``
    (the plane leaves themselves are zero-width on the off side)."""
    la, lb = leaves(a), leaves(b)
    for k, v in la.items():
        if any(s in k for s in skip):
            continue
        assert np.array_equal(v, lb[k]), f"{what} leaf {k} differs"


def oracle_pin(p, st, orc):
    """The protocol-counter + committed-chain subset of the fuzz
    invariants between one (unbatched, host) engine state and an oracle."""
    assert int(st.n_events) == orc.n_events
    assert int(st.clock) == orc.clock
    assert int(st.stamp_ctr) == orc.stamp_ctr
    assert int(st.n_msgs_sent) == orc.n_msgs_sent
    assert int(st.n_msgs_dropped) == orc.n_msgs_dropped
    H = int(st.ctx.log_depth.shape[-1])
    for a in range(p.n_nodes):
        cc = int(st.ctx.commit_count[a])
        chain = [(int(st.ctx.log_depth[a, i % H]),
                  int(st.ctx.log_tag[a, i % H]))
                 for i in range(max(cc - H, 0), cc)]
        assert chain == orc.committed_chain(a), a


# ---------------------------------------------------------------------------
# (a) off/inert identity.
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("eng,p_off,p_adv", [
    (S, P_OFF, P_ADV_SER), (PS, P_OFF, P_ADV_LANE)],
    ids=["serial", "lane"])
def test_inert_plane_identity(eng, p_off, p_adv):
    """Armed-but-quiet plane == plane off, bit-identical on both engines
    (the dynamic twin of the R6 off-inert arm + census gates)."""
    st_off = eng.run_to_completion(p_off, eng.init_state(p_off, 7),
                                   chunk=FLEET_CHUNK)
    st_adv = eng.run_to_completion(p_adv, eng.init_state(p_adv, 7),
                                   chunk=FLEET_CHUNK)
    assert int(st_off.n_events) > 0
    assert_equal_leaves(st_off, st_adv, what="inert-plane")


# ---------------------------------------------------------------------------
# (b) static-mask reproduction (serial + lane + 2-shard sharded leg).
# ---------------------------------------------------------------------------

#: An always-on silent window on node 0 — the legacy byz_masks(f=1,
#: "silent") schedule expressed as an attack program.
SILENT_0 = dsl.AttackProgram(
    windows=(dsl.Window(behavior="silent", targets=(0,)),))


@pytest.mark.slow
@pytest.mark.parametrize("eng,p_off,p_adv", [
    (S, P_OFF, P_ADV_SER), (PS, P_OFF, P_ADV_LANE)],
    ids=["serial", "lane"])
def test_static_mask_window_reproduction(eng, p_off, p_adv):
    st_w = SILENT_0.install(p_adv, eng.init_state(p_adv, 7))
    st_w = eng.run_to_completion(p_adv, st_w, chunk=FLEET_CHUNK)
    _, sil, _ = byzantine.byz_masks(p_off, 1, "silent")
    st_m = eng.run_to_completion(
        p_off, eng.init_state(p_off, 7, byz_silent=sil), chunk=FLEET_CHUNK)
    assert_equal_leaves(st_m, st_w, skip=(".adv_", ".byz_"),
                        what="static-mask window")


@pytest.mark.slow
def test_static_mask_window_sharded_2dp():
    """The sharded leg: a 2-shard adversary fleet running the windowed
    schedule is leaf-bit-identical to the unsharded legacy static-mask
    fleet."""
    from librabft_simulator_tpu.parallel import mesh as mesh_ops
    from librabft_simulator_tpu.parallel import sharded

    if len(jax.devices()) < 2:
        pytest.skip("needs virtual devices (conftest sets 8)")
    mesh = mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])
    seeds = sharded.fleet_seeds(0xAD, 4)
    st0 = jax.vmap(lambda s: SILENT_0.install(
        P_ADV_SER, S.init_state(P_ADV_SER, s)))(jnp.asarray(seeds))
    st_sh = sharded.run_sharded(P_ADV_SER, mesh, st0, num_steps=4096,
                                chunk=FLEET_CHUNK)
    _, sil, _ = byzantine.byz_masks(P_OFF, 1, "silent")
    st_ref = jax.vmap(lambda s: S.init_state(P_OFF, s, byz_silent=sil))(
        jnp.asarray(seeds))
    st_ref = S.run_to_completion(P_OFF, st_ref, batched=True,
                                 chunk=FLEET_CHUNK)
    assert np.all(np.asarray(jax.device_get(st_sh.halted)))
    assert_equal_leaves(st_ref, st_sh, skip=(".adv_", ".byz_"),
                        what="sharded windowed")


# ---------------------------------------------------------------------------
# (c) oracle parity under composed attacks.
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_windowed_attack_oracle_parity():
    """Windowed equivocation + leader-targeted delay + asymmetric link
    matrix + partition-with-heal, all at once, vs the oracle mirror."""
    prog = dsl.AttackProgram(
        windows=(dsl.Window(behavior="equivocate", start=50, end=200,
                            targets=(1,)),
                 dsl.Window(behavior="delay_leader", start=0, end=250,
                            arg=15)),
        partition=dsl.Partition(groups=((0, 1), (2, 3)), heal=120),
        link_delay=((0, 2, 3, 4), (1, 0, 1, 1), (2, 2, 0, 2),
                    (5, 1, 1, 0)))
    st = prog.install(P_ADV_SER, S.init_state(P_ADV_SER, 11))
    st = S.run_to_completion(P_ADV_SER, st, chunk=FLEET_CHUNK)
    orc = OracleSim(P_ADV_SER, 11, attack=prog).run()
    oracle_pin(P_ADV_SER, st, orc)
    # The partition actually cut traffic (drops >> the 0-drop-prob base).
    assert orc.n_msgs_dropped > 0
    # Safety holds for the honest remainder (node 1 is the equivocator).
    honest = ~np.isin(np.arange(4), sorted(dsl.byz_targets(prog)))
    st1 = jax.tree.map(lambda x: np.asarray(x)[None], st)
    assert byzantine.check_safety_reference(st1, honest_mask=honest)[0]


@pytest.mark.slow
def test_targeted_silence_window_heals():
    """A TIME-bounded silence window: the target is mute inside the
    window and resumes after — liveness recovers (commits land past the
    window), and the trajectory pins against the oracle."""
    prog = dsl.AttackProgram(
        windows=(dsl.Window(behavior="silent", start=0, end=150,
                            targets=(0,)),))
    st = prog.install(P_ADV_SER, S.init_state(P_ADV_SER, 23))
    st = S.run_to_completion(P_ADV_SER, st, chunk=FLEET_CHUNK)
    orc = OracleSim(P_ADV_SER, 23, attack=prog).run()
    oracle_pin(P_ADV_SER, st, orc)
    # The silenced node recovers: it sends again after the window.
    assert int(st.n_msgs_sent) > 0
    assert int(np.sum(np.asarray(st.ctx.commit_count))) > 0


@pytest.mark.slow
def test_epoch_window_and_event_window_oracle_parity():
    """MODE_EPOCH and MODE_EVENTS bounds on the serial engine (the
    per-event reference for event-count windows)."""
    p = dataclasses.replace(P_ADV_SER, commands_per_epoch=6)
    prog = dsl.AttackProgram(windows=(
        dsl.Window(behavior="forge_qc", mode="epoch", start=1, end=2,
                   targets=(2,)),
        dsl.Window(behavior="delay", mode="events", start=40, end=160,
                   targets=(0, 3), arg=11),
    ))
    st = prog.install(p, S.init_state(p, 31))
    st = S.run_to_completion(p, st, chunk=FLEET_CHUNK)
    orc = OracleSim(p, 31, attack=prog).run()
    oracle_pin(p, st, orc)


# ---------------------------------------------------------------------------
# (d) per-link lane horizon.
# ---------------------------------------------------------------------------

ASYM_LINK = ((0, 3, 4, 5), (3, 0, 3, 6), (7, 3, 0, 3), (4, 5, 3, 0))


def test_link_lookahead_bounds():
    """The derived lookahead: >= the global bound always, strictly
    tighter on an asymmetric all-positive matrix, identity on zeros."""
    n = 4
    zero = jnp.zeros((n, n), jnp.int32)
    assert int(aplane.link_lookahead(zero, n)) == 0
    asym = jnp.asarray(np.array(ASYM_LINK, np.int32))
    # min off-diagonal = 3: the horizon gains exactly the guaranteed
    # minimum extra latency of ANY live link.
    assert int(aplane.link_lookahead(asym, n)) == 3
    # Negative entries clamp to 0 (never loosen below the table bound).
    assert int(aplane.link_lookahead(jnp.full((n, n), -5, jnp.int32),
                                     n)) == 0


@pytest.mark.slow
def test_per_link_horizon_composition_invariance():
    """The soundness referee: under an asymmetric link matrix (derived
    horizon = global + 3) the protocol-visible state is bit-identical
    across lane/drain window shapes — a horizon bug would break this."""
    prog = dsl.AttackProgram(
        windows=(dsl.Window(behavior="delay", start=40, end=200,
                            targets=(2,), arg=9),),
        link_delay=ASYM_LINK)

    def fingerprint(p_i):
        st = prog.install(p_i, PS.init_state(p_i, 13))
        st = PS.run_to_completion(p_i, st, chunk=FLEET_CHUNK)
        return (np.asarray(st.store.current_round),
                np.asarray(st.ctx.commit_count),
                np.asarray(st.ctx.last_depth),
                np.asarray(st.ctx.last_tag),
                np.asarray(st.ctx.log_tag),
                np.asarray(st.n_events),
                np.asarray(st.n_msgs_sent),
                np.asarray(st.n_msgs_dropped),
                np.asarray(st.n_inbox_full))
    ref = fingerprint(dataclasses.replace(P_ADV_LANE, active_lanes=2,
                                          drain_k=2))
    got = fingerprint(dataclasses.replace(P_ADV_LANE, active_lanes=4,
                                          drain_k=8))
    for x, y in zip(ref, got):
        np.testing.assert_array_equal(x, y)


# ---------------------------------------------------------------------------
# Serve integration: attacks as admissible requests.
# ---------------------------------------------------------------------------

ATTACKS = [
    # >= 4 distinct program families (the acceptance set).
    {"windows": [{"behavior": "equivocate", "start": 40, "end": 180,
                  "targets": [0]}]},
    {"windows": [{"behavior": "silent", "start": 0, "end": 120,
                  "targets": [1]}]},
    {"partition": {"groups": [[0, 1], [2, 3]], "heal": 100}},
    {"windows": [{"behavior": "delay_leader", "start": 0, "end": 250,
                  "arg": 20}]},
    # Second wave: composed + link-matrix programs.
    {"windows": [{"behavior": "forge_qc", "start": 60, "end": 200,
                  "targets": [2]}],
     "link_delay": [[0, 2, 2, 2], [1, 0, 1, 1], [3, 3, 0, 3],
                    [2, 2, 2, 0]]},
    {"windows": [{"behavior": "delay", "start": 30, "end": 220,
                  "targets": [0, 3], "arg": 12}]},
]


@pytest.mark.slow
def test_adversarial_fleet_bit_identical_per_slot():
    """Heterogeneous ATTACK fleet on one scenario+adversary executable:
    each slot bit-identical to its dedicated single-scenario run."""
    base = SimParams(max_clock=MAX_CLOCK, **FLEET_ADV_SERVE_KW)
    p_sc = base  # scenario already armed in the serve shape
    specs = [sc.ScenarioSpec(max_clock=MAX_CLOCK, seed=100 + i, attack=atk)
             for i, atk in enumerate(ATTACKS[:SERVE_SLOTS])]
    st = sc.init_specs(p_sc, specs)
    st = S.run_to_completion(p_sc, st, batched=True, chunk=SERVE_CHUNK)
    for i, spec in enumerate(specs):
        p_i = spec.to_params(base)
        prog = spec.attack_program()
        ded = prog.install(p_i, S.init_state(p_i, spec.seed))
        ded = S.run_to_completion(p_i, ded, chunk=SERVE_CHUNK)
        ded_l, het_l = leaves(ded), leaves(st)
        for k, v in ded_l.items():
            if ".sc_delay" in k or ".sc_commit" in k:
                continue
            assert np.array_equal(v, het_l[k][i]), f"slot {i} leaf {k}"


@pytest.mark.slow
def test_resident_fleet_admits_attacks_one_compile(tmp_path):
    """The acceptance scenario: >= 4 distinct attack programs over >= 2
    waves on ONE resident executable (exactly 1 sharded compile entry),
    every request refereed by the in-graph watchdog trip counts."""
    from librabft_simulator_tpu.parallel import mesh as mesh_ops
    from librabft_simulator_tpu.serve.service import ResidentFleet
    from librabft_simulator_tpu.telemetry import ledger as tledger

    if len(jax.devices()) < SERVE_DP:
        pytest.skip("needs virtual devices (conftest sets 8)")
    base = SimParams(max_clock=MAX_CLOCK, **FLEET_ADV_SERVE_KW)
    mesh = mesh_ops.make_mesh(n_dp=SERVE_DP, n_mp=1,
                              devices=jax.devices()[:SERVE_DP])
    before = len([e for e in tledger.get().compiles
                  if str(e.get("engine", "")).startswith("sharded")])
    svc = ResidentFleet(base, slots=SERVE_SLOTS, mesh=mesh,
                        chunk=SERVE_CHUNK,
                        out=str(tmp_path / "serve.ndjson"))
    # Two waves: 6 attack requests into 4 slots.
    ids = [svc.submit(sc.ScenarioSpec(max_clock=MAX_CLOCK, seed=200 + i,
                                      attack=atk))
           for i, atk in enumerate(ATTACKS)]
    res = svc.drain()
    svc.close()
    entries = [e for e in tledger.get().compiles
               if str(e.get("engine", "")).startswith("sharded")]
    assert len(entries) - before == 1, \
        [e.get("structural") for e in entries]
    assert set(res) == set(ids)
    for i, rid in enumerate(ids):
        r = res[rid]
        # Per-request watchdog referee: verdict present, attacks modeled
        # here cannot break safety (f <= (n-1)/3 Byzantine targets).
        assert r["watchdog"]["safety_ok"] is True, r["watchdog"]
        assert r["safe"] is True
        assert r["attack"]["windows"] is not None
        # Each slot's summary equals its dedicated single-scenario run.
        spec = sc.ScenarioSpec(max_clock=MAX_CLOCK, seed=200 + i,
                               attack=ATTACKS[i])
        p_i = spec.to_params(base)
        ded = spec.attack_program().install(
            p_i, S.init_state(p_i, spec.seed))
        ded = S.run_to_completion(p_i, ded, chunk=SERVE_CHUNK)
        assert r["events"] == int(jax.device_get(ded.n_events)), rid
        assert r["commits"] == [int(c) for c in np.asarray(
            jax.device_get(ded.ctx.commit_count))], rid


# ---------------------------------------------------------------------------
# Host-side units (fast; run inside the 870 s suite too).
# ---------------------------------------------------------------------------


def test_dsl_validation():
    with pytest.raises(ValueError, match="unknown behavior"):
        dsl.Window(behavior="omission")
    with pytest.raises(ValueError, match="unknown window mode"):
        dsl.Window(behavior="silent", mode="rounds")
    with pytest.raises(ValueError, match="bounds"):
        dsl.Window(behavior="silent", start=10, end=5)
    with pytest.raises(ValueError, match="arg"):
        dsl.Window(behavior="delay", arg=-1)
    with pytest.raises(ValueError, match="target 9"):
        dsl.AttackProgram(
            windows=(dsl.Window(behavior="silent", targets=(9,)),)
        ).validate(P_ADV_SER)
    with pytest.raises(ValueError, match="adversary=True"):
        SILENT_0.validate(P_OFF)
    with pytest.raises(ValueError, match="exceed the plane capacity"):
        dsl.AttackProgram(windows=tuple(
            dsl.Window(behavior="silent", targets=(0,))
            for _ in range(P_ADV_SER.adv_windows + 1))).validate(P_ADV_SER)
    with pytest.raises(ValueError, match="two partition groups"):
        dsl.Partition(groups=((0, 1), (1, 2)))
    with pytest.raises(ValueError, match="4x4"):
        dsl.AttackProgram(link_delay=((0, 1), (1, 0))).validate(P_ADV_SER)
    with pytest.raises(ValueError, match="link delay"):
        dsl.AttackProgram(link_delay=tuple(
            tuple(aplane.DELAY_CAP + 1 for _ in range(4))
            for _ in range(4))).validate(P_ADV_SER)


def test_dsl_round_trip_and_unknown_fields():
    prog = dsl.AttackProgram.from_dict(ATTACKS[4])
    assert dsl.AttackProgram.from_dict(prog.to_dict()) == prog
    with pytest.raises(ValueError, match="unknown attack field"):
        dsl.AttackProgram.from_dict({"window": []})
    with pytest.raises(ValueError, match="unknown field"):
        dsl.AttackProgram.from_dict(
            {"windows": [{"behavior": "silent", "targett": [0]}]})
    # ScenarioSpec grammar-checks the attack at construction.
    with pytest.raises(ValueError, match="unknown attack field"):
        sc.ScenarioSpec(attack={"windoes": []})
    spec = sc.ScenarioSpec(attack=ATTACKS[0])
    assert sc.ScenarioSpec.from_dict(spec.to_dict()) == spec
    # An attack on an unarmed base fails loud at lowering time.
    with pytest.raises(ValueError, match="adversary=False"):
        spec.plane_row(dataclasses.replace(P_OFF, scenario=True))


def test_dsl_sweep_grid():
    progs = list(dsl.sweep(
        P_ADV_SER, behaviors=("equivocate", "silent"), starts=(0, 100),
        durations=(50,), targets=((0,), (1,))))
    assert len(progs) == 8
    assert len({repr(p) for p in progs}) == 8
    for p in progs:
        rows = p.lower(P_ADV_SER)
        assert rows["adv_sched"].shape == (P_ADV_SER.adv_windows, 7)
    # Seedable random programs are deterministic per seed.
    import random
    a = dsl.sample_program(P_ADV_SER, random.Random(5))
    b = dsl.sample_program(P_ADV_SER, random.Random(5))
    assert a == b


def test_plane_decode_units():
    """Host/device decode agreement on a hand-built schedule."""
    p = P_ADV_SER
    prog = dsl.AttackProgram(windows=(
        dsl.Window(behavior="silent", start=10, end=20, targets=(1, 2)),
        dsl.Window(behavior="delay", mode="events", start=5, end=50,
                   targets=(0,), arg=7),
    ))
    rows = prog.lower(p)
    hp = prog.host_plane(p)
    sched = jnp.asarray(rows["adv_sched"])
    for (t, ev, ep, node) in [(15, 6, 0, 1), (15, 6, 0, 0), (25, 6, 0, 2),
                              (10, 4, 0, 2), (19, 60, 1, 1)]:
        act = aplane.active_windows(sched, t, ev, ep)
        dev = tuple(bool(x) for x in aplane.node_masks(sched, act, node))
        assert dev == hp.node_masks(t, ev, ep, node), (t, ev, ep, node)
        dev_extra = int(aplane.delay_extra(
            sched, act, jnp.asarray([node]), jnp.asarray(3))[0])
        assert dev_extra == hp.delay_extra(t, ev, ep, node, 3)
    # describe(): the decoded-program record minidumps/results carry.
    d = hp.describe()
    assert d["windows"][0]["behavior"] == "silent"
    assert d["windows"][0]["targets"] == [1, 2]


def test_default_rows_are_inert():
    rows = aplane.default_rows(P_ADV_SER)
    hp = aplane.HostPlane(rows["adv_sched"], rows["adv_link"],
                          rows["adv_group"], rows["adv_heal"])
    assert hp.node_masks(0, 0, 0, 0) == (False, False, False)
    assert hp.delay_extra(100, 100, 1, 2, 0) == 0
    assert not hp.cut(0, 1, 0)
    assert hp.describe()["windows"] == []
    # Off params: zero-width rows.
    off = aplane.default_rows(P_OFF)
    assert off["adv_sched"].shape == (0, 7)
    assert off["adv_link"].shape == (0, 0)


def test_submit_rejects_params_invalid_attack():
    """A grammar-valid attack that violates THIS fleet's params (too many
    windows, bad target, unarmed base) is rejected at submit() — the
    queue stays untouched and the serve loop never sees it."""
    from librabft_simulator_tpu.parallel import mesh as mesh_ops
    from librabft_simulator_tpu.serve.service import ResidentFleet

    if len(jax.devices()) < SERVE_DP:
        pytest.skip("needs virtual devices (conftest sets 8)")
    base = SimParams(max_clock=MAX_CLOCK, **FLEET_ADV_SERVE_KW)
    mesh = mesh_ops.make_mesh(n_dp=SERVE_DP, n_mp=1,
                              devices=jax.devices()[:SERVE_DP])
    svc = ResidentFleet(base, slots=SERVE_SLOTS, mesh=mesh,
                        chunk=SERVE_CHUNK)
    too_many = {"windows": [{"behavior": "silent", "targets": [0]}
                            for _ in range(base.adv_windows + 1)]}
    with pytest.raises(ValueError, match="exceed the plane capacity"):
        svc.submit(sc.ScenarioSpec(max_clock=MAX_CLOCK, attack=too_many))
    with pytest.raises(ValueError, match="target 9"):
        svc.submit(sc.ScenarioSpec(max_clock=MAX_CLOCK, attack={
            "windows": [{"behavior": "silent", "targets": [9]}]}))
    assert svc.pending_count == 0 and not svc.requests
    svc.close()
    # Unarmed base: the same rejection, before any queue mutation.
    off = ResidentFleet(dataclasses.replace(P_OFF, watchdog=True,
                                            watchdog_stall_events=48),
                        slots=SERVE_SLOTS, mesh=mesh, chunk=SERVE_CHUNK)
    with pytest.raises(ValueError, match="adversary=False"):
        off.submit(sc.ScenarioSpec(max_clock=MAX_CLOCK,
                                   attack=ATTACKS[0]))
    assert off.pending_count == 0 and not off.requests
    off.close()


def test_checkpoint_refuses_dropping_armed_plane(tmp_path):
    """The reverse of the inert-fill rule: a checkpoint CARRYING an
    attack program refuses to load onto params that cannot represent it
    (adversary off, or a resized window capacity) — zero-filling would
    silently report an attacked run as attack-free."""
    st = SILENT_0.install(P_ADV_SER, S.init_state(P_ADV_SER, 3))
    path = str(tmp_path / "armed.npz")
    ckpt.save(path, st)
    with pytest.raises(ValueError, match="adv_sched"):
        ckpt.load(path, P_OFF, like=S.init_state(P_OFF, 0))
    p_resized = dataclasses.replace(P_ADV_SER, adv_windows=8)
    with pytest.raises(ValueError, match="adv_sched"):
        ckpt.load(path, p_resized, like=S.init_state(p_resized, 0))
    # Round trip onto matching params keeps the program bit-exact.
    back = ckpt.load(path, P_ADV_SER, like=S.init_state(P_ADV_SER, 0))
    assert np.array_equal(np.asarray(back.adv_sched),
                          np.asarray(st.adv_sched))


def test_checkpoint_restores_inert_plane(tmp_path):
    """A pre-plane checkpoint (adversary off) restores onto adversary-on
    params with the inert program — and continues running."""
    st = S.init_state(P_OFF, 3)
    path = str(tmp_path / "old.npz")
    ckpt.save(path, st)
    restored = ckpt.load(path, P_ADV_SER,
                         like=S.init_state(P_ADV_SER, 0))
    assert np.asarray(restored.adv_sched).shape == (
        P_ADV_SER.adv_windows, 7)
    assert not np.asarray(restored.adv_sched).any()
    assert not np.asarray(restored.adv_link).any()


def test_byz_targets():
    prog = dsl.AttackProgram(windows=(
        dsl.Window(behavior="silent", targets=(0, 2)),
        dsl.Window(behavior="delay", targets=(3,), arg=5),
    ))
    assert dsl.byz_targets(prog) == {0, 2}
    allp = dsl.AttackProgram(
        windows=(dsl.Window(behavior="equivocate"),))
    assert 63 in dsl.byz_targets(allp)
