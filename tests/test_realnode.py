"""Real-node stack: crypto, network, store, mempool round-trips, and a 3-node
local consensus run committing real batches."""

import asyncio
import json

import pytest

# Environment-bound: realnode's Ed25519 layer needs the `cryptography`
# package, which this container does not ship (and the no-new-deps rule
# forbids installing).  importorskip turns what was a COLLECTION ERROR —
# the seed suite's one real red mark — into a clean module skip wherever
# the dependency is absent, while hosts that have it still run the full
# realnode leg.
pytest.importorskip("cryptography")

from librabft_simulator_tpu.realnode.crypto import (  # noqa: E402
    Digest, Signature, SignatureService, generate_keypair,
)
from librabft_simulator_tpu.realnode.driver import ConsensusCore, NodeParameters
from librabft_simulator_tpu.realnode.mempool import (
    Authority, Committee, Mempool, Parameters,
)
from librabft_simulator_tpu.realnode.network import (
    Receiver, ReliableSender, SimpleSender, write_frame,
)
from librabft_simulator_tpu.realnode.store import Store

BASE_PORT = 17600


def test_crypto_sign_verify():
    pub, sec = generate_keypair()
    pub2, sec2 = generate_keypair()
    d1 = Digest.of(b"Foo::", b"35")
    d2 = Digest.of(b"Bar::", b"35")
    assert d1 != d2
    sig = Signature.new(d1, sec)
    sig.verify(d1, pub)
    with pytest.raises(Exception):
        sig.verify(d1, pub2)   # wrong key
    with pytest.raises(Exception):
        sig.verify(d2, pub)    # wrong digest
    Signature.verify_batch(d1, [(pub, sig)])


def test_signature_service():
    async def go():
        pub, sec = generate_keypair()
        svc = SignatureService(sec)
        d = Digest.of(b"hello")
        sig = await svc.request_signature(d)
        sig.verify(d, pub)
        svc.close()

    asyncio.run(go())


def test_network_simple_sender_roundtrip():
    async def go():
        got = asyncio.Queue()

        async def handler(writer, msg):
            await got.put(msg)

        recv = Receiver(("127.0.0.1", BASE_PORT), handler)
        await recv.spawn()
        sender = SimpleSender()
        await sender.send(("127.0.0.1", BASE_PORT), b"hello-simple")
        msg = await asyncio.wait_for(got.get(), 5)
        assert msg == b"hello-simple"
        sender.close()
        await recv.close()

    asyncio.run(go())


def test_network_reliable_sender_acks_and_retries():
    async def go():
        async def handler(writer, msg):
            await writer.send(b"ack:" + msg)

        sender = ReliableSender()
        # Send BEFORE the receiver exists: must retry until it comes up.
        fut = await sender.send(("127.0.0.1", BASE_PORT + 1), b"persistent")
        await asyncio.sleep(0.3)
        recv = Receiver(("127.0.0.1", BASE_PORT + 1), handler)
        await recv.spawn()
        ack = await asyncio.wait_for(fut, 10)
        assert ack == b"ack:persistent"
        sender.close()
        await recv.close()

    asyncio.run(go())


def test_store_notify_read(tmp_path):
    async def go():
        store = Store(str(tmp_path / "db.log"))
        await store.write(b"k1", b"v1")
        assert await store.read(b"k1") == b"v1"
        assert await store.read(b"nope") is None
        # notify_read blocks until the key is written.
        task = asyncio.create_task(store.notify_read(b"k2"))
        await asyncio.sleep(0.05)
        assert not task.done()
        await store.write(b"k2", b"v2")
        assert await asyncio.wait_for(task, 5) == b"v2"
        store.close()
        # Reopen: recovered from log.
        store2 = Store(str(tmp_path / "db.log"))
        assert await store2.read(b"k1") == b"v1"
        store2.close()

    asyncio.run(go())


def test_mempool_batches(tmp_path):
    async def go():
        store = Store(str(tmp_path / "db.log"))
        mp = Mempool(("127.0.0.1", BASE_PORT + 2),
                     Parameters(batch_size=64, max_batch_delay=0.05), store)
        await mp.spawn()
        reader, writer = await asyncio.open_connection("127.0.0.1", BASE_PORT + 2)
        for i in range(10):
            await write_frame(writer, b"tx-%03d" % i)
        digest = await asyncio.wait_for(mp.next_command(), 5)
        batch = await store.read(digest.to_vec())
        assert batch and b"tx-000" in batch
        writer.close()
        await mp.close()
        store.close()

    asyncio.run(go())


def make_committee(n, base):
    keys = [generate_keypair() for _ in range(n)]
    auths = [
        Authority(pub, 1, ("127.0.0.1", base + i), ("127.0.0.1", base + 100 + i))
        for i, (pub, _) in enumerate(keys)
    ]
    return Committee(auths), [sec for _, sec in keys]


def test_committee_json_roundtrip():
    committee, _ = make_committee(3, BASE_PORT + 10)
    c2 = Committee.from_json(committee.to_json())
    assert c2.quorum_threshold() == committee.quorum_threshold() == 3
    assert [n.to_base64() for n in c2.names()] == \
        [n.to_base64() for n in committee.names()]


def test_three_real_nodes_commit(tmp_path):
    async def go():
        committee, secrets = make_committee(3, BASE_PORT + 20)
        params = NodeParameters(delta=150, gamma=1.0)
        cores = []
        for i, sec in enumerate(secrets):
            store = Store(str(tmp_path / f"db{i}.log"))
            auth = list(committee.authorities.values())[i]
            core = ConsensusCore(i, committee, sec, params, None, store,
                                 auth.address)
            cores.append(core)
        for c in cores:
            await c.spawn()
        try:
            for _ in range(100):
                await asyncio.sleep(0.2)
                if min(len(c.committed) for c in cores) >= 3:
                    break
            commits = [c.committed for c in cores]
            assert min(len(c) for c in commits) >= 3, f"commits: {list(map(len, commits))}"
            # Agreement: common prefix of (depth, tag) chains.
            k = min(len(c) for c in commits)
            for i in range(k):
                assert commits[0][i] == commits[1][i] == commits[2][i]
        finally:
            for c in cores:
                await c.close()

    asyncio.run(go())


# ---- Timer (bft-driver/src/tests/timer_tests.rs) ---------------------------


def test_timer_schedule_fires_after_deadline():
    """timer_tests.rs `schedule`: a 100 ms deadline resolves no earlier."""
    from librabft_simulator_tpu.realnode.driver import Timer

    async def go():
        timer = Timer()
        now_ms = lambda: time.monotonic() * 1000.0  # noqa: E731
        t0 = time.monotonic()
        timer.schedule(now_ms() + 100)
        await timer.wait(now_ms)
        assert time.monotonic() - t0 > 0.095

    import time

    asyncio.run(go())


def test_timer_reschedule_overrides_deadline():
    """The reference timer is resettable: re-arming to an earlier deadline
    preempts the pending one (core.rs re-schedules on every update)."""
    from librabft_simulator_tpu.realnode.driver import Timer
    import time

    async def go():
        timer = Timer()
        now_ms = lambda: time.monotonic() * 1000.0  # noqa: E731
        t0 = time.monotonic()
        timer.schedule(now_ms() + 5000)
        waiter = asyncio.create_task(timer.wait(now_ms))
        await asyncio.sleep(0.05)
        timer.schedule(now_ms() + 50)  # pull the deadline in
        await asyncio.wait_for(waiter, timeout=2.0)
        elapsed = time.monotonic() - t0
        assert 0.09 < elapsed < 2.0, elapsed

    asyncio.run(go())


def test_timer_wait_blocks_until_armed():
    """wait() with no deadline parks until schedule() arms one."""
    from librabft_simulator_tpu.realnode.driver import Timer
    import time

    async def go():
        timer = Timer()
        now_ms = lambda: time.monotonic() * 1000.0  # noqa: E731
        waiter = asyncio.create_task(timer.wait(now_ms))
        await asyncio.sleep(0.05)
        assert not waiter.done()
        timer.schedule(now_ms() + 10)
        await asyncio.wait_for(waiter, timeout=2.0)

    asyncio.run(go())
