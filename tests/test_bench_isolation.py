"""bench.py run_all: per-engine failure isolation + one retry.

A flaky remote compile of ONE engine must not demote the whole TPU
measurement to CPU (it did, once: the parallel compile 500'd and the
serial number was forfeited).  These tests drive run_all with a
monkeypatched run_bench to pin the isolation contract.
"""

import importlib

import pytest


@pytest.fixture()
def bench(monkeypatch):
    monkeypatch.setenv("BENCH_PLATFORM", "cpu")  # skip supervisor + attach
    import bench as mod  # repo root is on sys.path via conftest.py
    importlib.reload(mod)
    return mod


def _row(engine):
    return {"rounds_per_sec": 100.0 if engine == "serial" else 50.0,
            "commits_per_sec": 1.0, "events_per_sec": 2.0, "elapsed_s": 1.0,
            "compile_s": 0.1, "overflow_frac": 0.0, "max_epoch": 0,
            "instances": 8, "n_nodes": 4, "steps": 4, "engine": engine,
            "epoch_handoff": False, "select_kernel": "xla"}


def test_one_engine_failure_keeps_the_other(bench, monkeypatch):
    attempts = {"parallel": 0, "serial": 0}

    def fake_run_bench(n, b, c, r, engine_name, **kw):
        attempts[engine_name] += 1
        if engine_name == "parallel":
            raise RuntimeError("remote_compile: HTTP 500")
        return _row(engine_name)

    monkeypatch.setattr(bench, "run_bench", fake_run_bench)
    monkeypatch.setenv("BENCH_ENGINE", "both")
    out = bench.run_all()
    assert out["engine"] == "serial" and out["value"] == 100.0
    assert "HTTP 500" in out["parallel_error"]
    # Exactly ONE retry for the failing engine, no retries for the winner.
    assert attempts == {"parallel": 2, "serial": 1}


def test_transient_failure_retried_once(bench, monkeypatch):
    calls = {"n": 0}

    def flaky(n, b, c, r, engine_name, **kw):
        calls["n"] += 1
        if engine_name == "serial" and calls["n"] == 1:
            raise RuntimeError("response body closed")
        return _row(engine_name)

    monkeypatch.setattr(bench, "run_bench", flaky)
    monkeypatch.setenv("BENCH_ENGINE", "serial")
    out = bench.run_all()
    # Retry succeeded: the serial row is the headline, no error key rides,
    # and the engine was attempted exactly twice (one retry, no more).
    assert out["engine"] == "serial" and out["value"] == 100.0
    assert "serial_error" not in out
    assert calls["n"] == 2


def test_all_engines_failing_raises(bench, monkeypatch):
    def broken(*a, **kw):
        raise RuntimeError("dead chip")

    monkeypatch.setattr(bench, "run_bench", broken)
    monkeypatch.setenv("BENCH_ENGINE", "both")
    with pytest.raises(RuntimeError, match="all engines failed"):
        bench.run_all()
