"""Referees for the static-analysis subsystem (librabft_simulator_tpu/audit/).

Three legs:

1. **Seeded violations** — known-bad toy graphs (a traced-index scalar
   scatter, a float leak into an int carry, a smuggled pure_callback, a
   traced dynamic-update-slice) must each be flagged with the RIGHT rule
   ID; known-good forms (one-hot wset, static-offset slice updates) must
   pass.  An auditor nobody has watched catch a bug is worse than no
   auditor — it retires review vigilance without replacing it.
2. **Real engines pass clean** — both engines at the audit micro shapes
   (graph_lint.MICRO_*) through R1-R4 + R6, and the dp-sharded runner
   through R3/R5 + the mp arm of R6 — the tier-1 form of
   ``scripts/graph_audit.py --assert-clean`` (CI runs the census-shape
   matrix separately).
3. **Sanitizer smoke** — the checkify build of both engines runs a micro
   fleet chunk (the warmed tests/fleet_shapes.py contract) with no error,
   values bit-identical to the unchecked engine, and a doctored state
   trips the right invariant.

Plus the source-lint fixtures (each S-rule on synthetic sources + the
whole repo clean) and the budgets/knob-registry wiring.
"""

import dataclasses
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fleet_shapes import FLEET_B, FLEET_CHUNK, FLEET_LANE_KW, \
    FLEET_SCENARIO_SER_KW, FLEET_SER_KW
from librabft_simulator_tpu.audit import concurrency_lint as CL
from librabft_simulator_tpu.audit import donation_lint as DL
from librabft_simulator_tpu.audit import graph_lint as GL
from librabft_simulator_tpu.audit import hlo_lint as HL
from librabft_simulator_tpu.audit import knobs as KN
from librabft_simulator_tpu.audit import sanitize as SAN
from librabft_simulator_tpu.audit import source_lint as SL
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import parallel_sim as PE
from librabft_simulator_tpu.sim import simulator as S

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings, severity="error"):
    return {f.rule for f in findings if f.severity == severity}


# ---------------------------------------------------------------------------
# Leg 1: seeded violations — wrong graphs flagged with the right rule ID.
# ---------------------------------------------------------------------------


class TestSeededViolations:
    def test_scalar_traced_scatter_is_r1(self):
        fs = GL.check_toy(lambda x, i: x.at[i].set(1),
                          jnp.zeros(8, jnp.int32), jnp.int32(3))
        assert "R1" in _rules(fs)
        assert any("scalar" in f.summary for f in fs if f.rule == "R1")

    def test_unwaived_vector_scatter_is_r1(self):
        # Vector form at a toy (unwaived) site: flagged as error too —
        # waivers are per registered engine file, not a global pass.
        fs = GL.check_toy(lambda x, i: x.at[i].set(1),
                          jnp.zeros(8, jnp.int32),
                          jnp.arange(3, dtype=jnp.int32))
        assert "R1" in _rules(fs)
        assert any("unwaived" in f.summary for f in fs if f.rule == "R1")

    def test_traced_dus_is_r1(self):
        fs = GL.check_toy(
            lambda x, i: jax.lax.dynamic_update_slice(
                x, jnp.zeros((1,), jnp.int32), (i,)),
            jnp.zeros(8, jnp.int32), jnp.int32(3))
        assert "R1" in _rules(fs)

    def test_static_dus_passes(self):
        fs = GL.check_toy(
            lambda x: jax.lax.dynamic_update_slice(
                x, jnp.zeros((2,), jnp.int32), (3,)),
            jnp.zeros(8, jnp.int32))
        assert "R1" not in _rules(fs)

    def test_onehot_wset_passes(self):
        from librabft_simulator_tpu.utils.xops import wset
        fs = GL.check_toy(lambda x, i: wset(x, i, 1),
                          jnp.zeros(8, jnp.int32), jnp.int32(3))
        assert not _rules(fs)

    def test_float_carry_is_r2(self):
        def leak(x):
            def body(c, _):
                ci, cf = c
                return (ci + 1, cf * 1.5), ()
            (ci, cf), _ = jax.lax.scan(body, (x, jnp.float32(1.0)),
                                       None, length=4)
            return ci + cf.astype(jnp.int32)
        fs = GL.check_toy(leak, jnp.int32(0))
        assert "R2" in _rules(fs)
        assert any("carry" in f.summary for f in fs if f.rule == "R2")

    def test_float_eqn_is_r2(self):
        fs = GL.check_toy(
            lambda x: (x.astype(jnp.float32) * 2.0).astype(jnp.int32),
            jnp.zeros(4, jnp.int32))
        assert "R2" in _rules(fs)

    def test_smuggled_pure_callback_is_r3(self):
        def smuggle(x):
            return jax.pure_callback(
                lambda v: np.asarray(v) + 1,
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        fs = GL.check_toy(smuggle, jnp.zeros(4, jnp.int32))
        assert "R3" in _rules(fs)

    def test_debug_callback_is_r3(self):
        def tap(x):
            jax.debug.callback(lambda v: None, x)
            return x + 1
        fs = GL.check_toy(tap, jnp.zeros(4, jnp.int32))
        assert "R3" in _rules(fs)

    def test_integer_graph_passes_r2_r3(self):
        fs = GL.check_toy(lambda x: jnp.cumsum(x) + jnp.max(x),
                          jnp.zeros(8, jnp.int32))
        assert not _rules(fs)


# ---------------------------------------------------------------------------
# Leg 2: the real engines audit clean at the micro shapes.
# ---------------------------------------------------------------------------


class TestEnginesClean:
    def test_serial_clean_with_r6(self):
        findings, stats = GL.audit_engine("serial", GL.MICRO_SER_KW)
        errors = [f for f in findings if f.severity == "error"]
        assert errors == []
        # The TPU-shape serial graph carries exactly the one waived
        # vector scatter (free-slot ranks) and zero float eqns.
        st = stats["serial/tpu_shape"]
        assert st["writes"]["scalar"] == 0
        assert st["writes"]["vector"] == st["writes"]["vector_waived"]
        assert st["float_eqns"] == 0
        # The K-macro flavors (macro_step's rolled inner scan) audit
        # clean too, with the same single waived site — the scan body is
        # traced once, so K cannot multiply write sites — and the R6
        # macro arm (K=1 == the bare step graph) held above (no errors).
        for kf in ("serial/tpu_shape_k4", "serial/tpu_shape_k16"):
            ks = stats[kf]
            assert ks["writes"]["scalar"] == 0
            assert ks["writes"]["vector_waived"] == 1
            assert ks["float_eqns"] == 0
        # The scenario-plane flavor (per-slot traced delay table +
        # commit-chain select, serve/scenario.py) adds NO write sites —
        # the plane is read-only config (the R6 scenario arm held above:
        # off-graph sc-leaf-inert + on-graph identity pass-through).
        sc = stats["serial/tpu_shape_scenario"]
        assert sc["writes"]["scalar"] == 0
        assert sc["writes"]["vector_waived"] == 1
        assert sc["float_eqns"] == 0

    def test_lane_clean(self):
        # R6 (the DCE pass) for the lane engine runs in the CI census-
        # shape audit; the tier-1 leg keeps to R1-R4 to bound trace time.
        findings, stats = GL.audit_engine(
            "lane", GL.MICRO_LANE_KW, r6=False,
            flavors=("tpu_shape", "tpu_telemetry"))
        errors = [f for f in findings if f.severity == "error"]
        assert errors == []
        st = stats["lane/tpu_shape"]
        assert st["writes"]["scalar"] == 0
        assert st["writes"]["vector"] == st["writes"]["vector_waived"] > 0
        assert st["float_eqns"] == 0

    def test_sharded_digest_contract(self):
        findings, stats = GL.audit_sharded(GL.MICRO_SER_KW)
        assert [f for f in findings if f.severity == "error"] == []
        assert stats["sharded/tpu_shape"]["padded_batch"] == 6  # 5 -> 2-mesh

    def test_digest_width_pinned(self):
        from librabft_simulator_tpu.telemetry import stream as tstream
        assert GL.DIGEST_WIDTH == tstream.DIGEST_WIDTH == 13


# ---------------------------------------------------------------------------
# Source lint: fixtures + the repo itself.
# ---------------------------------------------------------------------------


class TestSourceLint:
    def test_unregistered_knob_is_s3(self):
        fs = SL.lint_text(
            "scripts/example.py",
            "import os\nv = os.environ.get('LIBRABFT_BOGUS_KNOB')\n")
        assert {f.rule for f in fs} == {"S3"}
        assert "LIBRABFT_BOGUS_KNOB" in fs[0].summary

    def test_registered_and_external_keys_pass(self):
        fs = SL.lint_text(
            "scripts/example.py",
            "import os\n"
            "a = os.environ.get('LIBRABFT_PACKED')\n"
            "b = os.environ.get('JAX_PLATFORMS')\n")
        assert fs == []

    def test_unresolvable_key_is_s3(self):
        fs = SL.lint_text(
            "scripts/example.py",
            "import os\nv = os.environ.get('PREFIX_' + name)\n")
        assert {f.rule for f in fs} == {"S3"}

    def test_constant_resolved_key(self):
        fs = SL.lint_text(
            "scripts/example.py",
            "import os\nKEY = 'LIBRABFT_WRITE_MODE'\n"
            "v = os.environ.get(KEY)\n")
        assert fs == []

    def test_unsanctioned_device_get_is_s2(self):
        fs = SL.lint_text(
            "parallel/sharded.py",
            "import jax\n"
            "def sneaky_poll(st):\n"
            "    return jax.device_get(st.halted)\n")
        assert "S2" in {f.rule for f in fs}

    def test_bare_name_device_get_is_s2(self):
        # `from jax import device_get` must not bypass the rule.
        fs = SL.lint_text(
            "parallel/sharded.py",
            "from jax import device_get\n"
            "def sneaky_poll(st):\n"
            "    return device_get(st.halted)\n")
        assert "S2" in {f.rule for f in fs}

    def test_sanctioned_site_passes(self):
        fs = SL.lint_text(
            "parallel/sharded.py",
            "import jax\n"
            "def _poll_digest(dg):\n"
            "    return jax.device_get(dg)\n")
        assert fs == []

    def test_np_in_traced_code_is_s1(self):
        fs = SL.lint_text(
            "sim/simulator.py",
            "import numpy as np\n"
            "def step(p, delay_table, dur_table, st):\n"
            "    return np.maximum(st, 0)\n")
        assert "S1" in {f.rule for f in fs}

    def test_if_on_tracer_is_s1(self):
        fs = SL.lint_text(
            "sim/simulator.py",
            "def step(p, delay_table, dur_table, st):\n"
            "    if st.halted:\n"
            "        return st\n"
            "    return st\n")
        assert any(f.rule == "S1" and "tracer" in f.summary for f in fs)

    def test_if_on_params_passes(self):
        fs = SL.lint_text(
            "sim/simulator.py",
            "def step(p, delay_table, dur_table, st):\n"
            "    if p.telemetry:\n"
            "        return st\n"
            "    return st\n")
        assert fs == []

    def test_repo_is_clean(self):
        fs = SL.run(REPO)
        assert [f"{f.rule} {f.site}: {f.summary[:60]}" for f in fs] == []


# ---------------------------------------------------------------------------
# Budgets + knob registry wiring.
# ---------------------------------------------------------------------------


class TestBudgetsAndKnobs:
    def test_budgets_single_source(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "budgets.py"),
             "--sh"], capture_output=True, text=True, check=True).stdout
        for var in ("CENSUS_BUDGET", "TELEMETRY_CENSUS_BUDGET",
                    "WATCHDOG_CENSUS_BUDGET", "SHARDED_CENSUS_BUDGET",
                    "K4_CENSUS_BUDGET", "K16_CENSUS_BUDGET",
                    "TIER1_MIN_DOTS"):
            assert var in out
        # ci_tier1.sh consumes the eval line and holds no inline default.
        with open(os.path.join(REPO, "scripts", "ci_tier1.sh")) as f:
            sh = f.read()
        assert "budgets.py --sh" in sh

    def test_budget_values_sane(self):
        ns = SL._load_budgets(REPO)
        assert set(ns) == {"census_off", "census_telemetry",
                           "census_watchdog", "census_sharded",
                           "census_ring_k4", "census_ring_k16",
                           "census_k4", "census_k16", "census_scenario",
                           "census_adversary", "census_adversary_lane",
                           "tier1_min_dots", "bench_sentinel_tol_pct"}
        assert ns["census_telemetry"] > ns["census_off"]
        # The scenario plane's per-slot selects cost a bounded premium
        # over the off graph (serve/scenario.py; +21 measured round 14).
        assert ns["census_off"] < ns["census_scenario"] \
            <= ns["census_off"] + 100
        # The adversary plane's windowed decode is the same bounded-
        # premium story (+9 measured round 17, adversary/plane.py); the
        # lane window step carries its own (first-recorded) budget.
        assert ns["census_adversary"] <= ns["census_off"] + 100
        assert ns["census_adversary_lane"] > 0
        # The macro rungs' dispatched program stays ~flat in K (the
        # rolled inner scan's body is one step): the K=16 budget may not
        # silently balloon past K=4 — fusions-per-event amortization is
        # the whole point.
        assert ns["census_k16"] <= ns["census_k4"] + 10
        # Same flatness pin for the device-dispatch ring (round 19): the
        # in-graph chunk-retirement while_loop body is ONE chunk, so the
        # ring program is a bounded premium over the sharded base and may
        # not balloon with ring depth (K x census_sharded would mean XLA
        # unrolled the retirement loop).
        assert ns["census_sharded"] <= ns["census_ring_k4"] \
            <= ns["census_sharded"] + 100
        assert ns["census_ring_k16"] <= ns["census_ring_k4"] + 10
        # Fusions per EVENT must amortize >= 3x at K=16 even at budget
        # ceiling (the headroom-adjusted form of the round-11 claim).
        assert ns["census_k16"] / 16 <= ns["census_off"] / 3
        # The sentinel tolerance must stay wide enough that container
        # scheduler noise (measured ~1.6x between committed rows, PERF
        # NOTES round 18) cannot fire the gate, and tight enough that a
        # lost double-buffer / dead AOT store (2x-class) still does.
        assert 50 <= ns["bench_sentinel_tol_pct"] <= 150

    def test_readme_knob_table_in_sync(self):
        assert KN.readme_in_sync()

    def test_every_knob_prefix_grouped(self):
        for k in KN.KNOBS:
            assert k.group in ("engine", "bench", "fuzz", "script"), k
            assert k.desc and k.where and k.values, k


# ---------------------------------------------------------------------------
# Leg 3: the checkify sanitizer (tier-1 smoke; shapes warmed via
# scripts/warm_cache.py SANITIZE_SHAPES — the fleet_shapes contract).
# ---------------------------------------------------------------------------


class TestSanitizer:
    def test_off_by_default(self, monkeypatch):
        monkeypatch.delenv(SAN.CHECKIFY_ENV, raising=False)
        assert not SAN.enabled()
        monkeypatch.setenv(SAN.CHECKIFY_ENV, "1")
        assert SAN.enabled()
        monkeypatch.setenv(SAN.CHECKIFY_ENV, "off")
        assert not SAN.enabled()

    def test_serial_smoke_and_bit_identity(self):
        p = SimParams(max_clock=500, **FLEET_SER_KW)
        seeds = np.arange(FLEET_B, dtype=np.uint32)
        checked = SAN.run_checked(p, S.init_batch(p, seeds), FLEET_CHUNK,
                                  batched=True, engine=S)
        plain = S.make_run_fn(p, FLEET_CHUNK)(
            S.dedupe_buffers(S.init_batch(p, seeds)))
        for a, b in zip(jax.tree_util.tree_leaves(checked),
                        jax.tree_util.tree_leaves(plain)):
            assert jnp.array_equal(a, b)

    def test_lane_smoke(self):
        p = SimParams(max_clock=500, **FLEET_LANE_KW)
        st = PE.init_batch(p, np.arange(FLEET_B, dtype=np.uint32))
        out = SAN.run_checked(p, st, FLEET_CHUNK, batched=True, engine=PE)
        assert int(jnp.sum(out.n_events)) > 0

    def test_doctored_state_trips(self):
        from jax.experimental import checkify
        p = SimParams(max_clock=500, **FLEET_SER_KW)
        st = S.init_batch(p, np.arange(FLEET_B, dtype=np.uint32))
        bad = st.replace(n_events=st.n_events - jnp.int32(100))
        with pytest.raises(checkify.JaxRuntimeError,
                           match="n_events wrapped negative"):
            SAN.run_checked(p, bad, FLEET_CHUNK, batched=True, engine=S)

    def test_doctored_ledger_trips(self):
        from jax.experimental import checkify
        p = SimParams(max_clock=500, **FLEET_SER_KW)
        st = S.init_batch(p, np.arange(FLEET_B, dtype=np.uint32))
        bad = st.replace(ctx=st.ctx.replace(
            skipped_commits=st.ctx.skipped_commits + jnp.int32(1)))
        with pytest.raises(checkify.JaxRuntimeError,
                           match="commit ledger inconsistent"):
            SAN.run_checked(p, bad, FLEET_CHUNK, batched=True, engine=S)

    def test_stream_plus_checkify_refused(self, monkeypatch):
        # The stream loop runs the UNchecked chunk; pretending it was
        # invariant-checked would be worse than not checking — refuse.
        from librabft_simulator_tpu.telemetry import stream as tstream
        monkeypatch.setenv(SAN.CHECKIFY_ENV, "1")
        p = SimParams(max_clock=500, **FLEET_SER_KW)
        st = S.init_batch(p, np.arange(FLEET_B, dtype=np.uint32))
        rec = tstream.TimelineRecorder(p)
        with pytest.raises(ValueError, match="mutually exclusive"):
            S.run_to_completion(p, st, chunk=FLEET_CHUNK, max_chunks=1,
                                batched=True, stream=rec)
        with pytest.raises(ValueError, match="mutually exclusive"):
            PE.run_to_completion(
                SimParams(max_clock=500, **FLEET_LANE_KW),
                PE.init_batch(SimParams(max_clock=500, **FLEET_LANE_KW),
                              np.arange(FLEET_B, dtype=np.uint32)),
                chunk=FLEET_CHUNK, max_chunks=1, batched=True, stream=rec)

    def test_run_to_completion_wiring(self, monkeypatch):
        # LIBRABFT_CHECKIFY=1 routes run_to_completion through the
        # checked chunk — same executable as the smoke above (the params
        # and chunk match the fleet_shapes contract), same trajectory.
        monkeypatch.setenv(SAN.CHECKIFY_ENV, "1")
        p = SimParams(max_clock=500, **FLEET_SER_KW)
        seeds = np.arange(FLEET_B, dtype=np.uint32)
        out = S.run_to_completion(p, S.init_batch(p, seeds),
                                  chunk=FLEET_CHUNK, max_chunks=2,
                                  batched=True)
        monkeypatch.delenv(SAN.CHECKIFY_ENV)
        ref = S.run_to_completion(p, S.init_batch(p, seeds),
                                  chunk=FLEET_CHUNK, max_chunks=2,
                                  batched=True)
        for a, b in zip(jax.tree_util.tree_leaves(out),
                        jax.tree_util.tree_leaves(ref)):
            assert jnp.array_equal(a, b)


# ---------------------------------------------------------------------------
# R6 on the serial engine at micro shape is covered by
# TestEnginesClean.test_serial_clean_with_r6; pin one structural detail the
# audit relies on so a jax upgrade that breaks DCE comparison fails loud
# here instead of silently passing everything.
# ---------------------------------------------------------------------------


def test_ledger_on_off_lowering_identical():
    """The runtime ledger (telemetry/ledger.py) is host-only BY
    CONSTRUCTION — prove it, don't assert it: both engines' chunk scans
    trace to eqn-identical jaxprs with the process ledger enabled and
    disabled.  Spans and compile attribution wrap the host call around
    the executable; nothing of the ledger may ever enter the traced
    graph (zero added fusions, census budgets and audit signatures
    unchanged)."""
    from librabft_simulator_tpu.telemetry import ledger as tledger

    lg = tledger.get()
    prev = lg.enabled

    def sig(engine, kw):
        p = SimParams(max_clock=100, **kw)
        st = engine.init_batch(p, np.arange(2, dtype=np.uint32))
        cj = jax.make_jaxpr(engine.make_scan_fn(p, 2))(st)
        return GL.eqn_signature(cj.jaxpr)

    try:
        lg.enabled = True
        on = [sig(S, GL.MICRO_SER_KW), sig(PE, GL.MICRO_LANE_KW)]
        lg.enabled = False
        off = [sig(S, GL.MICRO_SER_KW), sig(PE, GL.MICRO_LANE_KW)]
    finally:
        lg.enabled = prev
    assert on == off


# ---------------------------------------------------------------------------
# Donation/aliasing verifier (audit/donation_lint.py, D-rules): seeded
# violations each flagged with the right rule ID, and the repo clean.
# ---------------------------------------------------------------------------


def _toy_state():
    return {"a": jnp.zeros((4,), jnp.int32), "b": jnp.zeros((2,), jnp.int32)}


class TestDonationLint:
    def test_donation_map_reads_donated_leaves(self):
        f = jax.jit(lambda t, st: jax.tree.map(lambda x: x + t, st),
                    donate_argnums=(1,))
        dm = DL.donation_map(f, (jnp.int32(1), _toy_state()))
        assert len(dm["donated"]) == 2 and len(dm["kept"]) == 1
        assert all(p.startswith("[1]") for p in dm["donated"])

    def test_undonated_state_is_d1(self):
        # A "chunk runner" that stopped donating: every chunk would pay
        # a fleet-sized copy — flagged, with the leaf named.
        f = jax.jit(lambda t, st: jax.tree.map(lambda x: x + t, st))
        fs, _ = DL.check_donation(f, (jnp.int32(1), _toy_state()), 1,
                                  "toy")
        assert _rules(fs) == {"D1"}
        assert any("NOT donated" in f.summary for f in fs)

    def test_non_state_donation_is_d1(self):
        # Donating the shared table would free a host-reused buffer.
        f = jax.jit(lambda t, st: jax.tree.map(lambda x: x + t, st),
                    donate_argnums=(0, 1))
        fs, _ = DL.check_donation(f, (jnp.int32(7), _toy_state()), 1,
                                  "toy")
        assert any(f.rule == "D1" and "non-state leaf" in f.summary
                   for f in fs)

    def test_donation_count_pin_drift_is_d1(self):
        f = jax.jit(lambda t, st: jax.tree.map(lambda x: x + t, st),
                    donate_argnums=(1,))
        fs, _ = DL.check_donation(f, (jnp.int32(1), _toy_state()), 1,
                                  "toy", expected_donated=3)
        assert any(f.rule == "D1" and "drift" in f.summary for f in fs)

    def test_donation_free_contract(self):
        # The sanitizer-build contract: donating anything is the error.
        f = jax.jit(lambda st: jax.tree.map(lambda x: x + 1, st),
                    donate_argnums=(0,))
        fs, _ = DL.check_donation(f, (_toy_state(),), None, "toy")
        assert any(f.rule == "D1" and "donation-free" in f.summary
                   for f in fs)

    def test_pr9_bare_placement_reconstruction_is_d2(self):
        """THE PR-9 segfault class, reconstructed: a checkpoint-restored
        host tree placed with a bare shard_batch (no dedupe_buffers) on
        the path into the donating resident runner — D2 flags it."""
        src = (
            "import jax\n"
            "def restore(svc, path, p, like):\n"
            "    host = load(path, p, like=like)\n"
            "    svc._st = mesh_ops.shard_batch(svc.mesh, host)\n"
            "    return svc\n")
        fs = DL.lint_text("serve/service.py", src)
        assert _rules(fs) == {"D2"}
        assert any("dedupe_buffers" in f.summary for f in fs)
        # jax.device_put spelling of the same bug: also flagged.
        src_dp = (
            "import jax\n"
            "def restore(svc, path, p, like):\n"
            "    svc._st = jax.device_put(load(path, p, like=like))\n"
            "    return svc\n")
        assert _rules(DL.lint_text("serve/service.py", src_dp)) == {"D2"}

    def test_deduped_placement_passes_d2(self):
        src = (
            "def restore(svc, path, p, like):\n"
            "    host = load(path, p, like=like)\n"
            "    svc._st = mesh_ops.shard_batch(\n"
            "        svc.mesh, sim_ops.dedupe_buffers(host))\n"
            "    return svc\n")
        assert DL.lint_text("serve/service.py", src) == []

    def test_out_of_scope_placement_ignored(self):
        src = "def f(mesh, x):\n    return mesh_ops.shard_batch(mesh, x)\n"
        assert DL.lint_text("analysis/sweeps.py", src) == []

    def test_use_after_donate_is_d3(self):
        src = (
            "def loop(run, st):\n"
            "    st2, dg = run(st)\n"
            "    return st.clock\n")  # st's buffer was donated to run()
        fs = DL.lint_text("parallel/sharded.py", src)
        assert _rules(fs) == {"D3"}

    def test_rebound_donation_idiom_passes_d3(self):
        src = (
            "def loop(run, st):\n"
            "    st, dg = run(st)\n"
            "    return st.clock\n")
        assert DL.lint_text("parallel/sharded.py", src) == []

    def test_self_attr_use_after_donate_is_d3(self):
        src = (
            "class F:\n"
            "    def pump(self):\n"
            "        nxt, dg = self._run(self._st)\n"
            "        x = self._st.halted\n"
            "        self._st = nxt\n"
            "        return x\n")
        fs = DL.lint_text("serve/service.py", src)
        assert _rules(fs) == {"D3"}

    def test_branch_separated_read_is_not_d3(self):
        # A donation in one branch followed by a read that only executes
        # on the mutually exclusive path (early return / else) is NOT a
        # use-after-donate — the branches never rejoin.
        src = (
            "def f(run, st, cond):\n"
            "    if cond:\n"
            "        nxt, dg = run(st)\n"
            "        return nxt\n"
            "    return st.clock\n")
        assert DL.lint_text("parallel/sharded.py", src) == []
        src_else = (
            "def f(run, st, cond):\n"
            "    if cond:\n"
            "        nxt, dg = run(st)\n"
            "        return nxt\n"
            "    else:\n"
            "        return st.clock\n")
        assert DL.lint_text("parallel/sharded.py", src_else) == []

    def test_branch_rejoining_read_is_d3(self):
        # No early return: the post-if read DOES execute after the
        # branch's donation — still flagged.
        src = (
            "def f(run, st, cond):\n"
            "    if cond:\n"
            "        nxt, dg = run(st)\n"
            "    return st.clock\n")
        assert _rules(DL.lint_text("parallel/sharded.py", src)) == {"D3"}

    def test_repo_source_clean_d2_d3(self):
        fs = DL.run_source(REPO)
        assert [f"{f.rule} {f.site}: {f.summary[:60]}" for f in fs] == []

    def test_budgets_pin_covers_the_flavor_matrix(self):
        pinned = DL._expected_table()
        assert set(pinned) == set(DL.DONATION_FLAVORS)
        # The engine state flattens to >100 leaves; a pin collapsing
        # toward 0 means the map silently stopped being read.
        assert pinned["serial/run"] > 50
        assert pinned["sanitize/serial"] == 0

    def test_real_serial_runner_donation_map(self):
        """One real flavor end-to-end in tier-1 (the full matrix runs in
        scripts/graph_audit.py): the serial chunk runner donates exactly
        its state leaves, pinned to the budgets table."""
        from librabft_simulator_tpu.sim import simulator as S2
        from librabft_simulator_tpu.utils import xops

        p = xops.resolve_params(
            SimParams(**GL.MICRO_SER_KW, **GL.TPU_FORMS))
        st = S2.init_batch(p, np.arange(3, dtype=np.uint32))
        args = (jnp.asarray(p.delay_table()),
                jnp.asarray(p.duration_table()), st)
        fs, stats = DL.check_donation(
            S2._compiled_run(p.structural(), 2, True), args, 2,
            "serial/run",
            expected_donated=DL._expected_table()["serial/run"])
        assert fs == []
        assert stats["donated"] == len(jax.tree_util.tree_leaves(st))


# ---------------------------------------------------------------------------
# Host-concurrency lint (audit/concurrency_lint.py, C-rules).
# ---------------------------------------------------------------------------


class TestConcurrencyLint:
    def test_unbounded_wait_is_c1(self):
        src = (
            "def reap(procs):\n"
            "    for p in procs:\n"
            "        p.wait()\n")
        fs = CL.lint_text("distributed/bootstrap.py", src)
        assert _rules(fs) == {"C1"}

    def test_bounded_wait_passes_c1(self):
        src = (
            "def reap(procs):\n"
            "    for p in procs:\n"
            "        p.wait(timeout=10)\n"
            "    handle.wait(600)\n")
        assert CL.lint_text("distributed/bootstrap.py", src) == []

    def test_unbounded_join_is_c1(self):
        src = "def stop(t):\n    t.join()\n"
        assert _rules(CL.lint_text("serve/service.py", src)) == {"C1"}

    def test_blocking_flock_is_c1(self):
        src = (
            "import fcntl\n"
            "def lock(f):\n"
            "    fcntl.flock(f, fcntl.LOCK_EX)\n")
        fs = CL.lint_text("utils/aot.py", src)
        assert _rules(fs) == {"C1"}
        assert any("LOCK_EX" in f.summary for f in fs)

    def test_nonblocking_flock_passes_c1(self):
        src = (
            "import fcntl\n"
            "def lock(f):\n"
            "    fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)\n")
        assert CL.lint_text("utils/aot.py", src) == []

    def test_none_timeout_is_still_c1(self):
        # `wait(None)` / `wait(timeout=None)` is the unbounded form in a
        # bounded costume.
        src = "def f(p):\n    p.wait(None)\n    p.wait(timeout=None)\n"
        fs = CL.lint_text("distributed/bootstrap.py", src)
        assert len(fs) == 2 and _rules(fs) == {"C1"}

    def test_out_of_scope_wait_ignored(self):
        src = "def f(p):\n    p.wait()\n"
        assert CL.lint_text("analysis/sweeps.py", src) == []

    def test_unlocked_mutation_is_c2(self):
        src = (
            "class RuntimeLedger:\n"
            "    def sneak(self, sp):\n"
            "        self.spans.append(sp)\n")
        fs = CL.lint_text("telemetry/ledger.py", src)
        assert _rules(fs) == {"C2"}

    def test_locked_mutation_passes_c2(self):
        src = (
            "class RuntimeLedger:\n"
            "    def record(self, sp):\n"
            "        with self._lock:\n"
            "            self.spans.append(sp)\n"
            "            self.dropped += 1\n")
        assert CL.lint_text("telemetry/ledger.py", src) == []

    def test_module_level_guarded_dict_is_c2(self):
        src = (
            "def refuse(ck):\n"
            "    _REFUSED[ck] = 'aot-miss'\n")
        fs = CL.lint_text("utils/aot.py", src)
        assert _rules(fs) == {"C2"}

    def test_serve_queue_mutation_outside_lock_is_c2(self):
        src = (
            "class ResidentFleet:\n"
            "    def submit(self, req, rid):\n"
            "        self._pending.append(req)\n"
            "        return rid\n")
        assert _rules(CL.lint_text("serve/service.py", src)) == {"C2"}

    def test_mutation_in_test_expr_is_c2(self):
        # `while pending.pop():` / `if pending.popleft():` mutate just
        # as much as a statement-level call.
        src = (
            "class ResidentFleet:\n"
            "    def f(self):\n"
            "        while self._pending.pop():\n"
            "            pass\n"
            "        if self._pending.popleft():\n"
            "            return 1\n")
        fs = CL.lint_text("serve/service.py", src)
        assert len(fs) == 2 and _rules(fs) == {"C2"}

    def test_unflushed_ndjson_row_is_c3(self):
        src = (
            "import json\n"
            "def emit(out, obj):\n"
            "    out.write(json.dumps(obj) + '\\n')\n")
        fs = CL.lint_text("telemetry/stream.py", src)
        assert _rules(fs) == {"C3"}

    def test_flushed_ndjson_row_passes_c3(self):
        src = (
            "import json\n"
            "def emit(out, obj):\n"
            "    out.write(json.dumps(obj) + '\\n')\n"
            "    out.flush()\n")
        assert CL.lint_text("telemetry/stream.py", src) == []

    def test_wrong_stream_flush_is_still_c3(self):
        # Flushing a DIFFERENT stream must not satisfy the rule: the
        # rows still buffer on out.
        src = (
            "import json, sys\n"
            "def emit(out, obj):\n"
            "    out.write(json.dumps(obj) + '\\n')\n"
            "    sys.stderr.flush()\n")
        assert _rules(CL.lint_text("telemetry/stream.py", src)) == {"C3"}

    def test_repo_source_clean_c_rules(self):
        fs = CL.run(REPO)
        assert [f"{f.rule} {f.site}: {f.summary[:60]}" for f in fs] == []


# ---------------------------------------------------------------------------
# Compiled-HLO audit (audit/hlo_lint.py, rule HLO): parser-level seeded
# fixtures (synthetic optimized-module text) + a real compiled toy.  The
# full three-runner compiled matrix runs in scripts/graph_audit.py.
# ---------------------------------------------------------------------------

_GOOD_HEADER = (
    "HloModule jit_f, is_scheduled=true, input_output_alias={ {0}: (0, {}, "
    "may-alias), {1}: (1, {}, may-alias) }, entry_computation_layout="
    "{(s32[6,4]{1,0}, s32[6]{0})->(s32[6,4]{1,0}, s32[6]{0}, s32[13]{0})}")


class TestHloLint:
    def test_scalar_scatter_instruction_flagged(self):
        txt = (
            "HloModule jit_f, is_scheduled=true\n"
            "ENTRY %main.1 (p0: s32[8]) -> s32[8] {\n"
            "  %sc = s32[8]{0} scatter(s32[8]{0} %p0, s32[1]{0} %i, "
            "s32[1]{0} %u), update_window_dims={}, inserted_window_dims={0},"
            " scatter_dims_to_operand_dims={0}, index_vector_dim=1\n"
            "}\n")
        fs, stats = HL.check_hlo_scatters(txt, "toy", ())
        assert any(f.rule == "HLO" and "single-update" in f.summary
                   for f in fs)
        assert stats["scatter_scalar"] == 1

    def test_vector_scatter_instruction_passes(self):
        txt = (
            "HloModule jit_f, is_scheduled=true\n"
            "ENTRY %main.1 (p0: s32[8]) -> s32[8] {\n"
            "  %sc = s32[8]{0} scatter(s32[8]{0} %p0, s32[3,1]{1,0} %i, "
            "s32[3]{0} %u), update_window_dims={}, inserted_window_dims={0},"
            " scatter_dims_to_operand_dims={0}, index_vector_dim=1\n"
            "}\n")
        fs, stats = HL.check_hlo_scatters(txt, "toy", ())
        assert fs == []
        assert stats["scatter_instructions"] == 1

    def test_uncertified_scatter_site_flagged(self):
        txt = (
            'HloModule jit_f\n'
            '  %f = s32[4]{0} fusion(s32[4]{0} %p0), kind=kLoop, metadata='
            '{op_name="jit(f)/jit(main)/scatter" '
            'source_file="/repo/librabft_simulator_tpu/core/rogue.py" '
            'source_line=7}\n')
        fs, _ = HL.check_hlo_scatters(
            txt, "toy", ("sim/simulator.py", "telemetry/plane.py"))
        assert any(f.rule == "HLO" and "uncertified" in f.summary
                   for f in fs)

    def test_certified_scatter_site_passes(self):
        txt = (
            'HloModule jit_f\n'
            '  %f = s32[4]{0} fusion(s32[4]{0} %p0), kind=kLoop, metadata='
            '{op_name="jit(f)/jit(main)/scatter" '
            'source_file="/repo/librabft_simulator_tpu/sim/simulator.py" '
            'source_line=7}\n')
        fs, stats = HL.check_hlo_scatters(
            txt, "toy", ("sim/simulator.py",))
        assert fs == []
        assert stats["scatter_sites"] == 1

    def test_digest_only_root_passes(self):
        assert HL.check_hlo_root(_GOOD_HEADER, "toy", 6, 13) == []

    def test_extra_small_root_output_flagged(self):
        bad = _GOOD_HEADER.replace(
            "s32[6]{0}, s32[13]{0})}", "s32[6]{0}, s32[13]{0}, s32[2]{0})}")
        fs = HL.check_hlo_root(bad, "toy", 6, 13)
        assert any("non-fleet-sized" in f.summary for f in fs)

    def test_double_digest_root_flagged(self):
        bad = _GOOD_HEADER.replace(
            "s32[6]{0}, s32[13]{0})}", "s32[13]{0}, s32[13]{0})}")
        fs = HL.check_hlo_root(bad, "toy", 6, 13)
        assert any("exactly 1" in f.summary for f in fs)

    def test_alias_survival_counts(self):
        fs, stats = HL.check_hlo_alias(_GOOD_HEADER, "toy", 2)
        assert fs == [] and stats["alias_pairs"] == 2
        fs, _ = HL.check_hlo_alias(_GOOD_HEADER, "toy", 3)
        assert any("dropped by the compiler" in f.summary for f in fs)

    def test_real_compiled_toy_alias_and_scatters(self):
        """End-to-end on a real compiled executable: a donating int map
        keeps its alias pair, and a traced-index .at[].set from THIS
        (uncertified) file surfaces in the scatter provenance."""
        f = jax.jit(lambda x, i: x.at[i].set(1) + x.sum(),
                    donate_argnums=(0,))
        txt = f.lower(jnp.zeros((8,), jnp.int32),
                      jnp.arange(3, dtype=jnp.int32)).compile().as_text()
        fs, stats = HL.check_hlo_scatters(txt, "toy", ())
        assert stats["scatter_sites"] >= 1  # this test file, uncertified
        assert any(f.rule == "HLO" and "uncertified" in f.summary
                   for f in fs)
        _, astats = HL.check_hlo_alias(txt, "toy", 1)
        assert astats["alias_pairs"] <= 1  # x consumed by sum: may drop

    def test_hlo_static_scatter_registry_documented(self):
        for fname, why in HL.HLO_STATIC_SCATTER_FILES.items():
            assert fname.endswith(".py") and len(why) > 20


# ---------------------------------------------------------------------------
# Scenario-flavor sanitizer (round-16 satellite): LIBRABFT_CHECKIFY on a
# SimParams.scenario=True build — bit-identity pinned (shape warmed via
# warm_cache SANITIZE_SHAPES).
# ---------------------------------------------------------------------------


class TestSanitizerScenario:
    def test_scenario_smoke_and_bit_identity(self):
        p = SimParams(max_clock=500, **FLEET_SCENARIO_SER_KW)
        seeds = np.arange(FLEET_B, dtype=np.uint32)
        checked = SAN.run_checked(p, S.init_batch(p, seeds), FLEET_CHUNK,
                                  batched=True, engine=S)
        plain = S.make_run_fn(p, FLEET_CHUNK)(
            S.dedupe_buffers(S.init_batch(p, seeds)))
        assert int(jnp.sum(checked.n_events)) > 0
        for a, b in zip(jax.tree_util.tree_leaves(checked),
                        jax.tree_util.tree_leaves(plain)):
            assert jnp.array_equal(a, b)

    def test_scenario_doctored_state_trips(self):
        from jax.experimental import checkify
        p = SimParams(max_clock=500, **FLEET_SCENARIO_SER_KW)
        st = S.init_batch(p, np.arange(FLEET_B, dtype=np.uint32))
        bad = st.replace(n_events=st.n_events - jnp.int32(100))
        with pytest.raises(checkify.JaxRuntimeError,
                           match="n_events wrapped negative"):
            SAN.run_checked(p, bad, FLEET_CHUNK, batched=True, engine=S)


def test_r6_detects_feedback():
    """A graph where the 'telemetry' value DOES feed consensus must NOT
    compare equal under the R6 DCE construction."""
    from jax.interpreters import partial_eval as pe

    def make(feedback):
        def f(x, m):
            m2 = m + jnp.sum(x)              # telemetry write
            x2 = x + (m2[0] if feedback else 0)  # feedback into consensus
            return x2, m2
        return f

    x = jnp.zeros(4, jnp.int32)
    m = jnp.zeros(3, jnp.int32)

    def sliced_sig(fn):
        cj = jax.make_jaxpr(fn)(x, m)
        dj, _ = pe.dce_jaxpr(cj.jaxpr, [True, False])  # keep consensus out
        return GL.eqn_signature(dj)

    clean = sliced_sig(make(False))
    leaky = sliced_sig(make(True))
    assert clean != leaky
