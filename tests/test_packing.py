"""core/packing.py: packed state planes must be a lossless re-layout.

Two contracts: (1) pack -> unpack is the identity for every leaf of
``SimState``/``PSimState`` (uint32 bitcast, bool as 0/1 — bit-preserving);
(2) the packed engines produce bit-identical trajectories to the unpacked
ones — committed chains, counters, and every other state leaf.
"""

import dataclasses

import jax
import numpy as np
import pytest

from librabft_simulator_tpu.core import packing
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import parallel_sim as P
from librabft_simulator_tpu.sim import simulator as S


def assert_trees_equal(a, b):
    flat_a = jax.tree_util.tree_flatten_with_path(a)[0]
    flat_b = jax.tree_util.tree_flatten_with_path(b)[0]
    assert len(flat_a) == len(flat_b)
    for (pt, la), (_, lb) in zip(flat_a, flat_b):
        path = "/".join(str(q) for q in pt)
        assert la.dtype == lb.dtype, path
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb), path)


def test_node_width_matches_slot_map():
    p = SimParams(n_nodes=4)
    slots, width = packing.slot_map(p.structural())
    assert width == packing.node_width(p)
    assert width == sum(s[1] for s in slots)
    # Offsets tile the vector exactly.
    off = 0
    for o, size, _, _ in slots:
        assert o == off
        off += size


def test_pack_unpack_roundtrip_sim_state():
    # A warmed-up state exercises nonzero values in every table.
    p = SimParams(n_nodes=3, max_clock=400)
    st = S.run_to_completion(p, S.init_state(p, 5))
    pst = packing.pack_state(p, st)
    assert pst.planes.shape == (p.n_nodes, packing.node_width(p))
    assert_trees_equal(st, packing.unpack_state(p, pst))


def test_pack_unpack_roundtrip_batched():
    p = SimParams(n_nodes=3, max_clock=300)
    st = S.run_to_completion(p, S.init_batch(p, np.arange(4, dtype=np.uint32)),
                             batched=True)
    pst = packing.pack_state(p, st)
    assert pst.planes.shape == (4, p.n_nodes, packing.node_width(p))
    assert_trees_equal(st, packing.unpack_state(p, pst))


def test_pack_unpack_roundtrip_psim_state():
    # Initial state only (no engine compile): covers every PSimState leaf's
    # slot/dtype mapping; nonzero-value coverage rides the slow engine
    # identity test below and the shared pack_node path of the SimState
    # roundtrips above.
    p = SimParams(n_nodes=4, max_clock=300, epoch_handoff=False)
    st = P.init_state(p, 2)
    pst = P.pack_pstate(p, st)
    assert pst.planes.shape == (p.n_nodes, packing.node_width(p))
    assert_trees_equal(st, P.unpack_pstate(p, pst))


def test_packed_serial_engine_bit_identical():
    """Same seed, packed vs unpacked layout: every leaf equal — including
    the committed chains (ctx.log_*) and all counters."""
    p = SimParams(n_nodes=3, max_clock=400)
    a = S.run_to_completion(p, S.init_state(p, 0))
    b = S.run_to_completion(dataclasses.replace(p, packed=True),
                            S.init_state(p, 0))
    assert_trees_equal(a, b)
    assert min(int(c) for c in a.ctx.commit_count) > 0  # non-trivial run


@pytest.mark.slow  # two fresh parallel-engine compiles (~3 min on CPU);
# tier-1 coverage of the packed layout rides the serial identity test +
# the cheap PSimState roundtrip above.
def test_packed_parallel_engine_bit_identical():
    p = SimParams(n_nodes=4, max_clock=400, epoch_handoff=False)
    a = P.run_to_completion(p, P.init_state(p, 1), chunk=32)
    b = P.run_to_completion(dataclasses.replace(p, packed=True),
                            P.init_state(p, 1), chunk=32)
    assert_trees_equal(a, b)
    assert int(a.n_events) > 0


def test_gated_handlers_bit_identical():
    """gate_handlers=True (the TPU default) vs False (the CPU default):
    the lax.cond gating must not change the trajectory — the false branch
    returns (s_a, False)/(s_a, nx_a, cx_a), which is exactly what the
    ungated per-field _sel would have selected for the wrong kind.  CPU
    auto-resolves the gate off, so without this test the gated graph would
    only ever execute on-chip."""
    p = SimParams(n_nodes=3, max_clock=400)
    a = S.run_to_completion(dataclasses.replace(p, gate_handlers=False),
                            S.init_state(p, 0))
    b = S.run_to_completion(dataclasses.replace(p, gate_handlers=True),
                            S.init_state(p, 0))
    assert_trees_equal(a, b)
    assert min(int(c) for c in a.ctx.commit_count) > 0


def test_resolved_params_cpu_defaults():
    """On a CPU backend the auto fields resolve to the proven forms."""
    from librabft_simulator_tpu.utils import xops

    if jax.default_backend() != "cpu":
        pytest.skip("resolution targets differ off-CPU")
    p = xops.resolve_params(SimParams(n_nodes=3))
    assert p.packed is False
    assert p.dense_writes == "scatter"
    assert p.gate_handlers is False  # CPU keeps the exact pre-PR graph
