"""telemetry/ledger.py: the host-side runtime ledger.

Referees for the observability-PR acceptance criteria:

(a) span mechanics — nesting (parent/depth from the per-thread stack),
    schema, and fully deterministic output under an injected clock;
(b) round trips — NDJSON streaming (meta line, per-row flush, summary on
    close) and the Chrome-trace/Perfetto export reproduce the recorded
    spans exactly;
(c) the compile ledger — jax.monitoring cache hit/miss events classify
    entries correctly (fed through the listener entry points for
    determinism), and a REAL engine executable built through
    ``make_run_fn`` lands an attributed entry keyed on the structural
    params + shapes;
(d) the pipeline analysis — overlap fraction, bubble flags, and
    time_to_first_chunk computed from known synthetic spans, and a real
    ``run_sharded`` micro-fleet run (the warmed fleet_shapes contract)
    recording per-chunk dispatch/poll spans;
(e) hardening — the stream/ledger NDJSON readers tolerate a mid-write
    trailing line, and fleet_watch's --once/--summary/--ledger views fail
    with a clear message (not a traceback) on empty or foreign files.

The ledger is strictly host-side; tests/test_audit.py separately pins
that the engine lowerings are eqn-identical with the ledger on and off.
"""

import json
import os
import sys

import jax
import numpy as np
import pytest

from fleet_shapes import FLEET_B, FLEET_CHUNK, FLEET_SER_KW
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import simulator as S
from librabft_simulator_tpu.telemetry import ledger as tledger
from librabft_simulator_tpu.telemetry import stream as tstream

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "scripts"))
import fleet_watch  # noqa: E402

P_SER = SimParams(max_clock=120, **FLEET_SER_KW)
SEEDS = np.arange(FLEET_B, dtype=np.uint32)


class FakeClock:
    """Deterministic monotonic clock: every read advances by ``tick``."""

    def __init__(self, tick=0.5):
        self.t = 0.0
        self.tick = tick

    def __call__(self):
        t = self.t
        self.t += self.tick
        return t


def test_span_nesting_schema_and_deterministic_clock():
    """(a): seq/parent/depth from the thread stack, attrs preserved, and
    every timestamp an exact function of the injected clock."""
    lg = tledger.RuntimeLedger(clock=FakeClock(1.0))
    # epoch consumed clock tick 0 -> epoch = 0.0
    with lg.span("dispatch", run=1, chunk=0) as outer:
        with lg.span("compile", key="k1") as inner:
            pass
    assert inner.parent == outer.seq
    assert inner.depth == 1 and outer.depth == 0
    assert outer.attrs == {"run": 1, "chunk": 0}
    assert inner.attrs == {"key": "k1"}
    # Clock reads: span t0 (tick 1), inner t0 (tick 2), inner end (3),
    # outer end (4) -> exact offsets from the epoch.
    assert outer.t0_s == 1.0 and inner.t0_s == 2.0
    assert inner.dur_s == 1.0 and outer.dur_s == 3.0
    rows = [sp.to_json() for sp in lg.spans]
    assert [r["name"] for r in rows] == ["compile", "dispatch"]  # close order
    for r in rows:
        assert r["kind"] == "span"
        assert {"seq", "name", "t0_s", "dur_s", "thread", "parent",
                "depth"} <= set(r)


def test_disabled_ledger_times_but_records_nothing():
    lg = tledger.RuntimeLedger(clock=FakeClock(1.0))
    lg.enabled = False
    with lg.span("run") as sp:
        pass
    assert sp.dur_s == 1.0  # callers still read wall time from the span
    assert lg.spans == []


def test_max_spans_drops_instead_of_growing():
    lg = tledger.RuntimeLedger(clock=FakeClock(), max_spans=2)
    for _ in range(4):
        with lg.span("poll", chunk=0):
            pass
    assert len(lg.spans) == 2
    assert lg.dropped == 2


def test_ndjson_stream_and_roundtrip(tmp_path):
    """(b): meta line first, one flushed row per span/compile, a summary
    row on close, and load_ndjson returns exactly what was recorded."""
    path = str(tmp_path / "ledger.ndjson")
    lg = tledger.RuntimeLedger(clock=FakeClock(0.25), out=path,
                               meta={"argv0": "test"})
    rid = lg.new_run("unit", devices=2)
    with lg.span(tledger.DISPATCH, run=rid, chunk=0):
        pass
    with lg.compile_attribution("deadbeef", engine="serial", shapes="(5,)x3"):
        lg.on_event("/jax/compilation_cache/cache_misses")
        lg.on_event_duration(
            "/jax/core/compile/backend_compile_duration", 2.5)
    lg.close()
    meta, rows = tledger.load_ndjson(path)
    assert meta["ledger_version"] == tledger.LEDGER_VERSION
    assert meta["schema"] == "runtime_ledger"
    assert meta["argv0"] == "test"
    kinds = [r["kind"] for r in rows]
    assert kinds == ["run", "span", "span", "compile", "summary"]
    comp = rows[3]
    assert comp["key"] == "deadbeef" and comp["cache"] == "persistent-miss"
    assert comp["compile_s"] == 2.5
    summary = rows[-1]
    assert summary["compile_entries"] == 1
    assert summary["persistent_cache"] == {"hits": 0, "misses": 1}
    assert summary["spans"]["dispatch"]["count"] == 1


def test_perfetto_export_roundtrip(tmp_path):
    """(b): the Chrome-trace export carries every span as a complete ('X')
    event with µs timestamps derived exactly from the ledger clock."""
    lg = tledger.RuntimeLedger(clock=FakeClock(0.5))
    with lg.span(tledger.POLL, run=1, chunk=3):
        pass
    path = str(tmp_path / "trace.json")
    doc = lg.to_perfetto(path)
    with open(path) as f:
        assert json.load(f) == doc
    assert doc["otherData"]["ledger_version"] == tledger.LEDGER_VERSION
    (ev,) = doc["traceEvents"]
    assert ev["ph"] == "X" and ev["cat"] == "librabft_host"
    assert ev["name"] == "poll"
    assert ev["ts"] == 0.5e6 and ev["dur"] == 0.5e6  # µs, from the clock
    assert ev["args"]["chunk"] == 3 and ev["args"]["run"] == 1


def test_compile_ledger_cache_verdicts():
    """(c): hit/miss classification from the monitoring events, fed
    deterministically through the listener entry points."""
    lg = tledger.RuntimeLedger(clock=FakeClock())
    with lg.compile_attribution("k-hit"):
        lg.on_event("/jax/compilation_cache/cache_hits")
        lg.on_event_duration(
            "/jax/core/compile/backend_compile_duration", 0.1)
    with lg.compile_attribution("k-miss"):
        lg.on_event("/jax/compilation_cache/cache_misses")
        lg.on_event_duration(
            "/jax/core/compile/backend_compile_duration", 4.0)
    with lg.compile_attribution("k-uncached"):
        lg.on_event_duration(
            "/jax/core/compile/backend_compile_duration", 1.0)
    with lg.compile_attribution("k-memory"):
        pass  # no compile events at all: in-process executable reuse
    verdicts = {e["key"]: e["cache"] for e in lg.compiles}
    assert verdicts == {"k-hit": "persistent-hit",
                       "k-miss": "persistent-miss",
                       "k-uncached": "uncached",
                       "k-memory": "memory"}
    # Events fired OUTSIDE any attribution context tally, not vanish.
    lg.on_event_duration("/jax/core/compile/backend_compile_duration", 0.5)
    tally = lg.unattributed["/jax/core/compile/backend_compile_duration"]
    assert tally[0] == 1 and tally[1] == 0.5


def test_wrap_compile_records_real_engine_build():
    """(c): building + calling a real engine executable through
    make_run_fn lands exactly one attributed compile-ledger entry per
    (structural key, shapes), on the process ledger."""
    lg = tledger.get()
    before = len(lg.compiles)
    st = S.dedupe_buffers(S.init_batch(P_SER, SEEDS))
    run = S.make_run_fn(P_SER, FLEET_CHUNK)
    st = run(st)
    entries = lg.compiles[before:]
    if not entries:
        # Another test in this session already built this executable and
        # claimed the (key, shapes) token — the dedup IS the contract.
        ps = S.xops.resolve_params(P_SER).structural()
        key = tledger.params_key(ps)
        entries = [e for e in lg.compiles if e["key"] == key]
    assert entries, "no compile-ledger entry for the engine executable"
    e = entries[0]
    assert e["engine"] == "serial"
    # aot-* verdicts appear when the AOT executable store (utils/aot.py)
    # served or exported this shape — tests/test_aot.py pins their exact
    # semantics; here any classified verdict proves the attribution.
    assert e["cache"] in ("persistent-hit", "persistent-miss", "uncached",
                          "memory", "stale-toolchain",
                          "aot-hit", "aot-stale", "aot-export")
    assert e["shapes"].startswith(f"({FLEET_B},")
    assert "structural" in e and "n_nodes=3" in e["structural"]
    # A second call of the same executable records nothing new.
    n = len(lg.compiles)
    run(st)
    assert len(lg.compiles) == n


def _span_row(name, run, chunk, t0, dur):
    return {"kind": "span", "name": name, "run": run, "chunk": chunk,
            "t0_s": t0, "dur_s": dur, "thread": 1, "parent": None,
            "depth": 0, "seq": 0}


def test_pipeline_stats_overlap_bubbles_ttfc():
    """(d): the measured quantities, on spans with known values.  Chunk 0
    (cold) is excluded from steady-state aggregates; overlap is
    poll/(poll+dispatch); a sub-floor poll flags a bubble; ttfc spans
    first dispatch start to first poll end."""
    rows = [
        _span_row("dispatch", 7, 0, 0.0, 4.0),     # cold: compile-laden
        _span_row("poll", 7, 0, 4.0, 1.0),         # ttfc = 5.0
        _span_row("dispatch", 7, 1, 5.0, 0.1),
        _span_row("poll", 7, 1, 5.1, 0.9),         # overlapped wait
        _span_row("dispatch", 7, 2, 6.0, 0.3),
        _span_row("poll", 7, 2, 6.3, 0.00001),     # bubble: already done
        # A different run id must not leak into run 7's stats.
        _span_row("dispatch", 8, 1, 9.0, 5.0),
    ]
    out = tledger.pipeline_stats(rows, run=7)
    assert out["run"] == 7 and out["chunks"] == 3
    assert out["time_to_first_chunk_s"] == 5.0
    assert out["dispatch_s"] == pytest.approx(0.4)
    assert out["poll_s"] == pytest.approx(0.90001)
    assert out["overlap_fraction"] == pytest.approx(0.9 / 1.3, abs=0.01)
    assert out["bubbles"] == [2] and out["bubble_count"] == 1
    # run=None picks the LAST run id present.
    assert tledger.pipeline_stats(rows)["run"] == 8


def test_ring_stats_oracle_rows():
    """(d) ring twin: retired/cap attrs on the outer-call POLL spans feed
    the amortization math — full vs early-exit classification, the
    polls-per-retired-chunk headline, None on a host-wrap ledger (no
    ``retired`` attr anywhere), and run selection (last id wins)."""
    def ring_row(run, chunk, retired, cap, t0):
        return dict(_span_row("poll", run, chunk, t0, 0.5),
                    retired=retired, cap=cap)
    rows = [
        _span_row("dispatch", 3, 0, 0.0, 0.1),   # no retired attr: ignored
        ring_row(3, 0, 4, 4, 0.1),               # full budget
        ring_row(3, 1, 4, 4, 0.7),               # full budget
        ring_row(3, 2, 2, 4, 1.3),               # early exit: fleet halted
        ring_row(4, 0, 1, 4, 9.0),               # later run must win run=None
    ]
    out = tledger.ring_stats(rows, run=3)
    assert out["run"] == 3 and out["dispatches"] == 3
    assert out["retired_chunks"] == 10
    assert out["retired_per_dispatch"] == pytest.approx(10 / 3, abs=1e-3)
    assert out["polls_per_retired_chunk"] == pytest.approx(0.3, abs=1e-3)
    assert out["ring_full"] == 2 and out["early_exit"] == 1
    assert tledger.ring_stats(rows)["run"] == 4
    host_rows = [_span_row("poll", 3, 0, 0.0, 0.5)]
    assert tledger.ring_stats(host_rows) is None


def test_run_sharded_records_chunk_spans():
    """(d): the fleet runtime's per-chunk dispatch-enqueue vs poll spans
    land on the process ledger (the warmed 2-shard micro-fleet shape),
    and the overlap/ttfc computation runs on them."""
    from librabft_simulator_tpu.parallel import mesh as mesh_ops
    from librabft_simulator_tpu.parallel import sharded

    assert len(jax.devices()) >= 2, "conftest must force 8 CPU devices"
    lg = tledger.get()
    mesh2 = mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])
    st = S.init_batch(P_SER, SEEDS)
    st = sharded.run_sharded(P_SER, mesh2, st,
                             num_steps=FLEET_CHUNK * 200, chunk=FLEET_CHUNK)
    pipe = lg.pipeline_stats()  # the last run recorded = this one
    assert pipe["chunks"] >= 1
    assert pipe["time_to_first_chunk_s"] > 0
    rows = pipe["rows"]
    assert rows[0]["chunk"] == 0 and rows[0]["dispatch_s"] > 0
    assert all(r["poll_s"] > 0 for r in rows), "every chunk is polled once"
    if pipe["overlap_fraction"] is not None:
        assert 0.0 <= pipe["overlap_fraction"] <= 1.0
    # The sharded executable itself is in the compile ledger.
    assert any(e["engine"].startswith("sharded/") for e in lg.compiles)
    # host_merge span from the padded unpad landing.
    assert "host_merge" in lg.span_totals()


def test_stream_ndjson_tolerates_midwrite_tail(tmp_path):
    """(e): a partially-written trailing line (live writer mid-flush, or
    a timeout-killed process) is skipped by both readers; corruption
    anywhere else still raises."""
    path = tmp_path / "mid.ndjson"
    meta = {"kind": "meta", "registry_version": tstream.REGISTRY_VERSION}
    row = {"kind": "row", "halted": 3, "t_s": 1.0}
    path.write_text(json.dumps(meta) + "\n" + json.dumps(row) + "\n"
                    + '{"kind": "row", "halt')  # torn mid-write
    loaded_meta, rows = tstream.load_ndjson(str(path))
    assert loaded_meta["registry_version"] == tstream.REGISTRY_VERSION
    assert rows == [row]
    # Corrupt NON-final line = damage, not liveness: still an error.
    bad = tmp_path / "bad.ndjson"
    bad.write_text('{"kind": "me\n' + json.dumps(row) + "\n")
    with pytest.raises(ValueError):
        tstream.load_ndjson(str(bad))


def test_fleet_watch_hardened_on_empty_and_foreign(tmp_path, capsys):
    """(e): --once/--summary/--ledger on empty or foreign files exit 1
    with a message — never a traceback."""
    empty = tmp_path / "empty.ndjson"
    empty.write_text("")
    for flags in (["--once"], ["--summary"], ["--ledger"]):
        assert fleet_watch.main([str(empty)] + flags) == 1
        assert capsys.readouterr().err.strip()
    missing = str(tmp_path / "nope.ndjson")
    assert fleet_watch.main([missing, "--once"]) == 1
    # A digest stream fed to --ledger is refused with a pointer, not
    # misparsed.
    stream_file = tmp_path / "stream.ndjson"
    stream_file.write_text(json.dumps(
        {"kind": "meta", "registry_version": tstream.REGISTRY_VERSION})
        + "\n")
    assert fleet_watch.main([str(stream_file), "--ledger"]) == 1
    assert "ledger" in capsys.readouterr().err


def test_fleet_watch_ledger_view(tmp_path, capsys):
    """The --ledger view renders per-chunk dispatch/poll timing, the
    overlap headline, bubbles, and the compile ledger from a streamed
    file."""
    path = str(tmp_path / "ledger.ndjson")
    lg = tledger.RuntimeLedger(clock=FakeClock(0.05), out=path)
    rid = lg.new_run("run_sharded", devices=2, pipeline=True)
    for chunk in range(3):
        with lg.span(tledger.DISPATCH, run=rid, chunk=chunk):
            pass
        with lg.span(tledger.POLL, run=rid, chunk=chunk):
            pass
    # A device-wrap run: outer-call polls carry retired/cap, and the
    # view grows the ring amortization line for it.
    rid2 = lg.new_run("run_sharded", devices=2, pipeline=False, ring_k=4)
    with lg.span(tledger.DISPATCH, run=rid2, chunk=0):
        pass
    with lg.span(tledger.POLL, run=rid2, chunk=0, retired=4, cap=4):
        pass
    with lg.span(tledger.POLL, run=rid2, chunk=1, retired=2, cap=4):
        pass
    with lg.compile_attribution("abc123", engine="serial", shapes="(5,)x3"):
        lg.on_event("/jax/compilation_cache/cache_hits")
    lg.close()
    assert fleet_watch.main([path, "--ledger"]) == 0
    out = capsys.readouterr().out
    assert "run 1 (run_sharded)" in out
    assert "overlap=" in out and "time_to_first_chunk=" in out
    assert "cold (compile)" in out
    assert "abc123" in out and "persistent-hit" in out
    assert "# ring: dispatches=2 retired_chunks=6" in out
    assert "polls_per_retired_chunk=0.3333" in out
    assert "ring_full=1 early_exit=1" in out


def test_attribution_cli(tmp_path, capsys):
    """The ci_tier1.sh consumer: python -m ...ledger --attribution
    summarizes a streamed file into the compile-vs-run block (and
    re-exports Perfetto)."""
    path = str(tmp_path / "ledger.ndjson")
    lg = tledger.RuntimeLedger(clock=FakeClock(0.1), out=path)
    rid = lg.new_run("run_sharded", pipeline=True)
    with lg.compile_attribution("feed00", engine="serial", shapes="(5,)x3"):
        lg.on_event("/jax/compilation_cache/cache_misses")
        lg.on_event_duration(
            "/jax/core/compile/backend_compile_duration", 3.0)
    with lg.span(tledger.DISPATCH, run=rid, chunk=0):
        pass
    with lg.span(tledger.POLL, run=rid, chunk=0):
        pass
    lg.close()
    out_json = str(tmp_path / "attr.json")
    perfetto = str(tmp_path / "trace.json")
    assert tledger.main(["--attribution", path, "--out", out_json,
                         "--perfetto", perfetto]) == 0
    capsys.readouterr()
    with open(out_json) as f:
        a = json.load(f)
    assert a["compile"]["entries"] == 1
    assert a["compile"]["compile_s"] == 3.0
    assert a["compile"]["persistent_cache"] == {"hits": 0, "misses": 1}
    assert a["compile"]["top"][0]["key"] == "feed00"
    assert a["compile_vs_run"]["compile_s"] == 3.0
    assert a["pipeline"]["chunks"] == 1
    with open(perfetto) as f:
        trace = json.load(f)
    # compile span + dispatch + poll all exported.
    assert {e["name"] for e in trace["traceEvents"]} == {
        "compile", "dispatch", "poll"}
    # A foreign/non-ledger file is a clear rc=1, not a stack trace.
    foreign = str(tmp_path / "foreign.ndjson")
    with open(foreign, "w") as f:
        f.write(json.dumps({"kind": "meta"}) + "\n")
    assert tledger.main(["--attribution", foreign]) == 1
    capsys.readouterr()
    # A ledger whose only chunked loop is NOT double-buffered must omit
    # the pipeline block: a serial completion loop polls the chunk it
    # just dispatched, so its ~1.0 overlap would be a lie.
    serial = str(tmp_path / "serial.ndjson")
    lg2 = tledger.RuntimeLedger(clock=FakeClock(0.1), out=serial)
    rid2 = lg2.new_run("run_to_completion", engine="serial")
    with lg2.span(tledger.DISPATCH, run=rid2, chunk=0):
        pass
    with lg2.span(tledger.POLL, run=rid2, chunk=0):
        pass
    lg2.close()
    out2 = str(tmp_path / "attr2.json")
    assert tledger.main(["--attribution", serial, "--out", out2]) == 0
    capsys.readouterr()
    with open(out2) as f:
        assert "pipeline" not in json.load(f)


def test_run_seconds_no_double_count():
    """compile_vs_run accounting: compile time nested inside a dispatch
    span is NOT run time, and a RUN section counts only its exclusive
    time over its recorded dispatch/poll children."""
    lg = tledger.RuntimeLedger(clock=FakeClock(1.0))
    # A RUN section containing one dispatch whose first call compiles,
    # plus one poll.  FakeClock(1.0): every clock read advances 1 s.
    with lg.span(tledger.RUN, what="section"):          # t0=1
        with lg.span(tledger.DISPATCH, chunk=0):        # t0=2
            with lg.span(tledger.COMPILE, key="k"):     # t0=3, end=4 -> 1s
                pass
        with lg.span(tledger.POLL, chunk=0):            # t0=6, end=7 -> 1s
            pass
    rows = [sp.to_json() for sp in lg.spans]
    # dispatch dur = 5-2 = 3 (contains the 1 s compile), poll = 1,
    # run dur = 8-1 = 7 (contains dispatch 3 + poll 1 -> exclusive 3).
    # run_s = (3 + 1 - 1 nested compile) + 3 exclusive = 6, NOT the
    # naive 3+1+7 = 11.
    assert tledger._run_seconds(rows) == pytest.approx(6.0)
