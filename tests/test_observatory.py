"""Fleet-observatory referees (telemetry/observatory.py + schema.py +
scripts/perf_sentinel.py): the round-18 observability layer.

Four contract families:

(a) **Ingest round-trips** — every NDJSON family the repo writes (fleet
    digest stream, per-host ``.p<pid>`` streams, serve stream with
    request rows, runtime ledger) lands in ONE store with correct
    stream/host tags, the original loaders' byte-identical version
    refusals, and tolerance for a torn FINAL line only.
(b) **Rollups** — windowed counter deltas re-fold to exactly the raw
    digest series (hand-folded oracle), on synthetic rows and on a real
    seeded 2-process local_cluster run.
(c) **Cross-host trace merge** — handshake-anchored clock offsets make
    per-host span orderings monotone on one merged Perfetto timeline
    (synthetic two-host ledgers with a known skew, and the real
    cluster's ledgers).
(d) **Perf sentinel gate** — the regression gate stays quiet while
    seeding (<3 rows), fires on a seeded 3x slowdown, and is green again
    on an honest re-run; plus the zero-traced-ops inertness pin for the
    whole layer.

The cluster test rides the warmed /tmp/librabft_aot_dist store like
tests/test_distributed.py (first-ever run pays the export compiles).
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest
import jax

from librabft_simulator_tpu.audit import graph_lint as GL
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.distributed import bootstrap
from librabft_simulator_tpu.sim import parallel_sim as PE
from librabft_simulator_tpu.sim import simulator as S
from librabft_simulator_tpu.telemetry import ledger as tledger
from librabft_simulator_tpu.telemetry import observatory as tobs
from librabft_simulator_tpu.telemetry import schema as tschema
from librabft_simulator_tpu.telemetry import stream as tstream

from fleet_shapes import FLEET_B, FLEET_CHUNK, FLEET_SER_KW

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SENTINEL = os.path.join(REPO, "scripts", "perf_sentinel.py")
FLEET_WATCH = os.path.join(REPO, "scripts", "fleet_watch.py")

#: The cluster children's AOT store (tests/test_distributed.py twin).
DIST_AOT = {"LIBRABFT_AOT_DIR": "/tmp/librabft_aot_dist",
            "LIBRABFT_AOT_WRITE": "1"}


def _load_sentinel():
    spec = importlib.util.spec_from_file_location("perf_sentinel", SENTINEL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# Synthetic stream writers.
# ---------------------------------------------------------------------------


def _write_fleet_stream(path, rows, meta_extra=None, version=None):
    meta = {"kind": "meta",
            "registry_version": tschema.REGISTRY_VERSION
            if version is None else version,
            "digest_slots": [n for n, _ in tschema.DIGEST_SLOTS],
            "n_nodes": 3, "watchdog": False, "total_instances": FLEET_B}
    meta.update(meta_extra or {})
    with open(path, "w") as f:
        f.write(json.dumps(meta) + "\n")
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return path


def _digest_row(chunk, t_s, events, halted=0, commits=0, qmax=1,
                crmin=0, crmax=0):
    return {"kind": "row", "chunk": chunk, "t_s": t_s, "events": events,
            "halted": halted, "commits": commits, "drops": 0,
            "overflow": 0, "queue_depth_max": qmax,
            "committed_round_min": crmin, "committed_round_max": crmax,
            "wd_stall": 0, "wd_queue_sat": 0, "wd_sync_jump": 0,
            "wd_safety_conflict": 0, "wd_round_regress": 0}


def _write_ledger(path, pid, handshake_end, spans):
    """A synthetic per-host runtime ledger: meta + handshake + spans.
    ``spans`` = [(name, t0, dur, attrs)] on the host's LOCAL clock."""
    rows = [{"kind": "meta", "schema": "runtime_ledger",
             "ledger_version": tschema.LEDGER_VERSION},
            {"kind": "span", "name": tledger.HANDSHAKE,
             "t0_s": handshake_end - 0.1, "dur_s": 0.1, "thread": 0,
             "process_id": pid, "process_count": 2}]
    for name, t0, dur, attrs in spans:
        rows.append(dict({"kind": "span", "name": name, "t0_s": t0,
                          "dur_s": dur, "thread": 0}, **attrs))
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return path


# ---------------------------------------------------------------------------
# (a) ingest round-trips + refusals.
# ---------------------------------------------------------------------------


def test_schema_is_the_single_source():
    """The writers' public constants ARE the schema table (hoist, not
    copies), and stream.py's slot registry re-exports it."""
    assert tstream.REGISTRY_VERSION is tschema.REGISTRY_VERSION
    assert tledger.LEDGER_VERSION is tschema.LEDGER_VERSION
    assert tstream.DIGEST_SLOTS is tschema.DIGEST_SLOTS
    assert tstream.DIGEST_WIDTH == tschema.DIGEST_WIDTH == 13
    assert tstream.WD_DETECTORS is tschema.WD_DETECTORS
    # Every serialized family is versioned, including the bench history.
    assert set(tschema.VERSIONS) == {"fleet_stream", "runtime_ledger",
                                     "serve_state", "bench_history"}


def test_version_refusals_byte_identical():
    """The hoisted refusal messages are the legacy loaders' exact
    phrasings — downstream tooling greps for them."""
    with pytest.raises(ValueError, match="slot-registry version 99 does "
                                         "not match this build's v1"):
        tschema.require_registry_version(99, what="x")
    with pytest.raises(ValueError,
                       match="ledger_version 7 does not match this "
                             "build's v1"):
        tschema.require_ledger_version(7, what="y")
    with pytest.raises(ValueError,
                       match=r"serve_version 3 != 1 \(foreign artifact\)"):
        tschema.require_serve_version(3, what="z")


def test_ingest_round_trip_all_kinds(tmp_path):
    """One store over a fleet stream, a per-host serve stream, and a
    ledger: rows keep every original field plus the _stream/_host/_path
    tags; queries filter across sources."""
    fleet = _write_fleet_stream(
        str(tmp_path / "fleet.ndjson"),
        [_digest_row(0, 0.1, 10), _digest_row(1, 0.4, 30)])
    serve_rows = [_digest_row(0, 0.2, 5),
                  {"kind": "request", "event": "submitted", "id": "r0",
                   "t_s": 0.05, "slot": None, "status": "pending",
                   "ttfc_s": None, "pending": 1, "active": 0,
                   "egressed": 0},
                  {"kind": "request", "event": "admitted", "id": "r0",
                   "t_s": 0.15, "slot": 2, "status": "active",
                   "ttfc_s": None, "pending": 0, "active": 1,
                   "egressed": 0}]
    serve = _write_fleet_stream(str(tmp_path / "serve.p1.ndjson"),
                                serve_rows, meta_extra={"serve": True})
    ledger = _write_ledger(str(tmp_path / "ledger-p0.ndjson"), 0, 0.5,
                           [(tledger.DISPATCH, 0.6, 0.05,
                             {"run": 1, "chunk": 0})])

    obs = tobs.from_paths([fleet, serve, ledger])
    assert obs.hosts() == ["p0", "p1"]
    assert {s["stream"] for s in obs.sources} == \
        {tobs.FLEET, tobs.SERVE, tobs.LEDGER}
    # sniff dispatched each file to the right family.
    assert tobs.sniff(fleet) == tobs.FLEET
    assert tobs.sniff(serve) == tobs.SERVE
    assert tobs.sniff(ledger) == tobs.LEDGER
    # Round-trip: stored rows == written rows (plus tags).
    frows = obs.select(stream=tobs.FLEET, kind="row")
    assert [r["events"] for r in frows] == [10, 30]
    assert all(r["_host"] == "p0" and r["_path"] == fleet for r in frows)
    # The serve stream's host came from the .p1 filename convention.
    reqs = obs.requests()
    assert list(reqs) == ["r0"]
    assert [e["event"] for e in reqs["r0"]] == ["submitted", "admitted"]
    assert reqs["r0"][0]["_host"] == "p1"
    # Ledger spans visible through the same store.
    spans = obs.select(stream=tobs.LEDGER, kind="span", run=1)
    assert len(spans) == 1 and spans[0]["name"] == tledger.DISPATCH
    # Time-bounded select uses each row's native timestamp.
    assert [r["events"] for r in obs.select(kind="row", since=0.15,
                                            until=0.45)] == [30, 5]
    # final_digest picks the LAST digest row across fleet+serve streams.
    assert obs.final_digest()["events"] == 30


def test_ingest_refuses_foreign_and_meta_less(tmp_path):
    foreign = _write_fleet_stream(str(tmp_path / "foreign.ndjson"),
                                  [_digest_row(0, 0.1, 1)], version=99)
    with pytest.raises(ValueError, match="slot-registry version 99"):
        tobs.Observatory().ingest(foreign)
    bare = str(tmp_path / "bare.ndjson")
    with open(bare, "w") as f:
        f.write(json.dumps({"kind": "row", "events": 1}) + "\n")
    with pytest.raises(ValueError, match="has no meta line"):
        tobs.Observatory().ingest(bare)
    with pytest.raises(ValueError, match="matched no files"):
        tobs.Observatory().ingest_glob(str(tmp_path / "nope*.ndjson"))


def test_torn_final_line_tolerated_corrupt_midfile_refused(tmp_path):
    """The crash-mid-write contract, through the ONE shared loader: a
    torn FINAL line is the reader racing the writer (ignored); a corrupt
    MID-file line is real corruption (loud)."""
    path = _write_fleet_stream(str(tmp_path / "torn.ndjson"),
                               [_digest_row(0, 0.1, 10)])
    with open(path, "a") as f:
        f.write('{"kind": "row", "chunk": 1, "ev')  # torn final line
    obs = tobs.Observatory()
    obs.ingest(path)
    assert len(obs.select(kind="row")) == 1
    # stream.load_ndjson delegates to the same loader -> same tolerance.
    meta, rows = tstream.load_ndjson(path)
    assert len(rows) == 1

    corrupt = str(tmp_path / "corrupt.ndjson")
    with open(path) as f:
        good = f.read()
    with open(corrupt, "w") as f:
        f.write(good.splitlines()[0] + "\n")
        f.write("NOT JSON\n")
        f.write(json.dumps(_digest_row(1, 0.2, 20)) + "\n")
    with pytest.raises(ValueError):  # json.JSONDecodeError, not tolerated
        tobs.Observatory().ingest(corrupt)


# ---------------------------------------------------------------------------
# (b) rollups == hand-folded digests.
# ---------------------------------------------------------------------------


def test_rollup_hand_folded_synthetic(tmp_path):
    """Window deltas on a synthetic digest series with known cumulative
    counters: deltas re-fold to the raw series, gauges fold by their
    registered kind, empty windows are omitted."""
    rows = [_digest_row(0, 0.2, 10, halted=0, qmax=4, crmin=1, crmax=2),
            _digest_row(1, 0.7, 25, halted=1, qmax=2, crmin=0, crmax=5),
            # window [1,2) empty — chunk 2 lands in [2,3)
            _digest_row(2, 2.3, 60, halted=3, qmax=9, crmin=2, crmax=7)]
    path = _write_fleet_stream(str(tmp_path / "fleet.ndjson"), rows)
    obs = tobs.from_paths([path], window_s=1.0)
    roll = obs.rollup()
    assert [w["t0_s"] for w in roll] == [0.0, 2.0]  # empty window omitted
    # Counter deltas: window 0 saw 0->25 cumulative, window 1 25->60.
    assert [w["events"] for w in roll] == [25, 35]
    # Hand-fold oracle: deltas re-accumulate to the final cumulative.
    assert sum(w["events"] for w in roll) == rows[-1]["events"]
    # Gauges: max over window rows; min over window rows; halted last.
    assert roll[0]["queue_depth_max"] == 4 and roll[1]["queue_depth_max"] == 9
    assert roll[0]["committed_round_min"] == 0
    assert roll[0]["committed_round_max"] == 5
    assert [w["halted"] for w in roll] == [1, 3]
    assert all(w["rows"] > 0 for w in roll)


def test_rollup_ring_batched_rows_hand_folded(tmp_path):
    """Ring-batched digest rows (wrap="device": K cumulative rows under
    ONE host poll timestamp, stream.TimelineRecorder.record_ring) must
    window to the SUM of the K per-chunk deltas — never collapse into one
    poll's worth — with retirement order kept at equal t_s and the
    ring_rows marker on windows that saw a batch."""
    # One host-wrap chunk, then a K=3 ring batch all polled at t=1.4, then
    # one more host-wrap chunk.  Counters are TRUE cumulatives per chunk.
    ring_t = 1.4
    rows = [_digest_row(0, 0.3, 10, commits=2),
            dict(_digest_row(1, ring_t, 25, commits=4, halted=1),
                 ring_i=0, ring_n=3),
            dict(_digest_row(2, ring_t, 45, commits=7, halted=2),
                 ring_i=1, ring_n=3),
            dict(_digest_row(3, ring_t, 50, commits=9, halted=3),
                 ring_i=2, ring_n=3),
            _digest_row(4, 2.6, 70, commits=11, halted=5)]
    path = _write_fleet_stream(str(tmp_path / "ring.ndjson"), rows)
    obs = tobs.from_paths([path], window_s=1.0)
    roll = obs.rollup()
    assert [w["t0_s"] for w in roll] == [0.0, 1.0, 2.0]
    # Window 1 holds the whole ring batch: its events delta is the SUM of
    # the three per-chunk deltas (15+20+5), not one chunk's 15.
    assert [w["events"] for w in roll] == [10, 40, 20]
    assert [w["commits"] for w in roll] == [2, 7, 2]
    # Hand-fold oracle: deltas re-accumulate to the final cumulative.
    assert sum(w["events"] for w in roll) == rows[-1]["events"]
    assert sum(w["commits"] for w in roll) == rows[-1]["commits"]
    # halted is a gauge: the LAST ring row in retirement order wins (the
    # (t_s, chunk) sort keeps order at the shared timestamp).
    assert [w["halted"] for w in roll] == [0, 3, 5]
    # Ring provenance: only the batch window carries the marker.
    assert "ring_rows" not in roll[0]
    assert roll[1]["ring_rows"] == 3
    assert "ring_rows" not in roll[2]
    # series() exposes ALL K ring rows, not one per poll timestamp.
    ser = obs.series("events")
    assert [v for _, v in ser] == [10, 25, 45, 50, 70]
    assert sum(1 for t, _ in ser if t == ring_t) == 3


def test_rollup_window_env_knob(tmp_path, monkeypatch):
    path = _write_fleet_stream(
        str(tmp_path / "fleet.ndjson"),
        [_digest_row(0, 0.2, 10), _digest_row(1, 0.3, 20)])
    monkeypatch.setenv(tobs.WINDOW_ENV, "0.25")
    roll = tobs.from_paths([path]).rollup()
    assert len(roll) == 2 and roll[1]["t0_s"] == 0.25
    assert [w["events"] for w in roll] == [10, 10]


def test_histogram_matches_quantile_tables():
    from librabft_simulator_tpu.utils import quantile
    h = tobs.Observatory.histogram([1, 1, 3, 200])
    assert sum(h["counts"]) == 4
    counts = np.zeros(quantile.HIST_BUCKETS, dtype=np.int64)
    np.add.at(counts, quantile.bucket_np(np.array([1, 1, 3, 200])), 1)
    assert h["counts"] == [int(c) for c in counts]
    assert h["p50_bounds"] == list(quantile.histogram_quantile(counts, .5))
    assert h["p99_bounds"] == list(quantile.histogram_quantile(counts, .99))


# ---------------------------------------------------------------------------
# (c) cross-host trace merge (synthetic skew oracle).
# ---------------------------------------------------------------------------


def test_clock_offsets_and_monotone_merge(tmp_path):
    """Two hosts whose ledger epochs differ by a KNOWN 0.2 s skew: the
    handshake anchor recovers it exactly, and the merged timeline puts
    simultaneous work at the same merged timestamp, per-host order
    monotone."""
    # Host p0's clock: handshake ends 0.5; dispatch at 0.6.
    # Host p1 started 0.2 s later, so the SAME instants read 0.2 less.
    lp0 = _write_ledger(str(tmp_path / "ledger-p0.ndjson"), 0, 0.5,
                        [(tledger.DISPATCH, 0.6, 0.05,
                          {"run": 1, "chunk": 0}),
                         (tledger.POLL, 0.66, 0.02,
                          {"run": 1, "chunk": 0})])
    lp1 = _write_ledger(str(tmp_path / "ledger-p1.ndjson"), 1, 0.3,
                        [(tledger.DISPATCH, 0.4, 0.05,
                          {"run": 1, "chunk": 0}),
                         (tledger.POLL, 0.46, 0.02,
                          {"run": 1, "chunk": 0})])
    obs = tobs.from_paths([lp0, lp1])
    offs = obs.clock_offsets()
    assert offs["p0"] == 0.0
    assert abs(offs["p1"] - 0.2) < 1e-9

    doc = obs.merged_perfetto(str(tmp_path / "merged.json"))
    with open(tmp_path / "merged.json") as f:
        assert json.load(f)["otherData"]["hosts"] == ["p0", "p1"]
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {m["args"]["name"] for m in metas} == {"host p0", "host p1"}
    # Clock-aligned: both hosts' dispatches land at the same merged ts.
    disp = {e["pid"]: e["ts"] for e in xs
            if e["name"] == tledger.DISPATCH}
    assert disp[0] == disp[1] == pytest.approx(0.6 * 1e6)
    # Monotone per-host ordering survives the shift.
    for pid in (0, 1):
        ts = [e["ts"] for e in xs if e["pid"] == pid]
        assert ts == sorted(ts)
    assert doc["otherData"]["clock_offsets_s"]["p1"] == \
        pytest.approx(0.2)


def test_fleet_watch_timeline_cli_jax_free(tmp_path):
    """scripts/fleet_watch.py --timeline writes the merged Perfetto doc
    from per-host ledgers WITHOUT importing jax (the pod-monitor
    contract), and fails loud on a glob with no ledger streams."""
    _write_ledger(str(tmp_path / "ledger-p0.ndjson"), 0, 0.5,
                  [(tledger.DISPATCH, 0.6, 0.05, {"run": 1, "chunk": 0})])
    _write_ledger(str(tmp_path / "ledger-p1.ndjson"), 1, 0.3,
                  [(tledger.DISPATCH, 0.4, 0.05, {"run": 1, "chunk": 0})])
    out = str(tmp_path / "timeline.json")
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-c",
         "import builtins, runpy, sys\n"
         "real = builtins.__import__\n"
         "def guard(name, *a, **k):\n"
         "    assert not name.startswith('jax'), 'jax imported: ' + name\n"
         "    return real(name, *a, **k)\n"
         "builtins.__import__ = guard\n"
         f"sys.argv = ['fleet_watch.py', {str(tmp_path / 'ledger-p*.ndjson')!r},"
         f" '--timeline', '--out', {out!r}]\n"
         f"runpy.run_path({FLEET_WATCH!r}, run_name='__main__')\n"],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    with open(out) as f:
        doc = json.load(f)
    assert doc["otherData"]["clock_offsets_s"]["p1"] == pytest.approx(0.2)
    assert any(e["ph"] == "X" for e in doc["traceEvents"])

    r2 = subprocess.run([sys.executable, FLEET_WATCH,
                         str(tmp_path / "none-p*.ndjson"), "--timeline"],
                        capture_output=True, text=True, env=env)
    assert r2.returncode != 0


# ---------------------------------------------------------------------------
# (c') the real thing: seeded 2-process cluster -> one merged timeline,
# rollups vs the raw per-host streams.
# ---------------------------------------------------------------------------


def test_cluster_observatory_end_to_end(tmp_path):
    """ACCEPTANCE: a seeded 2-process local_cluster fleet run yields ONE
    merged Perfetto trace with a handshake-anchored offset for every
    host and monotone per-host span ordering; the observatory's rollups
    over the per-host digest streams re-fold exactly to each stream's
    raw cumulative series."""
    if len(jax.devices()) < 2:
        pytest.skip("needs virtual devices (conftest sets 8)")
    out_dir = str(tmp_path / "out")
    work = str(tmp_path / "cluster")
    bootstrap.local_cluster(
        2, "librabft_simulator_tpu.distributed.workers:fleet_run",
        {"params_kw": dict(FLEET_SER_KW, max_clock=120),
         "engine": "serial", "b": FLEET_B, "chunk": FLEET_CHUNK,
         "out_dir": out_dir},
        timeout_s=900, workdir=work, ledger=True, env_extra=DIST_AOT)

    obs = tobs.Observatory()
    obs.ingest_glob(os.path.join(out_dir, "fleet.p*.ndjson"))
    obs.ingest_glob(os.path.join(work, "ledger-p*.ndjson"))
    assert obs.hosts() == ["p0", "p1"]

    # Every host recorded the handshake -> a real (finite) offset each,
    # reference host pinned to 0.
    offs = obs.clock_offsets()
    assert set(offs) == {"p0", "p1"} and offs["p0"] == 0.0
    handshakes = [e for e in obs.select(stream=tobs.LEDGER, kind="span")
                  if e.get("name") == tledger.HANDSHAKE]
    assert {e["_host"] for e in handshakes} == {"p0", "p1"}
    # Aligned handshake ENDS: the merge's anchor property, on real data.
    ends = {e["_host"]: e["t0_s"] + e["dur_s"] + offs[e["_host"]]
            for e in handshakes}
    assert abs(ends["p0"] - ends["p1"]) < 1e-6

    doc = obs.merged_perfetto(str(tmp_path / "merged.json"))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert xs, "cluster ledgers produced no spans"
    # Monotone per-host ordering on the MERGED clock: chunk i's dispatch
    # starts before chunk i+1's, per host per run (spans are emitted at
    # exit so raw file order proves nothing — the timeline must).
    for host in ("p0", "p1"):
        pid = int(host[1:])
        disp = [e for e in xs if e["pid"] == pid
                and e["name"] == tledger.DISPATCH
                and "chunk" in e["args"]]
        assert disp, f"host {host} dispatched no chunks"
        by_run: dict = {}
        for e in disp:
            by_run.setdefault(e["args"].get("run"), []).append(e)
        for run, evs in by_run.items():
            evs.sort(key=lambda e: e["args"]["chunk"])
            ts = [e["ts"] for e in evs]
            assert ts == sorted(ts), \
                f"host {host} run {run} dispatch order not monotone"
        # And every dispatch happens after the cluster handshake anchor.
        anchor = ends[host] * 1e6
        assert min(e["ts"] for e in disp) >= anchor - 1e3
    # Rollups vs the hand-folded raw stream, per host.
    for host in ("p0", "p1"):
        raw = sorted((r for r in obs.select(kind="row", host=host)),
                     key=lambda r: r["t_s"])
        assert raw, f"host {host} streamed no digest rows"
        roll = obs.rollup(window_s=0.05, host=host)
        for name in sorted(tschema.COUNTER_SLOTS):
            assert sum(w.get(name, 0) for w in roll) == raw[-1][name], name
        assert roll[-1]["halted"] == raw[-1]["halted"]
        hand_max = max(r["queue_depth_max"] for r in raw)
        assert max(w["queue_depth_max"] for w in roll
                   if "queue_depth_max" in w) == hand_max
    # The digest is mesh-reduced: both hosts' final digests agree.
    assert obs.final_digest(host="p0") == obs.final_digest(host="p1")


# ---------------------------------------------------------------------------
# (d) the perf sentinel's gate + the layer's inertness pin.
# ---------------------------------------------------------------------------


FIXED_SAMPLES = {
    "serial_step": [1000.0, 1010.0, 990.0],
    "aot_ttfc": [2.0],
}


def _run_sentinel(ps, monkeypatch, out, slowdown=None):
    monkeypatch.setattr(
        ps, "_collect_samples",
        lambda rungs, reps: {n: FIXED_SAMPLES[n] for n in rungs})
    monkeypatch.setenv(ps.RUNGS_ENV, "serial_step,aot_ttfc")
    if slowdown is None:
        monkeypatch.delenv(ps.SLOWDOWN_ENV, raising=False)
    else:
        monkeypatch.setenv(ps.SLOWDOWN_ENV, str(slowdown))
    return ps.main(["--out", out, "--reps", "3"])


def test_sentinel_gate_seeds_fires_and_recovers(tmp_path, monkeypatch):
    """The gate lifecycle against the REAL history/judge/verdict/rc
    plumbing (measurement stubbed): 3 seeding runs pass as 'baseline',
    a seeded 3x slowdown exits 2 with perf-regress ledger spans on BOTH
    rung polarities, and an honest re-run is green again."""
    ps = _load_sentinel()
    out = str(tmp_path / "history.ndjson")
    for _ in range(3):  # seed: below MIN_HISTORY the gate cannot fail
        assert _run_sentinel(ps, monkeypatch, out) == 0
    rows = ps.load_history(out)
    assert len(rows) == 3
    assert all(r["verdicts"] == {"serial_step": "baseline",
                                 "aot_ttfc": "baseline"} for r in rows)
    assert all(r["bench_history_version"] ==
               tschema.BENCH_HISTORY_VERSION for r in rows)
    # Median-of-reps landed in the row, not the raw samples.
    assert rows[0]["rungs"]["serial_step"]["value"] == 1000.0

    # Armed now: an honest 4th run is 'ok'.
    assert _run_sentinel(ps, monkeypatch, out) == 0
    assert ps.load_history(out)[-1]["verdicts"]["serial_step"] == "ok"

    # Seeded 3x slowdown: rate rung drops to ~333 (< 1000/2), time rung
    # rises to 6.0 (> 2.0*2) -> rc 2, both rungs regress, loud spans.
    lg = tledger.get()
    before = len(lg.spans)
    rc = _run_sentinel(ps, monkeypatch, out, slowdown=3)
    assert rc == 2
    last = ps.load_history(out)[-1]
    assert last["verdicts"] == {"serial_step": "regress",
                                "aot_ttfc": "regress"}
    new_spans = [sp for sp in lg.spans[before:]
                 if sp.kind == ps.PERF_REGRESS]
    assert {sp.attrs["rung"] for sp in new_spans} == \
        {"serial_step", "aot_ttfc"}

    # Honest re-run: green again (the regress row joins the history but
    # the rolling MEDIAN baseline shrugs off one bad row).
    assert _run_sentinel(ps, monkeypatch, out) == 0
    assert ps.load_history(out)[-1]["verdicts"]["serial_step"] == "ok"


def test_sentinel_judge_tolerance_boundaries():
    """The noise gate's edges: within (1+tol)x passes, past it fails,
    in BOTH directions; <3 prior rows is always 'baseline'."""
    ps = _load_sentinel()
    hist = [{"kind": "bench",
             "rungs": {"r_hi": {"value": 100.0}, "r_lo": {"value": 4.0}}}
            for _ in range(3)]
    cur = {"r_hi": {"value": 51.0, "direction": "higher"},
           "r_lo": {"value": 7.9, "direction": "lower"}}
    v = ps.judge(cur, hist, 100.0)
    assert v["r_hi"]["verdict"] == "ok" and v["r_lo"]["verdict"] == "ok"
    cur_bad = {"r_hi": {"value": 49.0, "direction": "higher"},
               "r_lo": {"value": 8.1, "direction": "lower"}}
    v = ps.judge(cur_bad, hist, 100.0)
    assert v["r_hi"]["verdict"] == "regress"
    assert v["r_lo"]["verdict"] == "regress"
    v = ps.judge(cur_bad, hist[:2], 100.0)
    assert all(x["verdict"] == "baseline" for x in v.values())
    # Tighter tolerance flips the 'ok' pair.
    v = ps.judge(cur, hist, 10.0)
    assert v["r_hi"]["verdict"] == "regress"
    assert v["r_lo"]["verdict"] == "regress"


@pytest.mark.slow
def test_sentinel_real_measurement_subprocess(tmp_path):
    """One REAL rung through the unpatched measurement path: subprocess
    run of scripts/perf_sentinel.py on serial_step appends a history row
    with a positive rate (slow: pays a cold compile on a fresh cache)."""
    out = str(tmp_path / "history.ndjson")
    env = dict(os.environ, PYTHONPATH=REPO, BENCH_SENTINEL_RUNGS="serial_step")
    r = subprocess.run([sys.executable, SENTINEL, "--out", out,
                        "--reps", "1"],
                       capture_output=True, text=True, env=env,
                       timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    rows = tledger.read_ndjson(out)
    assert len(rows) == 1
    rung = rows[0]["rungs"]["serial_step"]
    assert rung["value"] > 0 and rung["unit"] == "events/s"
    assert rows[0]["verdicts"]["serial_step"] == "baseline"


def test_observatory_inert_on_compiled_graphs(tmp_path, monkeypatch):
    """The whole observability layer is host-only BY CONSTRUCTION —
    prove it: both engines' chunk scans trace to eqn-identical jaxprs
    with the observatory armed (env knob set, a live store ingesting
    mid-trace) and without it.  The census budgets and DONATION pins
    ride the unchanged graphs (gated elsewhere in tier-1)."""
    path = _write_fleet_stream(str(tmp_path / "fleet.ndjson"),
                               [_digest_row(0, 0.1, 10)])

    def sig(engine, kw):
        p = SimParams(max_clock=100, **kw)
        st = engine.init_batch(p, np.arange(2, dtype=np.uint32))
        cj = jax.make_jaxpr(engine.make_scan_fn(p, 2))(st)
        return GL.eqn_signature(cj.jaxpr)

    off = [sig(S, GL.MICRO_SER_KW), sig(PE, GL.MICRO_LANE_KW)]
    monkeypatch.setenv(tobs.WINDOW_ENV, "0.5")
    obs = tobs.from_paths([path])
    obs.rollup()
    on = [sig(S, GL.MICRO_SER_KW), sig(PE, GL.MICRO_LANE_KW)]
    assert obs.final_digest() is not None
    assert on == off
