"""DataWriter, round plotter, sweeps, and the CLI."""

import csv
import json
import os

import numpy as np

from librabft_simulator_tpu.analysis import round_plotter, sweeps
from librabft_simulator_tpu.analysis.data_writer import DataWriter
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import simulator as S


def run_traced(n=3, max_clock=500, seed=42):
    p = SimParams(n_nodes=n, max_clock=max_clock, trace_cap=1024)
    st = S.run_to_completion(p, S.init_state(p, seed))
    return p, st


def test_data_writer_outputs(tmp_path):
    p, st = run_traced()
    summary = DataWriter(p, str(tmp_path)).write(st)
    with open(tmp_path / "round_switches.txt") as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["node 0", "node 1", "node 2"]
    assert len(rows) - 1 == summary["max_round"] + 1
    # Round-switch times are monotone per node where present.
    for node in range(3):
        times = [int(r[node]) for r in rows[1:] if r[node] != ""]
        assert times == sorted(times)
        assert len(times) > 3
    with open(tmp_path / "number_of_messages.txt") as f:
        assert int(f.read().strip()) == summary["n_msgs_sent"] > 0
    with open(tmp_path / "summary.json") as f:
        assert json.load(f)["n_events"] == summary["n_events"]


def test_data_writer_parallel_engine(tmp_path):
    """The parallel engine carries the same on-device trace ring; DataWriter
    decodes it identically (entries land in window-schedule order, but the
    per-node switch times are the same monotone protocol quantity)."""
    from librabft_simulator_tpu.sim import parallel_sim as P

    p = SimParams(n_nodes=4, max_clock=800, delay_kind="uniform", window=8,
                  chain_k=2, commit_log=16, trace_cap=1024)
    st = P.run_to_completion(p, P.init_state(p, 7), chunk=64, max_chunks=200)
    assert int(np.asarray(st.trace_count)) > 10
    summary = DataWriter(p, str(tmp_path)).write(st)
    with open(tmp_path / "round_switches.txt") as f:
        rows = list(csv.reader(f))
    assert len(rows) - 1 == summary["max_round"] + 1
    for node in range(4):
        times = [int(r[node]) for r in rows[1:] if r[node] != ""]
        assert times == sorted(times)
        assert len(times) > 3


def test_round_plotter_ascii_and_png(tmp_path, capsys):
    p, st = run_traced()
    DataWriter(p, str(tmp_path)).write(st)
    csv_path = str(tmp_path / "round_switches.txt")
    round_plotter.main([csv_path, "--ascii"])
    out = capsys.readouterr().out
    assert "round" in out
    png = str(tmp_path / "plot.png")
    round_plotter.main([csv_path, "--out", png])
    assert os.path.getsize(png) > 0


def test_sweep_single_config():
    p = SimParams(n_nodes=3, max_clock=400)
    res = sweeps.run_config(p, n_instances=4)
    assert res["instances"] == 4
    assert res["total_commits"] > 0
    assert res["rounds_per_sec"] > 0


def test_sweep_parallel_engine_config():
    """run_config drives the lane engine for the wide-fleet configs."""
    p = SimParams(n_nodes=4, max_clock=600, delay_kind="uniform", window=8,
                  chain_k=2, commit_log=16)
    res = sweeps.run_config(p, n_instances=6, engine=sweeps.P)
    assert res["instances"] == 6
    assert res["total_commits"] > 0
    assert res["queue_full"] == 0


def test_cli_main_json(capsys):
    from librabft_simulator_tpu.main import main

    summary = main(["--nodes", "3", "--max_clock", "400", "--seed", "5",
                    "--instances", "2", "--json"])
    assert summary["instances"] == 2
    assert summary["mean_commits_per_node"] > 0
    out = capsys.readouterr().out
    assert json.loads(out.strip().splitlines()[-1])["seed"] == 5


def test_cli_writes_data_files(tmp_path):
    from librabft_simulator_tpu.main import main

    main(["--nodes", "3", "--max_clock", "400", "--seed", "5",
          "--output_data_files", str(tmp_path)])
    assert (tmp_path / "round_switches.txt").exists()
    assert (tmp_path / "summary.json").exists()
