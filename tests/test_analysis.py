"""DataWriter, round plotter, sweeps, and the CLI."""

import csv
import json
import os

import numpy as np

from librabft_simulator_tpu.analysis import round_plotter, sweeps
from librabft_simulator_tpu.analysis.data_writer import DataWriter
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import simulator as S


def run_traced(n=3, max_clock=500, seed=42):
    p = SimParams(n_nodes=n, max_clock=max_clock, trace_cap=1024)
    st = S.run_to_completion(p, S.init_state(p, seed))
    return p, st


def test_data_writer_outputs(tmp_path):
    p, st = run_traced()
    summary = DataWriter(p, str(tmp_path)).write(st)
    with open(tmp_path / "round_switches.txt") as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["node 0", "node 1", "node 2"]
    assert len(rows) - 1 == summary["max_round"] + 1
    # Round-switch times are monotone per node where present.
    for node in range(3):
        times = [int(r[node]) for r in rows[1:] if r[node] != ""]
        assert times == sorted(times)
        assert len(times) > 3
    with open(tmp_path / "number_of_messages.txt") as f:
        assert int(f.read().strip()) == summary["n_msgs_sent"] > 0
    with open(tmp_path / "summary.json") as f:
        assert json.load(f)["n_events"] == summary["n_events"]


def test_data_writer_parallel_engine(tmp_path):
    """The parallel engine carries the same on-device trace ring; DataWriter
    decodes it identically (entries land in window-schedule order, but the
    per-node switch times are the same monotone protocol quantity)."""
    from librabft_simulator_tpu.sim import parallel_sim as P

    p = SimParams(n_nodes=4, max_clock=800, delay_kind="uniform", window=8,
                  chain_k=2, commit_log=16, trace_cap=1024)
    st = P.run_to_completion(p, P.init_state(p, 7), chunk=64, max_chunks=200)
    assert int(np.asarray(st.trace_count)) > 10
    summary = DataWriter(p, str(tmp_path)).write(st)
    with open(tmp_path / "round_switches.txt") as f:
        rows = list(csv.reader(f))
    assert len(rows) - 1 == summary["max_round"] + 1
    for node in range(4):
        times = [int(r[node]) for r in rows[1:] if r[node] != ""]
        assert times == sorted(times)
        assert len(times) > 3


def test_round_switch_table_wrapped_ring():
    """Ring-overflow decode: when trace_count > trace_cap the surviving
    last-T entries are rotated in storage (oldest at count % T); the decoder
    must iterate chronologically or stale entries shadow fresh ones under
    the first-write-wins rule."""
    from types import SimpleNamespace

    from librabft_simulator_tpu.analysis.data_writer import round_switch_table

    p = SimParams(n_nodes=2, trace_cap=4)
    # 6 switches appended to a cap-4 ring: entries 0,1 were overwritten by
    # 4,5.  Storage order is [4, 5, 2, 3]; chronological order is 2,3,4,5.
    # Node 0 entered round 1 at t=12 (entry 2) and round 1 AGAIN at t=40
    # (entry 4, e.g. after a sync-jump re-entry): first-write-wins must
    # record t=12, which only happens if decode starts at count % T == 2.
    st = SimpleNamespace(
        trace_node=np.array([0, 1, 0, 1]),
        trace_round=np.array([1, 2, 1, 1]),
        trace_time=np.array([40, 50, 12, 13]),
        trace_count=np.array(6),
    )
    table = round_switch_table(p, st)
    assert table[1, 0] == 12  # chronological first entry, not the stale 40
    assert table[1, 1] == 13
    assert table[2, 1] == 50
    # Tracing off (trace_cap == 0): trace_count still advances in both
    # engines, and the decode must return the empty table, not divide by
    # the zero capacity.
    p0 = SimParams(n_nodes=2, trace_cap=0)
    st0 = SimpleNamespace(
        trace_node=np.zeros(0, np.int32), trace_round=np.zeros(0, np.int32),
        trace_time=np.zeros(0, np.int32), trace_count=np.array(36))
    assert round_switch_table(p0, st0).shape == (1, 2)
    # Unwrapped ring (count <= cap) keeps the plain in-order decode.
    st2 = SimpleNamespace(
        trace_node=np.array([0, 1, 0, 0]),
        trace_round=np.array([1, 1, 2, 2]),
        trace_time=np.array([5, 6, 9, 11]),
        trace_count=np.array(3),
    )
    table2 = round_switch_table(p, st2)
    assert table2[1, 0] == 5 and table2[1, 1] == 6 and table2[2, 0] == 9


def test_round_plotter_ascii_and_png(tmp_path, capsys):
    p, st = run_traced()
    DataWriter(p, str(tmp_path)).write(st)
    csv_path = str(tmp_path / "round_switches.txt")
    round_plotter.main([csv_path, "--ascii"])
    out = capsys.readouterr().out
    assert "round" in out
    png = str(tmp_path / "plot.png")
    round_plotter.main([csv_path, "--out", png])
    assert os.path.getsize(png) > 0


def test_sweep_single_config():
    p = SimParams(n_nodes=3, max_clock=400)
    res = sweeps.run_config(p, n_instances=4)
    assert res["instances"] == 4
    assert res["total_commits"] > 0
    assert res["rounds_per_sec"] > 0


def test_sweep_parallel_engine_config():
    """run_config drives the lane engine for the wide-fleet configs."""
    p = SimParams(n_nodes=4, max_clock=600, delay_kind="uniform", window=8,
                  chain_k=2, commit_log=16)
    res = sweeps.run_config(p, n_instances=6, engine=sweeps.P)
    assert res["instances"] == 6
    assert res["total_commits"] > 0
    assert res["queue_full"] == 0


def test_cli_main_json(capsys):
    from librabft_simulator_tpu.main import main

    summary = main(["--nodes", "3", "--max_clock", "400", "--seed", "5",
                    "--instances", "2", "--json"])
    assert summary["instances"] == 2
    assert summary["mean_commits_per_node"] > 0
    out = capsys.readouterr().out
    assert json.loads(out.strip().splitlines()[-1])["seed"] == 5


def test_cli_writes_data_files(tmp_path):
    from librabft_simulator_tpu.main import main

    main(["--nodes", "3", "--max_clock", "400", "--seed", "5",
          "--output_data_files", str(tmp_path)])
    assert (tmp_path / "round_switches.txt").exists()
    assert (tmp_path / "summary.json").exists()
