"""DataSync catch-up (/root/reference/librabft-v2/src/data_sync.rs).

The reference's serde round-trip tests degenerate under fixed-shape tensors
(a Payload is always 'serialized'); instead we test the behavioural surface:
notification insert paths, request/response catch-up, state-sync jumps.
"""

import jax
import jax.numpy as jnp

from librabft_simulator_tpu.core import config, data_sync, node as node_ops, \
    store as store_ops
from librabft_simulator_tpu.core.types import (
    Context, NodeExtra, Pacemaker, SimParams, Store,
)


def make_round(p, s, w, time):
    leader = int(config.leader_of_round(w, s.current_round))
    r, t = store_ops.hqc_ref(p, s)
    s, ok = store_ops.propose_block(p, s, w, leader, r, t, time, int(time))
    assert bool(ok)
    var = int(s.proposed_var)
    for a in range(int(config.quorum_threshold(w))):
        s, ok = store_ops.create_vote(p, s, w, a, s.current_round, var)
        assert bool(ok)
    s, created = store_ops.check_new_qc(p, s, w, leader)
    assert bool(created)
    return s


def advanced_store(p, rounds=3):
    w = jnp.ones((p.n_nodes,), jnp.int32)
    s = Store.initial(p)
    for i in range(rounds):
        s = make_round(p, s, w, 10 * (i + 1))
    return s, w


def test_notification_carries_hqc_and_catchup():
    p = SimParams(n_nodes=2)
    s_a, w = advanced_store(p, rounds=3)
    s_b = Store.initial(p)
    pay = data_sync.create_notification(p, s_a, 0)
    assert bool(pay.hqc.valid) and int(pay.hqc.round) == 3
    s_b2, should_sync = data_sync.handle_notification(p, s_b, w, pay)
    # B can't verify A's QC without the blocks -> still behind, wants to sync.
    assert bool(should_sync)
    assert int(s_b2.hqc_round) == 0


def test_request_response_catchup_within_window():
    p = SimParams(n_nodes=2, chain_k=4)
    s_a, w = advanced_store(p, rounds=3)
    s_b = Store.initial(p)
    req = data_sync.create_request(p, s_b)
    assert int(req.req_hqc_round) == 0
    resp = data_sync.handle_request(p, s_a, 0, req)
    nx, cx = NodeExtra.initial(), Context.initial(p)
    s_b2, nx2, cx2 = data_sync.handle_response(p, s_b, nx, cx, w, resp)
    # The K-tail replays blocks+QCs in order: B fully catches up.
    assert int(s_b2.hqc_round) == 3
    assert int(s_b2.current_round) == 4
    assert int(cx2.sync_jumps) == 0
    # And B's committed chain rule agrees: hcr advanced by the contiguous QCs.
    assert int(s_b2.hcr) == 1


def test_state_sync_jump_beyond_window():
    p = SimParams(n_nodes=2, window=8, chain_k=2)
    s_a, w = advanced_store(p, rounds=12)  # far beyond B's window
    s_b = Store.initial(p)
    resp = data_sync.handle_request(p, s_a, 0, data_sync.create_request(p, s_b))
    nx, cx = NodeExtra.initial(), Context.initial(p)
    s_b2, nx2, cx2 = data_sync.handle_response(p, s_b, nx, cx, w, resp)
    assert int(cx2.sync_jumps) == 1
    # B re-anchored at the base of A's chain tail and replayed the rest.
    assert int(s_b2.initial_round) > 0
    assert int(s_b2.hqc_round) == int(s_a.hqc_round)
    # The adopted committed state matches A's commit certificate.
    assert int(cx2.last_depth) == int(jnp.where(
        s_a.hcc_valid,
        s_a.qc_commit_depth[int(s_a.hcc_round) % p.window, int(s_a.hcc_var)], 0))


def test_notification_proposal_and_vote_paths():
    p = SimParams(n_nodes=2)
    w = jnp.ones((2,), jnp.int32)
    s_a = Store.initial(p)
    leader = int(config.leader_of_round(w, 1))
    r, t = store_ops.hqc_ref(p, s_a)
    s_a, ok = store_ops.propose_block(p, s_a, w, leader, r, t, 5, 0)
    assert bool(ok)
    s_a, ok = store_ops.create_vote(p, s_a, w, leader, s_a.current_round,
                                    int(s_a.proposed_var))
    assert bool(ok)
    pay = data_sync.create_notification(p, s_a, leader)
    assert bool(pay.prop_blk.valid)
    assert bool(pay.vote.valid)
    # Receiver inserts the proposal and the vote; its ballot counts 1 vote.
    s_b = Store.initial(p)
    s_b2, _ = data_sync.handle_notification(p, s_b, w, pay)
    assert int(jnp.sum(s_b2.blk_valid)) == 1
    assert bool(s_b2.vt_valid[leader])


def test_notification_does_not_reshare_others_proposal():
    p = SimParams(n_nodes=2)
    w = jnp.ones((2,), jnp.int32)
    s_a = Store.initial(p)
    leader = int(config.leader_of_round(w, 1))
    other = 1 - leader
    r, t = store_ops.hqc_ref(p, s_a)
    s_a, ok = store_ops.propose_block(p, s_a, w, leader, r, t, 5, 0)
    assert bool(ok)
    pay = data_sync.create_notification(p, s_a, other)  # not the proposer
    assert not bool(pay.prop_blk.valid)  # data_sync.rs:99-109


def test_timeout_batch_insert_forms_tc():
    p = SimParams(n_nodes=3)
    w = jnp.ones((3,), jnp.int32)
    s_a = Store.initial(p)
    for a in range(3):
        s_a, ok = store_ops.create_timeout(p, s_a, w, a, s_a.current_round)
        if int(s_a.htc_round) > 0:
            break
    assert int(s_a.htc_round) == 1
    pay = data_sync.create_notification(p, s_a, 0)
    s_b = Store.initial(p)
    s_b2, _ = data_sync.handle_notification(p, s_b, w, pay)
    assert int(s_b2.htc_round) == 1
    assert int(s_b2.current_round) == 2
