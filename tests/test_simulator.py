"""End-to-end batched simulator runs
(/root/reference/bft-lib/src/simulator.rs + simulated_run in README)."""

import jax
import jax.numpy as jnp
import numpy as np

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import simulator as S


def committed_chain(st, node):
    """(depth, tag) pairs committed by `node`, ascending, from the ring log."""
    cc = int(st.ctx.commit_count[node])
    H = st.ctx.log_depth.shape[-1]
    out = []
    for i in range(max(cc - H, 0), cc):
        pos = i % H
        out.append((int(st.ctx.log_depth[node, pos]), int(st.ctx.log_tag[node, pos])))
    return out


def assert_safety(st, n):
    """All nodes agree on (depth -> tag) for every depth committed by >1 node."""
    seen = {}
    for a in range(n):
        for d, t in committed_chain(st, a):
            if d in seen:
                assert seen[d] == t, f"conflicting commit at depth {d}"
            else:
                seen[d] = t
    return seen


def test_three_nodes_commit_nontrivial_equal_histories():
    p = SimParams(n_nodes=3, max_clock=1000)
    st = S.init_state(p, 42)
    st = S.run_to_completion(p, st)
    counts = [int(c) for c in st.ctx.commit_count]
    # Reference README run commits ~27 per 1000 time units.
    assert min(counts) >= 15
    assert_safety(st, 3)
    # All nodes converged to the same last state.
    depths = [int(d) for d in st.ctx.last_depth]
    assert max(depths) - min(depths) <= 3


def test_eight_nodes_commit():
    p = SimParams(n_nodes=8, max_clock=1000, queue_cap=64)
    st = S.init_state(p, 7)
    st = S.run_to_completion(p, st)
    counts = [int(c) for c in st.ctx.commit_count]
    assert min(counts) >= 5
    assert_safety(st, 8)


def test_determinism_same_seed():
    p = SimParams(n_nodes=3, max_clock=500)
    a = S.run_to_completion(p, S.init_state(p, 123))
    b = S.run_to_completion(p, S.init_state(p, 123))
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_different_seeds_differ():
    p = SimParams(n_nodes=3, max_clock=500)
    a = S.run_to_completion(p, S.init_state(p, 1))
    b = S.run_to_completion(p, S.init_state(p, 2))
    assert int(a.n_events) != int(b.n_events) or \
        committed_chain(a, 0) != committed_chain(b, 0)


def test_batched_run_matches_single_runs():
    p = SimParams(n_nodes=3, max_clock=300)
    seeds = [5, 6, 7, 8]
    batch = S.run_to_completion(p, S.init_batch(p, np.asarray(seeds)), batched=True)
    for i, seed in enumerate(seeds):
        single = S.run_to_completion(p, S.init_state(p, seed))
        bi = jax.tree.map(lambda x: x[i], batch)
        for x, y in zip(jax.tree.leaves(bi), jax.tree.leaves(single)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_message_drop_still_commits():
    # BASELINE config #3 capability: liveness under 5% drop (DataSync recovers).
    p = SimParams(n_nodes=3, max_clock=3000, drop_prob=0.05)
    st = S.run_to_completion(p, S.init_state(p, 9))
    assert int(st.n_msgs_dropped) > 0
    counts = [int(c) for c in st.ctx.commit_count]
    assert min(counts) >= 5
    assert_safety(st, 3)


def test_pareto_delays_commit():
    p = SimParams(n_nodes=3, max_clock=3000, delay_kind="pareto")
    st = S.run_to_completion(p, S.init_state(p, 11))
    counts = [int(c) for c in st.ctx.commit_count]
    assert min(counts) >= 1
    assert_safety(st, 3)


def test_clock_monotone_and_bounded():
    p = SimParams(n_nodes=3, max_clock=400)
    st = S.run_to_completion(p, S.init_state(p, 3))
    assert bool(st.halted)
    assert int(st.clock) <= 400 + 1
