"""utils/xops.wset: the scatter-free scalar write the whole engine uses.

wset exists because vmapped scalar scatters miscompile on the axon TPU
stack (scripts/tpu_scatter_bug_repro.py); these tests pin its semantics
against .at[].set on CPU, including the drop-on-out-of-range contract.
"""

import jax
import jax.numpy as jnp
import numpy as np

from librabft_simulator_tpu.utils.xops import wset


def test_wset_matches_at_set_1d():
    arr = jnp.arange(8, dtype=jnp.int32)
    for i in range(8):
        np.testing.assert_array_equal(
            np.asarray(wset(arr, jnp.int32(i), 99)),
            np.asarray(arr.at[i].set(99)))


def test_wset_tuple_index_2d():
    arr = jnp.arange(12, dtype=jnp.int32).reshape(3, 4)
    out = wset(arr, (jnp.int32(1), jnp.int32(2)), -7)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(arr.at[1, 2].set(-7)))


def test_wset_row_value_broadcast():
    arr = jnp.zeros((4, 5), jnp.int32)
    row = jnp.arange(5, dtype=jnp.int32)
    out = wset(arr, jnp.int32(2), row)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(arr.at[2].set(row)))


def test_wset_out_of_range_drops():
    arr = jnp.arange(4, dtype=jnp.int32)
    # Sentinel == length and negative indices write nothing (mode="drop"
    # semantics; .at[] would clip negatives — call sites rely on drop).
    np.testing.assert_array_equal(np.asarray(wset(arr, jnp.int32(4), 99)),
                                  np.asarray(arr))
    np.testing.assert_array_equal(np.asarray(wset(arr, jnp.int32(-1), 99)),
                                  np.asarray(arr))


def test_wset_when_gates_the_write():
    arr = jnp.zeros((4,), jnp.bool_)
    on = wset(arr, jnp.int32(1), True, when=jnp.bool_(True))
    off = wset(arr, jnp.int32(1), True, when=jnp.bool_(False))
    assert bool(on[1]) and not bool(off[1])
    assert not np.asarray(off).any()


def test_wset_dtype_cast_matches_at():
    arr = jnp.zeros((4,), jnp.uint32)
    out = wset(arr, jnp.int32(3), 7)  # python int -> uint32, like .at[].set
    assert out.dtype == jnp.uint32 and int(out[3]) == 7


def test_wset_under_vmap():
    B, N = 512, 4
    rng = np.random.default_rng(1)
    base = jnp.asarray(rng.random((B, N)) < 0.3)
    idx = jnp.asarray(rng.integers(0, N, B), jnp.int32)
    ok = jnp.asarray(rng.random(B) < 0.5)
    got = jax.jit(jax.vmap(lambda b, a, o: wset(b, a, True, when=o)))(
        base, idx, ok)
    want = np.array(base)
    for i in range(B):
        if ok[i]:
            want[i, idx[i]] = True
    np.testing.assert_array_equal(np.asarray(got), want)
