"""utils/xops.wset: the scatter-free scalar write the whole engine uses.

wset exists because vmapped scalar scatters miscompile on the axon TPU
stack (scripts/tpu_scatter_bug_repro.py); these tests pin its semantics
against .at[].set on CPU, including the drop-on-out-of-range contract.
"""

import jax
import jax.numpy as jnp
import numpy as np

from librabft_simulator_tpu.utils.xops import scatter_set, wset


def test_wset_matches_at_set_1d():
    arr = jnp.arange(8, dtype=jnp.int32)
    for i in range(8):
        np.testing.assert_array_equal(
            np.asarray(wset(arr, jnp.int32(i), 99)),
            np.asarray(arr.at[i].set(99)))


def test_wset_tuple_index_2d():
    arr = jnp.arange(12, dtype=jnp.int32).reshape(3, 4)
    out = wset(arr, (jnp.int32(1), jnp.int32(2)), -7)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(arr.at[1, 2].set(-7)))


def test_wset_row_value_broadcast():
    arr = jnp.zeros((4, 5), jnp.int32)
    row = jnp.arange(5, dtype=jnp.int32)
    out = wset(arr, jnp.int32(2), row)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(arr.at[2].set(row)))


def test_wset_out_of_range_drops():
    arr = jnp.arange(4, dtype=jnp.int32)
    # Sentinel == length and negative indices write nothing (mode="drop"
    # semantics; .at[] would clip negatives — call sites rely on drop).
    np.testing.assert_array_equal(np.asarray(wset(arr, jnp.int32(4), 99)),
                                  np.asarray(arr))
    np.testing.assert_array_equal(np.asarray(wset(arr, jnp.int32(-1), 99)),
                                  np.asarray(arr))


def test_wset_when_gates_the_write():
    arr = jnp.zeros((4,), jnp.bool_)
    on = wset(arr, jnp.int32(1), True, when=jnp.bool_(True))
    off = wset(arr, jnp.int32(1), True, when=jnp.bool_(False))
    assert bool(on[1]) and not bool(off[1])
    assert not np.asarray(off).any()


def test_wset_dtype_cast_matches_at():
    arr = jnp.zeros((4,), jnp.uint32)
    out = wset(arr, jnp.int32(3), 7)  # python int -> uint32, like .at[].set
    assert out.dtype == jnp.uint32 and int(out[3]) == 7


def _both_modes(dst, idx, src):
    a = scatter_set(dst, idx, src, mode="scatter")
    b = scatter_set(dst, idx, src, mode="dense")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    return a


def test_scatter_set_dense_matches_scatter_1d():
    """The dense one-hot sum-select queue write (the TPU form) must equal
    the proven .at[].set(mode='drop') scatter bit-for-bit."""
    rng = np.random.default_rng(0)
    dst = jnp.asarray(rng.integers(-50, 50, 32), jnp.int32)
    idx = jnp.asarray([3, 7, 0, 31, 12], jnp.int32)
    src = jnp.asarray(rng.integers(-50, 50, 5), jnp.int32)
    out = _both_modes(dst, idx, src)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(dst.at[idx].set(src)))


def test_scatter_set_sentinel_and_negative_drop():
    """Index semantics follow .at[] exactly in BOTH forms: the sentinel
    idx == len (the queue's overflow path) and far-out-of-range targets
    write nothing; values in [-len, 0) wrap (numpy semantics — unlike
    wset, which drops all negatives)."""
    dst = jnp.arange(8, dtype=jnp.int32)
    idx = jnp.asarray([8, -1, 100, -9], jnp.int32)
    src = jnp.asarray([91, 92, 93, 94], jnp.int32)
    out = _both_modes(dst, idx, src)
    want = np.arange(8)
    want[7] = 92  # -1 wraps; 8, 100, -9 all drop
    np.testing.assert_array_equal(np.asarray(out), want)


def test_scatter_set_duplicate_indices_last_wins():
    """The DENSE form's duplicate resolution is part of scatter_set's own
    contract: the last matching source wins (deterministic by
    construction).  The scatter form is NOT asserted here — XLA leaves
    repeated-index .at[].set ordering unspecified, and the engine never
    produces duplicates anyway (queue targets are distinct free slots or
    the dropped sentinel), so pinning XLA's current order would just make
    a JAX upgrade fail this test spuriously."""
    dst = jnp.zeros((6,), jnp.int32)
    idx = jnp.asarray([2, 4, 2, 2], jnp.int32)
    src = jnp.asarray([10, 20, 30, 40], jnp.int32)
    out = scatter_set(dst, idx, src, mode="dense")
    assert int(out[2]) == 40 and int(out[4]) == 20


def test_scatter_set_payload_rows():
    """2-D row payloads: the dense form is the one-hot integer matmul
    (PERF_NOTES' 'MXU-shaped payload select')."""
    rng = np.random.default_rng(1)
    dst = jnp.asarray(rng.integers(-2**30, 2**30, (16, 20)), jnp.int32)
    idx = jnp.asarray([0, 15, 16, 3, 7], jnp.int32)  # incl sentinel drop
    src = jnp.asarray(rng.integers(-2**30, 2**30, (5, 20)), jnp.int32)
    out = _both_modes(dst, idx, src)
    np.testing.assert_array_equal(np.asarray(out[3]), np.asarray(src[3]))
    # Dense-only: a duplicate row target resolves last-wins (scatter_set's
    # own contract; XLA's .at[] ordering for duplicates is unspecified).
    dup = scatter_set(dst, jnp.asarray([5, 5], jnp.int32), src[:2],
                      mode="dense")
    np.testing.assert_array_equal(np.asarray(dup[5]), np.asarray(src[1]))


def test_scatter_set_3d_rows():
    """>1 trailing dim (not a current engine shape): both forms must still
    agree, so the dense form never works-on-CPU-only."""
    rng = np.random.default_rng(4)
    dst = jnp.asarray(rng.integers(0, 100, (6, 3, 2)), jnp.int32)
    idx = jnp.asarray([1, 6, 4], jnp.int32)  # incl sentinel drop
    src = jnp.asarray(rng.integers(0, 100, (3, 3, 2)), jnp.int32)
    _both_modes(dst, idx, src)


def test_bool_env_strict(monkeypatch):
    """LIBRABFT_PACKED=off must not silently mean 'on'."""
    from librabft_simulator_tpu.utils import xops

    monkeypatch.setenv(xops.PACKED_ENV, "off")
    assert xops.packed_mode() is False
    monkeypatch.setenv(xops.PACKED_ENV, "on")
    assert xops.packed_mode() is True
    monkeypatch.setenv(xops.PACKED_ENV, "bogus")
    with np.testing.assert_raises(ValueError):
        xops.packed_mode()


def test_macro_mode_resolution(monkeypatch):
    """LIBRABFT_MACRO_K: explicit SimParams.macro_k wins, else env, else
    1 — and malformed/non-positive values raise instead of silently
    benching the wrong graph (the packed_mode strict-parse discipline)."""
    from librabft_simulator_tpu.utils import xops

    monkeypatch.delenv(xops.MACRO_ENV, raising=False)
    assert xops.macro_mode() == 1
    assert xops.macro_mode(4) == 4
    monkeypatch.setenv(xops.MACRO_ENV, "16")
    assert xops.macro_mode() == 16
    assert xops.macro_mode(2) == 2  # explicit beats env
    for bad in ("bogus", "0", "-3"):
        monkeypatch.setenv(xops.MACRO_ENV, bad)
        with np.testing.assert_raises(ValueError):
            xops.macro_mode()
    # resolve_params lands the resolved K in the params (compile key).
    from librabft_simulator_tpu.core.types import SimParams

    monkeypatch.setenv(xops.MACRO_ENV, "8")
    assert xops.resolve_params(SimParams()).macro_k == 8
    assert xops.resolve_params(SimParams(macro_k=2)).macro_k == 2
    monkeypatch.delenv(xops.MACRO_ENV)
    assert xops.resolve_params(SimParams()).macro_k == 1


def test_scatter_set_bool_and_scalar_src():
    dst = jnp.zeros((10,), jnp.bool_)
    idx = jnp.asarray([1, 9, 10, 4], jnp.int32)
    out = _both_modes(dst, idx, True)
    want = np.zeros(10, bool)
    want[[1, 9, 4]] = True
    np.testing.assert_array_equal(np.asarray(out), want)


def test_scatter_set_dense_under_vmap():
    """The batched lowering the serial engine actually uses."""
    B, cm, m = 64, 12, 5
    rng = np.random.default_rng(2)
    dst = jnp.asarray(rng.integers(0, 100, (B, cm)), jnp.int32)
    idx = jnp.asarray(rng.integers(0, cm + 1, (B, m)), jnp.int32)  # incl drop
    src = jnp.asarray(rng.integers(0, 100, (B, m)), jnp.int32)
    f = lambda mode: jax.jit(jax.vmap(  # noqa: E731
        lambda d, i, s: scatter_set(d, i, s, mode=mode)))(dst, idx, src)
    np.testing.assert_array_equal(np.asarray(f("scatter")),
                                  np.asarray(f("dense")))


def test_dense_node_update_plane_matches_per_leaf():
    """The packed engine's plane write (one wset on [n, S]) must equal the
    per-leaf scatter form, including sentinel-index drop."""
    rng = np.random.default_rng(3)
    planes = jnp.asarray(rng.integers(-2**30, 2**30, (4, 33)), jnp.int32)
    row = jnp.asarray(rng.integers(-2**30, 2**30, 33), jnp.int32)
    for a in [0, 3]:
        np.testing.assert_array_equal(
            np.asarray(wset(planes, jnp.int32(a), row)),
            np.asarray(planes.at[a].set(row)))
    # Sentinel index == n drops the write entirely.
    np.testing.assert_array_equal(
        np.asarray(wset(planes, jnp.int32(4), row)), np.asarray(planes))


def test_wset_under_vmap():
    B, N = 512, 4
    rng = np.random.default_rng(1)
    base = jnp.asarray(rng.random((B, N)) < 0.3)
    idx = jnp.asarray(rng.integers(0, N, B), jnp.int32)
    ok = jnp.asarray(rng.random(B) < 0.5)
    got = jax.jit(jax.vmap(lambda b, a, o: wset(b, a, True, when=o)))(
        base, idx, ok)
    want = np.array(base)
    for i in range(B):
        if ok[i]:
            want[i, idx[i]] = True
    np.testing.assert_array_equal(np.asarray(got), want)
