"""Pallas TPU kernel for the batched event-selection step.

``_select_event`` (sim/simulator.py) is the per-step serial gate of the whole
simulator: a lexicographic argmin over (time asc, kind desc, stamp asc) across
the message queue + per-node timers.  Under vmap, XLA emits three separate
masked reductions over the [B, M] batch; this kernel fuses them into one VMEM
pass per instance block (one load of each operand instead of three, no
intermediate [B, M] masks in HBM).

Inputs are padded to a lane-aligned M (invalid entries carry time=NEVER), so
the fleet event-select runs as a single grid over instance blocks.  On CPU the
same kernel runs in interpret mode — bit-identical, which keeps the parity
suite meaningful.

Call site: ``sim/simulator.py::_select_event`` with
``SimParams.select_kernel`` in {"pallas", "pallas_interpret"} (the engine's
vmap batches the per-instance call over the fleet); ``BENCH_SELECT=pallas``
selects it for on-chip A/B against the XLA reductions.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEVER = 2**31 - 1
LANE = 128


def _select_kernel(time_ref, kind_ref, stamp_ref, idx_ref, tmin_ref):
    t = time_ref[:]      # [bB, M]
    k = kind_ref[:]
    s = stamp_ref[:]
    t_min = jnp.min(t, axis=1, keepdims=True)
    c1 = t == t_min
    k_best = jnp.max(jnp.where(c1, k, -1), axis=1, keepdims=True)
    c2 = c1 & (k == k_best)
    s_best = jnp.min(jnp.where(c2, s, NEVER), axis=1, keepdims=True)
    c3 = c2 & (s == s_best)
    m = t.shape[1]
    cols = jax.lax.broadcasted_iota(jnp.int32, t.shape, 1)
    idx = jnp.min(jnp.where(c3, cols, m), axis=1)
    # Outputs are [bB, LANE] with the scalar result broadcast across the
    # lane dim: TPU lowering requires the last block dim be 128-divisible
    # (or equal to the array dim), which a [bB] 1-D output can never
    # satisfy — compiled mode rejects it.  The caller reads lane 0.
    idx_ref[:] = jnp.broadcast_to(idx[:, None], idx_ref.shape)
    tmin_ref[:] = jnp.broadcast_to(t_min, tmin_ref.shape)


@functools.partial(jax.jit, static_argnames=("block_b", "interpret"))
def select_events(times, kinds, stamps, block_b: int = 8,
                  interpret: bool = False):
    """Batched lexicographic argmin.

    times/kinds/stamps: int32 [B, M] (invalid slots: time == NEVER).
    Returns (idx [B], t_min [B]): winning column per instance.
    """
    B, M = times.shape
    m_pad = (-M) % LANE
    b_pad = (-B) % block_b
    if m_pad or b_pad:
        times = jnp.pad(times, ((0, b_pad), (0, m_pad)), constant_values=NEVER)
        kinds = jnp.pad(kinds, ((0, b_pad), (0, m_pad)), constant_values=-1)
        stamps = jnp.pad(stamps, ((0, b_pad), (0, m_pad)), constant_values=NEVER)
    Bp, Mp = times.shape
    grid = (Bp // block_b,)
    spec = pl.BlockSpec((block_b, Mp), lambda i: (i, 0))
    out = pl.pallas_call(
        _select_kernel,
        grid=grid,
        in_specs=[spec, spec, spec],
        out_specs=[
            pl.BlockSpec((block_b, LANE), lambda i: (i, 0)),
            pl.BlockSpec((block_b, LANE), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Bp, LANE), jnp.int32),
            jax.ShapeDtypeStruct((Bp, LANE), jnp.int32),
        ],
        interpret=interpret,
    )(times, kinds, stamps)
    idx, tmin = out
    return idx[:B, 0], tmin[:B, 0]


def select_events_reference(times, kinds, stamps):
    """Plain-XLA reference (mirrors sim/simulator.py::_select_event)."""
    t_min = jnp.min(times, axis=1)
    c1 = times == t_min[:, None]
    k_best = jnp.max(jnp.where(c1, kinds, -1), axis=1)
    c2 = c1 & (kinds == k_best[:, None])
    s_best = jnp.min(jnp.where(c2, stamps, NEVER), axis=1)
    c3 = c2 & (stamps == s_best[:, None])
    idx = jnp.argmax(c3, axis=1).astype(jnp.int32)
    return idx, t_min
