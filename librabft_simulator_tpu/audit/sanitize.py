"""Checkify sanitizer: a runtime-checked build of both engines' chunk
runners, behind the ``LIBRABFT_CHECKIFY`` knob (default OFF).

The graph auditor proves structural invariants; this module checks the
*value-level* ones at runtime, in a separately-compiled debug build —
the engine graphs themselves are untouched (off is trivially bit- and
kernel-identical: nothing in the hot path even imports this at trace
time, and the kernel-census CI gates pin the compiled graphs).

What it checks, per chunk:

* **division checks** (``checkify.div_checks``) anywhere in the step;
* **index-bounds preconditions**: every in-step gather is in bounds iff
  the state invariants below hold between chunks, so the sanitizer
  asserts them on the chunk output — queue/inbox ``receiver`` and
  ``kind`` in range wherever valid, rounds >= 1, commit log consistency
  (``commit_count + skipped == last_depth``, the Context invariant);
  NOTE ``checkify.index_checks`` itself is deliberately NOT enabled:
  the engines' sentinel-drop writes (queue overflow routes to index ==
  capacity, dropped by ``mode="drop"``) are *intentional* out-of-bounds
  indices, so a blanket OOB sanitizer flags the design, not bugs;
* **int-overflow sentinels**: the monotone int32 counters (events,
  stamps, messages, commits) must stay non-negative — a wrapped counter
  shows up negative long before it corrupts downstream arithmetic — and
  the clock must stay inside ``[0, NEVER]``.

Wiring: ``run_to_completion`` in both engines consults :func:`enabled`
and swaps its chunk runner for :func:`make_checked_run_fn`'s, throwing
on the first tripped check (``scripts/graph_audit.py --sanitize`` and
tests/test_audit.py drive it at the warmed micro shapes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core.types import NEVER, KIND_RESPONSE, SimParams
from ..utils import xops

CHECKIFY_ENV = "LIBRABFT_CHECKIFY"


def enabled() -> bool:
    """The static debug flag: strict-parsed ``LIBRABFT_CHECKIFY`` env."""
    return xops._bool_env(CHECKIFY_ENV) or False


def _chk():
    from jax.experimental import checkify
    return checkify


def check_state_invariants(p: SimParams, st) -> None:
    """``checkify.check`` every cross-chunk state invariant (both engine
    state flavors; fields are probed by name so one checker serves
    SimState and PSimState).  Must be called under a checkify trace."""
    checkify = _chk()
    n = p.n_nodes

    def all_(x):
        return jnp.all(jnp.asarray(x))

    # Monotone counters: int32 wrap shows up negative first.
    for field in ("n_events", "n_msgs_sent", "n_msgs_dropped",
                  "n_queue_full", "n_inbox_full", "stamp_ctr", "node_ctr",
                  "trace_count"):
        if hasattr(st, field):
            checkify.check(all_(getattr(st, field) >= 0),
                           f"int32 overflow: {field} wrapped negative")
    checkify.check(all_((st.clock >= 0) & (st.clock <= NEVER)),
                   "clock left [0, NEVER]")
    # Gather preconditions: the next chunk indexes node state by queue
    # receiver and payload bank by kind — both must be in range wherever
    # a slot is valid (sentinel-drop writes only ever DROP, so a bad
    # value here means a write invariant broke).
    if hasattr(st, "queue"):
        q = st.queue
        ok_recv = ~q.valid | ((q.receiver >= 0) & (q.receiver < n))
        ok_kind = ~q.valid | ((q.kind >= 0) & (q.kind <= KIND_RESPONSE))
        ok_time = ~q.valid | (q.time >= 0)
        checkify.check(all_(ok_recv), "queue receiver out of [0, n)")
        checkify.check(all_(ok_kind), "queue kind out of range")
        checkify.check(all_(ok_time), "queued event at negative time")
    if hasattr(st, "in_valid"):
        ok_kind = ~st.in_valid | ((st.in_kind >= 0)
                                  & (st.in_kind <= KIND_RESPONSE))
        ok_send = ~st.in_valid | ((st.in_sender >= 0)
                                  & (st.in_sender < n))
        checkify.check(all_(ok_kind), "inbox kind out of range")
        checkify.check(all_(ok_send), "inbox sender out of [0, n)")
    # Protocol-state bounds.
    checkify.check(all_(st.store.current_round >= 1),
                   "store round below 1 (rounds start at 1)")
    checkify.check(all_(st.ctx.commit_count >= 0),
                   "int32 overflow: commit_count wrapped negative")
    # The Context ledger invariant (core/types.py): every depth is either
    # delivered or accounted as skipped.
    checkify.check(
        all_(st.ctx.commit_count + st.ctx.skipped_commits
             == st.ctx.last_depth),
        "commit ledger inconsistent: commit_count + skipped != depth")
    checkify.check(all_(st.timer_time >= 0), "timer at negative time")


@functools.lru_cache(maxsize=None)
def _cached_checked_run(p_structural: SimParams, num_steps: int,
                        batched: bool, engine_name: str):
    checkify = _chk()
    from ..sim import parallel_sim, simulator
    eng = parallel_sim if engine_name == "parallel" else simulator
    scan = eng.make_scan_fn(p_structural, num_steps, batched=batched)

    def checked(st):
        # Both chunk-boundary states are validated: the INPUT check
        # catches corrupt externally-supplied states (checkpoint
        # restores, doctored fixtures) before the scan consumes them —
        # in-chunk transients are the oracle/fuzz harness's job.
        check_state_invariants(p_structural, st)
        st = scan(st)
        check_state_invariants(p_structural, st)
        return st

    errors = checkify.user_checks | checkify.div_checks
    jit_fn = jax.jit(checkify.checkify(checked, errors=errors))
    # AOT executable store (utils/aot.py): the checkify build is its own
    # heavy executable (error plumbing wraps the whole scan) with tables
    # baked into the scan closure — keyed on the FULL resolved params,
    # like the sharded runner.  warm_cache's SANITIZE_SHAPES children
    # export it; tier-1's sanitizer smoke then loads instead of
    # re-deriving.  Wrapped inside this lru cache so repeated
    # make_checked_run_fn calls share one consult/load.
    from ..telemetry import ledger as tledger
    from ..utils import aot

    call = aot.wrap_jit(
        jit_fn, (), key=tledger.params_key(p_structural),
        engine=engine_name, flavor="sanitize", num_steps=num_steps,
        batched=batched)
    # Compile ledger: the checkify build records like the engines', so
    # the store's verdicts (aot-hit/aot-stale/aot-export) land on a real
    # entry instead of vanishing (annotate_compile is a no-op outside an
    # attribution block).  The "sanitize/" engine prefix keeps these rows
    # out of warm_cache --from-ledger, which rebuilds engine chunks only.
    return tledger.wrap_compile(
        call, key=tledger.params_key(p_structural),
        structural=repr(p_structural),
        engine="sanitize/" + engine_name,
        n_nodes=p_structural.n_nodes, num_steps=num_steps, batched=batched)


def make_checked_run_fn(p: SimParams, num_steps: int, batched: bool = True,
                        engine=None):
    """``st -> (error, st)``: the engine's chunk scan under checkify.
    Values are bit-identical to the unchecked scan (checkify only adds
    error plumbing); compile is separate — warm it via
    ``scripts/warm_cache.py`` (the sanitizer children) before tier-1."""
    from ..sim import parallel_sim
    p = xops.resolve_params(p)
    name = "parallel" if engine is parallel_sim else "serial"
    # Memoized like the engines' _compiled_run: note the structural()
    # projection would drop the delay table the scan closure bakes in, so
    # the cache key keeps the full resolved params.
    return _cached_checked_run(p, num_steps, batched, name)


def run_checked(p: SimParams, st, num_steps: int, batched: bool = True,
                engine=None):
    """One checked chunk; raises ``checkify.JaxRuntimeError`` on the first
    tripped invariant, else returns the post-chunk state."""
    err, out = make_checked_run_fn(p, num_steps, batched=batched,
                                   engine=engine)(st)
    err.throw()
    return out


def checked_completion(p: SimParams, st, chunk: int, max_chunks: int,
                       batched: bool, engine):
    """The ``run_to_completion`` drop-in both engines use when
    :func:`enabled` — same halt loop, every chunk checked."""
    import numpy as np
    run = make_checked_run_fn(p, chunk, batched=batched, engine=engine)
    for _ in range(max_chunks):
        err, st = run(st)
        err.throw()
        if bool(np.all(jax.device_get(st.halted))):
            break
    return st
