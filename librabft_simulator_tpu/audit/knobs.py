"""The environment-knob registry: every env var this repo reads, in one
place.

Knobs had accreted across four PRs — lowering overrides in
``utils/xops.py``, a dozen ``BENCH_*`` switches in ``bench.py``, fuzz and
script locals — with no single list, so a typo'd knob silently did
nothing and a new knob shipped undocumented.  This registry is the
machine-checked fix:

* the source lint (:mod:`.source_lint`, rule S3) fails on any
  ``os.environ`` read whose key is not registered here (or in
  :data:`EXTERNAL` — infra vars owned by jax/XLA, not us);
* the README "Configuration knobs" table is GENERATED from this file
  (``python -m librabft_simulator_tpu.audit.knobs --write-readme``;
  ``--check`` verifies sync, and the audit runs the check), so docs
  cannot drift from code.

To add a knob: read it in code, add a :class:`Knob` row here, regenerate
the README table.  The lint makes all three happen or none.
"""

from __future__ import annotations

import dataclasses
import os
import sys


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str     # the env var
    group: str    # "engine" | "bench" | "fuzz" | "script"
    where: str    # module that reads it
    values: str   # accepted values / type, human-readable
    desc: str     # one line


KNOBS: tuple[Knob, ...] = (
    # --- engine lowering / debug (read inside the package) ---------------
    Knob("LIBRABFT_WRITE_MODE", "engine", "utils/xops.py",
         "scatter|dense",
         "A/B override for the queue-write lowering form "
         "(SimParams.dense_writes='auto' resolves TPU->dense)."),
    Knob("LIBRABFT_PACKED", "engine", "utils/xops.py", "0|1",
         "A/B override for the packed [N,S] node-state planes "
         "(SimParams.packed=None resolves TPU->on)."),
    Knob("LIBRABFT_GATE_HANDLERS", "engine", "utils/xops.py", "0|1",
         "A/B override for lax.cond handler gating "
         "(SimParams.gate_handlers=None resolves TPU->on)."),
    Knob("LIBRABFT_MACRO_K", "engine", "utils/xops.py", "int >= 1",
         "A/B override for the serial engine's K-event macro-steps "
         "(SimParams.macro_k=None resolves env->K, else 1; each "
         "dispatched step retires K events, bit-identically)."),
    Knob("LIBRABFT_WRAP", "engine", "utils/xops.py", "host|device",
         "A/B override for the fleet dispatch wrap (SimParams.wrap=None "
         "resolves env->mode, else 'host').  'device' wraps the chunk "
         "scan in an in-graph while loop that retires up to ring_k "
         "chunks per dispatched outer program, streaming each chunk's "
         "[13] digest into a device-side ring — one host egress per "
         "outer call instead of per chunk, bit-identically."),
    Knob("LIBRABFT_RING_K", "engine", "utils/xops.py", "int >= 1",
         "A/B override for the device-dispatch ring depth "
         "(SimParams.ring_k=None resolves env->K, else 16).  Only "
         "meaningful under wrap='device' (normalized out of host-wrap "
         "compile keys); a compile key — the [K,13] ring shape is baked "
         "into the outer program."),
    Knob("LIBRABFT_CHECKIFY", "engine", "audit/sanitize.py", "0|1",
         "Debug: run_to_completion runs the checkify-instrumented chunk "
         "(state-invariant + div checks) and raises on the first trip; "
         "off (default) leaves the engine graphs untouched.  Mutually "
         "exclusive with stream= (the stream loop is unchecked — "
         "run_to_completion refuses the combination)."),
    Knob("LIBRABFT_COMPILE_CACHE", "engine", "utils/cache.py",
         "path|0|off",
         "The ONE persistent XLA compile-cache directory every entry "
         "point shares (default /tmp/jax_cache; tier-1, warm_cache.py, "
         "bench.py and the CLI all hit the same cache).  0/off disables "
         "persistent caching."),
    Knob("LIBRABFT_LEDGER_OUT", "engine", "telemetry/ledger.py", "path",
         "Stream the host-side runtime ledger (compile/dispatch/poll "
         "spans + compile ledger) as NDJSON to this path, flushed per "
         "row — readable mid-run (and after a timeout kill) with "
         "scripts/fleet_watch.py --ledger.  Unset: the ledger stays "
         "in-memory only."),
    Knob("LIBRABFT_AOT", "engine", "utils/aot.py", "0|1",
         "Consult the AOT executable store before tracing (default on): "
         "make_run_fn / make_sharded_run_fn / the sanitizer build load a "
         "ready serialized executable on a store hit (ledger verdict "
         "aot-hit) and fall back to the untouched jit path on any miss, "
         "staleness, or load error.  0 = provably inert pass-through."),
    Knob("LIBRABFT_AOT_DIR", "engine", "utils/aot.py", "path",
         "The AOT store directory (default /tmp/librabft_aot): a "
         "relocatable artifact dir of serialized executables + sidecars "
         "+ manifest.json, built by scripts/warm_cache.py and listed by "
         "python -m librabft_simulator_tpu.utils.aot --list."),
    Knob("LIBRABFT_SERVE_SLOTS", "engine", "serve/api.py", "int >= 1",
         "Resident fleet service: slot count of the continuously-batched "
         "fleet (default 8; rounded up to the mesh size).  FleetService "
         "constructor args override."),
    Knob("LIBRABFT_SERVE_CHUNK", "engine", "serve/api.py", "int >= 1",
         "Resident fleet service: macro-steps per dispatched chunk "
         "(default 64) — the admission/egress granularity, since the "
         "host inspects one [13] digest per chunk."),
    Knob("LIBRABFT_SERVE_OUT", "engine", "serve/api.py", "path",
         "Stream the service's digest + request-lifecycle NDJSON here "
         "(admission queue depth, slot occupancy, per-request ttfc); "
         "follow live with scripts/fleet_watch.py --serve."),
    Knob("LIBRABFT_SERVE_RING_K", "engine", "serve/api.py", "int >= 1",
         "Resident fleet service: arm the device dispatch wrap at this "
         "ring depth — admission and egress then land at outer-call "
         "boundaries (up to ring_k chunks apart), trading admission "
         "latency for up-to-ring_k-fewer host polls per retired chunk "
         "(RUNTIME_LEDGER_r14 quantifies the tradeoff).  Unset: the "
         "base params' own wrap/ring_k resolution decides."),
    Knob("LIBRABFT_DIST_COORD", "engine", "distributed/bootstrap.py",
         "host:port",
         "Multi-process fleet: the jax.distributed coordinator address "
         "(the standard pod-launcher triple with _NPROC/_PID; "
         "local_cluster sets all three for its children).  Unset or "
         "_NPROC<=1: single-process, nothing initializes."),
    Knob("LIBRABFT_DIST_NPROC", "engine", "distributed/bootstrap.py",
         "int >= 1",
         "Multi-process fleet: total process count of the job.  > 1 "
         "arms jax.distributed.initialize (gloo collectives on CPU) at "
         "bootstrap.init_from_env(); the 'dp' mesh then spans every "
         "process's devices."),
    Knob("LIBRABFT_DIST_PID", "engine", "distributed/bootstrap.py",
         "0..NPROC-1",
         "Multi-process fleet: this process's id within the job "
         "(required, with _COORD, whenever _NPROC > 1 — a partial "
         "triple fails loud)."),
    Knob("LIBRABFT_AOT_WRITE", "engine", "utils/aot.py", "0|1",
         "Export freshly compiled chunk executables back into the AOT "
         "store on a miss (default off; warm_cache children set it). "
         "The export compile bypasses the persistent XLA cache (a "
         "cache-hydrated executable re-serializes broken) and the "
         "written artifact is verified by loading it back."),
    # --- bench.py -------------------------------------------------------
    Knob("BENCH_PLATFORM", "bench", "bench.py", "cpu|tpu",
         "Force the bench backend (skips the tunnel probe)."),
    Knob("BENCH_SUPERVISED", "bench", "bench.py", "1",
         "Internal: set in the watchdog-supervised child."),
    Knob("BENCH_ATTACH_MARKER", "bench", "bench.py", "path",
         "Internal: attach-progress marker file for the supervisor."),
    Knob("BENCH_INIT_TIMEOUT", "bench", "bench.py", "seconds",
         "Backend-attach watchdog budget (default 600)."),
    Knob("BENCH_PROBE_DIAG", "bench", "bench.py", "text",
         "Internal: tunnel-probe diagnosis carried into the child."),
    Knob("BENCH_TUNNEL_PORTS", "bench", "bench.py", "p1,p2,...",
         "TPU tunnel relay ports to probe (default 8082,8083,8087)."),
    Knob("BENCH_B", "bench", "bench.py", "int",
         "Headline bench batch size (default 2048)."),
    Knob("BENCH_STEPS", "bench", "bench.py", "int",
         "Events per timed dispatch (default 32; sweeps 64/16)."),
    Knob("BENCH_REPS", "bench", "bench.py", "int",
         "Timed repetitions per config."),
    Knob("BENCH_NODES", "bench", "bench.py", "int",
         "Nodes per instance (default 4)."),
    Knob("BENCH_ENGINE", "bench", "bench.py", "serial|parallel|both",
         "Which engine(s) the headline bench times."),
    Knob("BENCH_SELECT", "bench", "bench.py", "xla|pallas",
         "Event-selection kernel for the serial engine."),
    Knob("BENCH_TELEMETRY", "bench", "bench.py", "1",
         "Attach the decoded telemetry block to the contract line."),
    Knob("BENCH_SWEEP", "bench", "bench.py", "1",
         "Run the 5-config BASELINE sweep instead of the headline."),
    Knob("BENCH_SWEEP_SCALE", "bench", "bench.py", "float",
         "Sweep instance-count scale (default 1.0 on TPU, 0.1 host)."),
    Knob("BENCH_SWEEP_ONLY", "bench", "bench.py", "1-based index",
         "Run a single sweep config (warm_cache children use this)."),
    Knob("BENCH_SWEEP_OUT", "bench", "bench.py", "path",
         "Sweep artifact path (default BENCH_SWEEP.json)."),
    Knob("BENCH_FLEET", "bench", "bench.py", "1",
         "Run the dp-ladder fleet bench (one subprocess per rung)."),
    Knob("BENCH_FLEET_CHILD", "bench", "bench.py", "dp",
         "Internal: marks a fleet-ladder rung child."),
    Knob("BENCH_FLEET_ENGINE", "bench", "bench.py", "serial|parallel",
         "Fleet-ladder engine (default serial)."),
    Knob("BENCH_FLEET_B", "bench", "bench.py", "int",
         "Per-shard instances per rung (default 256)."),
    Knob("BENCH_FLEET_STEPS", "bench", "bench.py", "int",
         "Events per chunk per rung (default 16)."),
    Knob("BENCH_FLEET_REPS", "bench", "bench.py", "int",
         "Timed chunk repetitions per rung (default 2)."),
    Knob("BENCH_FLEET_DP", "bench", "bench.py", "d1,d2,...",
         "Ladder rungs (default 1,2,4,8)."),
    Knob("BENCH_FLEET_OUT", "bench", "bench.py", "path",
         "Fleet-ladder artifact path."),
    Knob("BENCH_STREAM", "bench", "bench.py", "1",
         "Stream per-chunk digests during the fleet ladder (NDJSON + "
         "FLEET_TIMELINE artifact)."),
    Knob("BENCH_STREAM_OUT", "bench", "bench.py", "path",
         "NDJSON timeline path for BENCH_STREAM."),
    Knob("BENCH_WATCHDOG", "bench", "bench.py", "1",
         "Arm the consensus watchdog in the fleet ladder."),
    Knob("BENCH_MACRO", "bench", "bench.py", "1",
         "Run the macro-step K-ladder (K in BENCH_MACRO_KS, one "
         "subprocess per rung): ev/s + fusions-per-event per rung, "
         "BENCH_MACRO_r11.json artifact (CPU-lowering proxy)."),
    Knob("BENCH_MACRO_CHILD", "bench", "bench.py", "K",
         "Internal: marks a macro-ladder rung child."),
    Knob("BENCH_MACRO_KS", "bench", "bench.py", "k1,k2,...",
         "Macro-ladder rungs (default 1,4,16,64)."),
    Knob("BENCH_MACRO_OUT", "bench", "bench.py", "path",
         "Macro-ladder artifact path."),
    Knob("BENCH_MACRO_CENSUS", "bench", "bench.py", "0|1",
         "Census fusions-per-event per macro rung (default on; off "
         "skips the second compile per rung)."),
    Knob("BENCH_LEDGER_OUT", "bench", "bench.py", "path",
         "RUNTIME_LEDGER artifact path for the fleet ladder (default "
         "RUNTIME_LEDGER_r13.json): per-rung compile ledger, per-chunk "
         "dispatch/poll spans, measured pipeline-overlap fraction, and "
         "the time_to_first_chunk headline with the ttfc_aot/ttfc_jit "
         "A/B."),
    Knob("BENCH_FLEET_AOT_AB", "bench", "bench.py", "0|1",
         "Per-rung AOT A/B in the fleet ladder (default on): each dp "
         "rung runs a second cold process with LIBRABFT_AOT=0, landing "
         "ttfc_aot (store-loaded) vs ttfc_jit (trace+lower+compile) in "
         "the RUNTIME_LEDGER artifact.  0 = production leg only."),
    Knob("BENCH_RING", "bench", "bench.py", "1",
         "Run the device-dispatch ring ladder (one subprocess per rung): "
         "host-vs-device A/B at each ring depth in BENCH_RING_KS — "
         "ttfc, polls-per-retired-chunk, ev/s per rung — writing the "
         "RUNTIME_LEDGER_r14 artifact (CPU-lowering proxy)."),
    Knob("BENCH_RING_CHILD", "bench", "bench.py", "json",
         "Internal: marks a ring-ladder rung child (k/wrap/dp/engine)."),
    Knob("BENCH_RING_KS", "bench", "bench.py", "k1,k2,...",
         "Ring-ladder depths (default 1,4,16,64)."),
    Knob("BENCH_RING_B", "bench", "bench.py", "int",
         "Ring ladder: instances per shard (default 64)."),
    Knob("BENCH_RING_STEPS", "bench", "bench.py", "int",
         "Ring ladder: macro-steps per chunk (default 8)."),
    Knob("BENCH_RING_CHUNKS", "bench", "bench.py", "int",
         "Ring ladder: timed chunks per rung (default 64; non-halting "
         "horizon, so device rungs retire full caps)."),
    Knob("BENCH_RING_OUT", "bench", "bench.py", "path",
         "Ring-ladder artifact path (default RUNTIME_LEDGER_r14.json)."),
    Knob("BENCH_POD", "bench", "bench.py", "1",
         "Run the multi-process pod ladder (scripts/fleet_pod.py): "
         "1/2/4 REAL jax.distributed processes over a loopback "
         "coordinator, per-host digest streams + ledger spans + "
         "checkpoint-shard egress, MULTIHOST_FLEET artifact "
         "(CPU-emulated; ~1/P efficiency caveat)."),
    Knob("BENCH_POD_PROCS", "bench", "scripts/fleet_pod.py", "p1,p2,...",
         "Pod-ladder rungs in process count (default 1,2,4)."),
    Knob("BENCH_POD_B", "bench", "scripts/fleet_pod.py", "int",
         "Pod ladder: instances PER PROCESS (weak scaling; default 64)."),
    Knob("BENCH_POD_STEPS", "bench", "scripts/fleet_pod.py", "int",
         "Pod ladder: macro-steps per dispatched chunk (default 16)."),
    Knob("BENCH_POD_REPS", "bench", "scripts/fleet_pod.py", "int",
         "Pod ladder: minimum dispatched chunks per rung (default 4)."),
    Knob("BENCH_POD_OUT", "bench", "scripts/fleet_pod.py", "path",
         "Pod-ladder artifact path (default MULTIHOST_FLEET_r15.json)."),
    Knob("BENCH_POD_AOT_DIR", "bench", "scripts/fleet_pod.py", "path",
         "Pod ladder: the per-topology AOT store the rungs warm "
         "(default /tmp/librabft_aot_pod).  Multi-process CPU cannot "
         "share the persistent XLA cache across processes (the device "
         "assignment rides the cache key on non-GPU platforms), so the "
         "store is how rung reruns — and real pods — skip every "
         "process's recompile."),
    # --- fuzz -----------------------------------------------------------
    Knob("FUZZ_PACKED", "fuzz", "scripts/fuzz_parity.py", "0|1",
         "Run every fuzz trial on the packed-plane engine."),
    Knob("FUZZ_MACRO_K", "fuzz", "scripts/fuzz_parity.py", "0|1",
         "Randomize the serial engine's macro_k per trial (K in "
         "{1,2,4,8}; minidumps record it); writes the macro-flavor "
         "campaign artifact FUZZ_PARITY_r11_macro.json."),
    Knob("FUZZ_SCENARIO", "fuzz", "scripts/fuzz_parity.py", "0|1",
         "Heterogeneous-fleet mode: every trial runs a small batch of "
         "randomized per-slot scenario rows (delay/drop/commit-chain/"
         "Byzantine schedule/seed) on ONE scenario-armed executable and "
         "pins each slot against its own oracle; minidumps record the "
         "full plane.  Writes FUZZ_PARITY_r14_scenario.json."),
    Knob("FUZZ_ADVERSARY", "fuzz", "scripts/fuzz_parity.py", "0|1",
         "Adversary-engine campaign mode: every trial runs a randomized "
         "attack program (windowed equivocation/silence/forged QCs, "
         "targeted + leader-targeted delay, per-link matrices, "
         "partition-with-heal — adversary/dsl.sample_program) on the "
         "adversary-armed serial engine and checks full oracle parity; "
         "minidumps record the DECODED program.  Writes "
         "FUZZ_PARITY_r17_adversary.json."),
    Knob("LIBRABFT_ADV_WINDOWS", "fuzz", "scripts/fuzz_parity.py",
         "int >= 1",
         "FUZZ_ADVERSARY campaign: attack-schedule window capacity W of "
         "the fuzzed plane (SimParams.adv_windows; default 4).  A "
         "compile key — each W is one executable per structural shape."),
    # --- script-local ---------------------------------------------------
    Knob("LADDER_UNROLL", "script", "scripts/tpu_ladder.py", "0|1",
         "Census/ladder the unrolled-scan variant."),
    Knob("LADDER_CHUNK", "script", "scripts/tpu_ladder.py", "int",
         "Events per timed dispatch (default 64)."),
    Knob("LADDER_REPS", "script", "scripts/tpu_ladder.py", "int",
         "Timed repetitions (default 2)."),
    Knob("XPLAT_NODES", "script", "scripts/xplat_parity.py", "int",
         "Cross-platform parity config: nodes."),
    Knob("XPLAT_DELAY", "script", "scripts/xplat_parity.py", "kind",
         "Cross-platform parity config: delay kind."),
    Knob("XPLAT_DROP", "script", "scripts/xplat_parity.py", "float",
         "Cross-platform parity config: drop probability."),
    Knob("XPLAT_CHAIN", "script", "scripts/xplat_parity.py", "2|3",
         "Cross-platform parity config: commit chain."),
    Knob("AB_B", "script", "scripts/scatter_ab.py", "int",
         "Scatter-vs-dense A/B batch size."),
    Knob("AB_ITERS", "script", "scripts/scatter_ab.py", "int",
         "Scatter-vs-dense A/B iterations."),
    Knob("PN", "script", "scripts/component_profile.py", "int",
         "Component profile: nodes."),
    Knob("PB", "script", "scripts/component_profile.py", "int",
         "Component profile: batch."),
    Knob("PREPS", "script", "scripts/component_profile.py", "int",
         "Component profile: repetitions."),
    Knob("PHO", "script", "scripts/component_profile.py", "0|1",
         "Component profile: epoch handoff on."),
    Knob("LIBRABFT_OBS_WINDOW_S", "engine", "telemetry/observatory.py",
         "float > 0",
         "Fleet observatory: default rollup window (seconds, default "
         "1.0) for windowed counter/gauge aggregation over ingested "
         "NDJSON streams.  Query-time only — ingest stores raw rows."),
    Knob("BENCH_SENTINEL_REPS", "script", "scripts/perf_sentinel.py",
         "int >= 1",
         "Perf sentinel: measurements per rung; the history row records "
         "the median (default 3), so one scheduler hiccup cannot poison "
         "a baseline."),
    Knob("BENCH_SENTINEL_OUT", "script", "scripts/perf_sentinel.py",
         "path",
         "Perf sentinel: history NDJSON path (default the committed "
         "BENCH_HISTORY.ndjson at the repo root)."),
    Knob("BENCH_SENTINEL_RUNGS", "script", "scripts/perf_sentinel.py",
         "name,name,...",
         "Perf sentinel: comma-separated subset of the canonical rung "
         "matrix (serial_step lane_step fleet_chunk macro_k16 aot_ttfc "
         "serve_admit ring_dispatch; default all)."),
    Knob("BENCH_SENTINEL_TOL_PCT", "script", "scripts/perf_sentinel.py",
         "float > 0",
         "Perf sentinel: regression tolerance in percent over the "
         "rolling-median baseline (default scripts/budgets.py "
         "bench_sentinel_tol_pct; ci_tier1.sh materializes it)."),
    Knob("BENCH_SENTINEL_SLOWDOWN", "script", "scripts/perf_sentinel.py",
         "float >= 1",
         "Perf sentinel self-test hook: scale every recorded value this "
         "factor WORSE after measurement (rates divided, times "
         "multiplied) — proves the gate fires without burning the CPU "
         "(tests/test_observatory.py)."),
)

REGISTERED = frozenset(k.name for k in KNOBS)

#: Infra variables owned by jax/XLA/the tunnel stack — read, never defined,
#: by this repo; exempt from registration (but still resolved by the lint).
EXTERNAL = frozenset({"JAX_PLATFORMS", "XLA_FLAGS", "PALLAS_AXON_POOL_IPS"})

_GROUP_TITLES = (
    ("engine", "Engine lowering & debug"),
    ("bench", "bench.py"),
    ("fuzz", "Fuzzing"),
    ("script", "Script-local"),
)

BEGIN_MARK = "<!-- knobs:begin (generated by audit/knobs.py; do not edit) -->"
END_MARK = "<!-- knobs:end -->"


def readme_table() -> str:
    """The generated README block (between the knob markers)."""
    lines = [BEGIN_MARK, ""]
    for group, title in _GROUP_TITLES:
        rows = [k for k in KNOBS if k.group == group]
        if not rows:
            continue
        lines += [f"**{title}**", "",
                  "| Knob | Values | Read by | Effect |",
                  "|---|---|---|---|"]
        for k in rows:
            lines.append(
                f"| `{k.name}` | `{k.values}` | `{k.where}` | {k.desc} |")
        lines.append("")
    lines.append(END_MARK)
    return "\n".join(lines)


def _split_readme(text: str) -> tuple[str, str, str]:
    if BEGIN_MARK not in text or END_MARK not in text:
        raise ValueError(
            "README has no knob-table markers; add the "
            f"'{BEGIN_MARK}' / '{END_MARK}' pair under a 'Configuration "
            "knobs' heading first")
    head, rest = text.split(BEGIN_MARK, 1)
    _, tail = rest.split(END_MARK, 1)
    return head, text[len(head):len(text) - len(tail)], tail


def readme_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "README.md")


def readme_in_sync(path: str | None = None) -> bool:
    with open(path or readme_path()) as f:
        _, current, _ = _split_readme(f.read())
    return current == readme_table()


def write_readme(path: str | None = None) -> None:
    path = path or readme_path()
    with open(path) as f:
        head, _, tail = _split_readme(f.read())
    with open(path, "w") as f:
        f.write(head + readme_table() + tail)


def main(argv) -> int:
    if "--write-readme" in argv:
        write_readme()
        print(f"wrote knob table ({len(KNOBS)} knobs) into README.md")
        return 0
    if "--check" in argv:
        ok = readme_in_sync()
        print("README knob table " + ("in sync" if ok else
              "STALE — run python -m librabft_simulator_tpu.audit.knobs "
              "--write-readme"))
        return 0 if ok else 1
    # Default: print the table (for piping / review).
    print(readme_table())
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
