"""Jaxpr-level graph auditor: machine-checked invariants of the traced step.

Both engines' step functions are traced (``jax.make_jaxpr`` — no XLA
compile, so a full audit costs seconds, not census minutes) in every
lowering flavor the fleet can run — cpu_default, tpu_shape (packed planes +
dense writes + gated handlers), the telemetry/watchdog twins, and the
dp-sharded runner — and the resulting ClosedJaxprs are walked eqn by eqn
against the rules below.  Per the JAX tracing model (PAPERS.md), every
property here is decidable on the jaxpr: the graph IS the program.

Rules
-----

R1  **No miscompile-class writes in TPU-gated graphs.**  The axon TPU
    stack miscompiles vmapped *scalar* scatters at fleet batch sizes
    (scripts/tpu_scatter_bug_repro.py; the PR-1 corruption was 21 vs
    34,144 commits).  In any graph a TPU lowering can run (``packed`` /
    ``dense_writes="dense"`` / gated flavors): scalar scatters and
    scalar dynamic-update-slices with traced indices are HARD errors
    (never waivable); *vector* scatters with traced indices — the
    fuzz-certified, chip-validated form the inbox router and free-slot
    ranker use — are allowed only at sites enumerated in
    :data:`R1_WAIVERS`.  Constant-index forms (the telemetry plane's
    static-offset slice updates) always pass.
R2  **Integer discipline.**  Consensus state is int32/uint32/bool by
    design (README "Determinism & parity": no device floats anywhere, so
    trajectories are bit-identical across backends).  Every carry of
    every ``while``/``scan``, every step output leaf, and in fact every
    eqn output in the step graph must be integer/bool-typed; a float
    escaping the (host-precomputed, integer-quantized) RNG-delay tables
    into the graph is flagged at the offending eqn.
R3  **No host callbacks.**  ``pure_callback`` / ``io_callback`` /
    ``debug_callback`` inside a jitted step would serialize every
    dispatch through the host — flagged anywhere in any flavor.
R4  **Fixed-shape loop carries.**  Every ``scan``/``while`` body must
    carry exactly the avals it receives (no shape polymorphism across
    iterations) — the property that lets one compiled while loop serve
    the whole run.
R5  **Digest-only host contract.**  The sharded chunk runner's only
    small (host-fetched) output is the ``[DIGEST_WIDTH]`` int32 digest;
    every other output is a fleet-sized state leaf (leading dim = padded
    batch).  This is the static form of the monkeypatched-``device_get``
    test in tests/test_multichip.py.  The device dispatch wrap
    (``SimParams.wrap="device"``) gets its own arm: the ring runner's
    only small outputs are ONE ``[ring_k, DIGEST_WIDTH]`` int32 digest
    ring plus ONE scalar int retired count (:func:`check_r5_ring`).
R6  **Knob-off graph equality.**  With telemetry/watchdog off the graph
    must be *structurally identical* to the baseline — checked in its
    strongest form: the knob-ON graph, dead-code-eliminated to its
    consensus outputs, must equal the knob-OFF graph eqn-for-eqn
    (``pe.dce_jaxpr``).  That proves observability is write-only — it
    reads consensus state, nothing flows back — turning the engine
    bit-identity tests into a static guarantee.  For ``mp_authors``: the
    off graph must contain zero 'mp'-axis collectives inside the chunk
    scan, and the armed (n_mp=1) graph must contain the quorum psums.
    For the dispatch wrap: ``wrap="host"`` must trace eqn-identical to
    an inline-built pre-ring twin (:func:`check_r6_ring`) — the device
    ring is a sibling branch, never a wrapper on the default path.

Waivers: ``R1_WAIVERS`` maps (package-relative file) -> justification for
*vector*-class traced-index writes.  Scalar-class hits cannot be waived.
Add a waiver only with a fuzz campaign + census entry behind it, and say
so in the justification (see README "Static guarantees").
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
from typing import Any, Callable

import numpy as np
import jax
import jax.numpy as jnp
from jax.interpreters import partial_eval as pe

from ..core import packing
from ..core.types import SimParams
from ..telemetry import stream as tstream

try:  # Literal moved across jax versions; all of these are the same class.
    from jax.extend.core import Literal  # type: ignore
except ImportError:  # pragma: no cover
    from jax.core import Literal  # type: ignore

try:
    from jax._src import source_info_util as _siu
except ImportError:  # pragma: no cover
    _siu = None

#: The fixed digest width of the sharded poll contract (R5).  Pinned here
#: *independently* of telemetry/stream.py so a registry edit that widens
#: the digest shows up as an audit finding, not a silent contract change.
DIGEST_WIDTH = 13

# Audit micro shapes: capacity-trimmed params for fast auditing in tests
# (tests/test_audit.py).  Observability knobs are left OFF here — the
# auditor toggles them per flavor.  tests/fleet_shapes.py's warmed fleet
# shapes are these plus telemetry/trace capacities.
MICRO_SER_KW = dict(n_nodes=3, window=8, chain_k=2, commit_log=8,
                    queue_cap=16)
MICRO_LANE_KW = dict(MICRO_SER_KW, n_nodes=4, delay_kind="uniform")
# The kernel-census shape (scripts/kernel_census.py defaults): what CI
# audits, so the censused graph and the audited graph are the same trace.
CENSUS_KW = dict(n_nodes=4, delay_kind="uniform", queue_cap=32)

#: R1 vector-write waivers: package-relative file -> justification.  Only
#: the VECTOR class is waivable; see the module docstring.
R1_WAIVERS = {
    "sim/simulator.py":
        "free-slot rank assignment (step's slot_of_rank): a [<=2n+1]-index "
        "vector scatter with unique in-range ranks + sentinel drop; not in "
        "the scalar-scatter miscompile class, certified by the 1,222-trial "
        "FUZZ_PACKED campaign and the round-5 on-chip parity runs.",
    "sim/parallel_sim.py":
        "lane scatter-back + inbox routing: [A]- and [K*A*(2n+1)]-index "
        "vector row scatters with distinct targets (PERF_NOTES.md calls "
        "these the proven-safe class); chip-validated at B=1024 in round 5.",
}


#: Pinned waived-site counts per flavor: a waiver is file-granular, so a
#: NEW vector scatter in an already-waived engine file would silently ride
#: the existing waiver — this pin makes it fail loudly instead.  When the
#: count changes on purpose (site added/removed), recertify (fuzz +
#: census) and re-pin here; the audit error text says so.
R1_EXPECTED_WAIVED = {
    "serial/tpu_shape": 1,        # free-slot rank scatter
    "serial/tpu_telemetry": 1,
    "serial/tpu_watchdog": 1,
    # K-macro flavors: the rolled inner scan's body is traced ONCE, so
    # the jaxpr carries the same single waived site regardless of K.
    "serial/tpu_shape_k4": 1,
    "serial/tpu_shape_k16": 1,
    # Scenario-plane flavor (SimParams.scenario): per-slot knobs ride as
    # traced data; no new write sites — the plane is READ-only config
    # (the R6 scenario arm pins pass-through).
    "serial/tpu_shape_scenario": 1,
    # Adversary-plane flavor (SimParams.adversary): the attack-schedule /
    # link / partition decode is one-hot/select forms only — no new
    # write sites (the plane is READ-only config; the R6 adversary arm
    # pins pass-through).
    "serial/tpu_shape_adversary": 1,
    "lane/tpu_shape": 13,         # lane scatter-back + inbox routing
    "lane/tpu_telemetry": 14,     # + the flight-recorder ring scatter
    "lane/tpu_watchdog": 13,
    "lane/tpu_shape_scenario": 13,
    "lane/tpu_shape_adversary": 13,
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str        # "R1".."R6"
    flavor: str      # e.g. "serial/tpu_shape"
    severity: str    # "error" | "waived"
    summary: str
    site: str = ""   # "file:function:line" when recoverable from the trace

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# Jaxpr walking.
# ---------------------------------------------------------------------------


def _subjaxprs(params: dict) -> list:
    """Every Jaxpr nested in an eqn's params (scan/while/cond/pjit/
    shard_map/custom_* all stash theirs under different keys and shapes —
    recurse by type, not by name, so new primitives keep working)."""
    out = []

    def rec(v):
        t = type(v).__name__
        if t == "ClosedJaxpr":
            out.append(v.jaxpr)
        elif t == "Jaxpr":
            out.append(v)
        elif isinstance(v, (tuple, list)):
            for x in v:
                rec(x)

    for v in params.values():
        rec(v)
    return out


def iter_eqns(jaxpr, depth: int = 0, in_loop: bool = False):
    """Yield ``(depth, eqn, in_loop)`` over every eqn, recursively.
    ``in_loop`` is True inside any scan/while body — R6's mp check needs
    to distinguish per-iteration collectives from chunk-boundary ones."""
    for eqn in jaxpr.eqns:
        yield depth, eqn, in_loop
        looped = in_loop or eqn.primitive.name in ("scan", "while")
        for sub in _subjaxprs(eqn.params):
            yield from iter_eqns(sub, depth + 1, looped)


def eqn_site(eqn) -> str:
    """Best-effort ``file:function:line`` of the user frame that created an
    eqn (jax keeps source provenance on the trace)."""
    if _siu is None:
        return ""
    try:
        fallback = ""
        for frame in _siu.user_frames(eqn.source_info):
            name = frame.file_name.replace("\\", "/")
            if "librabft_simulator_tpu" in name:
                rel = name.split("librabft_simulator_tpu/", 1)[-1]
                return f"{rel}:{frame.function_name}:{frame.start_line}"
            if not fallback:
                fallback = f"{name}:{frame.function_name}:{frame.start_line}"
        return fallback
    except Exception:  # noqa: BLE001 — provenance is advisory; a lost
        pass           # site makes a vector hit UNWAIVABLE (fail-safe)
    return ""


def _site_file(site: str) -> str:
    return site.split(":", 1)[0] if site else ""


def eqn_signature(jaxpr) -> tuple:
    """Structural signature of an eqn sequence: (primitive, output avals,
    nested signatures), recursively.  Variable *names* and literal values
    are excluded on purpose — two traces of the same program must compare
    equal even though jax renumbers vars per trace."""
    out = []
    for eqn in jaxpr.eqns:
        out.append((
            eqn.primitive.name,
            tuple(str(v.aval) for v in eqn.outvars),
            tuple(eqn_signature(s) for s in _subjaxprs(eqn.params)),
        ))
    return tuple(out)


def signature_hash(jaxpr) -> str:
    """sha256 of the structural signature — the eqn-sequence hash recorded
    per flavor in GRAPH_AUDIT artifacts (drift observability)."""
    return hashlib.sha256(repr(eqn_signature(jaxpr)).encode()).hexdigest()


# ---------------------------------------------------------------------------
# Write-op classification (R1).
# ---------------------------------------------------------------------------

_SCATTER_PRIMS = ("scatter", "scatter-add", "scatter-mul", "scatter-min",
                  "scatter-max", "scatter_apply")


def classify_write(eqn) -> str | None:
    """Classify a scatter/dynamic-update-slice eqn:

    ``"static"``  — constant (Literal) indices: compile-time addressing.
    ``"scalar"``  — ONE traced-index update (the miscompile class).
    ``"vector"``  — K>1 traced-index updates (the proven class).
    ``None``      — not a write-op eqn.
    """
    name = eqn.primitive.name
    if name in _SCATTER_PRIMS:
        idx = eqn.invars[1]  # (operand, scatter_indices, updates)
        if isinstance(idx, Literal):
            return "static"
        shape = tuple(idx.aval.shape)
        # lax convention: the LAST indices dim is the index vector; the
        # rest enumerate updates.  Rank-1 [k] is a single k-coordinate
        # index (one update) — the conservative (scalar) reading.
        n_upd = int(np.prod(shape[:-1])) if len(shape) > 1 else 1
        return "vector" if n_upd > 1 else "scalar"
    if name == "dynamic_update_slice":
        starts = eqn.invars[2:]
        if all(isinstance(v, Literal) for v in starts):
            return "static"
        upd = eqn.invars[1]
        size = int(np.prod(upd.aval.shape)) if upd.aval.shape else 1
        return "vector" if size > 1 else "scalar"
    return None


# ---------------------------------------------------------------------------
# Rule passes over one traced flavor.
# ---------------------------------------------------------------------------


def check_r1(jaxpr, flavor: str) -> tuple[list[Finding], dict]:
    findings, stats = [], {"static": 0, "scalar": 0, "vector": 0,
                           "vector_waived": 0}
    for _, eqn, _ in iter_eqns(jaxpr):
        cls = classify_write(eqn)
        if cls is None:
            continue
        stats[cls] += 1
        site = eqn_site(eqn)
        if cls == "static":
            continue
        if cls == "scalar":
            findings.append(Finding(
                "R1", flavor, "error",
                f"scalar traced-index {eqn.primitive.name} — the TPU "
                "miscompile class (scripts/tpu_scatter_bug_repro.py); "
                "use utils/xops.wset (one-hot where) or scatter_set",
                site))
        else:
            waiver = R1_WAIVERS.get(_site_file(site))
            if waiver:
                stats["vector_waived"] += 1
                findings.append(Finding(
                    "R1", flavor, "waived",
                    f"vector traced-index {eqn.primitive.name} (waived: "
                    f"{waiver.split(':')[0]})", site))
            else:
                findings.append(Finding(
                    "R1", flavor, "error",
                    f"vector traced-index {eqn.primitive.name} at an "
                    "unwaived site — if this form is deliberate, certify "
                    "it (fuzz + census) and add an R1_WAIVERS entry",
                    site))
    return findings, stats


def _loop_carries(eqn):
    """(label, [in avals], [out avals]) for a scan/while eqn's carries."""
    name = eqn.primitive.name
    if name == "scan":
        body = eqn.params["jaxpr"].jaxpr
        nc, nconst = eqn.params["num_carry"], eqn.params["num_consts"]
        ins = [v.aval for v in body.invars[nconst:nconst + nc]]
        outs = [v.aval for v in body.outvars[:nc]]
        return "scan", ins, outs
    if name == "while":
        body = eqn.params["body_jaxpr"].jaxpr
        return "while", [v.aval for v in body.invars], \
            [v.aval for v in body.outvars]
    return None


def _non_integer(dt) -> bool:
    """True for any non-int/uint/bool dtype.  Allowlist, not a 'kind ==
    f' denylist: bfloat16/float8 register under ml_dtypes with numpy kind
    'V', and complex is 'c' — all of them must trip R2."""
    if dt is None:
        return False
    return np.dtype(dt).kind not in "iub"


def check_r2(jaxpr, flavor: str, out_avals=None) -> tuple[list[Finding], dict]:
    findings = []
    n_float = 0
    for _, eqn, _ in iter_eqns(jaxpr):
        carries = _loop_carries(eqn)
        if carries is not None:
            label, ins, _ = carries
            for av in ins:
                if _non_integer(getattr(av, "dtype", None)):
                    findings.append(Finding(
                        "R2", flavor, "error",
                        f"non-integer {label} carry {av} — consensus "
                        "state is int32/uint32/bool only", eqn_site(eqn)))
        for v in eqn.outvars:
            if _non_integer(getattr(v.aval, "dtype", None)):
                n_float += 1
                findings.append(Finding(
                    "R2", flavor, "error",
                    f"non-integer eqn output {v.aval} from "
                    f"{eqn.primitive.name} — the graph is integer-only by "
                    "design (bit-parity across backends)", eqn_site(eqn)))
    for av in (out_avals or []):
        if _non_integer(getattr(av, "dtype", None)):
            findings.append(Finding(
                "R2", flavor, "error",
                f"non-integer step output leaf {av}", ""))
    return findings, {"float_eqns": n_float}


def check_r3(jaxpr, flavor: str) -> list[Finding]:
    findings = []
    for _, eqn, _ in iter_eqns(jaxpr):
        if "callback" in eqn.primitive.name:
            findings.append(Finding(
                "R3", flavor, "error",
                f"host callback primitive {eqn.primitive.name} inside the "
                "jitted step — every dispatch would sync through the host",
                eqn_site(eqn)))
    return findings


def check_r4(jaxpr, flavor: str) -> list[Finding]:
    findings = []
    for _, eqn, _ in iter_eqns(jaxpr):
        carries = _loop_carries(eqn)
        if carries is None:
            continue
        label, ins, outs = carries
        ins_s, outs_s = [str(a) for a in ins], [str(a) for a in outs]
        if ins_s != outs_s:
            findings.append(Finding(
                "R4", flavor, "error",
                f"{label} carry avals change across iterations: "
                f"{ins_s} -> {outs_s}", eqn_site(eqn)))
    return findings


# ---------------------------------------------------------------------------
# Flavor tracing.
# ---------------------------------------------------------------------------

#: The concrete TPU lowering forms, resolved explicitly (NOT 'auto') so the
#: audit checks what a TPU will run regardless of the auditing host.
TPU_FORMS = dict(packed=True, dense_writes="dense", gate_handlers=True)
CPU_FORMS = dict(packed=False, dense_writes="scatter", gate_handlers=False)


def _engine(name: str):
    if name == "serial":
        from ..sim import simulator as S
        return S
    from ..sim import parallel_sim as PS
    return PS


def trace_step(engine_name: str, p: SimParams):
    """``(closed_jaxpr, out_paths, out_avals)`` of one engine's
    single-instance step at params ``p`` (packed layout applied when the
    flavor asks for it, exactly as the compiled scan body does).  For the
    serial engine with ``macro_k > 1`` the traced unit is the engine's
    own ``macro_step`` (the K-event chunk body — the same function the
    census compiles), so the audited and dispatched graphs are one trace.
    The step is state-in/state-out, so the input tree's paths label the
    trace's output leaves — no second trace needed."""
    eng = _engine(engine_name)
    st = eng.init_state(p, 0)
    dt = jnp.asarray(p.delay_table())
    du = jnp.asarray(p.duration_table())
    if engine_name == "serial":
        if p.packed:
            st = packing.pack_state(p, st)
        fn = eng.macro_step if (p.macro_k or 1) > 1 else eng.step
        cj = jax.make_jaxpr(functools.partial(fn, p))(dt, du, st)
    else:
        if p.packed:
            st = eng.pack_pstate(p, st)
        cj = jax.make_jaxpr(
            functools.partial(eng.step, p, dt, du, eng.d_min_of(p)))(st)
    paths = [jax.tree_util.keystr(k) for k, _ in
             jax.tree_util.tree_flatten_with_path(st)[0]]
    return cj, paths, list(cj.out_avals)


_OBS_LEAVES = (".metrics", ".flight", ".wd")


def _consensus_dce(cj, paths) -> tuple:
    """DCE a step trace down to its consensus outputs (observability
    leaves dropped) and return the structural signature.  Used on BOTH
    sides of the R6 comparison: DCE normalizes trace-level dead code, so
    off-graph == dce(on-graph) is exactly 'nothing flows back'."""
    used = [not any(k in pth for k in _OBS_LEAVES) for pth in paths]
    dj, _ = pe.dce_jaxpr(cj.jaxpr, used)
    return eqn_signature(dj)


def check_r6_engine(engine_name: str, base_kw: dict, flavor_prefix: str,
                    traces: dict | None = None):
    """R6 for one engine: telemetry/watchdog ON graphs, DCE'd to consensus
    outputs, must equal the OFF graph eqn-for-eqn.  ``traces`` lets
    audit_engine share the flavor traces it already paid for
    (flavor-name -> (closed_jaxpr, out_paths))."""
    findings = []
    traces = dict(traces or {})

    def get(name, **kw):
        if name not in traces:
            p = SimParams(**base_kw, **TPU_FORMS, **kw)
            cj, paths, _ = trace_step(engine_name, p)
            traces[name] = (cj, paths)
        return traces[name]

    sig_off = _consensus_dce(*get("tpu_shape"))
    knob_sets = {
        "tpu_telemetry": dict(telemetry=True, flight_cap=32),
        "tpu_watchdog": dict(watchdog=True),
        "tpu_telemetry_watchdog": dict(telemetry=True, flight_cap=32,
                                       watchdog=True),
    }
    for name, kw in knob_sets.items():
        sig_on = _consensus_dce(*get(name, **kw))
        if sig_on != sig_off:
            i = next((k for k, (a, b) in enumerate(zip(sig_off, sig_on))
                      if a != b), min(len(sig_off), len(sig_on)))
            findings.append(Finding(
                "R6", f"{flavor_prefix}/{name}", "error",
                f"knob-on graph is not the off graph plus write-only "
                f"observability: consensus-sliced eqn sequences diverge at "
                f"eqn {i} ({len(sig_off)} off vs {len(sig_on)} on-DCE "
                "eqns) — an observability value is feeding back into "
                "consensus state", ""))
    return findings


# ---------------------------------------------------------------------------
# Sharded runner checks (R5 + the mp arm of R6).
# ---------------------------------------------------------------------------


def trace_sharded(p: SimParams, batch: int, dp: int):
    from ..parallel import mesh as mesh_ops
    from ..parallel import sharded
    from ..sim import simulator as S
    from ..utils import xops

    mesh = mesh_ops.make_mesh(n_dp=dp, n_mp=1, devices=jax.devices()[:dp])
    st = S.init_batch(p, sharded.fleet_seeds(0, batch))
    st, _ = sharded.pad_to_multiple(p, st, mesh.size)
    padded_b = sharded.batch_size(st)
    st = mesh_ops.shard_batch(mesh, st)
    run = sharded.make_sharded_run_fn(p, mesh, 2)
    if xops.resolve_params(p).wrap == "device":
        # The ring runner takes the traced chunk-budget scalar too.
        return jax.make_jaxpr(run)(st, jnp.int32(1)), padded_b
    return jax.make_jaxpr(run)(st), padded_b


def check_r5(cj, padded_b: int, flavor: str) -> list[Finding]:
    findings = []
    if DIGEST_WIDTH != tstream.DIGEST_WIDTH:
        findings.append(Finding(
            "R5", flavor, "error",
            f"digest width changed: telemetry/stream.DIGEST_WIDTH="
            f"{tstream.DIGEST_WIDTH} vs the audited contract "
            f"{DIGEST_WIDTH} — re-pin BOTH after bumping "
            "REGISTRY_VERSION", ""))
    outs = [v.aval for v in cj.jaxpr.outvars]
    digests = [a for a in outs
               if tuple(a.shape) == (tstream.DIGEST_WIDTH,)
               and np.dtype(a.dtype).kind == "i"]
    if len(digests) != 1:
        findings.append(Finding(
            "R5", flavor, "error",
            f"sharded runner must return exactly one [{DIGEST_WIDTH}] "
            f"int32 digest (found {len(digests)}) — the poll path "
            "contract of parallel/sharded.run_sharded", ""))
    for a in outs:
        if tuple(a.shape) == (tstream.DIGEST_WIDTH,) \
                and np.dtype(a.dtype).kind == "i":
            continue
        if not a.shape or a.shape[0] != padded_b:
            findings.append(Finding(
                "R5", flavor, "error",
                f"non-state, non-digest output {a}: every extra output "
                "is another per-chunk host transfer candidate", ""))
    return findings


def check_r5_ring(cj, padded_b: int, ring_k: int,
                  flavor: str) -> list[Finding]:
    """R5's ring arm (``SimParams.wrap="device"``): the only SMALL
    outputs of the ring runner are ONE ``[ring_k, 13]`` int digest ring
    and ONE scalar int retired count — everything else must be
    fleet-sized, exactly the host-flavor contract one level up (the
    outer call's egress is the ring + count, never a per-chunk or
    non-batch extra)."""
    findings = []
    if DIGEST_WIDTH != tstream.DIGEST_WIDTH:
        findings.append(Finding(
            "R5", flavor, "error",
            f"digest width changed: telemetry/stream.DIGEST_WIDTH="
            f"{tstream.DIGEST_WIDTH} vs the audited contract "
            f"{DIGEST_WIDTH} — re-pin BOTH after bumping "
            "REGISTRY_VERSION", ""))
    outs = [v.aval for v in cj.jaxpr.outvars]

    def is_ring(a):
        return (tuple(a.shape) == (ring_k, tstream.DIGEST_WIDTH)
                and np.dtype(a.dtype).kind == "i")

    def is_count(a):
        return not a.shape and np.dtype(a.dtype).kind == "i"

    if sum(1 for a in outs if is_ring(a)) != 1:
        findings.append(Finding(
            "R5", flavor, "error",
            f"ring runner must return exactly one [{ring_k}, "
            f"{DIGEST_WIDTH}] int32 digest ring "
            f"(found {sum(1 for a in outs if is_ring(a))}) — the "
            "one-egress-per-outer-call contract of "
            "parallel/sharded.run_sharded's device wrap", ""))
    if sum(1 for a in outs if is_count(a)) != 1:
        findings.append(Finding(
            "R5", flavor, "error",
            f"ring runner must return exactly one scalar int retired "
            f"count (found {sum(1 for a in outs if is_count(a))})", ""))
    for a in outs:
        if is_ring(a) or is_count(a):
            continue
        if not a.shape or a.shape[0] != padded_b:
            findings.append(Finding(
                "R5", flavor, "error",
                f"non-state, non-ring output {a}: every extra output "
                "is another per-outer-call host transfer candidate", ""))
    return findings


def check_r6_ring(p_base: SimParams, batch: int, dp: int,
                  cj_off=None) -> list[Finding]:
    """The ring knob's R6 arm: ``wrap="host"`` must stay the EXACT
    pre-ring graph.  The HEAD twin — shard_map(scan + digest) built
    inline here, bypassing make_sharded_run_fn's wrap dispatch — must
    trace eqn-identical to the audited host runner, so the device wrap
    can only ever be a sibling branch, never a wrapper that grows the
    default path (the macro-k1-identity pin one level up)."""
    import dataclasses as _dc

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as _P

    from ..core import types as _types
    from ..parallel import mesh as mesh_ops
    from ..parallel import sharded
    from ..sim import simulator as S
    from ..utils import xops

    if cj_off is None:
        cj_off, _ = trace_sharded(
            _dc.replace(p_base, wrap="host"), batch, dp)
    # The twin normalizes params exactly as make_sharded_run_fn does
    # (resolve + runtime-field normalization) so the two traces differ
    # only if the HOST BRANCH itself drifted.
    key_p = _dc.replace(xops.resolve_params(p_base), max_clock=0,
                        drop_prob=0.0)
    if key_p.scenario:
        key_p = _dc.replace(key_p, commit_chain=3,
                            **_types.DELAY_KEY_DEFAULTS)
    key_p = _dc.replace(key_p, wrap="host", ring_k=None)
    mesh = mesh_ops.make_mesh(n_dp=dp, n_mp=1, devices=jax.devices()[:dp])
    st = S.init_batch(key_p, sharded.fleet_seeds(0, batch))
    st, _ = sharded.pad_to_multiple(key_p, st, mesh.size)
    st = mesh_ops.shard_batch(mesh, st)
    axes = tuple(mesh.axis_names)
    inner = S.make_scan_fn(key_p, 2, batched=True)

    def local(s):
        s = inner(s)
        return s, tstream.compute_digest(key_p, s, axis_names=axes)

    f = shard_map(local, mesh=mesh, in_specs=(_P(axes),),
                  out_specs=(_P(axes), _P()), check_rep=False)
    cj_twin = jax.make_jaxpr(jax.jit(f, donate_argnums=(0,)))(st)
    if eqn_signature(cj_twin.jaxpr) != eqn_signature(cj_off.jaxpr):
        return [Finding(
            "R6", "sharded/wrap_host", "error",
            "wrap='host' is no longer the exact pre-ring graph: the "
            "host-dispatch runner's trace differs from the inline "
            "shard_map(scan + digest) twin — the device-wrap branch "
            "leaked into the default path", "")]
    return []


_COLLECTIVES = ("psum", "pmax", "pmin", "all_gather", "all_reduce",
                "ppermute", "all_to_all")


def _mp_collectives_in_scan(cj) -> int:
    n = 0
    for _, eqn, in_loop in iter_eqns(cj.jaxpr):
        if not in_loop or eqn.primitive.name not in _COLLECTIVES:
            continue
        axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
        if not isinstance(axes, (tuple, list)):
            axes = (axes,)
        if "mp" in axes:
            n += 1
    return n


def check_r6_mp(p_base: SimParams, batch: int, dp: int,
                cj_off=None) -> list[Finding]:
    """mp_authors OFF must pay zero 'mp'-axis collectives inside the chunk
    scan; ON (n_mp=1 degenerate) must actually arm the quorum psums.
    ``cj_off`` lets audit_sharded pass the off trace it already paid for
    (mp_authors defaults to False, so its R5 trace IS the off graph)."""
    findings = []
    if cj_off is None or p_base.mp_authors:
        cj_off, _ = trace_sharded(
            dataclasses.replace(p_base, mp_authors=False), batch, dp)
    n_off = _mp_collectives_in_scan(cj_off)
    if n_off:
        findings.append(Finding(
            "R6", "sharded/mp_off", "error",
            f"{n_off} 'mp'-axis collectives inside the chunk scan with "
            "mp_authors off — the off graph must be collective-free "
            "per iteration", ""))
    cj_on, _ = trace_sharded(
        dataclasses.replace(p_base, mp_authors=True), batch, dp)
    n_on = _mp_collectives_in_scan(cj_on)
    if n_on == 0:
        findings.append(Finding(
            "R6", "sharded/mp_on", "error",
            "mp_authors=True armed zero in-scan 'mp' psums — the quorum "
            "sites in core/store.py are no longer wired through "
            "core/config.py's axis aggregation", ""))
    return findings


# ---------------------------------------------------------------------------
# The full audit.
# ---------------------------------------------------------------------------


def _flavors(base_kw: dict, engine_name: str = "serial"):
    """(name, forms, rules) per engine flavor.  cpu_default keeps its
    proven scatter forms, so R1 (a TPU-lowering rule) does not apply.
    The serial engine adds the K-macro flavors (``macro_step``'s rolled
    inner scan at K=4/16 — the census rungs), which run the same
    R1-R4 write/dtype/callback/carry rules on the K-event graph."""
    flavors = [
        ("cpu_default", CPU_FORMS, ("R2", "R3", "R4")),
        ("tpu_shape", TPU_FORMS, ("R1", "R2", "R3", "R4")),
        ("tpu_telemetry", dict(TPU_FORMS, telemetry=True, flight_cap=32),
         ("R1", "R2", "R3", "R4")),
        ("tpu_watchdog", dict(TPU_FORMS, watchdog=True),
         ("R1", "R2", "R3", "R4")),
    ]
    # Scenario-plane flavor (SimParams.scenario; serve/): per-slot traced
    # delay table + commit-chain select.  Same write/dtype/callback/carry
    # rules on the scenario graph; the R6 scenario arm adds the
    # off-inert / read-only pass-through pins.
    flavors.append(("tpu_shape_scenario", dict(TPU_FORMS, scenario=True),
                    ("R1", "R2", "R3", "R4")))
    # Adversary-plane flavor (SimParams.adversary; adversary/): the
    # windowed attack decode, per-link delay adds, and partition cuts.
    # Same write/dtype/callback/carry rules on the adversary graph; the
    # R6 adversary arm adds the off-inert / read-only pass-through pins.
    flavors.append(("tpu_shape_adversary", dict(TPU_FORMS, adversary=True),
                    ("R1", "R2", "R3", "R4")))
    if engine_name == "serial":
        flavors += [
            ("tpu_shape_k4", dict(TPU_FORMS, macro_k=4),
             ("R1", "R2", "R3", "R4")),
            ("tpu_shape_k16", dict(TPU_FORMS, macro_k=16),
             ("R1", "R2", "R3", "R4")),
        ]
    return flavors


def check_r6_macro(engine_name: str, base_kw: dict,
                   traces: dict | None = None) -> list[Finding]:
    """The macro knob's R6 arm: ``macro_k=1`` must lower to the EXACT
    macro-free graph — ``macro_step`` at K=1 and the bare ``step`` must
    trace to identical eqn sequences.  This is the static twin of the
    census K=1-identity gate: the default can never silently grow a
    wrapper."""
    traces = dict(traces or {})
    if "tpu_shape" in traces:
        cj_off, _ = traces["tpu_shape"]
    else:
        cj_off, _, _ = trace_step(
            engine_name, SimParams(**base_kw, **TPU_FORMS))
    eng = _engine(engine_name)
    p1 = SimParams(**base_kw, **TPU_FORMS, macro_k=1)
    st = eng.init_state(p1, 0)
    if p1.packed:
        st = packing.pack_state(p1, st)
    cj_k1 = jax.make_jaxpr(functools.partial(eng.macro_step, p1))(
        jnp.asarray(p1.delay_table()), jnp.asarray(p1.duration_table()), st)
    if eqn_signature(cj_k1.jaxpr) != eqn_signature(cj_off.jaxpr):
        return [Finding(
            "R6", f"{engine_name}/tpu_shape_k1", "error",
            "macro_k=1 is not the identity lowering: macro_step's K=1 "
            "graph differs from the bare step — the default no longer "
            "lowers to the exact pre-macro graph", "")]
    return []


def _check_r6_plane(engine_name: str, base_kw: dict, traces: dict,
                    leaf_substrings: tuple, n_leaves: int, what: str,
                    on_flavor: str, on_kw: dict) -> list[Finding]:
    """Shared R6 arm for a per-slot traced-config PLANE (the scenario and
    adversary planes both ride it) — two static pins:

    * **off-inert**: with the knob OFF the plane's state leaves are
      zero-width and NO eqn consumes them — the step graph is the exact
      knob-free lowering (the census twin: existing budgets unchanged);
    * **read-only pass-through**: with the knob ON the step must return
      every plane leaf as the IDENTITY of its input (the same jaxpr
      Var) — the plane is per-slot config, and an engine write to it
      would let one chunk silently rewrite a slot's config out from
      under the resident service's admission bookkeeping."""
    findings = []

    def get(name, **kw):
        if name not in traces:
            p = SimParams(**base_kw, **TPU_FORMS, **kw)
            cj, paths, _ = trace_step(engine_name, p)
            traces[name] = (cj, paths)
        return traces[name]

    def plane_slots(cj, paths):
        offset = len(cj.jaxpr.invars) - len(paths)
        idx = [i for i, pth in enumerate(paths)
               if any(leaf in pth for leaf in leaf_substrings)]
        return offset, idx

    cj_off, paths_off = get("tpu_shape")
    offset, idx = plane_slots(cj_off, paths_off)
    if len(idx) != n_leaves:
        findings.append(Finding(
            "R6", f"{engine_name}/tpu_shape", "error",
            f"expected the {n_leaves} zero-width {what} leaves in the "
            f"off state, found {len(idx)} — the state layout drifted "
            "from the audited contract", ""))
        return findings
    off_vars = {cj_off.jaxpr.invars[offset + i] for i in idx}
    for eqn in cj_off.jaxpr.eqns:
        used = [v for v in eqn.invars
                if not isinstance(v, Literal) and v in off_vars]
        if used:
            findings.append(Finding(
                "R6", f"{engine_name}/tpu_shape", "error",
                f"{what}-OFF graph consumes a zero-width plane leaf in "
                f"{eqn.primitive.name} — the off graph must be the exact "
                "knob-free lowering (census budgets depend on it)",
                eqn_site(eqn)))
    cj_on, paths_on = get(on_flavor, **on_kw)
    offset_on, idx_on = plane_slots(cj_on, paths_on)
    for i in idx_on:
        if cj_on.jaxpr.outvars[i] is not cj_on.jaxpr.invars[offset_on + i]:
            findings.append(Finding(
                "R6", f"{engine_name}/{on_flavor}", "error",
                f"{what} plane leaf {paths_on[i]} is not passed through "
                "unchanged — the plane is read-only per-slot config; an "
                "engine write to it would rewrite a slot's config out "
                "from under the admission bookkeeping", ""))
    return findings


def check_r6_scenario(engine_name: str, base_kw: dict,
                      traces: dict | None = None) -> list[Finding]:
    """The scenario plane's R6 arm (see :func:`_check_r6_plane`)."""
    return _check_r6_plane(
        engine_name, base_kw, dict(traces or {}),
        (".sc_delay", ".sc_commit"), 2, "scenario",
        "tpu_shape_scenario", dict(scenario=True))


_ADV_LEAVES = (".adv_sched", ".adv_link", ".adv_group", ".adv_heal")


def check_r6_adversary(engine_name: str, base_kw: dict,
                       traces: dict | None = None) -> list[Finding]:
    """The adversary plane's R6 arm (see :func:`_check_r6_plane`): the
    attack-state leaves are off-inert and read-only — an engine write to
    them would additionally invalidate the lane engine's link-derived
    horizon mid-window."""
    return _check_r6_plane(
        engine_name, base_kw, dict(traces or {}),
        _ADV_LEAVES, len(_ADV_LEAVES), "adversary",
        "tpu_shape_adversary", dict(adversary=True))


def audit_engine(engine_name: str, base_kw: dict, r6: bool = True,
                 flavors=None) -> tuple[list[Finding], dict]:
    """Run R1-R4 (+R6) over one engine's lowering flavors at shape
    ``base_kw``; returns (findings, per-flavor stats)."""
    findings, stats, traces = [], {}, {}
    wanted = set(flavors) if flavors is not None else None
    for name, forms, rules in _flavors(base_kw, engine_name):
        if wanted is not None and name not in wanted:
            continue
        flavor = f"{engine_name}/{name}"
        p = SimParams(**base_kw, **forms)
        cj, paths, out_avals = trace_step(engine_name, p)
        if name != "cpu_default" and "macro_k" not in forms:
            traces[name] = (cj, paths)  # R6 reuses the TPU-form traces
        st = {"eqns": sum(1 for _ in iter_eqns(cj.jaxpr)),
              "eqn_hash": signature_hash(cj.jaxpr)}
        if "R1" in rules:
            f1, s1 = check_r1(cj.jaxpr, flavor)
            findings += f1
            st["writes"] = s1
            expected = R1_EXPECTED_WAIVED.get(flavor)
            if expected is not None and s1["vector_waived"] != expected:
                findings.append(Finding(
                    "R1", flavor, "error",
                    f"waived vector-scatter count changed: "
                    f"{s1['vector_waived']} sites vs the pinned "
                    f"{expected} — a write site was added or removed "
                    "under an existing file waiver; recertify (fuzz + "
                    "census) and re-pin R1_EXPECTED_WAIVED", ""))
        if "R2" in rules:
            f2, s2 = check_r2(cj.jaxpr, flavor, out_avals)
            findings += f2
            st.update(s2)
        if "R3" in rules:
            findings += check_r3(cj.jaxpr, flavor)
        if "R4" in rules:
            findings += check_r4(cj.jaxpr, flavor)
        stats[flavor] = st
    if r6:
        findings += check_r6_engine(engine_name, base_kw, engine_name,
                                    traces=traces)
        findings += check_r6_scenario(engine_name, base_kw, traces=traces)
        findings += check_r6_adversary(engine_name, base_kw, traces=traces)
        if engine_name == "serial":
            findings += check_r6_macro(engine_name, base_kw, traces=traces)
    return findings, stats


def audit_sharded(base_kw: dict, batch: int = 5, dp: int = 2,
                  mp: bool = True) -> tuple[list[Finding], dict]:
    """R3/R5 (+ the mp arm of R6) on the dp-sharded serial runner."""
    if len(jax.devices()) < dp:
        return [Finding(
            "R5", "sharded", "error",
            f"cannot audit the sharded runner: {len(jax.devices())} "
            f"devices < dp={dp}; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 before "
            "importing jax (scripts/graph_audit.py does)", "")], {}
    p = SimParams(**base_kw, **TPU_FORMS)
    cj, padded_b = trace_sharded(p, batch, dp)
    findings = check_r5(cj, padded_b, "sharded/tpu_shape")
    findings += check_r3(cj.jaxpr, "sharded/tpu_shape")
    if mp:
        findings += check_r6_mp(p, batch, dp, cj_off=cj)
    stats = {"sharded/tpu_shape": {
        "eqns": sum(1 for _ in iter_eqns(cj.jaxpr)),
        "eqn_hash": signature_hash(cj.jaxpr),
        "padded_batch": padded_b,
        "outputs": len(cj.jaxpr.outvars),
    }}
    # Device dispatch wrap: the ring flavor's R5 arm (only small outputs
    # = one [K, 13] ring + one retired count) and the R6 arm pinning
    # wrap="host" graph-identical to the pre-ring runner.
    ring_k = 4
    p_ring = dataclasses.replace(p, wrap="device", ring_k=ring_k)
    cj_r, padded_r = trace_sharded(p_ring, batch, dp)
    findings += check_r5_ring(cj_r, padded_r, ring_k, "sharded/ring_k4")
    findings += check_r3(cj_r.jaxpr, "sharded/ring_k4")
    findings += check_r6_ring(p, batch, dp, cj_off=cj)
    stats["sharded/ring_k4"] = {
        "eqns": sum(1 for _ in iter_eqns(cj_r.jaxpr)),
        "eqn_hash": signature_hash(cj_r.jaxpr),
        "padded_batch": padded_r,
        "outputs": len(cj_r.jaxpr.outvars),
    }
    return findings, stats


def audit_all(shape: str = "census", engines=("serial", "lane"),
              sharded: bool = True) -> dict:
    """The whole matrix; returns the GRAPH_AUDIT artifact dict."""
    ser_kw = dict(CENSUS_KW if shape == "census" else MICRO_SER_KW)
    lane_kw = dict(CENSUS_KW if shape == "census" else MICRO_LANE_KW)
    findings: list[Finding] = []
    stats: dict[str, Any] = {}
    for eng in engines:
        f, s = audit_engine(eng, ser_kw if eng == "serial" else lane_kw)
        findings += f
        stats.update(s)
    if sharded:
        f, s = audit_sharded(ser_kw)
        findings += f
        stats.update(s)
    errors = [f for f in findings if f.severity == "error"]
    return {
        "shape": shape,
        "digest_width": tstream.DIGEST_WIDTH,
        "registry_version": tstream.REGISTRY_VERSION,
        "flavors": stats,
        "findings": [f.to_json() for f in findings],
        "n_errors": len(errors),
        "clean": not errors,
    }


# --- small helpers for test fixtures ---------------------------------------


def check_toy(fn: Callable, *args, rules=("R1", "R2", "R3", "R4"),
              flavor: str = "toy") -> list[Finding]:
    """Trace an arbitrary function and run the write/dtype/callback/carry
    rules on it — the seeded-violation entry point tests/test_audit.py
    feeds known-bad graphs through."""
    cj = jax.make_jaxpr(fn)(*args)
    findings = []
    if "R1" in rules:
        findings += check_r1(cj.jaxpr, flavor)[0]
    if "R2" in rules:
        findings += check_r2(cj.jaxpr, flavor)[0]
    if "R3" in rules:
        findings += check_r3(cj.jaxpr, flavor)
    if "R4" in rules:
        findings += check_r4(cj.jaxpr, flavor)
    return findings
