"""Host-concurrency lint (C-rules): the cross-process/threading invariants
of the serve/distributed era, machine-checked on the source AST.

The round-15 distributed runtime added the failure classes no jaxpr rule
can see: a wedged gloo collective (dead peer) turning into an unbounded
host wait — the hang class that forced ci_tier1's hard 1500 s cap — and
shared mutable host state (the runtime ledger, the resident service's
admission queue) touched from more than one thread.  These rules make the
working discipline unrepresentable to violate:

C1  **Every cross-process wait is bounded.**  In the hot modules
    (:data:`C1_SCOPE`): a ``.wait()`` / ``.join()`` call with no timeout
    and a *blocking* ``fcntl.flock`` (``LOCK_EX`` without ``LOCK_NB``)
    are errors unless registered in :data:`C1_SANCTIONED` with a
    justification.  ``ClusterHandle.wait`` takes its deadline
    positionally by design, the reaper uses ``proc.wait(timeout=...)``,
    and the AOT manifest lock spins ``LOCK_NB`` against a deadline
    (``utils/aot._flock_bounded``) — the wedged-collective /
    dead-writer hang class made a review-time error.
C2  **Lock discipline over shared mutable state.**  :data:`C2_GUARDED`
    registers (file, class) -> (owning lock attribute, guarded
    attributes); every MUTATION of a guarded attribute (assignment,
    augmented assignment, subscript store, mutating method call —
    ``append``/``pop``/``update``/...) must be lexically inside a
    ``with <lock>:`` block.  Single-threaded setup paths are registered
    in :data:`C2_EXEMPT`.  Reads are deliberately not flagged: the
    guarded structures tolerate racy point-in-time snapshots
    (``len(pending)``), never racy mutation.
C3  **NDJSON rows flush per write.**  The PR-7 contract: a
    ``timeout``-killed process must leave every completed row on disk,
    so any function that writes a ``json.dumps`` row to a stream must
    also ``.flush()`` it (same function).  Was convention; now a rule.
"""

from __future__ import annotations

import ast

from .source_lint import Finding, _attr_chain, _functions, \
    enclosing_functions, iter_repo_sources

# ---------------------------------------------------------------------------
# C1 — bounded waits.
# ---------------------------------------------------------------------------

#: Hot modules where an unbounded wait wedges the fleet/CI: the
#: distributed runtime and its callers, the serve loop, the parallel
#: runtime, the AOT store (fcntl manifest lock) and the ledger.
#: realnode/ (the asyncio reference node) and analysis are host tools
#: outside the fleet hot path.
C1_SCOPE_PREFIXES = ("distributed/", "serve/", "parallel/", "utils/",
                     "telemetry/")
C1_SCOPE_FILES = ("scripts/fleet_pod.py", "scripts/fleet_serve.py")

#: (file, enclosing function) -> justification for an unbounded wait.
C1_SANCTIONED: dict = {}


def _c1_in_scope(rel: str) -> bool:
    return rel.startswith(C1_SCOPE_PREFIXES) or rel in C1_SCOPE_FILES


def _is_none(node) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _has_timeout(call: ast.Call) -> bool:
    if call.args:
        # A positional deadline (ClusterHandle.wait(timeout_s)) counts;
        # a LITERAL None does not — `proc.wait(None)` is the unbounded
        # form in a bounded costume.  A variable that may hold None
        # stays best-effort-accepted (lexical lint, not dataflow).
        return not _is_none(call.args[0])
    return any(kw.arg in ("timeout", "timeout_s", "deadline")
               and not _is_none(kw.value) for kw in call.keywords)


def lint_c1(rel: str, tree: ast.Module) -> list[Finding]:
    if not _c1_in_scope(rel):
        return []
    findings = []
    funcs = _functions(tree)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if not chain:
            continue
        enclosing = enclosing_functions(funcs, node.lineno)
        func = enclosing[-1]
        if any((rel, fname) in C1_SANCTIONED for fname in enclosing):
            continue
        name = chain[-1]
        if name in ("wait", "join") and len(chain) > 1 \
                and not _has_timeout(node):
            findings.append(Finding(
                "C1", "source", "error",
                f".{name}() without a timeout in {func}() — a dead peer "
                "(wedged gloo collective, killed child) parks this wait "
                "forever; pass an explicit bounded timeout, or register "
                "the site in C1_SANCTIONED with a justification",
                f"{rel}:{node.lineno}"))
        elif name == "flock" and len(node.args) >= 2:
            flags = ast.dump(node.args[1])
            if "LOCK_EX" in flags and "LOCK_NB" not in flags:
                findings.append(Finding(
                    "C1", "source", "error",
                    f"blocking fcntl.flock(LOCK_EX) in {func}() — a "
                    "crashed writer holding the lock wedges every later "
                    "process; spin LOCK_NB against a deadline "
                    "(utils/aot._flock_bounded)",
                    f"{rel}:{node.lineno}"))
    return findings


# ---------------------------------------------------------------------------
# C2 — lock discipline.
# ---------------------------------------------------------------------------

#: (file, class name or None for module level) ->
#: (owning lock attribute, frozenset of guarded attributes).
C2_GUARDED = {
    ("telemetry/ledger.py", "RuntimeLedger"): ("_lock", frozenset({
        "spans", "compiles", "unattributed", "_compile_seen", "dropped",
        "_seq", "_run_seq"})),
    ("utils/aot.py", None): ("_lock", frozenset({"_LOADED", "_REFUSED"})),
    ("serve/service.py", "ResidentFleet"): ("_qlock", frozenset({
        "_pending", "requests", "results"})),
}

#: (file, function) setup paths that run before any second thread can
#: exist (constructors, classmethod restore building a fresh instance).
C2_EXEMPT = {
    ("telemetry/ledger.py", "__init__"),
    ("serve/service.py", "__init__"),
    ("serve/service.py", "restore"),
}

#: Method calls that mutate their receiver.
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard", "sort", "reverse"})


def _guarded_access(node, attrs: frozenset, cls: str | None):
    """The guarded attribute named by ``node`` under registry scope
    ``cls`` (class -> ``self.<attr>``; module level -> bare ``<attr>``),
    else None."""
    if cls is not None:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" and node.attr in attrs:
            return node.attr
        return None
    if isinstance(node, ast.Name) and node.id in attrs:
        return node.id
    return None


def _lock_expr_matches(expr, lock: str, cls: str | None) -> bool:
    if cls is not None:
        return isinstance(expr, ast.Attribute) and expr.attr == lock \
            and isinstance(expr.value, ast.Name) and expr.value.id == "self"
    return isinstance(expr, ast.Name) and expr.id == lock


def _mutation_in(node, attrs: frozenset, cls: str | None) -> str | None:
    """A guarded-attribute MUTATION anywhere in an expression subtree
    (mutating method call, subscript store/del), else None."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in _MUTATORS:
            a = _guarded_access(sub.func.value, attrs, cls)
            if a:
                return a
        elif isinstance(sub, ast.Subscript) \
                and isinstance(sub.ctx, (ast.Store, ast.Del)):
            a = _guarded_access(sub.value, attrs, cls)
            if a:
                return a
    return None


def _c2_walk(node, lock: str, cls: str | None, attrs: frozenset,
             under: bool, hits: list) -> None:
    if isinstance(node, ast.With):
        # With-item expressions evaluate BEFORE this statement's lock
        # takes effect: scan them under the OUTER lock state.
        if not under:
            for item in node.items:
                a = _mutation_in(item.context_expr, attrs, cls)
                if a:
                    hits.append((node.lineno, a))
        locked = under or any(
            _lock_expr_matches(item.context_expr, lock, cls)
            for item in node.items)
        for child in node.body:
            _c2_walk(child, lock, cls, attrs, locked, hits)
        return
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return  # nested scopes get their own pass
    if not under:
        target = None
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                for sub in ast.walk(t):
                    a = _guarded_access(sub, attrs, cls)
                    if a:
                        target = a
        if target is None:
            if isinstance(node, (ast.If, ast.While, ast.For, ast.Try)):
                # Compound: bodies recurse below, but the test/iter
                # expressions execute too — `while pending.pop():` is as
                # much a mutation as a statement-level pop.
                for expr in ([node.test]
                             if isinstance(node, (ast.If, ast.While))
                             else [node.iter, node.target]
                             if isinstance(node, ast.For) else []):
                    a = _mutation_in(expr, attrs, cls)
                    if a:
                        target = a
            else:
                target = _mutation_in(node, attrs, cls)
        if target is not None:
            hits.append((node.lineno, target))
    for field in ("body", "orelse", "finalbody"):
        for child in getattr(node, field, []) or []:
            _c2_walk(child, lock, cls, attrs, under, hits)
    for handler in getattr(node, "handlers", []) or []:
        for child in handler.body:
            _c2_walk(child, lock, cls, attrs, under, hits)


def lint_c2(rel: str, tree: ast.Module,
            guarded: dict | None = None) -> list[Finding]:
    registry = guarded if guarded is not None else C2_GUARDED
    entries = [(cls, lock, attrs)
               for (f, cls), (lock, attrs) in registry.items() if f == rel]
    if not entries:
        return []
    findings = []
    for fn in _functions(tree):
        for cls, lock, attrs in entries:
            if cls is not None and cls not in fn.classes:
                continue
            if (rel, fn.name) in C2_EXEMPT:
                continue
            hits: list = []
            for stmt in fn.node.body:
                _c2_walk(stmt, lock, cls, attrs, False, hits)
            for lineno, attr in hits:
                where = f"{cls}.{attr}" if cls else attr
                findings.append(Finding(
                    "C2", "source", "error",
                    f"guarded attribute {where} mutated in {fn.name}() "
                    f"outside `with {'self.' if cls else ''}{lock}:` — "
                    "shared mutable state races without the owning lock; "
                    "take the lock, or register a single-threaded setup "
                    "path in C2_EXEMPT",
                    f"{rel}:{lineno}"))
    return findings


# ---------------------------------------------------------------------------
# C3 — NDJSON flush-per-row.
# ---------------------------------------------------------------------------

#: (file, function) -> justification for a row write with no flush.
C3_SANCTIONED: dict = {}


def _is_row_write(call: ast.Call) -> bool:
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "write" and call.args):
        return False
    for sub in ast.walk(call.args[0]):
        if isinstance(sub, ast.Call):
            chain = _attr_chain(sub.func)
            if chain and chain[-1] == "dumps":
                return True
    return False


def lint_c3(rel: str, tree: ast.Module) -> list[Finding]:
    findings = []
    for fn in _functions(tree):
        # Writes and flushes are matched BY RECEIVER (the dotted chain
        # before .write/.flush): flushing stderr while rows buffer on
        # out_f must not satisfy the rule.
        rows: dict[tuple, int] = {}
        flushed: set[tuple] = set()
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            if _is_row_write(node):
                recv = tuple(_attr_chain(node.func)[:-1])
                rows.setdefault(recv, node.lineno)
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "flush":
                flushed.add(tuple(_attr_chain(node.func)[:-1]))
        if (rel, fn.name) in C3_SANCTIONED:
            continue
        for recv, lineno in sorted(rows.items(), key=lambda kv: kv[1]):
            if recv in flushed:
                continue
            findings.append(Finding(
                "C3", "source", "error",
                f"{fn.name}() writes NDJSON rows (json.dumps -> .write) "
                f"on {'.'.join(recv) or 'an expression'} without "
                "flushing that stream — a timeout-killed process loses "
                "every buffered row (the PR-7 contract: flush per row "
                "so the stream survives the kill)",
                f"{rel}:{lineno}"))
    return findings


# ---------------------------------------------------------------------------
# Entry points.
# ---------------------------------------------------------------------------


def lint_text(rel: str, text: str,
              guarded: dict | None = None) -> list[Finding]:
    """C1-C3 on one file's source (fixture entry point, mirroring
    source_lint.lint_text)."""
    tree = ast.parse(text)
    return (lint_c1(rel, tree) + lint_c2(rel, tree, guarded=guarded)
            + lint_c3(rel, tree))


def run(root: str | None = None) -> list[Finding]:
    """C1-C3 over the repo (source_lint.iter_repo_sources — one shared
    walk contract for every rule family)."""
    findings: list[Finding] = []
    for rel, text in iter_repo_sources(root):
        try:
            findings += lint_text(rel, text)
        except SyntaxError as e:
            findings.append(Finding(
                "C1", "source", "error",
                f"unparseable source: {e}", rel))
    return findings
