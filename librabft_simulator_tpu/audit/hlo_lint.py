"""Compiled-HLO audit: extend the static audit past the jaxpr into what
the backend compiler actually emitted.

The jaxpr rules (R1-R6) are backend-independent; R1's miscompile class is
ultimately a property of what the TPU *compiler* emits (ROADMAP tunnel
checklist item 8).  This module is the backend-portable half of that
item: it compiles the warmed chunk runners via
``jit(...).lower(...).compile().as_text()`` on whatever backend is
visible and audits the OPTIMIZED module — on CPU today; the on-chip run
is a backend flag flip, not new code.  Parsing follows the conventions of
``scripts/kernel_census.py``'s repaired HLO parser (comment stripping,
greedy tuple-typed headers) — the census import is reused for the
op-count cross-check.

Checks (rule ID ``HLO``):

* **Scatter instruction class** — every ``scatter`` instruction that
  SURVIVES optimization is classified by its indices operand (the
  ``classify_write`` convention): a single-update scatter is the
  miscompile class and can never be waived.  XLA CPU expands most
  scatters into sort/while forms (0 surviving instructions is normal
  and recorded); on TPU the instructions survive and this check is the
  round-5 certification, re-verified per build.
* **Scatter site provenance** — expansion keeps jax's ``op_name``/
  ``source_file`` metadata, so every scatter-derived instruction in the
  compiled module is traced back to its source file, which must be an
  ``R1_WAIVERS``-certified file: a scatter from any other file reached
  the compiled program without the jaxpr audit seeing it (or a new site
  rode an existing waiver) — works whether or not the backend expanded
  the op.
* **Digest-only small root** (sharded runner) — the ENTRY computation's
  result tuple holds exactly ONE small output, the ``[DIGEST_WIDTH]``
  int32 digest; every other output is fleet-sized (leading dim = padded
  batch).  R5 proved this on the jaxpr; this proves the *executable*
  kept it (a backend pass that materialized an extra small live-out
  would widen the per-chunk host transfer).
* **Alias survival** (the compiled half of the D1 donation rule) — the
  executable's ``input_output_alias`` map still carries every donated
  state leaf: donation requested at trace time but dropped by the
  compiler would silently double the fleet's memory footprint.
"""

from __future__ import annotations

import os
import re
import sys

import numpy as np

from .source_lint import Finding

#: Files allowed to contribute scatter-primitive-derived instructions to
#: a compiled module (path suffix -> justification).  The R1_WAIVERS
#: engine files (certified vector sites) are added automatically by
#: audit_hlo; entries here cover the STATIC-index class — scatters whose
#: indices are constants/iota, which the jaxpr R1 rule classifies
#: "static" and passes, but whose op_name metadata still says scatter in
#: the compiled text.
HLO_STATIC_SCATTER_FILES = {
    "telemetry/plane.py":
        "metrics-plane one-hot adds and the flight-recorder ring write: "
        "constant/iota-derived indices (R1 'static' class — the jaxpr "
        "audit classifies them, tests pin the decode against the "
        "oracle); not the traced-index miscompile class.",
}

#: Optimized-module scatter instruction: "%name = TYPE scatter(".
_SCATTER_INSTR_RE = re.compile(r"=\s[^=]*?\sscatter\(")
_OPERAND_TYPE_RE = re.compile(r"[a-z][a-z0-9]*\[([\d,]*)\]")
_IVD_RE = re.compile(r"index_vector_dim=(\d+)")


def _scatter_indices_shape(line: str) -> tuple | None:
    """The indices operand's shape from a scatter instruction line, or
    ``None`` when the operand list cannot be read (fail-safe: the caller
    flags unclassifiable scatters).  HLO scatter is VARIADIC —
    ``scatter(op_1..op_N, indices, upd_1..upd_N)``, 2N+1 operands — so
    the indices operand is the middle one; positional 3-operand parsing
    would mistake a data operand for the indices on N > 1 and classify
    from a fleet-sized shape."""
    start = line.find("scatter(")
    if start < 0:
        return None
    end = line.find(")", start)
    if end < 0:
        return None
    shapes = _OPERAND_TYPE_RE.findall(line[start:end])
    if not shapes or len(shapes) % 2 == 0:
        return None
    return _shape(shapes[len(shapes) // 2])
#: jax metadata on any instruction derived from a scatter primitive.
_SCATTER_META_RE = re.compile(
    r'op_name="[^"]*/scatter[^"/]*"[^\n]*?source_file="([^"]+)"'
    r'[^\n]*?source_line=(\d+)')
_ALIAS_PAIR_RE = re.compile(r":\s*\(\d+,")
_TYPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([\d,]*)\]")


def _header_block(header: str, key: str) -> str | None:
    """The brace-matched ``key={...}`` block from an HloModule header
    (alias maps and layouts nest braces, so non-greedy regexes
    under-read them)."""
    start = header.find(key + "={")
    if start < 0:
        return None
    i = header.index("{", start)
    depth = 0
    for j in range(i, len(header)):
        if header[j] == "{":
            depth += 1
        elif header[j] == "}":
            depth -= 1
            if depth == 0:
                return header[i + 1:j]
    return None


def load_census():
    """Import scripts/kernel_census.py (the repaired HLO parser) from the
    package-relative scripts dir — the op-count conventions are shared,
    not restated."""
    from .source_lint import repo_root

    sdir = os.path.join(repo_root(), "scripts")
    if sdir not in sys.path:
        sys.path.insert(0, sdir)
    import kernel_census

    return kernel_census


def _shape(text: str) -> tuple:
    return tuple(int(x) for x in text.split(",") if x) if text else ()


def scatter_updates(indices_shape: tuple, index_vector_dim: int) -> int:
    """Number of independent updates a scatter performs, from its indices
    operand (the HLO-level twin of graph_lint.classify_write): every
    indices dim except ``index_vector_dim`` enumerates updates."""
    if not indices_shape:
        return 1
    dims = [d for i, d in enumerate(indices_shape)
            if i != index_vector_dim]
    return int(np.prod(dims)) if dims else 1


def check_hlo_scatters(txt: str, flavor: str, allowed_files) -> tuple:
    """The scatter-class + provenance checks on one optimized module.
    ``allowed_files`` are the R1-certified source files (path suffixes);
    returns ``(findings, stats)``."""
    findings: list[Finding] = []
    surviving = 0
    scalar = 0
    for line in txt.splitlines():
        if not _SCATTER_INSTR_RE.search(line):
            continue
        surviving += 1
        idx_shape = _scatter_indices_shape(line)
        ivd_m = _IVD_RE.search(line)
        if idx_shape is None or not ivd_m:
            findings.append(Finding(
                "HLO", flavor, "error",
                "unparseable scatter instruction in optimized HLO — the "
                "audit cannot classify it; update hlo_lint's parser for "
                "this toolchain's text format (fail-safe: unclassified "
                "is an error, like lost R1 provenance)", ""))
            continue
        n_upd = scatter_updates(idx_shape, int(ivd_m.group(1)))
        if n_upd <= 1:
            scalar += 1
            findings.append(Finding(
                "HLO", flavor, "error",
                "single-update scatter instruction survived to the "
                "optimized module — the TPU miscompile class at the "
                "executable level (scripts/tpu_scatter_bug_repro.py); "
                "the jaxpr R1 rule should have caught the site upstream",
                ""))
    sites = {}
    for m in _SCATTER_META_RE.finditer(txt):
        fname = m.group(1).replace("\\", "/")
        sites.setdefault(fname, set()).add(int(m.group(2)))
    for fname, lines in sorted(sites.items()):
        if any(fname.endswith(ok) for ok in allowed_files):
            continue
        findings.append(Finding(
            "HLO", flavor, "error",
            f"compiled module contains scatter-derived instructions from "
            f"uncertified file {fname} (lines {sorted(lines)[:4]}) — "
            "every scatter site in a dispatched program must be an "
            "R1_WAIVERS-certified site (fuzz + census + chip validation "
            "behind it)", f"{fname}:{min(lines)}"))
    stats = {
        "scatter_instructions": surviving,
        "scatter_scalar": scalar,
        "scatter_site_files": sorted(sites),
        "scatter_sites": sum(len(v) for v in sites.values()),
    }
    return findings, stats


def check_hlo_root(txt: str, flavor: str, padded_b: int,
                   digest_width: int) -> list[Finding]:
    """The executable-level R5: exactly one small root output (the
    ``[digest_width]`` int digest), everything else fleet-sized."""
    findings: list[Finding] = []
    header = txt.splitlines()[0] if txt else ""
    layout = _header_block(header, "entry_computation_layout")
    if layout is None or "->" not in layout:
        return [Finding(
            "HLO", flavor, "error",
            "no entry_computation_layout in the optimized module header "
            "— the digest-only root check cannot run (update hlo_lint "
            "for this toolchain's header format)", "")]
    outs = _TYPE_RE.findall(layout.split("->", 1)[1])
    digests = [s for d, s in outs
               if _shape(s) == (digest_width,) and d.startswith(("s", "u"))]
    if len(digests) != 1:
        findings.append(Finding(
            "HLO", flavor, "error",
            f"compiled sharded runner has {len(digests)} "
            f"[{digest_width}]-int outputs (want exactly 1: the digest) "
            "— the executable-level poll contract of "
            "parallel/sharded.run_sharded", ""))
    for dtype, shape_s in outs:
        shape = _shape(shape_s)
        if shape == (digest_width,) and dtype.startswith(("s", "u")):
            continue
        if not shape or shape[0] != padded_b:
            findings.append(Finding(
                "HLO", flavor, "error",
                f"non-fleet-sized output {dtype}[{shape_s}] in the "
                f"compiled root (leading dim != padded batch {padded_b}) "
                "— an extra small live-out is another per-chunk host "
                "transfer candidate the jaxpr R5 rule did not see", ""))
    return findings


def check_hlo_alias(txt: str, flavor: str,
                    expected_donated: int) -> tuple[list[Finding], dict]:
    """The compiled half of D1: the executable's input_output_alias map
    must still pair every donated state leaf."""
    header = txt.splitlines()[0] if txt else ""
    block = _header_block(header, "input_output_alias")
    pairs = len(_ALIAS_PAIR_RE.findall(block)) if block else 0
    findings: list[Finding] = []
    if pairs != expected_donated:
        findings.append(Finding(
            "HLO", flavor, "error",
            f"executable input_output_alias carries {pairs} pairs vs "
            f"{expected_donated} donated state leaves — donation "
            "requested at trace time was dropped by the compiler "
            "(every dropped pair is a fleet-leaf-sized copy per chunk)",
            ""))
    return findings, {"alias_pairs": pairs}


# ---------------------------------------------------------------------------
# The compiled matrix.
# ---------------------------------------------------------------------------


def audit_hlo() -> tuple[list[Finding], dict]:
    """Compile the warmed micro-fleet chunk runners (both engines + the
    dp-sharded digest runner) on the visible backend and run every check.

    The shapes are the tests/fleet_shapes.py contract — the executables
    tier-1 already compiles — so with a warm persistent compile cache
    this costs seconds; the first-ever run on a cold container pays the
    compiles once into the cache.  On a TPU backend the same three
    compiles audit the real chip lowering (tunnel item 8's flag flip)."""
    import jax
    import jax.numpy as jnp

    from ..core.types import SimParams
    from ..parallel import mesh as mesh_ops
    from ..parallel import sharded
    from ..sim import parallel_sim as PE
    from ..sim import simulator as S
    from ..utils import xops
    from . import graph_lint as GL
    from .source_lint import repo_root

    tdir = os.path.join(repo_root(), "tests")
    if tdir not in sys.path:
        sys.path.insert(0, tdir)
    from fleet_shapes import FLEET_B, FLEET_CHUNK, FLEET_LANE_KW, \
        FLEET_SER_KW

    allowed = tuple(GL.R1_WAIVERS) + tuple(HLO_STATIC_SCATTER_FILES)
    findings: list[Finding] = []
    stats: dict = {}

    def audit_text(flavor, txt, donated, padded_b=None):
        f, st = check_hlo_scatters(txt, flavor, allowed)
        findings.extend(f)
        f2, st2 = check_hlo_alias(txt, flavor, donated)
        findings.extend(f2)
        st.update(st2)
        if padded_b is not None:
            findings.extend(check_hlo_root(
                txt, flavor, padded_b, GL.DIGEST_WIDTH))
        cns = load_census().hlo_counts(txt)
        st["top_fusions"] = cns["top_fusions"]
        st["backend"] = jax.default_backend()
        stats[flavor] = st

    # Serial chunk runner (the digest flavor tier-1 streams).
    p = xops.resolve_params(
        SimParams(max_clock=500, **FLEET_SER_KW, **GL.TPU_FORMS))
    st = S.dedupe_buffers(S.init_batch(
        p, np.arange(FLEET_B, dtype=np.uint32)))
    inner = S._compiled_digest_run(p.structural(), FLEET_CHUNK, True)
    txt = inner.lower(jnp.asarray(p.delay_table()),
                      jnp.asarray(p.duration_table()), st) \
        .compile().as_text()
    n_state = len(jax.tree_util.tree_leaves(st))
    audit_text("serial/chunk", txt, donated=n_state)

    # Lane chunk runner.
    p_l = xops.resolve_params(
        SimParams(max_clock=500, **FLEET_LANE_KW, **GL.TPU_FORMS))
    st_l = S.dedupe_buffers(PE.init_batch(
        p_l, np.arange(FLEET_B, dtype=np.uint32)))
    inner = PE._compiled_digest_run(p_l.structural(), FLEET_CHUNK, True)
    txt = inner.lower(jnp.asarray(p_l.delay_table()),
                      jnp.asarray(p_l.duration_table()),
                      jnp.asarray(PE.d_min_of(p_l), jnp.int32), st_l) \
        .compile().as_text()
    audit_text("lane/chunk", txt, donated=len(jax.tree_util.tree_leaves(st_l)))

    # The dp-sharded fleet runner: + the digest-only-root check.
    if len(jax.devices()) < 2:
        findings.append(Finding(
            "HLO", "sharded/chunk", "error",
            "cannot HLO-audit the sharded runner: <2 devices (set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 before "
            "importing jax; scripts/graph_audit.py does)", ""))
        return findings, stats
    import dataclasses as dc

    mesh = mesh_ops.make_mesh(n_dp=2, n_mp=1, devices=jax.devices()[:2])
    st_sh = S.init_batch(p, sharded.fleet_seeds(0, FLEET_B))
    st_sh, _ = sharded.pad_to_multiple(p, st_sh, mesh.size)
    padded_b = sharded.batch_size(st_sh)
    st_sh = mesh_ops.shard_batch(mesh, S.dedupe_buffers(st_sh))
    key_p = dc.replace(p, max_clock=0, drop_prob=0.0)
    run = sharded._cached_sharded_run_fn(key_p, mesh, FLEET_CHUNK, S,
                                         "shard_map")
    txt = run.lower(st_sh).compile().as_text()
    # Under shard_map the optimized module IS the per-shard program
    # (scripts/kernel_census.py census_sharded documents the same), so
    # "fleet-sized" at the executable level means the LOCAL batch rows.
    audit_text("sharded/chunk", txt,
               donated=len(jax.tree_util.tree_leaves(st_sh)),
               padded_b=padded_b // mesh.size)
    return findings, stats
