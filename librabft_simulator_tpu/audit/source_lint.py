"""AST source lint: the invariants the jaxpr auditor cannot see.

The graph auditor (:mod:`.graph_lint`) checks what a trace *produced*;
these rules check what the source *could* produce on a path the audit
shapes didn't take, plus repo-hygiene rules that live outside any trace.
Pure-AST, no jax import — the whole pass is milliseconds.

Rules
-----

S1  **Host libraries in traced code.**  ``np.*`` / ``math.*`` calls inside
    the engines' traced functions silently materialize tracers (or crash
    at a shape nobody traced); only static shape arithmetic
    (:data:`ALLOWED_NP`) is exempt.  Scope: :data:`S1_SCOPE` modules minus
    their registered host-side functions (:data:`HOST_FUNCTIONS` /
    :data:`HOST_CLASSES`).  S1b: in the step functions proper
    (:data:`STEP_TRACER_ARGS`), an ``if``/``while`` test may not
    reference a tracer argument (Python control flow on a tracer is a
    trace-time crash at best, a silently-specialized graph at worst) —
    branch on ``SimParams`` fields, which are static.
S2  **Host syncs in hot-loop modules.**  ``jax.device_get`` /
    ``block_until_ready`` stall the dispatch pipeline; inside the
    hot-loop modules (engines, parallel runtime, in-graph telemetry)
    every occurrence must be a registered sanctioned site
    (:data:`SANCTIONED_SYNCS`) — the fleet runtime's whole design is ONE
    digest fetch per chunk (tests/test_multichip.py pins it dynamically;
    this rule pins it at review time).  Post-run decode modules
    (analysis/, telemetry/report.py, checkpoint.py, ...) fetch to host by
    design and are out of scope.
S3  **Unregistered env knobs.**  Every ``os.environ`` read must use a key
    registered in :mod:`.knobs` (or an :data:`knobs.EXTERNAL` infra var).
    Keys are resolved through module-level constants and the registered
    reader helpers, so ``os.environ.get(MODE_ENV)`` resolves fine; an
    unresolvable key is itself a finding.
S4  **Budget literals outside scripts/budgets.py.**  The CI census/audit
    budgets are single-sourced in ``scripts/budgets.py``; a budget value
    reappearing as a literal on a budget-ish line in ``scripts/*.py`` or
    as an inline ``${VAR:-N}`` default in ``scripts/ci_tier1.sh`` is the
    drift this satellite existed to kill.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

from . import knobs as knobs_mod


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    flavor: str      # always "source" (mirrors graph_lint.Finding)
    severity: str    # "error"
    summary: str
    site: str = ""   # "relpath:line"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


PACKAGE = "librabft_simulator_tpu"

# ---------------------------------------------------------------------------
# S1 scope + registries.
# ---------------------------------------------------------------------------

#: Modules whose function bodies are (mostly) traced.  Relative to the
#: package root.
S1_SCOPE = (
    "core/store.py", "core/node.py", "core/data_sync.py",
    "core/pacemaker.py", "core/config.py", "core/packing.py",
    "core/types.py",
    "sim/simulator.py", "sim/parallel_sim.py",
    "telemetry/plane.py", "telemetry/stream.py",
    "utils/xops.py", "utils/hashing.py",
)

#: Host-side functions inside S1_SCOPE modules (np/math is their job:
#: post-run decode, host loops, table precompute, env resolution).
HOST_FUNCTIONS = {
    "sim/simulator.py": {"run_to_completion", "stream_completion",
                         "init_batch"},
    "sim/parallel_sim.py": {"run_to_completion", "init_batch",
                            "d_min_of"},  # static lookahead from the
                                          # host-precomputed delay table
    "telemetry/plane.py": {"fold_planes", "decode", "np_registry",
                           "np_width", "ring_order"},
    "telemetry/stream.py": {"decode_digest", "pad_digest", "fold_digests",
                            "load_ndjson"},
    "core/types.py": {"payload_width"},
    "utils/xops.py": {"backend_mode", "packed_mode", "gate_mode",
                      "macro_mode", "resolve_params", "_bool_env"},
}

#: Whole classes that are host-side (every method exempt from S1).
HOST_CLASSES = {
    "core/types.py": {"SimParams"},
    "telemetry/stream.py": {"TimelineRecorder"},
}

#: np attributes that are STATIC shape arithmetic, legal under a trace
#: (they consume Python ints / .shape tuples, never tracers).
ALLOWED_NP = {"prod", "int32", "uint32", "dtype"}

#: S1b — the step functions and their tracer argument names: an
#: ``if``/``while`` test referencing one of these is Python control flow
#: on a tracer.
STEP_TRACER_ARGS = {
    "sim/simulator.py": {
        "step": {"st", "delay_table", "dur_table"},
        "_select_event": {"st"},
        "_equivocated_payload": {"s_a", "pay"},
        "_forged_qc_payload": {"s_a", "pay"},
    },
    "sim/parallel_sim.py": {
        "step": {"st", "delay_table", "dur_table"},
        "_earliest": {"in_valid", "in_time", "in_kind", "in_stamp",
                      "timer_time"},
        "_equivocate": {"pay"},
    },
}

# ---------------------------------------------------------------------------
# S2 scope + sanctions.
# ---------------------------------------------------------------------------


def _s2_in_scope(rel: str) -> bool:
    """Hot-loop modules: the engines, the parallel runtime, in-graph
    telemetry, core protocol, kernels, utils — and since round 16 the
    serve/ resident loop and the distributed/ runtime (both live INSIDE
    the dispatch pipeline: an unsanctioned sync there stalls every
    chunk, which is exactly the modules the round-10 scope predated).
    Post-run decode modules are host-side by design (analysis/,
    report.py, checkpoint.py, byzantine referees, main.py, oracle/,
    realnode/).  telemetry/ledger.py is in scope BY REGISTRATION, not
    waiver: the runtime ledger wraps the fleet loop's dispatch/poll from
    the host side and must itself contain zero device syncs — this rule
    proves that on every lint run.  Same registration for round 18's
    telemetry/schema.py (the version table) and telemetry/observatory.py
    (the cross-stream store): both are jax-free by contract, so the lint
    proving zero syncs there is free and keeps them honest."""
    if rel in ("sim/simulator.py", "sim/parallel_sim.py",
               "telemetry/plane.py", "telemetry/stream.py",
               "telemetry/ledger.py", "telemetry/schema.py",
               "telemetry/observatory.py"):
        return True
    return rel.startswith(("core/", "parallel/", "ops/", "utils/",
                           "serve/", "distributed/"))


#: (package-relative file, enclosing function) -> justification.  Every
#: device_get / block_until_ready inside S2 scope must appear here.
SANCTIONED_SYNCS = {
    ("parallel/sharded.py", "_poll_digest"):
        "THE poll path: the fleet loop's single blocking fetch — one [D] "
        "digest per chunk (pinned dynamically by test_multichip's "
        "monkeypatched device_get).",
    ("parallel/sharded.py", "_poll_ring"):
        "the device-wrap poll path (round 19): ONE blocking fetch of the "
        "[ring_k, D] digest ring + retired count per OUTER call — up to "
        "ring_k retired chunks amortize it (tledger ring_stats "
        "polls_per_retired_chunk <= 1/K is the acceptance pin).",
    ("parallel/sharded.py", "pad_to_multiple"):
        "one-time host-side padding of a host (checkpoint-restored) "
        "fleet: filler is fetched once, outside the chunk loop.",
    ("sim/simulator.py", "run_to_completion"):
        "single-chip host completion loop (tests/CLI), not the fleet "
        "runtime hot path.",
    ("sim/simulator.py", "stream_completion"):
        "the digest-contract host loop: one [D] fetch per chunk by "
        "construction.",
    ("sim/parallel_sim.py", "run_to_completion"):
        "single-chip host completion loop (tests/CLI).",
    # --- serve/ (round 16: the resident fleet loop joined S2 scope) ----
    ("serve/service.py", "_egress"):
        "digest-TRIGGERED only (never steady-state): one [slots] halted "
        "fetch to identify finished slots, then one gather per leaf over "
        "the k finished rows — between chunks, outside the double-"
        "buffered dispatch (tests/test_serve.py pins the poll path "
        "stays one [13] digest per chunk).",
    ("serve/service.py", "_admit"):
        "admission-time fetch of k freshly-initialised scenario rows "
        "into the host-side donor — per admission wave, not per chunk; "
        "the resident executable itself is never touched.",
    ("serve/service.py", "save"):
        "preemption checkpoint: the whole resident fleet lands on host "
        "by design, once, at an eviction boundary.",
    # --- distributed/ (round 16) ---------------------------------------
    ("distributed/egress.py", "local_rows_at"):
        "per-host egress landing: O(k) device-side row gathers over the "
        "finished slots only — per egress event, outside the chunk "
        "loop, never the whole local shard.",
    ("distributed/workers.py", "fleet_run"):
        "one-time host-staging of the init fleet before placement (the "
        "multi-process device_put contract) — before the chunk loop "
        "starts.",
    ("distributed/workers.py", "fleet_phase"):
        "one-time host-staging of the init fleet (same contract as "
        "fleet_run) for the resize-under-fire checkpoint phase.",
}

# ---------------------------------------------------------------------------
# S3 helpers.
# ---------------------------------------------------------------------------

#: Functions that read os.environ with a key passed by parameter; the lint
#: checks their CALL SITES' first argument instead of the read inside.
#: _bool_knob is utils/aot.py's jax-free restatement of _bool_env;
#: _int_env is serve/api.py's integer twin.
READER_HELPERS = {"_bool_env", "_bool_knob", "_int_env"}


# ---------------------------------------------------------------------------
# AST walking.
# ---------------------------------------------------------------------------


def _module_constants(tree: ast.Module) -> dict:
    """Module-level ``NAME = "literal"`` string assignments (how xops names
    its env keys)."""
    out = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _attr_chain(node) -> list[str]:
    """['os', 'environ', 'get'] for os.environ.get — [] if not a chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


class _FuncInfo:
    def __init__(self, node, classes):
        self.node = node
        self.name = node.name
        self.classes = tuple(classes)  # enclosing class names


def _functions(tree) -> list[_FuncInfo]:
    out = []

    def rec(node, classes):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(_FuncInfo(child, classes))
                rec(child, classes)
            elif isinstance(child, ast.ClassDef):
                rec(child, classes + [child.name])
            else:
                rec(child, classes)

    rec(tree, [])
    return out


def _names_in(node) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def enclosing_functions(funcs: list, lineno: int) -> list[str]:
    """All enclosing function names for a line, outermost first (or
    ``["<module>"]``).  Shared by every registry-keyed rule (S2, D2, C1):
    a sanction on a host function must cover its nested helpers, so
    lookups check the whole chain — the innermost-only form silently
    false-positives the moment a sanctioned body grows a closure."""
    names = [fn.name for fn in funcs
             if fn.node.lineno <= lineno <= (fn.node.end_lineno or 0)]
    return names or ["<module>"]


# ---------------------------------------------------------------------------
# Rules.
# ---------------------------------------------------------------------------


def _s1_host(rel: str, fn: _FuncInfo) -> bool:
    if fn.name in HOST_FUNCTIONS.get(rel, ()):
        return True
    host_classes = HOST_CLASSES.get(rel, ())
    return any(c in host_classes for c in fn.classes)


def lint_s1(rel: str, tree: ast.Module) -> list[Finding]:
    if rel not in S1_SCOPE:
        return []
    findings = []
    host_spans = []  # line spans of host functions: nested defs inherit
    for fn in _functions(tree):
        if _s1_host(rel, fn):
            host_spans.append((fn.node.lineno, fn.node.end_lineno))
    for fn in _functions(tree):
        span_host = any(a <= fn.node.lineno <= b for a, b in host_spans)
        if span_host:
            continue
        for node in ast.walk(fn.node):
            chain = _attr_chain(node) if isinstance(node, ast.Attribute) \
                else []
            if len(chain) >= 2 and chain[0] in ("np", "math") \
                    and chain[1] not in (ALLOWED_NP
                                         if chain[0] == "np" else ()):
                findings.append(Finding(
                    "S1", "source", "error",
                    f"{'.'.join(chain)} inside traced function "
                    f"{fn.name}() — host numerics silently materialize "
                    "tracers; use jnp (or register the function in "
                    "HOST_FUNCTIONS with a reason)",
                    f"{rel}:{node.lineno}"))
        tracer_args = STEP_TRACER_ARGS.get(rel, {}).get(fn.name)
        if tracer_args:
            for node in ast.walk(fn.node):
                if isinstance(node, (ast.If, ast.While)):
                    hit = _names_in(node.test) & tracer_args
                    if hit:
                        findings.append(Finding(
                            "S1", "source", "error",
                            f"Python {type(node).__name__.lower()} on "
                            f"tracer argument(s) {sorted(hit)} in "
                            f"{fn.name}() — branch with lax.cond/"
                            "jnp.where, or on static SimParams fields",
                            f"{rel}:{node.lineno}"))
    return findings


def lint_s2(rel: str, tree: ast.Module) -> list[Finding]:
    if not _s2_in_scope(rel):
        return []
    findings = []
    funcs = _functions(tree)

    for node in ast.walk(tree):
        # Both spellings: jax.device_get / x.block_until_ready
        # (Attribute) AND `from jax import device_get; device_get(...)`
        # (bare Name) — the import form must not bypass the rule.
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            name = node.id
        else:
            continue
        if name not in ("device_get", "block_until_ready"):
            continue
        chain = enclosing_functions(funcs, node.lineno)
        func = chain[-1]
        if any((rel, fname) in SANCTIONED_SYNCS for fname in chain):
            continue
        findings.append(Finding(
            "S2", "source", "error",
            f"{name} in hot-loop module function {func}() outside "
            "the sanctioned sites — the fleet contract is one [D] digest "
            "fetch per chunk (parallel/sharded._poll_digest); add a "
            "SANCTIONED_SYNCS entry only with a justification",
            f"{rel}:{node.lineno}"))
    return findings


def _env_reads(tree: ast.Module):
    """Yield (key_expr, lineno, enclosing_reader_param_names) for every
    os.environ read in a module."""
    funcs = _functions(tree)

    def reader_params(lineno):
        for fn in funcs:
            if fn.name in READER_HELPERS and \
                    fn.node.lineno <= lineno <= (fn.node.end_lineno or 0):
                return {a.arg for a in fn.node.args.args}
        return set()

    for node in ast.walk(tree):
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and _attr_chain(node.value) == ["os", "environ"]:
            yield node.slice, node.lineno, reader_params(node.lineno)
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain in (["os", "environ", "get"],
                         ["os", "environ", "setdefault"],
                         ["os", "getenv"]) and node.args:
                yield node.args[0], node.lineno, reader_params(node.lineno)
            elif chain and chain[-1] in READER_HELPERS and node.args:
                # A registered reader call: its first arg IS the key.
                yield node.args[0], node.lineno, set()


def lint_s3(rel: str, tree: ast.Module) -> list[Finding]:
    findings = []
    consts = _module_constants(tree)
    for key_expr, lineno, reader_params in _env_reads(tree):
        if isinstance(key_expr, ast.Constant) \
                and isinstance(key_expr.value, str):
            key = key_expr.value
        elif isinstance(key_expr, ast.Name) and key_expr.id in consts:
            key = consts[key_expr.id]
        elif isinstance(key_expr, ast.Name) \
                and key_expr.id in reader_params:
            continue  # the reader helper itself; call sites are checked
        else:
            findings.append(Finding(
                "S3", "source", "error",
                "os.environ read with an unresolvable key — name env "
                "keys with string literals or module-level constants so "
                "the knob registry stays checkable",
                f"{rel}:{lineno}"))
            continue
        if key in knobs_mod.REGISTERED or key in knobs_mod.EXTERNAL:
            continue
        findings.append(Finding(
            "S3", "source", "error",
            f"env knob {key!r} is not registered in audit/knobs.py — add "
            "a Knob row (and regenerate the README table) or drop the "
            "read",
            f"{rel}:{lineno}"))
    return findings


def _load_budgets(root: str) -> dict:
    path = os.path.join(root, "scripts", "budgets.py")
    ns: dict = {}
    with open(path) as f:
        exec(compile(f.read(), path, "exec"), ns)  # noqa: S102 — our file
    return ns["BUDGETS"]


_BUDGETISH = re.compile(r"(?i)budget|assert|min_dots|floor")


def lint_s4(root: str) -> list[Finding]:
    findings = []
    try:
        budgets = _load_budgets(root)
    except FileNotFoundError:
        return [Finding("S4", "source", "error",
                        "scripts/budgets.py missing — the census budgets "
                        "have no single source", "scripts/budgets.py")]
    values = set(budgets.values())
    sdir = os.path.join(root, "scripts")
    for name in sorted(os.listdir(sdir)):
        if not name.endswith(".py") or name == "budgets.py":
            continue
        path = os.path.join(sdir, name)
        with open(path) as f:
            text = f.read()
        lines = text.splitlines()
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and type(node.value) is int \
                    and node.value in values \
                    and _BUDGETISH.search(lines[node.lineno - 1]):
                findings.append(Finding(
                    "S4", "source", "error",
                    f"budget literal {node.value} in scripts/{name} — "
                    "consume scripts/budgets.py instead of restating the "
                    "value", f"scripts/{name}:{node.lineno}"))
    sh = os.path.join(sdir, "ci_tier1.sh")
    if os.path.exists(sh):
        with open(sh) as f:
            for i, line in enumerate(f, 1):
                if re.search(r"(BUDGET|MIN_DOTS)\w*=", line) \
                        and re.search(r":-\s*\d|=\s*\d", line):
                    findings.append(Finding(
                        "S4", "source", "error",
                        "inline budget default in ci_tier1.sh — budgets "
                        "come from `eval \"$(python scripts/budgets.py "
                        "--sh)\"`", f"scripts/ci_tier1.sh:{i}"))
    return findings


# ---------------------------------------------------------------------------
# Entry points.
# ---------------------------------------------------------------------------


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def iter_repo_sources(root: str | None = None):
    """Yield ``(rel, text)`` for every lintable .py file — THE one repo
    walk contract, shared by the S/D/C rule runners (source_lint,
    donation_lint, concurrency_lint) so their scopes can never drift:
    package files get package-relative paths ('sim/simulator.py'),
    everything else repo-relative ('scripts/x.py')."""
    root = root or repo_root()
    skip_dirs = {"tests", "__pycache__", "native", ".git", ".claude",
                 "related"}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in skip_dirs]
        for name in sorted(filenames):
            if not name.endswith(".py") or name == "__graft_entry__.py":
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if rel.startswith(PACKAGE + "/"):
                rel = rel[len(PACKAGE) + 1:]
            with open(path) as f:
                yield rel, f.read()


def lint_text(rel: str, text: str) -> list[Finding]:
    """Lint one file's source (S1-S3).  ``rel`` is the path the scope
    rules see: package files are package-relative ('sim/simulator.py'),
    everything else repo-relative ('bench.py', 'scripts/x.py') — exactly
    what :func:`run` passes.  Fixture tests feed synthetic sources here."""
    tree = ast.parse(text)
    return lint_s1(rel, tree) + lint_s2(rel, tree) + lint_s3(rel, tree)


def run(root: str | None = None) -> list[Finding]:
    """Lint the whole repo; returns all findings (S1-S4)."""
    root = root or repo_root()
    findings: list[Finding] = []
    for rel, text in iter_repo_sources(root):
        try:
            findings += lint_text(rel, text)
        except SyntaxError as e:
            findings.append(Finding(
                "S1", "source", "error",
                f"unparseable source: {e}", rel))
    findings += lint_s4(root)
    try:
        in_sync = knobs_mod.readme_in_sync(
            os.path.join(root, "README.md"))
    except (ValueError, FileNotFoundError) as e:
        in_sync = False
        findings.append(Finding(
            "S3", "source", "error", str(e), "README.md"))
    if not in_sync:
        findings.append(Finding(
            "S3", "source", "error",
            "README 'Configuration knobs' table is stale — run "
            "python -m librabft_simulator_tpu.audit.knobs --write-readme",
            "README.md"))
    return findings
