"""Donation & aliasing verifier (D-rules): the buffer-lifetime half of the
audit — what the jaxpr rules cannot see.

The fleet runtime's whole memory story rides on buffer donation (the chunk
runner threads a fleet-sized state in place between dispatches), and
donation has exactly one host-side obligation: every donated buffer must
be XLA-OWNED.  The PR-9 incident is the canonical violation — a
checkpoint-restored numpy tree was ``device_put``-placed without
``dedupe_buffers`` and fed to the donating chunk runner; on the CPU
backend ``device_put`` of host numpy can ZERO-COPY alias the numpy memory,
so XLA recycled buffers it did not own (deterministic segfault on the
second post-restore dispatch).  ``serve/service.py:141``/``:533`` carry
the hand-threaded fix; these rules make the whole class machine-checked.

Rules
-----

D1  **Donation map pinned per flavor.**  Every runner flavor is staged
    (``.lower()`` — trace + StableHLO emission, no XLA compile, so the
    whole matrix costs seconds like the jaxpr audit) and the per-leaf
    donation record (``Lowered.args_info``; the emitted modules carry it
    as ``tf.aliasing_output`` for plain jit, ``jax.buffer_donor`` under
    shard_map) is read back and checked:
    every donated leaf lives under the STATE argument and every
    state leaf is donated — tables, lookahead scalars, admission masks
    and donors are never donated.  The donated/total leaf counts are
    pinned in ``scripts/budgets.py`` (``DONATION``), so a donation-map
    change is a gated diff, not a silent rebaseline.  (The compiled
    executable's ``input_output_alias`` survival is re-checked by the
    HLO audit on the flavors it compiles — :mod:`.hlo_lint`.)
D2  **dedupe-before-placement.**  AST rule over the donation-adjacent
    modules (:data:`D2_SCOPE`): every host→device placement
    (``shard_batch`` / ``device_put``) must route its placed value
    through ``dedupe_buffers`` (the copy that forces every leaf into an
    XLA-owned buffer), or the (file, function) site must be registered
    in :data:`D2_SANCTIONED` with a justification — i.e. the exact PR-9
    bare-``device_put``-into-a-donating-runner path cannot be written
    without tripping review.
D3  **Host use-after-donate.**  AST rule: a name passed as the donated
    argument of a registered donating callable (:data:`D3_DONATING`)
    and then READ again in the same scope before being rebound is an
    error — the read dereferences a buffer XLA already recycled.  The
    safe idiom rebinds in the same statement (``st, dg = run(st)``).
    Lexical forward scan: straight-line misuse is caught at review
    time; loop-carried aliasing stays the fuzz/test harness's job.
"""

from __future__ import annotations

import ast

from .source_lint import Finding, _attr_chain, _functions, \
    enclosing_functions, iter_repo_sources

#: The D1 runner matrix (audit_donation's flavors) — scripts/budgets.py
#: DONATION must pin exactly this set (tests/test_audit.py checks).
DONATION_FLAVORS = (
    "serial/run", "serial/digest", "serial/telemetry", "serial/scenario",
    "lane/digest", "sharded/digest", "sharded/ring", "sharded/scenario",
    "serve/install", "sanitize/serial")

#: D2: donation-adjacent modules — everything that stages host trees onto
#: the mesh a donating runner consumes (package-relative, plus the serve
#: and distributed trees wholesale).
D2_SCOPE_PREFIXES = ("serve/", "distributed/")
D2_SCOPE_FILES = ("sim/checkpoint.py", "parallel/sharded.py")

#: Placement callees: callee attr name -> index of the PLACED argument.
_PLACEMENTS = {"shard_batch": 1, "device_put": 0}

#: (file, enclosing function) -> justification.  Every placement in D2
#: scope that does not visibly route through dedupe_buffers must appear
#: here.
D2_SANCTIONED = {
    ("serve/service.py", "_admit"):
        "admission donor/mask placement: install_rows donates ONLY its "
        "state argument (the D1 pin), never the donor or mask — and the "
        "donor rows are device_get-fetched into fresh host-owned numpy "
        "the install write only READS; the XLA-owned output is what "
        "flows onward.",
}

#: D3: per-file donating callables — dotted callee pattern -> donated
#: argument index.  These are the runners jitted with donate_argnums
#: (engine chunk runners, the sharded fleet runner, the admission write).
D3_DONATING = {
    "serve/service.py": {"self._run": 0, "sc.install_rows": 0,
                         "install_rows": 0},
    "parallel/sharded.py": {"run": 0},
    "sim/simulator.py": {"run": 0},
    "sim/parallel_sim.py": {"run": 0},
    "audit/sanitize.py": {"run": 0},
}


# ---------------------------------------------------------------------------
# D1 — the lowered donation map.
# ---------------------------------------------------------------------------


def donation_map(jit_fn, args: tuple) -> dict:
    """Lower ``jit_fn(*args)`` (no XLA compile) and return the donation
    view: ``{"donated": [leaf paths], "kept": [leaf paths], "total": n}``.
    Paths are ``jax.tree_util.keystr`` forms over the args tuple, so
    ``[2].store.hcr`` names arg 2's state leaf.  The map is read from
    ``Lowered.args_info`` — jax's own per-leaf donation record over the
    FULL call signature (unused-arg pruning can drop parameters from the
    emitted module, so the module text alone under-counts); the emitted
    ``tf.aliasing_output``/``jax.buffer_donor`` markers and the compiled
    executable's ``input_output_alias`` are re-checked by the HLO audit
    on the flavors it compiles."""
    import jax

    lowered = jit_fn.lower(*args)
    info = lowered.args_info
    if isinstance(info, tuple) and len(info) == 2 \
            and isinstance(info[1], dict):
        info = info[0]  # (args, kwargs) form: kwargs are always empty here
    flat_info = jax.tree_util.tree_flatten_with_path(info)[0]
    paths = [jax.tree_util.keystr(k) for k, _ in flat_info]
    donated = [p for p, (_, info) in zip(paths, flat_info)
               if getattr(info, "donated", False)]
    kept = [p for p, (_, info) in zip(paths, flat_info)
            if not getattr(info, "donated", False)]
    return {"donated": donated, "kept": kept, "total": len(flat_info)}


def check_donation(jit_fn, args: tuple, state_argpos: int | None,
                   flavor: str, expected_donated: int | None = None
                   ) -> tuple[list[Finding], dict]:
    """D1 on one staged runner: every donated leaf under the state
    argument, every state leaf donated (nothing else ever donated), and
    the donated count pinned when ``expected_donated`` is given.
    ``state_argpos=None`` asserts a donation-FREE callable (the checkify
    sanitizer build: no donation, so no dedupe obligation)."""
    findings: list[Finding] = []
    dm = donation_map(jit_fn, args)
    prefix = None if state_argpos is None else f"[{state_argpos}]"
    if prefix is None:
        for p in dm["donated"]:
            findings.append(Finding(
                "D1", flavor, "error",
                f"donation-free contract violated: leaf {p} is donated — "
                "this callable's callers do not route their inputs "
                "through dedupe_buffers (re-audit every call site before "
                "donating here)", ""))
    else:
        for p in dm["donated"]:
            if not p.startswith(prefix):
                findings.append(Finding(
                    "D1", flavor, "error",
                    f"non-state leaf {p} is donated — only the fleet "
                    "state input may be donated (tables/masks/donors are "
                    "host-reused across dispatches)", ""))
        undonated_state = [p for p in dm["kept"] if p.startswith(prefix)]
        if undonated_state:
            findings.append(Finding(
                "D1", flavor, "error",
                f"{len(undonated_state)} state leaves are NOT donated "
                f"(first: {undonated_state[0]}) — the chunk runner must "
                "thread the whole fleet state in place or every chunk "
                "pays a fleet-sized copy", ""))
    if expected_donated is not None \
            and len(dm["donated"]) != expected_donated:
        findings.append(Finding(
            "D1", flavor, "error",
            f"donation-map drift: {len(dm['donated'])} donated leaves vs "
            f"the pinned {expected_donated} (scripts/budgets.py DONATION) "
            "— a state leaf was added/removed or a donate_argnums "
            "changed; re-audit the dedupe call sites and re-pin", ""))
    stats = {"donated": len(dm["donated"]), "kept": len(dm["kept"]),
             "total": dm["total"]}
    return findings, stats


def _expected_table() -> dict:
    """The pinned per-flavor donated-leaf counts from scripts/budgets.py
    (``DONATION``; absent = unpinned, counts recorded but not gated)."""
    import os

    from .source_lint import repo_root

    path = os.path.join(repo_root(), "scripts", "budgets.py")
    ns: dict = {}
    try:
        with open(path) as f:
            exec(compile(f.read(), path, "exec"), ns)  # noqa: S102
    except FileNotFoundError:
        return {}
    return ns.get("DONATION", {})


def audit_donation(shape: str = "micro") -> tuple[list[Finding], dict]:
    """D1 over the runner matrix: both engines' chunk runners (run +
    digest flavors, the telemetry and scenario twins), the dp-sharded
    fleet runner (plain + the scenario-armed resident-serve key), the
    admission write, and the checkify sanitizer build.  Staging only —
    ``.lower()`` never invokes XLA, so the matrix costs seconds."""
    import dataclasses as dc

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..core.types import SimParams
    from ..parallel import mesh as mesh_ops
    from ..parallel import sharded
    from ..serve import scenario as sc
    from ..sim import parallel_sim as PE
    from ..sim import simulator as S
    from ..utils import xops
    from . import graph_lint as GL

    ser_kw = dict(GL.CENSUS_KW if shape == "census" else GL.MICRO_SER_KW)
    lane_kw = dict(GL.CENSUS_KW if shape == "census" else GL.MICRO_LANE_KW)
    expected = _expected_table()
    findings: list[Finding] = []
    stats: dict = {}
    steps, batch = 2, 3

    def run_check(flavor, jit_fn, args, state_argpos):
        f, st = check_donation(jit_fn, args, state_argpos, flavor,
                               expected_donated=expected.get(flavor))
        findings.extend(f)
        stats[flavor] = st

    def ser_args(p):
        st = S.init_batch(p, np.arange(batch, dtype=np.uint32))
        return (jnp.asarray(p.delay_table()),
                jnp.asarray(p.duration_table()), st)

    # Serial engine: run + digest twins, then the telemetry and scenario
    # flavors (each changes the state leaf set, hence the donation map).
    for name, kw in (("serial/run", {}),
                     ("serial/digest", {}),
                     ("serial/telemetry", dict(telemetry=True,
                                               flight_cap=32)),
                     ("serial/scenario", dict(scenario=True))):
        p = xops.resolve_params(
            SimParams(**ser_kw, **GL.TPU_FORMS, **kw))
        maker = (S._compiled_run if name == "serial/run"
                 else S._compiled_digest_run)
        run_check(name, maker(p.structural(), steps, True), ser_args(p), 2)

    # Lane engine (digest flavor: the stream/fleet contract one).
    p_lane = xops.resolve_params(
        SimParams(**lane_kw, **GL.TPU_FORMS))
    st = PE.init_batch(p_lane, np.arange(batch, dtype=np.uint32))
    lane_args = (jnp.asarray(p_lane.delay_table()),
                 jnp.asarray(p_lane.duration_table()),
                 jnp.asarray(PE.d_min_of(p_lane), jnp.int32), st)
    run_check("lane/digest",
              PE._compiled_digest_run(p_lane.structural(), steps, True),
              lane_args, 3)

    # The dp-sharded fleet runner (the production chunk loop) and its
    # scenario-armed twin — the resident fleet service's executable key.
    if len(jax.devices()) < 2:
        findings.append(Finding(
            "D1", "sharded/digest", "error",
            "cannot audit the sharded runner's donation map: <2 devices "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "before importing jax; scripts/graph_audit.py does)", ""))
    else:
        mesh = mesh_ops.make_mesh(n_dp=2, n_mp=1,
                                  devices=jax.devices()[:2])
        for name, kw in (("sharded/digest", {}),
                         ("sharded/ring", dict(wrap="device", ring_k=4)),
                         ("sharded/scenario", dict(scenario=True))):
            p = xops.resolve_params(
                SimParams(**ser_kw, **GL.TPU_FORMS, **kw))
            st = S.init_batch(p, sharded.fleet_seeds(0, 4))
            st = mesh_ops.shard_batch(mesh, S.dedupe_buffers(st))
            key_p = dc.replace(p, max_clock=0, drop_prob=0.0)
            if key_p.scenario:
                from ..core import types as core_types
                key_p = dc.replace(key_p, commit_chain=3,
                                   **core_types.DELAY_KEY_DEFAULTS)
            # The ring runner takes (state, cap): the state is donated,
            # the host's chunk-budget scalar NEVER is.
            args = ((st, jnp.int32(1)) if key_p.wrap == "device"
                    else (st,))
            run_check(name,
                      sharded._cached_sharded_run_fn(
                          key_p, mesh, steps, S, "shard_map"),
                      args, 0)

        # The admission write: state donated, mask and donor NEVER (the
        # static pin that makes _admit's undeduped donor placement safe —
        # see D2_SANCTIONED).
        p_sc = dc.replace(
            xops.resolve_params(SimParams(**ser_kw, **GL.TPU_FORMS)),
            scenario=True)
        rows = sc.init_rows(
            p_sc, sc.stack_rows([sc.default_row(p_sc, s)
                                 for s in range(4)]))
        st_sc = S.dedupe_buffers(rows)
        mask = jnp.zeros((4,), jnp.bool_)
        donor = jax.tree.map(jnp.zeros_like, st_sc)
        run_check("serve/install", sc.install_rows,
                  (st_sc, mask, donor), 0)

    # The checkify sanitizer build: donation-FREE by contract (its
    # callers hand it arbitrary externally-held states — doctored
    # fixtures, checkpoint trees — with no dedupe obligation).
    from . import sanitize as SAN

    p_san = xops.resolve_params(SimParams(max_clock=500, **ser_kw))
    st = S.init_batch(p_san, np.arange(batch, dtype=np.uint32))
    checked = SAN._cached_checked_run(p_san, steps, True, "serial")
    inner = getattr(checked, "__wrapped__", checked)
    # wrap_compile/wrap_jit forward lower only for prefix-free runners;
    # the sanitizer takes just the state, so the staging API is live.
    run_check("sanitize/serial", inner, (st,), None)

    return findings, stats


# ---------------------------------------------------------------------------
# D2 — dedupe-before-placement (AST).
# ---------------------------------------------------------------------------


def _d2_in_scope(rel: str) -> bool:
    return rel.startswith(D2_SCOPE_PREFIXES) or rel in D2_SCOPE_FILES


def _contains_dedupe(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            chain = _attr_chain(sub.func)
            if chain and chain[-1] == "dedupe_buffers":
                return True
    return False


def lint_d2(rel: str, tree: ast.Module) -> list[Finding]:
    if not _d2_in_scope(rel):
        return []
    findings = []
    funcs = _functions(tree)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if not chain or chain[-1] not in _PLACEMENTS:
            continue
        argpos = _PLACEMENTS[chain[-1]]
        if argpos >= len(node.args):
            continue
        placed = node.args[argpos]
        if _contains_dedupe(placed):
            continue
        enclosing = enclosing_functions(funcs, node.lineno)
        func = enclosing[-1]
        if any((rel, fname) in D2_SANCTIONED for fname in enclosing):
            continue
        findings.append(Finding(
            "D2", "source", "error",
            f"{chain[-1]} placement in {func}() does not route through "
            "dedupe_buffers — a bare device placement of host numpy can "
            "zero-copy alias host memory, and a donating runner then "
            "frees buffers XLA does not own (the PR-9 segfault); wrap "
            "the placed tree in dedupe_buffers, or register the site in "
            "D2_SANCTIONED with a justification",
            f"{rel}:{node.lineno}"))
    return findings


# ---------------------------------------------------------------------------
# D3 — host use-after-donate (AST).
# ---------------------------------------------------------------------------


def _var_key(node):
    """A trackable donated-argument expression: a bare name ('st') or a
    self attribute ('self._st'); None for anything else (untrackable
    expressions are not checkable lexically)."""
    if isinstance(node, ast.Name):
        return ("name", node.id)
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return ("self", node.attr)
    return None


def _stores_in(node) -> set:
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
            out.add(("name", sub.id))
        elif isinstance(sub, ast.Attribute) \
                and isinstance(sub.ctx, ast.Store) \
                and isinstance(sub.value, ast.Name) \
                and sub.value.id == "self":
            out.add(("self", sub.attr))
    return out


def _loads_in(node, key) -> list[int]:
    out = []
    for sub in ast.walk(node):
        if key[0] == "name" and isinstance(sub, ast.Name) \
                and isinstance(sub.ctx, ast.Load) and sub.id == key[1]:
            out.append(sub.lineno)
        elif key[0] == "self" and isinstance(sub, ast.Attribute) \
                and isinstance(sub.ctx, ast.Load) \
                and sub.attr == key[1] \
                and isinstance(sub.value, ast.Name) \
                and sub.value.id == "self":
            out.append(sub.lineno)
    return out


def _sub_suites(stmt) -> list:
    """The statement suites nested directly in a compound statement."""
    suites = [getattr(stmt, f, None) for f in ("body", "orelse",
                                               "finalbody")]
    suites += [h.body for h in getattr(stmt, "handlers", []) or []]
    return [s for s in suites if s]


def _scan_continuation(stmts: list, key, pattern: str, rel: str,
                       findings: list) -> bool:
    """Walk the statements that lexically execute after a donation; flag
    the first read of ``key``.  Returns True when the scan is RESOLVED
    (read flagged, name rebound, or control left the function via
    return/raise) — the caller then skips the ancestor continuations,
    which only execute on paths this branch never rejoins."""
    for later in stmts:
        if isinstance(later, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            continue
        # Loads anywhere in the statement — including inside branch
        # bodies: a read on ANY path after the donation is a potential
        # use-after-free (loads evaluate before same-statement stores).
        loads = _loads_in(later, key)
        if loads:
            findings.append(Finding(
                "D3", "source", "error",
                f"{key[1]} is read after being donated to {pattern}() — "
                "the buffer was recycled by XLA at dispatch; rebind the "
                "name from the runner's output (`st, dg = run(st)`) "
                "before any further use",
                f"{rel}:{loads[0]}"))
            return True
        if key in _stores_in(later):
            return True  # rebound — later reads see the new buffer
        if isinstance(later, (ast.Return, ast.Raise, ast.Break,
                              ast.Continue)):
            return True  # control leaves this path before any more reads
    return False


def _scan_d3_suite(suite: list, continuations: list, table: dict,
                   rel: str, findings: list) -> None:
    """One statement suite: donations found in simple statements scan
    the suite's own remainder, then the enclosing suites' remainders
    (``continuations``, innermost first).  Branch suites are scanned
    separately with the SAME continuation, so a read in a mutually
    exclusive branch is never attributed to another branch's donation
    (loop-carried reads remain the fuzz/test harness's job)."""
    for i, stmt in enumerate(suite):
        rest = suite[i + 1:]
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue  # nested scopes are scanned as their own functions
        subs = _sub_suites(stmt)
        if subs:
            for sub in subs:
                _scan_d3_suite(sub, [rest] + continuations, table, rel,
                               findings)
            continue
        for call in ast.walk(stmt):
            if not isinstance(call, ast.Call):
                continue
            pattern = ".".join(_attr_chain(call.func))
            argnum = table.get(pattern)
            if argnum is None or argnum >= len(call.args):
                continue
            key = _var_key(call.args[argnum])
            if key is None:
                continue
            if key in _stores_in(stmt):
                continue  # `st, dg = run(st)` — rebound in place
            for chunk in [rest] + continuations:
                if _scan_continuation(chunk, key, pattern, rel,
                                      findings):
                    break


def lint_d3(rel: str, tree: ast.Module,
            donating: dict | None = None) -> list[Finding]:
    table = (donating if donating is not None else D3_DONATING).get(rel)
    if not table:
        return []
    findings: list[Finding] = []
    for fn in _functions(tree):
        _scan_d3_suite(fn.node.body, [], table, rel, findings)
    return findings


# ---------------------------------------------------------------------------
# Entry points (source rules; D1 runs from audit_donation).
# ---------------------------------------------------------------------------


def lint_text(rel: str, text: str,
              donating: dict | None = None) -> list[Finding]:
    """D2+D3 on one file's source (fixture entry point, mirroring
    source_lint.lint_text)."""
    tree = ast.parse(text)
    return lint_d2(rel, tree) + lint_d3(rel, tree, donating=donating)


def run_source(root: str | None = None) -> list[Finding]:
    """D2+D3 over the repo (source_lint.iter_repo_sources — one shared
    walk contract for every rule family)."""
    findings: list[Finding] = []
    for rel, text in iter_repo_sources(root):
        try:
            findings += lint_text(rel, text)
        except SyntaxError as e:
            findings.append(Finding(
                "D2", "source", "error",
                f"unparseable source: {e}", rel))
    return findings
