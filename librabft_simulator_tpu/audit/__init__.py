"""Static-analysis subsystem: jaxpr lint, source lint, runtime-contract
lints (donation, concurrency, compiled HLO), checkify sanitizer.

Four PRs of perf and observability work rest on invariants that were only
example-tested until now — "no scalar scatters in TPU-gated graphs" (the
miscompile class PR 1/2 designed around), "consensus state is int32/uint32
only", "one host fetch per dispatched chunk", "knob-off graphs are
bit-identical".  Every one of them is decidable on the traced jaxpr or the
source AST, so this package enforces them statically — and since round 16
the audit also covers the layer the serve/distributed subsystems live in:
host-side buffer lifetimes, cross-process waits, and the compiled
executable itself.

* :mod:`.graph_lint` — traces both engines' step functions (every lowering
  flavor) and walks the ClosedJaxpr: rules R1-R6.
* :mod:`.source_lint` — AST rules over the repo source: host-library calls
  in traced code, unsanctioned host syncs, unregistered env knobs,
  duplicated CI budget literals (S1-S4).
* :mod:`.donation_lint` — the donation/aliasing verifier (D1-D3): the
  per-flavor donation map pinned from the staged lowering, the
  dedupe-before-placement rule (the PR-9 segfault class), and the
  host use-after-donate rule.
* :mod:`.concurrency_lint` — host-concurrency rules (C1-C3): every
  cross-process wait bounded, lock discipline over registered shared
  state, NDJSON rows flushed per write.
* :mod:`.hlo_lint` — the compiled-HLO audit (rule ``HLO``): scatter
  class + site provenance, the digest-only small root, and donation
  alias survival, read from ``jit(...).lower(...).compile().as_text()``
  on whatever backend is visible (tunnel checklist item 8's
  backend-portable half).
* :mod:`.knobs` — the env-knob registry the source lint checks against
  (and the README "Configuration knobs" table generator).
* :mod:`.sanitize` — a checkify-instrumented build of both engines'
  chunk runners behind the ``LIBRABFT_CHECKIFY`` knob (including the
  scenario-plane flavor); off, the engine graphs are untouched (the
  census gates pin this transitively).

``scripts/graph_audit.py`` runs every pass and gates CI via
``--assert-clean``; see the README "Static guarantees" section for the
rule tables and the waiver protocol.
"""
