"""Static-analysis subsystem: jaxpr lint, source lint, checkify sanitizer.

Four PRs of perf and observability work rest on invariants that were only
example-tested until now — "no scalar scatters in TPU-gated graphs" (the
miscompile class PR 1/2 designed around), "consensus state is int32/uint32
only", "one host fetch per dispatched chunk", "knob-off graphs are
bit-identical".  Every one of them is decidable on the traced jaxpr or the
source AST, so this package enforces them statically:

* :mod:`.graph_lint` — traces both engines' step functions (every lowering
  flavor) and walks the ClosedJaxpr: rules R1-R6.
* :mod:`.source_lint` — AST rules over the repo source: host-library calls
  in traced code, unsanctioned host syncs, unregistered env knobs,
  duplicated CI budget literals.
* :mod:`.knobs` — the env-knob registry the source lint checks against
  (and the README "Configuration knobs" table generator).
* :mod:`.sanitize` — a checkify-instrumented build of both engines'
  chunk runners behind the ``LIBRABFT_CHECKIFY`` knob; off, the engine
  graphs are untouched (the census gates pin this transitively).

``scripts/graph_audit.py`` runs every pass and gates CI via
``--assert-clean``; see the README "Static guarantees" section for the
rule table and the waiver protocol.
"""
