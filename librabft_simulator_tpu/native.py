"""ctypes bindings for the native C++ engine (native/engine.cpp).

Builds ``libbft_engine.so`` on demand with g++ (cached next to the source) and
exposes :func:`run` returning the same observables as the oracle/JAX paths —
parity-checked in tests/test_native.py.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

from .core.types import SimParams

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_SRC = os.path.join(_NATIVE_DIR, "engine.cpp")
_LIB = os.path.join(_NATIVE_DIR, "libbft_engine.so")

_lib: Optional[ctypes.CDLL] = None


def build(force: bool = False) -> str:
    """Compile the shared library if missing or stale."""
    if (not force and os.path.exists(_LIB)
            and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
        return _LIB
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", _LIB, _SRC],
        check=True,
    )
    return _LIB


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build())
        lib.bft_run.restype = ctypes.c_int
        lib.bft_run.argtypes = (
            [ctypes.c_int] * 13
            + [ctypes.c_uint32, ctypes.c_uint32, ctypes.c_longlong]
            + [
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),   # delay
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),   # dur
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),   # weights
                np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),   # eq
                np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),   # silent
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),   # global
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),   # node
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),   # log
            ]
        )
        _lib = lib
    return _lib


class NativeResult:
    def __init__(self, p: SimParams, halted, glob, node, log):
        self.p = p
        self.halted = bool(halted)
        (self.n_events, self.clock, self.stamp_ctr, self.n_msgs_sent,
         self.n_msgs_dropped, self.n_queue_full) = (int(x) for x in glob)
        self.node = node.reshape(p.n_nodes, 8)
        self.log = log.reshape(p.n_nodes, p.commit_log, 3)

    def commit_count(self, a):
        return int(self.node[a, 0])

    def last_depth(self, a):
        return int(self.node[a, 1])

    def last_tag(self, a):
        return int(self.node[a, 2])

    def current_round(self, a):
        return int(self.node[a, 3])

    def hqc_round(self, a):
        return int(self.node[a, 4])

    def hcr(self, a):
        return int(self.node[a, 5])

    def sync_jumps(self, a):
        return int(self.node[a, 6])

    def skipped_commits(self, a):
        return int(self.node[a, 7])

    def committed_chain(self, a):
        cc = self.commit_count(a)
        H = self.p.commit_log
        out = []
        for i in range(max(cc - H, 0), cc):
            pos = i % H
            out.append((int(self.log[a, pos, 1]), int(self.log[a, pos, 2])))
        return out


def run(p: SimParams, seed: int, weights=None, byz_equivocate=None,
        byz_silent=None, max_events: int = 10_000_000) -> NativeResult:
    lib = _load()
    n = p.n_nodes
    delay = np.ascontiguousarray(p.delay_table(), np.int32)
    dur = np.ascontiguousarray(p.duration_table(), np.int32)
    w = np.ascontiguousarray(
        weights if weights is not None else np.ones(n), np.int32)
    eq = np.ascontiguousarray(
        byz_equivocate if byz_equivocate is not None else np.zeros(n), np.uint8)
    silent = np.ascontiguousarray(
        byz_silent if byz_silent is not None else np.zeros(n), np.uint8)
    glob = np.zeros(6, np.int64)
    node = np.zeros(n * 8, np.int64)
    log = np.zeros(n * p.commit_log * 3, np.int64)
    halted = lib.bft_run(
        p.n_nodes, p.window, p.queue_cap, p.chain_k, p.commit_log,
        p.commands_per_epoch, p.target_commit_interval, p.lam_fp,
        p.commit_chain, p.max_clock, p.dur_table_size,
        int(p.shuffle_receivers),
        # epoch_handoff carries the ring depth E (0 = handoff off).
        p.handoff_epochs if p.epoch_handoff else 0,
        ctypes.c_uint32(p.drop_u32), ctypes.c_uint32(seed & 0xFFFFFFFF),
        ctypes.c_longlong(max_events),
        delay, dur, w, eq, silent, glob, node, log,
    )
    return NativeResult(p, halted, glob, node, log)
