"""The attack-program DSL: declarative adversary descriptions that lower
to plane rows.

An :class:`AttackProgram` composes time/event/epoch-windowed behaviors
(:class:`Window`), a healing network partition (:class:`Partition`), and
a per-link extra-delay matrix — everything is validated host-side
(capacities, node ids, delay caps), then :meth:`AttackProgram.lower`
emits exactly the numpy rows the engines' ``adv_*`` state leaves trace,
so a program is DATA: installing one is a device write, admitting one to
the resident fleet is a :class:`~..serve.scenario.ScenarioSpec` with an
``attack`` field, and sweeping millions of them reuses ONE compiled
executable.

Grammar (the NDJSON/request form, ``AttackProgram.from_dict``)::

    {"windows": [{"behavior": "equivocate", "mode": "time",
                  "start": 100, "end": 400, "targets": [0]},
                 {"behavior": "delay_leader", "start": 0, "end": 800,
                  "arg": 25}],
     "partition": {"groups": [[0, 1], [2, 3]], "heal": 300},
     "link_delay": [[0, 5, 5, 5], [1, 0, 1, 1],
                    [1, 1, 0, 1], [1, 1, 1, 0]]}

Semantics in one breath: a window's behavior applies to its ``targets``
(omitted = all nodes) whenever its key — event time (``mode="time"``,
default), the instance's event count (``"events"``; the lane engine
evaluates this one at window granularity), or the handled node's epoch
(``"epoch"``) — lies in ``[start, end)``.  ``equivocate``/``silent``/
``forge_qc`` windows OR onto the static Byzantine masks; ``delay``
windows add ``arg`` time units to messages TO the targeted receivers and
``delay_leader`` to messages addressed to the sender's current-round
leader (overlapping delay windows compose by max).  ``link_delay[s][r]``
adds to every message on that link; partition groups drop every crossing
message sent before ``heal``.
"""

from __future__ import annotations

import dataclasses
import itertools

import numpy as np

from ..core.types import ADV_FIELDS, NEVER, SimParams
from . import plane

#: Windowable behaviors (BEH_NONE is the inert padding row, not a verb).
WINDOW_BEHAVIORS = tuple(b for b in plane.BEHAVIORS if b != "none")


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(msg)


@dataclasses.dataclass(frozen=True)
class Window:
    """One windowed behavior: ``behavior`` applies to ``targets`` while
    the ``mode`` key is in ``[start, end)``.  ``targets=None`` = every
    node (``delay_leader`` ignores targets — the leader is the target);
    ``arg`` is the delay amount for the delay behaviors."""

    behavior: str
    start: int = 0
    end: int = int(NEVER)
    mode: str = "time"
    targets: tuple[int, ...] | None = None
    arg: int = 0

    def __post_init__(self):
        _require(self.behavior in WINDOW_BEHAVIORS,
                 f"unknown behavior {self.behavior!r}; want one of "
                 f"{WINDOW_BEHAVIORS}")
        _require(self.mode in plane.MODES,
                 f"unknown window mode {self.mode!r}; want one of "
                 f"{plane.MODES}")
        _require(0 <= self.start <= self.end <= int(NEVER),
                 f"window bounds must satisfy 0 <= start <= end <= NEVER "
                 f"(got [{self.start}, {self.end}))")
        _require(0 <= self.arg <= plane.DELAY_CAP,
                 f"window arg {self.arg} outside [0, {plane.DELAY_CAP}] "
                 "(adversarial delays are capped so int32 clocks cannot "
                 "wrap)")
        if self.targets is not None:
            object.__setattr__(self, "targets",
                               tuple(int(t) for t in self.targets))

    def validate(self, p: SimParams) -> None:
        for t in self.targets or ():
            _require(0 <= t < p.n_nodes,
                     f"window target {t} outside 0..{p.n_nodes - 1}")

    def _row(self, p: SimParams) -> list[int]:
        if self.targets is None:
            mask = (1 << p.n_nodes) - 1
        else:
            mask = 0
            for t in self.targets:
                mask |= 1 << t
        lo32 = mask & 0xFFFFFFFF
        hi32 = (mask >> 32) & 0xFFFFFFFF
        # numpy int32 rows: re-express the top bit as the two's-complement
        # value the device mask decode reads back bit-exactly.
        as_i32 = lambda u: u - (1 << 32) if u >= (1 << 31) else u  # noqa: E731
        return [plane.MODES.index(self.mode), int(self.start),
                int(min(self.end, int(NEVER))),
                plane.BEHAVIORS.index(self.behavior),
                as_i32(lo32), as_i32(hi32), int(self.arg)]


@dataclasses.dataclass(frozen=True)
class Partition:
    """Group assignment + heal time: messages crossing groups before
    ``heal`` are cut.  Nodes not listed in any group share one implicit
    extra group (they see each other, and nobody else, until heal)."""

    groups: tuple[tuple[int, ...], ...]
    heal: int = int(NEVER)

    def __post_init__(self):
        object.__setattr__(self, "groups",
                           tuple(tuple(int(n) for n in g)
                                 for g in self.groups))
        _require(0 <= self.heal <= int(NEVER),
                 f"heal time {self.heal} outside [0, NEVER]")
        seen: set[int] = set()
        for g in self.groups:
            for n in g:
                _require(n not in seen,
                         f"node {n} appears in two partition groups")
                seen.add(n)

    def validate(self, p: SimParams) -> None:
        for g in self.groups:
            for n in g:
                _require(0 <= n < p.n_nodes,
                         f"partition node {n} outside 0..{p.n_nodes - 1}")

    def assignment(self, p: SimParams) -> np.ndarray:
        group = np.full((p.n_nodes,), len(self.groups), np.int32)
        for gi, g in enumerate(self.groups):
            for n in g:
                group[n] = gi
        return group


@dataclasses.dataclass(frozen=True)
class AttackProgram:
    """A composed attack: windows + optional partition + optional
    per-link delay matrix.  ``lower(p)`` emits the ``adv_*`` plane rows;
    ``install(p, st)`` stamps them onto an engine state."""

    windows: tuple[Window, ...] = ()
    partition: Partition | None = None
    link_delay: tuple[tuple[int, ...], ...] | None = None

    def __post_init__(self):
        object.__setattr__(self, "windows", tuple(self.windows))
        if self.link_delay is not None:
            object.__setattr__(
                self, "link_delay",
                tuple(tuple(int(v) for v in row) for row in self.link_delay))

    def validate(self, p: SimParams) -> None:
        _require(p.adversary,
                 "attack programs need SimParams.adversary=True (the "
                 "adv_* plane leaves are zero-width otherwise)")
        _require(p.n_nodes <= 64,
                 f"attack-schedule target masks cover 64 authors "
                 f"(n_nodes={p.n_nodes})")
        _require(len(self.windows) <= p.adv_windows,
                 f"{len(self.windows)} windows exceed the plane capacity "
                 f"SimParams.adv_windows={p.adv_windows}")
        for w in self.windows:
            w.validate(p)
        if self.partition is not None:
            self.partition.validate(p)
        if self.link_delay is not None:
            _require(
                len(self.link_delay) == p.n_nodes
                and all(len(r) == p.n_nodes for r in self.link_delay),
                f"link_delay must be an {p.n_nodes}x{p.n_nodes} matrix")
            for row in self.link_delay:
                for v in row:
                    _require(0 <= v <= plane.DELAY_CAP,
                             f"link delay {v} outside "
                             f"[0, {plane.DELAY_CAP}]")

    def lower(self, p: SimParams) -> dict:
        """The plane rows (numpy, ``types.adv_*_init`` shapes): validate,
        stamp each window into ``adv_sched``, the matrix into
        ``adv_link``, the partition into ``adv_group``/``adv_heal``.
        Unused window rows stay the inert all-zero row."""
        self.validate(p)
        rows = plane.default_rows(p)
        for i, w in enumerate(self.windows):
            rows["adv_sched"][i] = np.asarray(w._row(p), np.int32)
        if self.link_delay is not None:
            rows["adv_link"][:] = np.asarray(self.link_delay, np.int32)
        if self.partition is not None:
            rows["adv_group"][:] = self.partition.assignment(p)
            rows["adv_heal"][0] = min(self.partition.heal, int(NEVER))
        return rows

    def install(self, p: SimParams, st):
        """Stamp this program onto one (unbatched) engine state — the
        dedicated-run entry point tests and the fuzzer use; batched
        fleets install per-slot rows through serve/scenario.py."""
        import jax.numpy as jnp

        rows = self.lower(p)
        return st.replace(**{k: jnp.asarray(v) for k, v in rows.items()})

    def host_plane(self, p: SimParams) -> plane.HostPlane:
        """The oracle-side decode twin of exactly these lowered rows."""
        rows = self.lower(p)
        return plane.HostPlane(rows["adv_sched"], rows["adv_link"],
                               rows["adv_group"], rows["adv_heal"])

    # -- wire form ---------------------------------------------------------

    def to_dict(self) -> dict:
        out: dict = {"windows": [
            {k: v for k, v in dataclasses.asdict(w).items()
             if v is not None} for w in self.windows]}
        if self.partition is not None:
            out["partition"] = {"groups": [list(g) for g in
                                           self.partition.groups],
                                "heal": self.partition.heal}
        if self.link_delay is not None:
            out["link_delay"] = [list(r) for r in self.link_delay]
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "AttackProgram":
        """Parse the NDJSON/request form; unknown keys fail loud (a
        typo'd field must not silently weaken an attack)."""
        _require(isinstance(d, dict), "attack program must be an object")
        known = {"windows", "partition", "link_delay"}
        extra = set(d) - known
        _require(not extra,
                 f"unknown attack field(s) {sorted(extra)}; known: "
                 f"{sorted(known)}")
        wins = []
        wkeys = {f.name for f in dataclasses.fields(Window)}
        for i, wd in enumerate(d.get("windows", ())):
            _require(isinstance(wd, dict), f"windows[{i}] must be an object")
            wextra = set(wd) - wkeys
            _require(not wextra,
                     f"windows[{i}]: unknown field(s) {sorted(wextra)}; "
                     f"known: {sorted(wkeys)}")
            wd = dict(wd)
            if wd.get("targets") is not None:
                wd["targets"] = tuple(wd["targets"])
            wins.append(Window(**wd))
        part = None
        if d.get("partition") is not None:
            pd = d["partition"]
            _require(isinstance(pd, dict), "partition must be an object")
            pextra = set(pd) - {"groups", "heal"}
            _require(not pextra,
                     f"partition: unknown field(s) {sorted(pextra)}")
            part = Partition(groups=tuple(tuple(g) for g in pd["groups"]),
                             **({"heal": pd["heal"]} if "heal" in pd else {}))
        link = d.get("link_delay")
        return cls(windows=tuple(wins), partition=part,
                   link_delay=(tuple(tuple(r) for r in link)
                               if link is not None else None))


# ---------------------------------------------------------------------------
# Sweep front-end: parameter grids + seedable random programs.
# ---------------------------------------------------------------------------


def sweep(p: SimParams, *, behaviors=("equivocate", "silent"),
          starts=(0,), durations=(int(NEVER),), targets=((0,),),
          modes=("time",), args=(0,), partitions=(None,),
          link_delays=(None,)):
    """The grid front-end: the cartesian product of single-window attack
    parameters (x partition x link matrix), each yielded as a VALIDATED
    :class:`AttackProgram` — feed them to ``serve`` as requests or to a
    batched init via serve/scenario.py.  Lazily generated, so a
    million-point grid costs nothing until consumed."""
    for beh, s, dur, tgt, mode, arg, part, link in itertools.product(
            behaviors, starts, durations, targets, modes, args,
            partitions, link_delays):
        prog = AttackProgram(
            windows=(Window(behavior=beh, start=s,
                            end=min(s + dur, int(NEVER)), mode=mode,
                            targets=tuple(tgt) if tgt is not None else None,
                            arg=arg),),
            partition=part, link_delay=link)
        prog.validate(p)
        yield prog


def sample_program(p: SimParams, rng, max_windows: int | None = None,
                   f_max: int | None = None, horizon: int = 1000,
                   p_partition: float = 0.3,
                   p_link: float = 0.4) -> AttackProgram:
    """One seedable random attack program (the ``FUZZ_ADVERSARY``
    generator): 1..max_windows random windows whose Byzantine behaviors
    target at most ``f_max`` distinct nodes (so the safety invariant
    stays checkable against the honest remainder), plus an optional
    random partition-with-heal and per-link matrix."""
    n = p.n_nodes
    if f_max is None:
        f_max = max((n - 1) // 3, 0)
    if max_windows is None:
        max_windows = p.adv_windows
    byz_pool = rng.sample(range(n), f_max) if f_max else []
    wins = []
    for _ in range(rng.randrange(1, max_windows + 1)):
        beh = rng.choice(WINDOW_BEHAVIORS)
        mode = rng.choice(["time", "time", "events", "epoch"])
        if mode == "time":
            lo = rng.randrange(0, horizon)
            hi = min(lo + rng.randrange(1, horizon), int(NEVER))
        elif mode == "events":
            lo = rng.randrange(0, 400)
            hi = lo + rng.randrange(1, 800)
        else:
            lo, hi = 0, rng.randrange(1, 3)
        if beh in ("equivocate", "silent", "forge_qc"):
            if not byz_pool:
                continue
            tgt = tuple(rng.sample(byz_pool,
                                   rng.randrange(1, len(byz_pool) + 1)))
            arg = 0
        elif beh == "delay":
            tgt = tuple(rng.sample(range(n), rng.randrange(1, n + 1)))
            arg = rng.randrange(1, 60)
        else:  # delay_leader
            tgt = None
            arg = rng.randrange(1, 60)
        wins.append(Window(behavior=beh, start=lo, end=hi, mode=mode,
                           targets=tgt, arg=arg))
    part = None
    if rng.random() < p_partition and n >= 2:
        cutpoint = rng.randrange(1, n)
        ids = list(range(n))
        rng.shuffle(ids)
        part = Partition(
            groups=(tuple(ids[:cutpoint]), tuple(ids[cutpoint:])),
            heal=rng.choice([0, horizon // 4, horizon // 2, int(NEVER)]))
    link = None
    if rng.random() < p_link:
        link = tuple(tuple(0 if i == j else rng.randrange(0, 20)
                           for j in range(n)) for i in range(n))
    prog = AttackProgram(windows=tuple(wins), partition=part,
                         link_delay=link)
    prog.validate(p)
    return prog


def byz_targets(program: AttackProgram) -> set[int]:
    """Every node a Byzantine-behavior window (equivocate/silent/
    forge_qc) can activate — the complement is the honest mask safety
    checks run against."""
    out: set[int] = set()
    for w in program.windows:
        if w.behavior in ("equivocate", "silent", "forge_qc"):
            if w.targets is None:
                return set(range(64))
            out |= set(w.targets)
    return out
