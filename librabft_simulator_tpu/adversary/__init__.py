"""Adversary engine: vectorized attack schedules + per-link network planes.

``plane`` holds the traced tensor schema (the ``[W, ADV_FIELDS]``
attack-schedule plane, the ``[n, n]`` link-delay matrix, the partition
row) and the in-graph decode forms both engines share; ``dsl`` is the
host-side attack-program language that validates and lowers to plane
rows.  See README "Adversary engine".
"""

from . import dsl, plane  # noqa: F401
