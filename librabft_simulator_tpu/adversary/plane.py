"""The adversary plane: traced per-slot attack state + its in-graph decode.

Schema (all int32, the R2 discipline; every array rides in
``SimState``/``PSimState`` as per-slot traced DATA, so one compiled
executable serves millions of distinct attack scenarios — the same
move the scenario plane made for delay/commit knobs):

* ``adv_sched`` — ``[W, ADV_FIELDS]`` attack-schedule plane, one row per
  window: ``(mode, lo, hi, behavior, target_lo, target_hi, arg)``.  A
  window is ACTIVE when its mode's key (event time, instance event
  count, or the handled node's epoch) lies in ``[lo, hi)``; its behavior
  then applies to the nodes whose bit is set in the 64-bit
  ``(target_lo, target_hi)`` author mask.  The all-zero row is inert
  (``hi = 0`` never admits a key >= 0), so a zero plane is the off
  schedule by construction.
* ``adv_link`` — ``[n, n]`` per-link extra-delay matrix: message latency
  on link ``(sender, receiver)`` gains ``clip(adv_link[s, r], 0, CAP)``
  on top of the drawn table delay.  Zero = the uniform network.
* ``adv_group`` / ``adv_heal`` — the partition schedule: a message sent
  at time ``t < adv_heal[0]`` between nodes in DIFFERENT groups is cut
  (dropped, counted in ``n_msgs_dropped``); from ``heal`` on, the
  network is whole again.  All-equal groups or ``heal = 0`` = no
  partition.

Decode discipline: one-hot/select/elementwise forms only — no scalar
scatters (the R1 miscompile class), nothing written back (the plane is
READ-ONLY per-slot config; the graph audit's R6 adversary arm pins the
pass-through).  Every decode is replayed exactly by the oracle through
:class:`HostPlane`, so windowed attacks stay inside the bit-parity
contract.

Lane-engine lookahead: per-link extra delays only ADD latency, so the
minimum live-link extra (:func:`link_lookahead`) soundly TIGHTENS the
Chandy–Misra horizon from the global ``t_min + d_min`` bound to
``t_min + d_min + min_link`` — a raw-speed win on delay-skewed matrices
(wider windows, fewer dispatches).  Partitions only REMOVE messages and
window-scoped delays only add, so neither can break the bound.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.types import ADV_FIELDS, NEVER, SimParams

I32 = jnp.int32

# Field offsets of one [ADV_FIELDS] schedule row.
F_MODE, F_LO, F_HI, F_BEH, F_TGT_LO, F_TGT_HI, F_ARG = range(ADV_FIELDS)

# Window bound modes: what key the [lo, hi) interval is tested against.
MODE_TIME = 0    # event (global) time — partitions-that-heal, timed attacks
MODE_EVENTS = 1  # instance event count — "after N events" attacks (the
                 # lane engine evaluates this at WINDOW granularity: all
                 # events of one horizon window see the window-start count)
MODE_EPOCH = 2   # the handled node's pre-event epoch — epoch-boundary
                 # attacks (arm exactly while a node is in epoch e)
MODES = ("time", "events", "epoch")

# Behavior selectors.  1..3 generalize the static byz_* masks into
# windowed activations (OR-composed onto the static masks per event);
# 4..5 are the network behaviors (extra delay on messages TO the targeted
# receivers / to the sender's current-round leader, amount = arg,
# overlapping windows compose by MAX).
BEH_NONE = 0
BEH_EQUIVOCATE = 1
BEH_SILENT = 2
BEH_FORGE_QC = 3
BEH_DELAY = 4
BEH_DELAY_LEADER = 5
BEHAVIORS = ("none", "equivocate", "silent", "forge_qc", "delay",
             "delay_leader")

#: Hard cap on any adversarial delay contribution (per-link entry or
#: window arg), clamped in-graph AND validated by the DSL: arrival times
#: are int32 and the engines add delays without saturation, so adversary
#: data must never be able to wrap the clock.
DELAY_CAP = 1 << 20


# ---------------------------------------------------------------------------
# Device decode (traced; shared by both engines).
# ---------------------------------------------------------------------------


def active_windows(sched, t, ev, epoch):
    """``[W]`` bool: each window's ``[lo, hi)`` test against its mode's
    key — ``t`` (event time), ``ev`` (instance event count), or ``epoch``
    (the handled node's pre-event epoch), all scalar int32."""
    mode = sched[:, F_MODE]
    key = jnp.where(mode == MODE_TIME, jnp.asarray(t, I32),
                    jnp.where(mode == MODE_EVENTS, jnp.asarray(ev, I32),
                              jnp.asarray(epoch, I32)))
    return (key >= sched[:, F_LO]) & (key < sched[:, F_HI])


def _target_hit(sched, node):
    """Bit of ``node`` (any shape) in each window's 64-bit author mask:
    bool ``[W, *node.shape]``.  ``(word >> bit) & 1`` reads the bit
    correctly under arithmetic int32 shifts (low bits are fill-invariant),
    so an all-ones mask stores as the int32 ``-1``."""
    node = jnp.asarray(node, I32)
    ext = (sched.shape[0],) + (1,) * node.ndim
    lo = sched[:, F_TGT_LO].reshape(ext)
    hi = sched[:, F_TGT_HI].reshape(ext)
    nd = node[None]
    word = jnp.where(nd < 32, lo, hi)
    bit = jnp.clip(jnp.where(nd < 32, nd, nd - 32), 0, 31)
    return ((word >> bit) & 1) != 0


def behavior_hit(sched, active, beh, node):
    """Any active window with behavior ``beh`` targeting ``node``: bool
    of ``node``'s shape (scalar for the serial engine's handled node,
    ``[A]`` for the lane compaction)."""
    node = jnp.asarray(node, I32)
    on = active & (sched[:, F_BEH] == beh)
    ext = on.reshape((on.shape[0],) + (1,) * node.ndim)
    return jnp.any(ext & _target_hit(sched, node), axis=0)


def node_masks(sched, active, node):
    """(equivocate, silent, forge_qc) windowed activations for ``node`` —
    the decode the engines OR onto the static ``byz_*`` masks."""
    return (behavior_hit(sched, active, BEH_EQUIVOCATE, node),
            behavior_hit(sched, active, BEH_SILENT, node),
            behavior_hit(sched, active, BEH_FORGE_QC, node))


def delay_extra(sched, active, recvs, leader):
    """Window-scoped extra delay per candidate receiver: int32 of
    ``recvs``'s shape — the MAX over active delay windows of ``arg``,
    where a window applies to receiver ``r`` if ``BEH_DELAY`` targets it
    or ``BEH_DELAY_LEADER`` and ``r == leader`` (``leader`` must
    broadcast against ``recvs``)."""
    recvs = jnp.asarray(recvs, I32)
    ext = lambda v: v.reshape((sched.shape[0],) + (1,) * recvs.ndim)  # noqa: E731
    arg = jnp.clip(sched[:, F_ARG], 0, DELAY_CAP)
    beh = sched[:, F_BEH]
    applies = ((ext(active & (beh == BEH_DELAY)) & _target_hit(sched, recvs))
               | (ext(active & (beh == BEH_DELAY_LEADER))
                  & (recvs[None] == jnp.asarray(leader, I32))))
    return jnp.max(jnp.where(applies, ext(arg), 0), axis=0)


def link_lookahead(link, n: int):
    """Minimum off-diagonal per-link extra delay (scalar int32, >= 0):
    the amount by which EVERY message's latency exceeds the delay-table
    bound, hence the sound tightening the lane engine adds to its
    Chandy–Misra horizon.  (Partition cuts only remove messages and
    window delays only add, so neither loosens this bound; n == 1 has no
    links and any horizon is vacuously sound.)"""
    off = ~jnp.eye(n, dtype=bool)
    return jnp.min(jnp.where(off, jnp.clip(link, 0, DELAY_CAP), DELAY_CAP))


# ---------------------------------------------------------------------------
# Host mirror (oracle + minidump reporter).
# ---------------------------------------------------------------------------


class HostPlane:
    """Plain-Python twin of the device decode, built from the lowered
    numpy rows — the oracle (oracle/sim.py) replays every adversary
    decision through this class, so any engine/decode divergence shows as
    a parity failure, and ``describe()`` is the decoded-program record
    fuzz minidumps carry."""

    def __init__(self, sched, link, group, heal):
        self.sched = [[int(v) for v in row] for row in np.asarray(sched)]
        self.link = np.asarray(link, np.int64)
        self.group = [int(g) for g in np.asarray(group)]
        self.heal = int(np.asarray(heal).reshape(-1)[0]) if np.asarray(
            heal).size else 0

    def _active(self, t: int, ev: int, epoch: int) -> list[bool]:
        out = []
        for row in self.sched:
            key = (t if row[F_MODE] == MODE_TIME
                   else ev if row[F_MODE] == MODE_EVENTS else epoch)
            out.append(row[F_LO] <= key < row[F_HI])
        return out

    @staticmethod
    def _targets(row, node: int) -> bool:
        word = row[F_TGT_LO] if node < 32 else row[F_TGT_HI]
        return ((word >> min(max(node if node < 32 else node - 32, 0), 31))
                & 1) != 0

    def node_masks(self, t, ev, epoch, node) -> tuple[bool, bool, bool]:
        act = self._active(t, ev, epoch)
        out = []
        for beh in (BEH_EQUIVOCATE, BEH_SILENT, BEH_FORGE_QC):
            out.append(any(
                a and row[F_BEH] == beh and self._targets(row, node)
                for a, row in zip(act, self.sched)))
        return tuple(out)

    def delay_extra(self, t, ev, epoch, recv, leader) -> int:
        act = self._active(t, ev, epoch)
        best = 0
        for a, row in zip(act, self.sched):
            if not a:
                continue
            hit = ((row[F_BEH] == BEH_DELAY and self._targets(row, recv))
                   or (row[F_BEH] == BEH_DELAY_LEADER and recv == leader))
            if hit:
                best = max(best, min(max(row[F_ARG], 0), DELAY_CAP))
        return best

    def link_extra(self, sender: int, recv: int) -> int:
        return int(min(max(self.link[sender, recv], 0), DELAY_CAP))

    def cut(self, sender: int, recv: int, t: int) -> bool:
        return self.group[sender] != self.group[recv] and t < self.heal

    def describe(self) -> dict:
        """Decoded program for minidumps/results: named windows + the
        network rows (the counterexample reporter contract)."""
        windows = []
        for row in self.sched:
            if row[F_HI] <= row[F_LO] or row[F_BEH] == BEH_NONE:
                continue
            tgt = (row[F_TGT_LO] & 0xFFFFFFFF) \
                | ((row[F_TGT_HI] & 0xFFFFFFFF) << 32)
            windows.append(dict(
                behavior=BEHAVIORS[row[F_BEH]], mode=MODES[row[F_MODE]],
                lo=row[F_LO], hi=row[F_HI],
                targets=[i for i in range(64) if (tgt >> i) & 1],
                arg=row[F_ARG]))
        return dict(
            windows=windows,
            link=self.link.tolist() if self.link.size else [],
            groups=self.group,
            heal=self.heal if self.heal < int(NEVER) else "never")


def default_rows(p: SimParams) -> dict:
    """The inert (all-quiet) plane rows for ``p`` — numpy, the same
    zero-filled values ``types.adv_*_init`` traces, for host-side row
    assembly (serve admission, DSL lowering base)."""
    w = p.adv_windows if p.adversary else 0
    n = p.n_nodes if p.adversary else 0
    return dict(
        adv_sched=np.zeros((w, ADV_FIELDS), np.int32),
        adv_link=np.zeros((n, n), np.int32),
        adv_group=np.zeros((n,), np.int32),
        adv_heal=np.zeros((1 if p.adversary else 0,), np.int32),
    )
