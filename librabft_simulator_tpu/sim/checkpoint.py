"""Checkpoint / resume: the whole fleet is one pytree.

The reference persists per-node state through ``Storage::store/load``
(/root/reference/bft-lib/src/smr_context.rs) and node.rs save_node/load_node.
Here the *entire simulation* (all instances, queues, rng counters) is a single
pytree of arrays, so checkpointing is one ``jax.device_get`` away and a
restored run continues bit-identically (everything that matters — clocks,
stamps, seeds — is in the state).

Two backends: numpy ``.npz`` (zero deps, default) and orbax (when installed).
"""

from __future__ import annotations

import os

import jax
import numpy as np

from ..core.types import SimParams, SimState


def _key(path) -> str:
    """Stable string key for a tree path — the single source of the
    save/load key-derivation rule."""
    return "/".join(
        getattr(p, "name", None) or str(getattr(p, "idx", p)) for p in path)


def _flatten_with_paths(state):
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    out = {}
    for path, leaf in flat:
        out[_key(path)] = np.asarray(jax.device_get(leaf))
    return out, treedef


def _ho_default(field: str, leaf) -> np.ndarray:
    """Fresh-init value of a cross-epoch handoff leaf (soft cache state):
    zero packs, -1 ('no epoch held') slots.  Single source for both the
    missing-key and pre-ring shape-mismatch restore paths."""
    fill = -1 if field == "ho_epoch" else 0
    return np.full(leaf.shape, fill, leaf.dtype)


def _sc_default(p: SimParams, field: str, leaf) -> np.ndarray:
    """Knob-default scenario-plane rows for a pre-PR-11 (or
    scenario-toggled) checkpoint: NOT soft state — the plane is consensus
    config — but the correct restore for a checkpoint that predates it is
    exactly the scenario the load params themselves describe (the same
    restore rule the PR 4 watchdog used, except the default is the
    params' values, not zeros).  A zero-width target (scenario off)
    restores empty regardless of what was saved."""
    if leaf.shape[-1] == 0:
        return np.zeros(leaf.shape, leaf.dtype)
    row = (np.asarray(p.delay_table(), leaf.dtype) if field == "sc_delay"
           else np.asarray([p.commit_chain], leaf.dtype))
    return np.broadcast_to(row, leaf.shape).copy()


#: Adversary-plane leaves (round 17): a checkpoint that predates the
#: plane (missing keys), or whose plane was OFF while the load params arm
#: it, restores the INERT program — all-zero rows are the no-attack
#: schedule by construction (adversary/plane.py), which is exactly what
#: those params were simulating.  The reverse direction (an armed plane
#: loaded onto off/resized params) REFUSES: the rows are per-slot attack
#: data, not derivable from params — see the shape-mismatch branch.
_ADV_FIELDS = ("adv_sched", "adv_link", "adv_group", "adv_heal")


def save(path: str, state: SimState) -> None:
    arrays, _ = _flatten_with_paths(state)
    np.savez_compressed(path, **arrays)


def load(path: str, p: SimParams, like: SimState | None = None) -> SimState:
    """Restore a SimState.  ``like`` provides the tree structure (defaults to a
    freshly initialised state of matching shape)."""
    from . import simulator as S

    data = np.load(path)
    if like is None:
        # Structure only; leaf values are replaced below.
        sample = data["clock"]
        if sample.ndim > 0:  # batched checkpoint
            like = S.init_batch(p, np.zeros(sample.shape[0], np.uint32))
        else:
            like = S.init_state(p, 0)
    leaves = []
    flat = [(_key(path), leaf)
            for path, leaf in jax.tree_util.tree_flatten_with_path(like)[0]]

    # A trace_cap change resets the ring arrays below; the count must reset
    # WITH them or the decoder reads `count` fabricated entries from an
    # all-zero ring and post-resume writes start mid-ring.  (Its own shape
    # never changes, so this must be decided up front.)
    ring_reset = any(
        k.split("/")[-1] == "trace_node" and k in data
        and data[k].shape != lf.shape for k, lf in flat)

    for key, leaf in flat:
        field = key.split("/")[-1]
        if field == "trace_count" and ring_reset:
            leaves.append(np.zeros(leaf.shape, leaf.dtype))
            continue
        if key not in data:
            # Forward compatibility for KNOWN later-added fields only
            # (round 4's cross-epoch handoff state; round 5's parallel-
            # engine trace ring): synthesize the fresh-init default
            # explicitly — ``like`` may be mid-run, and copying its leaf
            # would inject stale soft state into the restore.  Anything
            # else missing is a corrupt/foreign checkpoint.
            if field in ("ho_pay", "ho_epoch"):
                leaves.append(_ho_default(field, leaf))
                continue
            if field in ("trace_node", "trace_round", "trace_time",
                         "trace_count"):
                leaves.append(np.zeros(leaf.shape, leaf.dtype))
                continue
            if field in ("metrics", "flight"):
                # Round 7's telemetry plane + flight recorder: diagnostic
                # soft state, restored empty from older checkpoints.
                leaves.append(np.zeros(leaf.shape, leaf.dtype))
                continue
            if field == "wd":
                # Round 9's consensus-watchdog plane: detector soft state,
                # restored empty (counters restart) from pre-stream
                # checkpoints — same synthesis as the telemetry leaves.
                leaves.append(np.zeros(leaf.shape, leaf.dtype))
                continue
            if field in ("sc_delay", "sc_commit"):
                # Round 14's per-slot scenario plane: a pre-PR-11
                # checkpoint restores with knob-DEFAULT rows derived from
                # the load params (the scenario those params describe),
                # so the resumed run is bit-identical to what the static
                # engine would have done — see tests/test_checkpoint.py.
                leaves.append(_sc_default(p, field, leaf))
                continue
            if field in _ADV_FIELDS:
                # Round 17's adversary plane: pre-plane checkpoints
                # restore the inert (all-zero) program — bit-identical
                # to what the adversary-free engine would have done.
                leaves.append(np.zeros(leaf.shape, leaf.dtype))
                continue
            raise KeyError(f"checkpoint missing leaf {key}")
        arr = data[key]
        if arr.shape != leaf.shape:
            if field in ("ho_pay", "ho_epoch"):
                # Pre-ring checkpoints hold a single [N, F] pack per node;
                # the handoff cache is soft state, so restore it empty
                # rather than failing the whole load.
                leaves.append(_ho_default(field, leaf))
                continue
            if field in ("trace_node", "trace_round", "trace_time"):
                # trace_cap changed between save and resume: the ring is
                # diagnostic soft state — restart it empty.
                leaves.append(np.zeros(leaf.shape, leaf.dtype))
                continue
            if field in ("metrics", "flight", "wd"):
                # telemetry/flight_cap/watchdog changed between save and
                # resume: observability soft state — restart it empty.
                leaves.append(np.zeros(leaf.shape, leaf.dtype))
                continue
            if field in ("sc_delay", "sc_commit"):
                # SimParams.scenario toggled between save and resume:
                # restore the knob-default rows of the LOAD params.  A
                # scenario-on checkpoint loaded scenario-off keeps only
                # what the static knobs express — the loud shape change
                # is the operator's cue that per-slot scenarios were
                # dropped.
                leaves.append(_sc_default(p, field, leaf))
                continue
            if field in _ADV_FIELDS and arr.size == 0:
                # Adversary toggled ON between save and resume (the
                # saved leaf is zero-width): arm the inert program —
                # exactly what the adversary-free run was simulating.
                # Any OTHER mismatch (adversary-on -> off, an
                # adv_windows resize) falls through to the ValueError:
                # the plane rows are per-slot attack DATA, not derivable
                # from params, and zero-filling them would silently
                # report an attacked run as attack-free.
                leaves.append(np.zeros(leaf.shape, leaf.dtype))
                continue
            raise ValueError(f"{key}: shape {arr.shape} != {leaf.shape}")
        leaves.append(arr.astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(like), leaves)


def load_sharded(path: str, p: SimParams, mesh, engine=None, like=None):
    """Restore a batched checkpoint onto a device mesh; returns
    ``(state, n_valid)``.

    Placement is shard-by-shard (``jax.make_array_from_callback``): each
    device is fed only its own batch slice, so no device ever materializes
    a full-leaf buffer — a fleet checkpoint restores onto a pod without a
    single-chip-sized staging copy.  When the mesh's device count doesn't
    divide the checkpoint's batch B, the fleet is padded to the next
    multiple with pre-halted instances instead of crashing
    (parallel/sharded.pad_to_multiple: padding is masked out of telemetry
    and DataWriter by construction); ``n_valid`` is the original B — slice
    ``[:n_valid]`` after fetching to drop the padding.

    ``engine`` picks the state flavor (sim.simulator default, or
    sim.parallel_sim for PSimState checkpoints); ``like`` overrides the
    tree template exactly as in :func:`load`."""
    from ..parallel import mesh as mesh_ops
    from ..parallel import sharded as sharded_ops
    from . import simulator as S

    eng = engine if engine is not None else S
    if like is None:
        sample = np.load(path)["clock"]
        if sample.ndim == 0:
            raise ValueError(
                "load_sharded needs a batched checkpoint (this one holds a "
                "single instance); use load() for single-instance restores")
        # Abstract template only: load() reads shapes/dtypes/structure
        # from ``like``, so eval_shape avoids actually initialising (and
        # device-allocating) a fleet-sized state just to describe one.
        like = jax.eval_shape(
            lambda: eng.init_batch(p, np.zeros(sample.shape[0], np.uint32)))
    # load() returns an all-numpy tree, so pad_to_multiple pads ON HOST
    # (a device concat would stage full leaves on the default device,
    # exactly what the shard-by-shard placement below exists to avoid).
    host = load(path, p, like=like)
    host, n_valid = sharded_ops.pad_to_multiple(p, host, mesh.size,
                                                engine=eng)
    sh = mesh_ops.batch_sharding(mesh)

    def put(x):
        return jax.make_array_from_callback(
            x.shape, sh, lambda idx, x=x: x[idx])

    return jax.tree.map(put, host), n_valid


def save_orbax(path: str, state: SimState) -> None:
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), state)
    ckptr.wait_until_finished()


def load_orbax(path: str, like: SimState) -> SimState:
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.abspath(path), like)
