"""Lane-compacted conservative-window parallel engine.

The serial engine (:mod:`.simulator`) replays the reference's event loop one
event at a time — the parity reference.  This engine is the throughput mode:
classic conservative parallel discrete-event simulation (PDES) with network
lookahead (match: the capability bar of
/root/reference/bft-lib/src/simulator.rs:26-160, where one BinaryHeap serves
64-node fleets), re-expressed for TPU.

Correctness argument (standard Chandy-Misra lookahead): nodes influence each
other ONLY via messages, and every message has latency >= ``d_min`` (the
minimum of the delay table, floored to 1).  With ``t_min`` the earliest
pending event anywhere, every in-window send happens at some t >= t_min and
arrives at >= t_min + d_min — so events strictly below the global horizon

    hz = t_min + d_min

cannot be affected by any in-window work (one hop arrives at >= hz; a
two-hop reply at >= t_min + 2*d_min; and so on).  Each node may therefore
drain ALL its pending events below ``hz`` in local (time, kind desc, stamp)
order without hearing from anyone.  The horizon must be global: a per-node
min-over-*others* horizon is unsound under draining, because a node's own
send at t can spawn another node's event at t + d_min whose reply lands back
at t + 2*d_min — inside the wider per-node window (caught bit-exactly by
tests/test_parallel_sim.py's composition-invariance tests).

TPU shape — the two ideas that make this fast rather than merely correct:

* **Lane compaction.**  A vmap over all N nodes pays N× the per-node update
  cost per window even when only a couple of nodes have work (masked lanes
  still compute).  Instead the window's work is compacted onto ``A =
  lanes_of(p)`` *lanes*: the A earliest qualifying nodes (stable argsort of
  earliest-event times) are gathered, stepped densely, and scattered back.
  Cost per window is A× update_node, not N×, and A is sized to typical
  window occupancy, not fleet width.
* **Multi-event draining.**  Each lane drains up to ``K = drain_of(p)`` of
  its node's events per window under an inner ``lax.scan`` — the same-node
  chain is inherently sequential (event i+1 sees event i's state), but K
  same-node events now cost one window's fixed overhead (selection,
  compaction, routing) instead of K windows'.  Burst arrivals (a round's
  broadcast landing on one node at equal timestamps) drain in one window.

Per-receiver inboxes ``[N, IC]`` replace the serial engine's shared queue;
candidate messages are ranked per receiver with O(K·A·n) column cumsums and
scattered into free slots (overflow counted, never silent).

Determinism: rng/stamps are node-local counters (stamp stream ``ctr*N+n``),
so trajectories are bit-reproducible for a seed (CPU == TPU) and — absent
inbox overflow — *independent of window composition*: lookahead ``d_min``,
lane count, and drain depth only decide how much work lands in each step,
never the per-node event order.  ``tests/test_parallel_sim.py`` asserts this
bit-exactly across d_min/lanes/drain variants.  Trajectories are NOT the
serial engine's (different stamp interleaving): the serial engine remains
the oracle-parity reference, and the same test file checks this engine
statistically against it (commit/event density per unit virtual time) plus
Byzantine safety and overflow accounting.  (Under overflow the window shape
changes which concurrent sends compete for free slots, so the discarded set
— and hence the trajectory — may differ.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..adversary import plane as aplane
from ..core import config, data_sync, node as node_ops, packing, \
    store as store_ops
from .simulator import _forged_qc_payload
from ..core.types import (
    adv_group_init,
    adv_heal_init,
    adv_link_init,
    adv_sched_init,
    KIND_NOTIFY,
    KIND_REQUEST,
    KIND_RESPONSE,
    KIND_TIMER,
    NEVER,
    Context,
    NodeExtra,
    Pacemaker,
    SimParams,
    Store,
    TracedParams,
    pack_payload,
    payload_width,
    sat_add,
    sc_commit_init,
    sc_delay_init,
    unpack_payload,
)
from ..telemetry import ledger as tledger
from ..telemetry import plane as tplane
from ..telemetry import stream as tstream
from ..telemetry.profiling import scope
from ..utils import aot
from ..utils import hashing as H
from ..utils import xops
from ..utils.xops import wset
from ..utils.quantile import TABLE_BITS

I32 = jnp.int32
EQUIV_SALT = 1 << 20

# Debug hook: set to a host callable before tracing to receive
# (act, t, kind, node, is_timer, ctr, t_ev, hz, qualify) per drain
# iteration — lane arrays first, then the window-level selection inputs
# (unbatched runs only; one ordered callback site so host-side window/
# iteration alignment is exact).  None (default) compiles to nothing.
_debug_tap = None


def _i32(x):
    return jnp.asarray(x, I32)


@struct.dataclass
class PSimState:
    """One instance under the parallel engine."""

    store: Store          # [N, ...]
    pm: Pacemaker         # [N]
    node: NodeExtra       # [N]
    ctx: Context          # [N, ...]
    # Per-receiver inboxes.
    byz_forge_qc: jnp.ndarray
    max_clock: jnp.ndarray   # i32 horizon (dynamic; see SimParams.structural)
    drop_u32: jnp.ndarray    # u32 drop threshold (dynamic)
    ho_pay: jnp.ndarray      # [N, F] cross-epoch handoff packs ([N, 0] if off)
    ho_epoch: jnp.ndarray    # [N]; -1 = none
    in_valid: jnp.ndarray    # [N, IC] bool
    in_time: jnp.ndarray     # [N, IC]
    in_kind: jnp.ndarray     # [N, IC]
    in_stamp: jnp.ndarray    # [N, IC]
    in_sender: jnp.ndarray   # [N, IC]
    in_pay: jnp.ndarray      # [N, IC, F] packed payloads
    timer_time: jnp.ndarray  # [N]
    startup: jnp.ndarray     # [N]
    weights: jnp.ndarray     # [N]
    byz_equivocate: jnp.ndarray
    byz_silent: jnp.ndarray
    clock: jnp.ndarray
    node_ctr: jnp.ndarray    # [N] per-node stamp/rng counters
    halted: jnp.ndarray
    seed: jnp.ndarray
    n_events: jnp.ndarray
    n_msgs_sent: jnp.ndarray
    n_msgs_dropped: jnp.ndarray
    n_inbox_full: jnp.ndarray
    # Round-switch trace ring (same layout as SimState so
    # analysis/data_writer.py decodes both engines; entries are appended in
    # window-schedule order — sort by time for a chronological view).
    trace_node: jnp.ndarray
    trace_round: jnp.ndarray
    trace_time: jnp.ndarray
    trace_count: jnp.ndarray
    # Telemetry plane + flight-recorder ring (telemetry/plane.py); both
    # zero-width when SimParams.telemetry is off.
    metrics: jnp.ndarray
    flight: jnp.ndarray
    # Consensus watchdog plane (telemetry/stream.py); zero-width when
    # SimParams.watchdog is off.
    wd: jnp.ndarray
    # Per-slot traced scenario plane (SimParams.scenario; serve/): both
    # zero-width when off, read-only config when on — see SimState.
    sc_delay: jnp.ndarray   # [T] int32 delay table row ([0] when off)
    sc_commit: jnp.ndarray  # [1] int32 commit-chain selector ([0] when off)
    # Adversary plane (SimParams.adversary; adversary/): zero-width when
    # off, read-only per-slot attack config when on — see SimState.
    adv_sched: jnp.ndarray  # [W, ADV_FIELDS] int32 ([0, F] when off)
    adv_link: jnp.ndarray   # [n, n] int32 ([0, 0] when off)
    adv_group: jnp.ndarray  # [n] int32 ([0] when off)
    adv_heal: jnp.ndarray   # [1] int32 ([0] when off)


@struct.dataclass
class PackedPSimState:
    """``PSimState`` with the four per-node sub-states fused into one
    ``[N, S]`` plane (core/packing.py).  Every other field matches
    ``PSimState`` by name, so the step shares one code path."""

    planes: jnp.ndarray      # [N, S] packed (store, pm, node, ctx) rows
    byz_forge_qc: jnp.ndarray
    max_clock: jnp.ndarray
    drop_u32: jnp.ndarray
    ho_pay: jnp.ndarray
    ho_epoch: jnp.ndarray
    in_valid: jnp.ndarray
    in_time: jnp.ndarray
    in_kind: jnp.ndarray
    in_stamp: jnp.ndarray
    in_sender: jnp.ndarray
    in_pay: jnp.ndarray
    timer_time: jnp.ndarray
    startup: jnp.ndarray
    weights: jnp.ndarray
    byz_equivocate: jnp.ndarray
    byz_silent: jnp.ndarray
    clock: jnp.ndarray
    node_ctr: jnp.ndarray
    halted: jnp.ndarray
    seed: jnp.ndarray
    n_events: jnp.ndarray
    n_msgs_sent: jnp.ndarray
    n_msgs_dropped: jnp.ndarray
    n_inbox_full: jnp.ndarray
    trace_node: jnp.ndarray
    trace_round: jnp.ndarray
    trace_time: jnp.ndarray
    trace_count: jnp.ndarray
    metrics: jnp.ndarray
    flight: jnp.ndarray
    wd: jnp.ndarray
    sc_delay: jnp.ndarray
    sc_commit: jnp.ndarray
    adv_sched: jnp.ndarray
    adv_link: jnp.ndarray
    adv_group: jnp.ndarray
    adv_heal: jnp.ndarray


_PSIM_COMMON = packing._common_fields(PSimState)


def pack_pstate(p: SimParams, st: PSimState) -> PackedPSimState:
    """PSimState -> PackedPSimState (leading batch dims supported)."""
    planes = packing.pack_node(p, st.store, st.pm, st.node, st.ctx)
    return PackedPSimState(
        planes=planes, **{f: getattr(st, f) for f in _PSIM_COMMON})


def unpack_pstate(p: SimParams, pst: PackedPSimState) -> PSimState:
    """Exact inverse of :func:`pack_pstate`."""
    store, pm, nx, ctx = packing.unpack_node(p, pst.planes)
    return PSimState(
        store=store, pm=pm, node=nx, ctx=ctx,
        **{f: getattr(pst, f) for f in _PSIM_COMMON})


def d_min_of(p: SimParams) -> int:
    """Network lookahead: minimum message latency (>= 1).

    With the scenario plane on, slots carry their OWN delay tables (the
    params' table is just the knob default), so the static value here is
    only the conservative ARGUMENT default (1 — sound for any admitted
    table); the step ignores it and derives each slot's true lookahead
    in-graph from its ``sc_delay`` row (same formula), which is what
    keeps per-slot window composition — and hence the whole trajectory,
    inbox layout included — bit-identical to a dedicated static run."""
    if p.scenario:
        return 1
    return max(int(np.min(p.delay_table())), 1)


def inbox_cap(p: SimParams) -> int:
    """Per-receiver inbox slots: ``SimParams.inbox_cap`` if set, else 4 per
    peer.  Memory scales O(n) per node vs the serial engine's shared queue,
    which needs O(n^2)-ish capacity to stay lossless (in-flight broadcasts ~
    n*(n-1)*mean_delay/round_duration)."""
    return p.inbox_cap if p.inbox_cap > 0 else max(16, 4 * p.n_nodes)


def lanes_of(p: SimParams) -> int:
    """Active lanes per window: nodes stepped densely after compaction.
    ``SimParams.active_lanes`` if set, else min(n, max(8, n/4)) — sized to
    typical window occupancy (CPU probe, uniform delays: ~24 events/window
    at n=16, ~120 at n=64; A=16/K=8 beat A=8/K=4 by 1.3x at n=64)."""
    if p.active_lanes > 0:
        return min(p.n_nodes, p.active_lanes)
    return min(p.n_nodes, max(8, p.n_nodes // 4))


def drain_of(p: SimParams) -> int:
    """Events each lane may drain per window (same-node chain, sequential).
    Bigger fleets see deeper same-node bursts (a round's n-1 notifies)."""
    return p.drain_k if p.drain_k > 0 else (4 if p.n_nodes <= 16 else 8)


def init_state(p: SimParams, seed, weights=None, byz_equivocate=None,
               byz_silent=None, byz_forge_qc=None) -> PSimState:
    if p.shuffle_receivers:
        raise NotImplementedError(
            "SimParams.shuffle_receivers is a parity-trio semantic "
            "(serial/oracle/C++); the parallel engine delivers in index "
            "order — use the serial engine for shuffle fuzzing.")
    if p.select_kernel != "xla":
        import warnings

        warnings.warn(
            f"select_kernel={p.select_kernel!r} is ignored by the parallel "
            "engine (no shared event queue to select from)", stacklevel=2)
    n = p.n_nodes
    ic = inbox_cap(p)
    F = payload_width(p)
    seed = jnp.asarray(seed).astype(jnp.uint32)
    delay_table = jnp.asarray(p.delay_table())
    draws = jax.vmap(lambda c: H.rng_u32(seed, c.astype(jnp.uint32)))(jnp.arange(n))
    startup = (delay_table[(draws >> (32 - TABLE_BITS)).astype(I32)] + 1).astype(I32)
    if weights is None:
        weights = jnp.ones((n,), I32)
    if byz_equivocate is None:
        byz_equivocate = jnp.zeros((n,), jnp.bool_)
    if byz_silent is None:
        byz_silent = jnp.zeros((n,), jnp.bool_)
    if byz_forge_qc is None:
        byz_forge_qc = jnp.zeros((n,), jnp.bool_)
    return PSimState(
        store=Store.initial(p, (n,)),
        pm=Pacemaker.initial((n,)),
        node=NodeExtra.initial((n,)),
        ctx=Context.initial(p, (n,)),
        in_valid=jnp.zeros((n, ic), jnp.bool_),
        in_time=jnp.zeros((n, ic), I32),
        in_kind=jnp.zeros((n, ic), I32),
        in_stamp=jnp.zeros((n, ic), I32),
        in_sender=jnp.zeros((n, ic), I32),
        in_pay=jnp.zeros((n, ic, F), I32),
        timer_time=startup,
        startup=startup,
        weights=jnp.asarray(weights, I32),
        byz_equivocate=jnp.asarray(byz_equivocate, jnp.bool_),
        byz_silent=jnp.asarray(byz_silent, jnp.bool_),
        byz_forge_qc=jnp.asarray(byz_forge_qc, jnp.bool_),
        max_clock=_i32(p.max_clock),
        drop_u32=jnp.uint32(p.drop_u32),
        ho_pay=jnp.zeros(
            (n, p.handoff_epochs if p.epoch_handoff else 0, F), I32),
        ho_epoch=jnp.full(
            (n, p.handoff_epochs if p.epoch_handoff else 0), -1, I32),
        clock=_i32(0),
        node_ctr=jnp.ones((n,), I32),
        halted=jnp.bool_(False),
        seed=seed,
        n_events=_i32(0),
        n_msgs_sent=_i32(0),
        n_msgs_dropped=_i32(0),
        n_inbox_full=_i32(0),
        trace_node=jnp.zeros((p.trace_cap,), I32),
        trace_round=jnp.zeros((p.trace_cap,), I32),
        trace_time=jnp.zeros((p.trace_cap,), I32),
        trace_count=_i32(0),
        metrics=tplane.init_plane(p),
        flight=tplane.init_flight(p),
        wd=tstream.init_wd(p),
        sc_delay=sc_delay_init(p),
        sc_commit=sc_commit_init(p),
        adv_sched=adv_sched_init(p),
        adv_link=adv_link_init(p),
        adv_group=adv_group_init(p),
        adv_heal=adv_heal_init(p),
    )


def _earliest(in_valid, in_time, in_kind, in_stamp, timer_time):
    """Per row: earliest pending event by (time, kind desc, stamp).

    Returns (time, kind, slot, is_timer) with leading dim = rows; slot is the
    inbox slot (or -1 for a timer).  Timer wins at equal (time, kind=3):
    timers and messages never share a kind (messages are 0..2)."""
    msg_time = jnp.where(in_valid, in_time, NEVER)
    t_best = jnp.minimum(jnp.min(msg_time, axis=1), timer_time)
    m1 = msg_time == t_best[:, None]
    k_msg = jnp.max(jnp.where(m1, in_kind, -1), axis=1)
    timer_due = timer_time == t_best
    k_best = jnp.maximum(k_msg, jnp.where(timer_due, KIND_TIMER, -1))
    m2 = m1 & (in_kind == k_best[:, None])
    s_best = jnp.min(jnp.where(m2, in_stamp, NEVER), axis=1)
    is_timer = timer_due & (k_best == KIND_TIMER)
    slot = jnp.argmax(m2 & (in_stamp == s_best[:, None]), axis=1).astype(I32)
    slot = jnp.where(is_timer, -1, slot)
    return t_best, k_best, slot, is_timer


def step(p: SimParams, delay_table, dur_table, d_min: int, st: PSimState):
    """One window: compact the A earliest qualifying nodes onto lanes, drain
    up to K events per lane, then route all emitted messages at once."""
    n = p.n_nodes
    ic = inbox_cap(p)
    F = payload_width(p)
    A = lanes_of(p)
    K = drain_of(p)
    nc = 2 * n + 1
    # Scenario plane (SimParams.scenario): per-slot delay table + traced
    # commit-chain view — see sim/simulator.py.  The ``d_min`` lookahead
    # is derived IN-GRAPH from the slot's OWN table (one fused min over
    # the [T] row — exactly ``d_min_of``'s formula), not the caller's
    # conservative scalar: window composition (horizon, drain batching,
    # inbox routing order, the window-health telemetry) follows the
    # lookahead, so only the slot's own value reproduces a dedicated
    # static run of that scenario bit-for-bit, inbox layout included.
    if p.scenario:
        pp = TracedParams(p, st.sc_commit[0])
        delay_table = st.sc_delay
        d_min = jnp.maximum(jnp.min(st.sc_delay), 1)
    else:
        pp = p
    # Adversary network plane: every link's latency exceeds the drawn
    # table delay by at least the minimum off-diagonal adv_link entry, so
    # the Chandy–Misra horizon soundly TIGHTENS by exactly that amount —
    # per-link lookahead instead of the global table bound (wider windows
    # on delay-skewed matrices).  ``d_min`` itself stays the table bound:
    # it also clamps the per-message draws below, where folding the link
    # extra in would inflate the base draws and change trajectories.
    if p.adversary:
        d_hz = d_min + aplane.link_lookahead(st.adv_link, n)
    else:
        d_hz = d_min

    # ---- Window bookkeeping: per-node earliest times, global horizon.
    # The horizon must be GLOBAL (t_min + d_min), not per-node: with
    # multi-event draining, a node's own in-window send at t can trigger
    # another node's event at t + d_min whose *reply* lands back at
    # t + 2*d_min — so any event at or beyond t_min + d_min may causally
    # depend on in-window work.  Events strictly below t_min + d_min cannot
    # (every in-window send arrives at >= t_min + d_min), which makes the
    # global window safe for draining K same-node events.  (A per-node
    # min-over-others horizon is sound only for one-event-per-node windows,
    # where each node processes an event that precedes every other node's
    # first possible send.)
    msg_time = jnp.where(st.in_valid, st.in_time, NEVER)
    t_ev = jnp.minimum(jnp.min(msg_time, axis=1), st.timer_time)  # [N]
    t_min = jnp.min(t_ev)
    halt = st.halted | (t_min > st.max_clock)
    live = ~halt
    clock = jnp.maximum(st.clock, jnp.minimum(t_min, NEVER - 1))
    hz = jnp.minimum(t_min, NEVER - d_hz) + d_hz  # scalar
    qualify = live & (t_ev < hz) & (t_ev <= st.max_clock)

    # ---- Lane compaction: the A earliest qualifying nodes (ties by index).
    sort_key = jnp.where(qualify, t_ev, NEVER)
    sel = jnp.argsort(sort_key, stable=True)[:A].astype(I32)  # [A] node ids
    lane_on = qualify[sel]
    lane_startup = st.startup[sel]
    lane_silent = st.byz_silent[sel]
    lane_equiv = st.byz_equivocate[sel]
    lane_forge = st.byz_forge_qc[sel]
    others_l = sel[:, None] != jnp.arange(n)[None, :]  # [A, n]
    # Loop constants: drains only flip in_valid; times/kinds/stamps/payloads
    # of already-queued messages never change mid-window.
    g_it = st.in_time[sel]
    g_ik = st.in_kind[sel]
    g_is = st.in_stamp[sel]
    g_isnd = st.in_sender[sel]
    g_ipay = st.in_pay[sel]
    # Watchdog conflict reference: every node's committed log as of the
    # window start (lanes' own rows are superseded by their carried ctx;
    # the not_self mask below excludes them).  Packed layouts unpack views.
    if p.watchdog:
        wd_ctx_all = (packing.unpack_node(p, st.planes)[3] if p.packed
                      else st.ctx)

    def drain_iter(c, _):
        (g_store, g_pm, g_nx, g_cx, g_iv, g_timer, g_ctr, g_hop, g_hoe,
         ev_n, drop_n, tr_n, tr_r, tr_t, tr_c) = c[:15]
        extra = 15
        m = fl = wd = None
        if p.telemetry:
            m, fl = c[extra], c[extra + 1]
            extra += 2
        if p.watchdog:
            wd = c[extra]
        pm_pre_round = g_pm.active_round  # [A] for the round-switch trace
        pm_pre_start = g_pm.round_start   # [A] for the round-latency histogram
        pre_cc = g_cx.commit_count        # [A] for the commit-latency histogram
        pre_sync = g_cx.sync_jumps        # [A] for the sync-jump tally
        t_l, k_l, slot_l, is_tm = _earliest(g_iv, g_it, g_ik, g_is, g_timer)
        act = lane_on & (t_l < hz) & (t_l <= st.max_clock)
        slot_c = jnp.maximum(slot_l, 0)
        pay_rows = jnp.take_along_axis(g_ipay, slot_c[:, None, None], axis=1)[:, 0]
        sender = jnp.take_along_axis(g_isnd, slot_c[:, None], axis=1)[:, 0]
        consume = act & ~is_tm
        # Per-lane scalar write via wset (utils/xops.py — scalar-per-row
        # scatters miscompile on the axon TPU stack).
        g_iv = jax.vmap(lambda row, i, c: wset(row, i, False, when=c))(
            g_iv, slot_c, consume)

        is_notify = act & ~is_tm & (k_l == KIND_NOTIFY)
        is_request = act & ~is_tm & (k_l == KIND_REQUEST)
        is_response = act & ~is_tm & (k_l == KIND_RESPONSE)
        do_update = act & (is_tm | is_notify | is_response)
        lclk = t_l - lane_startup  # each lane handles its own event time

        # ---- Adversary plane decode, per lane (adversary/plane.py):
        # windowed behaviors OR-composed onto the static masks.  Keys are
        # each lane's OWN event time and pre-handler epoch (both
        # window-composition-invariant), and the instance event count at
        # WINDOW start (st.n_events — MODE_EVENTS bounds are evaluated at
        # window granularity here; the serial engine is the per-event
        # reference for that mode).  Off: compiled out entirely.
        if p.adversary:
            ep_pre = g_store.epoch_id  # [A] pre-handler epochs
            adv_act = jax.vmap(
                lambda t, ep: aplane.active_windows(
                    st.adv_sched, t, st.n_events, ep))(t_l, ep_pre)
            adv_eq, adv_sil, adv_forge = jax.vmap(
                lambda ac, i: aplane.node_masks(st.adv_sched, ac, i))(
                adv_act, sel)
            l_eq = lane_equiv | adv_eq
            l_sil = lane_silent | adv_sil
            l_forge = lane_forge | adv_forge
        else:
            l_eq, l_sil, l_forge = lane_equiv, lane_silent, lane_forge

        def per_lane(i, s_a, pm_a, nx_a, cx_a, pay_row, lc, ho_row, ho_ep):
            a = sel[i]
            pay_in = unpack_payload(p, pay_row)
            s_n, should_sync = data_sync.handle_notification(
                pp, s_a, st.weights, pay_in)
            s_r, nx_r, cx_r = data_sync.handle_response(
                pp, s_a, nx_a, cx_a, st.weights, pay_in)
            s_in = store_ops._sel(is_notify[i], s_n,
                                  store_ops._sel(is_response[i], s_r, s_a))
            nx_in = store_ops._sel(is_response[i], nx_r, nx_a)
            cx_in = store_ops._sel(is_response[i], cx_r, cx_a)
            s_u, pm_u, nx_u, cx_u, actions = node_ops.update_node(
                pp, s_in, pm_a, nx_in, cx_in, st.weights, a, lc, dur_table)
            s_f = store_ops._sel(do_update[i], s_u, s_in)
            pm_f = store_ops._sel(do_update[i], pm_u, pm_a)
            nx_f = store_ops._sel(do_update[i], nx_u, nx_in)
            cx_f = store_ops._sel(do_update[i], cx_u, cx_in)
            notif = data_sync.create_notification(pp, s_f, a)
            notif = store_ops._sel(l_forge[i],
                                   _forged_qc_payload(pp, s_f, a, notif), notif)
            request = data_sync.create_request(pp, s_f)
            response = data_sync.handle_request(pp, s_f, a, pay_in, notif=notif)
            resp_packed = pack_payload(response)
            if p.epoch_handoff:
                # Cross-epoch handoff ring (mirrors sim/simulator.py):
                # capture the pack update_node built from the post-update,
                # pre-switch store; serve any requester whose epoch matches
                # a held pack.
                E = p.handoff_epochs
                switched = do_update[i] & actions.ho_switched
                wslot = jnp.remainder(jnp.maximum(actions.ho_epoch, 0), E)
                ho_row = wset(ho_row, wslot, actions.ho_pack, when=switched)
                ho_ep = wset(ho_ep, wslot, actions.ho_epoch, when=switched)
                rslot = jnp.remainder(jnp.maximum(pay_in.epoch, 0), E)
                serve_ho = (is_request[i] & (ho_ep[rslot] == pay_in.epoch)
                            & (pay_in.epoch < s_f.epoch_id))
                resp_row = jnp.where(serve_ho, ho_row[rslot], resp_packed)
            else:
                resp_row = resp_packed
            bank = jnp.stack([
                pack_payload(notif),
                pack_payload(_equivocate(p, notif)),
                pack_payload(request),
                resp_row,
            ])
            return (s_f, pm_f, nx_f, cx_f, actions, should_sync, bank,
                    ho_row, ho_ep)

        (g_store, g_pm, g_nx, g_cx, actions, should_sync, banks, g_hop,
         g_hoe) = jax.vmap(per_lane)(
            jnp.arange(A), g_store, g_pm, g_nx, g_cx, pay_rows, lclk,
            g_hop, g_hoe)

        # ---- Outgoing candidates: [A lanes, 2n+1 candidates].
        want_sync_req = is_notify & should_sync & ~l_sil
        want_response = is_request & ~l_sil
        cand0_want = want_sync_req | want_response
        cand0_kind = jnp.where(want_response, KIND_RESPONSE, KIND_REQUEST)
        cand0_recv = jnp.clip(sender, 0, n - 1)
        send_mask = (actions.send_mask & others_l & do_update[:, None]
                     & ~l_sil[:, None])
        query_mask = ((actions.should_query_all & do_update
                       & ~l_sil)[:, None] & others_l)

        want = jnp.concatenate([cand0_want[:, None], send_mask, query_mask],
                               axis=1)
        recvs = jnp.concatenate([
            cand0_recv[:, None],
            jnp.broadcast_to(jnp.arange(n, dtype=I32), (A, n)),
            jnp.broadcast_to(jnp.arange(n, dtype=I32), (A, n)),
        ], axis=1)
        kinds = jnp.concatenate([
            cand0_kind[:, None],
            jnp.full((A, n), KIND_NOTIFY, I32),
            jnp.full((A, n), KIND_REQUEST, I32),
        ], axis=1)
        upper = (jnp.arange(n) * 2 >= n)[None, :]
        eq_sel = jnp.where(l_eq[:, None] & upper, 1, 0)
        pay_sel = jnp.concatenate([
            jnp.where(want_response, 3, 2)[:, None],
            eq_sel,
            jnp.full((A, n), 2, I32),
        ], axis=1)

        # Per-lane stamps: node-local streams (ctr*N + node), disjoint across
        # nodes so rng draws are deterministic however windows interleave.
        pos = jnp.cumsum(want, axis=1) - 1
        timer_gap = jnp.where(do_update, 1, 0)
        local_idx = g_ctr[:, None] + pos + jnp.where(
            jnp.arange(nc)[None, :] > 0, timer_gap[:, None], 0)
        stamps = local_idx * n + sel[:, None]
        consumed = jnp.sum(want, axis=1) + timer_gap
        g_ctr = g_ctr + jnp.where(act, consumed, 0)

        u_delay = H.rng_u32(st.seed, stamps.astype(jnp.uint32))
        u_drop = H.mix32(u_delay, jnp.uint32(0x632BE59B))
        delays = jnp.maximum(
            delay_table[(u_delay >> (32 - TABLE_BITS)).astype(I32)], d_min)
        dropped = want & (u_drop < st.drop_u32)
        if p.adversary:
            # Network plane: per-link + windowed targeted/leader delay
            # extras on top of each drawn latency (the extras are what
            # the d_hz horizon tightening above is backed by), and the
            # partition cut on crossing messages sent before heal.
            leader = config.leader_of_round(st.weights, g_pm.active_round)
            extra = jax.vmap(
                lambda ac, rv, ld: aplane.delay_extra(
                    st.adv_sched, ac, rv, ld))(adv_act, recvs, leader)
            delays = (delays
                      + jnp.clip(st.adv_link[sel[:, None], recvs], 0,
                                 aplane.DELAY_CAP)
                      + extra)
            cut = ((st.adv_group[sel][:, None] != st.adv_group[recvs])
                   & (t_l[:, None] < st.adv_heal[0]))
            dropped = dropped | (want & cut)
        arrive = t_l[:, None] + delays  # lane's event time + latency
        go = want & ~dropped

        # ---- Timer reschedule (sat_add: see types.sat_add).
        next_g = sat_add(actions.next_sched, lane_startup)
        g_timer = jnp.where(do_update, jnp.maximum(next_g, t_l + 1), g_timer)

        ev_n = ev_n + jnp.sum(act)
        drop_n = drop_n + jnp.sum(dropped)

        # ---- Round-switch trace (mirrors sim/simulator.py; ring append in
        # lane order, compiled out when trace_cap == 0).
        switched_tr = do_update & (g_pm.active_round > pm_pre_round)
        if p.trace_cap > 0:
            tr_pos = tr_c + jnp.cumsum(switched_tr) - 1
            # Index == cap is out-of-bounds and dropped (-1 would wrap).
            tpos = jnp.where(switched_tr, jnp.remainder(tr_pos, p.trace_cap),
                             _i32(p.trace_cap))
            tr_n = tr_n.at[tpos].set(sel, mode="drop")
            tr_r = tr_r.at[tpos].set(g_pm.active_round, mode="drop")
            tr_t = tr_t.at[tpos].set(t_l, mode="drop")
        tr_c = tr_c + jnp.sum(switched_tr)

        # ---- Consensus watchdog for this drain iteration (lane-wise
        # masks over the tiny [WD] plane; compiled out when
        # SimParams.watchdog is off).  queue_sat is a window-level signal
        # and accumulates after routing, outside the scan.
        if p.watchdog:
            with scope("watchdog"):
                T = p.watchdog_stall_events
                # Liveness stall: events drained since ANY lane advanced a
                # pacemaker round; a switch anywhere resets the counter for
                # the whole instance (the instance IS making progress).
                stall_ev0 = wd[tstream.WD_STALL_EV]
                stall_ev = jnp.where(jnp.any(switched_tr), 0,
                                     stall_ev0 + jnp.sum(act))
                stall_trip = (stall_ev0 < T) & (stall_ev >= T)
                sj_inc = jnp.sum(g_cx.sync_jumps - pre_sync)
                # Safety invariants on each committed lane's NEWEST entry.
                comm = g_cx.commit_count > pre_cc  # [A]
                Hl = p.commit_log
                pick = lambda arr, idx: jnp.take_along_axis(  # noqa: E731
                    arr, idx[:, None], axis=1)[:, 0]
                pos = jnp.remainder(
                    jnp.maximum(g_cx.commit_count - 1, 0), Hl)
                pos2 = jnp.remainder(
                    jnp.maximum(g_cx.commit_count - 2, 0), Hl)
                d_new, t_new = pick(g_cx.log_depth, pos), pick(
                    g_cx.log_tag, pos)
                r_new, r_prev = pick(g_cx.log_round, pos), pick(
                    g_cx.log_round, pos2)
                same_epoch = (d_new // p.commands_per_epoch
                              == pick(g_cx.log_depth, pos2)
                              // p.commands_per_epoch)
                regress = (comm & (g_cx.commit_count >= 2) & same_epoch
                           & (r_new <= r_prev))
                # Conflicting commit at the same height — the serial
                # semantics (a commit trips iff a conflicting entry EXISTS
                # in another node's log at commit time), assembled from the
                # two places an entry can live mid-window: (a) every node's
                # window-start log (wd_ctx_all — exact for non-lane nodes,
                # which cannot commit during the window; own rows excluded,
                # own depths strictly increase); (b) the other LANES'
                # carried logs (g_cx), which hold this window's commits
                # from earlier drain iterations too.  Entries written in
                # THIS iteration count only for higher-index lanes (the
                # causally-independent pair maps to two serial events in
                # either order, and serial trips exactly once — at the
                # later one).
                entry_ok = (jnp.arange(Hl)[None, :] < jnp.minimum(
                    wd_ctx_all.commit_count, Hl)[:, None])      # [N, Hl]
                hit = (entry_ok[None]
                       & (wd_ctx_all.log_depth[None]
                          == d_new[:, None, None])
                       & (wd_ctx_all.log_tag[None]
                          != t_new[:, None, None]))             # [A, N, Hl]
                not_self = sel[:, None] != jnp.arange(n)[None, :]
                nl = d_new.shape[0]
                cc_l = g_cx.commit_count                        # [A] post
                qpos = jnp.arange(Hl)[None, :]
                entry_ok_l = qpos < jnp.minimum(cc_l, Hl)[:, None]
                # Ring position -> commit ordinal (latest write at q);
                # ordinals >= the iteration-start count are this
                # iteration's entries.
                ord_l = (cc_l[:, None] - 1
                         - jnp.remainder(cc_l[:, None] - 1 - qpos, Hl))
                new_l = ord_l >= pre_cc[:, None]                # [A, Hl]
                lane_hit = (entry_ok_l[None]
                            & (g_cx.log_depth[None]
                               == d_new[:, None, None])
                            & (g_cx.log_tag[None]
                               != t_new[:, None, None]))        # [A, A, Hl]
                li = jnp.arange(nl)[:, None, None]
                lj = jnp.arange(nl)[None, :, None]
                seen = ~new_l[None] | (li > lj)  # same-iter: count once
                conflict = comm & (
                    jnp.any(hit & not_self[:, :, None], axis=(1, 2))
                    | jnp.any(lane_hit & (li != lj) & seen, axis=(1, 2)))
                wd = jnp.stack([
                    stall_ev,
                    wd[tstream.WD_STALL] + stall_trip.astype(I32),
                    wd[tstream.WD_QUEUE_SAT],
                    wd[tstream.WD_SYNC_JUMP] + sj_inc,
                    wd[tstream.WD_SAFETY_CONFLICT]
                    + jnp.sum(conflict.astype(I32)),
                    wd[tstream.WD_ROUND_REGRESS]
                    + jnp.sum(regress.astype(I32)),
                ]).astype(I32)

        # ---- Telemetry accumulation for this drain iteration (lane-wise
        # masks; compiled out when SimParams.telemetry is off).
        if p.telemetry:
            with scope("telemetry"):
                m = tplane.bump(p, m, "ev_notify", jnp.sum(is_notify))
                m = tplane.bump(p, m, "ev_request", jnp.sum(is_request))
                m = tplane.bump(p, m, "ev_response", jnp.sum(is_response))
                m = tplane.bump(p, m, "ev_timer", jnp.sum(act & is_tm))
                m = tplane.bump(p, m, "drops", jnp.sum(dropped))
                m = tplane.bump(p, m, "sync_jumps",
                                jnp.sum(g_cx.sync_jumps - pre_sync))
                rlat = jnp.maximum(g_pm.round_start - pm_pre_start, 0)
                m = tplane.bump_hist(p, m, "round_lat_hist", rlat,
                                     switched_tr)
                committed = g_cx.commit_count > pre_cc
                cfound, clat = jax.vmap(
                    lambda s_r, cx_r, t: tplane.commit_latency(
                        p, s_r, cx_r, st.startup, t))(g_store, g_cx, t_l)
                m = tplane.bump_hist(p, m, "commit_lat_hist", clat,
                                     committed & cfound)
                m = tplane.bump(p, m, "commit_lat_miss",
                                jnp.sum(committed & ~cfound))
                # Flight recorder: one row per active lane, appended in lane
                # order (same ring discipline as the trace ring above).  When
                # more lanes are active than the ring holds, ranks K apart
                # would collide on one slot and duplicate-index scatter order
                # is unspecified — keep only the newest flight_cap ranks so
                # every written slot has exactly one writer (the older rows
                # would have been overwritten anyway).
                frc = tplane.read(p, m, "fr_count")
                fr_rank = jnp.cumsum(act) - 1
                fr_keep = act & (fr_rank >= jnp.sum(act) - p.flight_cap)
                fpos = jnp.where(fr_keep,
                                 jnp.remainder(frc + fr_rank, p.flight_cap),
                                 _i32(p.flight_cap))
                occ = jnp.sum(g_iv, axis=1).astype(I32)
                rows = jnp.stack(
                    [k_l, sel, t_l, g_pm.active_round, occ], axis=1)
                fl = fl.at[fpos].set(rows, mode="drop")
                m = tplane.bump(p, m, "fr_count", jnp.sum(act))

        if _debug_tap is not None:
            jax.debug.callback(_debug_tap, act, t_l, k_l, sel, is_tm, g_ctr,
                               t_ev, hz, qualify, ordered=True)
        c2 = (g_store, g_pm, g_nx, g_cx, g_iv, g_timer, g_ctr, g_hop, g_hoe,
              ev_n, drop_n, tr_n, tr_r, tr_t, tr_c)
        if p.telemetry:
            c2 = c2 + (m, fl)
        if p.watchdog:
            c2 = c2 + (wd,)
        return c2, (go, kinds, recvs, stamps, arrive, pay_sel, banks)

    if p.packed:
        # One [A, S] row gather + free slicing replaces ~70 per-leaf
        # gathers (core/packing.py).
        l_store, l_pm, l_nx, l_cx = packing.unpack_node(p, st.planes[sel])
    else:
        slicer = lambda x: x[sel]  # noqa: E731
        l_store = jax.tree.map(slicer, st.store)
        l_pm = jax.tree.map(slicer, st.pm)
        l_nx = jax.tree.map(slicer, st.node)
        l_cx = jax.tree.map(slicer, st.ctx)
    carry0 = (
        l_store, l_pm, l_nx, l_cx,
        st.in_valid[sel], st.timer_time[sel], st.node_ctr[sel],
        st.ho_pay[sel], st.ho_epoch[sel], _i32(0), _i32(0),
        st.trace_node, st.trace_round, st.trace_time, st.trace_count)
    if p.telemetry:
        carry0 = carry0 + (st.metrics, st.flight)
    if p.watchdog:
        carry0 = carry0 + (st.wd,)
    with scope("lane_drain"):
        carryN, ys = jax.lax.scan(drain_iter, carry0, None, length=K)
    (g_store, g_pm, g_nx, g_cx, g_iv, g_timer, g_ctr, g_hop, g_hoe, ev_n,
     drop_n, trace_node, trace_round, trace_time, trace_count) = carryN[:15]
    _extra = 15
    if p.telemetry:
        metrics, flight = carryN[_extra], carryN[_extra + 1]
        _extra += 2
    else:
        metrics, flight = st.metrics, st.flight
    wd_plane = carryN[_extra] if p.watchdog else st.wd
    go_k, kind_k, recv_k, stamp_k, arrive_k, paysel_k, bank_k = ys  # [K, A, .]

    # ---- Scatter lane state back (sel indices are distinct; inactive lanes
    # carried their original values, so unconditional writes are no-ops).
    put = lambda x, v: x.at[sel].set(v)  # noqa: E731
    if p.packed:
        # One [A, S] row scatter replaces ~70 per-leaf scatters (vector row
        # scatters are the proven-safe class, PERF_NOTES.md).
        node_updates = dict(planes=put(
            st.planes, packing.pack_node(p, g_store, g_pm, g_nx, g_cx)))
    else:
        node_updates = dict(
            store=jax.tree.map(put, st.store, g_store),
            pm=jax.tree.map(put, st.pm, g_pm),
            node=jax.tree.map(put, st.node, g_nx),
            ctx=jax.tree.map(put, st.ctx, g_cx),
        )
    in_valid = put(st.in_valid, g_iv)
    timer_time = put(st.timer_time, g_timer)
    node_ctr = put(st.node_ctr, g_ctr)
    ho_pay = put(st.ho_pay, g_hop)
    ho_epoch = put(st.ho_epoch, g_hoe)

    # ---- Route all K*A*(2n+1) candidates to receiver inboxes.  Receiver
    # rank order is (candidate-block, drain-iter, lane) — deterministic given
    # state, O(K·A·n) column cumsums instead of an O(N·M) rank matrix.
    KA = K * A
    go_f = go_k.reshape(KA, nc)
    recv_f = recv_k.reshape(KA, nc)
    go0 = go_f[:, 0]
    recv0 = jnp.clip(recv_f[:, 0], 0, n - 1)
    oh0 = (recv0[:, None] == jnp.arange(n)[None, :]) & go0[:, None]  # [KA, n]
    cnt0 = jnp.sum(oh0, axis=0)                                      # [n]
    rank0 = (jnp.cumsum(oh0, axis=0) - 1)[jnp.arange(KA), recv0]
    go1 = go_f[:, 1:n + 1]   # receiver == column
    go2 = go_f[:, n + 1:]
    cnt1 = jnp.sum(go1, axis=0)
    rank1 = cnt0[None, :] + jnp.cumsum(go1, axis=0) - 1
    rank2 = (cnt0 + cnt1)[None, :] + jnp.cumsum(go2, axis=0) - 1
    rank = jnp.concatenate([rank0[:, None], rank1, rank2], axis=1)  # [KA, nc]

    flat_go = go_f.reshape(-1)
    flat_recv = recv_f.reshape(-1)
    flat_rank = rank.reshape(-1)
    free = ~in_valid                                     # [N, IC] post-drain
    free_rank = jnp.cumsum(free, axis=1) - 1
    n_free = jnp.sum(free, axis=1)                       # [N]
    # slot_of_rank[r, k] = inbox slot holding receiver r's k-th free slot.
    slot_of_rank = jnp.full((n, ic), ic, I32).at[
        jnp.arange(n)[:, None], jnp.where(free, free_rank, ic)
    ].set(jnp.broadcast_to(jnp.arange(ic, dtype=I32), (n, ic)), mode="drop")
    overflow_m = flat_go & (flat_rank >= jnp.minimum(n_free, ic)[flat_recv])
    place_m = flat_go & ~overflow_m
    slot_m = slot_of_rank[flat_recv, jnp.clip(flat_rank, 0, ic - 1)]
    # Global scatter target over the flattened [N*IC] inbox; N*IC == dropped.
    g = jnp.where(place_m, flat_recv * ic + slot_m, n * ic)

    flat_sender = jnp.broadcast_to(sel[None, :, None], (K, A, nc)).reshape(-1)
    bank_f = bank_k.reshape(KA, 4, F)
    flat_pay = bank_f[
        jnp.repeat(jnp.arange(KA), nc), paysel_k.reshape(-1)]  # [KA*nc, F]

    with scope("inbox_route"):
        in_valid2 = in_valid.reshape(-1).at[g].set(
            True, mode="drop").reshape(n, ic)
        in_time2 = st.in_time.reshape(-1).at[g].set(
            arrive_k.reshape(-1), mode="drop").reshape(n, ic)
        in_kind2 = st.in_kind.reshape(-1).at[g].set(
            kind_k.reshape(-1), mode="drop").reshape(n, ic)
        in_stamp2 = st.in_stamp.reshape(-1).at[g].set(
            stamp_k.reshape(-1), mode="drop").reshape(n, ic)
        in_sender2 = st.in_sender.reshape(-1).at[g].set(
            flat_sender, mode="drop").reshape(n, ic)
        in_pay2 = st.in_pay.reshape(n * ic, F).at[g].set(
            flat_pay, mode="drop").reshape(n, ic, F)

    delivered = jnp.sum(place_m)

    # ---- Window-level watchdog: queue-pressure saturation — any receiver
    # inbox full after this window's routing.  One-hot add over the [WD]
    # plane (static offset).
    if p.watchdog:
        qsat = live & jnp.any(
            jnp.sum(in_valid2.astype(I32), axis=1) >= ic)
        wd_plane = wd_plane + jnp.where(
            jnp.arange(tstream.WD_WIDTH) == tstream.WD_QUEUE_SAT,
            qsat.astype(I32), 0)
        wd_updates = dict(wd=wd_plane)
    else:
        wd_updates = {}

    # ---- Window-level telemetry: occupancy/stall health of the
    # conservative window plus post-routing queue pressure.
    if p.telemetry:
        with scope("telemetry"):
            m = metrics
            m = tplane.bump(p, m, "windows", when=live)
            # Nodes with an eligible event stalled beyond the lookahead
            # horizon: work exists but conservatism defers it.
            m = tplane.bump(
                p, m, "horizon_stall",
                jnp.sum((t_ev <= st.max_clock) & (t_ev >= hz)), when=live)
            # Qualifying nodes that didn't fit on the A lanes.
            m = tplane.bump(p, m, "lane_spill",
                            jnp.maximum(jnp.sum(qualify) - A, 0), when=live)
            m = tplane.bump(p, m, "overflow", jnp.sum(overflow_m), when=live)
            depths = jnp.sum(in_valid2, axis=1)
            m = tplane.region_max(p, m, "node_depth_hwm", depths)
            m = tplane.region_max(p, m, "queue_hwm", jnp.sum(depths))
            tel_updates = dict(metrics=m, flight=flight)
    else:
        tel_updates = {}

    return st.replace(
        **node_updates,
        **tel_updates,
        **wd_updates,
        ho_pay=ho_pay, ho_epoch=ho_epoch,
        in_valid=in_valid2, in_time=in_time2, in_kind=in_kind2,
        in_stamp=in_stamp2, in_sender=in_sender2, in_pay=in_pay2,
        timer_time=timer_time,
        clock=jnp.where(live, clock, st.clock),
        node_ctr=node_ctr,
        halted=halt,
        n_events=st.n_events + jnp.where(live, ev_n, 0),
        n_msgs_sent=st.n_msgs_sent + jnp.where(live, delivered, 0),
        n_msgs_dropped=st.n_msgs_dropped + jnp.where(live, drop_n, 0),
        n_inbox_full=st.n_inbox_full + jnp.where(live, jnp.sum(overflow_m), 0),
        trace_node=trace_node,
        trace_round=trace_round,
        trace_time=trace_time,
        trace_count=trace_count,
    )


def _equivocate(p: SimParams, pay):
    b = pay.prop_blk
    tag = store_ops.block_tag(
        pay.epoch, b.round, b.author, b.prev_round, b.prev_tag, b.time,
        b.cmd_proposer, b.cmd_index + EQUIV_SALT)
    return pay.replace(
        prop_blk=b.replace(cmd_index=b.cmd_index + EQUIV_SALT, tag=tag),
        vote=pay.vote.replace(valid=jnp.bool_(False)),
    )


def _scan_run(p_structural: SimParams, num_steps: int, batched: bool):
    """The raw (untransformed) window-chunk scan (see simulator._scan_run)."""
    packed = bool(p_structural.packed)

    def run(delay_table, dur_table, d_min, st):
        if packed:
            st = pack_pstate(p_structural, st)

        def body(s, _):
            return step(p_structural, delay_table, dur_table, d_min, s), ()

        st, _ = jax.lax.scan(body, st, None, length=num_steps)
        if packed:
            st = unpack_pstate(p_structural, st)
        return st

    if batched:
        run = jax.vmap(run, in_axes=(None, None, None, 0))
    return run


@functools.lru_cache(maxsize=None)
def _compiled_run(p_structural: SimParams, num_steps: int, batched: bool):
    return jax.jit(_scan_run(p_structural, num_steps, batched),
                   donate_argnums=(3,))


@functools.lru_cache(maxsize=None)
def _compiled_digest_run(p_structural: SimParams, num_steps: int,
                         batched: bool):
    """Window-chunk scan returning ``(state, [D] digest)`` — the lane
    engine's flavor of the stream contract (see simulator's twin)."""
    run = _scan_run(p_structural, num_steps, batched)

    def f(delay_table, dur_table, d_min, st):
        st = run(delay_table, dur_table, d_min, st)
        return st, tstream.compute_digest(p_structural, st)

    return jax.jit(f, donate_argnums=(3,))


def _reject_macro(p: SimParams) -> None:
    """The serial engine's K-event macro-steps (SimParams.macro_k) do not
    apply here: the lane engine already amortizes dispatch over whole
    global-horizon windows — its ``num_steps`` unit IS a multi-event
    window.  Silently ignoring the knob would fake a K-rung measurement,
    so a macro-armed lane run fails loud instead."""
    if (p.macro_k or 1) > 1:
        raise ValueError(
            f"SimParams.macro_k={p.macro_k} is a serial-engine knob; the "
            "lane engine's horizon windows already batch events per "
            "dispatch — run the serial engine, or set macro_k=None "
            "(and unset LIBRABFT_MACRO_K) for lane runs")


def make_scan_fn(p: SimParams, num_steps: int, batched: bool = True,
                 d_min: int | None = None):
    """Uncompiled counterpart of :func:`make_run_fn` (same contract as
    simulator.make_scan_fn): the window-chunk scan with tables and lookahead
    bound but no ``jax.jit``, for the dp-fleet ``shard_map`` wrapping in
    ``parallel/sharded.py``."""
    dmin = d_min_of(p) if d_min is None else d_min
    assert 1 <= dmin <= d_min_of(p), (dmin, d_min_of(p))
    p = xops.resolve_params(p)
    _reject_macro(p)
    run = _scan_run(p.structural(), num_steps, batched)
    delay_table = jnp.asarray(p.delay_table())
    dur_table = jnp.asarray(p.duration_table())
    dmin_arr = jnp.asarray(dmin, I32)
    return lambda st: run(delay_table, dur_table, dmin_arr, st)


def make_run_fn(p: SimParams, num_steps: int, batched: bool = True,
                d_min: int | None = None, digest: bool = False):
    """``d_min`` overrides the lookahead (must be <= the true minimum message
    latency).  As long as no inbox overflows, any conservative value — and
    any ``active_lanes``/``drain_k`` choice — yields the SAME trajectories:
    window shape only decides how much work lands in each step, which
    `tests/test_parallel_sim.py` asserts bit-exactly.  (Under overflow the
    window shape changes which concurrent sends compete for free slots, so
    the discarded set — and hence the trajectory — may differ.)  The
    executable is memoized on ``p.structural()`` with the lookahead as a
    runtime scalar, so delay/drop/horizon variants share one compile.
    ``digest=True`` returns ``st -> (st, [D] digest)``
    (telemetry/stream.py) exactly like the serial engine's make_run_fn."""
    dmin = d_min_of(p) if d_min is None else d_min
    assert 1 <= dmin <= d_min_of(p), (dmin, d_min_of(p))
    p = xops.resolve_params(p)
    _reject_macro(p)
    ps = p.structural()
    maker = _compiled_digest_run if digest else _compiled_run
    inner = maker(ps, num_steps, batched)
    delay_table = jnp.asarray(p.delay_table())
    dur_table = jnp.asarray(p.duration_table())
    dmin_arr = jnp.asarray(dmin, I32)
    # AOT executable store (utils/aot.py): consult before tracing — see
    # simulator.make_run_fn.  Tables and the lookahead scalar are
    # arguments of the stored executable, so one entry serves every
    # delay/drop/d_min config at this structural shape.
    call = aot.wrap_jit(
        inner, (delay_table, dur_table, dmin_arr),
        key=tledger.params_key(ps), engine="lane",
        flavor="digest" if digest else "run",
        num_steps=num_steps, batched=batched)
    # Compile ledger (telemetry/ledger.py): host-side only, same graph.
    return tledger.wrap_compile(
        call,
        key=tledger.params_key(ps), structural=repr(ps), engine="lane",
        n_nodes=p.n_nodes, num_steps=num_steps, batched=batched,
        digest=digest)


def init_batch(p: SimParams, seeds) -> PSimState:
    seeds = jnp.asarray(seeds).astype(jnp.uint32)
    return jax.vmap(lambda s: init_state(p, s))(seeds)


# Default host-loop budget (windows per dispatch x dispatch cap); see
# simulator.RUN_CHUNK — the dp-fleet sweep path reads these by name.
RUN_CHUNK = 256
RUN_MAX_CHUNKS = 400


def run_to_completion(p: SimParams, st: PSimState, chunk: int = RUN_CHUNK,
                      max_chunks: int = RUN_MAX_CHUNKS,
                      batched: bool = False, stream=None):
    from .simulator import dedupe_buffers, stream_completion

    st = dedupe_buffers(st)
    from ..audit import sanitize
    if stream is not None:
        if sanitize.enabled():
            # See simulator.run_to_completion: never pretend the stream
            # loop was invariant-checked.
            raise ValueError(
                "LIBRABFT_CHECKIFY=1 and stream= are mutually exclusive: "
                "the digest stream loop runs the unchecked chunk; unset "
                "the knob or drop the recorder")
        # Digest poll contract (see simulator.stream_completion).
        return stream_completion(
            make_run_fn(p, chunk, batched=batched, digest=True), st,
            chunk, max_chunks, batched, stream)
    if sanitize.enabled():
        # LIBRABFT_CHECKIFY debug build — see simulator.run_to_completion.
        import sys as _sys
        return sanitize.checked_completion(
            p, st, chunk, max_chunks, batched, _sys.modules[__name__])
    run = make_run_fn(p, chunk, batched=batched)
    lg = tledger.get()
    rid = lg.new_run("run_to_completion", engine="lane", chunk_steps=chunk)
    for i in range(max_chunks):
        with lg.span(tledger.DISPATCH, run=rid, chunk=i):
            st = run(st)
        with lg.span(tledger.POLL, run=rid, chunk=i):
            halted = jax.device_get(st.halted)
        if bool(np.all(halted)):
            break
    return st
