"""Conservative-window parallel engine: N nodes per instance step together.

The serial engine (:mod:`.simulator`) replays the reference's event loop one
event at a time — the parity reference.  This engine is the throughput mode:
classic conservative parallel discrete-event simulation (PDES) with network
lookahead, re-expressed for TPU.

Correctness argument (standard Chandy-Misra lookahead): nodes influence each
other ONLY via messages, and every message has latency >= ``d_min`` (the
minimum of the delay table, floored to 1 here).  Hence all events with
timestamps in the window ``[t_min, t_min + d_min)`` at *different* nodes are
causally independent and may be processed concurrently; same-node causality
is preserved by processing at most one event per node per step (a node's
events are totally ordered by (time, kind desc, stamp)).  The messages they
emit arrive at or after ``t_min + d_min``, i.e. outside the window.

TPU shape: per-receiver inboxes ``[N, IC]`` instead of one global queue; the
whole per-node protocol machinery (data-sync handlers + update_node) runs
under ``jax.vmap`` over the node axis — the same XLA kernels as the serial
engine now do up to N instances' worth of useful work per launch, which is
what makes 64-node fleets (BASELINE config #3) tractable.

Determinism: rng/stamps are node-local counters (stamp stream ``ctr*N+n``),
so trajectories are bit-reproducible for a seed (CPU == TPU), independent of
how many nodes happen to share a window — ``tests/test_parallel_sim.py``
asserts this bit-exactly by shrinking the lookahead.  They are NOT the serial
engine's trajectories (different stamp interleaving): the serial engine
remains the oracle-parity reference, and the same test file checks this
engine statistically against it (commit/event density per unit virtual time)
plus safety under Byzantine masks and inbox-overflow accounting.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..core import data_sync, node as node_ops, store as store_ops
from .simulator import _forged_qc_payload
from ..core.types import (
    KIND_NOTIFY,
    KIND_REQUEST,
    KIND_RESPONSE,
    KIND_TIMER,
    NEVER,
    Context,
    NodeExtra,
    Pacemaker,
    SimParams,
    Store,
    pack_payload,
    payload_width,
    sat_add,
    unpack_payload,
)
from ..utils import hashing as H
from ..utils.quantile import TABLE_BITS

I32 = jnp.int32
EQUIV_SALT = 1 << 20


def _i32(x):
    return jnp.asarray(x, I32)


@struct.dataclass
class PSimState:
    """One instance under the parallel engine."""

    store: Store          # [N, ...]
    pm: Pacemaker         # [N]
    node: NodeExtra       # [N]
    ctx: Context          # [N, ...]
    # Per-receiver inboxes.
    byz_forge_qc: jnp.ndarray
    max_clock: jnp.ndarray   # i32 horizon (dynamic; see SimParams.structural)
    drop_u32: jnp.ndarray    # u32 drop threshold (dynamic)
    ho_pay: jnp.ndarray      # [N, F] cross-epoch handoff packs ([N, 0] if off)
    ho_epoch: jnp.ndarray    # [N]; -1 = none
    in_valid: jnp.ndarray    # [N, IC] bool
    in_time: jnp.ndarray     # [N, IC]
    in_kind: jnp.ndarray     # [N, IC]
    in_stamp: jnp.ndarray    # [N, IC]
    in_sender: jnp.ndarray   # [N, IC]
    in_pay: jnp.ndarray      # [N, IC, F] packed payloads
    timer_time: jnp.ndarray  # [N]
    startup: jnp.ndarray     # [N]
    weights: jnp.ndarray     # [N]
    byz_equivocate: jnp.ndarray
    byz_silent: jnp.ndarray
    clock: jnp.ndarray
    node_ctr: jnp.ndarray    # [N] per-node stamp/rng counters
    halted: jnp.ndarray
    seed: jnp.ndarray
    n_events: jnp.ndarray
    n_msgs_sent: jnp.ndarray
    n_msgs_dropped: jnp.ndarray
    n_inbox_full: jnp.ndarray


def d_min_of(p: SimParams) -> int:
    """Network lookahead: minimum message latency (>= 1)."""
    return max(int(np.min(p.delay_table())), 1)


def inbox_cap(p: SimParams) -> int:
    """Per-receiver inbox slots: ``SimParams.inbox_cap`` if set, else 4 per
    peer.  Memory scales O(n) per node vs the serial engine's shared queue,
    which needs O(n^2)-ish capacity to stay lossless (in-flight broadcasts ~
    n*(n-1)*mean_delay/round_duration)."""
    return p.inbox_cap if p.inbox_cap > 0 else max(16, 4 * p.n_nodes)


def init_state(p: SimParams, seed, weights=None, byz_equivocate=None,
               byz_silent=None, byz_forge_qc=None) -> PSimState:
    n = p.n_nodes
    ic = inbox_cap(p)
    F = payload_width(p)
    seed = jnp.asarray(seed).astype(jnp.uint32)
    delay_table = jnp.asarray(p.delay_table())
    draws = jax.vmap(lambda c: H.rng_u32(seed, c.astype(jnp.uint32)))(jnp.arange(n))
    startup = (delay_table[(draws >> (32 - TABLE_BITS)).astype(I32)] + 1).astype(I32)
    if weights is None:
        weights = jnp.ones((n,), I32)
    if byz_equivocate is None:
        byz_equivocate = jnp.zeros((n,), jnp.bool_)
    if byz_silent is None:
        byz_silent = jnp.zeros((n,), jnp.bool_)
    if byz_forge_qc is None:
        byz_forge_qc = jnp.zeros((n,), jnp.bool_)
    return PSimState(
        store=Store.initial(p, (n,)),
        pm=Pacemaker.initial((n,)),
        node=NodeExtra.initial((n,)),
        ctx=Context.initial(p, (n,)),
        in_valid=jnp.zeros((n, ic), jnp.bool_),
        in_time=jnp.zeros((n, ic), I32),
        in_kind=jnp.zeros((n, ic), I32),
        in_stamp=jnp.zeros((n, ic), I32),
        in_sender=jnp.zeros((n, ic), I32),
        in_pay=jnp.zeros((n, ic, F), I32),
        timer_time=startup,
        startup=startup,
        weights=jnp.asarray(weights, I32),
        byz_equivocate=jnp.asarray(byz_equivocate, jnp.bool_),
        byz_silent=jnp.asarray(byz_silent, jnp.bool_),
        byz_forge_qc=jnp.asarray(byz_forge_qc, jnp.bool_),
        max_clock=_i32(p.max_clock),
        drop_u32=jnp.uint32(p.drop_u32),
        ho_pay=jnp.zeros((n, F if p.epoch_handoff else 0), I32),
        ho_epoch=jnp.full((n,), -1, I32),
        clock=_i32(0),
        node_ctr=jnp.ones((n,), I32),
        halted=jnp.bool_(False),
        seed=seed,
        n_events=_i32(0),
        n_msgs_sent=_i32(0),
        n_msgs_dropped=_i32(0),
        n_inbox_full=_i32(0),
    )


def _node_earliest(p, st):
    """Per node: earliest pending event by (time, kind desc, stamp).

    Returns (time[N], kind[N], slot[N], is_timer[N]); slot = inbox slot
    (or -1 for timer)."""
    msg_time = jnp.where(st.in_valid, st.in_time, NEVER)
    t_best = jnp.minimum(jnp.min(msg_time, axis=1), st.timer_time)  # [N]
    m1 = msg_time == t_best[:, None]
    k_msg = jnp.max(jnp.where(m1, st.in_kind, -1), axis=1)
    timer_due = st.timer_time == t_best
    k_best = jnp.maximum(k_msg, jnp.where(timer_due, KIND_TIMER, -1))
    m2 = m1 & (st.in_kind == k_best[:, None])
    s_best = jnp.min(jnp.where(m2, st.in_stamp, NEVER), axis=1)
    # Timer wins at equal (time, kind=3): timers and messages never share a
    # kind (messages are 0..2), so k_best==3 <=> timer.
    is_timer = timer_due & (k_best == KIND_TIMER)
    slot = jnp.argmax(m2 & (st.in_stamp == s_best[:, None]), axis=1).astype(I32)
    slot = jnp.where(is_timer, -1, slot)
    return t_best, k_best, slot, is_timer


def step(p: SimParams, delay_table, dur_table, d_min: int, st: PSimState):
    """One window: every node whose earliest event falls inside the global
    conservative window ``[t_min, t_min + d_min)`` processes that event.

    (A per-node ``min_{b != a} t_ev[b] + d_min`` horizon was tried and is
    provably equivalent when each node processes at most one event per
    window: it only widens the window of the unique global-minimum node,
    whose earliest event is already inside the global window.  A genuinely
    wider window needs multi-event draining per node per step.)"""
    n = p.n_nodes
    ic = inbox_cap(p)
    F = payload_width(p)

    t_ev, k_ev, slot, is_timer = _node_earliest(p, st)
    t_min = jnp.min(t_ev)
    halt = st.halted | (t_min > st.max_clock)
    live = ~halt
    clock = jnp.maximum(st.clock, jnp.minimum(t_min, NEVER - 1))
    horizon = jnp.minimum(t_min, NEVER - d_min) + d_min
    active = live & (t_ev < horizon)  # [N]
    # Never process events beyond max_clock inside a window that started
    # before it (they halt the next step).
    active = active & (t_ev <= st.max_clock)

    slot_c = jnp.maximum(slot, 0)
    pay_rows = jnp.take_along_axis(st.in_pay, slot_c[:, None, None], axis=1)[:, 0]
    sender = jnp.take_along_axis(st.in_sender, slot_c[:, None], axis=1)[:, 0]
    # Consume selected inbox slots.
    consume = active & ~is_timer
    in_valid = st.in_valid.at[jnp.arange(n), slot_c].set(
        jnp.where(consume, False, st.in_valid[jnp.arange(n), slot_c]))

    is_notify = active & ~is_timer & (k_ev == KIND_NOTIFY)
    is_request = active & ~is_timer & (k_ev == KIND_REQUEST)
    is_response = active & ~is_timer & (k_ev == KIND_RESPONSE)
    do_update = active & (is_timer | is_notify | is_response)
    local_clock = t_ev - st.startup  # each node handles its own event time

    def per_node(a, s_a, pm_a, nx_a, cx_a, pay_row, lclk, ho_row, ho_ep):
        pay_in = unpack_payload(p, pay_row)
        s_n, should_sync = data_sync.handle_notification(p, s_a, st.weights, pay_in)
        s_r, nx_r, cx_r = data_sync.handle_response(p, s_a, nx_a, cx_a,
                                                    st.weights, pay_in)
        s_in = store_ops._sel(is_notify[a], s_n,
                              store_ops._sel(is_response[a], s_r, s_a))
        nx_in = store_ops._sel(is_response[a], nx_r, nx_a)
        cx_in = store_ops._sel(is_response[a], cx_r, cx_a)
        s_u, pm_u, nx_u, cx_u, actions = node_ops.update_node(
            p, s_in, pm_a, nx_in, cx_in, st.weights, a, lclk, dur_table)
        s_f = store_ops._sel(do_update[a], s_u, s_in)
        pm_f = store_ops._sel(do_update[a], pm_u, pm_a)
        nx_f = store_ops._sel(do_update[a], nx_u, nx_in)
        cx_f = store_ops._sel(do_update[a], cx_u, cx_in)
        notif = data_sync.create_notification(p, s_f, a)
        notif = store_ops._sel(st.byz_forge_qc[a],
                               _forged_qc_payload(p, s_f, a, notif), notif)
        request = data_sync.create_request(p, s_f)
        response = data_sync.handle_request(p, s_f, a, pay_in, notif=notif)
        resp_packed = pack_payload(response)
        if p.epoch_handoff:
            # Cross-epoch handoff (mirrors sim/simulator.py): capture the
            # pack update_node built from the post-update, pre-switch store;
            # serve it to requesters still in that epoch.
            switched = do_update[a] & actions.ho_switched
            ho_row = jnp.where(switched, actions.ho_pack, ho_row)
            ho_ep = jnp.where(switched, actions.ho_epoch, ho_ep)
            serve_ho = (is_request[a] & (pay_in.epoch == ho_ep)
                        & (pay_in.epoch < s_f.epoch_id))
            resp_row = jnp.where(serve_ho, ho_row, resp_packed)
        else:
            resp_row = resp_packed
        notif_p = pack_payload(notif)
        bank = jnp.stack([
            notif_p,
            pack_payload(_equivocate(p, notif)),
            pack_payload(request),
            resp_row,
        ])
        return s_f, pm_f, nx_f, cx_f, actions, should_sync, bank, ho_row, ho_ep

    (s_f, pm_f, nx_f, cx_f, actions, should_sync, banks, ho_pay,
     ho_epoch) = jax.vmap(per_node)(
        jnp.arange(n), st.store, st.pm, st.node, st.ctx, pay_rows, local_clock,
        st.ho_pay, st.ho_epoch)

    # ---- Outgoing candidates: [N senders, 2n+1 candidates].
    silent = st.byz_silent
    want_sync_req = is_notify & should_sync & ~silent
    want_response = is_request & ~silent
    cand0_want = want_sync_req | want_response
    cand0_kind = jnp.where(want_response, KIND_RESPONSE, KIND_REQUEST)
    cand0_recv = jnp.clip(sender, 0, n - 1)
    others = ~jnp.eye(n, dtype=bool)
    send_mask = actions.send_mask & others & do_update[:, None] & ~silent[:, None]
    query_mask = (actions.should_query_all & do_update & ~silent)[:, None] & others

    nc = 2 * n + 1
    want = jnp.concatenate([cand0_want[:, None], send_mask, query_mask], axis=1)
    kinds = jnp.concatenate([
        cand0_kind[:, None],
        jnp.full((n, n), KIND_NOTIFY, I32),
        jnp.full((n, n), KIND_REQUEST, I32),
    ], axis=1)
    recvs = jnp.concatenate([
        cand0_recv[:, None],
        jnp.broadcast_to(jnp.arange(n, dtype=I32), (n, n)),
        jnp.broadcast_to(jnp.arange(n, dtype=I32), (n, n)),
    ], axis=1)
    upper = (jnp.arange(n) * 2 >= n)[None, :]
    eq_sel = jnp.where(st.byz_equivocate[:, None] & upper, 1, 0)
    pay_sel = jnp.concatenate([
        jnp.where(want_response, 3, 2)[:, None],
        eq_sel,
        jnp.full((n, n), 2, I32),
    ], axis=1)

    # Per-sender stamps: node-local streams (ctr*N + node), disjoint across
    # nodes so rng draws are deterministic however windows interleave.
    pos = jnp.cumsum(want, axis=1) - 1
    timer_gap = jnp.where(do_update, 1, 0)
    local_idx = st.node_ctr[:, None] + pos + jnp.where(jnp.arange(nc)[None, :] > 0,
                                                       timer_gap[:, None], 0)
    stamps = local_idx * n + jnp.arange(n)[:, None]
    consumed = jnp.sum(want, axis=1) + timer_gap
    node_ctr = st.node_ctr + jnp.where(active, consumed, 0)

    u_delay = H.rng_u32(st.seed, stamps.astype(jnp.uint32))
    u_drop = H.mix32(u_delay, jnp.uint32(0x632BE59B))
    delays = jnp.maximum(delay_table[(u_delay >> (32 - TABLE_BITS)).astype(I32)],
                         d_min)
    dropped = want & (u_drop < st.drop_u32)
    arrive = t_ev[:, None] + delays  # sender's event time + latency
    go = want & ~dropped

    # ---- Route to receiver inboxes: flatten all M = N*(2n+1) candidates and
    # scatter each into its receiver's free slots, ranked in (sender,
    # candidate) order — deterministic regardless of window composition.
    M = n * nc
    flat_go = go.reshape(-1)
    flat_recv = recvs.reshape(-1)
    flat_kind = kinds.reshape(-1)
    flat_stamp = stamps.reshape(-1)
    flat_arrive = arrive.reshape(-1)
    flat_sender = jnp.broadcast_to(jnp.arange(n, dtype=I32)[:, None],
                                   (n, nc)).reshape(-1)
    flat_paysel = pay_sel.reshape(-1)

    recv_onehot = (flat_recv[None, :] == jnp.arange(n)[:, None]) & flat_go[None, :]
    rank2d = jnp.cumsum(recv_onehot, axis=1) - 1         # [N, M]
    rank_m = rank2d[flat_recv, jnp.arange(M)]            # [M] rank at receiver
    free = ~in_valid                                     # [N, IC]
    free_rank = jnp.cumsum(free, axis=1) - 1
    n_free = jnp.sum(free, axis=1)                       # [N]
    # slot_of_rank[r, k] = inbox slot holding receiver r's k-th free slot.
    slot_of_rank = jnp.full((n, ic), ic, I32).at[
        jnp.arange(n)[:, None], jnp.where(free, free_rank, ic)
    ].set(jnp.broadcast_to(jnp.arange(ic, dtype=I32), (n, ic)), mode="drop")
    overflow_m = flat_go & (rank_m >= jnp.minimum(n_free, ic)[flat_recv])
    place_m = flat_go & ~overflow_m
    slot_m = slot_of_rank[flat_recv, jnp.clip(rank_m, 0, ic - 1)]
    # Global scatter target over the flattened [N*IC] inbox; N*IC == dropped.
    g = jnp.where(place_m, flat_recv * ic + slot_m, n * ic)

    flat_pay = banks[flat_sender, flat_paysel]           # [M, F]

    in_valid2 = in_valid.reshape(-1).at[g].set(True, mode="drop").reshape(n, ic)
    in_time2 = st.in_time.reshape(-1).at[g].set(flat_arrive, mode="drop").reshape(n, ic)
    in_kind2 = st.in_kind.reshape(-1).at[g].set(flat_kind, mode="drop").reshape(n, ic)
    in_stamp2 = st.in_stamp.reshape(-1).at[g].set(flat_stamp, mode="drop").reshape(n, ic)
    in_sender2 = st.in_sender.reshape(-1).at[g].set(flat_sender, mode="drop").reshape(n, ic)
    in_pay2 = st.in_pay.reshape(n * ic, F).at[g].set(flat_pay, mode="drop").reshape(n, ic, F)

    # ---- Timer reschedule per active node (sat_add: see types.sat_add).
    next_g = sat_add(actions.next_sched, st.startup)
    timer_time = jnp.where(do_update, jnp.maximum(next_g, t_ev + 1), st.timer_time)

    delivered = jnp.sum(place_m)

    return st.replace(
        store=s_f, pm=pm_f, node=nx_f, ctx=cx_f,
        ho_pay=ho_pay, ho_epoch=ho_epoch,
        in_valid=in_valid2, in_time=in_time2, in_kind=in_kind2,
        in_stamp=in_stamp2, in_sender=in_sender2, in_pay=in_pay2,
        timer_time=timer_time,
        clock=jnp.where(live, clock, st.clock),
        node_ctr=node_ctr,
        halted=halt,
        n_events=st.n_events + jnp.where(live, jnp.sum(active), 0),
        n_msgs_sent=st.n_msgs_sent + jnp.where(live, delivered, 0),
        n_msgs_dropped=st.n_msgs_dropped + jnp.where(live, jnp.sum(dropped), 0),
        n_inbox_full=st.n_inbox_full + jnp.where(live, jnp.sum(flat_go & overflow_m), 0),
    )


def _equivocate(p: SimParams, pay):
    b = pay.prop_blk
    tag = store_ops.block_tag(
        pay.epoch, b.round, b.author, b.prev_round, b.prev_tag, b.time,
        b.cmd_proposer, b.cmd_index + EQUIV_SALT)
    return pay.replace(
        prop_blk=b.replace(cmd_index=b.cmd_index + EQUIV_SALT, tag=tag),
        vote=pay.vote.replace(valid=jnp.bool_(False)),
    )


@functools.lru_cache(maxsize=None)
def _compiled_run(p_structural: SimParams, num_steps: int, batched: bool):
    def run(delay_table, dur_table, d_min, st):
        def body(s, _):
            return step(p_structural, delay_table, dur_table, d_min, s), ()

        st, _ = jax.lax.scan(body, st, None, length=num_steps)
        return st

    if batched:
        run = jax.vmap(run, in_axes=(None, None, None, 0))
    return jax.jit(run, donate_argnums=(3,))


def make_run_fn(p: SimParams, num_steps: int, batched: bool = True,
                d_min: int | None = None):
    """``d_min`` overrides the lookahead (must be <= the true minimum message
    latency).  As long as no inbox overflows, any conservative value yields
    the SAME trajectories — narrower windows only mean more steps — which
    `tests/test_parallel_sim.py` asserts bit-exactly.  (Under overflow the
    window width changes which concurrent sends compete for free slots, so
    the discarded set — and hence the trajectory — may differ.)  The
    executable is memoized on ``p.structural()`` with the lookahead as a
    runtime scalar, so delay/drop/horizon variants share one compile."""
    dmin = d_min_of(p) if d_min is None else d_min
    assert 1 <= dmin <= d_min_of(p), (dmin, d_min_of(p))
    inner = _compiled_run(p.structural(), num_steps, batched)
    delay_table = jnp.asarray(p.delay_table())
    dur_table = jnp.asarray(p.duration_table())
    dmin_arr = jnp.asarray(dmin, I32)
    return lambda st: inner(delay_table, dur_table, dmin_arr, st)


def init_batch(p: SimParams, seeds) -> PSimState:
    seeds = jnp.asarray(seeds).astype(jnp.uint32)
    return jax.vmap(lambda s: init_state(p, s))(seeds)


def run_to_completion(p: SimParams, st: PSimState, chunk: int = 256,
                      max_chunks: int = 400, batched: bool = False):
    from .simulator import dedupe_buffers

    run = make_run_fn(p, chunk, batched=batched)
    st = dedupe_buffers(st)
    for _ in range(max_chunks):
        st = run(st)
        if bool(np.all(jax.device_get(st.halted))):
            break
    return st
