"""Byzantine fault injection and safety analysis (BASELINE config #4).

The attack surface is wired into the core simulator
(:mod:`~librabft_simulator_tpu.sim.simulator`):

* ``byz_equivocate[a]``: node *a* sends a *conflicting* proposal (different
  command, different hash) to the upper half of receivers — classic
  equivocation.  The V=2 variant tables make the conflict observable.
* ``byz_silent[a]``: node *a* crashes (never sends; still receives).
* ``byz_forge_qc[a]``: node *a*'s notifications carry a quorum-less forged
  QC on its own proposal (self-consistent tag, author-mask = itself);
  honest receivers reject it in ``insert_qc``'s vote-set re-verification
  (record_store.rs:371-387).

This module builds fault-masked fleets, runs f-sweeps, and checks the safety
invariant: no two honest nodes commit different state tags at the same depth
(agreement over SimulatedContext.committed_history,
/root/reference/bft-lib/src/simulated_context.rs:220).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import SimParams
from . import simulator as S


#: The attack-schedule registry: every per-slot Byzantine schedule the
#: scenario plane (serve/scenario.py) can select.  A scenario request names
#: one of these plus a fault count f (or explicit authors); the selector is
#: realized as the three per-instance [N] bool masks the engines already
#: carry in state, so a heterogeneous fleet mixes schedules per slot with
#: zero graph changes — the masks are traced data.
SCHEDULES = ("honest", "equivocate", "silent", "forge_qc")


def schedule_masks(p: SimParams, kind: str = "honest", f: int = 0,
                   authors=None):
    """(equivocate, silent, forge_qc) masks for a named attack schedule —
    the scenario plane's Byzantine selector.  ``"honest"`` is all-clear
    regardless of ``f``; the other kinds mark ``f`` authors (or the
    explicit ``authors``) faulty via :func:`byz_masks`."""
    if kind not in SCHEDULES:
        raise ValueError(
            f"unknown Byzantine schedule {kind!r}; want one of {SCHEDULES}")
    if kind == "honest":
        z = jnp.zeros((p.n_nodes,), jnp.bool_)
        return z, z, z
    return byz_masks(p, f, kind, authors)


def byz_masks(p: SimParams, f: int, kind: str = "equivocate", authors=None):
    """(equivocate, silent, forge_qc) masks marking ``f`` authors as faulty
    (default: the first ``f``).

    ``authors`` overrides which indices are faulty.  Note the leader schedule
    (config.leader_of_round) is a fixed pseudorandom sequence, so *which*
    author is faulty determines how early a 3-consecutive-honest-leader
    window exists — liveness timing depends on it, safety never does.
    """
    idx = np.arange(p.n_nodes)
    m = np.isin(idx, np.asarray(authors)) if authors is not None else idx < f
    eq = m if kind == "equivocate" else np.zeros_like(m)
    silent = m if kind == "silent" else np.zeros_like(m)
    forge = m if kind == "forge_qc" else np.zeros_like(m)
    return jnp.asarray(eq), jnp.asarray(silent), jnp.asarray(forge)


def init_fault_batch(p: SimParams, seeds, f: int, kind: str = "equivocate",
                     authors=None):
    eq, silent, forge = byz_masks(p, f, kind, authors)
    seeds = jnp.asarray(seeds).astype(jnp.uint32)
    return jax.vmap(
        lambda s: S.init_state(p, s, byz_equivocate=eq, byz_silent=silent,
                               byz_forge_qc=forge)
    )(seeds)


@jax.jit
def _safety_device(log_depth, log_tag, commit_count, honest):
    """Device-side agreement reduction: sort each instance's (depth, tag)
    commit entries lexicographically; a violation is two adjacent entries
    with equal depth and different tags.  O(NH log NH) per instance instead
    of the Python triple loop — this is what makes config #4's 10k-instance
    f-sweep checkable (simulated_context.rs:220 committed-history
    agreement)."""
    B, N, H = log_depth.shape
    valid = (jnp.arange(H)[None, None, :]
             < jnp.minimum(commit_count, H)[:, :, None]) & honest[None, :, None]
    depth = log_depth.reshape(B, N * H)
    tag = log_tag.reshape(B, N * H)
    v = valid.reshape(B, N * H)
    # Invalid entries get unique negative depths so they never collide.
    uniq = -1 - jnp.arange(N * H, dtype=jnp.int32)
    depth = jnp.where(v, depth, uniq[None, :])
    order = jnp.lexsort((tag, depth), axis=-1)
    d_s = jnp.take_along_axis(depth, order, axis=-1)
    t_s = jnp.take_along_axis(tag, order, axis=-1)
    conflict = (d_s[:, 1:] == d_s[:, :-1]) & (t_s[:, 1:] != t_s[:, :-1])
    return ~jnp.any(conflict, axis=-1)


def check_safety(st, honest_mask=None):
    """Per-instance safety: across nodes, committed tags agree at equal depth.

    Works on a batched SimState/PSimState ([B] leading dim).  Returns a bool
    [B] numpy array: True = safe.  Comparison covers the ring log (the last
    ``commit_log`` commits of each node), which bounds memory like the rest
    of the design.  Runs on device (see ``_safety_device``)."""
    N = st.ctx.log_depth.shape[1]
    if honest_mask is None:
        honest_mask = np.ones((N,), bool)
    safe = _safety_device(st.ctx.log_depth, st.ctx.log_tag,
                          st.ctx.commit_count, jnp.asarray(honest_mask))
    return np.asarray(jax.device_get(safe))


def check_safety_reference(st, honest_mask=None):
    """Pure-Python reference of :func:`check_safety` (kept for testing the
    device reduction)."""
    log_depth = np.asarray(jax.device_get(st.ctx.log_depth))  # [B, N, H]
    log_tag = np.asarray(jax.device_get(st.ctx.log_tag))
    commit_count = np.asarray(jax.device_get(st.ctx.commit_count))  # [B, N]
    B, N, H = log_depth.shape
    if honest_mask is None:
        honest_mask = np.ones((N,), bool)
    safe = np.ones((B,), bool)
    for b in range(B):
        seen: dict[int, int] = {}
        for a in range(N):
            if not honest_mask[a]:
                continue
            cc = int(commit_count[b, a])
            for i in range(max(cc - H, 0), cc):
                pos = i % H
                d, t = int(log_depth[b, a, pos]), int(log_tag[b, a, pos])
                if d in seen and seen[d] != t:
                    safe[b] = False
                seen[d] = t
    return safe


@dataclasses.dataclass
class SweepResult:
    f: int
    kind: str
    instances: int
    safe_fraction: float
    live_fraction: float   # fraction of instances with >=1 honest commit
    mean_commits: float


def f_sweep(p: SimParams, n_instances: int, f_values=None, kind: str = "equivocate",
            seed0: int = 0):
    """Sweep the number of faulty authors; returns per-f safety/liveness."""
    if f_values is None:
        f_values = list(range(0, p.n_nodes // 3 + 2))
    out = []
    for f in f_values:
        seeds = np.arange(seed0, seed0 + n_instances, dtype=np.uint32)
        st = init_fault_batch(p, seeds, f, kind)
        st = S.run_to_completion(p, st, batched=True)
        honest = np.arange(p.n_nodes) >= f
        safe = check_safety(st, honest)
        cc = np.asarray(jax.device_get(st.ctx.commit_count))[:, honest]
        live = (cc.max(axis=1) > 0)
        out.append(SweepResult(
            f=f, kind=kind, instances=n_instances,
            safe_fraction=float(safe.mean()),
            live_fraction=float(live.mean()),
            mean_commits=float(cc.mean()),
        ))
    return out
