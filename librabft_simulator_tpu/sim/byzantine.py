"""Byzantine fault injection and safety analysis (BASELINE config #4).

The attack surface is wired into the core simulator
(:mod:`~librabft_simulator_tpu.sim.simulator`):

* ``byz_equivocate[a]``: node *a* sends a *conflicting* proposal (different
  command, different hash) to the upper half of receivers — classic
  equivocation.  The V=2 variant tables make the conflict observable.
* ``byz_silent[a]``: node *a* crashes (never sends; still receives).

This module builds fault-masked fleets, runs f-sweeps, and checks the safety
invariant: no two honest nodes commit different state tags at the same depth
(agreement over SimulatedContext.committed_history,
/root/reference/bft-lib/src/simulated_context.rs:220).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import SimParams
from . import simulator as S


def byz_masks(p: SimParams, f: int, kind: str = "equivocate", authors=None):
    """Masks marking ``f`` authors as faulty (default: the first ``f``).

    ``authors`` overrides which indices are faulty.  Note the leader schedule
    (config.leader_of_round) is a fixed pseudorandom sequence, so *which*
    author is faulty determines how early a 3-consecutive-honest-leader
    window exists — liveness timing depends on it, safety never does.
    """
    idx = np.arange(p.n_nodes)
    m = np.isin(idx, np.asarray(authors)) if authors is not None else idx < f
    eq = m if kind == "equivocate" else np.zeros_like(m)
    silent = m if kind == "silent" else np.zeros_like(m)
    return jnp.asarray(eq), jnp.asarray(silent)


def init_fault_batch(p: SimParams, seeds, f: int, kind: str = "equivocate",
                     authors=None):
    eq, silent = byz_masks(p, f, kind, authors)
    seeds = jnp.asarray(seeds).astype(jnp.uint32)
    return jax.vmap(
        lambda s: S.init_state(p, s, byz_equivocate=eq, byz_silent=silent)
    )(seeds)


def check_safety(st, honest_mask=None):
    """Per-instance safety: across nodes, committed tags agree at equal depth.

    Works on a batched SimState ([B] leading dim).  Returns a bool [B] array:
    True = safe.  Comparison covers the ring log (the last ``commit_log``
    commits of each node), which bounds memory like the rest of the design.
    """
    log_depth = np.asarray(jax.device_get(st.ctx.log_depth))  # [B, N, H]
    log_tag = np.asarray(jax.device_get(st.ctx.log_tag))
    commit_count = np.asarray(jax.device_get(st.ctx.commit_count))  # [B, N]
    B, N, H = log_depth.shape
    if honest_mask is None:
        honest_mask = np.ones((N,), bool)
    safe = np.ones((B,), bool)
    for b in range(B):
        seen: dict[int, int] = {}
        for a in range(N):
            if not honest_mask[a]:
                continue
            cc = int(commit_count[b, a])
            for i in range(max(cc - H, 0), cc):
                pos = i % H
                d, t = int(log_depth[b, a, pos]), int(log_tag[b, a, pos])
                if d in seen and seen[d] != t:
                    safe[b] = False
                seen[d] = t
    return safe


@dataclasses.dataclass
class SweepResult:
    f: int
    kind: str
    instances: int
    safe_fraction: float
    live_fraction: float   # fraction of instances with >=1 honest commit
    mean_commits: float


def f_sweep(p: SimParams, n_instances: int, f_values=None, kind: str = "equivocate",
            seed0: int = 0):
    """Sweep the number of faulty authors; returns per-f safety/liveness."""
    if f_values is None:
        f_values = list(range(0, p.n_nodes // 3 + 2))
    out = []
    for f in f_values:
        seeds = np.arange(seed0, seed0 + n_instances, dtype=np.uint32)
        st = init_fault_batch(p, seeds, f, kind)
        st = S.run_to_completion(p, st, batched=True)
        honest = np.arange(p.n_nodes) >= f
        safe = check_safety(st, honest)
        cc = np.asarray(jax.device_get(st.ctx.commit_count))[:, honest]
        live = (cc.max(axis=1) > 0)
        out.append(SweepResult(
            f=f, kind=kind, instances=n_instances,
            safe_fraction=float(safe.mean()),
            live_fraction=float(live.mean()),
            mean_commits=float(cc.mean()),
        ))
    return out
