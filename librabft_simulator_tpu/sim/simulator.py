"""Batched discrete-event simulator: the jittable, vmappable step function.

Tensor re-expression of ``Simulator``/``loop_until``
(/root/reference/bft-lib/src/simulator.rs:26-476).  One :class:`SimState`
pytree holds one instance (N nodes + queue); ``step`` processes exactly one
event; ``jax.vmap(step)`` runs the fleet; ``lax.scan`` unrolls time;
``jax.jit`` compiles the whole thing.

Event selection replaces the BinaryHeap with a lexicographic argmin over
(time asc, kind desc, stamp asc) — the exact ordering of ScheduledEvent::cmp
(simulator.rs:149-161).  Timers live in one slot per node (equivalent to the
reference's ignore_scheduled_updates_until cancellation, simulator.rs:311-323).

Known, self-consistent divergences from the reference (the oracle replays the
same semantics, so parity holds):
  * receivers are enumerated in index order by default; set
    ``SimParams.shuffle_receivers`` for the reference's per-broadcast shuffle
    semantics (simulator.rs:343) via a seeded, oracle-replayable permutation;
  * notification/request payloads snapshot the post-update node state;
  * message drops and queue overflow (counted) replace unbounded heaps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..adversary import plane as aplane
from ..core import config, data_sync, node as node_ops, packing, \
    store as store_ops
from ..core.types import (
    adv_group_init,
    adv_heal_init,
    adv_link_init,
    adv_sched_init,
    KIND_NOTIFY,
    KIND_REQUEST,
    KIND_RESPONSE,
    KIND_TIMER,
    NEVER,
    Context,
    NodeExtra,
    Pacemaker,
    Payload,
    Queue,
    SimParams,
    SimState,
    Store,
    TracedParams,
    pack_payload,
    sat_add,
    sc_commit_init,
    sc_delay_init,
    unpack_payload,
)
from ..telemetry import ledger as tledger
from ..telemetry import plane as tplane
from ..telemetry import stream as tstream
from ..telemetry.profiling import scope
from ..utils import aot
from ..utils import hashing as H
from ..utils import xops
from ..utils.xops import scatter_set, wset
from ..utils.quantile import TABLE_BITS

I32 = jnp.int32
EQUIV_SALT = 1 << 20  # command-index offset of an equivocating second proposal


def _i32(x):
    return jnp.asarray(x, I32)


def _node_slice(tree, a):
    return jax.tree.map(lambda x: x[a], tree)


def _node_update(tree, a, new):
    return jax.tree.map(lambda x, v: wset(x, a, v), tree, new)


def init_state(p: SimParams, seed: int | jnp.ndarray, weights=None,
               byz_equivocate=None, byz_silent=None,
               byz_forge_qc=None) -> SimState:
    """Simulator::new (simulator.rs:200-250): per-node random startup times,
    initial timers at local time 0."""
    n = p.n_nodes
    seed = jnp.asarray(seed).astype(jnp.uint32)
    delay_table = jnp.asarray(p.delay_table())
    draws = jax.vmap(lambda c: H.rng_u32(seed, c.astype(jnp.uint32)))(jnp.arange(n))
    startup = delay_table[(draws >> (32 - TABLE_BITS)).astype(I32)] + 1
    if weights is None:
        weights = jnp.ones((n,), I32)
    if byz_equivocate is None:
        byz_equivocate = jnp.zeros((n,), jnp.bool_)
    if byz_silent is None:
        byz_silent = jnp.zeros((n,), jnp.bool_)
    if byz_forge_qc is None:
        byz_forge_qc = jnp.zeros((n,), jnp.bool_)
    from ..core.types import payload_width

    return SimState(
        store=Store.initial(p, (n,)),
        pm=Pacemaker.initial((n,)),
        node=NodeExtra.initial((n,)),
        ctx=Context.initial(p, (n,)),
        queue=Queue.initial(p),
        ho_pay=jnp.zeros(
            (n, p.handoff_epochs if p.epoch_handoff else 0, payload_width(p)),
            I32),
        ho_epoch=jnp.full(
            (n, p.handoff_epochs if p.epoch_handoff else 0), -1, I32),
        timer_time=startup.astype(I32),
        timer_stamp=jnp.arange(n, dtype=I32),
        startup=startup.astype(I32),
        weights=jnp.asarray(weights, I32),
        byz_equivocate=jnp.asarray(byz_equivocate, jnp.bool_),
        byz_silent=jnp.asarray(byz_silent, jnp.bool_),
        byz_forge_qc=jnp.asarray(byz_forge_qc, jnp.bool_),
        clock=_i32(0),
        stamp_ctr=_i32(n),
        halted=jnp.bool_(False),
        seed=seed,
        max_clock=_i32(p.max_clock),
        drop_u32=jnp.uint32(p.drop_u32),
        n_events=_i32(0),
        n_msgs_sent=_i32(0),
        n_msgs_dropped=_i32(0),
        n_queue_full=_i32(0),
        trace_node=jnp.zeros((p.trace_cap,), I32),
        trace_round=jnp.zeros((p.trace_cap,), I32),
        trace_time=jnp.zeros((p.trace_cap,), I32),
        trace_count=_i32(0),
        metrics=tplane.init_plane(p),
        flight=tplane.init_flight(p),
        wd=tstream.init_wd(p),
        sc_delay=sc_delay_init(p),
        sc_commit=sc_commit_init(p),
        adv_sched=adv_sched_init(p),
        adv_link=adv_link_init(p),
        adv_group=adv_group_init(p),
        adv_heal=adv_heal_init(p),
    )


def _select_event(p: SimParams, st: SimState):
    """Lexicographic (time, kind desc, stamp) argmin over messages + timers.

    ``SimParams.select_kernel`` picks the backend: plain-XLA masked
    reductions (default) or the fused Pallas kernel (ops/pallas_queue.py);
    all backends are bit-identical (tests/test_ops.py)."""
    cm = p.queue_cap
    msg_time = jnp.where(st.queue.valid, st.queue.time, NEVER)
    all_time = jnp.concatenate([msg_time, st.timer_time])
    all_kind = jnp.concatenate([st.queue.kind, jnp.full((p.n_nodes,), KIND_TIMER, I32)])
    all_stamp = jnp.concatenate([st.queue.stamp, st.timer_stamp])
    if p.select_kernel.startswith("pallas"):
        from ..ops.pallas_queue import select_events

        idx_b, tmin_b = select_events(
            all_time[None], all_kind[None], all_stamp[None], block_b=1,
            interpret=(p.select_kernel == "pallas_interpret"))
        idx = idx_b[0].astype(I32)
        return idx, tmin_b[0], idx >= cm
    t_min = jnp.min(all_time)
    c1 = all_time == t_min
    k_best = jnp.max(jnp.where(c1, all_kind, -1))
    c2 = c1 & (all_kind == k_best)
    s_best = jnp.min(jnp.where(c2, all_stamp, NEVER))
    idx = jnp.argmax(c2 & (all_stamp == s_best)).astype(I32)
    return idx, t_min, idx >= cm


def _equivocated_payload(p: SimParams, s_a, author, pay: Payload) -> Payload:
    """Second, conflicting proposal for Byzantine equivocation sweeps."""
    b = pay.prop_blk
    tag = store_ops.block_tag(
        s_a.epoch_id, b.round, b.author, b.prev_round, b.prev_tag, b.time,
        b.cmd_proposer, b.cmd_index + EQUIV_SALT,
    )
    return pay.replace(
        prop_blk=b.replace(cmd_index=b.cmd_index + EQUIV_SALT, tag=tag),
        vote=pay.vote.replace(valid=jnp.bool_(False)),
    )


def _forged_qc_payload(p: SimParams, s_a, author, pay: Payload) -> Payload:
    """Quorum-less forged QC for Byzantine sweeps: the attacker claims a QC on
    its own current-round proposal backed only by its own vote (author-bit
    mask = {author}), with a self-consistent content tag.  Every other insert
    check passes at the receiver, so this isolates the vote-set
    re-verification (insert_qc ``quorum_ok``) as the rejecting predicate —
    the attack the reference's per-vote checks exist to stop
    (record_store.rs:371-387)."""
    author = jnp.asarray(author, I32)
    bvar = jnp.maximum(s_a.proposed_var, 0)
    r = s_a.current_round
    sl = jnp.remainder(r, p.window)
    blk_tag_ = s_a.blk_tag[sl, bvar]
    own = (s_a.proposed_var >= 0) & (s_a.blk_author[sl, bvar] == author)
    exec_ok, st_d, st_t = store_ops.compute_state(p, s_a, r, bvar)
    cs_ok, cs_d, cs_t, _ = store_ops.vote_committed_state(p, s_a, r, bvar)
    lo = jnp.where(author < 32, jnp.uint32(1) << author.astype(jnp.uint32),
                   jnp.uint32(0))
    hi = jnp.where(author >= 32,
                   jnp.uint32(1) << jnp.maximum(author - 32, 0).astype(jnp.uint32),
                   jnp.uint32(0))
    tag = store_ops.qc_tag(s_a.epoch_id, r, blk_tag_, st_d, st_t,
                           cs_ok, cs_d, cs_t, lo, hi, author)
    forged = pay.hqc.replace(
        valid=own & exec_ok, epoch=s_a.epoch_id, round=r, blk_tag=blk_tag_,
        state_depth=st_d, state_tag=st_t,
        commit_valid=cs_ok, commit_depth=cs_d, commit_tag=cs_t,
        votes_lo=lo, votes_hi=hi, author=author, tag=tag,
    )
    return pay.replace(hqc=forged)


def step(p: SimParams, delay_table, dur_table, st: SimState) -> SimState:
    """Process one event of one instance (loop_until body, simulator.rs:380-468)."""
    n, cm, k_chain = p.n_nodes, p.queue_cap, p.chain_k
    # Scenario plane (SimParams.scenario; serve/scenario.py): the delay
    # table and commit-chain selector come from the instance's OWN traced
    # rows instead of the shared argument / static knob; ``pp`` is the
    # params view the protocol code sees (types.TracedParams — only
    # commit_chain is traced, everything else delegates).  Off (default):
    # ``pp is p`` and the graph is the exact static-knob lowering.
    if p.scenario:
        pp = TracedParams(p, st.sc_commit[0])
        delay_table = st.sc_delay
    else:
        pp = p
    with scope("event_select"):
        idx, t_min, is_timer = _select_event(p, st)
    halt = st.halted | (t_min > st.max_clock)
    live = ~halt
    clock = jnp.maximum(st.clock, jnp.minimum(t_min, NEVER - 1))
    midx = jnp.minimum(idx, cm - 1)
    kind = jnp.where(is_timer, _i32(KIND_TIMER), st.queue.kind[midx])
    a = jnp.where(is_timer, idx - cm, st.queue.receiver[midx]).astype(I32)
    a = jnp.clip(a, 0, n - 1)
    sender = st.queue.sender[midx]
    pay_in = unpack_payload(p, st.queue.payload[midx])
    # Consume the message slot.
    queue = st.queue.replace(
        valid=wset(st.queue.valid, midx, False, when=live & ~is_timer))

    # ---- Node slices.  Packed layout: one row gather + free slicing
    # (core/packing.py) instead of ~70 per-leaf gathers.
    if p.packed:
        s_a, pm_a, nx_a, cx_a = packing.unpack_node(p, st.planes[a])
    else:
        s_a = _node_slice(st.store, a)
        pm_a = _node_slice(st.pm, a)
        nx_a = _node_slice(st.node, a)
        cx_a = _node_slice(st.ctx, a)
    local_clock = clock - st.startup[a]

    # ---- Adversary plane decode (adversary/plane.py): windowed behavior
    # activations for the handled node, OR-composed onto the static byz_*
    # masks.  Keys are the event time, the instance's PRE-event count,
    # and the handled node's PRE-handler epoch — all values the oracle
    # replays exactly.  Off (default): compiled out entirely, and the
    # byz_* reads below are the exact historical graph.
    if p.adversary:
        adv_act = aplane.active_windows(st.adv_sched, clock, st.n_events,
                                        s_a.epoch_id)
        adv_eq, adv_sil, adv_forge = aplane.node_masks(st.adv_sched,
                                                       adv_act, a)
        eqv_a = st.byz_equivocate[a] | adv_eq
        silent_a = st.byz_silent[a] | adv_sil
        forge_a = st.byz_forge_qc[a] | adv_forge
    else:
        eqv_a = st.byz_equivocate[a]
        silent_a = st.byz_silent[a]
        forge_a = st.byz_forge_qc[a]

    # ---- Handlers, masked by kind.
    is_notify = live & ~is_timer & (kind == KIND_NOTIFY)
    is_request = live & ~is_timer & (kind == KIND_REQUEST)
    is_response = live & ~is_timer & (kind == KIND_RESPONSE)
    do_update = live & (is_timer | is_notify | is_response)

    with scope("data_sync_handlers"):
        if p.gate_handlers:
            # lax.cond short-circuits the payload handlers behind the kind
            # predicates: unbatched lowerings skip the wrong-kind subgraph
            # entirely (the 16.6 ms handle_response graph runs for the ~5% of
            # events that are responses); vmapped lowerings de-branch to the
            # same per-leaf select the explicit _sel form used, so the
            # trajectory is bit-identical either way.
            s_n, should_sync = jax.lax.cond(
                is_notify,
                lambda: data_sync.handle_notification(
                    pp, s_a, st.weights, pay_in),
                lambda: (s_a, jnp.bool_(False)))
            s_r, nx_r, cx_r = jax.lax.cond(
                is_response,
                lambda: data_sync.handle_response(
                    pp, s_a, nx_a, cx_a, st.weights, pay_in),
                lambda: (s_a, nx_a, cx_a))
        else:
            s_n, should_sync = data_sync.handle_notification(
                pp, s_a, st.weights, pay_in)
            s_r, nx_r, cx_r = data_sync.handle_response(
                pp, s_a, nx_a, cx_a, st.weights, pay_in)
        s_in = store_ops._sel(
            is_notify, s_n, store_ops._sel(is_response, s_r, s_a))
        nx_in = store_ops._sel(is_response, nx_r, nx_a)
        cx_in = store_ops._sel(is_response, cx_r, cx_a)

    with scope("node_update"):
        s_u, pm_u, nx_u, cx_u, actions = node_ops.update_node(
            pp, s_in, pm_a, nx_in, cx_in, st.weights, a, local_clock, dur_table
        )
    s_f = store_ops._sel(do_update, s_u, s_in)
    pm_f = store_ops._sel(do_update, pm_u, pm_a)
    nx_f = store_ops._sel(do_update, nx_u, nx_in)
    cx_f = store_ops._sel(do_update, cx_u, cx_in)

    # ---- Outgoing messages.
    notif = data_sync.create_notification(pp, s_f, a)
    notif = store_ops._sel(forge_a,
                           _forged_qc_payload(pp, s_f, a, notif), notif)
    notif_b = _equivocated_payload(pp, s_f, a, notif)
    request = data_sync.create_request(pp, s_f)
    response = data_sync.handle_request(pp, s_f, a, pay_in, notif=notif)
    resp_packed = pack_payload(response)
    if p.epoch_handoff:
        # Cross-epoch handoff (reference keeps ALL previous epochs' stores:
        # node.rs record_store_at, data_sync.rs:82-92; here a ring of E
        # bounded packed responses per node): update_node captured the
        # old-epoch pack at the switch (post-update, pre-switch store — the
        # commit-enabling QC is often minted in the same update); serve any
        # requester whose epoch matches a held pack.
        E = p.handoff_epochs
        switched = do_update & actions.ho_switched
        wslot = jnp.remainder(jnp.maximum(actions.ho_epoch, 0), E)
        rows_a = st.ho_pay[a]       # [E, F]
        eps_a = st.ho_epoch[a]      # [E]
        rows_a = wset(rows_a, wslot, actions.ho_pack, when=switched)
        eps_a = wset(eps_a, wslot, actions.ho_epoch, when=switched)
        ho_pay = wset(st.ho_pay, a, rows_a)
        ho_epoch = wset(st.ho_epoch, a, eps_a)
        rslot = jnp.remainder(jnp.maximum(pay_in.epoch, 0), E)
        serve_ho = (is_request & (eps_a[rslot] == pay_in.epoch)
                    & (pay_in.epoch < s_f.epoch_id))
        resp_row = jnp.where(serve_ho, rows_a[rslot], resp_packed)
    else:
        ho_pay, ho_epoch = st.ho_pay, st.ho_epoch
        resp_row = resp_packed
    # [4, F] packed bank: one row per candidate payload kind.
    payload_bank = jnp.stack([
        pack_payload(notif), pack_payload(notif_b),
        pack_payload(request), resp_row,
    ])

    silent = silent_a
    others = jnp.arange(n) != a
    # Candidate order fixes the stamp sequence: [sync-request or response] then
    # (timer stamp) then notifications then query-all requests.
    want_sync_req = is_notify & should_sync & ~silent
    want_response = is_request & ~silent
    cand0_want = want_sync_req | want_response
    cand0_kind = jnp.where(want_response, _i32(KIND_RESPONSE), _i32(KIND_REQUEST))
    cand0_recv = jnp.clip(sender, 0, n - 1)
    cand0_pay = jnp.where(want_response, _i32(3), _i32(2))

    send_mask = actions.send_mask & others & do_update & ~silent
    # Equivocators send the conflicting proposal to the upper index half.
    upper = (jnp.arange(n) * 2 >= n)
    notif_sel = jnp.where(eqv_a & upper, _i32(1), _i32(0))
    query_mask = jnp.where(actions.should_query_all & do_update & ~silent, others, False)

    if p.shuffle_receivers:
        # Seeded per-event receiver permutation (the reference shuffles
        # delivery order per broadcast, simulator.rs:343): receivers keep
        # their payload/mask but take the stamp — hence the delay draw — of
        # their permuted position.  Keyed off (seed, stamp_ctr) so the oracle
        # and C++ engine replay it exactly (stable argsort, ties by index).
        base = H.rng_u32(st.seed, jnp.asarray(st.stamp_ctr).astype(jnp.uint32))
        keys = jax.vmap(lambda i: H.mix32(base, i + jnp.uint32(1)))(
            jnp.arange(n, dtype=jnp.uint32))
        recv_order = jnp.argsort(keys, stable=True).astype(I32)
    else:
        recv_order = jnp.arange(n, dtype=I32)

    want = jnp.concatenate([cand0_want[None], send_mask[recv_order],
                            query_mask[recv_order]])
    kinds = jnp.concatenate([
        cand0_kind[None],
        jnp.full((n,), KIND_NOTIFY, I32),
        jnp.full((n,), KIND_REQUEST, I32),
    ])
    recvs = jnp.concatenate([cand0_recv[None], recv_order, recv_order])
    pay_sel = jnp.concatenate([cand0_pay[None], notif_sel[recv_order],
                               jnp.full((n,), 2, I32)])

    # Stamps: candidate 0, then one for the timer reschedule, then the rest.
    pos_in_want = jnp.cumsum(want) - 1
    timer_gap = jnp.where(do_update, 1, 0)
    stamps = st.stamp_ctr + pos_in_want + jnp.where(jnp.arange(2 * n + 1) > 0, timer_gap, 0)
    total_consumed = jnp.sum(want) + timer_gap
    timer_stamp_new = st.stamp_ctr + jnp.where(cand0_want, 1, 0)

    # Delays + drops (schedule_network_event, simulator.rs:266-269).
    u_delay = jax.vmap(lambda c: H.rng_u32(st.seed, c.astype(jnp.uint32)))(stamps)
    u_drop = jax.vmap(lambda c: H.mix32(c, jnp.uint32(0x632BE59B)))(u_delay)
    delays = delay_table[(u_delay >> (32 - TABLE_BITS)).astype(I32)]
    dropped = want & (u_drop < st.drop_u32)
    if p.adversary:
        # Network plane: per-link extra delay + windowed targeted /
        # leader-targeted delay on top of the drawn latency, and the
        # partition cut — a crossing message sent before the heal time
        # is dropped (counted with the rng drops).  Extras only ADD and
        # cuts only REMOVE, so the lane engine's lookahead bound is
        # unaffected; the serial engine has no lookahead to protect.
        recv_c = jnp.clip(recvs, 0, n - 1)
        leader = config.leader_of_round(st.weights, pm_f.active_round)
        delays = (delays
                  + jnp.clip(st.adv_link[a, recv_c], 0, aplane.DELAY_CAP)
                  + aplane.delay_extra(st.adv_sched, adv_act, recv_c,
                                       leader))
        cut = ((st.adv_group[a] != st.adv_group[recv_c])
               & (clock < st.adv_heal[0]))
        dropped = dropped | (want & cut)
    arrive = clock + delays

    # Free-slot assignment.
    go = want & ~dropped
    free = ~queue.valid
    n_free = jnp.sum(free)
    rank = jnp.cumsum(go) - 1
    free_rank = jnp.cumsum(free) - 1
    # slot_of_rank[r] = index of r-th free slot
    slot_of_rank = jnp.full((2 * n + 1,), -1, I32).at[
        jnp.where(free, free_rank, 2 * n + 1)
    ].set(jnp.arange(cm, dtype=I32), mode="drop")
    overflow = go & (rank >= n_free)
    # Sentinel cm is out-of-bounds => scatter mode="drop" discards it
    # (a -1 sentinel would WRAP to the last slot and corrupt the queue).
    tgt = jnp.where(go & ~overflow, slot_of_rank[jnp.clip(rank, 0, 2 * n)], _i32(cm))

    out_pay = payload_bank[pay_sel]  # [2n+1, F]
    # The 7 queue writes: .at[].set scatters on CPU (XLA executes them in
    # place after fusion), one-hot sum-selects under TPU lowering (scatters
    # serialize into per-kernel dispatch there; the payload form is a
    # matmul).  Bit-identical forms — see utils/xops.scatter_set.
    wmode = xops.backend_mode(p.dense_writes)
    with scope("queue_route"):
        queue = queue.replace(
            valid=scatter_set(queue.valid, tgt, True, mode=wmode),
            time=scatter_set(queue.time, tgt, arrive, mode=wmode),
            kind=scatter_set(queue.kind, tgt, kinds, mode=wmode),
            stamp=scatter_set(queue.stamp, tgt, stamps, mode=wmode),
            sender=scatter_set(queue.sender, tgt, a, mode=wmode),
            receiver=scatter_set(queue.receiver, tgt, recvs, mode=wmode),
            payload=scatter_set(queue.payload, tgt, out_pay, mode=wmode),
        )

    # ---- Timer reschedule (process_node_actions, simulator.rs:310-324).
    # sat_add: next_sched + startup without int32 wrap (== the wide-int
    # min(next + startup, NEVER) of the oracle and C++ engine), valid for
    # negative next_sched (pre-startup local times).
    next_g = sat_add(actions.next_sched, st.startup[a])
    new_timer = jnp.maximum(next_g, clock + 1)
    timer_time = wset(st.timer_time, a, new_timer, when=do_update)
    timer_stamp = wset(st.timer_stamp, a, timer_stamp_new, when=do_update)

    # ---- Round-switch trace (data_writer.rs:34-49): the handled node entered
    # a higher pacemaker round.  Ring write; compiled out when trace_cap == 0.
    switched = do_update & (pm_f.active_round > pm_a.active_round)
    trace_count = st.trace_count + jnp.where(switched, 1, 0)
    if p.trace_cap > 0:
        # Index == cap is out-of-bounds and dropped (a -1 sentinel would wrap).
        tpos = jnp.remainder(st.trace_count, p.trace_cap)
        trace_node = wset(st.trace_node, tpos, a, when=switched)
        trace_round = wset(st.trace_round, tpos, pm_f.active_round, when=switched)
        trace_time = wset(st.trace_time, tpos, clock, when=switched)
    else:
        trace_node, trace_round, trace_time = (
            st.trace_node, st.trace_round, st.trace_time)

    # ---- Consensus watchdog (telemetry/stream.py).  Elementwise updates
    # over the tiny [WD] plane only — no scalar scatters — and compiled out
    # entirely when SimParams.watchdog is off.
    if p.watchdog:
        with scope("watchdog"):
            wd = st.wd
            T = p.watchdog_stall_events
            # Liveness stall: processed events since the handled fleet last
            # advanced a pacemaker round (only the handled node can advance
            # in this event).  Trip once per crossing of the threshold.
            stall_ev0 = wd[tstream.WD_STALL_EV]
            stall_ev = jnp.where(switched, 0,
                                 stall_ev0 + jnp.where(live, 1, 0))
            stall_trip = (stall_ev0 < T) & (stall_ev >= T)
            # Queue-pressure saturation: post-write occupancy at capacity.
            qsat = live & (jnp.sum(queue.valid.astype(I32)) >= cm)
            # Sync-jump anomaly: the handled node jumped this event.
            sj_inc = jnp.where(live, cx_f.sync_jumps - cx_a.sync_jumps, 0)
            # Safety invariants, checked at commit time on the NEWEST
            # committed entry: (a) round regression inside this node's own
            # committed chain (epoch-aware via the depth-derived epoch —
            # rounds legitimately restart at an epoch switch); (b) a
            # conflicting commit at the same height: any OTHER node's log
            # holds the same depth under a different tag.  Other nodes'
            # rows are untouched this event, so st.ctx is current for them.
            committed_wd = live & (cx_f.commit_count > cx_a.commit_count)
            Hl = p.commit_log
            pos = jnp.remainder(jnp.maximum(cx_f.commit_count - 1, 0), Hl)
            pos2 = jnp.remainder(jnp.maximum(cx_f.commit_count - 2, 0), Hl)
            d_new, t_new = cx_f.log_depth[pos], cx_f.log_tag[pos]
            r_new, r_prev = cx_f.log_round[pos], cx_f.log_round[pos2]
            same_epoch = (d_new // p.commands_per_epoch
                          == cx_f.log_depth[pos2] // p.commands_per_epoch)
            regress = (committed_wd & (cx_f.commit_count >= 2) & same_epoch
                       & (r_new <= r_prev))
            ctx_all = (packing.unpack_node(p, st.planes)[3] if p.packed
                       else st.ctx)
            entry_ok = (jnp.arange(Hl)[None, :]
                        < jnp.minimum(ctx_all.commit_count, Hl)[:, None])
            conflict = committed_wd & jnp.any(
                (jnp.arange(n) != a)[:, None] & entry_ok
                & (ctx_all.log_depth == d_new)
                & (ctx_all.log_tag != t_new))
            wd_updates = dict(wd=jnp.stack([
                stall_ev,
                wd[tstream.WD_STALL] + stall_trip.astype(I32),
                wd[tstream.WD_QUEUE_SAT] + qsat.astype(I32),
                wd[tstream.WD_SYNC_JUMP] + sj_inc,
                wd[tstream.WD_SAFETY_CONFLICT] + conflict.astype(I32),
                wd[tstream.WD_ROUND_REGRESS] + regress.astype(I32),
            ]).astype(I32))
    else:
        wd_updates = {}

    # ---- Telemetry plane + flight recorder (telemetry/plane.py).  Every
    # update is a fusion-friendly elementwise form over the [M] plane;
    # compiled out entirely when SimParams.telemetry is off.
    if p.telemetry:
        with scope("telemetry"):
            m = st.metrics
            m = tplane.bump(p, m, "ev_notify", when=is_notify)
            m = tplane.bump(p, m, "ev_request", when=is_request)
            m = tplane.bump(p, m, "ev_response", when=is_response)
            m = tplane.bump(p, m, "ev_timer", when=live & is_timer)
            m = tplane.bump(p, m, "drops", jnp.sum(dropped), when=live)
            m = tplane.bump(p, m, "overflow", jnp.sum(overflow), when=live)
            m = tplane.bump(p, m, "sync_jumps",
                            cx_f.sync_jumps - cx_a.sync_jumps, when=live)
            # Queue pressure after this step's writes.
            depth_n = jnp.sum(
                queue.valid[:, None]
                & (queue.receiver[:, None] == jnp.arange(n)[None, :]),
                axis=0)
            qtot = jnp.sum(queue.valid)
            m = tplane.region_max(p, m, "node_depth_hwm", depth_n)
            m = tplane.region_max(p, m, "queue_hwm", qtot)
            # Round-switch latency: local-clock dwell time in the round the
            # handled node just left (both round_starts are node-local).
            rlat = jnp.maximum(pm_f.round_start - pm_a.round_start, 0)
            m = tplane.bump_hist(p, m, "round_lat_hist", rlat[None],
                                 switched[None])
            # Proposal -> commit latency of the newest committed entry
            # (global time; miss = block already rotated out of the window).
            committed = live & (cx_f.commit_count > cx_a.commit_count)
            cfound, clat = tplane.commit_latency(p, s_f, cx_f, st.startup,
                                                 clock)
            m = tplane.bump_hist(p, m, "commit_lat_hist", clat[None],
                                 (committed & cfound)[None])
            m = tplane.bump(p, m, "commit_lat_miss",
                            when=committed & ~cfound)
            # Flight recorder: one row per processed event, ring position
            # from the plane's fr_count slot.
            frc = tplane.read(p, m, "fr_count")
            row = jnp.stack([kind, a, clock, s_f.current_round,
                             qtot.astype(I32)])
            flight = wset(st.flight, jnp.remainder(frc, p.flight_cap), row,
                          when=live)
            m = tplane.bump(p, m, "fr_count", when=live)
        tel_updates = dict(metrics=m, flight=flight)
    else:
        tel_updates = {}

    if p.packed:
        # One plane-wide masked select replaces ~70 per-leaf writes.
        node_updates = dict(planes=wset(
            st.planes, a, packing.pack_node(p, s_f, pm_f, nx_f, cx_f)))
    else:
        node_updates = dict(
            store=_node_update(st.store, a, s_f),
            pm=_node_update(st.pm, a, pm_f),
            node=_node_update(st.node, a, nx_f),
            ctx=_node_update(st.ctx, a, cx_f),
        )
    return st.replace(
        **node_updates,
        **tel_updates,
        **wd_updates,
        queue=queue,
        ho_pay=ho_pay,
        ho_epoch=ho_epoch,
        timer_time=timer_time,
        timer_stamp=timer_stamp,
        clock=jnp.where(live, clock, st.clock),
        stamp_ctr=st.stamp_ctr + jnp.where(live, total_consumed, 0),
        halted=halt,
        n_events=st.n_events + jnp.where(live, 1, 0),
        n_msgs_sent=st.n_msgs_sent + jnp.where(live, jnp.sum(go & ~overflow), 0),
        n_msgs_dropped=st.n_msgs_dropped + jnp.where(live, jnp.sum(dropped), 0),
        n_queue_full=st.n_queue_full + jnp.where(live, jnp.sum(overflow), 0),
        trace_node=trace_node,
        trace_round=trace_round,
        trace_time=trace_time,
        trace_count=trace_count,
    )


@functools.lru_cache(maxsize=None)
def _compiled_step(p_structural: SimParams, batched: bool):
    f = functools.partial(step, p_structural)
    if batched:
        f = jax.vmap(f, in_axes=(None, None, 0))
    if p_structural.packed:
        # Callers keep the SimState API: the packed layout lives inside the
        # executable (pack on entry, unpack on exit — exact round-trip, so
        # chunked runs compose bit-identically with the unpacked engine).
        def g(dt, du, st):
            pst = f(dt, du, packing.pack_state(p_structural, st))
            return packing.unpack_state(p_structural, pst)
    else:
        g = f
    # Tables are arguments (not baked constants): one executable serves every
    # delay/drop/max_clock config with this structural shape.
    return jax.jit(lambda dt, du, st: g(dt, du, st), donate_argnums=(2,))


def make_step_fn(p: SimParams, batched: bool = True):
    """Compiled step over a [B, ...] batch of instances."""
    p = xops.resolve_params(p)
    inner = _compiled_step(p.structural(), batched)
    delay_table = jnp.asarray(p.delay_table())
    dur_table = jnp.asarray(p.duration_table())
    return lambda st: inner(delay_table, dur_table, st)


def step_fn_partial(p: SimParams):
    """Uncompiled single-instance step with tables bound (for callers that
    wrap it in their own transforms).  Resolves the 'auto' lowering fields
    like make_step_fn/make_run_fn, so all three entry points build the
    same graph from the same params — including the SimState-in/
    SimState-out contract when ``packed`` resolves on (pack/unpack wrap
    the step exactly as _compiled_step does)."""
    p = xops.resolve_params(p)
    delay_table = jnp.asarray(p.delay_table())
    dur_table = jnp.asarray(p.duration_table())
    f = functools.partial(step, p, delay_table, dur_table)
    if p.packed:
        return lambda st: packing.unpack_state(
            p, f(packing.pack_state(p, st)))
    return f


def macro_k_of(p: SimParams) -> int:
    """The resolved macro-step width (``SimParams.macro_k``; 1 when unset
    — callers that bypass xops.resolve_params still get the identity)."""
    return int(p.macro_k) if p.macro_k is not None else 1


def macro_step(p_structural: SimParams, delay_table, dur_table, st):
    """One dispatched unit of work: ``macro_k`` queue events via a fixed-K
    rolled inner ``lax.scan`` over :func:`step`.

    This is THE macro-step graph — ``_scan_run``'s chunk body, what the
    kernel census censuses per K rung, and what the graph audit walks for
    the ``tpu_shape_k{4,16}`` flavors — one definition so the measured,
    audited, and executed graphs can never drift apart.  K == 1 returns
    the bare :func:`step` with no wrapper at all, so the default lowers
    to the exact macro-free graph (the census/audit K=1-identity pins).

    Bit-exactness across K: already-halted instances and drained queues
    make inner iterations exact no-ops (every write in :func:`step` is
    gated on ``live = ~halted`` — the pre-halted fleet-padding idiom), so
    a K-macro chunk equals K single-event chunks leaf-for-leaf and the
    halt/digest poll only changes granularity, never trajectory.

    Why a ROLLED inner scan: the body is traced once, so compile time,
    jaxpr size, and the per-dispatch fusion count stay ~flat in K while
    each dispatch retires K events — fusions per event drops ~K-fold on
    the census (PERF_NOTES round 11).  Unrolling the inner scan instead
    pays ~K-fold compile and graph growth for no cross-event fusion (XLA
    will not fuse across sequentially-dependent steps; measured round
    11); the unroll interplay on real TPU dispatch is a tunnel-checklist
    re-measure.
    """
    k = macro_k_of(p_structural)
    if k == 1:
        return step(p_structural, delay_table, dur_table, st)

    def body(s, _):
        return step(p_structural, delay_table, dur_table, s), ()

    st, _ = jax.lax.scan(body, st, None, length=k)
    return st


def _scan_run(p_structural: SimParams, num_steps: int, batched: bool):
    """The raw (untransformed) chunk scan: ``num_steps`` macro-steps per
    instance (``num_steps * macro_k`` events), pack/unpack at the boundary
    when the packed layout is on."""
    packed = bool(p_structural.packed)

    def run(delay_table, dur_table, st):
        if packed:
            st = packing.pack_state(p_structural, st)

        def body(s, _):
            return macro_step(p_structural, delay_table, dur_table, s), ()

        st, _ = jax.lax.scan(body, st, None, length=num_steps)
        if packed:
            st = packing.unpack_state(p_structural, st)
        return st

    if batched:
        run = jax.vmap(run, in_axes=(None, None, 0))
    return run


@functools.lru_cache(maxsize=None)
def _compiled_run(p_structural: SimParams, num_steps: int, batched: bool):
    return jax.jit(_scan_run(p_structural, num_steps, batched),
                   donate_argnums=(2,))


@functools.lru_cache(maxsize=None)
def _compiled_digest_run(p_structural: SimParams, num_steps: int,
                         batched: bool):
    """The chunk scan returning ``(state, [D] digest)``: the single-chip
    twin of the sharded runner's poll contract (telemetry/stream.py) — one
    small in-graph vector summarizes the whole batch, so a host loop can
    observe progress without ever fetching a [B] plane."""
    run = _scan_run(p_structural, num_steps, batched)

    def f(delay_table, dur_table, st):
        st = run(delay_table, dur_table, st)
        return st, tstream.compute_digest(p_structural, st)

    return jax.jit(f, donate_argnums=(2,))


def make_scan_fn(p: SimParams, num_steps: int, batched: bool = True):
    """Uncompiled counterpart of :func:`make_run_fn`: the same chunk scan
    with tables bound but no ``jax.jit``, for callers that stage it under
    their own transform — the dp-fleet ``shard_map`` wrapping in
    ``parallel/sharded.py`` needs the untransformed scan so each shard
    compiles to its own independent while loop.  Resolves the 'auto'
    lowering fields exactly as make_run_fn does, so both entry points
    trace the same graph."""
    p = xops.resolve_params(p)
    run = _scan_run(p.structural(), num_steps, batched)
    delay_table = jnp.asarray(p.delay_table())
    dur_table = jnp.asarray(p.duration_table())
    return lambda st: run(delay_table, dur_table, st)


def make_run_fn(p: SimParams, num_steps: int, batched: bool = True,
                digest: bool = False):
    """lax.scan of ``num_steps`` macro-steps per instance (loop_until) —
    ``num_steps * macro_k`` events per dispatch (:func:`macro_step`;
    ``macro_k`` defaults to 1 = one event per step, the exact historical
    contract).

    The jitted executable is memoized on ``p.structural()`` — calls for
    params differing only in delay/drop/horizon reuse one compile.  The
    'auto' lowering fields (packed planes, dense writes, macro_k) are
    resolved against the active backend/env here, before memoization.
    ``digest=True`` returns ``st -> (st, [D] digest)``
    (telemetry/stream.py): the fleet health summary computed in-graph at
    the chunk boundary, so callers can observe progress with one small
    fetch instead of a [B] plane."""
    p = xops.resolve_params(p)
    ps = p.structural()
    maker = _compiled_digest_run if digest else _compiled_run
    inner = maker(ps, num_steps, batched)
    delay_table = jnp.asarray(p.delay_table())
    dur_table = jnp.asarray(p.duration_table())
    # AOT executable store (utils/aot.py): the first call per argument-
    # shape signature consults the store before the jit path traces — a
    # hit deserializes a ready executable (no trace/lower/compile), any
    # miss or staleness falls through to `inner` untouched.  The tables
    # are ARGUMENTS of the stored executable (exactly as they are of the
    # jit one), so one AOT entry serves every delay/drop config with
    # this structural shape.
    call = aot.wrap_jit(
        inner, (delay_table, dur_table), key=tledger.params_key(ps),
        engine="serial", flavor="digest" if digest else "run",
        num_steps=num_steps, batched=batched)
    # Host-side compile ledger (telemetry/ledger.py): the first call per
    # argument-shape signature is recorded keyed on the structural params,
    # with the true backend-compile seconds and the persistent-cache or
    # AOT-store (aot-hit/aot-stale) verdict.  Strictly host-side — the
    # traced graph is the same `inner` either way.
    return tledger.wrap_compile(
        call,
        key=tledger.params_key(ps), structural=repr(ps), engine="serial",
        n_nodes=p.n_nodes, num_steps=num_steps, batched=batched,
        digest=digest)


def dedupe_buffers(st):
    """Give every leaf its own buffer (jnp.zeros constants are cached and
    aliased across fields, which breaks buffer donation)."""
    return jax.tree.map(lambda x: jnp.array(x, copy=True), st)


# Default host-loop budget: events per dispatch x dispatch cap.  Shared by
# name with the dp-fleet sweep path (analysis/sweeps.py), which must run
# under the identical step cap for its rows to be comparable.
RUN_CHUNK = 256
RUN_MAX_CHUNKS = 400


def stream_completion(run, st, chunk, max_chunks, batched, stream,
                      events_per_step: int = 1):
    """The digest-poll host loop both engines' ``run_to_completion`` share
    (telemetry/stream.py contract): ``run`` is a digest-flavor chunk fn
    (``st -> (st, [D])``); each chunk's halt check reads the one fetched
    digest vector — never a ``[B]`` plane — and every digest feeds the
    recorder.  ``events_per_step`` is the macro width (serial engine's
    resolved ``macro_k``): the recorder's ``steps`` metadata stays
    per-instance EVENT-steps attempted, not dispatch counts — the digest's
    event/commit slots are true in-state counters regardless."""
    b_total = (int(jax.tree_util.tree_leaves(st)[0].shape[0])
               if batched else 1)
    lg = tledger.get()
    rid = lg.new_run("stream_completion", chunk_steps=chunk)
    for i in range(max_chunks):
        with lg.span(tledger.DISPATCH, run=rid, chunk=i):
            st, dg = run(st)
        with lg.span(tledger.POLL, run=rid, chunk=i):
            fetched = np.asarray(jax.device_get(dg))
        d = stream.record(fetched, steps=(i + 1) * chunk * events_per_step)
        if d["halted"] >= b_total:
            break
    return st


def run_to_completion(p: SimParams, st: SimState, chunk: int = RUN_CHUNK,
                      max_chunks: int = RUN_MAX_CHUNKS,
                      batched: bool = False, stream=None):
    """Host loop: run until every instance passes max_clock (for tests).
    ``chunk``/``max_chunks`` count macro-steps (``macro_k`` events each).

    ``stream`` (a telemetry/stream.TimelineRecorder) switches the loop to
    the digest contract: each chunk's halt check fetches the one [D]
    digest vector instead of the halted plane, and the recorder receives
    every digest — the single-chip flavor of run_sharded's live stream."""
    st = dedupe_buffers(st)
    from ..audit import sanitize
    if stream is not None:
        if sanitize.enabled():
            # Silently running the UNchecked stream loop under
            # LIBRABFT_CHECKIFY would let an operator conclude a state
            # passed invariants that were never evaluated — refuse loud.
            raise ValueError(
                "LIBRABFT_CHECKIFY=1 and stream= are mutually exclusive: "
                "the digest stream loop runs the unchecked chunk; unset "
                "the knob or drop the recorder")
        return stream_completion(
            make_run_fn(p, chunk, batched=batched, digest=True), st,
            chunk, max_chunks, batched, stream,
            events_per_step=macro_k_of(xops.resolve_params(p)))
    if sanitize.enabled():
        # LIBRABFT_CHECKIFY: run the checkify-instrumented debug build
        # (audit/sanitize.py) — bit-identical values, raises on the first
        # tripped state invariant.  Off (default) never reaches here.
        import sys as _sys
        return sanitize.checked_completion(
            p, st, chunk, max_chunks, batched, _sys.modules[__name__])
    run = make_run_fn(p, chunk, batched=batched)
    lg = tledger.get()
    rid = lg.new_run("run_to_completion", engine="serial", chunk_steps=chunk)
    for i in range(max_chunks):
        with lg.span(tledger.DISPATCH, run=rid, chunk=i):
            st = run(st)
        with lg.span(tledger.POLL, run=rid, chunk=i):
            halted = jax.device_get(st.halted)
        if np.all(halted):
            break
    return st


def init_batch(p: SimParams, seeds) -> SimState:
    """vmapped init over an array of instance seeds."""
    seeds = jnp.asarray(seeds).astype(jnp.uint32)
    return jax.vmap(lambda s: init_state(p, s))(seeds)
