"""Process-limit helpers importable BEFORE jax (stdlib only).

XLA/LLVM recursion while compiling or (de)serializing this repo's largest
scan programs can overflow the default 8 MB C stack.  The main thread's
stack grows on demand up to RLIMIT_STACK, so raising the soft limit before
the first compile is sufficient.  Shared by tests/conftest.py, bench.py and
__graft_entry__.py.
"""

from __future__ import annotations

DEFAULT_STACK_BYTES = 512 * 1024 * 1024


def raise_stack_limit(want: int = DEFAULT_STACK_BYTES) -> None:
    """Raise the RLIMIT_STACK soft limit to ``want`` (capped by the hard
    limit); a no-op on platforms or containers where that's not possible."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_STACK)
        if soft != resource.RLIM_INFINITY and soft < want:
            new_soft = want if hard == resource.RLIM_INFINITY \
                else min(want, hard)
            resource.setrlimit(resource.RLIMIT_STACK, (new_soft, hard))
    except (ImportError, ValueError, OSError):
        pass
