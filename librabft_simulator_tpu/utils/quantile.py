"""Integer quantile tables for random-delay distributions.

The reference samples network delays from a LogNormal on the host CPU
(/root/reference/bft-lib/src/simulator.rs:98-107).  Sampling transcendental
distributions in float32 on TPU risks 1-ulp divergence from the CPU oracle,
which would break byte-identical parity of whole simulation trajectories.

TPU-first redesign: distributions are compiled on the *host* in float64 into a
1024-entry integer inverse-CDF table; on device a sample is
``table[u >> 22]`` — one gather, bit-identical everywhere.  Pareto
(long-tail) and uniform tables use the same machinery (BASELINE configs #2/#3).
"""

from __future__ import annotations

import math

import numpy as np

TABLE_BITS = 10
TABLE_SIZE = 1 << TABLE_BITS  # 1024


def _quantile_points():
    # Midpoint rule keeps both tails finite.
    return (np.arange(TABLE_SIZE, dtype=np.float64) + 0.5) / TABLE_SIZE


def lognormal_table(mean: float, variance: float) -> np.ndarray:
    """Integer delays from LogNormal parameterized like RandomDelay::new
    (/root/reference/bft-lib/src/simulator.rs:99-107): given the mean and
    variance of the *delay* itself."""
    mu = math.log(mean / math.sqrt(1.0 + variance / (mean * mean)))
    sigma = math.sqrt(math.log(1.0 + variance / (mean * mean)))
    q = _quantile_points()
    # Inverse CDF of lognormal = exp(mu + sigma * probit(q))
    from statistics import NormalDist

    probit = np.array([NormalDist().inv_cdf(p) for p in q])
    vals = np.exp(mu + sigma * probit)
    return np.maximum(vals.astype(np.int64), 0).astype(np.int32)


def pareto_table(scale: float, alpha: float, cap: float = 1e6) -> np.ndarray:
    """Long-tail delays: Pareto(scale, alpha), capped (BASELINE config #3)."""
    q = _quantile_points()
    vals = scale / np.power(1.0 - q, 1.0 / alpha)
    vals = np.minimum(vals, cap)
    return np.maximum(vals.astype(np.int64), 0).astype(np.int32)


def uniform_table(low: float, high: float) -> np.ndarray:
    q = _quantile_points()
    vals = low + q * (high - low)
    return np.maximum(vals.astype(np.int64), 0).astype(np.int32)


def constant_table(value: int) -> np.ndarray:
    return np.full(TABLE_SIZE, int(value), dtype=np.int32)


def sample_from_table_np(table: np.ndarray, u32: int) -> int:
    """Host/oracle-side sampling; the JAX side is table[u >> 22] inline."""
    return int(table[(int(u32) & 0xFFFFFFFF) >> (32 - TABLE_BITS)])


# -- latency histogram buckets (telemetry/plane.py) --------------------------
#
# The telemetry metrics plane records round-switch and proposal->commit
# latencies as fixed-width geometric histograms: bucket b holds samples in
# [edges[b-1], edges[b]) with edges 1, 2, 4, ... — integer powers of two, so
# bucketing on device is a handful of compares (no float math, bit-identical
# everywhere) and the dynamic range covers one event tick up to the longest
# horizon any BASELINE config runs (2^14 ticks; larger samples land in the
# open-ended last bucket).

HIST_BUCKETS = 16


def histogram_edges(n_buckets: int = HIST_BUCKETS) -> np.ndarray:
    """Ascending bucket boundaries [1, 2, 4, ...] of length n_buckets - 1.

    bucket(x) = #edges <= x  (i.e. ``np.searchsorted(edges, x, "right")``),
    so bucket 0 is x < 1 (instantaneous) and the last bucket is open-ended."""
    return (2 ** np.arange(n_buckets - 1)).astype(np.int32)


def bucket_np(x, n_buckets: int = HIST_BUCKETS) -> np.ndarray:
    """Host-side bucketing (oracle + report decode); mirrors the device's
    ``sum(x >= edges)`` exactly."""
    edges = histogram_edges(n_buckets)
    return np.searchsorted(edges, np.asarray(x), side="right").astype(np.int64)


def histogram_quantile(counts, q: float) -> tuple[int, int]:
    """(lo, hi) bucket bounds containing the q-th sample of a histogram
    (inverted-CDF rank: the ceil(q * total)-th sample).  (-1, -1) if empty;
    ``hi`` of the open-ended last bucket is INT32_MAX.

    Canonical home of the decode-side quantile math (report.py delegates):
    the geometric buckets bound the true quantile rather than estimate it,
    and the observatory's jax-free rollups need the same fold without
    importing the telemetry package."""
    counts = np.asarray(counts, np.int64)
    total = int(counts.sum())
    if total == 0:
        return (-1, -1)
    rank = max(int(np.ceil(q * total)), 1)
    b = int(np.searchsorted(np.cumsum(counts), rank))
    edges = histogram_edges(len(counts))
    lo = int(edges[b - 1]) if b > 0 else 0
    hi = int(edges[b]) if b < len(edges) else 2**31 - 1
    return (lo, hi)


def make_table(kind: str, **kw) -> np.ndarray:
    if kind == "lognormal":
        return lognormal_table(kw.get("mean", 10.0), kw.get("variance", 4.0))
    if kind == "pareto":
        return pareto_table(kw.get("scale", 5.0), kw.get("alpha", 1.5), kw.get("cap", 1e6))
    if kind == "uniform":
        return uniform_table(kw.get("low", 5.0), kw.get("high", 15.0))
    if kind == "constant":
        return constant_table(kw.get("value", 10))
    raise ValueError(f"unknown delay distribution: {kind}")
