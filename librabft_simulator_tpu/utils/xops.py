"""Scatter-free scalar writes.

``wset(arr, idx, val)`` is ``arr.at[idx].set(val)`` for *scalar* indices,
expressed as a one-hot ``jnp.where`` instead of an XLA scatter.

Why this exists: on the axon TPU stack, a vmapped scalar scatter into a
small trailing dim followed by a select miscomputes for a data-dependent
subset of batch rows at B >= ~2048 (repro: scripts/tpu_scatter_bug_repro.py
— ``vmap(lambda b, a, o: where(o, b.at[a].set(True), b))`` disagrees with
CPU on ~18% of rows; int8 and gated-scatter variants fail too, the where
one-hot form is correct).  The serial engine's consensus state was silently
corrupted at bench scale (21 vs 34,144 commits at B=2048 x 192 events)
until every scalar store/node/queue write went through this form.  The
where form is also fusion-friendly on TPU: it removes a scatter kernel
boundary per write.

Semantics note: out-of-range (including negative) indices write NOTHING —
i.e. ``mode="drop"``, which is what every call site wants (sentinel
indices == array length express "skip this write").  This differs from
``.at[]``'s default clip-at-edge for negative indices; call sites clip
their indices where a write must always land.
"""

from __future__ import annotations

import jax.numpy as jnp


def wset(arr, idx, val, when=None):
    """``arr.at[idx].set(val)`` via one-hot where; scalar indices only.

    ``idx``: a scalar index into dim 0, or a tuple of scalars indexing the
    leading dims.  ``val`` must broadcast against the indexed slice shape.
    ``when`` (optional bool scalar) gates the whole write — replaces the
    ``jnp.where(cond, arr.at[i].set(v), arr)`` pattern (the exact shape
    the TPU miscompile hits).
    """
    idxs = idx if isinstance(idx, tuple) else (idx,)
    mask = jnp.bool_(True) if when is None else when
    for d, ix in enumerate(idxs):
        shape = [1] * arr.ndim
        shape[d] = arr.shape[d]
        mask = mask & (jnp.arange(arr.shape[d]).reshape(shape) == ix)
    # .at[].set casts the value to the array dtype; mirror that exactly so
    # call sites behave identically to the scatter they replace.
    return jnp.where(mask, jnp.asarray(val, arr.dtype), arr)
