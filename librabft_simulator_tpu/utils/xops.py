"""Scatter-free scalar writes.

``wset(arr, idx, val)`` is ``arr.at[idx].set(val)`` for *scalar* indices,
expressed as a one-hot ``jnp.where`` instead of an XLA scatter.

Why this exists: on the axon TPU stack, a vmapped scalar scatter into a
small trailing dim followed by a select miscomputes for a data-dependent
subset of batch rows at B >= ~2048 (repro: scripts/tpu_scatter_bug_repro.py
— ``vmap(lambda b, a, o: where(o, b.at[a].set(True), b))`` disagrees with
CPU on ~18% of rows; int8 and gated-scatter variants fail too, the where
one-hot form is correct).  The serial engine's consensus state was silently
corrupted at bench scale (21 vs 34,144 commits at B=2048 x 192 events)
until every scalar store/node/queue write went through this form.  The
where form is also fusion-friendly on TPU: it removes a scatter kernel
boundary per write.

Semantics note: out-of-range (including negative) indices write NOTHING —
i.e. ``mode="drop"``, which is what every call site wants (sentinel
indices == array length express "skip this write").  This differs from
``.at[]``'s default clip-at-edge for negative indices; call sites clip
their indices where a write must always land.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# Backend dispatch: pick lowering *forms*, not semantics.
#
# Round-5 full-step timings proved the dense one-hot forms LOSE on CPU
# (XLA CPU executes the original scatters in place after fusion; dense pays
# full-plane writes: 74 -> 104-124 ms) while the same shapes are right for
# TPU (scatters serialize into per-kernel dispatch there; the payload
# sum-select is matmul-shaped).  ``backend_mode`` resolves which form a
# write site lowers to; every form is bit-identical (tests/test_xops.py),
# so this is purely a lowering decision.
# ---------------------------------------------------------------------------

#: Environment override for A/B benching without touching SimParams.
MODE_ENV = "LIBRABFT_WRITE_MODE"

_VALID_MODES = ("scatter", "dense")


def backend_mode(override: str = "auto") -> str:
    """Resolve the write-form mode: ``"scatter"`` (proven ``.at[]`` forms,
    the CPU default) or ``"dense"`` (one-hot sum-select, the TPU default).

    Priority: explicit ``override`` (a ``SimParams`` field) > ``MODE_ENV``
    env var > ``jax.default_backend()``.  Resolve BEFORE memoizing a
    compiled step on ``SimParams.structural()`` so the cached executable
    matches the mode it was traced with."""
    if override != "auto":
        mode = override
    else:
        mode = os.environ.get(MODE_ENV, "").strip() or (
            "dense" if jax.default_backend() == "tpu" else "scatter")
    if mode not in _VALID_MODES:
        raise ValueError(f"unknown write mode {mode!r}; want one of "
                         f"{_VALID_MODES} or 'auto'")
    return mode


#: Environment override for the packed-plane layout (0/1); see
#: ``SimParams.packed``.
PACKED_ENV = "LIBRABFT_PACKED"


def _bool_env(name: str) -> bool | None:
    """Strict boolean env parse; unrecognized values raise instead of
    silently enabling (LIBRABFT_PACKED=off must not mean 'on')."""
    env = os.environ.get(name, "").strip().lower()
    if not env:
        return None
    if env in ("1", "true", "yes", "on"):
        return True
    if env in ("0", "false", "no", "off"):
        return False
    raise ValueError(f"{name}={env!r}: want one of 1/0, true/false, "
                     f"yes/no, on/off")


def packed_mode(override=None) -> bool:
    """Resolve the packed-plane layout flag: explicit ``SimParams.packed``
    > ``PACKED_ENV`` env var > backend default (True on TPU)."""
    if override is not None:
        return bool(override)
    env = _bool_env(PACKED_ENV)
    if env is not None:
        return env
    return jax.default_backend() == "tpu"


#: Environment override for handler gating (0/1); see
#: ``SimParams.gate_handlers``.
GATE_ENV = "LIBRABFT_GATE_HANDLERS"


def gate_mode(override=None) -> bool:
    """Resolve the handler-gating flag: explicit ``SimParams.gate_handlers``
    > ``GATE_ENV`` env var > backend default (True on TPU only — the CPU
    graph stays exactly the pre-PR lowering)."""
    if override is not None:
        return bool(override)
    env = _bool_env(GATE_ENV)
    if env is not None:
        return env
    return jax.default_backend() == "tpu"


#: Environment override for the serial engine's K-event macro-steps
#: (positive int); see ``SimParams.macro_k``.
MACRO_ENV = "LIBRABFT_MACRO_K"


def macro_mode(override=None) -> int:
    """Resolve the macro-step width: explicit ``SimParams.macro_k`` >
    ``MACRO_ENV`` env var > 1 (the exact macro-free graph).  Strict
    parse — a malformed or non-positive value raises instead of silently
    benching the wrong graph."""
    if override is not None:
        return int(override)
    env = os.environ.get(MACRO_ENV, "").strip()
    if not env:
        return 1
    try:
        k = int(env)
    except ValueError:
        raise ValueError(f"{MACRO_ENV}={env!r}: want a positive integer")
    if k < 1:
        raise ValueError(f"{MACRO_ENV}={env!r}: want a positive integer")
    return k


#: Environment override for the dispatch wrap (host|device); see
#: ``SimParams.wrap``.
WRAP_ENV = "LIBRABFT_WRAP"

_VALID_WRAPS = ("host", "device")


def wrap_mode(override=None) -> str:
    """Resolve the dispatch wrap: explicit ``SimParams.wrap`` >
    ``WRAP_ENV`` env var > ``"host"`` (the exact pre-ring contract).
    Strict parse — an unrecognized value raises instead of silently
    benching the wrong dispatch loop."""
    if override is not None:
        wrap = override
    else:
        wrap = os.environ.get(WRAP_ENV, "").strip() or "host"
    if wrap not in _VALID_WRAPS:
        raise ValueError(f"{WRAP_ENV}={wrap!r}: want one of {_VALID_WRAPS}")
    return wrap


#: Environment override for the device-wrap digest-ring depth (positive
#: int); see ``SimParams.ring_k``.
RING_ENV = "LIBRABFT_RING_K"

#: Ring depth when wrap="device" and neither SimParams.ring_k nor
#: RING_ENV picked one (the BENCH_RING ladder's knee on the CPU proxy).
DEFAULT_RING_K = 16


def ring_mode(override=None, wrap: str = "host"):
    """Resolve the digest-ring depth: explicit ``SimParams.ring_k`` >
    ``RING_ENV`` env var > ``DEFAULT_RING_K`` — but ALWAYS ``None`` when
    the resolved ``wrap`` is ``"host"``, so the host flavor's
    compile/AOT keys never vary with a stray ``RING_ENV``.  Strict
    parse, same contract as :func:`macro_mode`."""
    if wrap == "host":
        return None
    if override is not None:
        return int(override)
    env = os.environ.get(RING_ENV, "").strip()
    if not env:
        return DEFAULT_RING_K
    try:
        k = int(env)
    except ValueError:
        raise ValueError(f"{RING_ENV}={env!r}: want a positive integer")
    if k < 1:
        raise ValueError(f"{RING_ENV}={env!r}: want a positive integer")
    return k


def resolve_params(p):
    """Resolve the 'auto' lowering fields of a SimParams (``dense_writes``,
    ``packed``, ``gate_handlers``, ``macro_k``, ``wrap``, ``ring_k``)
    against the active backend and environment.  Engines call this at
    make-time, BEFORE ``structural()`` memoization, so every cached
    executable is keyed by the concrete forms it was traced with."""
    import dataclasses

    mode = backend_mode(p.dense_writes)
    packed = packed_mode(p.packed)
    gate = gate_mode(p.gate_handlers)
    macro = macro_mode(p.macro_k)
    wrap = wrap_mode(p.wrap)
    ring = ring_mode(p.ring_k, wrap=wrap)
    if (mode == p.dense_writes and packed == p.packed
            and gate == p.gate_handlers and macro == p.macro_k
            and wrap == p.wrap and ring == p.ring_k):
        return p
    return dataclasses.replace(p, dense_writes=mode, packed=packed,
                               gate_handlers=gate, macro_k=macro,
                               wrap=wrap, ring_k=ring)


def scatter_set(dst, idx, src, *, mode: str = "scatter"):
    """``dst.at[idx].set(src, mode="drop")`` over dim 0, in the requested
    lowering form.

    ``dst``: ``[M, ...]``; ``idx``: ``[K]`` int targets.  Both forms follow
    ``.at[]``'s index semantics exactly: values in ``[-M, 0)`` wrap, and
    anything else out of ``[0, M)`` — notably the sentinel ``idx == M``
    the queue's overflow path uses — writes nothing.  ``src``: scalar,
    ``[K]``, or ``[K, ...]`` rows.  Duplicate targets resolve last-wins in
    both forms (XLA CPU applies scatter updates in order; the dense form
    selects the highest matching source index).

    ``mode="dense"`` lowers to a one-hot select: a ``[M, K]`` hit matrix,
    a per-row winner, and a sum-select (matmul-shaped for row payloads) —
    no scatter kernel boundary, the form TPU wants.
    """
    if mode == "scatter":
        return dst.at[idx].set(src, mode="drop")
    m, k = dst.shape[0], idx.shape[0]
    idx = jnp.asarray(idx, jnp.int32)
    idx = jnp.where(idx < 0, idx + m, idx)  # .at[]'s negative-index wrap
    src = jnp.broadcast_to(jnp.asarray(src, dst.dtype), (k,) + dst.shape[1:])
    hit = idx[None, :] == jnp.arange(m, dtype=jnp.int32)[:, None]  # [M, K]
    # Last matching source wins (mirrors in-order scatter application).
    winner = jnp.max(jnp.where(hit, jnp.arange(k, dtype=jnp.int32)[None, :],
                               -1), axis=1)                        # [M]
    placed = winner >= 0
    onehot = (jnp.arange(k, dtype=jnp.int32)[None, :] == winner[:, None])
    if src.ndim == 2:
        # Row payloads: integer dot keeps it bit-exact; the one-hot matmul
        # is the MXU-shaped payload select from PERF_NOTES.md.
        val = jax.lax.dot_general(
            onehot.astype(jnp.int32),
            src.astype(jnp.int32) if src.dtype != jnp.int32 else src,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ).astype(dst.dtype)
        return jnp.where(placed[:, None], val, dst)
    if src.ndim > 2:
        # General trailing dims: per-row winner gather + masked select.
        # Not a current engine shape (queue leaves are [CM] / [CM, F]);
        # kept total so the dense form never works-on-CPU-only.
        val = src[jnp.maximum(winner, 0)]
        mask = placed.reshape((m,) + (1,) * (dst.ndim - 1))
        return jnp.where(mask, val, dst)
    if dst.dtype == jnp.bool_:
        val = jnp.sum(jnp.where(onehot, src[None, :].astype(jnp.int32), 0),
                      axis=1) != 0
    else:
        val = jnp.sum(jnp.where(onehot, src[None, :],
                                jnp.zeros((), dst.dtype)),
                      axis=1, dtype=dst.dtype)
    return jnp.where(placed, val, dst)


def wset(arr, idx, val, when=None):
    """``arr.at[idx].set(val)`` via one-hot where; scalar indices only.

    ``idx``: a scalar index into dim 0, or a tuple of scalars indexing the
    leading dims.  ``val`` must broadcast against the indexed slice shape.
    ``when`` (optional bool scalar) gates the whole write — replaces the
    ``jnp.where(cond, arr.at[i].set(v), arr)`` pattern (the exact shape
    the TPU miscompile hits).
    """
    idxs = idx if isinstance(idx, tuple) else (idx,)
    mask = jnp.bool_(True) if when is None else when
    for d, ix in enumerate(idxs):
        shape = [1] * arr.ndim
        shape[d] = arr.shape[d]
        mask = mask & (jnp.arange(arr.shape[d]).reshape(shape) == ix)
    # .at[].set casts the value to the array dtype; mirror that exactly so
    # call sites behave identically to the scatter they replace.
    return jnp.where(mask, jnp.asarray(val, arr.dtype), arr)
