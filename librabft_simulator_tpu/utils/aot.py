"""Ahead-of-time executable store: kill the compile tax at cold start.

Every host-side bottleneck left in this repo is XLA compilation, not
simulation: the round-12 runtime ledger pins fleet time-to-first-chunk at
42.1 s cold (32.6 s backend compile) vs 9.7 s with the persistent compile
cache — still 2x over the ROADMAP's < 5 s target, because a persistent-
cache hit re-pays trace + lower + cache retrieval every process.  But the
staged trace->lower->compile pipeline makes a compiled executable a pure
function of ``(structural params, argument shapes, backend, toolchain)``
— exactly the key the compile ledger (telemetry/ledger.py) already
records — so it can be built ONCE and shipped like any other build
product.  This module is that build product's store:

* **Entries** are ``jax.experimental.serialize_executable`` payloads
  (the XLA serialized executable + calling-convention pytrees) written
  as ``<store_key>.bin`` + a ``<store_key>.json`` sidecar (engine,
  flavor, structural key, shapes, compile seconds, toolchain stamp),
  aggregated into a ``manifest.json`` under an fcntl lock.  The
  directory is relocatable: build it on one container, ship it, point
  ``LIBRABFT_AOT_DIR`` at it on another with the same toolchain.
* **Keying**: ``store_key = sha1(params_key(SimParams.structural()),
  flavor meta (engine / digest / num_steps / mesh / wrap), argument
  aval signature, backend platform, device count)``.  The toolchain
  stamp (jax + jaxlib versions, utils/cache.py) is checked at LOAD
  time, not hashed into the key, so a foreign-toolchain entry is
  reported as ``aot-stale`` in the compile ledger instead of silently
  missing — the failure mode the round-11 re-baseline hit with the
  bare persistent cache.
* **Consult-before-trace** (:func:`wrap_jit`): the engines'
  ``make_run_fn`` / ``make_sharded_run_fn`` (and the checkify
  sanitizer build) route their jitted chunk through this wrapper.  On
  the first call per argument-shape signature it consults the store: a
  hit deserializes a ready executable (no trace, no lower, no XLA
  compile — recorded as ``aot-hit`` with the true load seconds) and a
  miss, version skew, corrupt file, or any load error falls back to
  the existing jit path UNTOUCHED (never a crash).  With
  ``LIBRABFT_AOT_WRITE=1`` a miss additionally exports the freshly
  compiled executable back into the store (``scripts/warm_cache.py``
  children are the build step; test suites never write).
* **Inertness**: ``LIBRABFT_AOT=0`` makes the wrapper a transparent
  pass-through to the exact jit callable — no store I/O, no graph
  difference (there is none either way: the store is strictly
  host-side dispatch plumbing; census budgets and graph-audit
  signatures are pinned unchanged by tests/test_aot.py).

Like telemetry/ledger.py, this module is in the source-lint S2 hot-loop
scope by registration: it wraps the fleet loop's dispatch entry and must
itself contain zero device syncs (deserialization is host work; the
loaded executable dispatches exactly like the jit one).

CLI (no jax import — safe anywhere)::

    python -m librabft_simulator_tpu.utils.aot --list [--dir DIR]

prints the manifest: every stored executable with engine, flavor,
shapes, compile seconds and toolchain stamp.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import sys
import threading
import time

from . import cache as _cache

#: Env knob: 0/off disables consulting the store entirely (the wrapper
#: becomes a transparent pass-through to the jit path).  Default on.
AOT_ENV = "LIBRABFT_AOT"

#: Env knob: the store directory (relocatable artifact).  Default below.
DIR_ENV = "LIBRABFT_AOT_DIR"

#: Env knob: 1 = export freshly compiled executables back into the store
#: on a miss (the warm_cache build-step children set this; suites never
#: write — serialize() in a long-running many-compile process risks the
#: jaxlib segfault warm_cache's docstring describes).
WRITE_ENV = "LIBRABFT_AOT_WRITE"

#: One store for every entry point, mirroring utils/cache.py's shared
#: persistent-cache default: warm_cache children write here and tier-1 /
#: bench / the CLI load from here unless LIBRABFT_AOT_DIR moves it.
DEFAULT_AOT_DIR = "/tmp/librabft_aot"

#: Store schema version: bumped when the entry payload or sidecar layout
#: changes; foreign versions are refused at load (clean jit fallback).
AOT_VERSION = 1

_lock = threading.Lock()
#: (store dir, store_key) -> loaded executable callable; one deserialize
#: per process however many wrappers consult the same entry.  Keyed by
#: dir as well so repointing LIBRABFT_AOT_DIR mid-process (tests, tools)
#: can never serve an executable from the previous store.
_LOADED: dict = {}
#: (store dir, store_key) -> verdict string for keys already probed and
#: not loadable ("aot-stale" / "aot-error" / "aot-miss"): saves repeated
#: disk probes.
_REFUSED: dict = {}


def _bool_knob(env: str, default: bool) -> bool:
    """Strict boolean env parse (the xops._bool_env contract, restated
    here jax-free for the CLI): unrecognized values raise instead of
    silently picking a side — LIBRABFT_AOT=of must not mean 'on'."""
    val = os.environ.get(env, "").strip().lower()
    if not val:
        return default
    if val in ("1", "true", "yes", "on"):
        return True
    if val in ("0", "false", "no", "off"):
        return False
    raise ValueError(f"{env}={val!r}: want one of 1/0, true/false, "
                     f"yes/no, on/off")


def enabled() -> bool:
    """Whether the store is consulted at all (``LIBRABFT_AOT``; default
    on — a missing/empty store is just a miss, so on is always safe)."""
    return _bool_knob(AOT_ENV, True)


def write_enabled() -> bool:
    """Whether misses export back into the store (``LIBRABFT_AOT_WRITE``;
    default off)."""
    return _bool_knob(WRITE_ENV, False)


def store_dir() -> str:
    return os.environ.get(DIR_ENV, "").strip() or DEFAULT_AOT_DIR


def reset_cache() -> None:
    """Drop the in-process load/refusal caches (tests: re-probe a store
    this process already consulted)."""
    with _lock:
        _LOADED.clear()
        _REFUSED.clear()


# ---------------------------------------------------------------------------
# Keying.
# ---------------------------------------------------------------------------


def _avals(leaves) -> tuple:
    """Hashable (shape, dtype) tuple per leaf — the cheap per-dispatch
    identity the wrapper memoizes on (no repr, no sha1)."""
    return tuple((tuple(getattr(l, "shape", ())),
                  str(getattr(l, "dtype", type(l).__name__)))
                 for l in leaves)


def _sig_of(avals: tuple, treedef) -> str:
    """The store-key digest of an aval tuple + treedef (paid once per
    distinct signature, not per dispatch)."""
    sig = repr(list(avals)) + str(treedef)
    return hashlib.sha1(sig.encode()).hexdigest()[:16]


def shape_signature(args) -> str:
    """Stable signature of a call's full argument avals: every leaf's
    (shape, dtype) plus the treedef — stronger than the ledger's cheap
    leading-leaf signature, because a loaded executable is called with
    exactly these avals and a collision would raise at dispatch."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    return _sig_of(_avals(leaves), treedef)


def store_key(params_key: str, sig: str, **key_meta) -> str:
    """The entry key: structural-params key + flavor meta (engine,
    digest/run, num_steps, mesh, wrap — everything baked into the
    executable besides the params) + argument-shape signature + backend
    platform + visible device count.  The toolchain stamp is deliberately
    NOT hashed in — see the module docstring (stale must be loud)."""
    import jax

    material = json.dumps(
        [params_key, sorted((k, str(v)) for k, v in key_meta.items()), sig,
         jax.default_backend(), jax.device_count()])
    return hashlib.sha1(material.encode()).hexdigest()[:16]


def _paths(key: str) -> tuple[str, str]:
    d = store_dir()
    return os.path.join(d, key + ".bin"), os.path.join(d, key + ".json")


# ---------------------------------------------------------------------------
# Load / save.
# ---------------------------------------------------------------------------


def lookup(key: str) -> tuple[str, dict | None]:
    """Probe the store for ``key`` WITHOUT deserializing: returns
    ``(verdict, sidecar)`` where verdict is ``"hit"`` (present, toolchain
    and process topology match), ``"stale"`` (present, foreign toolchain
    / store version / process count), or ``"miss"``."""
    bin_path, meta_path = _paths(key)
    if not (os.path.exists(bin_path) and os.path.exists(meta_path)):
        return "miss", None
    try:
        with open(meta_path) as f:
            side = json.load(f)
    except (OSError, ValueError):
        return "stale", None
    if side.get("aot_version") != AOT_VERSION:
        return "stale", side
    if side.get("toolchain") != _cache.toolchain():
        return "stale", side
    # Process-topology hazard: the store KEY hashes the GLOBAL device
    # count, but a serialized executable bakes in the per-process device
    # assignment — a store built single-host (8 devices, 1 process) and
    # a pod slice (2 processes x 4) collide on the key while the
    # executable is wrong for the topology.  The sidecar's process_count
    # (absent = 1, the pre-field builds, all single-process) makes that
    # LOUDLY aot-stale instead of silently wrong; single-process stores
    # stay valid everywhere single-process.
    import jax

    if int(side.get("process_count") or 1) != jax.process_count():
        return "stale", side
    return "hit", side


def _deserialize(bin_path: str, side: dict | None, out_tree_thunk=None):
    """Payload -> loaded executable.  Entries whose calling-convention
    out-tree could not be pickled (``trees: "retrace-out"`` — e.g. the
    checkify sanitizer's error pytree carries live traceback objects)
    rebuild it from ``out_tree_thunk`` (an abstract ``eval_shape`` trace
    of the live jit fn: seconds, and still no lower/backend compile)."""
    from jax.experimental import serialize_executable as se

    with open(bin_path, "rb") as f:
        payload = pickle.load(f)
    if side and side.get("trees") == "retrace-out":
        if out_tree_thunk is None:
            raise ValueError("retrace-out entry needs an out_tree_thunk")
        serialized, in_tree = payload
        return se.deserialize_and_load(serialized, in_tree,
                                       out_tree_thunk())
    return se.deserialize_and_load(*payload)


def load(key: str, out_tree_thunk=None):
    """Deserialize the stored executable for ``key``; returns the loaded
    callable or ``None`` (miss / stale / corrupt — every failure is a
    clean miss, never an exception out of this function).  The verdict and
    true load seconds are annotated onto the compile-ledger entry being
    attributed, if any (``aot-hit`` / ``aot-stale``)."""
    from ..telemetry import ledger as tledger

    ck = (store_dir(), key)
    with _lock:
        if ck in _LOADED:
            return _LOADED[ck]
        refused = _REFUSED.get(ck)
    if refused is not None:
        if refused != "aot-miss":
            tledger.get().annotate_compile(_aot="stale")
        return None
    verdict, side = lookup(key)
    if verdict == "miss":
        with _lock:
            _REFUSED[ck] = "aot-miss"
        return None
    if verdict == "stale":
        with _lock:
            _REFUSED[ck] = "aot-stale"
        tledger.get().annotate_compile(_aot="stale")
        return None
    bin_path, _ = _paths(key)
    t0 = time.perf_counter()
    try:
        loaded = _deserialize(bin_path, side, out_tree_thunk)
    except Exception:  # corrupt bytes, device mismatch, pickle skew, ...
        # A broken artifact must cost a fallback, never a crash: the jit
        # path is always behind us.  Classified stale so the ledger says
        # the store needs a rebuild rather than hiding the event.
        with _lock:
            _REFUSED[ck] = "aot-error"
        tledger.get().annotate_compile(_aot="stale")
        return None
    load_s = time.perf_counter() - t0
    with _lock:
        _LOADED[ck] = loaded
    tledger.get().annotate_compile(_aot="hit", aot_load_s=round(load_s, 6))
    return loaded


def save(skey: str, compiled, compile_s: float | None = None,
         **meta) -> str | None:
    """Serialize ``compiled`` (a jax ``Compiled``) into the store under
    store key ``skey`` with a metadata sidecar; refreshes
    ``manifest.json`` under an fcntl lock.  Returns the .bin path, or
    ``None`` on any failure (export is best-effort — a read-only or full
    disk must not break the run that compiled the executable)."""
    from jax.experimental import serialize_executable as se

    import jax

    bin_path, meta_path = _paths(skey)
    try:
        os.makedirs(store_dir(), exist_ok=True)
        payload = se.serialize(compiled)
        try:
            blob = pickle.dumps(payload)
            trees = "full"
        except Exception:
            # Some calling conventions carry unpicklable aux data in the
            # OUT tree (the checkify sanitizer's error pytree holds live
            # tracebacks).  Store the executable + in-tree only; the
            # loader rebuilds the out-tree from an abstract trace of the
            # live jit fn (see _deserialize).
            blob = pickle.dumps((payload[0], payload[1]))
            trees = "retrace-out"
        tmp = bin_path + ".tmp.%d" % os.getpid()
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, bin_path)
        side = {
            "aot_version": AOT_VERSION,
            "store_key": skey,
            "file": os.path.basename(bin_path),
            "size_bytes": os.path.getsize(bin_path),
            "toolchain": _cache.toolchain(),
            "trees": trees,
            "compile_s": (round(compile_s, 3)
                          if compile_s is not None else None),
            # Process topology: the key hashes only the GLOBAL device
            # count, so the sidecar records the full picture — lookup()
            # refuses a process-count mismatch (aot-stale), and the
            # local/global split plus the builder's index are the
            # operator's diagnosis when it does.
            "process_count": int(jax.process_count()),
            "process_index": int(jax.process_index()),
            "device_count_global": int(jax.device_count()),
            "device_count_local": int(jax.local_device_count()),
            **meta,
        }
        tmp = meta_path + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as f:
            json.dump(side, f, indent=1)
        os.replace(tmp, meta_path)
        _refresh_manifest()
        return bin_path
    except Exception:  # serialize refusal, pickle failure, disk trouble
        return None


def _flock_bounded(fobj, timeout_s: float = 30.0) -> None:
    """Exclusive flock with a hard deadline: spin ``LOCK_NB`` until the
    lock lands or ``timeout_s`` expires (``TimeoutError``).  A plain
    blocking ``LOCK_EX`` would let one crashed/wedged writer park every
    later build forever — the C1 concurrency rule
    (audit/concurrency_lint.py) pins this as the only flock form.
    Only contention errnos retry; a real flock failure (ENOTSUP on a
    filesystem without flock, EBADF) re-raises immediately instead of
    burning the deadline on a misdiagnosis."""
    import errno
    import fcntl

    deadline = time.monotonic() + timeout_s
    while True:
        try:
            fcntl.flock(fobj, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return
        except OSError as e:
            if e.errno not in (errno.EAGAIN, errno.EWOULDBLOCK,
                               errno.EACCES):
                raise
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"manifest lock not acquired within {timeout_s}s — "
                    "another writer is wedged holding "
                    f"{getattr(fobj, 'name', '?')}; remove the stale "
                    ".manifest.lock holder and rebuild") from None
            time.sleep(0.05)


def _refresh_manifest() -> None:
    """Rebuild ``manifest.json`` from the sidecars, serialized across
    concurrent writers with a DEADLINE-bounded fcntl lock (warm_cache
    children and bench rungs may export into one store back-to-back; a
    wedged holder times out loudly instead of hanging the build)."""
    import fcntl

    d = store_dir()
    lock_path = os.path.join(d, ".manifest.lock")
    with open(lock_path, "w") as lk:
        _flock_bounded(lk)
        try:
            entries = []
            for name in sorted(os.listdir(d)):
                if not name.endswith(".json") or name == "manifest.json":
                    continue
                try:
                    with open(os.path.join(d, name)) as f:
                        entries.append(json.load(f))
                except (OSError, ValueError):
                    continue  # a concurrent writer's half-landed sidecar
            doc = {
                "schema": "librabft_aot_store",
                "aot_version": AOT_VERSION,
                "toolchain": _cache.toolchain(),
                "entries": entries,
            }
            tmp = os.path.join(d, "manifest.json.tmp.%d" % os.getpid())
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, os.path.join(d, "manifest.json"))
        finally:
            fcntl.flock(lk, fcntl.LOCK_UN)


def read_manifest(d: str | None = None) -> dict | None:
    """Load ``manifest.json`` from a store dir (``None`` = the active
    one); returns ``None`` when absent.  jax-free."""
    path = os.path.join(d or store_dir(), "manifest.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _out_tree(jit_fn, args):
    """The jit fn's output PyTreeDef from an abstract trace (no lowering,
    no compile) — the loader's out-tree source for ``retrace-out``
    entries."""
    import jax

    return jax.tree_util.tree_structure(jax.eval_shape(jit_fn, *args))


def _reset_jax_compilation_cache() -> None:
    """Drop jax's process-wide persistent-cache latch (private API,
    guarded: on a jax that moved it, the export path degrades to relying
    on the verify-by-reload step to catch hydration damage)."""
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass


def _export(jit_fn, args, skey: str, key: str, sig: str, key_meta: dict):
    """Build-step miss path (``LIBRABFT_AOT_WRITE=1``): compile the chunk
    AOT-style, export it into the store, and return the executable to
    dispatch (``None`` on export failure — caller falls back to jit).

    Two hard-won rules:

    * the compile must BYPASS the persistent XLA compile cache — an
      executable hydrated from that cache re-serializes with its object
      code missing ("Symbols not found" at load; measured on this
      container's jaxlib 0.4.36), so exporting demands a full fresh
      backend compile, which is also what stamps honest compile seconds
      into the sidecar;
    * the written artifact is VERIFIED by deserializing it back before
      the entry is trusted — a store that silently accumulated broken
      entries would turn every future cold start into the fallback path
      with an ``aot-stale`` mystery.  Misses, stale and corrupt entries
      are all (re)written: the store must come out of a build current."""
    import jax

    from ..telemetry import ledger as tledger

    prev_cache = jax.config.jax_compilation_cache_dir
    t0 = time.perf_counter()
    try:
        if prev_cache:
            # Setting the dir alone is NOT enough: jax caches its
            # is-cache-used decision once per process, so a hydrating
            # read (the exact failure the bypass exists to avoid) would
            # still be served.  reset_cache() drops that latch; the
            # second reset after restore lets later compiles re-latch
            # onto the restored dir.
            jax.config.update("jax_compilation_cache_dir", None)
            _reset_jax_compilation_cache()
        compiled = jit_fn.lower(*args).compile()
    finally:
        if prev_cache:
            jax.config.update("jax_compilation_cache_dir", prev_cache)
            _reset_jax_compilation_cache()
    compile_s = time.perf_counter() - t0
    leaves = jax.tree_util.tree_leaves(args)
    arg_shapes = (f"{tuple(getattr(leaves[0], 'shape', ()))}x{len(leaves)}"
                  if leaves else "()")
    bin_path = save(skey, compiled, compile_s=compile_s, key=key,
                    shapes=sig, arg_shapes=arg_shapes, **key_meta)
    if bin_path is None:
        # Export failed (read-only/full store dir, serialize refusal):
        # still dispatch the fresh build, but leave the base compile
        # verdict standing — an aot-export verdict must mean an entry
        # actually landed (it is annotated only after save + verify).
        return compiled
    try:
        _, side = lookup(skey)
        _deserialize(bin_path, side,
                     out_tree_thunk=lambda: _out_tree(jit_fn, args))
    except Exception:
        # Unloadable artifact: withdraw it (both files + manifest) so a
        # future cold start misses cleanly instead of going stale-loud.
        for path in _paths(skey):
            try:
                os.remove(path)
            except OSError:
                pass
        try:
            _refresh_manifest()
        except OSError:
            pass
        return compiled
    with _lock:
        _LOADED[(store_dir(), skey)] = compiled
        _REFUSED.pop((store_dir(), skey), None)
    tledger.get().annotate_compile(_aot="export")
    return compiled


# ---------------------------------------------------------------------------
# The consult-before-trace wrapper.
# ---------------------------------------------------------------------------


def wrap_jit(jit_fn, prefix_args: tuple, key: str, **key_meta):
    """Wrap a jitted chunk runner so its first call per argument-shape
    signature consults the AOT store before the jit path traces.

    ``jit_fn`` is the memoized ``jax.jit`` callable; ``prefix_args`` are
    the closure-bound leading arguments the engine feeds it (delay/
    duration tables, lookahead scalar — empty for runners taking only the
    state); ``key`` is the structural-params key
    (telemetry.ledger.params_key) and ``key_meta`` the flavor fields
    (engine, digest, num_steps, mesh...) that complete the store key.

    Call semantics per shape signature:

    * store hit — deserialize once (module-wide cache), dispatch the
      loaded executable; ``aot-hit`` + load seconds land on the compile-
      ledger entry.
    * stale / corrupt / foreign-version — ``aot-stale`` on the ledger,
      then the untouched jit path.
    * miss — the untouched jit path; with ``LIBRABFT_AOT_WRITE=1`` the
      chunk is instead built AOT-style (``jit_fn.lower(args).compile()``
      — same graph, same donation) so the fresh executable can be
      serialized into the store, then dispatched.
    * ``LIBRABFT_AOT=0`` — transparent pass-through, checked per call so
      tests can toggle the knob on a live wrapper.

    The returned callable forwards ``lower``/``trace``/``eval_shape``
    and ``__wrapped__`` from ``jit_fn`` so AOT consumers (kernel census,
    graph audit) keep driving the real staging API.
    """
    per_sig: dict = {}
    sig_lock = threading.Lock()

    def resolve(args, avals, treedef):
        sig = _sig_of(avals, treedef)
        skey = store_key(key, sig, **key_meta)
        fn = load(skey, out_tree_thunk=lambda: _out_tree(jit_fn, args))
        if fn is None and write_enabled():
            fn = _export(jit_fn, args, skey, key, sig, key_meta)
        if fn is None:
            fn = jit_fn
        return fn

    def wrapped(*call_args):
        args = (*prefix_args, *call_args)
        if not enabled():
            return jit_fn(*args)
        import jax

        # One flatten per dispatch covers both the memo key and the
        # tracer check; the repr/sha1 store-key digest is paid only on
        # the first call per signature (resolve).
        leaves, treedef = jax.tree_util.tree_flatten(args)
        if any(isinstance(l, jax.core.Tracer) for l in leaves):
            # Tracer arguments mean some outer transform is TRACING
            # through this runner (e.g. the sharded wrap="jit" A/B form
            # jits over the engine's run fn): a loaded executable cannot
            # consume tracers, but the jit path inlines — route there.
            return jit_fn(*args)
        cache_key = (_avals(leaves), treedef, store_dir())
        with sig_lock:
            fn = per_sig.get(cache_key)
        if fn is None:
            fn = resolve(args, cache_key[0], treedef)
            with sig_lock:
                per_sig[cache_key] = fn
        return fn(*args)

    wrapped.__wrapped__ = jit_fn
    if not prefix_args:
        # The staging API is forwarded only when the wrapper's calling
        # convention matches jit_fn's (sharded/sanitize runners): with
        # bound prefix args, run.lower(st) would silently expect the
        # full (tables..., st) arity — better the pre-AOT AttributeError.
        for attr in ("lower", "trace", "eval_shape"):
            if hasattr(jit_fn, attr):
                setattr(wrapped, attr, getattr(jit_fn, attr))
    return wrapped


# ---------------------------------------------------------------------------
# CLI: list the store (no jax import).
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="List an AOT executable store's manifest")
    ap.add_argument("--list", action="store_true", help="print the manifest")
    ap.add_argument("--dir", default=None,
                    help=f"store directory (default ${DIR_ENV} or "
                         f"{DEFAULT_AOT_DIR})")
    args = ap.parse_args(argv)
    d = args.dir or store_dir()
    man = read_manifest(d)
    if man is None:
        print(f"aot: no manifest at {d} (store empty or not built — run "
              "scripts/warm_cache.py with LIBRABFT_AOT_WRITE=1)",
              file=sys.stderr)
        return 1
    tc = man.get("toolchain", {})
    entries = man.get("entries", [])
    total = sum(e.get("size_bytes", 0) for e in entries)
    print(f"# aot store {d}: {len(entries)} executables, "
          f"{total / 1e6:.1f} MB, toolchain "
          f"jax={tc.get('jax')} jaxlib={tc.get('jaxlib')}")
    for e in entries:
        # arg_shapes is the operator-readable form (leading leaf shape +
        # leaf count, like the compile ledger); `shapes` is the full aval
        # digest the store key hashes.  Older entries only carry the hash.
        shapes = e.get("arg_shapes") or e.get("shapes")
        print(f"  {e.get('store_key')} {e.get('engine', '?'):>16} "
              f"flavor={e.get('flavor', '?')} shapes={shapes} "
              f"compile_s={e.get('compile_s')} "
              f"{e.get('size_bytes', 0) / 1e6:.1f}MB")
    return 0


if __name__ == "__main__":
    sys.exit(main())
