"""Single-source setup for the persistent XLA compilation cache.

Four different hardcoded cache paths had accreted across entry points
(``/tmp/librabft_tpu_jax_cache`` in main.py/bench.py/tpu_ladder.py,
``/tmp/jax_cache`` in conftest.py/warm_cache.py/fuzz_parity.py/
component_profile.py, conditional setup in xplat_parity.py) — so the
suite and the warm path could compile the SAME executable into two
different caches and both run cold.  This helper is the one place the
cache is configured; every entry point calls it, and the
``LIBRABFT_COMPILE_CACHE`` knob (audit/knobs.py) moves or disables it for
all of them at once.

The canonical default is ``/tmp/jax_cache`` — the directory tier-1
(tests/conftest.py) has always used, so existing warmed executables stay
warm across this consolidation.
"""

from __future__ import annotations

import json
import os

CACHE_ENV = "LIBRABFT_COMPILE_CACHE"

#: One cache for every entry point: the tier-1 suite, warm_cache.py
#: children, bench.py, the CLI, and the fuzz/profile scripts all share it.
DEFAULT_CACHE_DIR = "/tmp/jax_cache"

#: Executables cheaper than this to compile are not worth the disk/serialize
#: round trip (the same threshold every call site used).
MIN_COMPILE_TIME_S = 1.0

#: Name of the toolchain stamp written into the cache dir.  XLA's own
#: cache keys incorporate the compiler version, so a jaxlib upgrade
#: invalidates every entry *silently* — the suite just goes cold and the
#: ledger reports bare persistent-misses (the round-11 re-baseline found
#: this the hard way).  The stamp makes it loud: on mismatch every miss
#: in the process is classified ``stale-toolchain`` instead.
STAMP_FILE = "TOOLCHAIN.json"

#: Set by :func:`setup_compile_cache` when the cache dir's stamp names a
#: different toolchain than this process (telemetry/ledger.py reads it to
#: classify the resulting misses).
_STALE_TOOLCHAIN: dict | None = None


def toolchain() -> dict:
    """The toolchain stamp: the versions a compiled executable is a pure
    function of (beyond params + shapes + backend).  Shared by the
    persistent-cache stamp here and the AOT store (utils/aot.py)."""
    import jax
    import jaxlib

    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__}


def stale_toolchain() -> dict | None:
    """The previous stamp when the persistent cache was built by another
    toolchain (``None`` = stamp matched or no cache).  A truthy value
    means every persistent-cache miss this process is really a
    ``stale-toolchain`` miss — the entries exist, keyed by a compiler
    that is gone."""
    return _STALE_TOOLCHAIN


def _stamp_cache_dir(d: str) -> None:
    """Record/verify the toolchain stamp in the cache dir; flips
    :func:`stale_toolchain` on mismatch and rewrites the stamp so the
    NEXT session sees a warm, correctly-stamped cache."""
    global _STALE_TOOLCHAIN
    path = os.path.join(d, STAMP_FILE)
    current = toolchain()
    prior = None
    try:
        with open(path) as f:
            prior = json.load(f)
    except (OSError, ValueError):
        prior = None
    if prior is not None and prior != current:
        _STALE_TOOLCHAIN = prior
    if prior != current:
        try:
            tmp = path + ".tmp.%d" % os.getpid()
            with open(tmp, "w") as f:
                json.dump(current, f)
            os.replace(tmp, path)
        except OSError:
            pass  # read-only cache dir: stamping is best-effort


def cache_dir() -> str | None:
    """The resolved cache directory: ``LIBRABFT_COMPILE_CACHE`` if set (a
    path), ``None`` if explicitly disabled (``0``/``off``/``none``), else
    the shared default."""
    raw = os.environ.get(CACHE_ENV, "").strip()
    if raw.lower() in ("0", "off", "none", "disabled"):
        return None
    return raw or DEFAULT_CACHE_DIR


def setup_compile_cache(force: bool = False) -> str | None:
    """Point jax at the shared persistent compile cache; returns the
    active directory (``None`` when disabled).

    Idempotent and polite by default: if some earlier code in the process
    already configured a cache dir (e.g. conftest.py owns it under
    pytest), ``force=False`` leaves it alone — repointing mid-session
    would split the session's compiles across two caches, exactly the
    drift this helper removes."""
    import jax

    d = cache_dir()
    if d is None:
        return None
    current = jax.config.jax_compilation_cache_dir
    if current and not force:
        _stamp_cache_dir(current)
        return current
    os.makedirs(d, exist_ok=True)
    _stamp_cache_dir(d)
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      MIN_COMPILE_TIME_S)
    return d
