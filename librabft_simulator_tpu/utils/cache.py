"""Single-source setup for the persistent XLA compilation cache.

Four different hardcoded cache paths had accreted across entry points
(``/tmp/librabft_tpu_jax_cache`` in main.py/bench.py/tpu_ladder.py,
``/tmp/jax_cache`` in conftest.py/warm_cache.py/fuzz_parity.py/
component_profile.py, conditional setup in xplat_parity.py) — so the
suite and the warm path could compile the SAME executable into two
different caches and both run cold.  This helper is the one place the
cache is configured; every entry point calls it, and the
``LIBRABFT_COMPILE_CACHE`` knob (audit/knobs.py) moves or disables it for
all of them at once.

The canonical default is ``/tmp/jax_cache`` — the directory tier-1
(tests/conftest.py) has always used, so existing warmed executables stay
warm across this consolidation.
"""

from __future__ import annotations

import os

CACHE_ENV = "LIBRABFT_COMPILE_CACHE"

#: One cache for every entry point: the tier-1 suite, warm_cache.py
#: children, bench.py, the CLI, and the fuzz/profile scripts all share it.
DEFAULT_CACHE_DIR = "/tmp/jax_cache"

#: Executables cheaper than this to compile are not worth the disk/serialize
#: round trip (the same threshold every call site used).
MIN_COMPILE_TIME_S = 1.0


def cache_dir() -> str | None:
    """The resolved cache directory: ``LIBRABFT_COMPILE_CACHE`` if set (a
    path), ``None`` if explicitly disabled (``0``/``off``/``none``), else
    the shared default."""
    raw = os.environ.get(CACHE_ENV, "").strip()
    if raw.lower() in ("0", "off", "none", "disabled"):
        return None
    return raw or DEFAULT_CACHE_DIR


def setup_compile_cache(force: bool = False) -> str | None:
    """Point jax at the shared persistent compile cache; returns the
    active directory (``None`` when disabled).

    Idempotent and polite by default: if some earlier code in the process
    already configured a cache dir (e.g. conftest.py owns it under
    pytest), ``force=False`` leaves it alone — repointing mid-session
    would split the session's compiles across two caches, exactly the
    drift this helper removes."""
    import jax

    d = cache_dir()
    if d is None:
        return None
    current = jax.config.jax_compilation_cache_dir
    if current and not force:
        return current
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      MIN_COMPILE_TIME_S)
    return d
