"""Deterministic integer hashing / RNG used across the TPU path and the oracle.

The reference simulator uses Rust's ``DefaultHasher`` + BCS bytes for record
hashing (``/root/reference/bft-lib/src/simulated_context.rs:238``) and
``Xoshiro256StarStar`` for random delays and author picking
(``/root/reference/bft-lib/src/configuration.rs:65``,
``/root/reference/bft-lib/src/simulator.rs:110``).

TPU-first redesign: everything is uint32 lane arithmetic (wrapping), built
from murmur3-style finalizer rounds.  The exact same functions are
re-implemented in pure Python in ``librabft_simulator_tpu/oracle/engine.py``
(masked with ``& 0xFFFFFFFF``), giving bit-identical results on CPU, TPU and
in the oracle — no float transcendentals, no 64-bit requirement on device.
"""

from __future__ import annotations

import jax.numpy as jnp

U32 = jnp.uint32
I32 = jnp.int32

# Domain-separation tags for record hashing (arbitrary odd constants).
TAG_BLOCK = 0x9E3779B1
TAG_VOTE = 0x85EBCA77
TAG_QC = 0xC2B2AE3D
TAG_TIMEOUT = 0x27D4EB2F
TAG_STATE = 0x165667B1
TAG_EPOCH = 0x5851F42D
TAG_LEADER = 0x2545F491
TAG_SEED = 0x9E447687


def _u32(x):
    if isinstance(x, (int, bool)):
        return U32(x & 0xFFFFFFFF)
    return jnp.asarray(x).astype(U32)


def mix32(h, x):
    """Fold one uint32 word ``x`` into accumulator ``h`` (murmur3 fmix rounds)."""
    h = _u32(h) ^ _u32(x)
    h = h * U32(0x9E3779B1)
    h = h ^ (h >> U32(16))
    h = h * U32(0x85EBCA6B)
    h = h ^ (h >> U32(13))
    h = h * U32(0xC2B2AE35)
    h = h ^ (h >> U32(16))
    return h


def fold(*words):
    """Hash a sequence of uint32-like words into a single uint32 tag."""
    h = U32(0x811C9DC5)
    for w in words:
        h = mix32(h, w)
    return h


def rng_u32(seed, counter):
    """Counter-based uniform uint32: stream ``seed``, index ``counter``.

    Replaces the reference's sequential Xoshiro stream
    (/root/reference/bft-lib/src/simulator.rs:32) with a counter-based design
    so draws are order-independent within a jitted step and can be replayed
    exactly by the oracle.
    """
    return fold(TAG_SEED, seed, counter)


def rng_u32_pair(seed, counter):
    """Two independent uint32 draws for one counter (delay + drop decision)."""
    a = fold(TAG_SEED, seed, counter)
    b = mix32(a, U32(0x632BE59B))
    return a, b


def state_tag_next(prev_tag, cmd_proposer, cmd_index, time):
    """Rolling ledger-state hash: executing one command on top of prev state.

    Capability analog of SimulatedLedgerState::key()
    (/root/reference/bft-lib/src/simulated_context.rs:51): the reference hashes
    the whole execution history; we keep a rolling (depth, tag) pair instead.
    """
    return fold(TAG_STATE, prev_tag, _u32(cmd_proposer), _u32(cmd_index), _u32(time))


def epoch_initial_tag(epoch_id):
    """Initial QC 'hash' for an epoch (reference: hash(&epoch_id),
    /root/reference/librabft-v2/src/node.rs:116)."""
    return fold(TAG_EPOCH, _u32(epoch_id))


def initial_state_tag():
    """Tag of the empty ledger state (reference: hash of empty history)."""
    return fold(TAG_STATE, U32(0))
