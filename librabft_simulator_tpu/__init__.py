"""librabft_simulator_tpu: a TPU-native batched discrete-event simulator for
BFT consensus protocols (LibraBFTv2 + pluggable commit rules), with the
capabilities of novifinancial/librabft_simulator re-designed for JAX/XLA.
"""

__version__ = "0.2.0"
