"""Parameter sweeps over the BASELINE.json benchmark configs.

Config 1: LibraBFTv2, 3 nodes, 1 instance, default (lognormal) delays.
Config 2: 4 nodes, 10k instances, uniform delay.
Config 3: 64 nodes, 1k instances, Pareto delay + 5% drop.
Config 4: f equivocating authors swept over f in [0, n/3], 10k instances.
Config 5: two-chain HotStuff variant, 16 nodes, 10k instances.

Each sweep returns/records JSON-serializable dicts; the CLI entry point is
``python -m librabft_simulator_tpu.analysis.sweeps``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

import jax
import numpy as np

from ..core.types import SimParams
from ..sim import byzantine as B
from ..sim import parallel_sim as P
from ..sim import simulator as S
from ..telemetry import ledger as tledger


def _fleet_stats(p: SimParams, st, elapsed: float) -> dict:
    g = lambda x: np.asarray(jax.device_get(x))  # noqa: E731
    cc = g(st.ctx.commit_count)
    cur = g(st.store.current_round)
    if cc.ndim == 1:  # unbatched
        cc = cc[None]
        cur = cur[None]
    rounds = (cur.max(axis=-1) - 1).sum()
    out = {
        "instances": int(cc.shape[0]),
        "n_nodes": p.n_nodes,
        "total_commits": int(cc.sum()),
        "mean_commits_per_node": float(cc.mean()),
        "min_commits": int(cc.min()),
        "total_rounds": int(rounds),
        "elapsed_s": round(elapsed, 3),
        "rounds_per_sec": round(float(rounds) / elapsed, 1) if elapsed else None,
        "msgs_sent": int(g(st.n_msgs_sent).sum()),
        "msgs_dropped": int(g(st.n_msgs_dropped).sum()),
        # Shared-queue overflow (serial) / per-receiver inbox overflow
        # (parallel) — same fidelity meaning: sends lost to capacity.
        "queue_full": int(g(st.n_queue_full if hasattr(st, "n_queue_full")
                            else st.n_inbox_full).sum()),
        "sync_jumps": int(g(st.ctx.sync_jumps).sum()),
    }
    if p.telemetry:
        # Merged in-graph telemetry (event-kind counts, queue pressure,
        # latency quantile bounds) rides along on every sweep row.
        from ..telemetry import report as tel_report

        out["telemetry"] = tel_report.telemetry_block(p, st)
    return out


def run_config(p: SimParams, n_instances: int, seed0: int = 0,
               f: int = 0, byz_kind: str = "equivocate", engine=S,
               dp: int = 0, stream=None) -> dict:
    """``dp > 0`` runs the config on a dp-shard device mesh via the
    pipelined fleet runtime (parallel/sharded.py): the instance batch is
    padded to the device count with pre-halted instances (zero effect on
    every reported stat) and each shard dispatches its own chunk loop.

    ``stream`` (a telemetry/stream.TimelineRecorder) receives the
    per-chunk fleet-health digest on BOTH paths — the sharded runtime's
    halt poll carries it for free; the single-device loop switches its
    halt check to the same one-[D]-fetch contract — and the row gains the
    recorder's timeline summary."""
    seeds = np.arange(seed0, seed0 + n_instances, dtype=np.uint32)
    if f > 0:
        if engine is not S:
            raise NotImplementedError(
                "byzantine fault batches build serial SimStates "
                "(byzantine.init_fault_batch); run f>0 sweeps on the "
                "serial engine")
        st = B.init_fault_batch(p, seeds, f, byz_kind)
    else:
        st = engine.init_batch(p, seeds)
    if dp > 0:
        from ..parallel import mesh as mesh_ops
        from ..parallel import sharded

        mesh = mesh_ops.make_mesh(n_dp=dp, n_mp=1,
                                  devices=jax.devices()[:dp])
        # Mirror run_to_completion's own default budget (RUN_CHUNK x
        # RUN_MAX_CHUNKS) so dp and non-dp rows of one sweep run under
        # identical step caps and their stats stay comparable.
        chunk = engine.RUN_CHUNK
        with tledger.get().span(tledger.RUN, what="sweep_config",
                                dp=dp) as sp:
            st = sharded.run_sharded(
                p, mesh, st, num_steps=chunk * engine.RUN_MAX_CHUNKS,
                chunk=chunk, engine=engine, stream=stream)
            # The pipelined loop returns with the last chunk possibly
            # still in flight; sync before reading the clock or elapsed
            # understates.
            jax.block_until_ready(jax.tree_util.tree_leaves(st)[0])
        elapsed = sp.dur_s
    else:
        with tledger.get().span(tledger.RUN, what="sweep_config") as sp:
            st = engine.run_to_completion(p, st, batched=True, stream=stream)
        elapsed = sp.dur_s
    out = _fleet_stats(p, st, elapsed)
    if stream is not None:
        out["stream"] = stream.summary()
    if dp > 0:
        out["dp"] = dp
    if f > 0:
        honest = np.arange(p.n_nodes) >= f
        out["f"] = f
        out["byz_kind"] = byz_kind
        out["safe_fraction"] = float(B.check_safety(st, honest).mean())
    return out


def baseline_configs(scale: float = 1.0) -> dict:
    """The five BASELINE.json configs; ``scale`` shrinks instance counts for
    quick runs (scale=1.0 reproduces the stated sizes)."""
    k = lambda n: max(int(n * scale), 1)  # noqa: E731
    return {
        "1_default_3node": (SimParams(n_nodes=3, max_clock=1000), k(1), 0),
        "2_uniform_4node_10k": (
            SimParams(n_nodes=4, max_clock=1000, delay_kind="uniform"), k(10000), 0),
        # Wide fleets run on the lane-compacted parallel engine — the
        # faithful option at n >= 16 (per-receiver inboxes; the serial
        # shared queue needs O(n^2) capacity to stop overflowing).
        "3_pareto_drop_64node_1k": (
            SimParams(n_nodes=64, max_clock=1000, delay_kind="pareto",
                      drop_prob=0.05), k(1000), "parallel"),
        "4_byzantine_sweep_10k": (
            SimParams(n_nodes=4, max_clock=1000), k(10000), "sweep"),
        # inbox_cap 1024 (~64n): run-to-completion depth holds ~60n msgs in
        # flight per node at peaks for this uniform-delay 2-chain shape
        # (measured: 256 -> 7% loss, 1024 -> 0 over 2.9M msgs).  ~4.6 MB per
        # instance: lossless at analysis scales; a full 10k-instance fleet
        # (~46 GB) falls back to the bench-regime 256 (overflow is counted
        # and reported in ``queue_full``) — shard over dp for both.
        "5_hotstuff2_16node_10k": (
            SimParams(n_nodes=16, max_clock=1000, commit_chain=2,
                      inbox_cap=1024 if k(10000) <= 2000 else 256),
            k(10000), "parallel"),
    }


def run_all(scale: float = 1.0, out_path: str | None = None,
            telemetry: bool = False, dp: int = 0,
            stream_out: str | None = None, watchdog: bool = False,
            macro_k: int = 0) -> dict:
    """``stream_out`` streams every non-sweep config's per-chunk digest
    timeline as NDJSON — one file per config, ``{stem}.{config}.ndjson``
    (watch any of them live with scripts/fleet_watch.py) — and attaches
    the timeline summary to the config's result row.  ``macro_k > 0``
    arms the serial engine's K-event macro-steps on the serial-engine
    configs (the lane configs keep their horizon windows — macro_k is a
    serial-engine knob and the lane engine refuses it); the run budget
    stays RUN_CHUNK x RUN_MAX_CHUNKS macro-steps, i.e. K-fold more
    events, with trajectories bit-identical per instance."""
    results = {}
    for name, (p, n, f_mode) in baseline_configs(scale).items():
        if telemetry:
            p = dataclasses.replace(p, telemetry=True)
        if watchdog:
            p = dataclasses.replace(p, watchdog=True)
        if macro_k > 0 and f_mode != "parallel":
            p = dataclasses.replace(p, macro_k=macro_k)
        if f_mode == "sweep":
            # f > 0 batches stay on the single-device serial path (see
            # run_config); the dp mesh applies to the plain fleet configs.
            results[name] = [
                dataclasses.asdict(r)
                for r in B.f_sweep(p, n, f_values=list(range(p.n_nodes // 3 + 1)))
            ]
        else:
            stream = None
            if stream_out:
                from ..telemetry import stream as tstream

                stem = stream_out[:-7] if stream_out.endswith(".ndjson") \
                    else stream_out
                stream = tstream.TimelineRecorder(
                    p, out=f"{stem}.{name}.ndjson", meta={"config": name})
            try:
                results[name] = run_config(
                    p, n, engine=P if f_mode == "parallel" else S, dp=dp,
                    stream=stream)
            finally:
                if stream is not None:
                    stream.close()
        print(f"[sweep] {name}: done", file=sys.stderr)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(results, f, indent=2)
    return results


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scale", type=float, default=0.01,
                    help="instance-count scale factor (1.0 = full BASELINE sizes)")
    ap.add_argument("--out", default=None, help="write JSON to this path")
    ap.add_argument("--telemetry", action="store_true",
                    help="run with SimParams.telemetry on and attach the "
                         "merged telemetry block to every sweep row")
    ap.add_argument("--dp", type=int, default=0,
                    help="run the fleet configs dp-sharded over this many "
                         "devices (parallel/sharded.py pipelined runtime; "
                         "on CPU force virtual devices with XLA_FLAGS="
                         "--xla_force_host_platform_device_count=N)")
    ap.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                    help="pin the jax backend (the environment's TPU plugin "
                         "ignores JAX_PLATFORMS and hangs ~25 min when its "
                         "tunnel is down — pass cpu for host runs)")
    ap.add_argument("--stream-out", default=None, metavar="PATH",
                    help="stream each config's per-chunk fleet-health "
                         "digest timeline as NDJSON to PATH.<config>.ndjson "
                         "(live view: python scripts/fleet_watch.py <file>)")
    ap.add_argument("--watchdog", action="store_true",
                    help="run with SimParams.watchdog on so the streamed "
                         "digests carry live consensus-anomaly trip counts")
    ap.add_argument("--macro-k", type=int, default=0, metavar="K",
                    help="arm the serial engine's K-event macro-steps "
                         "(SimParams.macro_k) on the serial-engine "
                         "configs: each dispatched step retires K events, "
                         "bit-identically (lane configs are unaffected)")
    args = ap.parse_args(argv)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    elif os.environ.get("PALLAS_AXON_POOL_IPS") and not _tunnel_listening():
        # Safe default (mirrors bench.py's probe): with the TPU tunnel
        # relay dead, an axon attach spins ~25 min before failing.
        print("[sweep] tpu tunnel relay not listening; pinning cpu",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
    results = run_all(args.scale, args.out, telemetry=args.telemetry,
                      dp=args.dp, stream_out=args.stream_out,
                      watchdog=args.watchdog, macro_k=args.macro_k)
    print(json.dumps(results, indent=2))


def _tunnel_listening() -> bool:
    import socket

    for port in (8082, 8083, 8087):
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=5.0):
                return True
        except OSError:
            continue
    return False


if __name__ == "__main__":
    main()
