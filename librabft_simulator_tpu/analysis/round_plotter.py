"""Round-switch plot, capability analog of
/root/reference/bft-lib/src/visualization/round_switch/round_plotter.py.

Two input formats:

* the ``round_switches.txt`` CSV written by
  :class:`~librabft_simulator_tpu.analysis.data_writer.DataWriter`
  (the classic path, unchanged);
* a saved run-report JSON (``telemetry/report.py run_report`` ->
  ``save_report``): the flight-recorder tail becomes the round-switch
  step series (per-actor ``(time, round)`` switch points), and with
  ``--commit-latency`` the report's geometric commit-latency histogram
  is rendered against its bucket edges instead.

matplotlib is optional: without it (or with ``--ascii``) an ASCII plot
is printed instead, so the tool works in headless/TPU pods.  JSON mode
is jax-free (the version check rides telemetry/schema.py, not the
jax-importing report module).
"""

from __future__ import annotations

import argparse
import csv
import json
import sys

from ..telemetry import schema as tschema


def read_csv(csv_path):
    with open(csv_path) as f:
        return list(csv.reader(f))


def step_series(csv_data):
    """Per-node list of (time, round) switch points, ascending time."""
    n = len(csv_data[0])
    series = []
    for node in range(n):
        pts = []
        for r, row in enumerate(csv_data[1:]):
            cell = row[node] if node < len(row) else ""
            if cell != "":
                pts.append((int(cell), r))
        series.append(sorted(pts))
    return series


# ---------------------------------------------------------------------------
# Run-report JSON mode.
# ---------------------------------------------------------------------------


def load_report_json(path):
    """A saved run-report, version-checked without importing jax."""
    with open(path) as f:
        report = json.load(f)
    tschema.require_registry_version(report.get("registry_version"),
                                     what=f"run-report {path}")
    return report


def flight_round_series(report):
    """Per-actor (time, round) switch points from the decoded flight tail.

    The flight recorder logs every handled event with the actor's round
    AFTER handling, so consecutive rows with a changed round ARE the
    round switches — no separate switch log needed.  Only per-instance
    reports carry ``flight``; a fleet-aggregate report raises with the
    fix (re-save with ``instance=``).
    """
    if "flight" not in report:
        raise ValueError(
            "run-report has no 'flight' rows: fleet-aggregate reports "
            "carry merged metrics only — save the report with instance= "
            "(run_report(p, st, instance=i)) to plot one instance's "
            "round switches")
    by_actor: dict = {}
    for row in report["flight"]:
        by_actor.setdefault(int(row["actor"]), []).append(
            (int(row["time"]), int(row["round"])))
    n = max(by_actor) + 1 if by_actor else 0
    series = []
    for actor in range(n):
        pts, last = [], None
        for t, rnd in sorted(by_actor.get(actor, [])):
            if rnd != last:
                pts.append((t, rnd))
                last = rnd
        series.append(pts)
    return series


def commit_latency_hist(report):
    """(edges, counts) of the report's commit-latency histogram."""
    metrics = report.get("metrics") or {}
    if "commit_lat_hist" not in metrics or "histogram_edges" not in report:
        raise ValueError(
            "run-report has no commit-latency histogram: the report was "
            "saved with telemetry off (SimParams.telemetry=True records "
            "commit_lat_hist + histogram_edges)")
    counts = [int(c) for c in metrics["commit_lat_hist"]]
    edges = [int(e) for e in report["histogram_edges"]]
    return edges, counts


def plot_matplotlib(series, out=None):
    import matplotlib

    matplotlib.use("Agg" if out else matplotlib.get_backend())
    import matplotlib.pyplot as plt

    plt.figure()
    for node, pts in enumerate(series):
        if not pts:
            continue
        xs = [t for t, _ in pts]
        ys = [r for _, r in pts]
        plt.step(xs, ys, where="post", label=f"Node: {node}")
    plt.legend()
    plt.xlabel("Time")
    plt.ylabel("Round number")
    plt.grid(axis="both", which="both")
    if out:
        plt.savefig(out, dpi=120)
        print(f"wrote {out}")
    else:
        plt.show()


def plot_hist_matplotlib(edges, counts, out=None):
    import matplotlib

    matplotlib.use("Agg" if out else matplotlib.get_backend())
    import matplotlib.pyplot as plt

    labels = [f"<{e}" for e in edges[1:]] + [f">={edges[-1]}"]
    labels = labels[:len(counts)]
    plt.figure()
    plt.bar(range(len(counts)), counts)
    plt.xticks(range(len(counts)), labels, rotation=45, fontsize=7)
    plt.xlabel("Commit latency (sim time, geometric buckets)")
    plt.ylabel("Commits")
    plt.grid(axis="y")
    if out:
        plt.savefig(out, dpi=120)
        print(f"wrote {out}")
    else:
        plt.show()


def plot_ascii(series, width=72, height=18, file=None):
    file = file or sys.stdout
    pts_all = [pt for pts in series for pt in pts]
    if not pts_all:
        print("(no round switches recorded)", file=file)
        return
    tmax = max(t for t, _ in pts_all) or 1
    rmax = max(r for _, r in pts_all) or 1
    grid = [[" "] * width for _ in range(height)]
    for node, pts in enumerate(series):
        ch = str(node % 10)
        for t, r in pts:
            x = min(int(t / tmax * (width - 1)), width - 1)
            y = min(int(r / rmax * (height - 1)), height - 1)
            grid[height - 1 - y][x] = ch
    print(f"round 0..{rmax} (y) vs time 0..{tmax} (x); digit = node id", file=file)
    for row in grid:
        print("".join(row), file=file)


def plot_ascii_hist(edges, counts, width=48, file=None):
    file = file or sys.stdout
    total = sum(counts)
    if not total:
        print("(no commits recorded)", file=file)
        return
    peak = max(counts)
    print(f"commit latency histogram ({total} commits; geometric buckets)",
          file=file)
    for i, c in enumerate(counts):
        lo = edges[i] if i < len(edges) else edges[-1]
        label = f"<{edges[i + 1]}" if i + 1 < len(edges) else f">={lo}"
        bar = "#" * int(c / peak * width)
        print(f"{label:>8s} |{bar:<{width}s}| {c}", file=file)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", help="round_switches.txt from DataWriter, or a "
                                 "saved run-report .json")
    ap.add_argument("--out", help="save PNG instead of showing")
    ap.add_argument("--ascii", action="store_true", help="force ASCII output")
    ap.add_argument("--commit-latency", action="store_true",
                    help="plot the report's commit-latency histogram "
                         "(JSON reports only)")
    args = ap.parse_args(argv)

    if args.path.endswith(".json"):
        report = load_report_json(args.path)
        if args.commit_latency:
            edges, counts = commit_latency_hist(report)
            if args.ascii:
                plot_ascii_hist(edges, counts)
                return
            try:
                plot_hist_matplotlib(edges, counts, args.out)
            except ImportError:
                plot_ascii_hist(edges, counts)
            return
        series = flight_round_series(report)
    else:
        if args.commit_latency:
            ap.error("--commit-latency needs a run-report .json (the CSV "
                     "records round switches only)")
        series = step_series(read_csv(args.path))
    if args.ascii:
        plot_ascii(series)
        return
    try:
        plot_matplotlib(series, args.out)
    except ImportError:
        plot_ascii(series)


if __name__ == "__main__":
    main()
