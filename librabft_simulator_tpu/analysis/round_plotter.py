"""Round-switch plot, capability analog of
/root/reference/bft-lib/src/visualization/round_switch/round_plotter.py.

Reads the ``round_switches.txt`` CSV written by
:class:`~librabft_simulator_tpu.analysis.data_writer.DataWriter` and renders
each node's round number over global time.  matplotlib is optional: without it
(or with ``--ascii``) an ASCII step plot is printed instead, so the tool works
in headless/TPU pods.
"""

from __future__ import annotations

import argparse
import csv
import sys


def read_csv(csv_path):
    with open(csv_path) as f:
        return list(csv.reader(f))


def step_series(csv_data):
    """Per-node list of (time, round) switch points, ascending time."""
    n = len(csv_data[0])
    series = []
    for node in range(n):
        pts = []
        for r, row in enumerate(csv_data[1:]):
            cell = row[node] if node < len(row) else ""
            if cell != "":
                pts.append((int(cell), r))
        series.append(sorted(pts))
    return series


def plot_matplotlib(series, out=None):
    import matplotlib

    matplotlib.use("Agg" if out else matplotlib.get_backend())
    import matplotlib.pyplot as plt

    plt.figure()
    for node, pts in enumerate(series):
        if not pts:
            continue
        xs = [t for t, _ in pts]
        ys = [r for _, r in pts]
        plt.step(xs, ys, where="post", label=f"Node: {node}")
    plt.legend()
    plt.xlabel("Time")
    plt.ylabel("Round number")
    plt.grid(axis="both", which="both")
    if out:
        plt.savefig(out, dpi=120)
        print(f"wrote {out}")
    else:
        plt.show()


def plot_ascii(series, width=72, height=18, file=None):
    file = file or sys.stdout
    pts_all = [pt for pts in series for pt in pts]
    if not pts_all:
        print("(no round switches recorded)", file=file)
        return
    tmax = max(t for t, _ in pts_all) or 1
    rmax = max(r for _, r in pts_all) or 1
    grid = [[" "] * width for _ in range(height)]
    for node, pts in enumerate(series):
        ch = str(node % 10)
        for t, r in pts:
            x = min(int(t / tmax * (width - 1)), width - 1)
            y = min(int(r / rmax * (height - 1)), height - 1)
            grid[height - 1 - y][x] = ch
    print(f"round 0..{rmax} (y) vs time 0..{tmax} (x); digit = node id", file=file)
    for row in grid:
        print("".join(row), file=file)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("csv_path", help="round_switches.txt from DataWriter")
    ap.add_argument("--out", help="save PNG instead of showing")
    ap.add_argument("--ascii", action="store_true", help="force ASCII output")
    args = ap.parse_args(argv)
    series = step_series(read_csv(args.csv_path))
    if args.ascii:
        plot_ascii(series)
        return
    try:
        plot_matplotlib(series, args.out)
    except ImportError:
        plot_ascii(series)


if __name__ == "__main__":
    main()
