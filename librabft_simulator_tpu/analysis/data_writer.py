"""DataWriter: round-switch and message statistics
(/root/reference/bft-lib/src/data_writer.rs:10-102).

The reference observes the simulator after every event on the host; here the
round-switch trace is captured *on device* by the step function (SimState
``trace_*`` ring, sim/simulator.py) and decoded after the run — the TPU-first
equivalent with zero host sync in the hot loop.

Outputs match the reference formats: ``round_switches.txt`` (CSV, one column
per node, row r = global time node entered round r, empty if never) and
``number_of_messages.txt``, plus a JSON summary with the extra tensor-path
metrics (drops, queue overflows, sync jumps, commits).
"""

from __future__ import annotations

import csv
import json
import os
from typing import Optional

import jax
import numpy as np

from ..core.types import SimParams
from ..telemetry import plane


def round_switch_table(p: SimParams, st, instance: Optional[int] = None):
    """[max_round+1, N] global times; -1 = node never entered that round.
    ``instance`` selects one instance of a batched state (None = unbatched)."""
    g = lambda x: np.asarray(jax.device_get(x))  # noqa: E731
    node = g(st.trace_node)
    rnd = g(st.trace_round)
    time = g(st.trace_time)
    count = int(g(st.trace_count) if instance is None else g(st.trace_count)[instance])
    if instance is not None:
        node, rnd, time = node[instance], rnd[instance], time[instance]
    # Chronological decode (telemetry/plane.py ring_order): after overflow
    # only the last T switches survive, rotated in storage — iterating in
    # storage order would let a STALE entry (physically earlier, logically
    # newer) shadow the true first entry time of a (round, node) cell under
    # the first-write-wins rule below.
    order = plane.ring_order(count, p.trace_cap)
    max_round = int(rnd.max(initial=0))
    out = np.full((max_round + 1, p.n_nodes), -1, np.int64)
    for i in order:
        r, a, t = int(rnd[i]), int(node[i]), int(time[i])
        if out[r, a] < 0:
            out[r, a] = t
    return out


def summary_dict(p: SimParams, st, instance: Optional[int] = None,
                 table: Optional[np.ndarray] = None) -> dict:
    """The DataWriter summary as a plain dict (no files): shared between
    :class:`DataWriter` and the telemetry run-report exporter
    (telemetry/report.py)."""
    if table is None:
        table = round_switch_table(p, st, instance)
    sel = (lambda x: x) if instance is None else (lambda x: x[instance])
    g = lambda x: np.asarray(jax.device_get(x))  # noqa: E731
    return {
        "n_nodes": p.n_nodes,
        "clock": int(sel(g(st.clock))),
        "n_events": int(sel(g(st.n_events))),
        "n_msgs_sent": int(sel(g(st.n_msgs_sent))),
        "n_msgs_dropped": int(sel(g(st.n_msgs_dropped))),
        # Serial engine counts shared-queue overflow; the parallel
        # engine counts per-receiver inbox overflow.
        "n_queue_full": int(sel(g(
            st.n_queue_full if hasattr(st, "n_queue_full")
            else st.n_inbox_full))),
        "commit_count": g(st.ctx.commit_count)[instance].tolist()
        if instance is not None else g(st.ctx.commit_count).tolist(),
        "sync_jumps": g(st.ctx.sync_jumps)[instance].tolist()
        if instance is not None else g(st.ctx.sync_jumps).tolist(),
        "max_round": int(table.shape[0]) - 1,
    }


class DataWriter:
    """Host-side writer consuming a finished SimState."""

    def __init__(self, p: SimParams, path: str):
        self.p = p
        self.path = path
        os.makedirs(path, exist_ok=True)

    def write(self, st, instance: Optional[int] = None) -> dict:
        p = self.p
        table = round_switch_table(p, st, instance)

        with open(os.path.join(self.path, "round_switches.txt"), "w", newline="") as f:
            w = csv.writer(f)
            w.writerow([f"node {i}" for i in range(p.n_nodes)])
            for row in table:
                w.writerow(["" if t < 0 else int(t) for t in row])

        summary = summary_dict(p, st, instance, table=table)
        with open(os.path.join(self.path, "number_of_messages.txt"), "w") as f:
            f.write(f"{summary['n_msgs_sent']}\n")

        with open(os.path.join(self.path, "summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
        return summary
