"""Tensor state containers for the batched LibraBFTv2 simulator.

Layout philosophy (TPU-first): every piece of reference state becomes a
fixed-shape padded int32/uint32 array; one ``SimState`` pytree holds one
*instance* (a full network of N nodes + its event queue).  ``jax.vmap`` adds
the instance batch dimension; ``jax.jit`` compiles the whole step; sharding
over a ``jax.sharding.Mesh`` splits the instance dim across chips.

Reference counterparts are cited per group.  Key redesigns:

* Hash-map record stores (/root/reference/librabft-v2/src/record_store.rs:93)
  -> round-windowed tables ``[W, V]``: slot = round % W, V=2 variants per
  round (2 suffices: honest protocol has <=1 block/QC per round; the second
  slot catches Byzantine equivocation so safety violations are *observable*).
* ``BinaryHeap<ScheduledEvent>`` (/root/reference/bft-lib/src/simulator.rs:29)
  -> fixed-capacity message table + one timer slot per node (the reference
  cancels stale timers via ``ignore_scheduled_updates_until``; keeping only
  the newest timer is behaviourally equivalent).
* Unbounded ledger states -> rolling ``(depth, tag)`` pairs.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np
import jax
import jax.numpy as jnp
from flax import struct

from ..utils import hashing as H
from ..utils import quantile

Array = Any

NEVER = np.int32(2**31 - 1)  # NodeTime::never() (/root/reference/bft-lib/src/base_types.rs:57)


def sat_add(a, b):
    """min(a + b, NEVER) without int32 wraparound, for b in [0, NEVER].

    NodeTime arithmetic must saturate at NEVER (the oracle uses unbounded
    Python ints, the C++ engine wide i64); deadlines reach NEVER (durations
    are table-capped at NEVER//2 but bases approach NEVER) and bases can be
    NEGATIVE — a node handling a message delivered before its startup time
    runs at a negative local clock (simulator.rs:120-121).  The classic
    ``a + min(b, NEVER - a)`` guard breaks for a < 0 (``NEVER - a`` wraps);
    clamping the subtrahend to ``max(a, 0)`` covers both signs exactly."""
    a = jnp.asarray(a, jnp.int32)
    return a + jnp.minimum(jnp.asarray(b, jnp.int32), NEVER - jnp.maximum(a, 0))

# Event kinds; priority at equal time is DESCENDING kind
# (/root/reference/bft-lib/src/simulator.rs:149-161).
KIND_NOTIFY = 0
KIND_REQUEST = 1
KIND_RESPONSE = 2
KIND_TIMER = 3

# Election states (/root/reference/librabft-v2/src/record_store.rs:125).
ELECTION_ONGOING = 0
ELECTION_WON = 1
ELECTION_CLOSED = 2

#: The delay-family field defaults that compile keys normalize out — the
#: single source for BOTH ``SimParams.structural()`` and the sharded
#: runner's scenario-armed cache/AOT key (parallel/sharded.py).  Two
#: copies of these literals would let the keys drift apart, silently
#: reintroducing the per-config recompiles the scenario plane eliminates.
DELAY_KEY_DEFAULTS = dict(delay_kind="lognormal", delay_mean=10.0,
                          delay_variance=4.0, delay_pareto_scale=5.0,
                          delay_pareto_alpha=1.5)

#: Width of one attack-schedule window row (adversary/plane.py — the
#: schema constants live there; the width lives here so the zero-width
#: state init below needs no adversary import): (mode, lo, hi, behavior,
#: target_lo, target_hi, arg).
ADV_FIELDS = 7


@dataclasses.dataclass(frozen=True)
class SimParams:
    """Static (compile-time) simulation parameters.

    Mirrors NodeConfig (/root/reference/librabft-v2/src/node.rs:76) + CLI args
    (/root/reference/librabft-v2/src/main.rs) + tensor capacities.
    """

    n_nodes: int = 3
    window: int = 16          # W: record-store round window
    variants: int = 2         # V: slots per round
    queue_cap: int = 32       # CM: in-flight messages per instance
    chain_k: int = 4          # K: rounds of (block, QC) tail in a sync response
    commit_log: int = 32      # H: per-node committed-state ring
    # Protocol config (reference defaults from main.rs).
    commands_per_epoch: int = 30000
    target_commit_interval: int = 100000
    delta: int = 20
    gamma: float = 2.0
    lam: float = 0.5          # lambda; fixed-point applied as (lam_fp * d) >> 16
    commit_chain: int = 3     # 3 = LibraBFTv2 3-chain; 2 = HotStuff-style 2-chain
    epoch_handoff: bool = True  # serve laggard requesters a held previous
                                # epoch's K-tail (data_sync.rs:82-92,
                                # node.rs record_store_at); off = laggards jump
    handoff_epochs: int = 2     # E: ring of previous-epoch packs kept per
                                # node ([N, E, F]); any requester whose epoch
                                # matches a held pack is served (the reference
                                # keeps ALL previous epochs' stores —
                                # node.rs record_store_at — this keeps E
                                # bounded packs)
    # Event selection backend for the serial engine: "xla" (default, fused
    # masked reductions), "pallas" (ops/pallas_queue.py TPU kernel), or
    # "pallas_interpret" (same kernel, interpreter mode — CPU testable).
    # All three are bit-identical (tests/test_ops.py).
    select_kernel: str = "xla"
    # Fully unroll the small protocol-interior lax.scans (QC chain walks,
    # commit delivery, K-tail replay, timeout batches).  Rolled scans keep
    # the compiled graph small — right for CPU and for n=64 configs — but
    # every scan lowers to an XLA while loop that TPU executes with
    # per-iteration kernel-dispatch overhead; profiling at B=2048 shows
    # those whiles are ~half the on-chip step time.  Trajectories are
    # bit-identical either way (tests/test_parity.py::test_unroll_parity).
    unroll: bool = False
    # Packed state planes (core/packing.py): store the ~70 per-node
    # Store/Pacemaker/NodeExtra/Context leaves as one flat [N, S] int32
    # plane, so the step's node read is one row gather and its write-back
    # one plane-wide select instead of one kernel per leaf.  Bit-identical
    # either way (tests/test_packing.py).  None = auto: True under TPU
    # lowering, False elsewhere (full-plane writes lose on CPU — the
    # round-5 negative results).  Resolved by sim engines at make-time via
    # utils/xops.resolve_params.
    packed: bool | None = None
    # Lowering form for the step's vector scatters (the 7 queue writes):
    # "scatter" = proven .at[].set(mode="drop") forms (CPU default),
    # "dense" = one-hot sum-select / matmul forms (TPU default: scatters
    # serialize into per-kernel dispatch there).  "auto" resolves by
    # backend at make-time (utils/xops.backend_mode; LIBRABFT_WRITE_MODE
    # env overrides for A/B benching).  All forms bit-identical
    # (tests/test_xops.py).
    dense_writes: str = "auto"
    # Short-circuit handle_notification/handle_response behind the event-
    # kind predicates with lax.cond.  Unbatched lowerings (oracle-parity
    # runs, B=1) genuinely skip the wrong-kind subgraph; batched lowerings
    # select between branches exactly as the previous per-field _sel did,
    # so trajectories are bit-identical either way.  None = auto: True
    # under TPU lowering only — on CPU the conditional's extra branch
    # computations slow XLA *compiles* enough to cost tier-1 test-budget
    # dots (measured: 35 vs 39 in the 870 s gate), outweighing its ~10%
    # batched-runtime win, so the CPU graph stays exactly the pre-PR one.
    gate_handlers: bool | None = None
    # Author-dim (mp) quorum aggregation: when True, every quorum-weight
    # reduction in core/store.py (ballot wins, insert_qc vote-set
    # re-verification, TC formation) psums its local partial over the
    # mesh's 'mp' axis via core/config.py — the same code path
    # parallel/sharded.sharded_count_votes exercises standalone.  Requires
    # tracing inside a shard_map that binds 'mp'; with n_mp == 1 the psum
    # degenerates to the identity and trajectories are bit-identical to
    # the default (tests/test_multichip.py pins this).  Sharding the [N]
    # author *state tables* over mp (the N >> 64 regime) is future work —
    # today n_mp > 1 is for the standalone quorum helpers.
    mp_authors: bool = False
    # Network.
    shuffle_receivers: bool = False  # seeded per-event receiver permutation
                                     # (simulator.rs:343 fuzzing semantics);
                                     # parity trio only (serial/oracle/C++)
    inbox_cap: int = 0        # parallel engine per-receiver slots (0 = auto)
    # Parallel-engine window shape (see sim/parallel_sim.py): nodes stepped
    # densely per window after compaction, and events each lane may drain.
    # Both only reshape windows — trajectories are invariant absent inbox
    # overflow (tests/test_parallel_sim.py).  0 = auto heuristics.
    active_lanes: int = 0
    drain_k: int = 0
    delay_kind: str = "lognormal"
    delay_mean: float = 10.0
    delay_variance: float = 4.0
    delay_pareto_scale: float = 5.0
    delay_pareto_alpha: float = 1.5
    drop_prob: float = 0.0
    max_clock: int = 1000
    dur_table_size: int = 64
    trace_cap: int = 0        # round-switch trace entries (0 = tracing off)
    # In-graph telemetry (telemetry/plane.py): a fixed-shape [M] int32
    # metrics plane (per-event-kind counters, queue high-water marks,
    # drop/overflow/sync-jump tallies, latency histograms, lane-engine
    # window health) plus a last-K-events flight-recorder ring, both
    # per instance, zero host sync in the hot loop.  Static and default
    # OFF: disabled, the arrays are zero-width and every update compiles
    # out, so the graph is bit- and kernel-identical to a telemetry-free
    # build (tests/test_telemetry.py + the kernel-census CI gate).
    telemetry: bool = False
    flight_cap: int = 32      # K: flight-recorder ring rows (telemetry on)
    # K-event macro-steps (sim/simulator.py::macro_step): the serial
    # engine's dispatched unit of work retires macro_k queue events via a
    # fixed-K rolled inner lax.scan instead of one — the Chandy–Misra
    # lookahead idea the lane engine's horizon windows already exploit,
    # applied to the dispatch axis: the ~per-step kernel-launch cost of
    # the TPU execution model is amortized over K events per dispatched
    # program.  Trajectories are bit-identical for every K (already-
    # halted instances and drained queues make inner iterations exact
    # no-ops — every write is live-gated, the pre-halted-padding idiom),
    # so chunk runs compose bit-exactly across K (tests/test_checkpoint,
    # tests/test_stream, FUZZ_MACRO_K campaigns).  Static compile key;
    # num_steps/chunk arguments everywhere count MACRO-steps (each
    # retiring macro_k events).  None = auto: LIBRABFT_MACRO_K env
    # override, else 1 — and 1 lowers to the exact macro-free graph (the
    # inner scan is skipped entirely; pinned by the graph audit's
    # tpu_shape_k1 signature equality and the kernel census).  Serial
    # engine only: the lane engine raises on macro_k > 1 (its windows
    # are the same amortization by other means).
    macro_k: int | None = None
    # Dispatch wrap (parallel/sharded.py): who drives the chunk loop.
    # "host" is the classic contract — one host dispatch + one blocking
    # [13] digest fetch per chunk (the double-buffered run_sharded loop).
    # "device" moves the loop in-graph: a ``lax.while_loop`` retires up
    # to ``ring_k`` chunks per dispatched outer program, exits early on
    # the all-halted predicate, and streams each retired chunk's digest
    # into a device-side [ring_k, 13] int32 ring egressed ONCE per outer
    # call — the host becomes a ring reader instead of a per-chunk
    # poller (polls-per-retired-chunk drops from 1.0 to <= 1/ring_k on
    # non-halting horizons).  Chunks are bit-identical between wraps
    # (every engine write is live-gated, so extra iterations on halted
    # fleets are exact no-ops — the same idiom that makes macro_k and
    # pre-halted padding exact).  Static compile key; NOT the SPMD wrap
    # argument of make_sharded_run_fn ("shard_map"/"jit") — this is one
    # level up, the host-dispatch wrap.  None = auto: LIBRABFT_WRAP env
    # override, else "host" (the exact pre-ring contract; pinned
    # graph-identical by the audit's R6 ring arm).
    wrap: str | None = None
    # Digest-ring depth K for wrap="device": chunks retired per
    # dispatched outer program, and the ring's first dimension.  Static
    # compile key (the ring is a fixed-shape output).  None = auto:
    # LIBRABFT_RING_K env override, else 16 when wrap resolves to
    # "device".  Normalized to None when wrap resolves to "host" so the
    # host flavor's compile/AOT keys never vary with LIBRABFT_RING_K.
    ring_k: int | None = None
    # In-graph consensus watchdog (telemetry/stream.py): a per-instance
    # [WD] int32 plane of anomaly detectors — liveness stall (no pacemaker
    # round advance for ``watchdog_stall_events`` processed events),
    # queue-pressure saturation, sync-jump anomaly, and the safety
    # invariants (conflicting commit at the same height across nodes;
    # round regression inside one node's committed chain, epoch-aware).
    # Trip counts surface live in the fleet digest that rides the
    # run_sharded halt poll.  Static and default OFF: disabled, the wd
    # leaf is zero-width and every update compiles out, so the graph is
    # bit- and kernel-identical to a watchdog-free build
    # (tests/test_stream.py + the kernel-census CI gate).
    watchdog: bool = False
    watchdog_stall_events: int = 512  # static liveness-stall threshold
    # Per-slot traced scenario plane (serve/scenario.py): when ON, the
    # per-instance scenario knobs that used to be compile-time params ride
    # in SimState as traced data — the delay quantile table becomes a
    # per-slot [T] int32 row (``sc_delay``) and the commit rule becomes a
    # traced 2-vs-3-chain select on ``sc_commit`` (core/store.py reads it
    # through :class:`TracedParams`); drop rate, horizon, rng seed, and
    # the Byzantine masks were already per-instance state.  ONE compiled
    # executable then serves a heterogeneous fleet of scenarios
    # (``structural()`` additionally normalizes ``commit_chain``, and the
    # sharded runner stops baking delay tables into its key), which is
    # what the resident fleet service (serve/) runs on.  Static and
    # default OFF: disabled, the sc_* leaves are zero-width and the step
    # compiles to the exact static-knob graph (tests/test_serve.py + the
    # kernel-census gates); per-slot values are bit-identical to a
    # dedicated static run of the same scenario.
    scenario: bool = False
    # Adversary engine (adversary/): per-slot traced attack state — a
    # [W, ADV_FIELDS] attack-schedule plane (time/event/epoch-windowed
    # equivocation, targeted silence, forged QCs, targeted and
    # leader-targeted delay — decoded in-graph with one-hot/select forms
    # and OR-composed onto the static byz_* masks per event), a [n, n]
    # per-link extra-delay matrix (consumed by both engines' delay
    # draws; the lane engine derives a TIGHTER Chandy–Misra horizon
    # from its minimum off-diagonal entry), and a partition schedule
    # (group row + heal time: crossing messages sent before heal are
    # cut).  Attack programs (adversary/dsl.py) lower to these rows, so
    # one executable sweeps millions of distinct adversarial scenarios.
    # Static and default OFF: disabled, the adv_* leaves are zero-width
    # and every decode compiles out — the graph is bit- and
    # kernel-identical to an adversary-free build (tests/
    # test_adversary.py + the kernel-census gates + the graph audit's
    # R6 adversary arm).
    adversary: bool = False
    adv_windows: int = 4      # W: attack-schedule rows per slot (compile
                              # key: the plane's shape)

    def __post_init__(self):
        if self.epoch_handoff and self.handoff_epochs < 1:
            raise ValueError(
                "handoff_epochs must be >= 1 when epoch_handoff is on "
                f"(got {self.handoff_epochs}); the three engines would "
                "otherwise diverge on a zero-width ring")
        if self.telemetry and self.flight_cap < 1:
            raise ValueError(
                f"flight_cap must be >= 1 when telemetry is on "
                f"(got {self.flight_cap}); the flight-recorder ring "
                "write indices are taken modulo flight_cap")
        if self.macro_k is not None and self.macro_k < 1:
            raise ValueError(
                f"macro_k must be >= 1 (got {self.macro_k}); the serial "
                "engine's dispatched unit retires macro_k events — zero "
                "would dispatch empty programs forever")
        if self.wrap is not None and self.wrap not in ("host", "device"):
            raise ValueError(
                f"wrap must be 'host' or 'device' (got {self.wrap!r}); "
                "the dispatch wrap picks who drives the chunk loop — the "
                "SPMD wrap ('shard_map'/'jit') is a separate "
                "make_sharded_run_fn argument")
        if self.ring_k is not None and self.ring_k < 1:
            raise ValueError(
                f"ring_k must be >= 1 (got {self.ring_k}); the device "
                "dispatch wrap retires up to ring_k chunks per outer "
                "call — a zero-depth ring could never retire a chunk")
        if self.watchdog and self.watchdog_stall_events < 1:
            raise ValueError(
                f"watchdog_stall_events must be >= 1 when the watchdog is "
                f"on (got {self.watchdog_stall_events}); a zero threshold "
                "would trip the liveness-stall detector on every event")
        if self.adversary and self.adv_windows < 1:
            raise ValueError(
                f"adv_windows must be >= 1 when the adversary plane is on "
                f"(got {self.adv_windows}); a zero-row schedule cannot "
                "hold any attack window — turn adversary off instead")
        if self.adversary and self.n_nodes > 64:
            raise ValueError(
                f"the adversary plane's author target masks cover 64 "
                f"nodes (n_nodes={self.n_nodes}); widen the "
                "target_lo/target_hi fields before arming larger "
                "committees")
        if self.scenario and self.commit_chain not in (2, 3):
            raise ValueError(
                f"commit_chain must be 2 (HotStuff-style) or 3 "
                f"(LibraBFTv2) when the scenario plane is on, got "
                f"{self.commit_chain}; the traced per-slot select in "
                "core/store.py covers exactly these depths (static runs "
                "keep the generic Python-unrolled C-chain walk)")

    @property
    def lam_fp(self) -> int:
        return int(self.lam * 65536)

    @property
    def drop_u32(self) -> int:
        return min(int(self.drop_prob * 4294967296.0), 0xFFFFFFFF)

    def structural(self) -> "SimParams":
        """The compile-relevant projection: fields that only parameterize
        *data* (delay/duration tables, drop rate, horizon) are normalized to
        defaults.  Two SimParams with equal ``structural()`` share one
        compiled step executable — the tables ride in as runtime arguments
        and max_clock/drop_u32 live in SimState — which is what keeps the
        test suite's XLA compile count down.

        With the scenario plane on (``scenario=True``), ``commit_chain``
        is ALSO normalized out: the commit rule reads the per-slot traced
        ``sc_commit`` instead of the static knob, so 2-chain and 3-chain
        slots share one executable — the key gets strictly coarser, which
        is what collapses the AOT executable store for scenario sweeps."""
        out = dataclasses.replace(
            self, drop_prob=0.0, max_clock=0, delta=20, gamma=2.0,
            **DELAY_KEY_DEFAULTS)
        if self.scenario:
            out = dataclasses.replace(out, commit_chain=3)
        return out

    def delay_table(self) -> np.ndarray:
        if self.delay_kind == "pareto":
            return quantile.make_table(
                "pareto", scale=self.delay_pareto_scale, alpha=self.delay_pareto_alpha
            )
        if self.delay_kind == "uniform":
            return quantile.make_table(
                "uniform",
                low=max(self.delay_mean - 3 * self.delay_variance ** 0.5, 0.0),
                high=self.delay_mean + 3 * self.delay_variance ** 0.5,
            )
        if self.delay_kind == "constant":
            return quantile.make_table("constant", value=int(self.delay_mean))
        return quantile.make_table(
            "lognormal", mean=self.delay_mean, variance=self.delay_variance
        )

    def duration_table(self) -> np.ndarray:
        """round-duration(n) = delta * n^gamma, precomputed in float64 on host
        (/root/reference/librabft-v2/src/pacemaker.rs:111-124)."""
        n = np.arange(self.dur_table_size, dtype=np.float64)
        vals = np.floor(float(self.delta) * np.power(np.maximum(n, 0), self.gamma))
        return np.minimum(vals, float(NEVER // 2)).astype(np.int32)


def _zeros(shape, dtype=jnp.int32):
    return jnp.zeros(shape, dtype=dtype)


# ---------------------------------------------------------------------------
# Per-slot traced scenario plane (SimParams.scenario; serve/scenario.py).
# ---------------------------------------------------------------------------


def sc_delay_init(p: SimParams):
    """Knob-default ``sc_delay`` row: the params' own delay table (so a
    plain init is bit-identical to the static engine), [0] when off."""
    if not p.scenario:
        return jnp.zeros((0,), jnp.int32)
    return jnp.asarray(p.delay_table(), jnp.int32)


def sc_commit_init(p: SimParams):
    """Knob-default ``sc_commit`` row: the params' static commit_chain."""
    if not p.scenario:
        return jnp.zeros((0,), jnp.int32)
    return jnp.full((1,), p.commit_chain, jnp.int32)


# ---------------------------------------------------------------------------
# Adversary plane (SimParams.adversary; adversary/plane.py holds the
# schema + decode).  The all-zero rows are the inert program by
# construction: a window with hi=0 never activates, a zero link matrix
# adds nothing, all-equal groups with heal=0 never cut.
# ---------------------------------------------------------------------------


def adv_sched_init(p: SimParams):
    """Inert attack-schedule plane: [W, ADV_FIELDS] zeros ([0, F] off)."""
    return jnp.zeros((p.adv_windows if p.adversary else 0, ADV_FIELDS),
                     jnp.int32)


def adv_link_init(p: SimParams):
    """Zero per-link extra-delay matrix: [n, n] ([0, 0] off)."""
    n = p.n_nodes if p.adversary else 0
    return jnp.zeros((n, n), jnp.int32)


def adv_group_init(p: SimParams):
    """All-same partition groups: [n] zeros ([0] off)."""
    return jnp.zeros((p.n_nodes if p.adversary else 0,), jnp.int32)


def adv_heal_init(p: SimParams):
    """Heal-at-0 (= never partitioned): [1] zeros ([0] off)."""
    return jnp.zeros((1 if p.adversary else 0,), jnp.int32)


class TracedParams:
    """A :class:`SimParams` view whose ``commit_chain`` is a traced
    per-instance scalar — the scenario plane's ``sc_commit`` row.

    The engines construct one per step (inside the trace, from the state
    leaf) and hand it to the protocol code in core/store.py, core/node.py,
    and core/data_sync.py in place of the static params; every other
    attribute (shapes, capacities, lowering knobs, the bound methods like
    ``structural``/``delay_table``) delegates to the static params, so the
    whole call graph needs no signature changes.  The commit-rule sites
    branch on ``isinstance(commit_chain, int)``: a static int keeps
    today's Python-unrolled walk exactly; a tracer takes the
    2-vs-3-chain select form (both depths computed, the per-slot value
    picks — bit-identical per slot to the static graph of that depth).
    Never hashable and never a jit key: it exists only inside a trace."""

    __slots__ = ("_p", "commit_chain")

    def __init__(self, p: SimParams, commit_chain):
        self._p = p
        self.commit_chain = commit_chain

    def __getattr__(self, name):
        return getattr(self._p, name)

    __hash__ = None  # type: ignore[assignment]  # never a cache/jit key


# ---------------------------------------------------------------------------
# Wire-format structs (message payload pieces). All fields int32/uint32/bool.
# ---------------------------------------------------------------------------


@struct.dataclass
class BlockMsg:
    """Block_ (/root/reference/librabft-v2/src/record.rs:52-63)."""

    valid: Array
    round: Array
    author: Array
    prev_round: Array  # round of previous QC; 0 = epoch-initial QC
    prev_tag: Array    # uint32 content tag of previous QC (or initial tag)
    time: Array
    cmd_proposer: Array
    cmd_index: Array
    tag: Array         # uint32 content tag of this block

    @classmethod
    def empty(cls, shape=()):
        return cls(
            valid=_zeros(shape, jnp.bool_), round=_zeros(shape), author=_zeros(shape),
            prev_round=_zeros(shape), prev_tag=_zeros(shape, jnp.uint32),
            time=_zeros(shape), cmd_proposer=_zeros(shape), cmd_index=_zeros(shape),
            tag=_zeros(shape, jnp.uint32),
        )


@struct.dataclass
class QcMsg:
    """QuorumCertificate_ (/root/reference/librabft-v2/src/record.rs:83-99).

    The vote list is carried as a packed author-bit mask (``votes_lo/hi``,
    authors 0..63) folded into ``tag``.  Receivers re-verify the vote set on
    insert — mask weight must reach quorum and the tag must recompute from
    the carried fields (record_store.rs:330-389) — so a forged QC without a
    real quorum behind it is rejected, not trusted."""

    valid: Array
    epoch: Array
    round: Array
    blk_tag: Array       # uint32 tag of certified block (its round == round)
    state_depth: Array
    state_tag: Array     # uint32
    commit_valid: Array  # bool: committed_state.is_some()
    commit_depth: Array
    commit_tag: Array    # uint32
    votes_lo: Array      # uint32: author-bit mask, authors 0..31
    votes_hi: Array      # uint32: authors 32..63
    author: Array
    tag: Array           # uint32

    @classmethod
    def empty(cls, shape=()):
        return cls(
            valid=_zeros(shape, jnp.bool_), epoch=_zeros(shape), round=_zeros(shape),
            blk_tag=_zeros(shape, jnp.uint32), state_depth=_zeros(shape),
            state_tag=_zeros(shape, jnp.uint32), commit_valid=_zeros(shape, jnp.bool_),
            commit_depth=_zeros(shape), commit_tag=_zeros(shape, jnp.uint32),
            votes_lo=_zeros(shape, jnp.uint32), votes_hi=_zeros(shape, jnp.uint32),
            author=_zeros(shape), tag=_zeros(shape, jnp.uint32),
        )


@struct.dataclass
class VoteMsg:
    """Vote_ (/root/reference/librabft-v2/src/record.rs:66-80)."""

    valid: Array
    epoch: Array
    round: Array
    blk_tag: Array
    state_depth: Array
    state_tag: Array
    commit_valid: Array
    commit_depth: Array
    commit_tag: Array
    author: Array

    @classmethod
    def empty(cls, shape=()):
        return cls(
            valid=_zeros(shape, jnp.bool_), epoch=_zeros(shape), round=_zeros(shape),
            blk_tag=_zeros(shape, jnp.uint32), state_depth=_zeros(shape),
            state_tag=_zeros(shape, jnp.uint32), commit_valid=_zeros(shape, jnp.bool_),
            commit_depth=_zeros(shape), commit_tag=_zeros(shape, jnp.uint32),
            author=_zeros(shape),
        )


@struct.dataclass
class TimeoutsMsg:
    """A batch of Timeout_ records sharing one round
    (/root/reference/librabft-v2/src/record.rs:102-111): per-author validity
    mask + highest_certified_block_round."""

    round: Array        # scalar round shared by the batch
    valid: Array        # [N] bool
    hcbr: Array         # [N]

    @classmethod
    def empty(cls, n, shape=()):
        return cls(
            round=_zeros(shape),
            valid=_zeros(shape + (n,), jnp.bool_),
            hcbr=_zeros(shape + (n,)),
        )


@struct.dataclass
class Payload:
    """Superset of DataSyncNotification / Request / Response
    (/root/reference/librabft-v2/src/data_sync.rs:15-59), fixed shape.

    Notifications use: epoch, hcc, hqc, tc_to, cur_to, vote, prop_blk.
    Requests use: epoch, req_hqc_round, req_hcr.
    Responses use: epoch, chain_* (K ascending (block, QC) pairs ending at the
    sender's highest QC), hcc_blk+hcc, tc_to, cur_to, prop_blk.  Unbounded
    reference responses are replaced by the K-tail + state-sync jumps.
    """

    epoch: Array
    hcc: QcMsg
    hqc: QcMsg
    hcc_blk: BlockMsg
    prop_blk: BlockMsg
    vote: VoteMsg
    tc_to: TimeoutsMsg
    cur_to: TimeoutsMsg
    chain_blk: BlockMsg   # fields have leading [K]
    chain_qc: QcMsg       # fields have leading [K]
    req_hqc_round: Array
    req_hcr: Array

    @classmethod
    def empty(cls, n, k, shape=()):
        return cls(
            epoch=_zeros(shape),
            hcc=QcMsg.empty(shape), hqc=QcMsg.empty(shape),
            hcc_blk=BlockMsg.empty(shape), prop_blk=BlockMsg.empty(shape),
            vote=VoteMsg.empty(shape),
            tc_to=TimeoutsMsg.empty(n, shape), cur_to=TimeoutsMsg.empty(n, shape),
            chain_blk=BlockMsg.empty(shape + (k,)), chain_qc=QcMsg.empty(shape + (k,)),
            req_hqc_round=_zeros(shape), req_hcr=_zeros(shape),
        )


# ---------------------------------------------------------------------------
# Per-node record store (RecordStoreState, record_store.rs:93-119).
# Field leading dims below are written for ONE node; in SimState every array
# gains a leading [N] owner dim (and vmap adds the instance dim above that).
# ---------------------------------------------------------------------------


@struct.dataclass
class Store:
    # Verified blocks table [W, V].
    blk_valid: Array
    blk_round: Array
    blk_author: Array
    blk_prev_round: Array
    blk_prev_tag: Array
    blk_time: Array
    blk_cmd_proposer: Array
    blk_cmd_index: Array
    blk_tag: Array
    # Verified QCs table [W, V].
    qc_valid: Array
    qc_round: Array
    qc_blk_var: Array      # variant of certified block at slot qc_round % W
    qc_state_depth: Array
    qc_state_tag: Array
    qc_commit_valid: Array
    qc_commit_depth: Array
    qc_commit_tag: Array
    qc_votes_lo: Array     # uint32 author-bit mask of the aggregated votes
    qc_votes_hi: Array
    qc_author: Array
    qc_tag: Array
    # Votes at the current round, per author [N].
    vt_valid: Array
    vt_blk_var: Array
    vt_state_depth: Array
    vt_state_tag: Array
    vt_commit_valid: Array
    vt_commit_depth: Array
    vt_commit_tag: Array
    # Ballot (ElectionState::Ongoing, record_store.rs:125-134): weight per
    # (block variant, state slot); 2 state slots per variant tolerate one
    # bogus-state Byzantine vote per variant.
    bal_used: Array        # [V, 2] bool
    bal_weight: Array      # [V, 2]
    bal_state_depth: Array # [V, 2]
    bal_state_tag: Array   # [V, 2]
    # Timeouts at the current round, per author [N].
    to_valid: Array
    to_hcbr: Array
    to_weight: Array       # scalar: current_timeouts_weight
    # Snapshot of the highest TC (record_store.rs:112): per author [N].
    tc_valid: Array
    tc_hcbr: Array
    # Scalars.
    epoch_id: Array
    initial_round: Array       # round of the 'initial' QC (0 normally; the
                               # anchor QC's round after a state-sync jump)
    initial_tag: Array         # uint32: QuorumCertificateHash(hash(epoch_id))
    initial_state_depth: Array
    initial_state_tag: Array   # uint32
    current_round: Array
    proposed_var: Array        # variant of current_proposed_block, -1 = none
    election: Array            # ELECTION_*
    won_var: Array
    won_slot: Array            # ballot state slot that won
    hqc_round: Array           # 0 = initial
    hqc_var: Array
    htc_round: Array
    hcr: Array                 # highest_committed_round
    hcc_valid: Array           # bool
    hcc_round: Array
    hcc_var: Array
    anchored: Array            # bool: initial QC is a state-sync jump anchor
                               # with unknown history (see store.vote_committed_state)

    @classmethod
    def initial(cls, p: SimParams, shape=()):
        W, V, N = p.window, p.variants, p.n_nodes
        wv = shape + (W, V)
        na = shape + (N,)
        v2 = shape + (V, 2)
        init_tag = jnp.broadcast_to(H.epoch_initial_tag(0), shape).astype(jnp.uint32)
        state0 = jnp.broadcast_to(H.initial_state_tag(), shape).astype(jnp.uint32)
        return cls(
            blk_valid=_zeros(wv, jnp.bool_), blk_round=_zeros(wv), blk_author=_zeros(wv),
            blk_prev_round=_zeros(wv), blk_prev_tag=_zeros(wv, jnp.uint32),
            blk_time=_zeros(wv), blk_cmd_proposer=_zeros(wv), blk_cmd_index=_zeros(wv),
            blk_tag=_zeros(wv, jnp.uint32),
            qc_valid=_zeros(wv, jnp.bool_), qc_round=_zeros(wv), qc_blk_var=_zeros(wv),
            qc_state_depth=_zeros(wv), qc_state_tag=_zeros(wv, jnp.uint32),
            qc_commit_valid=_zeros(wv, jnp.bool_), qc_commit_depth=_zeros(wv),
            qc_commit_tag=_zeros(wv, jnp.uint32),
            qc_votes_lo=_zeros(wv, jnp.uint32), qc_votes_hi=_zeros(wv, jnp.uint32),
            qc_author=_zeros(wv), qc_tag=_zeros(wv, jnp.uint32),
            vt_valid=_zeros(na, jnp.bool_), vt_blk_var=_zeros(na),
            vt_state_depth=_zeros(na), vt_state_tag=_zeros(na, jnp.uint32),
            vt_commit_valid=_zeros(na, jnp.bool_), vt_commit_depth=_zeros(na),
            vt_commit_tag=_zeros(na, jnp.uint32),
            bal_used=_zeros(v2, jnp.bool_), bal_weight=_zeros(v2),
            bal_state_depth=_zeros(v2), bal_state_tag=_zeros(v2, jnp.uint32),
            to_valid=_zeros(na, jnp.bool_), to_hcbr=_zeros(na),
            to_weight=_zeros(shape),
            tc_valid=_zeros(na, jnp.bool_), tc_hcbr=_zeros(na),
            epoch_id=_zeros(shape),
            initial_round=_zeros(shape),
            initial_tag=init_tag,
            initial_state_depth=_zeros(shape),
            initial_state_tag=state0,
            current_round=jnp.ones(shape, jnp.int32),  # rounds start at 1
            proposed_var=jnp.full(shape, -1, jnp.int32),
            election=_zeros(shape), won_var=_zeros(shape), won_slot=_zeros(shape),
            hqc_round=_zeros(shape), hqc_var=_zeros(shape), htc_round=_zeros(shape),
            hcr=_zeros(shape), hcc_valid=_zeros(shape, jnp.bool_),
            hcc_round=_zeros(shape), hcc_var=_zeros(shape),
            anchored=_zeros(shape, jnp.bool_),
        )


@struct.dataclass
class Pacemaker:
    """PacemakerState (/root/reference/librabft-v2/src/pacemaker.rs:59-78)."""

    active_epoch: Array
    active_round: Array
    active_leader: Array       # -1 = none
    round_start: Array         # NodeTime we entered the round
    round_duration: Array

    @classmethod
    def initial(cls, shape=()):
        return cls(
            active_epoch=_zeros(shape), active_round=_zeros(shape),
            active_leader=jnp.full(shape, -1, jnp.int32),
            round_start=_zeros(shape), round_duration=_zeros(shape),
        )


@struct.dataclass
class NodeExtra:
    """NodeState scalar fields + CommitTracker
    (/root/reference/librabft-v2/src/node.rs:28-60)."""

    latest_voted_round: Array
    locked_round: Array
    latest_query_all: Array
    tracker_epoch: Array
    tracker_hcr: Array
    tracker_commit_time: Array

    @classmethod
    def initial(cls, shape=()):
        return cls(
            latest_voted_round=_zeros(shape), locked_round=_zeros(shape),
            latest_query_all=_zeros(shape), tracker_epoch=_zeros(shape),
            tracker_hcr=_zeros(shape), tracker_commit_time=_zeros(shape),
        )


@struct.dataclass
class Context:
    """SimulatedContext analog
    (/root/reference/bft-lib/src/simulated_context.rs:75-108): rolling-hash
    ledger + committed-history ring."""

    next_cmd_index: Array
    commit_count: Array
    last_depth: Array
    last_tag: Array           # uint32
    sync_jumps: Array
    skipped_commits: Array    # depths never delivered to the log: K-tail
                              # catch-up bypasses + state-sync-jump adoption.
                              # Invariant: commit_count + skipped == last_depth.
    log_round: Array          # [H]
    log_depth: Array          # [H]
    log_tag: Array            # [H] uint32

    @classmethod
    def initial(cls, p: SimParams, shape=()):
        h = shape + (p.commit_log,)
        return cls(
            next_cmd_index=_zeros(shape), commit_count=_zeros(shape),
            last_depth=_zeros(shape),
            last_tag=jnp.broadcast_to(H.initial_state_tag(), shape).astype(jnp.uint32),
            sync_jumps=_zeros(shape), skipped_commits=_zeros(shape),
            log_round=_zeros(h), log_depth=_zeros(h), log_tag=_zeros(h, jnp.uint32),
        )


def payload_template(p: SimParams) -> Payload:
    return Payload.empty(p.n_nodes, p.chain_k)


def payload_width(p: SimParams) -> int:
    """Packed width F of one Payload (see pack_payload)."""
    return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(
        payload_template(p)))


def pack_payload(pay: Payload) -> Array:
    """Flatten a Payload struct into one int32 [F] vector (bit-preserving).

    In transit a message is opaque, so the queue stores payloads as single
    wide rows: enqueue/dequeue/bank-select become one array op each instead
    of ~60 per-leaf gathers/scatters — the dominant op-count (and XLA
    compile-time) cost of the step function.
    """
    parts = []
    for leaf in jax.tree_util.tree_leaves(pay):
        flat = jnp.asarray(leaf).reshape((-1,))
        if flat.dtype == jnp.uint32:
            flat = jax.lax.bitcast_convert_type(flat, jnp.int32)
        else:
            flat = flat.astype(jnp.int32)
        parts.append(flat)
    return jnp.concatenate(parts)


def unpack_payload(p: SimParams, vec: Array) -> Payload:
    """Inverse of pack_payload for one [F] row."""
    template = payload_template(p)
    leaves, treedef = jax.tree_util.tree_flatten(template)
    out = []
    off = 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.ndim else 1
        piece = vec[off:off + n]
        off += n
        if leaf.dtype == jnp.uint32:
            piece = jax.lax.bitcast_convert_type(piece, jnp.uint32)
        elif leaf.dtype == jnp.bool_:
            piece = piece != 0
        out.append(piece.reshape(leaf.shape))
    return jax.tree_util.tree_unflatten(treedef, out)


@struct.dataclass
class Queue:
    """Fixed-capacity network-message table (replaces the BinaryHeap,
    /root/reference/bft-lib/src/simulator.rs:29).  Payloads are stored
    packed ([CM, F] int32, see pack_payload)."""

    valid: Array     # [CM] bool
    time: Array      # [CM] global time
    kind: Array      # [CM]
    stamp: Array     # [CM]
    sender: Array    # [CM]
    receiver: Array  # [CM]
    payload: Array   # [CM, F] int32 (packed Payload rows)

    @classmethod
    def initial(cls, p: SimParams, shape=()):
        cm = shape + (p.queue_cap,)
        return cls(
            valid=_zeros(cm, jnp.bool_), time=_zeros(cm), kind=_zeros(cm),
            stamp=_zeros(cm), sender=_zeros(cm), receiver=_zeros(cm),
            payload=_zeros(cm + (payload_width(p),)),
        )


@struct.dataclass
class SimState:
    """One simulated instance: N nodes + network.  vmap over a leading batch
    dim gives the fleet (Simulator, /root/reference/bft-lib/src/simulator.rs:26)."""

    store: Store          # fields [N, ...]
    pm: Pacemaker         # fields [N]
    node: NodeExtra       # fields [N]
    ctx: Context          # fields [N, ...]
    queue: Queue
    # Cross-epoch handoff: the response payload captured at this node's last
    # epoch switch, built from the pre-switch store (old epoch), served to
    # requesters still in that epoch (data_sync.rs:82-92 semantics).  Absent
    # when SimParams.epoch_handoff is False (zero-width arrays).
    ho_pay: Array         # [N, F] packed Payload rows (or [N, 0])
    ho_epoch: Array       # [N] epoch the pack belongs to; -1 = none
    timer_time: Array     # [N] global time of each node's (single) pending timer
    timer_stamp: Array    # [N]
    startup: Array        # [N] startup_time (global)
    weights: Array        # [N] voting rights
    byz_equivocate: Array # [N] bool
    byz_silent: Array     # [N] bool
    byz_forge_qc: Array   # [N] bool: notifications carry a quorum-less forged hqc
    clock: Array          # global clock
    stamp_ctr: Array      # event/rng counter
    halted: Array         # bool
    seed: Array           # uint32 instance seed
    max_clock: Array      # i32 horizon (dynamic: doesn't force recompiles)
    drop_u32: Array       # u32 drop threshold (dynamic)
    # Metrics.
    n_events: Array
    n_msgs_sent: Array
    n_msgs_dropped: Array
    n_queue_full: Array
    # Round-switch trace ring (DataWriter capability,
    # /root/reference/bft-lib/src/data_writer.rs:34-49): entry = (node, round,
    # global time) appended whenever a node enters a higher pacemaker round.
    trace_node: Array   # [T]
    trace_round: Array  # [T]
    trace_time: Array   # [T]
    trace_count: Array
    # Telemetry (telemetry/plane.py; both zero-width when
    # SimParams.telemetry is off): the [M] metrics plane and the
    # [K, FR_COLS] flight-recorder ring (kind, actor, time, round, queue
    # depth per processed event; running count in the plane's fr_count
    # slot).
    metrics: Array      # [M] int32
    flight: Array       # [K, FR_COLS] int32
    # Consensus watchdog plane (telemetry/stream.py; zero-width when
    # SimParams.watchdog is off): detector state + trip counters — see
    # stream.WD_SLOTS.  Trip counts ride the fleet digest on the
    # run_sharded halt poll, so anomalies surface live.
    wd: Array           # [WD] int32
    # Per-slot traced scenario plane (SimParams.scenario; serve/): the
    # instance's OWN delay quantile table and commit-chain selector ride
    # as state, so one executable serves heterogeneous scenarios and the
    # admission path installs a new scenario with a device write, never a
    # recompile.  Both zero-width when the scenario plane is off; READ-
    # ONLY config — the step passes them through untouched (pinned by the
    # graph audit's scenario R6 arm).
    sc_delay: Array     # [T] int32 delay table row ([0] when off)
    sc_commit: Array    # [1] int32 commit-chain (2|3; [0] when off)
    # Adversary plane (SimParams.adversary; adversary/): per-slot traced
    # attack state — the windowed attack schedule, per-link extra-delay
    # matrix, and partition row the engines decode in-graph.  All
    # zero-width when off; READ-ONLY config when on (pass-through pinned
    # by the graph audit's R6 adversary arm), installed by
    # adversary/dsl.AttackProgram.install or per-slot via serve/.
    adv_sched: Array    # [W, ADV_FIELDS] int32 ([0, F] when off)
    adv_link: Array     # [n, n] int32 per-link extra delay ([0, 0] off)
    adv_group: Array    # [n] int32 partition group ([0] when off)
    adv_heal: Array     # [1] int32 partition heal time ([0] when off)
