"""EpochConfiguration as weight tensors
(/root/reference/bft-lib/src/configuration.rs:18-76).

Voting rights are an int32 vector ``weights[N]`` (index = author).  Author
picking is cumsum + a branchless right-insertion count instead of the
reference's linear scan: O(N) elementwise work that vectorizes across
instances with no data-dependent control flow (jnp.searchsorted's O(log N)
binary search lowers to an XLA while loop, which costs more per TPU step
than the whole N-element sum).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils import hashing as H

#: Mesh axis the author dimension shards over (parallel/mesh.py).  Quorum
#: aggregations psum partial sums over it when ``SimParams.mp_authors`` is
#: on — the very-large-committee (N >> 64) scale-out path, where one chip
#: shouldn't hold the whole author axis.
MP_AXIS = "mp"


def mp_axis(p) -> str | None:
    """The axis name the quorum aggregations reduce over for these params
    (None = single-chip author math, the default).  When it returns
    ``MP_AXIS`` the caller must be tracing inside a ``shard_map`` (or other
    axis-binding transform) that binds 'mp' with the author tables sharded
    over it — see parallel/sharded.py."""
    return MP_AXIS if getattr(p, "mp_authors", False) else None


def _psum(x, axis_name):
    return jax.lax.psum(x, axis_name) if axis_name else x


def total_votes(weights, axis_name=None):
    return _psum(jnp.sum(weights, axis=-1), axis_name)


def quorum_threshold(weights, axis_name=None):
    """2N/3 + 1 (configuration.rs:52-56)."""
    return 2 * total_votes(weights, axis_name) // 3 + 1


def validity_threshold(weights, axis_name=None):
    """(N + 2) / 3 (configuration.rs:58-62)."""
    return (total_votes(weights, axis_name) + 2) // 3


def count_votes(weights, author_mask, axis_name=None):
    """Sum of voting rights over a boolean author mask (configuration.rs:43).

    With ``axis_name`` the author axis is sharded over that mesh axis: each
    shard sums its local authors and the psum rides ICI.  This one function
    is both the single-chip quorum check and the mp-sharded one
    (parallel/sharded.py wraps it in shard_map; the step's quorum sites in
    core/store.py arm it via :func:`mp_axis`)."""
    return _psum(jnp.sum(jnp.where(author_mask, weights, 0), axis=-1),
                 axis_name)


def pick_author(weights, seed_u32):
    """Weighted author choice: first author with cumweight > target
    (configuration.rs:65-75).  ``seed_u32`` is a uint32 uniform draw."""
    total = total_votes(weights).astype(jnp.uint32)
    target = (seed_u32.astype(jnp.uint32) % total).astype(jnp.int32)
    cum = jnp.cumsum(weights, axis=-1)
    # Right-insertion point == #{i : cum[i] <= target}.  Branchless on
    # purpose: jnp.searchsorted lowers to an XLA while-loop binary search,
    # which costs more per TPU step than this whole N-element sum.
    return jnp.sum((cum <= jnp.expand_dims(target, -1)).astype(jnp.int32),
                   axis=-1)


def leader_of_round(weights, round_):
    """PacemakerState::leader (/root/reference/librabft-v2/src/pacemaker.rs:100):
    hash the round, pick an author weighted by voting rights."""
    u = H.fold(H.TAG_LEADER, jnp.asarray(round_).astype(jnp.uint32))
    return pick_author(weights, u)
