"""EpochConfiguration as weight tensors
(/root/reference/bft-lib/src/configuration.rs:18-76).

Voting rights are an int32 vector ``weights[N]`` (index = author).  Author
picking is cumsum + searchsorted instead of the reference's linear scan, so it
vectorizes across instances and stays O(log N) per lookup on device.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..utils import hashing as H


def total_votes(weights):
    return jnp.sum(weights, axis=-1)


def quorum_threshold(weights):
    """2N/3 + 1 (configuration.rs:52-56)."""
    return 2 * total_votes(weights) // 3 + 1


def validity_threshold(weights):
    """(N + 2) / 3 (configuration.rs:58-62)."""
    return (total_votes(weights) + 2) // 3


def count_votes(weights, author_mask):
    """Sum of voting rights over a boolean author mask (configuration.rs:43)."""
    return jnp.sum(jnp.where(author_mask, weights, 0), axis=-1)


def pick_author(weights, seed_u32):
    """Weighted author choice: first author with cumweight > target
    (configuration.rs:65-75).  ``seed_u32`` is a uint32 uniform draw."""
    total = total_votes(weights).astype(jnp.uint32)
    target = (seed_u32.astype(jnp.uint32) % total).astype(jnp.int32)
    cum = jnp.cumsum(weights, axis=-1)
    return jnp.searchsorted(cum, target, side="right").astype(jnp.int32)


def leader_of_round(weights, round_):
    """PacemakerState::leader (/root/reference/librabft-v2/src/pacemaker.rs:100):
    hash the round, pick an author weighted by voting rights."""
    u = H.fold(H.TAG_LEADER, jnp.asarray(round_).astype(jnp.uint32))
    return pick_author(weights, u)
