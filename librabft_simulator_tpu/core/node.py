"""NodeState: the main per-node protocol loop ``update_node``.

Tensor re-expression of ``impl ConsensusNode for NodeState``
(/root/reference/librabft-v2/src/node.rs:206-305) + ``process_commits``
(node.rs:308-352) + ``CommitTracker`` (node.rs:354-398).

All functions operate on single-node slices (per-author axes keep their [N]
dim); the simulator vmaps/indexes the node dim, and vmap over instances sits
above that.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from . import pacemaker as pm_ops
from . import store as store_ops
from ..telemetry import profiling
from ..utils.xops import wset
from .types import (
    NEVER, Context, NodeExtra, Pacemaker, SimParams, Store, pack_payload,
    sat_add,
)

I32 = jnp.int32


def _i32(x):
    return jnp.asarray(x, I32)


@struct.dataclass
class NodeUpdateActions:
    """NodeUpdateActions (/root/reference/bft-lib/src/interfaces.rs:12-21):
    ``should_send``/``should_broadcast`` merged into one receiver mask, plus
    the cross-epoch handoff capture (old-epoch response pack built at an
    epoch switch; empty [0] when SimParams.epoch_handoff is off)."""

    next_sched: jnp.ndarray    # NodeTime
    send_mask: jnp.ndarray     # [N] bool — receivers of our notification
    should_query_all: jnp.ndarray
    ho_switched: jnp.ndarray   # bool: this update crossed an epoch boundary
    ho_epoch: jnp.ndarray      # epoch the pack belongs to
    ho_pack: jnp.ndarray       # [F] packed old-epoch response (or [0])


def update_node(
    p: SimParams,
    s: Store,
    pm: Pacemaker,
    nx: NodeExtra,
    ctx: Context,
    weights,
    author,
    clock,
    dur_table,
):
    """One step of the protocol main loop (node.rs:240-304).

    Returns (store, pm, node_extra, ctx, NodeUpdateActions).
    """
    n = p.n_nodes
    author = _i32(author)
    # --- Pacemaker update + its actions (node.rs:246-254, 177-204).
    pm, pa = pm_ops.update_pacemaker(
        p, pm, s, weights, author, s.epoch_id, nx.latest_query_all, clock, dur_table
    )
    send_mask = (jnp.arange(n) == pa.send_leader) & (pa.send_leader >= 0)
    # Create a timeout; never vote at a round we timed out
    # (process_pacemaker_actions, node.rs:191-196).
    s_to, _ = store_ops.create_timeout(p, s, weights, author, pa.timeout_round)
    s = store_ops._sel(pa.should_create_timeout, s_to, s)
    nx = nx.replace(
        latest_voted_round=jnp.where(
            pa.should_create_timeout,
            jnp.maximum(nx.latest_voted_round, pa.timeout_round),
            nx.latest_voted_round,
        )
    )
    # Propose a block (node.rs:197-200): fetch() always yields the next
    # (author, index) command (simulated_context.rs:116-125).
    s_pb, _ = store_ops.propose_block(
        p, s, weights, author, pa.propose_prev_round, pa.propose_prev_tag,
        clock, ctx.next_cmd_index,
    )
    s = store_ops._sel(pa.should_propose, s_pb, s)
    ctx = ctx.replace(
        next_cmd_index=ctx.next_cmd_index + jnp.where(pa.should_propose, 1, 0)
    )

    # --- Vote on the proposed block (node.rs:255-276).
    has_prop = pm_ops.proposed_block_valid(pm, s)
    bvar = jnp.maximum(s.proposed_var, 0)
    block_round = s.current_round
    sl = jnp.remainder(block_round, p.window)
    proposer = s.blk_author[sl, bvar]
    prev_r = store_ops.previous_round(p, s, block_round, bvar)
    may_vote = has_prop & (block_round > nx.latest_voted_round) & (prev_r >= nx.locked_round)
    second_prev = store_ops.second_previous_round(p, s, block_round, bvar)
    nx = nx.replace(
        latest_voted_round=jnp.where(may_vote, block_round, nx.latest_voted_round),
        locked_round=jnp.where(
            may_vote, jnp.maximum(nx.locked_round, second_prev), nx.locked_round
        ),
    )
    s_v, vote_ok = store_ops.create_vote(p, s, weights, author, block_round, bvar)
    voted = may_vote & vote_ok
    s = store_ops._sel(may_vote, s_v, s)
    # Send our vote to the proposer (replaces pacemaker's should_send,
    # node.rs:271-274).
    send_mask = jnp.where(voted, jnp.arange(n) == proposer, send_mask)

    # --- Mint a QC if our proposal won (node.rs:277-283).
    s, qc_created = store_ops.check_new_qc(p, s, weights, author)
    broadcast = pa.should_broadcast | qc_created
    next_sched = jnp.where(qc_created, _i32(clock), pa.next_sched)

    # --- Deliver commits / switch epochs (node.rs:284-285, 308-352).
    with profiling.scope("commit_delivery"):
        s, nx, ctx, ho_switched, ho_epoch, ho_pack = process_commits(
            p, s, nx, ctx, weights, author)

    # --- Commit tracker (node.rs:286-297, 363-397).
    nx, tr_query_all, tr_next = update_tracker(p, nx, s, clock)
    query_all = pa.should_query_all | tr_query_all
    next_sched = jnp.minimum(next_sched, tr_next)
    nx = nx.replace(
        latest_query_all=jnp.where(query_all, _i32(clock), nx.latest_query_all)
    )
    send_mask = send_mask | jnp.where(broadcast, jnp.arange(n) != author, False)
    actions = NodeUpdateActions(
        next_sched=next_sched, send_mask=send_mask, should_query_all=query_all,
        ho_switched=ho_switched, ho_epoch=ho_epoch, ho_pack=ho_pack,
    )
    return s, pm, nx, ctx, actions


def process_commits(p: SimParams, s: Store, nx: NodeExtra, ctx: Context, weights,
                    author=0):
    """node.rs:313-351: deliver newly committed states to the context in
    ascending round order; on an epoch boundary, rebuild the record store for
    the new epoch and stop delivering.

    Returns (store, nx, ctx, ho_switched, ho_epoch, ho_pack): the ho_* values
    are the cross-epoch handoff capture — the response payload of the
    POST-update, PRE-switch store (the reference keeps whole previous-epoch
    stores, node.rs record_store_at; this keeps one bounded pack), packed, or
    a [0] placeholder when SimParams.epoch_handoff is off."""
    keep, rounds, depths, tags = store_ops.committed_states_after(p, s, nx.tracker_hcr)
    H_ = p.commit_log

    def deliver(carry, x):
        (cc, lc_d, lc_t, sk, lr, ld, lt, stopped, sw, sw_e, sw_d, sw_t) = carry
        valid, r, d, t = x
        do = valid & ~stopped & (d > lc_d)
        # StateFinalizer::commit (simulated_context.rs:161-185): ring append.
        pos = jnp.remainder(cc, H_)
        lr = wset(lr, pos, r, when=do)
        ld = wset(ld, pos, d, when=do)
        lt = wset(lt, pos, t, when=do)
        cc = cc + jnp.where(do, 1, 0)
        # Depths between the last delivery and this one were bypassed (the
        # K-tail response didn't carry their records): account them.
        sk = sk + jnp.where(do, d - lc_d - 1, 0)
        lc_d = jnp.where(do, d, lc_d)
        lc_t = jnp.where(do, t, lc_t)
        # EpochReader::read_epoch_id = depth // commands_per_epoch
        # (simulated_context.rs:200-207).
        new_epoch = d // p.commands_per_epoch
        switch = do & (new_epoch > s.epoch_id)
        sw = sw | switch
        sw_e = jnp.where(switch, new_epoch, sw_e)
        sw_d = jnp.where(switch, d, sw_d)
        sw_t = jnp.where(switch, t, sw_t)
        stopped = stopped | switch
        return (cc, lc_d, lc_t, sk, lr, ld, lt, stopped, sw, sw_e, sw_d, sw_t), None

    init = (
        ctx.commit_count, ctx.last_depth, ctx.last_tag, ctx.skipped_commits,
        ctx.log_round, ctx.log_depth, ctx.log_tag,
        jnp.bool_(False), jnp.bool_(False), _i32(0), _i32(0), jnp.zeros((), jnp.uint32),
    )
    (cc, lc_d, lc_t, sk, lr, ld, lt, _, sw, sw_e, sw_d, sw_t), _ = jax.lax.scan(
        deliver, init, (keep, rounds, depths, tags), unroll=p.unroll
    )
    ctx = ctx.replace(
        commit_count=cc, last_depth=lc_d, last_tag=lc_t, skipped_commits=sk,
        log_round=lr, log_depth=ld, log_tag=lt,
    )
    # Cross-epoch handoff capture: the old store's full response pack (chain
    # K-tail + highest CC), built before the switch discards it.
    old_epoch = s.epoch_id
    if p.epoch_handoff:
        from . import data_sync

        notif_old = data_sync.create_notification(p, s, author)
        resp_old = data_sync.handle_request(p, s, author, notif_old,
                                            notif=notif_old)
        ho_pack = pack_payload(resp_old)
    else:
        ho_pack = jnp.zeros((0,), I32)
    # Epoch switch (node.rs:330-348): fresh record store anchored at the
    # committed state; reset voting constraints.
    s_new = new_epoch_store(p, s, sw_e, sw_d, sw_t)
    s = store_ops._sel(sw, s_new, s)
    nx = nx.replace(
        latest_voted_round=jnp.where(sw, 0, nx.latest_voted_round),
        locked_round=jnp.where(sw, 0, nx.locked_round),
    )
    return s, nx, ctx, sw, old_epoch, ho_pack


def new_epoch_store(p: SimParams, s: Store, epoch, state_depth, state_tag) -> Store:
    """RecordStoreState::new for a later epoch (record_store.rs:169-198)."""
    from ..utils import hashing as H

    fresh = Store.initial(p)
    return fresh.replace(
        epoch_id=_i32(epoch),
        initial_tag=H.epoch_initial_tag(jnp.asarray(epoch).astype(jnp.uint32)),
        initial_state_depth=_i32(state_depth),
        initial_state_tag=state_tag,
    )


def update_tracker(p: SimParams, nx: NodeExtra, s: Store, clock):
    """CommitTracker::update_tracker (node.rs:363-397).
    Returns (node_extra, should_query_all, next_sched)."""
    epoch_adv = s.epoch_id > nx.tracker_epoch
    commit_adv = s.hcr > nx.tracker_hcr
    bump = epoch_adv | commit_adv
    nx = nx.replace(
        tracker_epoch=jnp.maximum(nx.tracker_epoch, s.epoch_id),
        tracker_hcr=jnp.where(bump, s.hcr, nx.tracker_hcr),
        tracker_commit_time=jnp.where(bump, _i32(clock), nx.tracker_commit_time),
    )
    base = jnp.maximum(nx.tracker_commit_time, nx.latest_query_all)
    # Saturating add (types.sat_add): base can approach NEVER or be a
    # negative pre-startup local time.
    deadline = sat_add(base, _i32(p.target_commit_interval))
    should_query_all = clock >= deadline
    deadline = jnp.where(
        should_query_all, sat_add(clock, _i32(p.target_commit_interval)),
        deadline)
    return nx, should_query_all, deadline
