"""Pacemaker: round synchronization, leader election, timeouts, query-all.

Tensor re-expression of ``PacemakerState::update_pacemaker``
(/root/reference/librabft-v2/src/pacemaker.rs:140-221).  Round durations
(delta * n^gamma) come from a host-precomputed integer table; the query-all
period (lambda * duration) uses 16.16 fixed-point — no device floats, so the
oracle replays decisions bit-identically.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

from . import config
from . import store as store_ops
from .types import NEVER, Pacemaker, SimParams, Store, sat_add

I32 = jnp.int32


def _i32(x):
    return jnp.asarray(x, I32)


@struct.dataclass
class PacemakerActions:
    """PacemakerUpdateActions (/root/reference/librabft-v2/src/pacemaker.rs:17-31)."""

    should_propose: jnp.ndarray       # bool; on top of (prev_round, prev_tag)
    propose_prev_round: jnp.ndarray
    propose_prev_tag: jnp.ndarray
    should_create_timeout: jnp.ndarray  # bool, for `timeout_round`
    timeout_round: jnp.ndarray
    send_leader: jnp.ndarray          # author to sync with, -1 = none
    should_broadcast: jnp.ndarray
    should_query_all: jnp.ndarray
    next_sched: jnp.ndarray           # NodeTime


def round_duration(p: SimParams, dur_table, active_round, hcr):
    """pacemaker.rs:111-124: duration(round) = delta * n^gamma with
    n = round - (hcr > 0 ? hcr + 2 : 0)."""
    hccr = jnp.where(hcr > 0, hcr + 2, 0)
    n = jnp.clip(active_round - hccr, 0, p.dur_table_size - 1)
    return dur_table[n]


def update_pacemaker(
    p: SimParams,
    pm: Pacemaker,
    s: Store,
    weights,
    author,
    epoch_id,
    latest_query_all,
    clock,
    dur_table,
):
    """pacemaker.rs:142-207.  Returns (new_pm, PacemakerActions)."""
    active_round = jnp.maximum(s.hqc_round, s.htc_round) + 1
    enter = (epoch_id > pm.active_epoch) | (
        (epoch_id == pm.active_epoch) & (active_round > pm.active_round)
    )
    leader = config.leader_of_round(weights, active_round)
    duration = round_duration(p, dur_table, active_round, s.hcr)
    pm2 = Pacemaker(
        active_epoch=jnp.where(enter, _i32(epoch_id), pm.active_epoch),
        active_round=jnp.where(enter, active_round, pm.active_round),
        active_leader=jnp.where(enter, leader, pm.active_leader),
        round_start=jnp.where(enter, _i32(clock), pm.round_start),
        round_duration=jnp.where(enter, duration, pm.round_duration),
    )
    send_leader = jnp.where(
        enter & (pm2.active_leader != author), pm2.active_leader, _i32(-1)
    )

    next_sched = _i32(NEVER)
    # Leader with no proposal yet -> propose on top of the highest QC.
    has_prop = proposed_block_valid(pm2, s)
    hqc_r, hqc_t = store_ops.hqc_ref(p, s)
    should_propose = (pm2.active_leader == author) & ~has_prop
    should_broadcast = should_propose
    next_sched = jnp.where(should_propose, _i32(clock), next_sched)

    has_to = store_ops.has_timeout(s, author, pm2.active_round)
    # Saturating NodeTime sums (sat_add == the oracle's wide-int min(a+b,
    # NEVER)): round durations reach ~2^30 so plain adds overflow, and bases
    # (round_start / clock / latest_query_all) can be negative local times.
    timeout_deadline = sat_add(pm2.round_start, pm2.round_duration)
    past_deadline = clock >= timeout_deadline
    should_create_timeout = ~has_to & past_deadline
    should_broadcast = should_broadcast | should_create_timeout
    next_sched = jnp.where(
        ~has_to & ~past_deadline, jnp.minimum(next_sched, timeout_deadline), next_sched
    )
    # Once we hold a timeout, enforce periodic query-all (pacemaker.rs:195-204).
    # floor(lam_fp * d / 2^16) decomposed as hi*lam_fp + (lo*lam_fp >> 16)
    # (exact for lam <= 1) — the direct 32-bit product would wrap.
    d_hi, d_lo = pm2.round_duration >> 16, pm2.round_duration & 0xFFFF
    # Low-part product can reach 2^32 (lam == 1): keep it in uint32.
    lo_term = ((d_lo.astype(jnp.uint32) * jnp.uint32(p.lam_fp)) >> 16).astype(I32)
    period = d_hi * _i32(p.lam_fp) + lo_term
    qad = sat_add(latest_query_all, period)
    should_query_all = has_to & (clock >= qad)
    qad = jnp.where(should_query_all, sat_add(clock, period), qad)
    next_sched = jnp.where(has_to, jnp.minimum(next_sched, qad), next_sched)

    actions = PacemakerActions(
        should_propose=should_propose,
        propose_prev_round=hqc_r,
        propose_prev_tag=hqc_t,
        should_create_timeout=should_create_timeout,
        timeout_round=pm2.active_round,
        send_leader=send_leader,
        should_broadcast=should_broadcast,
        should_query_all=should_query_all,
        next_sched=next_sched,
    )
    return pm2, actions


def proposed_block_valid(pm: Pacemaker, s: Store):
    """RecordStore::proposed_block gating (record_store.rs:611-634): pacemaker
    must be on the store's epoch/round, a leader must exist, and a legitimate
    proposal must be recorded."""
    return (
        (pm.active_epoch == s.epoch_id)
        & (pm.active_round == s.current_round)
        & (pm.active_leader >= 0)
        & (s.proposed_var >= 0)
    )
