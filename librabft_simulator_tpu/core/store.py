"""Tensorized record store: verification, insertion, vote aggregation, QC
chaining and the commit rule.

Re-expresses ``RecordStoreState``
(/root/reference/librabft-v2/src/record_store.rs:93-541) as pure functions over
the round-windowed tables in :class:`~librabft_simulator_tpu.core.types.Store`.
Every function takes a *single-node* store slice (per-author axes retain their
[N] dim) and returns a new slice; conditionality is expressed by computing the
updated store and selecting per-field with the verification outcome, which keeps
everything jit/vmap-friendly (no data-dependent Python control flow).

Key mappings:
  verify_network_record   -> the ``ok`` predicates inside each insert_*
  try_insert_network_record -> insert_block / insert_vote / insert_qc / insert_timeout
  update_current_round    -> update_current_round (record_store.rs:207-219)
  update_commit_3chain_round -> update_commit_chain (record_store.rs:221-235),
      generalized to ``params.commit_chain`` (3 = LibraBFTv2, 2 = HotStuff-style;
      a static int normally, or a TRACED per-slot scalar when the scenario
      plane is on — types.TracedParams — in which case the commit-rule sites
      compute both depths and select, bit-identically per slot)
  vote_committed_state    -> vote_committed_state (record_store.rs:237-255)
  compute_state           -> compute_state (record_store.rs:426-454)
  check_for_new_quorum_certificate -> check_new_qc (record_store.rs:702-738)
  committed_states_after  -> committed_states_after (record_store.rs:557-574)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import config
from .types import (
    ELECTION_CLOSED,
    ELECTION_ONGOING,
    ELECTION_WON,
    BlockMsg,
    QcMsg,
    SimParams,
    Store,
    VoteMsg,
)
from ..telemetry import profiling
from ..utils import hashing as H
from ..utils.xops import wset

I32 = jnp.int32
U32 = jnp.uint32


def _i32(x):
    return jnp.asarray(x, I32)


def _sel(ok, new, old):
    """Per-field select of a whole struct/pytree on a scalar predicate."""
    return jax.tree.map(lambda a, b: jnp.where(ok, a, b), new, old)


def _slot(p: SimParams, r):
    return jnp.remainder(_i32(r), p.window)


# ---------------------------------------------------------------------------
# Lookups
# ---------------------------------------------------------------------------


def blk_find(p: SimParams, s: Store, r, tag):
    """Variant index of the block with content ``tag`` at round ``r``; -1 if
    absent.  Replaces ``blocks: HashMap<BlockHash, Block>`` lookups."""
    sl = _slot(p, r)
    match = s.blk_valid[sl] & (s.blk_round[sl] == r) & (s.blk_tag[sl] == tag)
    var = jnp.argmax(match).astype(I32)
    return jnp.where(jnp.any(match), var, _i32(-1))


def qc_find(p: SimParams, s: Store, r, tag):
    sl = _slot(p, r)
    match = s.qc_valid[sl] & (s.qc_round[sl] == r) & (s.qc_tag[sl] == tag)
    var = jnp.argmax(match).astype(I32)
    return jnp.where(jnp.any(match), var, _i32(-1))


def hqc_ref(p: SimParams, s: Store):
    """(round, tag) of the highest QC, or the initial QC
    (record_store.rs:553-555)."""
    sl = _slot(p, s.hqc_round)
    has_qc = s.hqc_round > s.initial_round
    tag = jnp.where(has_qc, s.qc_tag[sl, s.hqc_var], s.initial_tag)
    return s.hqc_round, tag


def _qc_state(p: SimParams, s: Store, r, var):
    sl = _slot(p, r)
    return s.qc_state_depth[sl, var], s.qc_state_tag[sl, var]


def _blk_prev(p: SimParams, s: Store, r, var):
    sl = _slot(p, r)
    return s.blk_prev_round[sl, var], s.blk_prev_tag[sl, var]


def _qc_blk_var(p: SimParams, s: Store, r, var):
    sl = _slot(p, r)
    return s.qc_blk_var[sl, var]


def prev_qc_of_block(p: SimParams, s: Store, blk_round, blk_var):
    """(found, prev_round, prev_var): the QC a block chains to; prev_var==-1
    means the epoch-initial (or jump-anchor) QC."""
    pr, pt = _blk_prev(p, s, blk_round, blk_var)
    is_initial = (pr == s.initial_round) & (pt == s.initial_tag)
    var = qc_find(p, s, pr, pt)
    found = is_initial | (var >= 0)
    return found, pr, jnp.where(is_initial, _i32(-1), var)


def qc_walk_back(p: SimParams, s: Store, start_valid, start_round, start_var, steps):
    """BackwardQuorumCertificateIterator (record_store.rs:137-166): from the QC
    at (start_round, start_var), follow block->previous-QC links for ``steps``
    hops.  Returns per-hop (valid, round, var) arrays, newest first."""

    def body(carry, _):
        alive, r, v = carry
        bvar = _qc_blk_var(p, s, r, v)
        found, pr, pv = prev_qc_of_block(p, s, r, bvar)
        hit_initial = alive & found & (pv < 0)  # chains directly to initial QC
        emit = (alive, r, v, hit_initial)
        alive2 = alive & found & (pv >= 0)
        return (alive2, jnp.where(alive2, pr, r), jnp.where(alive2, pv, v)), emit

    init = (jnp.asarray(start_valid) & (start_round > s.initial_round),
            _i32(start_round), _i32(start_var))
    _, (valids, rounds, vars_, hits) = jax.lax.scan(
        body, init, None, length=steps, unroll=p.unroll)
    return valids, rounds, vars_, hits


# ---------------------------------------------------------------------------
# Derived protocol values
# ---------------------------------------------------------------------------


def previous_round(p: SimParams, s: Store, blk_round, blk_var):
    """Round of the QC a block extends (record_store.rs:588-598)."""
    pr, _ = _blk_prev(p, s, blk_round, blk_var)
    return pr


def second_previous_round(p: SimParams, s: Store, blk_round, blk_var):
    """record_store.rs:600-609."""
    found, pr, pv = prev_qc_of_block(p, s, blk_round, blk_var)
    at_initial = pv < 0
    bvar = _qc_blk_var(p, s, pr, jnp.maximum(pv, 0))
    pr2, _ = _blk_prev(p, s, pr, bvar)
    return jnp.where(at_initial | ~found, s.initial_round, pr2)


def vote_committed_state(p: SimParams, s: Store, blk_round, blk_var):
    """(valid, depth, tag, undeterminable) of the state the commit rule would
    finalize if a QC formed on this block (record_store.rs:237-255),
    generalized to ``commit_chain`` C: the C-1 QCs below the block must have
    contiguous rounds; the oldest one's state is committed.

    ``undeterminable`` is True when the store is *anchored* (state-sync jump,
    data_sync.py) and the walk touched the synthetic anchor QC, whose history
    is unknown — the receiver must then trust the (signature-backed) commit
    fields of the incoming record rather than recompute them.

    ``p.commit_chain`` may be a TRACED per-slot scalar (types.TracedParams,
    scenario plane): the walk then runs to the max depth (2 hops) once and
    the C=2/C=3 predicates are selected by the traced value — per-slot
    values are bit-identical to the static graph of that depth."""
    C = p.commit_chain
    r_top = _i32(blk_round)
    found0, pr, pv = prev_qc_of_block(p, s, blk_round, blk_var)
    if isinstance(C, int):
        valids, rounds, vars_, hits = qc_walk_back(
            p, s, found0 & (pv >= 0), pr, jnp.maximum(pv, 0), C - 1
        )
        ok = jnp.bool_(True)
        prev_r = r_top
        for i in range(C - 1):
            ok = ok & valids[i] & (prev_r == rounds[i] + 1)
            prev_r = rounds[i]
        touched = (found0 & (pv < 0)) | jnp.any(hits[: C - 1])
        undet = s.anchored & touched
        d, t = _qc_state(p, s, rounds[C - 2], vars_[C - 2])
        zero_d = _i32(0)
        zero_t = jnp.zeros((), U32)
        return ok, jnp.where(ok, d, zero_d), jnp.where(ok, t, zero_t), undet
    # Traced commit_chain in {2, 3}.
    valids, rounds, vars_, hits = qc_walk_back(
        p, s, found0 & (pv >= 0), pr, jnp.maximum(pv, 0), 2
    )
    is3 = jnp.asarray(C, I32) >= 3
    ok2 = valids[0] & (r_top == rounds[0] + 1)
    ok3 = ok2 & valids[1] & (rounds[0] == rounds[1] + 1)
    ok = jnp.where(is3, ok3, ok2)
    touched2 = (found0 & (pv < 0)) | hits[0]
    touched = jnp.where(is3, touched2 | hits[1], touched2)
    undet = s.anchored & touched
    d2, t2 = _qc_state(p, s, rounds[0], vars_[0])
    d3, t3 = _qc_state(p, s, rounds[1], vars_[1])
    d = jnp.where(is3, d3, d2)
    t = jnp.where(is3, t3, t2)
    zero_d = _i32(0)
    zero_t = jnp.zeros((), U32)
    return ok, jnp.where(ok, d, zero_d), jnp.where(ok, t, zero_t), undet


def compute_state(p: SimParams, s: Store, blk_round, blk_var):
    """Execute the block's command on its parent state (record_store.rs:426-454
    + CommandExecutor::compute): rolling hash, depth + 1."""
    found, pr, pv = prev_qc_of_block(p, s, blk_round, blk_var)
    at_initial = pv < 0
    pd, pt = _qc_state(p, s, pr, jnp.maximum(pv, 0))
    base_d = jnp.where(at_initial, s.initial_state_depth, pd)
    base_t = jnp.where(at_initial, s.initial_state_tag, pt)
    sl = _slot(p, blk_round)
    tag = H.state_tag_next(
        base_t,
        s.blk_cmd_proposer[sl, blk_var],
        s.blk_cmd_index[sl, blk_var],
        s.blk_time[sl, blk_var],
    )
    return found, base_d + 1, tag


def update_commit_chain(p: SimParams, s: Store, qc_round, qc_var) -> Store:
    """The 3-chain (or C-chain) commit rule applied after inserting the QC at
    (qc_round, qc_var) (record_store.rs:221-235).  ``p.commit_chain`` may be
    a traced per-slot scalar (scenario plane): both depths are computed from
    one max-depth walk and the traced value selects, bit-identically per
    slot (see vote_committed_state)."""
    C = p.commit_chain
    if isinstance(C, int):
        valids, rounds, _, _ = qc_walk_back(p, s, True, qc_round, qc_var, C)
        ok = jnp.bool_(True)
        for i in range(C):
            ok = ok & valids[i]
            if i > 0:
                ok = ok & (rounds[i - 1] == rounds[i] + 1)
        r1 = rounds[C - 1]
    else:
        valids, rounds, _, _ = qc_walk_back(p, s, True, qc_round, qc_var, 3)
        is3 = jnp.asarray(C, I32) >= 3
        ok2 = valids[0] & valids[1] & (rounds[0] == rounds[1] + 1)
        ok3 = ok2 & valids[2] & (rounds[1] == rounds[2] + 1)
        ok = jnp.where(is3, ok3, ok2)
        r1 = jnp.where(is3, rounds[2], rounds[1])
    ok = ok & (r1 > s.hcr)
    return s.replace(
        hcr=jnp.where(ok, r1, s.hcr),
        hcc_valid=ok | s.hcc_valid,
        hcc_round=jnp.where(ok, _i32(qc_round), s.hcc_round),
        hcc_var=jnp.where(ok, _i32(qc_var), s.hcc_var),
    )


def update_current_round(s: Store, r) -> Store:
    """Advance the round and clear per-round aggregation state
    (record_store.rs:207-219)."""
    adv = _i32(r) > s.current_round
    z = jnp.zeros_like
    return s.replace(
        current_round=jnp.where(adv, _i32(r), s.current_round),
        proposed_var=jnp.where(adv, _i32(-1), s.proposed_var),
        vt_valid=jnp.where(adv, z(s.vt_valid), s.vt_valid),
        to_valid=jnp.where(adv, z(s.to_valid), s.to_valid),
        to_weight=jnp.where(adv, _i32(0), s.to_weight),
        bal_used=jnp.where(adv, z(s.bal_used), s.bal_used),
        bal_weight=jnp.where(adv, z(s.bal_weight), s.bal_weight),
        bal_state_depth=jnp.where(adv, z(s.bal_state_depth), s.bal_state_depth),
        bal_state_tag=jnp.where(adv, z(s.bal_state_tag), s.bal_state_tag),
        election=jnp.where(adv, _i32(ELECTION_ONGOING), s.election),
        won_var=jnp.where(adv, _i32(0), s.won_var),
        won_slot=jnp.where(adv, _i32(0), s.won_slot),
    )


# ---------------------------------------------------------------------------
# Record tags (content hashes; core of record.rs signing identities)
# ---------------------------------------------------------------------------


def block_tag(epoch, round_, author, prev_round, prev_tag, time, cmd_proposer, cmd_index):
    return H.fold(
        H.TAG_BLOCK, _u(epoch), _u(round_), _u(author), _u(prev_round), prev_tag,
        _u(time), _u(cmd_proposer), _u(cmd_index),
    )


def qc_tag(epoch, round_, blk_tag_, state_depth, state_tag, commit_valid,
           commit_depth, commit_tag, votes_lo, votes_hi, author):
    return H.fold(
        H.TAG_QC, _u(epoch), _u(round_), blk_tag_, _u(state_depth), state_tag,
        _u(commit_valid), _u(commit_depth), commit_tag, votes_lo, votes_hi, _u(author),
    )


def _u(x):
    return jnp.asarray(x).astype(U32)


def author_mask_words(mask):
    """Pack a [N<=64] author bool mask into two uint32 words (votes digest)."""
    n = mask.shape[-1]
    idx = jnp.arange(n)
    lo = jnp.sum(jnp.where(mask & (idx < 32), U32(1) << _u(jnp.minimum(idx, 31)), U32(0)),
                 axis=-1, dtype=U32)
    hi = jnp.sum(jnp.where(mask & (idx >= 32), U32(1) << _u(jnp.maximum(idx - 32, 0)), U32(0)),
                 axis=-1, dtype=U32)
    return lo, hi


def mask_weight(p: SimParams, weights, lo, hi):
    """Total voting weight of the authors set in the (lo, hi) bit mask, plus
    a validity flag rejecting bits outside 0..n-1 (an 'unknown author' in a
    QC vote list, record_store.rs:371-379)."""
    n = p.n_nodes
    idx = jnp.arange(n)
    word = jnp.where(idx < 32, lo, hi)
    bit = (word >> _u(jnp.where(idx < 32, idx, idx - 32))) & U32(1)
    w = jnp.sum(jnp.where(bit == 1, weights, 0))
    if n >= 64:
        known = jnp.bool_(True)
    elif n >= 32:
        known = (hi >> _u(n - 32)) == 0
    else:
        known = ((lo >> _u(n)) == 0) & (hi == U32(0))
    return w, known


# ---------------------------------------------------------------------------
# Insertions (verify_network_record + try_insert_network_record)
# ---------------------------------------------------------------------------


def _pick_variant(valid_col, round_col, tag_col, r, tag):
    """Choose a table variant for a new record at round ``r``: reuse
    stale/empty slots, detect duplicates, cap at V live variants.

    Returns (var, is_dup, has_room)."""
    stale0 = ~valid_col[0] | (round_col[0] != r)
    stale1 = ~valid_col[1] | (round_col[1] != r)
    dup0 = ~stale0 & (tag_col[0] == tag)
    dup1 = ~stale1 & (tag_col[1] == tag)
    is_dup = dup0 | dup1
    var = jnp.where(stale0, _i32(0), jnp.where(stale1, _i32(1), _i32(-1)))
    has_room = var >= 0
    return var, is_dup, has_room


def insert_block(p: SimParams, s: Store, weights, b: BlockMsg, rec_epoch):
    """record_store.rs:263-291 (verify) + :466-476 (insert)."""
    sl = _slot(p, b.round)
    var, is_dup, has_room = _pick_variant(s.blk_valid[sl], s.blk_round[sl], s.blk_tag[sl],
                                          b.round, b.tag)
    prev_initial = (b.prev_round == s.initial_round) & (b.prev_tag == s.initial_tag)
    prev_known = prev_initial | (qc_find(p, s, b.prev_round, b.prev_tag) >= 0)
    in_window = b.round > s.current_round - p.window
    ok = (
        b.valid
        & (rec_epoch == s.epoch_id)
        & ~is_dup
        & has_room
        & prev_known
        & (b.round > b.prev_round)  # rounds must be increasing; >=1 from initial
        & in_window
    )
    var = jnp.maximum(var, 0)
    s2 = s.replace(
        blk_valid=wset(s.blk_valid, (sl, var), True),
        blk_round=wset(s.blk_round, (sl, var), b.round),
        blk_author=wset(s.blk_author, (sl, var), b.author),
        blk_prev_round=wset(s.blk_prev_round, (sl, var), b.prev_round),
        blk_prev_tag=wset(s.blk_prev_tag, (sl, var), b.prev_tag),
        blk_time=wset(s.blk_time, (sl, var), b.time),
        blk_cmd_proposer=wset(s.blk_cmd_proposer, (sl, var), b.cmd_proposer),
        blk_cmd_index=wset(s.blk_cmd_index, (sl, var), b.cmd_index),
        blk_tag=wset(s.blk_tag, (sl, var), b.tag),
    )
    # current_proposed_block (record_store.rs:468-474): only the legitimate
    # leader's block at the current round becomes the proposal.
    is_proposal = (
        (b.round == s.current_round)
        & (config.leader_of_round(weights, s.current_round) == b.author)
    )
    s2 = s2.replace(
        proposed_var=jnp.where(is_proposal, var, s2.proposed_var),
    )
    return _sel(ok, s2, s), ok


def insert_vote(p: SimParams, s: Store, weights, v: VoteMsg):
    """record_store.rs:292-329 (verify) + :477-499 (insert + ballot)."""
    bvar = blk_find(p, s, v.round, v.blk_tag)
    cs_ok, cs_d, cs_t, cs_undet = vote_committed_state(
        p, s, v.round, jnp.maximum(bvar, 0))
    commit_match = cs_undet | (
        (v.commit_valid == cs_ok)
        & (~cs_ok | ((v.commit_depth == cs_d) & (v.commit_tag == cs_t)))
    )
    author = jnp.clip(v.author, 0, p.n_nodes - 1)
    ok = (
        v.valid
        & (v.epoch == s.epoch_id)
        & (bvar >= 0)
        & commit_match
        & (v.round == s.current_round)
        & ~s.vt_valid[author]
    )
    bvar = jnp.maximum(bvar, 0)
    s2 = s.replace(
        vt_valid=wset(s.vt_valid, author, True),
        vt_blk_var=wset(s.vt_blk_var, author, bvar),
        vt_state_depth=wset(s.vt_state_depth, author, v.state_depth),
        vt_state_tag=wset(s.vt_state_tag, author, v.state_tag),
        vt_commit_valid=wset(s.vt_commit_valid, author, v.commit_valid),
        vt_commit_depth=wset(s.vt_commit_depth, author, v.commit_depth),
        vt_commit_tag=wset(s.vt_commit_tag, author, v.commit_tag),
    )
    # Ballot update (ElectionState::Ongoing only).
    ongoing = s.election == ELECTION_ONGOING
    m0 = s2.bal_used[bvar, 0] & (s2.bal_state_depth[bvar, 0] == v.state_depth) \
        & (s2.bal_state_tag[bvar, 0] == v.state_tag)
    m1 = s2.bal_used[bvar, 1] & (s2.bal_state_depth[bvar, 1] == v.state_depth) \
        & (s2.bal_state_tag[bvar, 1] == v.state_tag)
    slot = jnp.where(
        m0, _i32(0),
        jnp.where(m1, _i32(1),
                  jnp.where(~s2.bal_used[bvar, 0], _i32(0),
                            jnp.where(~s2.bal_used[bvar, 1], _i32(1), _i32(-1)))),
    )
    has_slot = slot >= 0
    slot = jnp.maximum(slot, 0)
    w = weights[author]
    new_weight = s2.bal_weight[bvar, slot] + w
    do_ballot = ongoing & has_slot
    s3 = s2.replace(
        bal_used=wset(s2.bal_used, (bvar, slot), True),
        bal_weight=wset(s2.bal_weight, (bvar, slot), new_weight),
        bal_state_depth=wset(s2.bal_state_depth, (bvar, slot), v.state_depth),
        bal_state_tag=wset(s2.bal_state_tag, (bvar, slot), v.state_tag),
    )
    won = do_ballot & (new_weight >= config.quorum_threshold(
        weights, config.mp_axis(p)))
    s3 = s3.replace(
        election=jnp.where(won, _i32(ELECTION_WON), s3.election),
        won_var=jnp.where(won, bvar, s3.won_var),
        won_slot=jnp.where(won, slot, s3.won_slot),
    )
    s_final = _sel(do_ballot, s3, s2)
    return _sel(ok, s_final, s), ok


def insert_qc(p: SimParams, s: Store, weights, q: QcMsg):
    """record_store.rs:330-389 (verify) + :500-526 (insert).

    Vote-set re-verification on receipt (record_store.rs:371-387): the QC
    carries its aggregated author-bit mask (``votes_lo/hi``); the receiver
    checks (a) every masked author is a known index, (b) the masked voting
    weight reaches quorum, and (c) the QC content tag recomputes from the
    carried fields *including the mask* — the tag plays the role of the
    aggregate signature, so a forged mask or tampered field breaks it.
    Trust-model boundary: the tag is a hash, not a signature — a forger who
    recomputes the tag over a fabricated full-quorum mask passes these
    checks.  That mirrors the reference simulator's simulated-crypto model
    (hashes stand in for aggregate signatures); the stronger claim —
    unforgeable per-vote authentication — lives in the realnode stack
    (realnode/crypto.py, real Ed25519 over the wire).
    (Divergence note: on a failed state re-execution the reference leaves
    the QC in its map but skips the computed-value updates; we reject it
    entirely.)"""
    sl = _slot(p, q.round)
    var, is_dup, has_room = _pick_variant(s.qc_valid[sl], s.qc_round[sl], s.qc_tag[sl],
                                          q.round, q.tag)
    bvar = blk_find(p, s, q.round, q.blk_tag)
    bvar_c = jnp.maximum(bvar, 0)
    author_ok = s.blk_author[sl, bvar_c] == q.author
    cs_ok, cs_d, cs_t, cs_undet = vote_committed_state(p, s, q.round, bvar_c)
    commit_match = cs_undet | (
        (q.commit_valid == cs_ok)
        & (~cs_ok | ((q.commit_depth == cs_d) & (q.commit_tag == cs_t)))
    )
    exec_ok, st_d, st_t = compute_state(p, s, q.round, bvar_c)
    state_match = exec_ok & (st_d == q.state_depth) & (st_t == q.state_tag)
    in_window = q.round > s.current_round - p.window
    vote_w, authors_known = mask_weight(p, weights, q.votes_lo, q.votes_hi)
    quorum_ok = authors_known & (vote_w >= config.quorum_threshold(
        weights, config.mp_axis(p)))
    tag_ok = q.tag == qc_tag(
        q.epoch, q.round, q.blk_tag, q.state_depth, q.state_tag,
        q.commit_valid, q.commit_depth, q.commit_tag,
        q.votes_lo, q.votes_hi, q.author,
    )
    ok = (
        q.valid
        & (q.epoch == s.epoch_id)
        & ~is_dup
        & has_room
        & (bvar >= 0)
        & author_ok
        & commit_match
        & state_match
        & in_window
        & quorum_ok
        & tag_ok
    )
    var = jnp.maximum(var, 0)
    s2 = s.replace(
        qc_valid=wset(s.qc_valid, (sl, var), True),
        qc_round=wset(s.qc_round, (sl, var), q.round),
        qc_blk_var=wset(s.qc_blk_var, (sl, var), bvar_c),
        qc_state_depth=wset(s.qc_state_depth, (sl, var), q.state_depth),
        qc_state_tag=wset(s.qc_state_tag, (sl, var), q.state_tag),
        qc_commit_valid=wset(s.qc_commit_valid, (sl, var), q.commit_valid),
        qc_commit_depth=wset(s.qc_commit_depth, (sl, var), q.commit_depth),
        qc_commit_tag=wset(s.qc_commit_tag, (sl, var), q.commit_tag),
        qc_votes_lo=wset(s.qc_votes_lo, (sl, var), q.votes_lo),
        qc_votes_hi=wset(s.qc_votes_hi, (sl, var), q.votes_hi),
        qc_author=wset(s.qc_author, (sl, var), q.author),
        qc_tag=wset(s.qc_tag, (sl, var), q.tag),
    )
    newer = q.round > s2.hqc_round
    s2 = s2.replace(
        hqc_round=jnp.where(newer, q.round, s2.hqc_round),
        hqc_var=jnp.where(newer, var, s2.hqc_var),
    )
    s2 = update_current_round(s2, q.round + 1)
    s2 = update_commit_chain(p, s2, q.round, var)
    return _sel(ok, s2, s), ok


def insert_timeout(p: SimParams, s: Store, weights, t_epoch, t_round, t_hcbr, t_author):
    """record_store.rs:390-415 (verify) + :527-538 (insert + TC formation)."""
    author = jnp.clip(t_author, 0, p.n_nodes - 1)
    ok = (
        (t_epoch == s.epoch_id)
        & (t_hcbr <= s.hqc_round)
        & (t_round == s.current_round)
        & ~s.to_valid[author]
    )
    new_weight = s.to_weight + weights[author]
    s2 = s.replace(
        to_valid=wset(s.to_valid, author, True),
        to_hcbr=wset(s.to_hcbr, author, t_hcbr),
        to_weight=new_weight,
    )
    tc = new_weight >= config.quorum_threshold(weights, config.mp_axis(p))
    s3 = s2.replace(
        tc_valid=s2.to_valid,
        tc_hcbr=s2.to_hcbr,
        htc_round=s2.current_round,
    )
    s3 = update_current_round(s3, s2.current_round + 1)
    s2 = _sel(tc, s3, s2)
    return _sel(ok, s2, s), ok


# ---------------------------------------------------------------------------
# Record creation (RecordStore create_* APIs)
# ---------------------------------------------------------------------------


def make_block_msg(p: SimParams, s: Store, author, prev_round, prev_tag, time,
                   cmd_proposer, cmd_index, round_=None):
    r = s.current_round if round_ is None else _i32(round_)
    tag = block_tag(s.epoch_id, r, author, prev_round, prev_tag, time,
                    cmd_proposer, cmd_index)
    return BlockMsg(
        valid=jnp.bool_(True), round=r, author=_i32(author),
        prev_round=_i32(prev_round), prev_tag=prev_tag, time=_i32(time),
        cmd_proposer=_i32(cmd_proposer), cmd_index=_i32(cmd_index), tag=tag,
    )


def propose_block(p: SimParams, s: Store, weights, author, prev_round, prev_tag,
                  time, cmd_index):
    """record_store.rs:655-674: fetch a command (proposer=author, running
    index) and insert a block on top of ``prev``."""
    b = make_block_msg(p, s, author, prev_round, prev_tag, time, author, cmd_index)
    return insert_block(p, s, weights, b, s.epoch_id)


def create_vote(p: SimParams, s: Store, weights, author, blk_round, blk_var):
    """record_store.rs:676-700: execute the block, vote for the resulting
    state.  Returns (store, ok) — ok False if execution failed."""
    sl = _slot(p, blk_round)
    cs_ok, cs_d, cs_t, _ = vote_committed_state(p, s, blk_round, blk_var)
    exec_ok, st_d, st_t = compute_state(p, s, blk_round, blk_var)
    v = VoteMsg(
        valid=exec_ok, epoch=s.epoch_id, round=_i32(blk_round),
        blk_tag=s.blk_tag[sl, blk_var], state_depth=st_d, state_tag=st_t,
        commit_valid=cs_ok, commit_depth=cs_d, commit_tag=cs_t, author=_i32(author),
    )
    s2, ins_ok = insert_vote(p, s, weights, v)
    return s2, exec_ok & ins_ok


def create_timeout(p: SimParams, s: Store, weights, author, round_):
    """record_store.rs:636-649."""
    return insert_timeout(p, s, weights, s.epoch_id, _i32(round_), s.hqc_round,
                          _i32(author))


def has_timeout(s: Store, author, round_):
    """record_store.rs:651-653."""
    return (_i32(round_) == s.current_round) & s.to_valid[jnp.clip(author, 0, None)]


def check_new_qc(p: SimParams, s: Store, weights, author):
    """record_store.rs:702-738: if our proposal won the election, mint the QC
    from the recorded votes.  Returns (store, created)."""
    with profiling.scope("qc_mint"):
        return _check_new_qc(p, s, weights, author)


def _check_new_qc(p: SimParams, s: Store, weights, author):
    won = s.election == ELECTION_WON
    bvar = s.won_var
    sl = _slot(p, s.current_round)
    blk_author = s.blk_author[sl, bvar]
    trigger = won & (blk_author == _i32(author))
    st_d = s.bal_state_depth[bvar, s.won_slot]
    st_t = s.bal_state_tag[bvar, s.won_slot]
    cs_ok, cs_d, cs_t, _ = vote_committed_state(p, s, s.current_round, bvar)
    votes_mask = s.vt_valid & (s.vt_state_depth == st_d) & (s.vt_state_tag == st_t) \
        & (s.vt_blk_var == bvar)
    lo, hi = author_mask_words(votes_mask)
    tag = qc_tag(s.epoch_id, s.current_round, s.blk_tag[sl, bvar], st_d, st_t,
                 cs_ok, cs_d, cs_t, lo, hi, author)
    q = QcMsg(
        valid=trigger, epoch=s.epoch_id, round=s.current_round,
        blk_tag=s.blk_tag[sl, bvar], state_depth=st_d, state_tag=st_t,
        commit_valid=cs_ok, commit_depth=cs_d, commit_tag=cs_t,
        votes_lo=lo, votes_hi=hi, author=_i32(author), tag=tag,
    )
    s2 = s.replace(election=jnp.where(trigger, _i32(ELECTION_CLOSED), s.election))
    s3, _ = insert_qc(p, s2, weights, q)
    return _sel(trigger, s3, s), trigger


# ---------------------------------------------------------------------------
# Commit extraction
# ---------------------------------------------------------------------------


def committed_states_after(p: SimParams, s: Store, after_round):
    """record_store.rs:557-574: walk the highest-commit-certificate chain
    backward, skip the newest C-1 QCs (not yet committed), collect states with
    round > after_round.  Returns (valid[W], round[W], depth[W], tag[W]) in
    ASCENDING round order (valid entries are right-aligned)."""
    W = p.window
    start_r = jnp.where(s.hcc_valid, s.hcc_round, _i32(0))
    valids, rounds, vars_, _ = qc_walk_back(p, s, s.hcc_valid, start_r, s.hcc_var, W)
    # Works for both a static int and a traced per-slot commit_chain
    # (scenario plane): the skip count only feeds the elementwise keep mask.
    skip = p.commit_chain - 1
    idx = jnp.arange(W)
    keep = valids & (idx >= skip) & (rounds > _i32(after_round))
    sls = jnp.remainder(rounds, W)
    depths = s.qc_state_depth[sls, vars_]
    tags = s.qc_state_tag[sls, vars_]
    # Reverse to ascending-round order.
    return keep[::-1], rounds[::-1], depths[::-1], tags[::-1]
