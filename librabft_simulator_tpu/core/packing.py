"""Packed per-node state planes: the kernel-count fix for TPU.

Round-5 on-chip profiling (PERF_NOTES.md) showed the serial step is
kernel-count-bound on TPU: one event lowers to ~330 tiny fusions, and a
large share of them are the per-leaf gathers/selects that read and write
the ~70 small per-node arrays in ``Store``/``Pacemaker``/``NodeExtra``/
``Context``.  This module applies the trick that already fixed the queue
(``types.pack_payload``) to the node state itself: all per-node leaves are
stored as ONE flat ``[N, S]`` int32 plane with a static slot map, so

* reading a node's state is one row gather (``planes[a]``) followed by
  free slicing/reshaping/bitcasting (views, fused into consumers), and
* writing it back is one plane-wide masked select (``xops.wset``) instead
  of one kernel per leaf.

The packing is bit-preserving (uint32 bitcast, bool as 0/1), so packed and
unpacked engines produce bit-identical trajectories — pinned by
``tests/test_packing.py`` and the fuzz campaign.  Handlers keep operating
on the unpacked single-node struct slices; only the *storage* layout and
the slice/update boundary change.

``SimParams.packed`` gates the layout: ``None`` (auto) resolves to True
under TPU lowering and False elsewhere (the round-5 negative results —
dense full-plane writes are slower on CPU — stay respected).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from .types import (
    Context,
    NodeExtra,
    Pacemaker,
    Queue,
    SimParams,
    SimState,
    Store,
)

Array = jnp.ndarray
I32 = jnp.int32

# The four per-node sub-states, in SimState field order.  Their single-node
# slices are what the handlers in core/store.py, core/node.py, and
# core/data_sync.py operate on.
NODE_PARTS = ("store", "pm", "node", "ctx")


def node_template(p: SimParams):
    """Single-node template pytree (shape ``()`` per scalar leaf).

    Built under ``ensure_compile_time_eval`` so the template leaves are
    ALWAYS concrete constants: the initial-tag folds and broadcasts in
    ``*.initial`` would otherwise be traced as dead eqns whenever a
    caller's cache (``slot_map``) missed INSIDE a trace — making the
    traced graph depend on cache temperature and trace order, which the
    R6 graph-identity audits would flag as phantom drift."""
    with jax.ensure_compile_time_eval():
        return (Store.initial(p), Pacemaker.initial(), NodeExtra.initial(),
                Context.initial(p))


@functools.lru_cache(maxsize=None)
def slot_map(p_structural: SimParams):
    """Static slot map for one node's packed vector.

    Returns ``(slots, width)`` where ``slots`` is a tuple of
    ``(offset, size, shape, dtype_name)`` in ``tree_leaves`` order over
    :func:`node_template` and ``width`` is the total vector length S."""
    leaves = jax.tree_util.tree_leaves(node_template(p_structural))
    slots = []
    off = 0
    for leaf in leaves:
        size = int(np.prod(leaf.shape)) if leaf.ndim else 1
        slots.append((off, size, tuple(leaf.shape), str(leaf.dtype)))
        off += size
    return tuple(slots), off


def node_width(p: SimParams) -> int:
    """Packed width S of one node's state."""
    return slot_map(p.structural())[1]


def pack_node(p: SimParams, store, pm, nx, ctx) -> Array:
    """Pack (Store, Pacemaker, NodeExtra, Context) into ``[..., S]`` int32.

    Leaves may carry arbitrary leading dims (node axis, lane axis, batch
    axis): only the trailing per-node slice dims are flattened, mirroring
    ``types.pack_payload``'s bit-preserving dtype rules."""
    slots, _ = slot_map(p.structural())
    leaves = jax.tree_util.tree_leaves((store, pm, nx, ctx))
    parts = []
    for leaf, (_, size, shape, _dtype) in zip(leaves, slots):
        leaf = jnp.asarray(leaf)
        lead = leaf.shape[:leaf.ndim - len(shape)]
        flat = leaf.reshape(lead + (size,))
        if flat.dtype == jnp.uint32:
            flat = jax.lax.bitcast_convert_type(flat, jnp.int32)
        else:
            flat = flat.astype(jnp.int32)
        parts.append(flat)
    return jnp.concatenate(parts, axis=-1)


def unpack_node(p: SimParams, vec: Array):
    """Inverse of :func:`pack_node` for ``[..., S]`` rows.

    Pure slicing/reshaping/bitcasting — lowers to views that fuse into the
    consumers, not standalone kernels."""
    slots, width = slot_map(p.structural())
    template = node_template(p)
    leaves, treedef = jax.tree_util.tree_flatten(template)
    lead = vec.shape[:-1]
    out = []
    for leaf, (off, size, shape, dtype) in zip(leaves, slots):
        piece = vec[..., off:off + size]
        if dtype == "uint32":
            piece = jax.lax.bitcast_convert_type(piece, jnp.uint32)
        elif dtype == "bool":
            piece = piece != 0
        out.append(piece.reshape(lead + shape))
    return jax.tree_util.tree_unflatten(treedef, out)


def _common_fields(cls) -> tuple[str, ...]:
    return tuple(f.name for f in dataclasses.fields(cls)
                 if f.name not in NODE_PARTS)


@struct.dataclass
class PackedSimState:
    """``SimState`` with the four per-node sub-states fused into one
    ``[N, S]`` plane.  Every other field is identical to ``SimState`` (the
    step function reads them by name, so both layouts share one code
    path)."""

    planes: Array         # [N, S] packed (store, pm, node, ctx) rows
    queue: Queue
    ho_pay: Array
    ho_epoch: Array
    timer_time: Array
    timer_stamp: Array
    startup: Array
    weights: Array
    byz_equivocate: Array
    byz_silent: Array
    byz_forge_qc: Array
    clock: Array
    stamp_ctr: Array
    halted: Array
    seed: Array
    max_clock: Array
    drop_u32: Array
    n_events: Array
    n_msgs_sent: Array
    n_msgs_dropped: Array
    n_queue_full: Array
    trace_node: Array
    trace_round: Array
    trace_time: Array
    trace_count: Array
    metrics: Array
    flight: Array
    wd: Array
    sc_delay: Array
    sc_commit: Array
    adv_sched: Array
    adv_link: Array
    adv_group: Array
    adv_heal: Array


_SIM_COMMON = _common_fields(SimState)


def pack_state(p: SimParams, st: SimState) -> PackedSimState:
    """SimState -> PackedSimState (leading batch dims supported)."""
    planes = pack_node(p, st.store, st.pm, st.node, st.ctx)
    return PackedSimState(
        planes=planes, **{f: getattr(st, f) for f in _SIM_COMMON})


def unpack_state(p: SimParams, pst: PackedSimState) -> SimState:
    """PackedSimState -> SimState (exact inverse of :func:`pack_state`)."""
    store, pm, nx, ctx = unpack_node(p, pst.planes)
    return SimState(
        store=store, pm=pm, node=nx, ctx=ctx,
        **{f: getattr(pst, f) for f in _SIM_COMMON})
