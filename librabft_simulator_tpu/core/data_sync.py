"""DataSync: catch-up protocol over fixed-shape payloads.

Tensor re-expression of ``impl DataSyncNode for NodeState``
(/root/reference/librabft-v2/src/data_sync.rs:62-241).

TPU-first redesign of responses: the reference ships *unbounded* record chains
(``unknown_records``, record_store.rs:801-831).  Here a response carries a
K-round tail of (block, QC) pairs ending at the responder's highest QC, plus
the highest commit certificate with its block, timeouts and the proposal.  A
receiver lagging beyond the window performs a production-style *state-sync
jump*: it re-anchors a fresh store at the base of the received chain and
adopts the committed state (counted in ``Context.sync_jumps``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import store as store_ops
from .types import (
    BlockMsg,
    Context,
    NodeExtra,
    Payload,
    QcMsg,
    SimParams,
    Store,
    VoteMsg,
)
from ..utils import hashing as H

I32 = jnp.int32


def _i32(x):
    return jnp.asarray(x, I32)


def _slot(p, r):
    return jnp.remainder(_i32(r), p.window)


def qc_msg_at(p: SimParams, s: Store, r, var, valid):
    sl = _slot(p, r)
    blk_var = s.qc_blk_var[sl, var]
    return QcMsg(
        valid=jnp.asarray(valid, jnp.bool_),
        epoch=s.epoch_id,
        round=s.qc_round[sl, var],
        blk_tag=s.blk_tag[sl, blk_var],
        state_depth=s.qc_state_depth[sl, var],
        state_tag=s.qc_state_tag[sl, var],
        commit_valid=s.qc_commit_valid[sl, var],
        commit_depth=s.qc_commit_depth[sl, var],
        commit_tag=s.qc_commit_tag[sl, var],
        votes_lo=s.qc_votes_lo[sl, var],
        votes_hi=s.qc_votes_hi[sl, var],
        author=s.qc_author[sl, var],
        tag=s.qc_tag[sl, var],
    )


def blk_msg_at(p: SimParams, s: Store, r, var, valid):
    sl = _slot(p, r)
    return BlockMsg(
        valid=jnp.asarray(valid, jnp.bool_),
        round=s.blk_round[sl, var],
        author=s.blk_author[sl, var],
        prev_round=s.blk_prev_round[sl, var],
        prev_tag=s.blk_prev_tag[sl, var],
        time=s.blk_time[sl, var],
        cmd_proposer=s.blk_cmd_proposer[sl, var],
        cmd_index=s.blk_cmd_index[sl, var],
        tag=s.blk_tag[sl, var],
    )


def own_vote_msg(p: SimParams, s: Store, author):
    """current_vote (record_store.rs:762-764) as a wire vote."""
    a = jnp.clip(_i32(author), 0, p.n_nodes - 1)
    valid = s.vt_valid[a]
    bvar = s.vt_blk_var[a]
    sl = _slot(p, s.current_round)
    return VoteMsg(
        valid=valid, epoch=s.epoch_id, round=s.current_round,
        blk_tag=s.blk_tag[sl, bvar],
        state_depth=s.vt_state_depth[a], state_tag=s.vt_state_tag[a],
        commit_valid=s.vt_commit_valid[a], commit_depth=s.vt_commit_depth[a],
        commit_tag=s.vt_commit_tag[a], author=a,
    )


def create_notification(p: SimParams, s: Store, author) -> Payload:
    """data_sync.rs:82-111.  (Past-epoch commit certificates are not kept in
    the windowed design; cross-epoch laggards catch up via state-sync jumps.)"""
    pay = Payload.empty(p.n_nodes, p.chain_k)
    hcc = qc_msg_at(p, s, s.hcc_round, s.hcc_var, s.hcc_valid)
    hqc = qc_msg_at(p, s, s.hqc_round, s.hqc_var, s.hqc_round > 0)
    sl = _slot(p, s.current_round)
    prop_var = jnp.maximum(s.proposed_var, 0)
    # Do not reshare other leaders' proposals (data_sync.rs:99-109).
    prop_valid = (s.proposed_var >= 0) & (s.blk_author[sl, prop_var] == author)
    prop = blk_msg_at(p, s, s.current_round, prop_var, prop_valid)
    return pay.replace(
        epoch=s.epoch_id,
        hcc=hcc,
        hqc=hqc,
        prop_blk=prop,
        vote=own_vote_msg(p, s, author),
        tc_to=pay.tc_to.replace(round=s.htc_round, valid=s.tc_valid, hcbr=s.tc_hcbr),
        cur_to=pay.cur_to.replace(round=s.current_round, valid=s.to_valid, hcbr=s.to_hcbr),
    )


def create_request(p: SimParams, s: Store) -> Payload:
    """data_sync.rs:66-72, 179-181: our epoch + where our chain stands (the
    power2-minus-1 known-QC set degenerates to (hqc_round, hcr) under the
    K-tail response design)."""
    pay = Payload.empty(p.n_nodes, p.chain_k)
    return pay.replace(epoch=s.epoch_id, req_hqc_round=s.hqc_round, req_hcr=s.hcr)


def _insert_timeout_batch(p, s, weights, to_msg, rec_epoch):
    """Insert a TimeoutsMsg author-by-author (lax.scan keeps the graph small
    for N=64 configs)."""

    def body(carry, a):
        st = carry
        st2, _ = store_ops.insert_timeout(
            p, st, weights, rec_epoch, to_msg.round, to_msg.hcbr[a], a
        )
        return store_ops._sel(to_msg.valid[a], st2, st), None

    s, _ = jax.lax.scan(body, s, jnp.arange(p.n_nodes), unroll=p.unroll)
    return s


def handle_notification(p: SimParams, s: Store, weights, pay: Payload):
    """data_sync.rs:113-177.  Returns (store, should_sync)."""
    should_sync = pay.epoch > s.epoch_id
    # Highest commit certificate.
    s2, _ = store_ops.insert_qc(p, s, weights, pay.hcc)
    s = store_ops._sel(pay.hcc.valid, s2, s)
    should_sync = should_sync | (
        pay.hcc.valid
        & ((pay.hcc.epoch > s.epoch_id)
           | ((pay.hcc.epoch == s.epoch_id) & (pay.hcc.round > s.hcr + 2)))
    )
    # Highest QC.
    s2, _ = store_ops.insert_qc(p, s, weights, pay.hqc)
    s = store_ops._sel(pay.hqc.valid, s2, s)
    should_sync = should_sync | (
        pay.hqc.valid
        & ((pay.hqc.epoch > s.epoch_id)
           | ((pay.hqc.epoch == s.epoch_id) & (pay.hqc.round > s.hqc_round)))
    )
    # Proposed block, timeouts, vote (data_sync.rs:150-169).
    s2, _ = store_ops.insert_block(p, s, weights, pay.prop_blk, pay.epoch)
    s = store_ops._sel(pay.prop_blk.valid, s2, s)
    s = _insert_timeout_batch(p, s, weights, pay.tc_to, pay.epoch)
    s = _insert_timeout_batch(p, s, weights, pay.cur_to, pay.epoch)
    s2, _ = store_ops.insert_vote(p, s, weights, pay.vote)
    s = store_ops._sel(pay.vote.valid, s2, s)
    return s, should_sync


def handle_request(p: SimParams, s: Store, author, req: Payload,
                   notif: Payload | None = None) -> Payload:
    """data_sync.rs:183-207 with the K-tail redesign of unknown_records.

    ``notif`` lets callers that already built create_notification(s, author)
    (the simulator step does) avoid retracing it."""
    resp = notif if notif is not None else create_notification(p, s, author)
    # Walk back K QCs from our highest QC; emit ascending (blocks + QCs).
    valids, rounds, vars_, _ = store_ops.qc_walk_back(
        p, s, s.hqc_round > 0, s.hqc_round, s.hqc_var, p.chain_k
    )
    valids, rounds, vars_ = valids[::-1], rounds[::-1], vars_[::-1]

    def emit(i):
        bvar = s.qc_blk_var[_slot(p, rounds[i]), vars_[i]]
        blk = blk_msg_at(p, s, rounds[i], bvar, valids[i])
        qc = qc_msg_at(p, s, rounds[i], vars_[i], valids[i])
        return blk, qc

    blks, qcs = jax.vmap(emit)(jnp.arange(p.chain_k))
    hcc_bvar = s.qc_blk_var[_slot(p, s.hcc_round), s.hcc_var]
    hcc_blk = blk_msg_at(p, s, s.hcc_round, hcc_bvar, s.hcc_valid)
    return resp.replace(
        chain_blk=blks, chain_qc=qcs, hcc_blk=hcc_blk,
        vote=resp.vote.replace(valid=jnp.bool_(False)),  # votes are skipped
    )


def handle_response(p: SimParams, s: Store, nx: NodeExtra, ctx: Context, weights,
                    pay: Payload):
    """data_sync.rs:209-241 + state-sync jump.  Returns (store, nx, ctx).

    Known fidelity boundary of the K-tail design: a response whose chain
    base does not connect to the receiver's store (intra-epoch round gap
    wider than ``chain_k``) and whose hqc round is NOT beyond the
    ``window - chain_k`` jump threshold is simply absorbed without effect —
    the receiver re-requests until either the gap closes or the gap grows
    jump-worthy.  The reference cannot hit this (it ships the exact
    ``unknown_records`` delta, record_store.rs:801-831).  Size ``chain_k``
    to cover an epoch's typical round count when relying on the cross-epoch
    handoff ring (tests/test_epoch_handoff.py::
    test_multi_epoch_laggard_recovers_via_ring)."""
    # Decide whether normal chain replay can possibly connect.
    gap_jump = pay.hqc.valid & (
        (pay.epoch > s.epoch_id)
        | (pay.hqc.round > s.hqc_round + (p.window - p.chain_k))
    )
    chain_has_base = pay.chain_qc.valid[0]
    do_jump = gap_jump & chain_has_base
    s_jump = _anchored_store(p, s, pay)
    s = store_ops._sel(do_jump, s_jump, s)
    nx = nx.replace(
        latest_voted_round=jnp.where(do_jump, 0, nx.latest_voted_round),
        locked_round=jnp.where(do_jump, 0, nx.locked_round),
    )
    # Adopt the committed state carried by the commit certificate on a jump.
    adopt = do_jump & pay.hcc.valid & pay.hcc.commit_valid \
        & (pay.hcc.commit_depth > ctx.last_depth)
    ctx = ctx.replace(
        last_depth=jnp.where(adopt, pay.hcc.commit_depth, ctx.last_depth),
        last_tag=jnp.where(adopt, pay.hcc.commit_tag, ctx.last_tag),
        sync_jumps=ctx.sync_jumps + jnp.where(do_jump, 1, 0),
        # Adopted depths (last_depth+1 .. commit_depth) never reach the log.
        skipped_commits=ctx.skipped_commits + jnp.where(
            adopt, pay.hcc.commit_depth - ctx.last_depth, 0),
    )
    # Replay the chain tail in ascending order: block then QC.  lax.scan keeps
    # the insert machinery traced once instead of K times (it is the single
    # largest piece of the step graph).
    def replay(st_, x):
        blk, qc, skip_anchor = x
        s2, _ = store_ops.insert_block(p, st_, weights, blk, pay.epoch)
        st_ = store_ops._sel(blk.valid & ~skip_anchor, s2, st_)
        s2, _ = store_ops.insert_qc(p, st_, weights, qc)
        st_ = store_ops._sel(qc.valid & ~skip_anchor, s2, st_)
        return st_, None

    skip = do_jump & (jnp.arange(p.chain_k) == 0)
    s, _ = jax.lax.scan(replay, s, (pay.chain_blk, pay.chain_qc, skip),
                        unroll=p.unroll)
    # Highest commit certificate with its block, then the rest.
    s2, _ = store_ops.insert_block(p, s, weights, pay.hcc_blk, pay.epoch)
    s = store_ops._sel(pay.hcc_blk.valid, s2, s)
    s2, _ = store_ops.insert_qc(p, s, weights, pay.hcc)
    s = store_ops._sel(pay.hcc.valid, s2, s)
    s = _insert_timeout_batch(p, s, weights, pay.tc_to, pay.epoch)
    s = _insert_timeout_batch(p, s, weights, pay.cur_to, pay.epoch)
    s2, _ = store_ops.insert_block(p, s, weights, pay.prop_blk, pay.epoch)
    s = store_ops._sel(pay.prop_blk.valid, s2, s)
    return s, nx, ctx


def _anchored_store(p: SimParams, s: Store, pay: Payload) -> Store:
    """Fresh store re-anchored at the base QC of the received chain: the base
    QC becomes the 'initial' QC of the store (state-sync jump)."""
    base_qc = jax.tree.map(lambda x: x[0], pay.chain_qc)
    fresh = Store.initial(p)
    return fresh.replace(
        epoch_id=pay.epoch,
        initial_round=base_qc.round,
        initial_tag=base_qc.tag,
        initial_state_depth=base_qc.state_depth,
        initial_state_tag=base_qc.state_tag,
        current_round=base_qc.round + 1,
        hqc_round=base_qc.round,   # 'no QC beyond the anchor yet'
        htc_round=base_qc.round,
        hcr=base_qc.round,
        anchored=jnp.bool_(True),
    )
