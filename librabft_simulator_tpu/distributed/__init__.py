"""Multi-process (pod-scale) fleet runtime.

``bootstrap`` wires ``jax.distributed`` (env knobs + the local CPU
cluster test harness), ``egress`` lands results/telemetry/checkpoints
per host, ``elastic`` is the resize/failover path, and ``workers`` holds
the cluster worker targets.  See each module's docstring; the chunk
program itself lives untouched in ``parallel/sharded.py`` — this package
is host-side orchestration only (zero traced ops)."""

from .bootstrap import (  # noqa: F401
    DistContext, LocalClusterError, context, global_mesh, init_from_env,
    local_cluster, spawn_cluster)
