"""Multi-process fleet bootstrap: ``jax.distributed`` wiring + a local
CPU cluster for testing the whole subsystem without a pod.

Every scaling layer so far — the pipelined sharded runtime, the digest
stream, the AOT store, the resident service — runs inside ONE host
process, so the fleet caps at one host's devices (and the tunnel's
B=32768 per-chip cap makes multi-chip the only route past that).  The
next order of magnitude is a PROCESS-SPANNING ``dp`` mesh over a TPU pod
slice: JAX's trace-once model means the chunk program ports unchanged
(pjit/shard_map over global devices), but the host-side runtime must
become multi-process aware.  This module is the entry gate:

* :func:`init_from_env` reads the ``LIBRABFT_DIST_*`` knobs (coordinator
  address, process id, process count — the same triple every pod
  launcher exports) and calls ``jax.distributed.initialize`` exactly
  once, selecting the gloo CPU collectives implementation when the
  backend is CPU (the local-cluster testing mode; TPU pods carry their
  own ICI collectives).  With no knobs set it is a no-op returning the
  degenerate single-process :class:`DistContext` — every existing entry
  point stays valid unmodified.
* :func:`global_mesh` builds the ('dp', 'mp') mesh over GLOBAL devices
  (every process's), which threads through ``make_sharded_run_fn`` /
  ``run_sharded`` / ``ResidentFleet`` unchanged: the chunk program, the
  one-[D]-digest-per-chunk poll (already psum-reduced across the mesh,
  so every process polls the same replicated vector), and the
  double-buffered dispatch are multi-host-correct by construction
  (pinned by tests/test_distributed.py).
* :func:`local_cluster` forks *n* fresh CPU subprocesses wired into one
  ``jax.distributed`` job (loopback coordinator, one virtual device
  each), runs a named worker function in every process, and collects
  per-process JSON results — the whole distributed subsystem is
  testable on this container until the TPU tunnel revives, and the same
  harness drives the pod ladder bench (scripts/fleet_pod.py) and the
  resize-under-fire failover referee (distributed/elastic.py).

Host-side orchestration only: nothing here traces a single op — the
graph-audit sharded flavor is byte-identical with this module in play.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

#: Env knobs (the standard pod-launcher triple).  Registered in
#: audit/knobs.py; read only through these module constants so the
#: source lint (S3) can resolve every site.
COORD_ENV = "LIBRABFT_DIST_COORD"
NPROC_ENV = "LIBRABFT_DIST_NPROC"
PID_ENV = "LIBRABFT_DIST_PID"

_CTX = None  # the one process-wide context (initialize is once-only)


@dataclasses.dataclass(frozen=True)
class DistContext:
    """This process's place in the fleet (degenerate when single-process)."""

    process_id: int
    process_count: int
    coordinator: str | None
    initialized: bool  # whether jax.distributed.initialize actually ran

    @property
    def is_multiprocess(self) -> bool:
        return self.process_count > 1

    @property
    def is_host0(self) -> bool:
        return self.process_id == 0


def init_from_env() -> DistContext:
    """Initialize ``jax.distributed`` from the ``LIBRABFT_DIST_*`` knobs.

    ``LIBRABFT_DIST_NPROC`` unset or <= 1 is the single-process world:
    nothing is initialized and the degenerate context returns — safe to
    call from every entry point unconditionally.  Multi-process requires
    all three knobs; a partial triple fails loud (a process silently
    running single-process inside a pod job would psum with nobody).
    Idempotent: repeat calls return the first context."""
    global _CTX
    if _CTX is not None:
        return _CTX
    nproc = int(os.environ.get(NPROC_ENV, "1") or "1")
    if nproc <= 1:
        _CTX = DistContext(0, 1, None, False)
        return _CTX
    coord = os.environ.get(COORD_ENV, "").strip()
    pid_s = os.environ.get(PID_ENV, "").strip()
    if not coord or not pid_s:
        raise ValueError(
            f"{NPROC_ENV}={nproc} but {COORD_ENV}/{PID_ENV} unset — a "
            "multi-process fleet needs the full coordinator triple "
            "(address, process id, process count)")
    pid = int(pid_s)
    if not 0 <= pid < nproc:
        raise ValueError(f"{PID_ENV}={pid} out of range for "
                         f"{NPROC_ENV}={nproc}")
    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # CPU backends need an explicit cross-process collectives
        # implementation; must land before the backend initializes.
        # ONLY on an explicit cpu pin: an unset JAX_PLATFORMS means
        # auto-detect — on a real TPU pod the ICI collectives own the
        # mesh and gloo must stay unarmed (local_cluster children and
        # the test suite both pin cpu explicitly).
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    # The initialize barrier is the cross-host clock anchor: every
    # process leaves the coordinator handshake at (nearly) the same wall
    # instant, so recording it as a ledger span — the ledger epoch is
    # created HERE, by the get() — gives the observatory's trace merge a
    # per-host offset (align the handshake-span ends) without any
    # wall-clock exchange.  Already-initialized processes (tests driving
    # initialize themselves) record a zero-width span: offset 0.
    from ..telemetry import ledger as tledger

    with tledger.get().span(tledger.HANDSHAKE, process_id=pid,
                            process_count=nproc, coordinator=coord):
        if not _already_initialized():
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=nproc, process_id=pid)
    _CTX = DistContext(pid, nproc, coord, True)
    return _CTX


def _already_initialized() -> bool:
    """Whether jax.distributed.initialize already ran in this process
    (initialize is once-only and raises on a repeat; jax offers no
    public query, so this peeks — fail-open to 'not initialized', which
    reproduces jax's own loud error if the peek ever breaks)."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def context() -> DistContext:
    """The active context (initializing from env on first use)."""
    return init_from_env()


def global_mesh(n_dp: int | None = None, n_mp: int = 1):
    """The ('dp', 'mp') mesh over GLOBAL devices — every process's.

    In a multi-process job ``jax.devices()`` already spans the fleet, so
    this is :func:`parallel.mesh.make_mesh` verbatim; the wrapper exists
    as the documented entry (call :func:`init_from_env` first) and to
    assert the mesh actually crosses processes when one was promised."""
    import jax

    from ..parallel import mesh as mesh_ops

    ctx = context()
    mesh = mesh_ops.make_mesh(n_dp=n_dp, n_mp=n_mp)
    if ctx.is_multiprocess:
        procs = {d.process_index for d in mesh.devices.flat}
        if len(procs) != ctx.process_count:
            raise ValueError(
                f"mesh covers processes {sorted(procs)} but the job has "
                f"{ctx.process_count} — pass n_dp=None (all devices) or "
                "a shape spanning every process")
    return mesh


# ---------------------------------------------------------------------------
# The local CPU cluster: n real OS processes, one jax.distributed job.
# ---------------------------------------------------------------------------


class LocalClusterError(RuntimeError):
    """A cluster child failed; carries per-process diagnostics."""

    def __init__(self, msg: str, reports: list[dict]):
        super().__init__(msg)
        self.reports = reports


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _child_env(base: dict, *, coord: str, nproc: int, pid: int,
               local_devices: int, workdir: str, ledger: bool) -> dict:
    env = dict(base)
    env[COORD_ENV] = coord
    env[NPROC_ENV] = str(nproc)
    env[PID_ENV] = str(pid)
    env["JAX_PLATFORMS"] = "cpu"
    # Children get their OWN virtual-device count: the parent suite's
    # forced 8-device flag would multiply the global mesh under the test.
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={local_devices}"
    ).strip()
    if ledger:
        env["LIBRABFT_LEDGER_OUT"] = os.path.join(
            workdir, f"ledger-p{pid}.ndjson")
    else:
        env.pop("LIBRABFT_LEDGER_OUT", None)
    return env


@dataclasses.dataclass
class ClusterHandle:
    """A running local cluster (see :func:`spawn_cluster`)."""

    procs: list
    workdir: str
    coordinator: str
    n: int

    def result_path(self, pid: int) -> str:
        return os.path.join(self.workdir, f"result-p{pid}.json")

    def report(self, pid: int) -> dict:
        """Everything known about one child: rc, result, stderr tail."""
        proc = self.procs[pid]
        out = {"process_id": pid, "returncode": proc.poll()}
        try:
            with open(self.result_path(pid)) as f:
                out["result"] = json.load(f)
        except (OSError, ValueError):
            out["result"] = None
        try:
            with open(os.path.join(self.workdir, f"p{pid}.err")) as f:
                out["stderr_tail"] = f.read()[-2000:]
        except OSError:
            out["stderr_tail"] = ""
        return out

    def kill(self, pid: int, sig=signal.SIGKILL) -> None:
        """Kill one child (the failover harness's victim)."""
        try:
            self.procs[pid].send_signal(sig)
        except (OSError, ProcessLookupError):
            pass

    def terminate_all(self) -> None:
        for proc in self.procs:
            if proc.poll() is None:
                proc.kill()
        for proc in self.procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass

    def wait(self, timeout_s: float) -> list[int]:
        """Wait for every child; on deadline kill the stragglers.  Returns
        return codes (child killed on timeout -> its signal rc)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(proc.poll() is not None for proc in self.procs):
                break
            # One child dying usually wedges the rest inside a gloo
            # collective: give survivors a grace window, then reap.
            rcs = [proc.poll() for proc in self.procs]
            if any(rc not in (None, 0) for rc in rcs):
                grace = min(deadline, time.monotonic() + 20)
                while time.monotonic() < grace:
                    if all(proc.poll() is not None for proc in self.procs):
                        break
                    time.sleep(0.2)
                break
            time.sleep(0.2)
        self.terminate_all()
        return [proc.poll() for proc in self.procs]


def spawn_cluster(n: int, target: str, kwargs: dict | None = None, *,
                  local_devices: int = 1, workdir: str | None = None,
                  ledger: bool = False, env_extra: dict | None = None
                  ) -> ClusterHandle:
    """Launch *n* local worker processes wired into one jax.distributed
    job; returns immediately with a :class:`ClusterHandle` (the failover
    harness kills children mid-run through it).  ``target`` is a
    ``"package.module:function"`` name resolved inside each child; the
    function is called as ``fn(ctx, **kwargs)`` and its JSON-serializable
    return value lands in ``workdir/result-p<pid>.json``."""
    if n < 1:
        raise ValueError(f"cluster size must be >= 1, got {n}")
    workdir = workdir or tempfile.mkdtemp(prefix="librabft_cluster_")
    os.makedirs(workdir, exist_ok=True)
    coord = f"127.0.0.1:{_free_port()}"
    kwargs_path = os.path.join(workdir, "kwargs.json")
    with open(kwargs_path, "w") as f:
        json.dump(kwargs or {}, f)
    procs = []
    for pid in range(n):
        env = _child_env(dict(os.environ), coord=coord, nproc=n, pid=pid,
                         local_devices=local_devices, workdir=workdir,
                         ledger=ledger)
        if env_extra:
            env.update(env_extra)
        out = open(os.path.join(workdir, f"p{pid}.out"), "w")
        err = open(os.path.join(workdir, f"p{pid}.err"), "w")
        procs.append(subprocess.Popen(
            [sys.executable, "-m",
             "librabft_simulator_tpu.distributed.bootstrap",
             "--target", target, "--kwargs", kwargs_path,
             "--result", os.path.join(workdir, f"result-p{pid}.json")],
            env=env, stdout=out, stderr=err,
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))))
        out.close()
        err.close()
    return ClusterHandle(procs=procs, workdir=workdir, coordinator=coord,
                         n=n)


def local_cluster(n: int, target: str, kwargs: dict | None = None, *,
                  local_devices: int = 1, timeout_s: float = 600,
                  workdir: str | None = None, ledger: bool = False,
                  env_extra: dict | None = None) -> list:
    """Run ``target`` in an *n*-process local cluster to completion and
    return the per-process result values (index = process id).  Any child
    failure (nonzero rc, missing/error result) raises
    :class:`LocalClusterError` with every child's stderr tail."""
    handle = spawn_cluster(n, target, kwargs, local_devices=local_devices,
                           workdir=workdir, ledger=ledger,
                           env_extra=env_extra)
    rcs = handle.wait(timeout_s)
    reports = [handle.report(pid) for pid in range(n)]
    bad = [r for r, rc in zip(reports, rcs)
           if rc != 0 or not (r["result"] or {}).get("ok")]
    if bad:
        lines = [f"local_cluster({n}, {target}) failed:"]
        for r in bad:
            err = (r["result"] or {}).get("error") or \
                r["stderr_tail"].strip().splitlines()[-1:] or "?"
            lines.append(f"  p{r['process_id']} rc={r['returncode']}: {err}")
        raise LocalClusterError("\n".join(lines), reports)
    return [r["result"]["value"] for r in reports]


def _resolve_target(name: str):
    import importlib

    if ":" not in name:
        raise ValueError(f"target {name!r} must be 'module:function'")
    mod_name, fn_name = name.split(":", 1)
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, fn_name, None)
    if fn is None:
        raise ValueError(f"no function {fn_name!r} in module {mod_name!r}")
    return fn


def _child_main(argv=None) -> int:
    """The cluster-child entry (``python -m ...distributed.bootstrap``):
    initialize the distributed runtime from env, run the target, land the
    result file atomically.  Every failure writes a diagnosable result
    before the nonzero exit."""
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--target", required=True)
    ap.add_argument("--kwargs", required=True)
    ap.add_argument("--result", required=True)
    args = ap.parse_args(argv)

    def land(obj) -> None:
        tmp = args.result + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, args.result)

    try:
        ctx = init_from_env()
        from ..utils.cache import setup_compile_cache

        setup_compile_cache()  # children share the suite's persistent cache
        with open(args.kwargs) as f:
            kwargs = json.load(f)
        fn = _resolve_target(args.target)
        land({"ok": True, "value": fn(ctx, **kwargs)})
        return 0
    except Exception as e:  # noqa: BLE001 - child boundary: report, exit 1
        import traceback

        land({"ok": False, "error": f"{type(e).__name__}: {e}",
              "traceback": traceback.format_exc()[-4000:]})
        return 1


if __name__ == "__main__":
    # ``python -m`` runs this file as a FRESH '__main__' module; delegate
    # to the canonically-imported copy so workers and the child entry
    # share one module state (_CTX — initialize is once-only).
    from librabft_simulator_tpu.distributed import bootstrap as _bs

    sys.exit(_bs._child_main())
