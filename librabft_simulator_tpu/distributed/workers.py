"""Cluster worker targets: the functions ``local_cluster`` children run.

Each worker is called as ``fn(ctx, **kwargs)`` inside an initialized
``jax.distributed`` process (bootstrap._child_main) and returns a
JSON-serializable summary.  They are the shared substrate of the
2-process parity referees (tests/test_distributed.py), the ci_tier1
local-cluster smoke, the pod ladder bench (scripts/fleet_pod.py), and
the resize-under-fire failover harness (distributed/elastic.py) — one
implementation of "run the sharded fleet multi-process and egress
per host" for all of them.
"""

from __future__ import annotations

import os
import time


def _engine(name: str):
    from ..sim import parallel_sim, simulator

    return parallel_sim if name == "parallel" else simulator


def _digest_rows(recorder) -> list[dict]:
    """The deterministic digest columns of a recorder's rows (wall-clock
    and derived-rate fields stripped) — the cross-topology comparison
    payload."""
    from ..telemetry import stream as tstream

    keep = [name for name, _ in tstream.DIGEST_SLOTS]
    return [dict({k: r[k] for k in keep}, chunk=r["chunk"],
                 steps=r["steps"]) for r in recorder.rows]


def fleet_run(ctx, params_kw: dict, engine: str = "serial", b: int = 5,
              seeds_base: int = 0, chunk: int = 32,
              num_steps: int | None = None, out_dir: str | None = None,
              pin_poll: bool = True, reps_floor: int = 0) -> dict:
    """Run one sharded fleet over the GLOBAL (multi-process) mesh and
    egress per host: result shard (``out_dir/``), per-host digest stream
    NDJSON, per-host telemetry partial — plus the digest-poll contract
    counters (``pin_poll``: exactly one [13] fetch per dispatched chunk
    IN THIS PROCESS, the monkeypatch pin of test_multichip restated per
    host).  ``reps_floor`` forces at least that many dispatched chunks
    (the pod bench's timed window) by raising num_steps."""
    import numpy as np

    from ..core.types import SimParams
    from ..parallel import sharded
    from ..telemetry import report as treport
    from ..telemetry import stream as tstream
    from . import bootstrap, egress

    p = SimParams(**params_kw)
    eng = _engine(engine)
    mesh = bootstrap.global_mesh()
    seeds = sharded.fleet_seeds(seeds_base, b)
    st = eng.init_batch(p, seeds)
    # Host-staged init: every process builds the identical numpy fleet
    # (layout-independent by fleet_seeds) and shard_batch places each
    # host's rows — the multi-process device_put contract.
    import jax

    st = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), st)
    num_steps = num_steps if num_steps is not None else chunk * 200
    if reps_floor:
        num_steps = max(num_steps, chunk * reps_floor)

    fetched: list[tuple] = []
    dispatched: list[int] = []
    real_poll = sharded._poll_digest
    real_make = sharded.make_sharded_run_fn

    def spy_poll(dg):
        out = real_poll(dg)
        fetched.append(tuple(np.shape(out)))
        return out

    def make_counting(*a, **kw):
        run = real_make(*a, **kw)

        def counting(state):
            dispatched.append(1)
            return run(state)

        return counting

    rec = None
    stream_path = None
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        stream_path = egress.host_stream_path(
            os.path.join(out_dir, "fleet.ndjson"), ctx.process_id)
        rec = tstream.TimelineRecorder(
            p, out=stream_path, meta=egress.host_meta(ctx))
    if pin_poll:
        sharded._poll_digest = spy_poll
        sharded.make_sharded_run_fn = make_counting
    t0 = time.perf_counter()
    try:
        final = sharded.run_sharded(p, mesh, st, num_steps=num_steps,
                                    chunk=chunk, engine=eng, stream=rec)
    finally:
        if pin_poll:
            sharded._poll_digest = real_poll
            sharded.make_sharded_run_fn = real_make
    elapsed = time.perf_counter() - t0
    spans = egress.local_spans(mesh, egress._padded_batch(mesh, b), b,
                               process_index=ctx.process_id)
    out = {
        "process_id": ctx.process_id,
        "process_count": ctx.process_count,
        "global_devices": int(jax.device_count()),
        "local_devices": int(jax.local_device_count()),
        "spans": [[s, e] for s, e in spans],
        "elapsed_s": round(elapsed, 3),
        "chunks_polled": len(fetched) if pin_poll else None,
        "chunks_dispatched": len(dispatched) if pin_poll else None,
        "poll_shapes_ok": (all(s == (tstream.DIGEST_WIDTH,)
                               for s in fetched) if pin_poll else None),
        "stream": stream_path,
        "digest_rows": _digest_rows(rec) if rec is not None else None,
    }
    if rec is not None:
        last = rec.rows[-1] if rec.rows else {}
        out["final_digest"] = {k: last.get(k)
                               for k, _ in tstream.DIGEST_SLOTS}
        out["events"] = last.get("events")
        rec.close()
    if out_dir:
        # Per-host result shard (the checkpoint format doubles as the
        # result egress — the merged fleet state IS the result) + the
        # per-host telemetry partial when the plane is armed.
        egress.save_shards(os.path.join(out_dir, "result.d"), final, b,
                           mesh, ctx)
        if p.telemetry:
            host_rows = egress.local_state(final, b)
            out["telemetry_partial"] = treport.merged_metrics(p, host_rows)
    return out


def fleet_phase(ctx, params_kw: dict, engine: str = "serial", b: int = 5,
                seeds_base: int = 0, chunk: int = 32,
                stop_chunks: int = 2, ckpt_dir: str | None = None,
                keep_firing: bool = False, fire_chunks: int = 10_000
                ) -> dict:
    """The failover worker: run exactly ``stop_chunks`` chunks, save this
    host's checkpoint shard at the boundary, then (``keep_firing``)
    resume from the just-written shard set and keep dispatching — the
    window in which :func:`elastic.resize_under_fire` kills a process.
    Deterministic by construction: the shard set captures the fleet at a
    chunk boundary, so a restart from it on ANY topology continues
    bit-identically."""
    import jax
    import numpy as np

    from ..core.types import SimParams
    from ..parallel import sharded
    from . import bootstrap, egress, elastic

    p = SimParams(**params_kw)
    eng = _engine(engine)
    mesh = bootstrap.global_mesh()
    st = eng.init_batch(p, sharded.fleet_seeds(seeds_base, b))
    st = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), st)
    mid = sharded.run_sharded(p, mesh, st, num_steps=stop_chunks * chunk,
                              chunk=chunk, engine=eng)
    egress.save_shards(ckpt_dir, mid, b, mesh, ctx)
    if keep_firing:
        # Barrier on the full shard SET before merging: this process
        # only wrote its own shard, and a fast host merging before a
        # slow peer's sidecar lands would die on merge_shards'
        # incomplete-coverage check (a lost race, not a real gap) —
        # which would also void the kill-mid-dispatch window the
        # failover harness needs.
        deadline = time.monotonic() + 120
        for pid in range(ctx.process_count):
            side = os.path.join(ckpt_dir, f"shard-{pid}.json")
            while not os.path.exists(side):
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"peer shard {side} never appeared (120s)")
                time.sleep(0.1)
        # Under fire: restart from the shard set (all hosts read the
        # same files — shared fs in the local cluster, the object store
        # on a pod) and keep the dispatch queue busy until killed.
        host, _ = elastic.resume(
            ckpt_dir, p, engine=eng,
            out_path=os.path.join(ckpt_dir, f"fire-p{ctx.process_id}.npz"))
        sharded.run_sharded(p, mesh, host,
                            num_steps=fire_chunks * chunk, chunk=chunk,
                            engine=eng)
    return {"process_id": ctx.process_id, "saved": True,
            "ckpt_dir": ckpt_dir}


def serve_smoke(ctx, params_kw: dict, specs: list[dict], slots: int = 4,
                chunk: int = 32, out_dir: str | None = None) -> dict:
    """Multi-process resident-service smoke: every controller submits
    the IDENTICAL request sequence (the multi-controller discipline),
    serves to drain, and reports its host-local egressed results."""
    from ..core.types import SimParams
    from ..serve.service import ResidentFleet
    from . import bootstrap, egress

    p = SimParams(**params_kw)
    mesh = bootstrap.global_mesh()
    out = None
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        out = egress.host_stream_path(
            os.path.join(out_dir, "serve.ndjson"), ctx.process_id)
    with ResidentFleet(p, slots=slots, mesh=mesh, chunk=chunk,
                       out=out, meta=egress.host_meta(ctx)) as svc:
        rids = [svc.submit(spec) for spec in specs]
        svc.serve(max_chunks=200)
        local = sorted(svc.results)
        return {
            "process_id": ctx.process_id,
            "submitted": rids,
            "egressed_local": local,
            "results": {rid: {k: svc.results[rid][k]
                              for k in ("events", "commits", "safe",
                                        "slot")}
                        for rid in local},
            "pending": svc.pending_count,
            "active": svc.active_count,
        }
