"""Elastic resize + failover: a fleet checkpointed on P processes
restarts on P' != P — including P' = fewer after killing a host mid-run.

The substrate was already in place: ``load_sharded`` pads-and-masks when
the new device count doesn't divide the batch, trajectories are
layout-independent (fleet_seeds + the sharded==unsharded pins), and
chunk boundaries are deterministic — so a restart from the last
checkpoint is bit-equal to the uninterrupted run no matter what
topology it resumes on.  This module makes that a first-class path:

* :func:`resume` — merge a per-host shard set
  (``distributed.egress.save_shards``) and place it on ANY mesh: a
  2-process fleet's shards restart on 1 process, 4, or a different
  device count entirely.
* :func:`resize_under_fire` — the failover referee: run a local cluster,
  wait for its mid-run checkpoint, SIGKILL one process while the fleet
  is still dispatching (the survivors wedge in a collective and are
  reaped — exactly a pod losing a host), then resume on FEWER processes
  from the surviving shard files and run to completion.  The caller
  verifies bit-equality against an uninterrupted run
  (tests/test_distributed.py pins it leaf-for-leaf).
"""

from __future__ import annotations

import os
import time


def resume(shard_dir: str, p, engine=None, out_path: str | None = None):
    """Restart a fleet from a per-host checkpoint shard set: merge and
    return ``(host_state, n_valid)`` — feed it straight to
    ``run_sharded`` on ANY mesh (any process count, any device count;
    padding-and-masking happens there when the topology doesn't divide
    the batch).

    Deliberately a HOST tree, not ``load_sharded``'s callback-placed
    arrays: the resumed fleet usually dispatches an AOT-store executable,
    and on this toolchain a deserialized executable aborts the process on
    callback-constructed inputs — ``device_put``-placed arrays (what
    ``run_sharded``'s shard_batch does) are the supported form (the same
    hard-won rule as ``ResidentFleet.restore``).  The host staging copy
    is the merge step's own cost, paid once per restart."""
    import jax
    import numpy as np

    from ..sim import checkpoint as ckpt
    from ..sim import simulator as S
    from . import egress

    eng = engine if engine is not None else S
    merged = egress.merge_shards(shard_dir, out_path=out_path)
    sample = np.load(merged)["clock"]
    like = jax.eval_shape(
        lambda: eng.init_batch(p, np.zeros(sample.shape[0], np.uint32)))
    return ckpt.load(merged, p, like=like), int(sample.shape[0])


def _wait_for(path: str, timeout_s: float, what: str) -> None:
    deadline = time.monotonic() + timeout_s
    while not os.path.exists(path):
        if time.monotonic() > deadline:
            raise TimeoutError(f"{what} not observed within {timeout_s}s "
                               f"({path} never appeared)")
        time.sleep(0.2)


def resize_under_fire(n: int, kwargs: dict, *, victim: int = 1,
                      timeout_s: float = 600, workdir: str | None = None
                      ) -> dict:
    """Kill one local-cluster process mid-run and report the crash scene.

    Phase A only (the resume is the caller's, on whatever topology they
    want): spawn an *n*-process cluster running
    ``distributed.workers:fleet_phase`` with ``kwargs`` (which must set
    ``ckpt_dir`` and ``keep_firing=True`` — every process checkpoints its
    shard at the agreed chunk boundary, then keeps dispatching), wait for
    EVERY host's shard to land, then SIGKILL process ``victim`` while the
    fleet is under fire.  Survivors wedge in the next cross-process
    collective (a dead gloo peer) and are reaped — the pod-loses-a-host
    failure mode, end to end.  Returns ``{"ckpt_dir", "workdir",
    "victim", "returncodes"}``; resume with :func:`resume` on P' < P."""
    from . import bootstrap

    ckpt_dir = kwargs.get("ckpt_dir")
    if not ckpt_dir:
        raise ValueError("kwargs must carry ckpt_dir (where the per-host "
                         "shards land)")
    if not kwargs.get("keep_firing"):
        raise ValueError("kwargs must set keep_firing=True — the kill "
                         "must land while the fleet is still dispatching")
    if not 0 <= victim < n:
        raise ValueError(f"victim {victim} out of range for n={n}")
    handle = bootstrap.spawn_cluster(
        n, "librabft_simulator_tpu.distributed.workers:fleet_phase",
        kwargs, workdir=workdir)
    try:
        for pid in range(n):
            _wait_for(os.path.join(ckpt_dir, f"shard-{pid}.json"),
                      timeout_s, f"process {pid}'s checkpoint shard")
        # Every shard is on disk; the fleet is still dispatching
        # (keep_firing).  Pull the trigger.
        handle.kill(victim)
        deadline = time.monotonic() + 60
        while handle.procs[victim].poll() is None:
            if time.monotonic() > deadline:
                raise TimeoutError("victim survived SIGKILL?")
            time.sleep(0.1)
    finally:
        # Survivors are wedged in a collective whose peer is gone; reap
        # them — a real orchestrator would do exactly this before
        # rescheduling the job on the remaining hosts.
        handle.terminate_all()
    return {
        "ckpt_dir": ckpt_dir,
        "workdir": handle.workdir,
        "victim": victim,
        "returncodes": [proc.poll() for proc in handle.procs],
    }
