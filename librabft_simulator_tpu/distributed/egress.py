"""Per-host shard-local egress: results, telemetry, and checkpoint shards
land process-by-process — the full fleet never crosses a host boundary.

The fleet runtime was built shard-by-shard from the start
(``plane.fold_planes`` partials, ``unpad``'s block walk,
``load_sharded``'s per-device placement); this module is the
multi-process face of that discipline:

* :func:`local_spans` names the GLOBAL batch rows this process owns
  (pure mesh arithmetic — no array fetch), and :func:`local_state`
  host-lands exactly those rows, padding-trimmed.
* :func:`host_stream_path` / :func:`host_meta` give every process its
  own NDJSON digest stream (``<base>.p<pid>.ndjson``, meta-tagged with
  the process id) — merge/follow them as one fleet with
  ``scripts/fleet_watch.py --merge 'base.p*.ndjson'``.
* :func:`save_shards` writes this host's checkpoint shard
  (``<dir>/shard-<pid>.npz`` + sidecar) and :func:`merge_shards`
  (the host-0 merge step) assembles the shard set back into ONE
  standard batched checkpoint that ``sim/checkpoint.py`` loads anywhere
  — on P' != P processes, or a different device count entirely
  (``load_sharded`` pads-and-masks): the elastic resize/failover path
  (distributed/elastic.py).
* :func:`fold_metric_dicts` merges per-host ``merged_metrics`` partials
  (each host folds only its addressable shards) into the fleet view
  with the registry's per-metric aggregation.

Host-side only — zero traced ops; the single traced helper in this
subsystem (:func:`make_halted_gather`, the resident service's
between-chunks slot gather) is OUTSIDE the audited chunk program and
never runs in the fleet hot loop.
"""

from __future__ import annotations

import json
import os

import numpy as np


def local_spans(mesh, batch: int, n_valid: int | None = None,
                process_index: int | None = None) -> list[tuple[int, int]]:
    """The global ``[start, stop)`` batch row spans owned by this process
    on ``mesh``, in ascending order, trimmed to ``n_valid`` (padding rows
    never egress).  Pure mesh arithmetic — derivable before any array
    exists, so checkpoint sidecars and result tags agree with placement
    by construction (the batch dim is split over ('dp', 'mp') in device
    order: device *d* owns rows ``[d*b, (d+1)*b)``)."""
    import jax

    devices = list(mesh.devices.flat)
    if batch % len(devices):
        raise ValueError(
            f"batch {batch} does not tile the mesh's {len(devices)} "
            "devices (pad first: parallel.sharded.pad_to_multiple)")
    per = batch // len(devices)
    pid = (jax.process_index() if process_index is None else process_index)
    n_valid = batch if n_valid is None else n_valid
    spans = []
    for i, d in enumerate(devices):
        if getattr(d, "process_index", 0) != pid:
            continue
        start, stop = i * per, min((i + 1) * per, n_valid)
        if stop > start:
            spans.append((start, stop))
    # Adjacent spans merge so shard files stay compact.
    merged: list[tuple[int, int]] = []
    for s, e in spans:
        if merged and merged[-1][1] == s:
            merged[-1] = (merged[-1][0], e)
        else:
            merged.append((s, e))
    return [tuple(se) for se in merged]


def local_state(state, n_valid: int):
    """Host-land this process's valid rows of a device fleet state —
    the per-leaf block walk of ``parallel.sharded.unpad``, usable on
    divisible (unpadded) fleets too.  Already-host (numpy) trees pass
    through unchanged (they ARE the local rows, by the unpad contract)."""
    import jax

    from ..parallel import sharded

    leaves = jax.tree_util.tree_leaves(state)
    if leaves and isinstance(leaves[0], np.ndarray):
        return state
    if sharded.batch_size(state) == n_valid:
        # Divisible fleet: unpad would return the device tree as-is;
        # force the block walk with the true batch as the trim bound.
        def trim(x):
            blocks = {}
            for sh in x.addressable_shards:
                start = sh.index[0].start or 0 if sh.index else 0
                if start not in blocks:
                    blocks[start] = np.asarray(sh.data)
            return np.concatenate(
                [blocks[s] for s in sorted(blocks)], axis=0)

        return jax.tree.map(trim, state)
    return sharded.unpad(state, n_valid)


def local_rows_at(state, indices):
    """Host-land SPECIFIC global rows from this process's shards:
    ``{global_index: host_row_tree}`` for every index this process can
    address (others are simply absent).  One small device-side row
    gather per (leaf, shard block) — O(k) host transfer, never the
    whole local shard (the resident service's egress discipline: a pod
    host with hundreds of slots lands only the finished ones)."""
    import jax

    idx = sorted(set(int(i) for i in indices))
    leaves, treedef = jax.tree_util.tree_flatten(state)

    def pick(x) -> dict:
        rows: dict = {}
        for sh in x.addressable_shards:
            start = sh.index[0].start or 0 if sh.index else 0
            n = int(sh.data.shape[0])
            offs = [(g, g - start) for g in idx
                    if start <= g < start + n and g not in rows]
            if not offs:
                continue
            block = np.asarray(jax.device_get(
                sh.data[np.asarray([o for _, o in offs])]))
            for j, (g, _) in enumerate(offs):
                rows[g] = block[j]
        return rows

    picked = [pick(leaf) for leaf in leaves]
    present = set(picked[0]) if picked else set()
    return {g: jax.tree_util.tree_unflatten(treedef,
                                            [p[g] for p in picked])
            for g in idx if g in present}


# ---------------------------------------------------------------------------
# Per-host digest streams.
# ---------------------------------------------------------------------------


def host_stream_path(base: str, process_id: int) -> str:
    """The per-host NDJSON stream path convention:
    ``fleet.ndjson`` -> ``fleet.p3.ndjson`` (fleet_watch --merge globs
    ``fleet.p*.ndjson``)."""
    root, ext = os.path.splitext(base)
    return f"{root}.p{process_id}{ext or '.ndjson'}"


def host_meta(ctx) -> dict:
    """The meta fields a per-host TimelineRecorder carries so merged
    views can tag every row with its writer."""
    return {"process_id": ctx.process_id,
            "process_count": ctx.process_count}


# ---------------------------------------------------------------------------
# Checkpoint shards (save per host; merge on host 0).
# ---------------------------------------------------------------------------

SHARD_VERSION = 1


def _shard_paths(d: str, pid: int) -> tuple[str, str]:
    return (os.path.join(d, f"shard-{pid}.npz"),
            os.path.join(d, f"shard-{pid}.json"))


def save_shards(d: str, state, n_valid: int, mesh, ctx) -> str:
    """Write THIS process's checkpoint shard: its local valid rows (one
    block per owned span) + a sidecar naming the spans.  Every process
    calls this; none ever holds another host's rows.  Returns the .npz
    path.  ``state`` may be the device fleet or the host tree
    ``run_sharded`` already landed."""
    import jax

    from ..sim import checkpoint as ckpt

    os.makedirs(d, exist_ok=True)
    leaves = jax.tree_util.tree_leaves(state)
    # A device fleet carries the padded batch on its leaves; a host tree
    # landed by unpad holds local rows only, so the padded batch is
    # re-derived from the mesh (the pad_to_multiple rule).
    padded = (int(leaves[0].shape[0])
              if leaves and not isinstance(leaves[0], np.ndarray)
              else _padded_batch(mesh, n_valid))
    host = local_state(state, n_valid)
    spans = local_spans(mesh, padded, n_valid,
                        process_index=ctx.process_id)
    rows = sum(e - s for s, e in spans)
    host_leaves = jax.tree_util.tree_leaves(host)
    if host_leaves and int(host_leaves[0].shape[0]) != rows:
        raise ValueError(
            f"local state holds {int(host_leaves[0].shape[0])} rows but "
            f"this process owns spans {spans} ({rows} rows) — state and "
            "mesh disagree")
    arrays, _ = ckpt._flatten_with_paths(host)
    blob = {}
    off = 0
    for j, (s, e) in enumerate(spans):
        for key, arr in arrays.items():
            blob[f"b{j}:{key}"] = arr[off:off + (e - s)]
        off += e - s
    bin_path, meta_path = _shard_paths(d, ctx.process_id)
    tmp = bin_path + ".tmp.%d.npz" % os.getpid()
    np.savez_compressed(tmp, **blob)
    os.replace(tmp, bin_path)
    side = {
        "shard_version": SHARD_VERSION,
        "process_id": ctx.process_id,
        "process_count": ctx.process_count,
        "n_valid": int(n_valid),
        "spans": [[int(s), int(e)] for s, e in spans],
    }
    tmp = meta_path + ".tmp.%d" % os.getpid()
    with open(tmp, "w") as f:
        json.dump(side, f, indent=1)
    os.replace(tmp, meta_path)
    return bin_path


def _padded_batch(mesh, n_valid: int) -> int:
    per = max(int(mesh.size), 1)
    return -(-n_valid // per) * per


def merge_shards(d: str, out_path: str | None = None) -> str:
    """The host-0 merge step: assemble every ``shard-<pid>`` pair in
    ``d`` into ONE standard batched checkpoint (.npz, the
    ``sim/checkpoint.py`` format) covering rows ``[0, n_valid)`` exactly.
    Refuses gaps, overlaps, and mixed fleets loudly — a failover restart
    from an incomplete shard set must never silently resume a partial
    fleet.  Returns the merged path (default ``<d>/merged.npz``)."""
    sidecars = []
    for name in sorted(os.listdir(d)):
        if name.startswith("shard-") and name.endswith(".json"):
            with open(os.path.join(d, name)) as f:
                sidecars.append(json.load(f))
    if not sidecars:
        raise FileNotFoundError(f"no checkpoint shards under {d}")
    for side in sidecars:
        if side.get("shard_version") != SHARD_VERSION:
            raise ValueError(
                f"{d}: shard-{side.get('process_id')} has shard_version "
                f"{side.get('shard_version')} != {SHARD_VERSION}")
    n_valid = {side["n_valid"] for side in sidecars}
    if len(n_valid) != 1:
        raise ValueError(f"{d}: shards disagree on n_valid ({n_valid}) — "
                         "mixed fleets?")
    n_valid = n_valid.pop()
    covered: list[tuple[int, int]] = []
    pieces: dict[str, list[tuple[int, np.ndarray]]] = {}
    for side in sidecars:
        pid = side["process_id"]
        bin_path = _shard_paths(d, pid)[0]
        # Corruption is refused LOUDLY with a recovery hint, never a
        # zipfile traceback: a failover restart reads shards written by
        # processes that may have been SIGKILLed mid-write, so a
        # truncated archive is an expected input here, not a bug.
        try:
            data = np.load(bin_path)
            arrays = {key: data[key] for key in data.files}
        except Exception as e:  # noqa: BLE001 — zipfile/OSError/pickle
            raise ValueError(
                f"{bin_path}: unreadable checkpoint shard "
                f"({type(e).__name__}: {e}) — the writer was likely "
                "killed mid-write; recover the shard from the owning "
                "host or re-checkpoint before resuming") from None
        for j, (s, e) in enumerate(side["spans"]):
            covered.append((s, e))
            n_keys = 0
            for key, arr in arrays.items():
                if not key.startswith(f"b{j}:"):
                    continue
                n_keys += 1
                if int(arr.shape[0]) != e - s:
                    # A payload/sidecar split-brain (partial rewrite,
                    # mixed-run directory) would otherwise concatenate
                    # into a silently-corrupt fleet.
                    raise ValueError(
                        f"{bin_path}: block b{j}:{key.split(':', 1)[1]} "
                        f"holds {int(arr.shape[0])} rows but the sidecar "
                        f"span [{s}, {e}) promises {e - s} — shard "
                        "payload and sidecar disagree (mixed checkpoint "
                        "generations in one dir?); re-checkpoint")
                pieces.setdefault(key.split(":", 1)[1], []).append(
                    (s, arr))
            if n_keys == 0:
                raise ValueError(
                    f"{bin_path}: sidecar promises span [{s}, {e}) as "
                    f"block b{j} but the archive has no b{j}:* arrays — "
                    "shard payload and sidecar disagree; re-checkpoint")
    covered.sort()
    pos = 0
    for s, e in covered:
        if s != pos:
            raise ValueError(
                f"{d}: shard set covers rows up to {pos} then jumps to "
                f"{s} — missing or overlapping shard (a failover restart "
                "needs every host's shard; recover the missing "
                f"shard-<pid> files or re-checkpoint)")
        pos = e
    if pos != n_valid:
        raise ValueError(f"{d}: shard set covers [0, {pos}) but n_valid="
                         f"{n_valid} — incomplete shard set")
    merged = {key: np.concatenate(
        [arr for _, arr in sorted(blocks, key=lambda kv: kv[0])], axis=0)
        for key, blocks in pieces.items()}
    out_path = out_path or os.path.join(d, "merged.npz")
    tmp = out_path + ".tmp.%d.npz" % os.getpid()
    np.savez_compressed(tmp, **merged)
    os.replace(tmp, out_path)
    return out_path


# ---------------------------------------------------------------------------
# Telemetry fold merge (host-0 step over per-host partial dicts).
# ---------------------------------------------------------------------------


def fold_metric_dicts(p, dicts: list[dict]) -> dict:
    """Merge per-host ``telemetry.report.merged_metrics`` partials into
    the fleet view: counters/histograms sum, high-water marks max — the
    registry's aggregation per metric (the associativity
    ``fold_planes`` already guarantees shard-by-shard)."""
    from ..telemetry import plane

    dicts = list(dicts)
    if not dicts:
        raise ValueError("fold_metric_dicts needs at least one partial")
    out: dict = {}
    for name, (_, size, agg) in plane.np_registry(p).items():
        vals = [d[name] for d in dicts]
        if size == 1:
            out[name] = (max(vals) if agg == plane.MAX else sum(vals))
        else:
            cols = list(zip(*vals))
            out[name] = [
                (max(c) if agg == plane.MAX else sum(c)) for c in cols]
    return out


# ---------------------------------------------------------------------------
# Resident-service slot gather (multi-process serve boundary).
# ---------------------------------------------------------------------------


def make_halted_gather(mesh):
    """A tiny jitted all-gather of the ``[B]`` halted plane, replicated
    to every process — the resident service's between-chunks egress
    trigger needs the SAME finished-slot list on every controller (its
    admission bookkeeping must stay SPMD-consistent), and the plane is
    batch-sharded.  One [B] bool vector per egress event, never in the
    chunk loop, never part of the audited chunk program."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)
    f = shard_map(lambda h: jax.lax.all_gather(h, axes, tiled=True),
                  mesh=mesh, in_specs=(P(axes),), out_specs=P(),
                  check_rep=False)
    return jax.jit(f)
